"""Micro-benchmark: decompose the fluid-solve cost at the flagship size.

Times the spectral substep's internals on the real chip — the batched
forward/inverse transforms, the diagonal k-space algebra between them,
the fused plan substep, the PRE-fusion chain (separate Helmholtz solves
-> projection -> pressure update) it replaced, and the bf16/split-real
mixed-precision transform path — so fluid-phase optimization is driven
by measurement instead of the aggregate ``phases`` table in bench.py
(round 6: PERF.md put fluid_solve at 39.3 ms, the dominant flagship
phase; this names which half of it — transform or algebra — the next
lever must attack).

Usage:  python tools/microbench_fluid.py [--n 256] [--reps 10]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# importable regardless of caller cwd (the relay watcher invokes this
# as a script; python puts tools/ on sys.path, not the repo root)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def timeit(fn, reps):
    import jax

    jax.block_until_ready(fn())  # compile + drain the warm-up step
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--dt", type=float, default=5e-5)
    ap.add_argument("--json", action="store_true",
                    help="emit a machine-readable JSON line after the "
                         "table (the relay watcher's capture format)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    d = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", d)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
    from ibamr_tpu.solvers import fft, spectral_plan

    n = args.n
    grid = StaggeredGrid(n=(n, n, n), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    dt, rho, mu = args.dt, 1.0, 0.05
    alpha, beta = rho / dt, -0.5 * mu
    print(f"n={n} dt={dt} backend={jax.default_backend()}")

    rng = np.random.default_rng(0)
    rhs = tuple(jnp.asarray(rng.standard_normal(grid.n), jnp.float32)
                for _ in range(3))
    plan = spectral_plan.get_plan(grid.n, grid.dx, jnp.float32)
    axes = (1, 2, 3)
    r = args.reps
    out = {"n": n, "backend": jax.default_backend()}

    # transform / algebra split of the fused substep
    x = jnp.stack(rhs)
    fwd = jax.jit(lambda: jnp.fft.rfftn(x, axes=axes))
    out["fwd_transform_ms"] = timeit(fwd, r)
    uh = fwd()
    alg = jax.jit(lambda: plan.kspace_algebra(uh, alpha, beta,
                                              (alpha, beta)))
    out["kspace_algebra_ms"] = timeit(alg, r)
    oh = alg()
    out["inv_transform_ms"] = timeit(
        jax.jit(lambda: jnp.fft.irfftn(oh, s=grid.n, axes=axes)), r)

    # the fused plan substep (2 batched FFT calls total)
    out["fused_substep_ms"] = timeit(jax.jit(
        lambda: plan.substep(rhs, alpha, beta, (alpha, beta))), r)
    # the bf16/split-real mixed-precision transform path
    out["fused_substep_bf16_ms"] = timeit(jax.jit(
        lambda: plan.substep(rhs, alpha, beta, (alpha, beta),
                             spectral_dtype="bf16")), r)

    # the PRE-fusion chain the fused substep replaced (8 single-field
    # transforms + stencil passes)
    def chained():
        from ibamr_tpu.ops import stencils
        u_star = fft.solve_helmholtz_periodic_vel(rhs, grid.dx,
                                                  alpha, beta)
        u_new, phi0 = fft.project_divergence_free(u_star, grid.dx)
        phi = alpha * phi0
        p_inc = phi - (0.5 * mu * dt / rho) * stencils.laplacian(
            phi, grid.dx)
        return u_new, p_inc

    out["chained_substep_ms"] = timeit(jax.jit(chained), r)

    # whole fluid step (convective + rhs assembly + fused substep) and
    # its bf16 twin — what the integrator actually pays per substep
    integ = INSStaggeredIntegrator(grid, rho=rho, mu=mu,
                                   dtype=jnp.float32)
    st = integ.initialize(u0_arrays=rhs)
    out["ins_step_ms"] = timeit(jax.jit(
        lambda: integ.step(st, dt)), r)
    integ_bf = INSStaggeredIntegrator(grid, rho=rho, mu=mu,
                                      dtype=jnp.float32,
                                      spectral_dtype="bf16")
    out["ins_step_bf16_ms"] = timeit(jax.jit(
        lambda: integ_bf.step(st, dt)), r)

    out["plan_cache"] = spectral_plan.plan_cache_stats()

    print(f"fwd transform      {out['fwd_transform_ms']:8.2f} ms")
    print(f"k-space algebra    {out['kspace_algebra_ms']:8.2f} ms")
    print(f"inv transform      {out['inv_transform_ms']:8.2f} ms")
    print(f"fused substep      {out['fused_substep_ms']:8.2f} ms")
    print(f"fused substep bf16 {out['fused_substep_bf16_ms']:8.2f} ms")
    print(f"chained substep    {out['chained_substep_ms']:8.2f} ms")
    print(f"ins step           {out['ins_step_ms']:8.2f} ms")
    print(f"ins step bf16      {out['ins_step_bf16_ms']:8.2f} ms")
    tr = out["fwd_transform_ms"] + out["inv_transform_ms"]
    share = tr / max(out["fused_substep_ms"], 1e-9)
    print(f"transform share of fused substep: {share:.2f} "
          f"({'transform-bound' if share > 0.5 else 'algebra-bound'})")
    if args.json:
        print(json.dumps({k: (round(v, 3) if isinstance(v, float)
                              else v) for k, v in out.items()}),
              flush=True)


if __name__ == "__main__":
    main()
