"""Relay-independent HLO traffic/FLOP audit of the flagship step
(VERDICT round 4, "Next round" item 1b).

``jit(...).lower().compile()`` on the host-CPU backend builds the same
HLO module structure the TPU backend compiles, and XLA's
``cost_analysis()`` / ``memory_analysis()`` report the module's
bytes-accessed and FLOP totals — numbers that do NOT need the relay.
This turns the transfer-engine claims ("occupancy packing lifts slot
utilization so every weight operand shrinks by the same factor; bf16
compression halves what remains") into measured per-engine byte
counts:

- per engine (scatter / mxu / packed / *_bf16): the ISOLATED spread
  and interp contractions at flagship shapes, plus bucket prep;
- the full coupled step and the isolated fluid solve, for the
  phase-share picture that the on-chip ``phases`` table measures in
  wall-clock.

Every leg runs in its own child process (the XLA CPU pipeline has a
rare native-crash flake; an isolated leg loses one data point, not the
artifact). Results land in ``HLO_COST_r06.json`` and feed PERF.md.
Round 6 adds an FFT census per leg (batched-transform call count +
per-transform bytes at the jaxpr primitive level) and the fluid trio
(``fluid`` fused / ``fluid_chained`` pre-fusion / ``fluid_bf16``
mixed-precision), pinning the spectral fusion by op count.

Caveats (stated in the artifact): CPU-backend fusion/layout decisions
differ from TPU in the details, so treat ratios between engines as the
signal, not absolute byte counts; `bytes accessed` is XLA's HLO-level
estimate (each buffer counted once per producing/consuming op), not an
HBM-transaction trace. The pallas engines cannot be audited this way
(interpret-mode lowering on CPU carries no real cost model) — their
evidence remains the on-chip shootout. The hybrid_bf16 engine is
audited PARTIALLY for the same reason: its interp / bucket-prep /
refresh legs are plain XLA and appear here; its spread leg is the
pallas kernel and does not.
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


# The census primitives now live in ibamr_tpu.analysis.graph_census
# (PR 8): ONE set of counting rules shared by this bench artifact, the
# CI drift gate (tools/graph_audit.py) and the tier-1 contract tests.
# Re-exported here because tests/test_forces_hlo.py and
# tests/test_hlo_budgets.py import it from this module.
from ibamr_tpu.analysis.graph_census import hlo_op_counts  # noqa: E402,F401


def _leg_child(q, n, n_lat, n_lon, engine, piece):
    try:
        from ibamr_tpu.utils.backend_guard import force_cpu

        jax = force_cpu()
        import jax.numpy as jnp

        from ibamr_tpu.models.shell3d import build_shell_example

        integ, state = build_shell_example(
            n_cells=n, n_lat=n_lat, n_lon=n_lon, radius=0.25,
            aspect=1.2, stiffness=1.0, rest_length_factor=0.75,
            mu=0.05, use_fast_interaction=engine,
            spectral_dtype="bf16" if piece == "fluid_bf16" else None)
        ib = integ.ib
        grid = integ.ins.grid
        dt = 5e-5
        X, mask = state.X, state.mask
        t0 = time.perf_counter()

        if piece == "step":
            fn = jax.jit(lambda s: integ.step(s, dt))
            lowered = fn.lower(state)
        elif piece in ("fluid", "fluid_bf16", "fluid_chained"):
            # fluid_bf16: the mixed-precision transform path (the
            # integrator was built with spectral_dtype="bf16" above);
            # fluid_chained: the PRE-fusion chain (separate Helmholtz
            # solves -> projection -> pressure update) the fused
            # substep replaced. These legs are the WHOLE ins.step
            # (convective + rhs assembly dilute the substep delta);
            # the substep* trio below isolates the solve itself — the
            # ">= 20% lower fluid-phase bytes-accessed" evidence
            if piece == "fluid_chained":
                integ.ins.fused_stokes = None
            f = tuple(jnp.zeros_like(u) for u in state.ins.u)
            fn = jax.jit(lambda st, ff: integ.ins.step(st, dt, f=ff))
            lowered = fn.lower(state.ins, f)
        elif piece in ("substep", "substep_bf16", "substep_chained"):
            # the spectral solve in ISOLATION: Helmholtz + projection
            # + pressure increment, holding the surrounding step fixed
            from ibamr_tpu.ops import stencils
            from ibamr_tpu.solvers import fft as _fft

            ins = integ.ins
            dx = grid.dx
            alpha, beta = ins.rho / dt, -0.5 * ins.mu
            rhs = state.ins.u
            if piece == "substep_chained":
                def sub(r):
                    u_star = _fft.solve_helmholtz_periodic_vel(
                        r, dx, alpha, beta)
                    u_new, phi0 = _fft.project_divergence_free(
                        u_star, dx)
                    phi = alpha * phi0
                    p_inc = phi + (beta / alpha) * stencils.laplacian(
                        phi, dx)
                    return u_new, p_inc
            else:
                sd = "bf16" if piece == "substep_bf16" else None

                def sub(r):
                    return _fft.helmholtz_project_periodic(
                        r, dx, alpha=alpha, beta=beta,
                        pinc_coeffs=(alpha, beta), spectral_dtype=sd)

            fn = jax.jit(sub)
            lowered = fn.lower(rhs)
        elif piece == "spread":
            F = jnp.zeros_like(X)

            def spread(Xa, Fa, m):
                ctx = ib.prepare(Xa, m)
                return ib.spread_force(Fa, grid, Xa, m, ctx=ctx)

            lowered = jax.jit(spread).lower(X, F, mask)
        elif piece == "interp":
            u = state.ins.u

            def interp(ua, Xa, m):
                ctx = ib.prepare(Xa, m)
                return ib.interpolate_velocity(ua, grid, Xa, m,
                                               ctx=ctx)

            lowered = jax.jit(interp).lower(u, X, mask)
        elif piece == "bucket_prep":
            if ib.fast is None:
                q.put({"skipped": "no fast engine -> no bucket prep"})
                return
            lowered = jax.jit(lambda Xa, m: ib.prepare(Xa, m)).lower(
                X, mask)
        elif piece == "refresh":
            # slot-preserving half-step refresh: the re-gather the
            # midpoint step pays INSTEAD of a second bucket_prep
            if ib.fast is None \
                    or getattr(ib.fast, "refresh", None) is None:
                q.put({"skipped": "engine has no refresh path"})
                return
            ctx0 = jax.jit(lambda Xa, m: ib.prepare(Xa, m))(X, mask)
            lowered = jax.jit(
                lambda c, Xa, m: ib.refresh(c, Xa, m)[0]).lower(
                    ctx0, X, mask)
        elif piece == "transfers_fused":
            # spread + 2x interp sharing ONE bucket prep — the step's
            # actual per-position transfer block, so op-boundary
            # effects (shared canonicalization, fused masks) show up
            F = jnp.zeros_like(X)
            u = state.ins.u

            def block(ua, Xa, Fa, m):
                ctx = ib.prepare(Xa, m)
                U1 = ib.interpolate_velocity(ua, grid, Xa, m, ctx=ctx)
                fv = ib.spread_force(Fa, grid, Xa, m, ctx=ctx)
                U2 = ib.interpolate_velocity(ua, grid, Xa, m, ctx=ctx)
                return U1, fv, U2

            lowered = jax.jit(block).lower(u, X, F, mask)
        else:
            raise ValueError(piece)

        # contraction + FFT censuses: the SHARED counting rules from
        # ibamr_tpu.analysis.graph_census (dot_census: operand bytes of
        # every dot_general — the (B,cap,P)/(B,cap,nz) einsum operands
        # ARE the claimed dominant traffic; fft_census: batched FFT
        # call count + per-transform bytes at the jaxpr PRIMITIVE level
        # — the CPU backend lowers lax.fft to a ducc custom-call, so an
        # HLO-text opcode census cannot see it)
        from ibamr_tpu.analysis.graph_census import dot_census, fft_census

        census = {"dot_lhs_bytes": 0, "dot_rhs_bytes": 0,
                  "dot_out_bytes": 0, "dot_count": 0, "dot_flops": 0,
                  "fft_ops": 0, "fft_bytes": 0, "fft_transforms": []}

        def _walk(jaxpr):
            census.update(fft_census(jaxpr))
            census.update(dot_census(jaxpr))

        try:
            if piece == "spread":
                cj = jax.make_jaxpr(spread)(X, F, mask)
            elif piece == "interp":
                cj = jax.make_jaxpr(interp)(u, X, mask)
            elif piece == "transfers_fused":
                cj = jax.make_jaxpr(block)(u, X, F, mask)
            elif piece == "step":
                cj = jax.make_jaxpr(lambda s: integ.step(s, dt))(state)
            elif piece in ("fluid", "fluid_bf16", "fluid_chained"):
                cj = jax.make_jaxpr(
                    lambda st, ff: integ.ins.step(st, dt, f=ff))(
                        state.ins, f)
            elif piece in ("substep", "substep_bf16",
                           "substep_chained"):
                cj = jax.make_jaxpr(sub)(rhs)
            elif piece == "refresh":
                cj = jax.make_jaxpr(
                    lambda c, Xa, m: ib.refresh(c, Xa, m)[0])(
                        ctx0, X, mask)
            else:
                cj = jax.make_jaxpr(
                    lambda Xa, m: ib.prepare(Xa, m))(X, mask)
            _walk(cj.jaxpr)
        except Exception as ce:  # census is best-effort
            census["census_error"] = f"{type(ce).__name__}: {ce}"

        compiled = lowered.compile()
        ca = compiled.cost_analysis() or {}
        if isinstance(ca, (list, tuple)):
            # older jax returns one properties dict per partition
            ca = ca[0] if ca else {}
        ma = compiled.memory_analysis()
        try:
            # scatter census: the round-5 tax the force-assembly gather
            # table and refresh path exist to eliminate
            ops = hlo_op_counts(compiled.as_text())
            scatter_ops = sum(v for k, v in ops.items()
                              if k.startswith("scatter"))
        except Exception:
            scatter_ops = None
        out = {
            "n": n,
            "markers": int(X.shape[0]),
            "engine": str(engine),
            "piece": piece,
            "flops": float(ca.get("flops", -1.0)),
            "bytes_accessed": float(ca.get("bytes accessed", -1.0)),
            "bytes_out": float(ca.get("bytes accessedout{}", -1.0)),
            "compile_s": round(time.perf_counter() - t0, 1),
            **census,
        }
        if scatter_ops is not None:
            out["scatter_ops"] = scatter_ops
        if ma is not None:
            out.update({
                "arg_bytes": int(ma.argument_size_in_bytes),
                "out_bytes": int(ma.output_size_in_bytes),
                "temp_bytes": int(ma.temp_size_in_bytes),
            })
        q.put(out)
    except Exception as e:  # noqa: BLE001 - report to parent
        q.put({"error": f"{type(e).__name__}: {e}",
               "engine": str(engine), "piece": piece, "n": n})


def run_leg(n, n_lat, n_lon, engine, piece, timeout_s):
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_leg_child,
                    args=(q, n, n_lat, n_lon, engine, piece))
    p.start()
    p.join(timeout_s)
    if p.is_alive():
        p.terminate()
        p.join(10)
        return {"error": f"timeout > {timeout_s:.0f}s",
                "engine": str(engine), "piece": piece, "n": n}
    try:
        return q.get_nowait()
    except Exception:
        return {"error": f"child died rc={p.exitcode}",
                "engine": str(engine), "piece": piece, "n": n}


ENGINES = {
    "scatter": False,
    "mxu": True,
    "mxu_bf16": "mxu_bf16",
    "packed": "packed",
    "packed_bf16": "packed_bf16",
    # round 5: fully-blocked (z-tiled) packing + spill-folding
    # overlap-add (ops.interaction_packed3)
    "packed3": "packed3",
    "packed3_bf16": "packed3_bf16",
    # round 6: pallas-spread + bf16-interp hybrid (XLA legs only — the
    # pallas spread has no CPU cost model; see module docstring)
    "hybrid_bf16": "hybrid_bf16",
}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--n-lat", type=int, default=316)
    ap.add_argument("--n-lon", type=int, default=316)
    ap.add_argument("--quick-n", type=int, default=64,
                    help="small cross-check size (0 disables)")
    ap.add_argument("--timeout", type=float, default=2400.0)
    ap.add_argument("--out", type=str,
                    default=os.path.join(REPO, "HLO_COST_r06.json"))
    ap.add_argument("--engines", type=str, default="",
                    help="comma-separated engine subset (default all)")
    ap.add_argument("--pieces", type=str, default="",
                    help="comma-separated piece subset (default all); "
                         "re-measured legs upsert into --out in place")
    args = ap.parse_args()
    args.pieces = ({s.strip() for s in args.pieces.split(",")}
                   if args.pieces else None)
    global ENGINES
    if args.engines:
        subset = {s.strip() for s in args.engines.split(",")}
        unknown = subset - set(ENGINES)
        if unknown:
            raise SystemExit(f"unknown engines {sorted(unknown)}")
        ENGINES = {k: v for k, v in ENGINES.items() if k in subset}

    legs = []
    sizes = ([(args.quick_n, 100, 100)] if args.quick_n else []) + \
        [(args.n, args.n_lat, args.n_lon)]
    for n, nla, nlo in sizes:
        for label, eng in ENGINES.items():
            if label.startswith("hybrid"):
                # only the XLA legs: spread is the pallas kernel
                pieces = ["interp", "bucket_prep", "refresh"]
            else:
                pieces = ["spread", "interp"]
                if eng is not False:
                    pieces.append("bucket_prep")
            if label in ("packed", "mxu", "packed3"):
                pieces.append("transfers_fused")
            if label in ("packed", "packed3"):
                pieces.append("step")
            if label == "packed":
                # the fluid trio (whole ins.step) plus the isolated
                # substep trio (the solve alone): fused plan path vs
                # the pre-fusion chain vs the bf16 transform path —
                # the round-6 ">= 20% lower fluid-phase bytes" evidence
                pieces.extend(["fluid", "fluid_chained", "fluid_bf16",
                               "substep", "substep_chained",
                               "substep_bf16"])
                pieces.append("refresh")
            for piece in pieces:
                if args.pieces and piece not in args.pieces:
                    continue
                legs.append((n, nla, nlo, label, eng, piece))

    # merge-don't-clobber: an --engines subset run must not destroy
    # the fuller artifact's other legs (re-measured legs replace their
    # own (n, engine, piece) slot only)
    doc = {"note": (
        "XLA HLO cost_analysis on the host-CPU backend "
        "(same HLO structure as TPU; ratios between engines "
        "are the signal, absolute bytes are backend "
        "estimates). pallas engines excluded: interpret-mode "
        "lowering carries no cost model."), "legs": []}
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                doc = json.load(f)
        except Exception:
            pass

    def upsert(r):
        key = (r.get("n"), r.get("engine"), r.get("piece"))
        doc["legs"] = [x for x in doc["legs"]
                       if (x.get("n"), x.get("engine"),
                           x.get("piece")) != key]
        doc["legs"].append(r)

    for i, (n, nla, nlo, label, eng, piece) in enumerate(legs):
        print(f"[audit] {i + 1}/{len(legs)}: n={n} engine={label} "
              f"piece={piece}", flush=True)
        r = run_leg(n, nla, nlo, eng, piece, args.timeout)
        r["engine"] = label
        print(f"[audit]   -> {json.dumps(r)}", flush=True)
        upsert(r)
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
    print(f"[audit] wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
