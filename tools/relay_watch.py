"""Relay watcher: poll the TPU relay and fire the flagship bench the
moment it comes back (VERDICT.md round 3, "Next round" item 1 — treat
relay-watching as a deliverable, not luck).

Loop: probe the accelerator backend in a killable subprocess every
``--interval`` seconds. On the first healthy probe, immediately run

  1. ``bench.py`` (staged flagship shootout; stdout JSON captured to
     ``--out``), and
  2. ``tools/microbench_transfer.py`` at 256^3 (per-engine legs), and
  3. ``tools/microbench_fluid.py`` at 256^3 (transform-vs-algebra
     split of the fluid substep + the bf16 transform twin), and
  4. ``tools/microbench_grad.py`` at 256^3 (primal-vs-VJP wall and
     fft/scatter census per differentiable piece — the adjoint-at-
     primal-cost ratios on the real chip),

then keep polling: if the relay was healthy but the bench failed to
produce a TPU-platform JSON line (the relay can die mid-run), the
watcher re-arms and tries again on the next healthy window, up to
``--max-captures`` successful captures.

Everything is logged to ``--log`` with timestamps so a later reader can
reconstruct exactly when the relay was up.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def log(f, msg: str) -> None:
    line = f"[{time.strftime('%Y-%m-%d %H:%M:%S')}] {msg}"
    print(line, file=sys.stderr, flush=True)
    f.write(line + "\n")
    f.flush()


def last_json_line(text: str):
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def newest_replay_capsule(record_dir):
    """Newest flight-recorder capsule under the bench's ``--record``
    directory (or None): the pointer attached to stall/kill log lines
    so the operator can hand the dead window straight to
    ``python -m tools.replay``."""
    if not record_dir:
        return None
    try:
        from tools.replay import newest_capsule
        return newest_capsule(record_dir)
    except Exception:
        return None


def run_bench_watched(cmd, f, env, timeout_s: float, hb_path: str,
                      stall_after_s: float, record_dir: str = ""):
    """Run the bench under heartbeat supervision.

    The bench writes ``hb_path`` (its ``--heartbeat``); this loop polls
    the file's age so a relay that drops MID-shootout surfaces as a
    structured stall log line the moment the heartbeat goes stale —
    instead of the old behavior (silence until the whole
    ``--bench-timeout`` burned). A stall sustained past 3x
    ``stall_after_s`` kills the bench early, returning the window to
    the probe loop. With ``record_dir`` (the bench's ``--record``
    directory) every stall/kill log line carries the newest replay
    capsule dumped so far. Returns ``(returncode, stdout, stderr,
    stalled)``; ``returncode`` is ``None`` when the bench was killed
    (stall or timeout).
    """
    from ibamr_tpu.utils.watchdog import heartbeat_age

    try:
        os.unlink(hb_path)               # ages must not leak across runs
    except OSError:
        pass
    # capture to FILES, not pipes: nobody drains a pipe while this loop
    # sleeps, and a chatty bench stderr would fill the 64K buffer and
    # deadlock the child mid-shootout
    with tempfile.TemporaryFile(mode="w+") as fo, \
            tempfile.TemporaryFile(mode="w+") as fe:
        proc = subprocess.Popen(cmd, stdout=fo, stderr=fe, text=True,
                                cwd=REPO, env=env)
        t0 = time.time()
        stalled = False
        stall_armed = True
        killed_reason = None
        while proc.poll() is None:
            if time.time() - t0 > timeout_s:
                killed_reason = f"timeout after {timeout_s:.0f}s"
                break
            time.sleep(min(10.0, stall_after_s / 3.0))
            age = heartbeat_age(hb_path)
            if age is None:
                continue                 # bench not far enough to beat yet
            if age > stall_after_s:
                stalled = True
                if stall_armed:
                    stall_armed = False
                    log(f, "STALL " + json.dumps(
                        {"event": "stall", "kind": "stall",
                         "beat_age_s": round(age, 1),
                         "threshold_s": stall_after_s,
                         "elapsed_s": round(time.time() - t0, 1),
                         "replay": newest_replay_capsule(record_dir)}))
                if age > 3.0 * stall_after_s:
                    killed_reason = (f"heartbeat stale {age:.0f}s "
                                     f"(> {3.0 * stall_after_s:.0f}s)")
                    break
            else:
                stall_armed = True       # bench moved again: re-arm
        rc = proc.poll()
        if rc is None:
            cap = newest_replay_capsule(record_dir)
            log(f, "killing bench: " + killed_reason
                + (f"; newest replay capsule: {cap}" if cap
                   else "; no replay capsule recorded"))
            proc.kill()
            proc.wait()
        fo.seek(0)
        fe.seek(0)
        out, err = fo.read(), fe.read()
    return rc, out, err, stalled


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=240.0)
    ap.add_argument("--probe-timeout", type=float, default=90.0)
    ap.add_argument("--bench-timeout", type=float, default=3600.0)
    ap.add_argument("--out", type=str,
                    default=os.path.join(REPO, "BENCH_TPU_CAPTURE.json"))
    ap.add_argument("--log", type=str,
                    default=os.path.join(REPO, "relay_watch.log"))
    ap.add_argument("--max-captures", type=int, default=1)
    ap.add_argument("--max-hours", type=float, default=11.0)
    ap.add_argument("--stall-after", type=float, default=300.0,
                    help="bench heartbeat age (s) that counts as a "
                         "stall; 3x this kills the bench early")
    ap.add_argument("--profile-stages", type=str,
                    default="n256,packed*",
                    help="stage globs the bench profiles on a healthy "
                         "window (bench.py --profile-stages); captures "
                         "land under <--out stem>_profile/ as "
                         "<stage>_<gitrev>/ ('' disables)")
    args = ap.parse_args()

    from ibamr_tpu.utils.backend_guard import probe_accelerator

    deadline = time.time() + args.max_hours * 3600.0
    captures = 0
    f = open(args.log, "a")
    log(f, f"watcher start: interval={args.interval}s "
           f"probe_timeout={args.probe_timeout}s out={args.out}")
    while time.time() < deadline and captures < args.max_captures:
        plat, err = probe_accelerator(args.probe_timeout)
        if plat is None or plat == "cpu":
            log(f, f"probe: relay unavailable ({err}); sleeping "
                   f"{args.interval:.0f}s")
            time.sleep(args.interval)
            continue
        log(f, f"probe: HEALTHY platform={plat} — launching bench shootout")
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)  # let the container default win
        hb_path = args.out.replace(".json", "_heartbeat.json")
        record_dir = args.out.replace(".json", "_record")
        profile_dir = args.out.replace(".json", "_profile")
        bench_cmd = [sys.executable, os.path.join(REPO, "bench.py"),
                     "--stages", "64,128,256", "--heartbeat", hb_path,
                     "--record", record_dir, "--fleet", "8",
                     "--fleet-mesh", "--tune-grid"]
        if args.profile_stages:
            # device profiles of the named stages ride the same healthy
            # window; they are the only trace-level artifact a dead
            # relay cannot be asked for afterwards
            bench_cmd += ["--profile", profile_dir,
                          "--profile-stages", args.profile_stages]
        t0 = time.time()
        rc, out, err, stalled = run_bench_watched(
            bench_cmd,
            f, env, args.bench_timeout, hb_path, args.stall_after,
            record_dir=record_dir)
        if rc is None:
            log(f, f"bench KILLED (stalled={stalled}); re-arming")
            time.sleep(args.interval)
            continue
        dtr = time.time() - t0
        result = last_json_line(out or "")
        log(f, f"bench rc={rc} wall={dtr:.0f}s stalled={stalled} "
               f"result={json.dumps(result) if result else 'NO JSON'}")
        tail = "\n".join((err or "").strip().splitlines()[-30:])
        log(f, "bench stderr tail:\n" + tail)
        if result is not None and result.get("platform") not in (None, "cpu"):
            with open(args.out, "w") as g:
                json.dump(result, g, indent=1)
            log(f, f"CAPTURED TPU bench -> {args.out}")
            captures += 1
            # manifest entries are dicts since PR 10 ({dir, stage,
            # rev, bytes, attributed}); older bench revs emitted bare
            # path strings — accept both
            profs = [d for d in
                     ((e.get("dir") if isinstance(e, dict) else e)
                      for e in (result.get("profiles") or []))
                     if d and os.path.isdir(d)]
            if profs:
                log(f, "profile captures: " + ", ".join(profs))
            elif args.profile_stages:
                log(f, "no profile captures landed (stages skipped "
                       "or profiler unavailable)")
            # follow with the per-engine microbench while the window is warm
            try:
                r2 = subprocess.run(
                    [sys.executable,
                     os.path.join(REPO, "tools", "microbench_transfer.py"),
                     "--n", "256"],
                    capture_output=True, text=True, cwd=REPO, env=env,
                    timeout=args.bench_timeout)
                log(f, f"microbench rc={r2.returncode}\n"
                       + "\n".join((r2.stdout or "").strip().splitlines()[-25:])
                       + "\n--- stderr tail ---\n"
                       + "\n".join((r2.stderr or "").strip().splitlines()[-15:]))
                with open(args.out.replace(".json", "_microbench.txt"),
                          "w") as g:
                    g.write(r2.stdout or "")
                    g.write("\n--- stderr ---\n")
                    g.write(r2.stderr or "")
            except subprocess.TimeoutExpired:
                log(f, "microbench timed out")
            # fluid-phase decomposition while the window is still warm
            # (round 6: transform-vs-algebra split + bf16 twin — the
            # numbers PERF.md's fluid-floor verdict is updated from)
            try:
                r3 = subprocess.run(
                    [sys.executable,
                     os.path.join(REPO, "tools", "microbench_fluid.py"),
                     "--n", "256", "--json"],
                    capture_output=True, text=True, cwd=REPO, env=env,
                    timeout=args.bench_timeout)
                log(f, f"microbench_fluid rc={r3.returncode}\n"
                       + "\n".join((r3.stdout or "").strip().splitlines()[-15:])
                       + "\n--- stderr tail ---\n"
                       + "\n".join((r3.stderr or "").strip().splitlines()[-10:]))
                with open(args.out.replace(".json", "_microbench_fluid.txt"),
                          "w") as g:
                    g.write(r3.stdout or "")
                    g.write("\n--- stderr ---\n")
                    g.write(r3.stderr or "")
            except subprocess.TimeoutExpired:
                log(f, "microbench_fluid timed out")
            # the adjoint's price while the window is warm (PR 19):
            # primal-vs-VJP wall per piece + the fft/scatter/widening
            # census — the measured side of the grad_* graph budgets
            try:
                r3g = subprocess.run(
                    [sys.executable,
                     os.path.join(REPO, "tools", "microbench_grad.py"),
                     "--n", "256", "--json"],
                    capture_output=True, text=True, cwd=REPO, env=env,
                    timeout=args.bench_timeout)
                log(f, f"microbench_grad rc={r3g.returncode}\n"
                       + "\n".join((r3g.stdout or "").strip().splitlines()[-10:])
                       + "\n--- stderr tail ---\n"
                       + "\n".join((r3g.stderr or "").strip().splitlines()[-10:]))
                with open(args.out.replace(".json", "_microbench_grad.txt"),
                          "w") as g:
                    g.write(r3g.stdout or "")
                    g.write("\n--- stderr ---\n")
                    g.write(r3g.stderr or "")
            except subprocess.TimeoutExpired:
                log(f, "microbench_grad timed out")
            # stamp the graph-contract state of the captured code rev
            # (PR 8): the audit's children force the CPU backend
            # themselves, so this costs no relay time — it just rides
            # the same capture so the bench numbers and the compiled-
            # graph census land as one auditable pair
            try:
                r4 = subprocess.run(
                    [sys.executable,
                     os.path.join(REPO, "tools", "graph_audit.py"),
                     "--json"],
                    capture_output=True, text=True, cwd=REPO, env=env,
                    timeout=args.bench_timeout)
                log(f, f"graph_audit rc={r4.returncode}\n"
                       + "\n".join((r4.stdout or "").strip().splitlines()[-5:]))
                with open(args.out.replace(".json", "_graph_audit.json"),
                          "w") as g:
                    g.write(r4.stdout or "")
            except subprocess.TimeoutExpired:
                log(f, "graph_audit timed out")
            # serving-latency capture (PR 12): cold-vs-warm
            # request-to-first-step through the warm-pool router on
            # the still-healthy accelerator — the only place the
            # REAL-device cold-start cost (and the warm pool's
            # amortization of it) is ever measured; CI's serve check
            # pins the same drill on CPU
            try:
                r6 = subprocess.run(
                    [sys.executable,
                     os.path.join(REPO, "tools", "serve.py"),
                     "bench"],
                    capture_output=True, text=True, cwd=REPO, env=env,
                    timeout=args.bench_timeout)
                log(f, f"serve bench rc={r6.returncode}\n"
                       + "\n".join((r6.stdout or "").strip().splitlines()[-3:]))
                with open(args.out.replace(".json", "_serve.json"),
                          "w") as g:
                    g.write(r6.stdout or "")
            except subprocess.TimeoutExpired:
                log(f, "serve bench timed out")
            # seventh step (PR 13): measured engine search on the real
            # accelerator — the ONLY place the tuning DB's numbers can
            # come from. Publishes winners to a per-capture DB next to
            # the artifact (never straight onto the committed
            # TUNING_DB.json — a human promotes it after `tune.py
            # check` holds); flagship-matched marker lattices per size
            try:
                tune_db = args.out.replace(".json", "_tuning_db.json")
                r7 = subprocess.run(
                    [sys.executable,
                     os.path.join(REPO, "tools", "tune.py"),
                     "search", "--n", "128,256",
                     "--engines",
                     "packed,packed_bf16,pallas_packed,packed3_bf16,mxu",
                     "--dtypes", "f32", "--chunk-lengths", "1,4",
                     "--reps", "5", "--publish", "--db", tune_db],
                    capture_output=True, text=True, cwd=REPO, env=env,
                    timeout=args.bench_timeout)
                log(f, f"tune search rc={r7.returncode}\n"
                       + "\n".join((r7.stderr or "").strip().splitlines()[-5:]))
                with open(args.out.replace(".json", "_tune.json"),
                          "w") as g:
                    g.write(r7.stdout or "")
            except subprocess.TimeoutExpired:
                log(f, "tune search timed out")
            # eighth step (PR 14): measured SLO attainment on the real
            # device — the CPU drill in CI proves the mechanism, but
            # only a healthy window can stamp what the latency SLOs
            # look like where traffic actually runs. Advisory here
            # (the exit code is logged, not enforced): the committed
            # SLO.json ceilings are CPU-calibrated.
            try:
                r8 = subprocess.run(
                    [sys.executable,
                     os.path.join(REPO, "tools", "slo.py"),
                     "check", "--backend", "device", "--json"],
                    capture_output=True, text=True, cwd=REPO, env=env,
                    timeout=args.bench_timeout)
                log(f, f"slo check rc={r8.returncode}\n"
                       + "\n".join((r8.stdout or "").strip().splitlines()[-3:]))
                with open(args.out.replace(".json", "_slo.json"),
                          "w") as g:
                    g.write(r8.stdout or "")
            except subprocess.TimeoutExpired:
                log(f, "slo check timed out")
            # ninth step (PR 15): comm attribution + merged-ledger
            # capture. Attribute each profile capture NOW so the
            # comm_s op-class rollup (and the roofline's achieved
            # interconnect GB/s) lands in prof_summary.json before
            # the archive step prunes the raw trace; then, when the
            # record dir holds per-process ledger shards (a pod run),
            # land the merged fleet rollup next to the bench artifact
            for d in profs:
                try:
                    r9 = subprocess.run(
                        [sys.executable,
                         os.path.join(REPO, "tools", "prof.py"),
                         "attribute", d, "--json"],
                        capture_output=True, text=True, cwd=REPO,
                        env=env, timeout=600)
                    tail = ""
                    try:
                        oc = (json.loads(r9.stdout or "{}")
                              .get("op_classes") or {})
                        tail = f"  comm_s={oc.get('comm_s')}"
                    except ValueError:
                        pass
                    log(f, f"comm attribution {d} "
                           f"rc={r9.returncode}{tail}")
                except subprocess.TimeoutExpired:
                    log(f, f"comm attribution timed out for {d}")
            shard_dir = os.path.dirname(os.path.abspath(args.out))
            if glob.glob(os.path.join(shard_dir, "ledger-*.jsonl")):
                try:
                    r9b = subprocess.run(
                        [sys.executable,
                         os.path.join(REPO, "tools", "obs.py"),
                         "summary", shard_dir, "--fleet"],
                        capture_output=True, text=True, cwd=REPO,
                        env=env, timeout=600)
                    log(f, f"fleet rollup rc={r9b.returncode}\n"
                           + "\n".join((r9b.stdout or ""
                                        ).strip().splitlines()[:4]))
                    with open(args.out.replace(".json", "_fleet.txt"),
                              "w") as g:
                        g.write(r9b.stdout or "")
                except subprocess.TimeoutExpired:
                    log(f, "fleet rollup timed out")
            # tenth step (PR 17): the sustained-traffic soak grid —
            # open-loop Poisson+burst arrivals over the heavy-tailed
            # mix, requests/s + shed rate per (rate, duration) cell.
            # A CPU-child signal like the serve leg (the device run's
            # health is what gated us here; the soak grid itself is
            # hermetic), landed next to the bench artifact so traffic
            # capacity is trended per healthy window.
            try:
                r10 = subprocess.run(
                    [sys.executable, "-c",
                     "import json; from bench import soak_reference; "
                     "print(json.dumps(soak_reference()))"],
                    capture_output=True, text=True, cwd=REPO, env=env,
                    timeout=args.bench_timeout)
                tail = ""
                try:
                    grid = (json.loads(r10.stdout or "{}")
                            .get("grid") or [])
                    if grid:
                        tail = (f"  cells={len(grid)} "
                                f"rps={grid[-1].get('requests_per_s')} "
                                f"shed={grid[-1].get('shed_rate')}")
                except ValueError:
                    pass
                log(f, f"soak grid rc={r10.returncode}{tail}")
                with open(args.out.replace(".json", "_soak.json"),
                          "w") as g:
                    g.write(r10.stdout or "")
            except subprocess.TimeoutExpired:
                log(f, "soak grid timed out")
            # eleventh step (PR 18): the elastic warm-pool drill —
            # mix shift + memory pressure + crash-safe restart in a
            # CPU child; scale-up latency, restart-to-warm time, and
            # fresh restart compiles (must stay 0) are trended per
            # healthy window next to the soak grid.
            try:
                r11 = subprocess.run(
                    [sys.executable, "-c",
                     "import json; "
                     "from bench import elastic_reference; "
                     "print(json.dumps(elastic_reference()))"],
                    capture_output=True, text=True, cwd=REPO, env=env,
                    timeout=args.bench_timeout)
                tail = ""
                try:
                    el = json.loads(r11.stdout or "{}")
                    if "scale_up_s" in el:
                        tail = (f"  scale_up={el['scale_up_s']} "
                                f"restart={el['restart_warm_s']} "
                                f"fresh="
                                f"{el['restart_fresh_compiles']}")
                except ValueError:
                    pass
                log(f, f"elastic drill rc={r11.returncode}{tail}")
                with open(args.out.replace(".json", "_elastic.json"),
                          "w") as g:
                    g.write(r11.stdout or "")
            except subprocess.TimeoutExpired:
                log(f, "elastic drill timed out")
            # thirteenth step (PR 20): the clean assimilation cadence
            # — per-cycle analysis wall vs the chunk cadence and
            # cycles/s for a small and a large ensemble in a CPU
            # child; a between-chunk cost regression (retrace, host
            # sync in the gain) is trended per healthy window next to
            # the soak/elastic legs.
            try:
                r13 = subprocess.run(
                    [sys.executable, "-c",
                     "import json; "
                     "from bench import assim_reference; "
                     "print(json.dumps(assim_reference()))"],
                    capture_output=True, text=True, cwd=REPO, env=env,
                    timeout=args.bench_timeout)
                tail = ""
                try:
                    asm = json.loads(r13.stdout or "{}")
                    if asm.get("legs"):
                        tail = "  " + " ".join(
                            f"B={g['lanes']}:"
                            f"{g['analysis_wall_steady_s']}s/"
                            f"{g['cycles_per_s']}cyc/s"
                            for g in asm["legs"])
                except ValueError:
                    pass
                log(f, f"assim cadence rc={r13.returncode}{tail}")
                with open(args.out.replace(".json", "_assim.json"),
                          "w") as g:
                    g.write(r13.stdout or "")
            except subprocess.TimeoutExpired:
                log(f, "assim cadence timed out")
            # fifth step (PR 10): archive each profile capture — the
            # attribution summary is the regression-comparable
            # artifact; the raw multi-MB traces are pruned ONLY after
            # `prof.py archive` schema-validated the summary (a
            # malformed summary exits 2 and the raw trace survives for
            # a human to parse)
            for d in profs:
                try:
                    r5 = subprocess.run(
                        [sys.executable,
                         os.path.join(REPO, "tools", "prof.py"),
                         "archive", d],
                        capture_output=True, text=True, cwd=REPO,
                        env=env, timeout=600)
                    log(f, f"prof archive {d} rc={r5.returncode}\n"
                           + "\n".join((r5.stdout or ""
                                        ).strip().splitlines()[-3:])
                           + ("\n" + "\n".join(
                               (r5.stderr or ""
                                ).strip().splitlines()[-5:])
                              if r5.returncode else ""))
                    if r5.returncode:
                        log(f, f"prof archive FAILED for {d}; raw "
                               "trace kept for manual attribution")
                except subprocess.TimeoutExpired:
                    log(f, f"prof archive timed out for {d}")
        else:
            log(f, "bench ran but did not produce a TPU JSON line; re-arming")
            time.sleep(args.interval)
    log(f, f"watcher exit: captures={captures}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
