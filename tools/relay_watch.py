"""Relay watcher: poll the TPU relay and fire the flagship bench the
moment it comes back (VERDICT.md round 3, "Next round" item 1 — treat
relay-watching as a deliverable, not luck).

Loop: probe the accelerator backend in a killable subprocess every
``--interval`` seconds. On the first healthy probe, immediately run

  1. ``bench.py`` (staged flagship shootout; stdout JSON captured to
     ``--out``), and
  2. ``tools/microbench_transfer.py`` at 256^3 (per-engine legs),

then keep polling: if the relay was healthy but the bench failed to
produce a TPU-platform JSON line (the relay can die mid-run), the
watcher re-arms and tries again on the next healthy window, up to
``--max-captures`` successful captures.

Everything is logged to ``--log`` with timestamps so a later reader can
reconstruct exactly when the relay was up.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def log(f, msg: str) -> None:
    line = f"[{time.strftime('%Y-%m-%d %H:%M:%S')}] {msg}"
    print(line, file=sys.stderr, flush=True)
    f.write(line + "\n")
    f.flush()


def last_json_line(text: str):
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=240.0)
    ap.add_argument("--probe-timeout", type=float, default=90.0)
    ap.add_argument("--bench-timeout", type=float, default=3600.0)
    ap.add_argument("--out", type=str,
                    default=os.path.join(REPO, "BENCH_TPU_CAPTURE.json"))
    ap.add_argument("--log", type=str,
                    default=os.path.join(REPO, "relay_watch.log"))
    ap.add_argument("--max-captures", type=int, default=1)
    ap.add_argument("--max-hours", type=float, default=11.0)
    args = ap.parse_args()

    from ibamr_tpu.utils.backend_guard import probe_accelerator

    deadline = time.time() + args.max_hours * 3600.0
    captures = 0
    f = open(args.log, "a")
    log(f, f"watcher start: interval={args.interval}s "
           f"probe_timeout={args.probe_timeout}s out={args.out}")
    while time.time() < deadline and captures < args.max_captures:
        plat, err = probe_accelerator(args.probe_timeout)
        if plat is None or plat == "cpu":
            log(f, f"probe: relay unavailable ({err}); sleeping "
                   f"{args.interval:.0f}s")
            time.sleep(args.interval)
            continue
        log(f, f"probe: HEALTHY platform={plat} — launching bench shootout")
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)  # let the container default win
        t0 = time.time()
        try:
            r = subprocess.run(
                [sys.executable, os.path.join(REPO, "bench.py"),
                 "--stages", "64,128,256"],
                capture_output=True, text=True, cwd=REPO, env=env,
                timeout=args.bench_timeout)
        except subprocess.TimeoutExpired:
            log(f, f"bench TIMED OUT after {args.bench_timeout:.0f}s; "
                   f"re-arming")
            time.sleep(args.interval)
            continue
        dtr = time.time() - t0
        result = last_json_line(r.stdout or "")
        log(f, f"bench rc={r.returncode} wall={dtr:.0f}s "
               f"result={json.dumps(result) if result else 'NO JSON'}")
        tail = "\n".join((r.stderr or "").strip().splitlines()[-30:])
        log(f, "bench stderr tail:\n" + tail)
        if result is not None and result.get("platform") not in (None, "cpu"):
            with open(args.out, "w") as g:
                json.dump(result, g, indent=1)
            log(f, f"CAPTURED TPU bench -> {args.out}")
            captures += 1
            # follow with the per-engine microbench while the window is warm
            try:
                r2 = subprocess.run(
                    [sys.executable,
                     os.path.join(REPO, "tools", "microbench_transfer.py"),
                     "--n", "256"],
                    capture_output=True, text=True, cwd=REPO, env=env,
                    timeout=args.bench_timeout)
                log(f, f"microbench rc={r2.returncode}\n"
                       + "\n".join((r2.stdout or "").strip().splitlines()[-25:])
                       + "\n--- stderr tail ---\n"
                       + "\n".join((r2.stderr or "").strip().splitlines()[-15:]))
                with open(args.out.replace(".json", "_microbench.txt"),
                          "w") as g:
                    g.write(r2.stdout or "")
                    g.write("\n--- stderr ---\n")
                    g.write(r2.stderr or "")
            except subprocess.TimeoutExpired:
                log(f, "microbench timed out")
        else:
            log(f, "bench ran but did not produce a TPU JSON line; re-arming")
            time.sleep(args.interval)
    log(f, f"watcher exit: captures={captures}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
