"""Fleet runner: B ensemble lanes through ONE vmapped trace (PR 7).

Runs ``--lanes B`` independently-perturbed instances of the 3-D shell
as a lane-stacked fleet: every state leaf carries a leading lane axis,
the chunk is ONE ``jax.vmap``-ped scan shared by all lanes, dt is a
(B,) vector and a (B,) lane-alive mask freezes quarantined lanes
in-graph — so B scenarios cost ONE compile and one host transfer per
chunk instead of B of each. Under ``ResilientDriver`` supervision a
lane that goes bad is rolled back alone (its slice restored from the
newest verified checkpoint, its dt backed off), and quarantined after
retry exhaustion — the other B-1 lanes never stop stepping.

Prints ONE JSON line (last line of stdout) with per-lane status
(steps completed, alive, dt, retries) and aggregate steps/s; progress
goes to stderr. ``--sequential`` also runs each lane alone as a B=1
fleet (the bitwise solo reference — docs/RESILIENCE.md "Lane
isolation") and reports the aggregate-vs-sequential speedup.

Examples::

    python tools/fleet.py --lanes 8 --steps 16 --dir /tmp/fleet
    python tools/fleet.py --lanes 64 --n 32 --sequential
    python tools/fleet.py --lanes 64 --mesh 8 --dir /tmp/pod  # B x D pod

``--mesh D`` composes the two scaling axes (PR 16): the lane axis is
sharded over a D-device lane mesh (``parallel.mesh.make_lane_mesh``),
each device owns B/D whole lanes, checkpoints go through the sharded
manifest path (elastic N→M restart re-places surviving lanes), and the
per-lane quarantine/dt machinery is untouched — sharded == replicated
bitwise in f64 (tests/test_fleet_mesh.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def perturb_lane(state, i: int, scale: float = 0.01):
    """Lane i's initial condition: the base state with a deterministic
    per-lane velocity perturbation (relative scale + a tiny absolute
    offset so lane 0 still differs from the unperturbed base)."""
    ins = state.ins
    u = tuple(c * (1.0 + scale * i) + 1e-4 * scale * (i + 1)
              for c in ins.u)
    return state._replace(ins=ins._replace(u=u))


def lane_steps(state, lane: int):
    """Steps completed by one lane (the per-lane fluid step counter)."""
    import numpy as np
    k = state.ins.k if hasattr(state, "ins") else state.k
    return int(np.asarray(k)[lane])


def build_fleet(n, n_lat, n_lon, mu, lanes, perturb, dtype):
    from ibamr_tpu.models.shell3d import build_shell_example
    from ibamr_tpu.utils.lanes import stack_lanes

    integ, st0 = build_shell_example(n_cells=n, n_lat=n_lat,
                                     n_lon=n_lon, mu=mu, dtype=dtype)
    lane_states = [perturb_lane(st0, i, perturb) for i in range(lanes)]
    return integ, lane_states, stack_lanes(lane_states)


def _emit_chunk_census(drv, stacked, cfg, lanes, lane_mesh):
    """Emit the structural comm census of the fleet chunk (PR 16) into
    the attached run ledger as one ``graph_census`` record, so the
    per-proc rollup (``tools/obs.py summary --fleet``) can show each
    process's hidden/unhidden collective split next to its measured
    ``comm_s`` share. One extra trace of the chunk per run; the traced
    signature is identical to the real run's, so the no-retrace
    contract (``trace_counts``) is untouched."""
    import jax
    import jax.numpy as jnp

    from ibamr_tpu import obs
    from ibamr_tpu.analysis.graph_census import structural_overlap_census

    n = min(cfg.health_interval, cfg.num_steps)
    fn = drv._chunk(n)
    fn = getattr(fn, "__wrapped__", fn)
    jx = jax.make_jaxpr(fn)(stacked, jnp.asarray(drv.lane_dt),
                            jnp.asarray(drv.lane_alive))
    c = structural_overlap_census(jx.jaxpr)
    obs.emit("graph_census", scope="fleet_chunk", chunk_length=n,
             lanes=lanes,
             mesh_devices=(int(lane_mesh.devices.size)
                           if lane_mesh is not None else 0),
             structural_collectives=c["structural_collectives"],
             hidden_collectives=c["hidden_collectives"],
             unhidden_collectives=c["unhidden_collectives"],
             hidden_fraction=c["hidden_fraction"])


def run_fleet(integ, stacked, cfg, lanes, directory=None,
              max_retries=2, dt_backoff=0.5, quarantine_threshold=0.5,
              heartbeat=None, lane_mesh=None):
    """One supervised fleet run; returns (summary dict, final state).

    With ``lane_mesh`` the lane axis is sharded over the mesh's devices
    (B×D pod fleet): the stacked state is device_put under the lane
    sharding, the chunk pins it there, and checkpoints/restores go
    through the sharded manifest path so an elastic N→M restart
    re-places surviving lanes."""
    import contextlib

    from ibamr_tpu import obs
    from ibamr_tpu.utils.health import HealthProbe
    from ibamr_tpu.utils.hierarchy_driver import HierarchyDriver
    from ibamr_tpu.utils.supervisor import ResilientDriver

    if lane_mesh is not None:
        from ibamr_tpu.parallel.mesh import place_lanes
        stacked = place_lanes(stacked, lane_mesh)
    probe = HealthProbe.for_integrator(integ)
    drv = HierarchyDriver(integ, cfg, lanes=lanes, health_probe=probe,
                          lane_mesh=lane_mesh)
    wd = None
    if heartbeat:
        from ibamr_tpu.utils.watchdog import RunWatchdog
        wd = RunWatchdog(heartbeat_path=heartbeat, interval_s=5.0,
                         min_stall_s=300.0)
    t0 = time.perf_counter()
    ledger_path = None
    ledger_seq = None
    if directory:
        # run ledger: spans/counters/incidents of THIS run land in one
        # seq-ordered stream, stamped with the flight-recorder run_id
        from ibamr_tpu.utils.flight_recorder import FlightRecorder
        try:
            fp = FlightRecorder(capacity=1).fingerprint(driver=drv)
        except Exception:
            fp = None
        ledger_path = os.path.join(directory, "ledger.jsonl")
        ledger_cm = obs.ledger(ledger_path, fingerprint=fp)
    else:
        ledger_cm = contextlib.nullcontext()
    if directory:
        sup = ResilientDriver(drv, directory, max_retries=max_retries,
                              dt_backoff=dt_backoff,
                              quarantine_threshold=quarantine_threshold,
                              handle_signals=False, watchdog=wd,
                              sharded=lane_mesh is not None,
                              mesh=lane_mesh,
                              incident_log=os.path.join(
                                  directory, "incidents.jsonl"))
        with ledger_cm as led:
            try:
                _emit_chunk_census(drv, stacked, cfg, lanes, lane_mesh)
            except Exception as e:  # noqa: BLE001 - census is advisory
                log(f"[fleet] chunk census skipped: "
                    f"{type(e).__name__}: {e}")
            final = sup.run(stacked)
        ledger_seq = led.last_seq if led is not None else None
        incidents = list(sup.incidents)
    else:
        if wd is not None:
            wd.start()
        try:
            final = drv.run(stacked)
        finally:
            if wd is not None:
                wd.stop()
        incidents = []
    wall = time.perf_counter() - t0

    per_lane = []
    total_steps = 0
    for i in range(lanes):
        k = lane_steps(final, i)
        total_steps += k
        per_lane.append({
            "lane": i,
            "steps": k,
            "alive": bool(drv.lane_alive[i]),
            "dt": float(drv.lane_dt[i]),
        })
    quarantined = sum(1 for rec in per_lane if not rec["alive"])
    backed_off = sum(1 for rec in per_lane
                     if rec["dt"] != float(cfg.dt))
    summary = {
        "lanes": lanes,
        "num_steps": cfg.num_steps,
        "wall_s": round(wall, 3),
        # aggregate throughput: lane-steps actually completed across
        # the whole fleet per wall second (compile included — both
        # legs of the sequential comparison pay it once)
        "aggregate_steps_per_s": round(total_steps / wall, 3),
        "lanes_quarantined": quarantined,
        "lanes_backed_off": backed_off,
        "trace_counts": dict(drv.trace_counts),
        "incidents": [r.get("event") for r in incidents],
        "per_lane": per_lane,
    }
    if lane_mesh is not None:
        summary["mesh_devices"] = int(lane_mesh.devices.size)
        summary["lanes_per_device"] = lanes // int(lane_mesh.devices.size)
    if ledger_path is not None:
        summary["ledger_path"] = ledger_path
        summary["ledger_records"] = (ledger_seq + 1
                                     if ledger_seq is not None else 0)
    return summary, final


def run_sequential(integ, lane_states, cfg):
    """Each lane alone as a B=1 fleet (the bitwise solo reference),
    back to back; returns aggregate steps/s over all lanes. The B=1
    trace is shared across lanes (identical signature), so compile is
    paid once here too — the comparison isolates the batching win."""
    from ibamr_tpu.utils.hierarchy_driver import HierarchyDriver
    from ibamr_tpu.utils.lanes import stack_lanes

    t0 = time.perf_counter()
    total = 0
    drv = HierarchyDriver(integ, cfg, lanes=1)
    for st in lane_states:
        final = drv.run(stack_lanes([st]))
        total += lane_steps(final, 0)
        # fresh per-lane dt/alive for the next lane; the compiled
        # chunk survives on the driver
        drv.lane_dt[0] = float(cfg.dt)
        drv.lane_alive[0] = True
    wall = time.perf_counter() - t0
    return {"wall_s": round(wall, 3),
            "aggregate_steps_per_s": round(total / wall, 3)}


def main():
    ap = argparse.ArgumentParser(
        description="vmapped ensemble fleet runner")
    ap.add_argument("--lanes", type=int, default=8,
                    help="fleet size B (8 and 64 are the reference "
                         "points)")
    ap.add_argument("--n", type=int, default=32, help="cells/axis")
    ap.add_argument("--n-lat", type=int, default=16)
    ap.add_argument("--n-lon", type=int, default=16)
    ap.add_argument("--mu", type=float, default=0.05)
    ap.add_argument("--steps", type=int, default=16)
    ap.add_argument("--dt", type=float, default=1e-3)
    ap.add_argument("--health-interval", type=int, default=4)
    ap.add_argument("--restart-interval", type=int, default=8)
    ap.add_argument("--perturb", type=float, default=0.01,
                    help="per-lane initial-velocity perturbation scale")
    ap.add_argument("--dir", type=str, default="",
                    help="checkpoint + incident directory (enables "
                         "per-lane rollback/quarantine supervision)")
    ap.add_argument("--max-retries", type=int, default=2)
    ap.add_argument("--dt-backoff", type=float, default=0.5)
    ap.add_argument("--quarantine-threshold", type=float, default=0.5)
    ap.add_argument("--heartbeat", type=str, default="",
                    help="heartbeat.json path (carries lanes_ok/"
                         "lanes_quarantined/lanes_retrying)")
    ap.add_argument("--mesh", type=int, nargs="?", const=0, default=None,
                    metavar="D",
                    help="shard the lane axis over a D-device lane "
                         "mesh (omit D to use every visible device); "
                         "lanes must divide D evenly — the B×D pod "
                         "fleet with per-lane quarantine/dt intact")
    ap.add_argument("--sequential", action="store_true",
                    help="also run every lane alone (B=1) and report "
                         "the speedup")
    ap.add_argument("--x64", action="store_true",
                    help="run the fleet in float64")
    args = ap.parse_args()

    result = {"lanes": args.lanes, "error": None}
    try:
        from ibamr_tpu.utils.backend_guard import init_backend_with_retry

        jax, platform, backend_err = init_backend_with_retry(
            retries=1, delay=2.0)
        result["platform"] = platform
        if args.x64:
            jax.config.update("jax_enable_x64", True)
        from ibamr_tpu.utils.hierarchy_driver import RunConfig

        cfg = RunConfig(dt=args.dt, num_steps=args.steps,
                        health_interval=args.health_interval,
                        restart_interval=(args.restart_interval
                                          if args.dir else 0))
        log(f"[fleet] building {args.lanes} lanes of the "
            f"{args.n}^3 shell ({args.n_lat * args.n_lon} markers)")
        integ, lane_states, stacked = build_fleet(
            args.n, args.n_lat, args.n_lon, args.mu, args.lanes,
            args.perturb, "float64" if args.x64 else None)
        lane_mesh = None
        if args.mesh is not None:
            from ibamr_tpu.parallel.mesh import make_lane_mesh
            lane_mesh = make_lane_mesh(
                n_devices=args.mesh if args.mesh > 0 else None)
            result["mesh_devices"] = int(lane_mesh.devices.size)
            log(f"[fleet] lane mesh: {result['mesh_devices']} devices "
                f"x {args.lanes // result['mesh_devices']} lanes each")
        summary, _ = run_fleet(
            integ, stacked, cfg, args.lanes,
            directory=args.dir or None, max_retries=args.max_retries,
            dt_backoff=args.dt_backoff,
            quarantine_threshold=args.quarantine_threshold,
            heartbeat=args.heartbeat or None, lane_mesh=lane_mesh)
        result.update(summary)
        log(f"[fleet] {args.lanes} lanes x {args.steps} steps: "
            f"{summary['aggregate_steps_per_s']} lane-steps/s "
            f"({summary['lanes_quarantined']} quarantined)")
        if args.sequential:
            cfg_solo = RunConfig(dt=args.dt, num_steps=args.steps,
                                 health_interval=args.health_interval)
            seq = run_sequential(integ, lane_states, cfg_solo)
            result["sequential"] = seq
            if seq["aggregate_steps_per_s"] > 0:
                result["fleet_speedup"] = round(
                    summary["aggregate_steps_per_s"]
                    / seq["aggregate_steps_per_s"], 3)
            log(f"[fleet] sequential: {seq['aggregate_steps_per_s']} "
                f"lane-steps/s -> speedup "
                f"{result.get('fleet_speedup')}")
    except Exception as e:  # noqa: BLE001 - the JSON line must land
        import traceback
        result["error"] = (f"{type(e).__name__}: {e}\n"
                           + traceback.format_exc()[-1200:])
    print(json.dumps(result), flush=True)
    return 0 if result["error"] is None else 1


if __name__ == "__main__":
    sys.exit(main())
