"""Fault-injection harness for the resilience layer (PR 2 tentpole 4).

The recovery machinery (atomic verified checkpoints, the
ResilientDriver rollback loop, engine degradation) is only trustworthy
if the failure paths are EXERCISED — a recovery path that has never run
is a second bug waiting behind the first. This module supplies the
deterministic fault injectors the resilience tests and the multichip
dryrun drill are built from:

- :func:`nan_injector_step` / :func:`inject_nan` — poison a named state
  leaf with NaN at a chosen step, inside or outside jit. The jittable
  wrapper is dt-gated so a supervised retry at backed-off dt passes
  cleanly (the injected fault models a too-aggressive timestep, the
  exact failure dt-backoff exists to cure).
- :func:`truncate_checkpoint` / :func:`corrupt_checkpoint` /
  :func:`drop_sidecar` — the three on-disk damage modes a crash or a
  bad disk can leave: a short file, flipped bytes at unchanged size,
  and an array file whose commit marker never landed.
- :func:`failing_checkpoint_writes` — make the Nth checkpoint write(s)
  raise, underneath the async writer's retry.
- :func:`run_crash_child` — the deterministic checkpoint-writer loop
  the SIGKILL-mid-write subprocess drill runs as its victim: the whole
  trajectory is a closed-form function of the step count
  (:func:`crash_state`), so the parent can verify any restored
  checkpoint bitwise without trusting the child.
- :func:`run_smoke` — a self-contained end-to-end drill (supervised
  NaN recovery + corruption fallback + flaky-write retry) wired into
  ``__graft_entry__.dryrun_multichip`` as path 16 and exposed as
  ``python -m tools.fault_injection --smoke``.
- :func:`bf16_drift_injector` / :func:`volume_leak_injector` (PR 5) —
  the silent-precision and invariant-violation faults the flight
  recorder + replay harness and the physics sentinels are drilled
  against, plus the :data:`ACTIVE_INJECTORS` registry that makes an
  injected fault part of the run fingerprint (so ``tools/replay.py``
  reproduces it BITWISE in a fresh process).
- :func:`run_replay_smoke` — record -> trip the shadow audit ->
  precision-escalate -> replay bitwise -> classify, as dryrun path 18
  and ``python -m tools.fault_injection --replay-smoke``.
- :func:`record_capsule_drill` — the victim process for the
  kill-and-replay drill: records a capsule, prints ``CAPSULE <dir>``
  and lingers for the parent's SIGKILL.
- :func:`corrupt_shard` / :func:`drop_shard` / :func:`tear_manifest` /
  :func:`stale_manifest_shard` (PR 6) — the on-disk failure modes a
  DISTRIBUTED writer adds: one shard of many damaged or lost, a torn
  commit marker, a shard rewritten after its manifest committed.
- :func:`run_sharded_crash_child` — the sharded SIGKILL-mid-commit
  victim loop (per-shard writes + manifest commit, closed-form
  trajectory, ``SAVED`` markers), and :func:`run_sharded_smoke` — the
  end-to-end sharded-checkpoint drill (no-gather save audit, elastic
  restore, damage inventory, concurrent-writer collision, supervised
  sharded rollback, ``tools.ckpt_fsck`` gate) wired as dryrun path 19
  and ``python -m tools.fault_injection --sharded-smoke``.
- :func:`lane_nan_injector` / :func:`lane_drift_injector` (PR 7) —
  faults confined to ONE lane of a vmapped fleet chunk, and
  :func:`run_fleet_smoke` — the end-to-end lane-quarantine drill (one
  poisoned lane, per-lane rollback + dt backoff, quarantine, healthy
  lanes bitwise untouched, sliced-capsule replay) wired as dryrun
  path 20 and ``python -m tools.fault_injection --fleet-smoke``.
- :func:`compile_storm_injector` / :func:`slow_lane_injector` /
  :func:`failing_build_injector` / :func:`kill_router_thread_injector`
  (PR 17) — SERVING-path faults against the warm-pool router: slow
  bucket compiles, straggler lanes, builds that raise, and build
  threads that die without publishing. These are latency/liveness
  faults, never state-value faults, so they are NOT ``recorded()`` —
  there is nothing for the flight recorder to replay bitwise.
  :func:`run_soak_smoke` composes them over the PR-17 open-loop load
  generator into the traffic-robustness drill (dryrun path 21,
  ``python -m tools.fault_injection --soak-smoke``): a chaos tenant
  burns through novel families and injected faults at a 4x burst
  while healthy tenants keep their warm p99, with the no-deadlock /
  no-lost-request / bounded-shed invariants pinned from the merged
  ledger.
- :func:`mix_shift_injector` / :func:`memory_pressure_injector`
  (PR 18) — ELASTICITY faults: the arrival mix rotates to an unseen
  bucket family mid-soak (pure schedule transform, bit-replayable),
  and the executable cache's bytes ceiling is squeezed mid-run.
  :func:`run_elastic_smoke` composes them into the elastic warm-pool
  drill (dryrun path 22, ``python -m tools.fault_injection
  --elastic-smoke``): the ElasticPoolManager must grow the shifted
  family before any of its requests shed, ride the brownout ladder
  without oscillating, shrink the cold family, and survive a
  checkpoint/restore restart with ZERO fresh XLA compiles.
- :func:`run_design_smoke` (PR 19) — the INVERSE-DESIGN drill (dryrun
  path 23, ``python -m tools.fault_injection --design-smoke``): the
  eel2d gait objective differentiated THROUGH the coupled rollout —
  the compiled adjoint must agree with an f64 central difference,
  three Adam iterations through ``DesignLoop`` must strictly decrease
  the objective, iteration 1 pays exactly one executable-cache MISS
  and iterations 2+ are pure HITS (zero warm compiles), and every
  iteration lands one ``design_iter`` ledger record.
- :func:`obs_dropout_injector` / :func:`obs_outlier_injector` /
  :func:`stale_obs_injector` / :func:`member_divergence_injector`
  (PR 20) — ASSIMILATION faults: dead, spiking and stale sensor
  channels as pure transforms of the assimilation cycle's
  ``obs_source`` seam, plus one ensemble member diverging mid-run
  (lane_nan mechanics, ``recorded()`` for capsule replay).
  :func:`run_assim_smoke` arms all four at once over the B-lane
  forecasting service (dryrun path 24, ``python -m
  tools.fault_injection --assim-smoke``): the QC gate rejects exactly
  the injected (channel, cycle, reason) triples, the divergent member
  is quarantined and excluded from the masked analysis statistics,
  every cycle lands a terminal ``assim_cycle`` record (zero lost),
  the final forecast error beats the open-loop ensemble, and the
  whole episode retraces nothing.

Everything here is deliberately boring and deterministic: no random
fuzzing, every fault lands at a named step/byte so a failure
reproduces.
"""

from __future__ import annotations

import argparse
import contextlib
import dataclasses
import json
import os
import tempfile
import threading
import time

import numpy as np


# ---------------------------------------------------------------------------
# NaN injection
# ---------------------------------------------------------------------------

def _match_paths(state, leaf_path: str):
    """Pytree paths whose keystr contains ``leaf_path`` (e.g. ``"u[0]"``
    matches the first MAC velocity component of an INSState)."""
    import jax

    flat = jax.tree_util.tree_flatten_with_path(state)[0]
    return [jax.tree_util.keystr(p) for p, _ in flat
            if leaf_path in jax.tree_util.keystr(p)]


def inject_nan(state, leaf_path: str):
    """Host-side: return ``state`` with NaN written into every floating
    leaf whose path contains ``leaf_path``. Raises if nothing matches
    (a typo'd path must not silently inject nothing)."""
    import jax
    import jax.numpy as jnp

    hit = []

    def _poison(path, leaf):
        key = jax.tree_util.keystr(path)
        if leaf_path in key and hasattr(leaf, "dtype") \
                and jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
            hit.append(key)
            bad = jnp.asarray(leaf).at[...].set(jnp.nan)
            return bad
        return leaf

    out = jax.tree_util.tree_map_with_path(_poison, state)
    if not hit:
        raise KeyError(
            f"no floating leaf path contains {leaf_path!r}; "
            f"available: {_match_paths(state, '')}")
    return out


def nan_injector_step(step_fn, at_step: int, leaf_path: str = "u",
                      dt_gate: float | None = None,
                      step_attr: str = "k"):
    """Wrap ``step_fn(state, dt) -> state`` so the stepped state comes
    out poisoned (NaN in every floating leaf matching ``leaf_path``)
    exactly when its step counter ``state.<step_attr>`` equals
    ``at_step`` — jit/scan-safe (the fault is a ``jnp.where`` on traced
    values, not python control flow). ``step_attr`` may be dotted
    (``"ins.k"`` reaches the fluid counter inside a coupled IB state).

    ``dt_gate`` arms the fault only while ``dt >= dt_gate``: a
    supervised retry at backed-off dt then passes cleanly, modelling an
    instability that a smaller timestep cures. Without it the injector
    would re-fire on every retry and the supervisor could never win.
    """
    import jax
    import jax.numpy as jnp

    def wrapped(state, dt):
        out = step_fn(state, dt)
        k = out
        for attr in step_attr.split("."):
            k = getattr(k, attr)
        fire = jnp.asarray(k) == at_step
        if dt_gate is not None:
            fire = jnp.logical_and(fire, jnp.asarray(dt) >= dt_gate)
        hit = []

        def _poison(path, leaf):
            key = jax.tree_util.keystr(path)
            if leaf_path in key and hasattr(leaf, "dtype") \
                    and jnp.issubdtype(leaf.dtype, jnp.floating):
                hit.append(key)
                return jnp.where(fire, jnp.asarray(jnp.nan, leaf.dtype),
                                 leaf)
            return leaf

        out = jax.tree_util.tree_map_with_path(_poison, out)
        if not hit:
            raise KeyError(f"no floating leaf path contains {leaf_path!r}")
        return out

    return wrapped


# ---------------------------------------------------------------------------
# Silent-failure injectors (PR 3): finite-but-diverging growth, a
# stagnating linear operator, and a slow host step — the three failure
# shapes the vitals / escalation / watchdog layers each exist to catch
# ---------------------------------------------------------------------------

def growth_injector_step(step_fn, rate: float = 1.5,
                         leaf_path: str = "u",
                         dt_gate: float | None = None):
    """Wrap ``step_fn(state, dt) -> state`` so every floating leaf
    matching ``leaf_path`` is multiplied by ``rate`` per step — a
    FINITE exponential blow-up, the silent failure the plain finite
    flag cannot see until checkpoints already hold garbage. jit/scan
    safe (the factor is a traced ``jnp.where``).

    ``dt_gate`` arms the growth only while ``dt >= dt_gate``, so the
    supervisor's dt backoff cures it — modelling an instability whose
    growth rate a smaller timestep tames.
    """
    import jax
    import jax.numpy as jnp

    def wrapped(state, dt):
        out = step_fn(state, dt)
        fire = jnp.asarray(True) if dt_gate is None \
            else jnp.asarray(dt) >= dt_gate
        hit = []

        def _grow(path, leaf):
            key = jax.tree_util.keystr(path)
            if leaf_path in key and hasattr(leaf, "dtype") \
                    and jnp.issubdtype(leaf.dtype, jnp.floating):
                hit.append(key)
                factor = jnp.where(fire, jnp.asarray(rate, leaf.dtype),
                                   jnp.asarray(1.0, leaf.dtype))
                return leaf * factor
            return leaf

        out = jax.tree_util.tree_map_with_path(_grow, out)
        if not hit:
            raise KeyError(f"no floating leaf path contains {leaf_path!r}")
        return out

    return wrapped


def stagnating_operator(A, direction=None):
    """Wrap a pytree linear operator so it is SINGULAR along
    ``direction`` (default: the all-ones pytree): the wrapper projects
    the input off that direction before applying ``A``, so any rhs with
    a component outside the crippled range leaves a residual floor no
    Krylov iteration can pass — a deterministic stagnating solve (the
    escalation chain walks, every level fails, ``SolverBreakdown``).
    """
    import jax
    import jax.numpy as jnp

    from ibamr_tpu.solvers.krylov import tree_axpy, tree_dot

    def wrapped(x):
        e = direction if direction is not None \
            else jax.tree_util.tree_map(jnp.ones_like, x)
        coef = tree_dot(e, x) / tree_dot(e, e)
        return A(tree_axpy(-coef, e, x))

    return wrapped


def slow_metrics(sleep_s: float, at_steps=None, metrics_fn=None):
    """A ``metrics_fn`` wrapper that sleeps ``sleep_s`` on the host —
    the watchdog drill's stalled chunk (from the outside a hung compile
    / dead relay and a sleeping callback look identical: no beat).
    ``at_steps`` limits the stall to the named post-chunk steps
    (``None`` = every chunk)."""
    at = None if at_steps is None else {int(s) for s in at_steps}

    def wrapped(state, step):
        if at is None or int(step) in at:
            time.sleep(sleep_s)
        return metrics_fn(state, step) if metrics_fn is not None else None

    return wrapped


# ---------------------------------------------------------------------------
# Recorded injectors (PR 5): faults the flight recorder fingerprints so
# tools/replay.py can RE-ARM them in a fresh process — without this, a
# capsule of an injected failure would replay clean and read as
# not_reproduced. ACTIVE_INJECTORS maps injector name -> JSON-safe
# params for every currently-armed recorded fault.
# ---------------------------------------------------------------------------

ACTIVE_INJECTORS: dict = {}


@contextlib.contextmanager
def recorded(name: str, **params):
    """Register an armed fault in ``ACTIVE_INJECTORS`` for the duration
    of the block, so flight-recorder fingerprints (and therefore replay
    capsules) carry it. The caller still applies the actual injector;
    this context only makes it REPRODUCIBLE. Params must be JSON-safe
    and sufficient for :func:`apply_recorded_injectors` to rebuild the
    injector (see the per-name cases there)."""
    if name in ACTIVE_INJECTORS:
        raise ValueError(f"recorded injector {name!r} already armed")
    ACTIVE_INJECTORS[name] = dict(params)
    try:
        yield params
    finally:
        ACTIVE_INJECTORS.pop(name, None)


@contextlib.contextmanager
def bf16_drift_injector(scale: float = 0.35):
    """Deterministically bias the bf16 spectral path's split-real
    operand rounding by ``(1 + scale)`` — k-space algebra corruption
    that ONLY fires on the mixed-precision path (``_round_complex`` is
    not called at f32/f64), so precision escalation or an
    ``--override spectral_dtype=f64`` replay genuinely cures it. The
    drift is smooth and finite: the plain finite flag never trips, only
    the f64 shadow audit can see it. Registers itself in
    ``ACTIVE_INJECTORS`` as ``bf16_drift``.

    NOTE: the patch takes effect at TRACE time — jit executables
    compiled before entering the context keep the clean rounding. Clear
    relevant caches (or use fresh chunk shapes) when arming mid-process.
    """
    with _bare_bf16_drift(scale):
        with recorded("bf16_drift", scale=float(scale)):
            yield


def volume_leak_injector(step_fn, rate: float = 0.01,
                         leaf_path: str = "X",
                         dt_gate: float | None = None):
    """Wrap ``step_fn(state, dt) -> state`` so every floating leaf
    matching ``leaf_path`` (default: the IB marker positions) is
    contracted toward its centroid by ``rate`` per step — a secular
    enclosed-volume drift (membrane leakage). The state stays finite
    and smooth; only the volume sentinel (vitals slot 5) can see it.
    jit/scan-safe; ``dt_gate`` arms the leak only while
    ``dt >= dt_gate`` (the supervisor's backoff disarms it)."""
    import jax
    import jax.numpy as jnp

    def wrapped(state, dt):
        out = step_fn(state, dt)
        fire = jnp.asarray(True) if dt_gate is None \
            else jnp.asarray(dt) >= dt_gate
        hit = []

        def _leak(path, leaf):
            key = jax.tree_util.keystr(path)
            if leaf_path in key and hasattr(leaf, "dtype") \
                    and jnp.issubdtype(leaf.dtype, jnp.floating) \
                    and getattr(leaf, "ndim", 0) >= 1:
                hit.append(key)
                c = jnp.mean(leaf, axis=0, keepdims=True)
                factor = jnp.where(fire,
                                   jnp.asarray(1.0 - rate, leaf.dtype),
                                   jnp.asarray(1.0, leaf.dtype))
                return c + (leaf - c) * factor
            return leaf

        out = jax.tree_util.tree_map_with_path(_leak, out)
        if not hit:
            raise KeyError(f"no floating leaf path contains {leaf_path!r}")
        return out

    return wrapped


# ---------------------------------------------------------------------------
# Lane-targeted injectors (PR 7): faults that poison exactly ONE lane of
# a vmapped fleet chunk — the failure shape the lane-quarantine and
# per-lane-rollback machinery exists to contain. They wrap the STACKED
# (already-vmapped) step, so the fire condition can address lanes.
# ---------------------------------------------------------------------------

def lane_nan_injector(stacked_step, at_step: int, lane: int,
                      fleet_size: int, leaf_path: str = "u",
                      dt_gate: float | None = None,
                      step_attr: str = "k"):
    """Wrap a STACKED ``step_fn(state, dt_vec) -> state`` (every leaf
    lane-stacked, dt a (B,) vector) so exactly lane ``lane``'s rows of
    every floating leaf matching ``leaf_path`` come out NaN when that
    lane's step counter equals ``at_step`` — jit/scan/vmap-safe (the
    fault is a ``jnp.where`` on traced values). Other lanes' rows pass
    through BITWISE untouched (``jnp.where`` is elementwise), which is
    what the healthy-lanes-unperturbed drill assertion pins.

    ``dt_gate`` arms the fault only while the LANE'S dt is
    ``>= dt_gate``: a per-lane dt backoff then cures it. Without the
    gate the injector re-fires on every per-lane retry, driving the
    lane to retry exhaustion and quarantine — the drill's second act.
    """
    import jax
    import jax.numpy as jnp

    lane_ids = jnp.arange(int(fleet_size))

    def wrapped(state, dt):
        out = stacked_step(state, dt)
        k = out
        for attr in step_attr.split("."):
            k = getattr(k, attr)
        fire = jnp.logical_and(lane_ids == lane,
                               jnp.asarray(k) == at_step)
        if dt_gate is not None:
            fire = jnp.logical_and(fire, jnp.asarray(dt) >= dt_gate)
        hit = []

        def _poison(path, leaf):
            key = jax.tree_util.keystr(path)
            if leaf_path in key and hasattr(leaf, "dtype") \
                    and jnp.issubdtype(leaf.dtype, jnp.floating):
                hit.append(key)
                m = fire.reshape((int(fleet_size),)
                                 + (1,) * (leaf.ndim - 1))
                return jnp.where(m, jnp.asarray(jnp.nan, leaf.dtype),
                                 leaf)
            return leaf

        out = jax.tree_util.tree_map_with_path(_poison, out)
        if not hit:
            raise KeyError(f"no floating leaf path contains {leaf_path!r}")
        return out

    return wrapped


def lane_drift_injector(stacked_step, rate: float = 1.5, lane: int = 0,
                        fleet_size: int = 1, leaf_path: str = "u",
                        dt_gate: float | None = None):
    """Wrap a STACKED step so lane ``lane``'s rows of every floating
    leaf matching ``leaf_path`` are multiplied by ``rate`` per step — a
    FINITE exponential blow-up confined to one lane, the silent failure
    only the per-lane vitals triage (``HealthProbe.check_lanes``) can
    attribute to the right lane. ``dt_gate`` arms the drift only while
    the lane's dt is ``>= dt_gate`` (per-lane backoff cures it)."""
    import jax
    import jax.numpy as jnp

    lane_ids = jnp.arange(int(fleet_size))

    def wrapped(state, dt):
        out = stacked_step(state, dt)
        fire = lane_ids == lane
        if dt_gate is not None:
            fire = jnp.logical_and(fire, jnp.asarray(dt) >= dt_gate)
        hit = []

        def _grow(path, leaf):
            key = jax.tree_util.keystr(path)
            if leaf_path in key and hasattr(leaf, "dtype") \
                    and jnp.issubdtype(leaf.dtype, jnp.floating):
                hit.append(key)
                m = fire.reshape((int(fleet_size),)
                                 + (1,) * (leaf.ndim - 1))
                return leaf * jnp.where(m, jnp.asarray(rate, leaf.dtype),
                                        jnp.asarray(1.0, leaf.dtype))
            return leaf

        out = jax.tree_util.tree_map_with_path(_grow, out)
        if not hit:
            raise KeyError(f"no floating leaf path contains {leaf_path!r}")
        return out

    return wrapped


@contextlib.contextmanager
def apply_recorded_injectors(injectors: dict):
    """Re-arm the faults a replay manifest recorded. Context-style
    faults (``bf16_drift``) are entered for the block; step-level
    faults yield through the returned ``wrap(step_fn)`` function, which
    the replay harness applies to the rebuilt integrator's step. Param
    vocabularies match what :func:`recorded` blocks in this module and
    the tests register:

    - ``bf16_drift``: {scale}
    - ``nan``: {at_step, leaf_path, dt_gate} -> nan_injector_step
    - ``growth``: {rate, leaf_path, dt_gate} -> growth_injector_step
    - ``volume_leak``: {rate, leaf_path, dt_gate} -> volume_leak_injector
    - ``lane_nan`` / ``lane_drift``: lane-targeted faults; the wrap
      applies to the STACKED step (replay of a lane capsule builds a
      B=1 fleet chunk and transforms ``lane``/``fleet_size`` before
      calling this — see ``tools.replay._lane_injectors``)
    - ``member_divergence``: the assimilation drill's lane fault
      (lane_nan mechanics under its own name, same lane transform)

    Unknown names raise: silently dropping a recorded fault would turn
    every replay of it into a false ``not_reproduced``/"cured" verdict.
    """
    wrappers = []
    with contextlib.ExitStack() as stack:
        for name, params in (injectors or {}).items():
            params = dict(params)
            if name == "bf16_drift":
                stack.enter_context(
                    _bare_bf16_drift(scale=params.get("scale", 0.35)))
            elif name == "nan":
                wrappers.append(lambda fn, p=params:
                                nan_injector_step(fn, **p))
            elif name == "growth":
                wrappers.append(lambda fn, p=params:
                                growth_injector_step(fn, **p))
            elif name == "volume_leak":
                wrappers.append(lambda fn, p=params:
                                volume_leak_injector(fn, **p))
            elif name == "lane_nan":
                wrappers.append(lambda fn, p=params:
                                lane_nan_injector(fn, **p))
            elif name == "lane_drift":
                wrappers.append(lambda fn, p=params:
                                lane_drift_injector(fn, **p))
            elif name == "member_divergence":
                wrappers.append(lambda fn, p=params:
                                member_divergence_injector(fn, **p))
            else:
                raise KeyError(
                    f"replay manifest records unknown injector {name!r}")

        def wrap(step_fn):
            for w in wrappers:
                step_fn = w(step_fn)
            return step_fn

        yield wrap


@contextlib.contextmanager
def _bare_bf16_drift(scale: float):
    """bf16_drift patch WITHOUT the ACTIVE_INJECTORS registration
    (replay must not re-record the fault it is re-arming)."""
    from ibamr_tpu.solvers import spectral_plan as sp

    orig = sp._round_complex

    def biased(z, sdtype):
        return orig(z, sdtype) * (1.0 + scale)

    sp._round_complex = biased
    try:
        yield
    finally:
        sp._round_complex = orig


# ---------------------------------------------------------------------------
# On-disk checkpoint damage
# ---------------------------------------------------------------------------

def _ckpt_path(directory: str, step: int, ext: str = "npz") -> str:
    return os.path.join(directory, f"restore.{step:08d}.{ext}")


def truncate_checkpoint(directory: str, step: int,
                        keep_bytes: int | None = None) -> str:
    """Chop the array file short (default: half) — what a torn write
    WOULD look like if the writer were not atomic. The sidecar's size
    record must now flunk verification."""
    path = _ckpt_path(directory, step)
    size = os.path.getsize(path)
    keep = size // 2 if keep_bytes is None else keep_bytes
    with open(path, "r+b") as f:
        f.truncate(keep)
    return path

def corrupt_checkpoint(directory: str, step: int,
                       offset: int | None = None) -> str:
    """Flip one byte WITHOUT changing the size — the bad-disk/bitrot
    mode that only the CRC32 can catch."""
    path = _ckpt_path(directory, step)
    size = os.path.getsize(path)
    pos = size // 2 if offset is None else offset
    with open(path, "r+b") as f:
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ 0xFF]))
    return path


def drop_sidecar(directory: str, step: int) -> str:
    """Remove the JSON commit marker: the array file may be perfect but
    without a sidecar the checkpoint never committed."""
    path = _ckpt_path(directory, step, "json")
    os.remove(path)
    return path


@contextlib.contextmanager
def failing_checkpoint_writes(fail_calls, exc_type=OSError):
    """Patch ``checkpoint._write_arrays`` so the 0-based call indices
    in ``fail_calls`` raise ``exc_type``. The async writer's retry
    looks the symbol up per attempt, so ``{0}`` fails only the first
    attempt and the retry lands. Yields the call counter dict."""
    from ibamr_tpu.utils import checkpoint as _ckpt

    fail = set(fail_calls)
    orig = _ckpt._write_arrays
    counter = {"calls": 0}

    def flaky(*args, **kwargs):
        i = counter["calls"]
        counter["calls"] += 1
        if i in fail:
            raise exc_type(f"injected checkpoint write failure (call {i})")
        return orig(*args, **kwargs)

    _ckpt._write_arrays = flaky
    try:
        yield counter
    finally:
        _ckpt._write_arrays = orig


# ---------------------------------------------------------------------------
# Crash-child loop (SIGKILL-mid-write victim)
# ---------------------------------------------------------------------------

def crash_state(step: int, n: int = 64) -> dict:
    """Closed-form deterministic trajectory: the state after ``step``
    iterations of a fixed contraction map. float64 numpy, so every
    process that evaluates it gets bitwise-identical leaves — the
    parent verifies a child's checkpoint by recomputing, not by
    trusting the (possibly killed) child."""
    u = np.linspace(0.0, 1.0, n)
    for k in range(1, step + 1):
        u = np.cos(u) * 0.9 + 0.01 * k
    return {"u": u, "k": np.int64(step)}


def run_crash_child(directory: str, num_steps: int, interval: int,
                    keep: int = 3) -> int:
    """The victim loop: resume from the newest VERIFIED checkpoint,
    iterate the contraction map, checkpoint every ``interval`` steps
    printing ``SAVED <k>`` markers (the parent kills on a marker).
    Returns the step reached."""
    from ibamr_tpu.utils.checkpoint import (latest_step,
                                            restore_checkpoint,
                                            save_checkpoint)

    start = latest_step(directory)
    if start is None:
        start, u = 0, crash_state(0)["u"]
    else:
        state, start, _ = restore_checkpoint(
            directory, template=crash_state(start), step=start)
        u = np.asarray(state["u"])
    print(f"START {start}", flush=True)
    for k in range(start + 1, num_steps + 1):
        u = np.cos(u) * 0.9 + 0.01 * k
        if k % interval == 0:
            save_checkpoint(directory, {"u": u, "k": np.int64(k)}, k,
                            keep=keep)
            print(f"SAVED {k}", flush=True)
    print("DONE", flush=True)
    return num_steps


# ---------------------------------------------------------------------------
# End-to-end smoke drill
# ---------------------------------------------------------------------------

def run_smoke(directory: str | None = None) -> dict:
    """Deterministic end-to-end resilience drill on a 16^2 INS run:

    1. supervised recovery — NaN injected at step 6 diverges the run;
       the ResilientDriver rolls back to the step-4 checkpoint, halves
       dt (which disarms the dt-gated injector) and completes;
    2. corruption fallback — flip a byte in the newest checkpoint and
       prove ``latest_step``/``restore_checkpoint`` fall back to the
       newest VERIFIED one;
    3. flaky-write retry — fail the next write's first attempt and
       prove the async writer's retry still lands a verified file.

    Returns (and the CLI prints) a one-line JSON summary. Raises on
    any failed expectation — wired into the multichip dryrun rotation,
    so a regression in the recovery path fails CI, not a real run.
    """
    import jax.numpy as jnp

    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
    from ibamr_tpu.utils.checkpoint import (AsyncCheckpointWriter,
                                            latest_step,
                                            restore_checkpoint,
                                            verify_checkpoint)
    from ibamr_tpu.utils.hierarchy_driver import HierarchyDriver, RunConfig
    from ibamr_tpu.utils.supervisor import ResilientDriver

    tmp = None
    if directory is None:
        tmp = tempfile.TemporaryDirectory(prefix="ibamr_fault_smoke_")
        directory = tmp.name
    try:
        g = StaggeredGrid(n=(16, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
        integ = INSStaggeredIntegrator(g, rho=1.0, mu=0.05)
        xf, yc = g.face_centers(0, jnp.float32)
        xc, yf = g.face_centers(1, jnp.float32)
        u = jnp.sin(2 * jnp.pi * xf) * jnp.cos(2 * jnp.pi * yc) + 0 * yc
        v = -jnp.cos(2 * jnp.pi * xc) * jnp.sin(2 * jnp.pi * yf) + 0 * xc
        st0 = integ.initialize(u0_arrays=(u, v))

        dt0 = 1e-3
        cfg = RunConfig(dt=dt0, num_steps=12, restart_interval=4,
                        health_interval=2)
        drv = HierarchyDriver(
            integ, cfg,
            step_fn=nan_injector_step(integ.step, at_step=6,
                                      leaf_path="u[0]",
                                      dt_gate=dt0 * 0.99))
        sup = ResilientDriver(drv, directory, max_retries=2,
                              dt_backoff=0.5, handle_signals=False)
        out = sup.run(st0)
        if int(out.k) != cfg.num_steps:
            raise AssertionError(f"supervised run stopped at {int(out.k)}")
        if not bool(jnp.all(jnp.isfinite(out.u[0]))):
            raise AssertionError("supervised run finished non-finite")
        div = [r for r in sup.incidents if r["event"] == "divergence"]
        if len(div) != 1 or div[0]["rollback_step"] != 4:
            raise AssertionError(f"unexpected incidents: {sup.incidents}")

        # 2. corruption fallback
        newest = latest_step(directory)
        corrupt_checkpoint(directory, newest)
        if verify_checkpoint(directory, newest):
            raise AssertionError("byte flip went undetected")
        fell_back = latest_step(directory)
        if fell_back is None or fell_back >= newest:
            raise AssertionError("latest_step did not fall back")
        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            _, got, _ = restore_checkpoint(directory, template=out)
        if got != fell_back:
            raise AssertionError("restore did not fall back")

        # 3. flaky-write retry under the async writer
        w = AsyncCheckpointWriter(directory, keep=3)
        try:
            with failing_checkpoint_writes({0}) as ctr:
                w.save(out, 99)
                w.wait()
            if ctr["calls"] != 2:
                raise AssertionError(f"expected a retry, saw {ctr}")
        finally:
            w.close()
        if not verify_checkpoint(directory, 99):
            raise AssertionError("retried write is not verified")

        return {"fault_smoke": "ok", "divergence_incidents": len(div),
                "rollback_step": div[0]["rollback_step"],
                "corrupt_step_skipped": newest,
                "fallback_step": fell_back,
                "flaky_write_calls": ctr["calls"]}
    finally:
        if tmp is not None:
            tmp.cleanup()


def run_silent_smoke(directory: str | None = None) -> dict:
    """Deterministic end-to-end SILENT-failure drill (PR 3, dryrun
    path 17) exercising all three early-warning layers:

    1. **health precursor** — a finite exponential velocity growth
       (``growth_injector_step``, dt-gated) on a 16^2 INS run trips the
       fused :class:`HealthProbe`'s functional-growth WARN streak; the
       ResilientDriver rolls back and backs dt off BEFORE any
       non-finite value ever materializes (every classified chunk must
       report ``finite == 1``), and the run completes;
    2. **solver escalation** — a restarted-GMRES-hostile diagonal
       system fails at the base geometry and at restarts_x4, converges
       at deep_x4_inner_x2 (the full declared chain walks, one
       recovered ``solver_escalation`` incident); the same system
       behind :func:`stagnating_operator` exhausts the chain and raises
       ``SolverBreakdown`` with a structured incident;
    3. **watchdog** — a slow host callback (``slow_metrics``) stalls a
       supervised run long past the rolling chunk expectation; the
       ResilientDriver-owned watchdog records a ``stall`` incident into
       the same ``incidents.jsonl`` and the heartbeat file holds the
       last REAL beat.

    Raises on any failed expectation; returns a one-line JSON summary.
    """
    import jax.numpy as jnp

    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
    from ibamr_tpu.solvers.escalation import SolverBreakdown, escalate_solve
    from ibamr_tpu.solvers.krylov import fgmres
    from ibamr_tpu.utils.health import HealthProbe
    from ibamr_tpu.utils.hierarchy_driver import HierarchyDriver, RunConfig
    from ibamr_tpu.utils.supervisor import ResilientDriver
    from ibamr_tpu.utils.watchdog import RunWatchdog, read_heartbeat

    tmp = None
    if directory is None:
        tmp = tempfile.TemporaryDirectory(prefix="ibamr_silent_smoke_")
        directory = tmp.name
    try:
        # -- 1. finite-blowup precursor: rollback before any NaN ------
        g = StaggeredGrid(n=(16, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
        integ = INSStaggeredIntegrator(g, rho=1.0, mu=0.05)
        xf, yc = g.face_centers(0, jnp.float32)
        xc, yf = g.face_centers(1, jnp.float32)
        u = jnp.sin(2 * jnp.pi * xf) * jnp.cos(2 * jnp.pi * yc) + 0 * yc
        v = -jnp.cos(2 * jnp.pi * xc) * jnp.sin(2 * jnp.pi * yf) + 0 * xc
        st0 = integ.initialize(u0_arrays=(u, v))

        dt0 = 1e-3
        probe = HealthProbe.for_integrator(integ, func_growth_warn=8.0,
                                           sustain=2)
        cfg = RunConfig(dt=dt0, num_steps=12, restart_interval=4,
                        health_interval=2)
        drv = HierarchyDriver(
            integ, cfg,
            step_fn=growth_injector_step(integ.step, rate=1.5,
                                         leaf_path="u",
                                         dt_gate=dt0 * 0.99),
            health_probe=probe)
        health_dir = os.path.join(directory, "health")
        sup = ResilientDriver(drv, health_dir, max_retries=2,
                              dt_backoff=0.5, handle_signals=False)
        out = sup.run(st0)
        if int(out.k) != cfg.num_steps:
            raise AssertionError(f"health drill stopped at {int(out.k)}")
        if not bool(jnp.all(jnp.isfinite(out.u[0]))):
            raise AssertionError("health drill finished non-finite")
        if any(rec["finite"] < 1.0 for rec in probe.history):
            raise AssertionError(
                "a non-finite value materialized — the precursor fired "
                "too late")
        hd = [r for r in sup.incidents
              if r["event"] == "divergence"
              and r.get("kind") == "health_degraded"]
        if len(hd) != 1 or hd[0]["rollback_step"] != 4:
            raise AssertionError(f"unexpected incidents: {sup.incidents}")
        if not hd[0].get("reasons"):
            raise AssertionError("health incident carries no reasons")

        # -- 2. solver escalation: recover, then exhaust --------------
        w = jnp.logspace(0, 2, 48)          # restarted-GMRES-hostile
        A = lambda x: w * x                 # noqa: E731
        b = jnp.ones(48)

        def attempt(level, _i):
            return fgmres(A, b, m=8 * level.m_scale, tol=1e-4,
                          restarts=1 * level.restarts_scale)

        esc_incidents = []
        sol = escalate_solve(attempt, context="silent_smoke_diag",
                             on_incident=esc_incidents.append)
        if not bool(sol.converged):
            raise AssertionError("escalated solve did not converge")
        if len(esc_incidents) != 1 \
                or esc_incidents[0]["event"] != "solver_escalation" \
                or not esc_incidents[0]["recovered"] \
                or len(esc_incidents[0]["attempts"]) != 3:
            raise AssertionError(f"unexpected escalation record: "
                                 f"{esc_incidents}")

        As = stagnating_operator(A)

        def attempt_stag(level, _i):
            return fgmres(As, b, m=8 * level.m_scale, tol=1e-4,
                          restarts=1 * level.restarts_scale)

        breakdown = None
        try:
            escalate_solve(attempt_stag, context="silent_smoke_stagnant",
                           on_incident=esc_incidents.append, step=42)
        except SolverBreakdown as e:
            breakdown = e
        if breakdown is None or breakdown.step != 42:
            raise AssertionError("stagnating solve did not break down")
        if esc_incidents[-1]["event"] != "solver_breakdown" \
                or esc_incidents[-1]["recovered"]:
            raise AssertionError(f"unexpected breakdown record: "
                                 f"{esc_incidents[-1]}")

        # -- 3. watchdog: the stalled chunk is an incident ------------
        cfg2 = RunConfig(dt=dt0, num_steps=8, health_interval=2)
        drv2 = HierarchyDriver(integ, cfg2)
        drv2.run(st0, start_step=6)         # warm the chunk compile
        drv2.metrics_fn = slow_metrics(1.2, at_steps={4})
        wd_dir = os.path.join(directory, "wd")
        wd = RunWatchdog(heartbeat_path=wd_dir, interval_s=0.05,
                         stall_factor=3.0, min_stall_s=0.4)
        sup2 = ResilientDriver(drv2, wd_dir, handle_signals=False,
                               watchdog=wd)
        sup2.run(st0)
        stalls = [r for r in sup2.incidents if r["event"] == "stall"]
        if not stalls or stalls[0].get("kind") != "stall":
            raise AssertionError(f"no stall incident: {sup2.incidents}")
        hb = read_heartbeat(os.path.join(wd_dir, "heartbeat.json"))
        if hb is None or hb["step"] is None:
            raise AssertionError(f"no usable heartbeat: {hb}")

        return {"silent_smoke": "ok",
                "health_rollback_step": hd[0]["rollback_step"],
                "health_reasons": hd[0]["reasons"],
                "escalation_recovered_level": esc_incidents[0]["level"],
                "breakdown_attempts": len(breakdown.attempts),
                "stall_incidents": len(stalls),
                "heartbeat_step": hb["step"]}
    finally:
        if tmp is not None:
            tmp.cleanup()


def _tg16_setup(spectral_dtype=None):
    """Shared 16^2 Taylor-Green INS setup for the drills."""
    import jax.numpy as jnp

    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.ins import INSStaggeredIntegrator

    g = StaggeredGrid(n=(16, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    integ = INSStaggeredIntegrator(g, rho=1.0, mu=0.05,
                                   spectral_dtype=spectral_dtype)
    xf, yc = g.face_centers(0, jnp.float32)
    xc, yf = g.face_centers(1, jnp.float32)
    u = jnp.sin(2 * jnp.pi * xf) * jnp.cos(2 * jnp.pi * yc) + 0 * yc
    v = -jnp.cos(2 * jnp.pi * xc) * jnp.sin(2 * jnp.pi * yf) + 0 * xc
    return integ, integ.initialize(u0_arrays=(u, v))


def run_replay_smoke(directory: str | None = None) -> dict:
    """Deterministic end-to-end REPLAY drill (PR 5, dryrun path 18):

    1. **precision escalation** — a 16^2 INS run at
       ``spectral_dtype="bf16"`` with an injected spectral rounding
       bias (:func:`bf16_drift_injector`) trips the per-chunk f64
       :class:`~ibamr_tpu.solvers.escalation.ShadowAuditor` on the
       FIRST chunk; the supervisor dumps a replay capsule, escalates
       bf16 -> f32 with dt UNCHANGED, rolls back and completes — one
       schema-v3 ``precision_escalation`` incident with a ``replay``
       pointer;
    2. **bitwise replay** — ``tools.replay`` re-executes the capsule
       in-process (fresh traces): the baseline re-arms the recorded
       injector and must match the recorded post-chunk digest bitwise
       -> verdict ``reproduced``;
    3. **classification** — the same capsule under
       ``--override spectral_dtype=f64`` no longer drifts (the biased
       bf16 rounding is never invoked on the escalated path) -> verdict
       ``precision_dependent``.

    Raises on any failed expectation; returns a one-line JSON summary.
    """
    import jax
    import jax.numpy as jnp

    from ibamr_tpu.solvers.escalation import ShadowAuditor
    from ibamr_tpu.utils.flight_recorder import FlightRecorder
    from ibamr_tpu.utils.hierarchy_driver import HierarchyDriver, RunConfig
    from ibamr_tpu.utils.supervisor import ResilientDriver
    from tools.replay import replay

    tmp = None
    if directory is None:
        tmp = tempfile.TemporaryDirectory(prefix="ibamr_replay_smoke_")
        directory = tmp.name
    try:
        integ, st0 = _tg16_setup(spectral_dtype="bf16")
        cfg = RunConfig(dt=1e-3, num_steps=8, restart_interval=4,
                        health_interval=2)
        drv = HierarchyDriver(integ, cfg,
                              recorder=FlightRecorder(capacity=4),
                              shadow_audit=ShadowAuditor(every=1,
                                                         bound=0.02))
        sup = ResilientDriver(drv, directory, max_retries=2,
                              handle_signals=False)
        with bf16_drift_injector(scale=0.35):
            # the biased rounding must reach the RETRACED chunk
            jax.clear_caches()
            out = sup.run(st0)
        if int(out.k) != cfg.num_steps:
            raise AssertionError(f"replay drill stopped at {int(out.k)}")
        if not bool(jnp.all(jnp.isfinite(out.u[0]))):
            raise AssertionError("replay drill finished non-finite")
        esc = [r for r in sup.incidents
               if r["event"] == "precision_escalation"]
        if len(esc) != 1:
            raise AssertionError(f"unexpected incidents: {sup.incidents}")
        rec = esc[0]
        if rec.get("schema") != 3 or not rec.get("replay"):
            raise AssertionError(f"incident is not replayable v3: {rec}")
        if (rec["spectral_dtype_before"], rec["spectral_dtype_after"]) \
                != ("bf16", "f32"):
            raise AssertionError(f"unexpected escalation: {rec}")
        if rec["dt"] != cfg.dt:
            raise AssertionError("precision escalation must not back "
                                 "dt off")

        base = replay(rec["replay"])
        if base["verdict"] != "reproduced" or not base["bitwise"]:
            raise AssertionError(f"baseline replay: {base}")
        cured = replay(rec["replay"],
                       overrides={"spectral_dtype": "f64"})
        if cured["verdict"] != "precision_dependent":
            raise AssertionError(f"override replay: {cured}")

        return {"replay_smoke": "ok",
                "escalation_step": rec["step"],
                "spectral_dtype_after": rec["spectral_dtype_after"],
                "drift": rec.get("drift"),
                "baseline_verdict": base["verdict"],
                "override_verdict": cured["verdict"],
                "capsule": rec["replay"]}
    finally:
        if tmp is not None:
            tmp.cleanup()


def record_capsule_drill(directory: str, linger: bool = True) -> str:
    """Victim process for the cross-mesh kill-and-replay drill: run a
    16^2 INS trajectory with a RECORDED NaN injection, let the
    supervisor dump the divergence capsule, print ``CAPSULE <dir>`` (the
    parent's kill marker) and linger until SIGKILL. The parent then
    replays the orphaned capsule on a DIFFERENT device mesh and pins it
    bitwise — capsules record unsharded host arrays, so mesh shape is
    not part of the reproduction contract."""
    from ibamr_tpu.utils.flight_recorder import FlightRecorder
    from ibamr_tpu.utils.hierarchy_driver import (HierarchyDriver,
                                                  RunConfig,
                                                  SimulationDiverged)
    from ibamr_tpu.utils.supervisor import ResilientDriver

    integ, st0 = _tg16_setup()
    cfg = RunConfig(dt=1e-3, num_steps=12, restart_interval=4,
                    health_interval=2)
    params = {"at_step": 6, "leaf_path": "u[0]"}
    with recorded("nan", **params):
        drv = HierarchyDriver(
            integ, cfg,
            step_fn=nan_injector_step(integ.step, **params),
            recorder=FlightRecorder(capacity=4))
        sup = ResilientDriver(drv, directory, max_retries=0,
                              handle_signals=False)
        try:
            sup.run(st0)
            raise AssertionError("injected NaN did not diverge the run")
        except SimulationDiverged:
            pass
    cap = sup.incidents[-1].get("replay")
    if not cap:
        raise AssertionError(f"no capsule dumped: {sup.incidents}")
    print(f"CAPSULE {cap}", flush=True)
    while linger:
        time.sleep(0.5)
    return cap


# ---------------------------------------------------------------------------
# Sharded-checkpoint damage (PR 6): the on-disk failure modes a
# DISTRIBUTED writer adds to the single-host inventory — one shard of
# many damaged, a torn commit marker, a shard rewritten after commit
# ---------------------------------------------------------------------------

def _shard_path(directory: str, step: int, shard: int) -> str:
    from ibamr_tpu.utils.checkpoint_sharded import _shard_name, _step_dir

    return os.path.join(_step_dir(directory, step), _shard_name(shard))


def corrupt_shard(directory: str, step: int, shard: int = 0,
                  offset: int | None = None) -> str:
    """Flip one byte of ONE shard file without changing its size — the
    single-device bitrot/bad-disk mode. Only the manifest's whole-file
    CRC for that shard can catch it; the other N-1 shards stay
    perfect, which is exactly why verification must be per-shard."""
    path = _shard_path(directory, step, shard)
    size = os.path.getsize(path)
    pos = size // 2 if offset is None else offset
    with open(path, "r+b") as f:
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ 0xFF]))
    return path


def drop_shard(directory: str, step: int, shard: int = 0) -> str:
    """Delete ONE shard file of a committed step — the lost-host mode:
    the writer on that host died after the manifest committed, or its
    local disk was reclaimed. The manifest still names the shard, so
    verification flunks the step."""
    path = _shard_path(directory, step, shard)
    os.remove(path)
    return path


def tear_manifest(directory: str, step: int) -> str:
    """Replace a step's manifest with a truncated (invalid-JSON)
    prefix — what a NON-atomic manifest writer killed mid-write would
    leave. With the atomic protocol this state is only reachable by
    injection, which is the point: the reader must treat it exactly
    like the no-manifest uncommitted case."""
    from ibamr_tpu.utils.checkpoint_sharded import _step_dir

    path = os.path.join(_step_dir(directory, step), "manifest.json")
    with open(path) as f:
        payload = f.read()
    with open(path, "w") as f:
        f.write(payload[: max(1, len(payload) // 2)].rstrip("}"))
    return path


def stale_manifest_shard(directory: str, step: int,
                         shard: int = 0) -> str:
    """Rewrite ONE shard file AFTER the manifest committed (arrays
    scaled by 2 — a valid npz, wrong bytes): the
    stale-manifest-newer-shards mode a restarted writer racing an old
    step leaves behind. The shard parses fine; only the manifest's
    recorded digest exposes that manifest and shard no longer describe
    the same checkpoint."""
    path = _shard_path(directory, step, shard)
    with np.load(path) as z:
        arrays = {k: np.asarray(z[k]) * 2 for k in z.files}
    with open(path, "wb") as f:
        np.savez(f, **arrays)
    return path


def run_sharded_crash_child(directory: str, num_steps: int,
                            interval: int, keep: int = 3,
                            n_devices: int = 8) -> int:
    """The sharded SIGKILL-mid-commit victim: the same closed-form
    :func:`crash_state` trajectory as :func:`run_crash_child`, but the
    state is sharded over an ``n_devices`` 1-D mesh and every
    checkpoint goes through :func:`save_sharded_checkpoint` — so the
    parent's kill lands between shard writes and the manifest commit
    (widen the window with ``IBAMR_SHARDED_COMMIT_DELAY_S``). Resumes
    from the newest VERIFIED sharded step; prints the same
    ``START``/``SAVED <k>``/``DONE`` markers.

    Requires f64 (the parent verifies restored leaves bitwise against
    the f64 closed form) — the CLI entry enables x64 before any jax
    compute."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    from ibamr_tpu.utils.checkpoint_sharded import (latest_sharded_step,
                                                    restore_sharded,
                                                    save_sharded_checkpoint)

    devs = sorted(jax.devices(), key=lambda d: d.id)[:n_devices]
    mesh = Mesh(np.array(devs), ("x",))
    sh = NamedSharding(mesh, P("x"))
    rep = NamedSharding(mesh, P())

    def place(d):
        return {"u": jax.device_put(jnp.asarray(d["u"]), sh),
                "k": jax.device_put(jnp.asarray(d["k"]), rep)}

    start = latest_sharded_step(directory)
    if start is None:
        start, u = 0, crash_state(0)["u"]
    else:
        state, start, _ = restore_sharded(
            directory, place(crash_state(start)), step=start)
        u = np.asarray(state["u"])
    print(f"START {start}", flush=True)
    for k in range(start + 1, num_steps + 1):
        u = np.cos(u) * 0.9 + 0.01 * k
        if k % interval == 0:
            save_sharded_checkpoint(
                directory, place({"u": u, "k": np.int64(k)}), k,
                keep=keep, mesh=mesh)
            print(f"SAVED {k}", flush=True)
    print("DONE", flush=True)
    return num_steps


def run_sharded_smoke(directory: str | None = None) -> dict:
    """Deterministic end-to-end SHARDED-checkpoint drill (PR 6, dryrun
    path 19), on however many devices this process has (>= 2 for the
    sharding to mean anything; the dryrun runs it on the virtual
    8-device mesh):

    1. **no-gather save + verified roundtrip** — a mesh-sharded state
       saves through :func:`save_sharded_checkpoint` with every
       device->host transfer audited to be shard-sized (never the
       global array), verifies, and restores bitwise onto the SAME
       mesh;
    2. **elastic restore** — the same step restores bitwise onto ONE
       device (N->1) from the manifest's recorded layout;
    3. **damage inventory** — single-shard byte flip, dropped shard,
       torn manifest, and a stale-manifest-newer-shard rewrite each
       flunk verification; ``latest_sharded_step``/``restore_sharded``
       fall back to the previous verified step, never silently
       restoring damage;
    4. **concurrent-writer collision** — two threads commit the SAME
       step simultaneously; the atomic per-file protocol guarantees
       the step afterwards either verifies AND restores bitwise to one
       writer's state, or is detected as unverified — never a silent
       mix of the two;
    5. **supervised sharded rollback** — a dt-gated NaN injector
       diverges a sharded INS run under
       ``ResilientDriver(sharded=True)``: rollback restores the newest
       VERIFIED sharded step through the elastic path and the run
       completes, with the divergence incident recording the mesh spec
       in its capsule fingerprint;
    6. **fsck gate** — ``tools.ckpt_fsck`` audits the drill directory:
       it must flag the damaged steps (nonzero exit) and pass clean
       after ``--repair`` quarantines them.

    Raises on any failed expectation; returns a one-line JSON summary.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    from ibamr_tpu.utils import checkpoint_sharded as cs
    from tools import ckpt_fsck

    tmp = None
    if directory is None:
        tmp = tempfile.TemporaryDirectory(prefix="ibamr_sharded_smoke_")
        directory = tmp.name
    try:
        n_dev = min(8, jax.device_count())
        devs = sorted(jax.devices(), key=lambda d: d.id)[:n_dev]
        mesh = Mesh(np.array(devs), ("x",))
        sh = NamedSharding(mesh, P("x"))

        n = 64
        base = np.linspace(-1.0, 1.0, n * n, dtype=np.float32)
        host = {"u": base.reshape(n, n), "k": np.int64(7)}
        state = {"u": jax.device_put(jnp.asarray(host["u"]), sh),
                 "k": jax.device_put(jnp.asarray(host["k"]),
                                     NamedSharding(mesh, P()))}

        # -- 1. no-gather save: audit every device->host transfer -----
        ckdir = os.path.join(directory, "ck")
        global_bytes = host["u"].nbytes
        fetched: list = []
        orig_fetch = cs._fetch_shard

        def counting_fetch(data):
            arr = orig_fetch(data)
            fetched.append(arr.nbytes)
            return arr

        cs._fetch_shard = counting_fetch
        try:
            cs.save_sharded_checkpoint(ckdir, state, 10, mesh=mesh)
        finally:
            cs._fetch_shard = orig_fetch
        grid_fetches = [b for b in fetched if b >= global_bytes]
        if n_dev > 1 and grid_fetches:
            raise AssertionError(
                f"sharded save fetched a global-sized array "
                f"({grid_fetches} bytes vs {global_bytes} global) — "
                f"the gather is back on the save path")
        if not cs.verify_sharded_checkpoint(ckdir, 10):
            raise AssertionError("fresh sharded step failed verify")

        r, got, _ = cs.restore_sharded(ckdir, state)
        if got != 10 or not np.array_equal(np.asarray(r["u"]),
                                           host["u"]):
            raise AssertionError("same-mesh sharded restore not bitwise")

        # -- 2. elastic N->1 ------------------------------------------
        one = devs[0]
        tmpl1 = {"u": jax.device_put(jnp.asarray(host["u"]), one),
                 "k": jax.device_put(jnp.asarray(host["k"]), one)}
        r1, _, _ = cs.restore_sharded(ckdir, tmpl1)
        if not np.array_equal(np.asarray(r1["u"]), host["u"]):
            raise AssertionError("elastic N->1 restore not bitwise")

        # -- 3. damage inventory --------------------------------------
        damaged = {}
        for step, damage in ((20, corrupt_shard), (30, drop_shard),
                             (40, tear_manifest),
                             (50, stale_manifest_shard)):
            cs.save_sharded_checkpoint(ckdir, state, step, mesh=mesh,
                                       keep=0)
            if damage is tear_manifest:
                damage(ckdir, step)
            else:
                damage(ckdir, step, shard=n_dev - 1)
            if cs.verify_sharded_checkpoint(ckdir, step):
                raise AssertionError(
                    f"{damage.__name__} went undetected at step {step}")
            damaged[damage.__name__] = step
        if cs.latest_sharded_step(ckdir) != 10:
            raise AssertionError(
                f"latest_sharded_step did not fall back to 10: "
                f"{cs.latest_sharded_step(ckdir)}")
        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            _, fell_back, _ = cs.restore_sharded(ckdir, state)
        if fell_back != 10:
            raise AssertionError("restore_sharded did not fall back")

        # -- 4. concurrent-writer collision ---------------------------
        import threading
        coll = os.path.join(directory, "collision")
        other = {"u": jax.device_put(jnp.asarray(host["u"] + 1.0), sh),
                 "k": state["k"]}
        errs: list = []

        def write(st):
            try:
                cs.save_sharded_checkpoint(coll, st, 60, mesh=mesh)
            except Exception as e:      # pragma: no cover - diagnostic
                errs.append(e)

        ts = [threading.Thread(target=write, args=(s,))
              for s in (state, other)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        if errs:
            raise AssertionError(f"collision writers raised: {errs}")
        collided_verified = cs.verify_sharded_checkpoint(coll, 60)
        if collided_verified:
            rc, _, _ = cs.restore_sharded(coll, state)
            ru = np.asarray(rc["u"])
            if not (np.array_equal(ru, host["u"])
                    or np.array_equal(ru, host["u"] + 1.0)):
                raise AssertionError(
                    "collision produced a verified FRANKENSTEIN step — "
                    "a mix of two writers' shards passed verification")
        else:
            # the manifest writer lost a shard-file race, so the step
            # is a detectable mix — the OTHER acceptable outcome. fsck
            # must flag it; --repair then deliberately spares a sole
            # damaged candidate (never delete the last one), so drop
            # the drill dir once detection is confirmed or the
            # clean-gate below could never pass.
            if ckpt_fsck.audit(coll)["clean"]:
                raise AssertionError(
                    "collision step failed verification but fsck "
                    "called the tree clean")
            import shutil
            shutil.rmtree(coll)

        # -- 5. supervised sharded rollback ---------------------------
        from ibamr_tpu.grid import StaggeredGrid
        from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
        from ibamr_tpu.parallel.mesh import (make_sharded_ins_step,
                                             place_state)
        from ibamr_tpu.utils.flight_recorder import FlightRecorder
        from ibamr_tpu.utils.hierarchy_driver import (HierarchyDriver,
                                                      RunConfig)
        from ibamr_tpu.utils.supervisor import ResilientDriver

        g = StaggeredGrid(n=(16, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
        integ = INSStaggeredIntegrator(g, rho=1.0, mu=0.05)
        xf, yc = g.face_centers(0, jnp.float32)
        xc, yf = g.face_centers(1, jnp.float32)
        u0 = jnp.sin(2 * jnp.pi * xf) * jnp.cos(2 * jnp.pi * yc) + 0 * yc
        v0 = -jnp.cos(2 * jnp.pi * xc) * jnp.sin(2 * jnp.pi * yf) + 0 * xc
        mesh2 = Mesh(np.array(devs[:min(2, n_dev)]), ("x",))
        st0 = place_state(integ.initialize(u0_arrays=(u0, v0)), g, mesh2)

        dt0 = 1e-3
        cfg = RunConfig(dt=dt0, num_steps=12, restart_interval=4,
                        health_interval=2)
        sup_dir = os.path.join(directory, "supervised")
        drv = HierarchyDriver(
            integ, cfg,
            step_fn=nan_injector_step(
                make_sharded_ins_step(integ, mesh2), at_step=6,
                leaf_path="u[0]", dt_gate=dt0 * 0.99),
            recorder=FlightRecorder(capacity=4))
        sup = ResilientDriver(drv, sup_dir, max_retries=2,
                              dt_backoff=0.5, handle_signals=False,
                              sharded=True, mesh=mesh2)
        out = sup.run(st0)
        if int(out.k) != cfg.num_steps:
            raise AssertionError(
                f"supervised sharded run stopped at {int(out.k)}")
        if not bool(jnp.all(jnp.isfinite(out.u[0]))):
            raise AssertionError("supervised sharded run non-finite")
        div = [r for r in sup.incidents if r["event"] == "divergence"]
        if len(div) != 1 or div[0]["rollback_step"] != 4:
            raise AssertionError(f"unexpected incidents: {sup.incidents}")
        if not cs._all_sharded_steps(sup_dir):
            raise AssertionError("supervised run wrote no sharded steps")
        import glob as _glob
        if _glob.glob(os.path.join(sup_dir, "restore.*.npz")):
            raise AssertionError(
                "sharded supervision wrote single-host checkpoints")
        if div[0].get("replay"):
            with open(os.path.join(div[0]["replay"],
                                   "manifest.json")) as f:
                cap_mesh = json.load(f)["fingerprint"].get("mesh")
            if not cap_mesh or cap_mesh.get("n_shards") \
                    != int(np.prod(mesh2.devices.shape)):
                raise AssertionError(
                    f"capsule fingerprint lacks the mesh spec: "
                    f"{cap_mesh}")

        # -- 6. fsck gate ---------------------------------------------
        rep = ckpt_fsck.audit(directory)
        n_bad = rep["counts"]["torn"] + rep["counts"]["corrupt"]
        if rep["clean"] or n_bad < len(damaged):
            raise AssertionError(
                f"fsck missed damage: {rep['counts']} vs {damaged}")
        rc = ckpt_fsck.main([directory, "--repair", "-q"])
        if rc != 1:
            raise AssertionError(f"fsck --repair exit {rc}, expected 1")
        rep2 = ckpt_fsck.audit(directory)
        if not rep2["clean"]:
            raise AssertionError(
                f"tree not clean after repair: {rep2['counts']}")
        if ckpt_fsck.main([directory, "-q"]) != 0:
            raise AssertionError("fsck exit nonzero on repaired tree")
        if cs.latest_sharded_step(ckdir) != 10:
            raise AssertionError("repair touched the verified step")

        return {"sharded_smoke": "ok", "n_devices": n_dev,
                "shard_fetches": len(fetched),
                "max_fetch_bytes": max(fetched),
                "global_bytes": global_bytes,
                "damage_detected": damaged,
                "collision_verified": bool(collided_verified),
                "rollback_step": div[0]["rollback_step"],
                "fsck_quarantined": n_bad}
    finally:
        if tmp is not None:
            tmp.cleanup()


def run_fleet_smoke(directory: str | None = None,
                    fleet_size: int = 8, bad_lane: int = 5) -> dict:
    """Deterministic end-to-end FLEET drill (PR 7, dryrun path 20): a
    B-lane vmapped ensemble of the 32^3 IB shell where ONE lane is
    poisoned mid-run, supervised by the lane-granular recovery loop.

    1. **one bad lane, one compiled trace** — B perturbed copies of the
       shell scenario step through a single vmapped chunk; an un-gated
       ``lane_nan_injector`` NaNs lane ``bad_lane`` at its 4th step.
       The driver's per-lane triage raises ``LaneFault`` naming exactly
       that lane;
    2. **per-lane rollback, then quarantine** — the supervisor restores
       ONLY the bad lane's slice from the newest verified lane-axis
       checkpoint and backs off that lane's dt (one ``lane_rollback``
       incident); the un-gated fault re-fires, retries exhaust, and the
       lane is QUARANTINED — restored rows frozen in-graph by the
       lane-alive mask (one ``lane_quarantine`` incident). The fleet
       completes; the whole recovery retraces NOTHING (one trace
       signature per chunk length);
    3. **healthy lanes untouched** — every surviving lane's final state
       is BITWISE identical to the same scenario run solo (a B=1 fleet
       chunk — the batch-size-invariance contract);
    4. **lane-sliced capsule** — the rollback incident's capsule is
       single-lane; ``tools.replay`` re-executes it unbatched (B=1,
       injector re-armed onto lane 0) and must match the recorded
       post-chunk digest bitwise -> verdict ``reproduced``.

    Raises on any failed expectation; returns a one-line JSON summary.
    Needs x64 (bitwise pins are f64) — enabled here if not already.
    """
    import jax
    import jax.numpy as jnp

    from ibamr_tpu.models.shell3d import build_shell_example
    from ibamr_tpu.utils.flight_recorder import (FlightRecorder,
                                                 factory_spec)
    from ibamr_tpu.utils.hierarchy_driver import HierarchyDriver, RunConfig
    from ibamr_tpu.utils.lanes import lane_slice, stack_lanes
    from ibamr_tpu.utils.supervisor import ResilientDriver
    from tools.replay import replay

    if not jax.config.jax_enable_x64:
        jax.config.update("jax_enable_x64", True)

    B, BAD = int(fleet_size), int(bad_lane)
    tmp = None
    if directory is None:
        tmp = tempfile.TemporaryDirectory(prefix="ibamr_fleet_smoke_")
        directory = tmp.name
    try:
        kwargs = dict(n_cells=32, n_lat=16, n_lon=16, mu=0.05,
                      dtype="float64")
        integ, st0 = build_shell_example(**kwargs)
        # heterogeneous fleet: per-lane initial-velocity perturbation
        lane_states = [st0._replace(ins=st0.ins._replace(
            u=tuple(c * (1.0 + 0.01 * i) + 1e-4 * (i + 1)
                    for c in st0.ins.u))) for i in range(B)]
        fleet0 = stack_lanes(lane_states)

        dt0 = 1e-3
        cfg = RunConfig(dt=dt0, num_steps=8, restart_interval=2,
                        health_interval=2)
        inj = dict(at_step=4, lane=BAD, fleet_size=B,
                   leaf_path="u[0]", step_attr="ins.k")
        with recorded("lane_nan", **inj):
            drv = HierarchyDriver(
                integ, cfg, lanes=B,
                fleet_step_wrap=lambda s: lane_nan_injector(s, **inj),
                recorder=FlightRecorder(capacity=4, spec=factory_spec(
                    "ibamr_tpu.models.shell3d", "build_shell_example",
                    **kwargs)))
            sup = ResilientDriver(drv, directory, max_retries=1,
                                  dt_backoff=0.5, handle_signals=False)
            out = sup.run(fleet0)

        k = np.asarray(out.ins.k)
        healthy = [i for i in range(B) if i != BAD]
        if any(int(k[i]) != cfg.num_steps for i in healthy):
            raise AssertionError(f"healthy lanes did not finish: {k}")
        if drv.lane_alive[BAD]:
            raise AssertionError("bad lane was never quarantined")
        bad_u = np.asarray(out.ins.u[0][BAD])
        if not np.isfinite(bad_u).all():
            raise AssertionError(
                "quarantined lane holds non-finite rows — the restore "
                "before freeze did not land")
        if float(drv.lane_dt[BAD]) != dt0 * 0.5:
            raise AssertionError(
                f"bad lane dt not backed off once: {drv.lane_dt}")
        if any(float(d) != dt0 for i, d in enumerate(drv.lane_dt)
               if i != BAD):
            raise AssertionError("a healthy lane's dt was touched")
        rolls = [r for r in sup.incidents
                 if r["event"] == "lane_rollback"]
        quars = [r for r in sup.incidents
                 if r["event"] == "lane_quarantine"]
        if len(rolls) != 1 or len(quars) != 1:
            raise AssertionError(f"unexpected incidents: "
                                 f"{[r['event'] for r in sup.incidents]}")
        if rolls[0]["lane"] != BAD or quars[0]["lane"] != BAD:
            raise AssertionError("incidents name the wrong lane")
        if not rolls[0]["from_checkpoint"]:
            raise AssertionError("rollback did not come from a "
                                 "verified checkpoint")
        # the recovery must never retrace: one signature per length
        if any(c != 1 for c in drv.trace_counts.values()):
            raise AssertionError(f"fleet recovery retraced: "
                                 f"{drv.trace_counts}")

        # -- 3. healthy lanes bitwise equal to solo (B=1) runs --------
        ref_cfg = RunConfig(dt=dt0, num_steps=8, health_interval=2)
        for i in healthy:
            ref_drv = HierarchyDriver(integ, ref_cfg, lanes=1)
            ref = ref_drv.run(stack_lanes([lane_states[i]]))
            got = jax.tree_util.tree_leaves(lane_slice(out, i))
            want = jax.tree_util.tree_leaves(lane_slice(ref, 0))
            if any(np.asarray(a).tobytes() != np.asarray(b).tobytes()
                   for a, b in zip(got, want)):
                raise AssertionError(
                    f"healthy lane {i} is not bitwise equal to its "
                    f"solo run — the quarantine machinery perturbed a "
                    f"lane it had no business touching")

        # -- 4. the lane-sliced capsule replays bitwise ---------------
        cap = rolls[0].get("replay")
        if not cap:
            raise AssertionError(f"rollback incident has no capsule: "
                                 f"{rolls[0]}")
        manifest = json.load(open(os.path.join(cap, "manifest.json")))
        if manifest.get("lane", {}).get("index") != BAD \
                or manifest.get("lane", {}).get("fleet_size") != B:
            raise AssertionError(f"capsule lane record wrong: "
                                 f"{manifest.get('lane')}")
        res = replay(cap)
        if res["verdict"] != "reproduced" or not res["bitwise"]:
            raise AssertionError(f"lane capsule replay: {res}")

        return {"fleet_smoke": "ok", "fleet_size": B, "bad_lane": BAD,
                "healthy_final_step": cfg.num_steps,
                "bad_lane_final_step": int(k[BAD]),
                "lane_rollbacks": len(rolls),
                "lane_quarantines": len(quars),
                "trace_counts": {str(n): c for n, c
                                 in drv.trace_counts.items()},
                "capsule": cap,
                "replay_verdict": res["verdict"]}
    finally:
        if tmp is not None:
            tmp.cleanup()


# ---------------------------------------------------------------------------
# serving-path chaos (PR 17): faults against the warm-pool router
# ---------------------------------------------------------------------------
#
# All four injectors monkey-patch the router's seams for the duration
# of a ``with`` block and restore them on exit. They are deliberately
# NOT ``recorded()``: they perturb latency and liveness, never state
# values, so there is no bitwise replay story — the soak drill's
# invariants are the reproduction.


@contextlib.contextmanager
def compile_storm_injector(extra_s: float = 0.5):
    """Every bucket build (the whole cost of a serving miss) takes
    ``extra_s`` longer — the host-side model of a compile storm, where
    novel families pile onto the build executor and cold requests wait.
    Warm pools are untouched (the patch sits on
    ``WarmPool.ensure_compiled``, which only runs at build time)."""
    from ibamr_tpu.serve.router import WarmPool

    orig = WarmPool.ensure_compiled

    def stormy(self):
        time.sleep(float(extra_s))
        return orig(self)

    WarmPool.ensure_compiled = stormy
    try:
        yield
    finally:
        WarmPool.ensure_compiled = orig


@contextlib.contextmanager
def slow_lane_injector(extra_s: float = 0.25, match=None):
    """Straggler: every compiled-chunk invocation on pools whose spec
    satisfies ``match`` (default: all pools) eats a host-side
    ``extra_s`` sleep first. Scoping ``match`` to the chaos family is
    how the soak proves a straggling tenant cannot drag a healthy
    tenant's p99 — slots, not speed, are the shared resource."""
    from ibamr_tpu.serve.router import WarmPool

    orig = WarmPool.chunk

    def straggler(self, length):
        ex = orig(self, length)
        if match is not None and not match(self.spec):
            return ex

        def slow_exec(*a, **k):
            time.sleep(float(extra_s))
            return ex(*a, **k)

        return slow_exec

    WarmPool.chunk = straggler
    try:
        yield
    finally:
        WarmPool.chunk = orig


@contextlib.contextmanager
def failing_build_injector(n_failures: int = 1,
                           message: str = "injected build failure"):
    """The first ``n_failures`` bucket builds raise — the transient
    compile failure the router's jittered-backoff retry budget exists
    for. Yields the live countdown list (``[remaining]``) so a drill
    can assert the faults were actually consumed."""
    from ibamr_tpu.serve.router import WarmPool

    orig = WarmPool.ensure_compiled
    remaining = [int(n_failures)]
    lock = threading.Lock()

    def flaky(self):
        with lock:
            fail = remaining[0] > 0
            if fail:
                remaining[0] -= 1
        if fail:
            raise RuntimeError(message)
        return orig(self)

    WarmPool.ensure_compiled = flaky
    try:
        yield remaining
    finally:
        WarmPool.ensure_compiled = orig


@contextlib.contextmanager
def kill_router_thread_injector(n_kills: int = 1):
    """The first ``n_kills`` pool-build threads DIE without publishing
    (``_build_pool`` returns before setting the flight event) — the
    harshest router liveness fault: every waiter on that flight would
    hang forever if the sliced-wait dead-thread failover did not
    exist. Yields the live countdown list (``[remaining]``)."""
    from ibamr_tpu.serve import router as _router

    orig = _router.WarmPoolRouter._build_pool
    remaining = [int(n_kills)]
    lock = threading.Lock()

    def killed(self, spec, flight):
        with lock:
            kill = remaining[0] > 0
            if kill:
                remaining[0] -= 1
        if kill:
            return  # thread exits: no pool, no error, no event
        return orig(self, spec, flight)

    _router.WarmPoolRouter._build_pool = killed
    try:
        yield remaining
    finally:
        _router.WarmPoolRouter._build_pool = orig


def mix_shift_injector(seed: int, duration_s: float, rate_rps: float,
                       shift_frac: float = 0.5,
                       shifted_family=(("n_lon", 12),),
                       burst_factor: float = 2.0):
    """Mix-shift fault (PR 18): a deterministic arrival schedule whose
    mix ROTATES to an unseen bucket family at ``shift_frac`` of the
    run — the traffic pattern a fixed warm-pool set cannot survive
    (every post-shift request would cold-compile or shed). Pure
    schedule transform, no monkey-patching: the same seed replays the
    same shift bit-for-bit. Returns ``(arrivals, shifted_family_str)``
    where the string matches the ``family`` field of
    ``request_admit``/``pool_scale`` ledger records."""
    from ibamr_tpu.serve.loadgen import (SCENARIO_MIX, ScenarioRequest,
                                         poisson_burst_schedule)

    shifted_mix = tuple(
        dataclasses.replace(s, family=tuple(shifted_family))
        for s in SCENARIO_MIX)
    arrivals = poisson_burst_schedule(
        seed=seed, duration_s=duration_s, rate_rps=rate_rps,
        burst_factor=burst_factor,
        mix_schedule=[(0.0, SCENARIO_MIX),
                      (float(shift_frac), shifted_mix)])
    fam = dict(shifted_family)
    probe = ScenarioRequest(
        tenant="probe", n_cells=fam.get("n_cells", 8),
        n_lat=fam.get("n_lat", 6), n_lon=fam.get("n_lon", 8),
        engine=fam.get("engine"),
        spectral_dtype=fam.get("spectral_dtype"),
        mu=fam.get("mu", 0.05))
    return arrivals, str(probe.family())


@contextlib.contextmanager
def memory_pressure_injector(cache, max_bytes: int):
    """Memory-pressure fault (PR 18): squeeze the executable cache's
    bytes ceiling mid-run (the ``aot_cache_bytes`` watermark the
    brownout pressure signal reads), restoring the original ceiling on
    exit. Yields the live eviction count ``[n]`` from the initial
    squeeze so a drill can assert what the pressure actually cost."""
    orig = cache.max_bytes
    evicted = [cache.set_max_bytes(int(max_bytes))]
    try:
        yield evicted
    finally:
        cache.set_max_bytes(orig)


def run_elastic_smoke(directory: str | None = None,
                      duration_s: float = 5.0, rate_rps: float = 8.0,
                      time_scale: float = 0.5,
                      shift_frac: float = 0.4) -> dict:
    """Deterministic elasticity drill (PR 18, dryrun path 22): a
    mid-soak MIX SHIFT onto an unseen family plus MEMORY PRESSURE on
    the executable cache drive the ``ElasticPoolManager`` through
    grow, brownout, shrink, and a crash-safe restart, and the
    invariants are pinned from the merged ledger:

    1. **no lost request** — every admitted ``trace_id`` reaches
       exactly one terminal record, shift or no shift;
    2. **scale-up before shed** — the shifted family's ``pool_scale``
       grow decision lands BEFORE any of its requests shed, and the
       family is eventually served warm;
    3. **brownout without oscillation** — the precompile backlog +
       bytes watermark push the mode ladder into brownout, it
       de-escalates through the dwell guard, and the total number of
       mode transitions stays bounded (no flapping);
    4. **elastic shrink** — the pre-shift family decays cold and is
       released (executables + bytes), never while serving;
    5. **restart drill** — ``serving_manifest.json`` is checkpointed,
       a FRESH router+cache restores it with bounded-concurrency
       re-warm and ZERO fresh XLA compiles (aot-cache ``cold_source``
       manifest attribution), then serves warm on the first request.

    Raises on any failed expectation; returns a one-line JSON summary
    (``tools/slo.py check --elastic`` evaluates the same ledger
    against SLO.json's ``elastic_slos``)."""
    from ibamr_tpu import obs as _obs
    from ibamr_tpu.serve import aot_cache
    from ibamr_tpu.serve.autoscale import (ElasticPoolManager,
                                           ScalePolicy,
                                           restore_serving_manifest)
    from ibamr_tpu.serve.capacity import capacity_report
    from ibamr_tpu.serve.loadgen import (SOAK_POLICIES,
                                         run_open_loop,
                                         traffic_summary)
    from ibamr_tpu.serve.router import (BucketSpec, ScenarioRequest,
                                        WarmPoolRouter)

    max_transitions = 6
    tmp = None
    if directory is None:
        tmp = tempfile.TemporaryDirectory(prefix="ibamr_elastic_smoke_")
        directory = tmp.name
    try:
        ledger_path = os.path.join(directory, "elastic_ledger.jsonl")
        manifest_path = os.path.join(directory,
                                     "serving_manifest.json")
        # the cross-process compile layer: restart re-warms through
        # XLA's disk cache (repo-default dir; never fatal if absent)
        aot_cache.enable_persistent_cache(min_compile_secs=0.0)
        cache = aot_cache.ExecutableCache(
            directory=os.path.join(directory, "cache"))
        spec = BucketSpec(n_cells=8, n_lat=6, n_lon=8, lanes=2,
                          chunk_steps=2)
        router = WarmPoolRouter([spec], cache=cache,
                                allow_dynamic=True,
                                policies=dict(SOAK_POLICIES))
        # backlog>=1 trips brownout: one async grow IS the pressure
        # this drill exercises; de-escalation dwell bounds flapping
        manager = ElasticPoolManager(
            router,
            policy=ScalePolicy(grow_share=0.08, grow_min_arrivals=2,
                               shrink_share=0.02, min_dwell_s=2.0,
                               idle_evict_s=6.0,
                               brownout_backlog=1,
                               brownout_exit_backlog=0,
                               urgent_share=0.15,
                               mode_min_dwell_s=0.5),
            manifest_path=manifest_path)

        arrivals, shifted_family = mix_shift_injector(
            seed=0, duration_s=duration_s, rate_rps=rate_rps,
            shift_frac=shift_frac)
        shift_t = shift_frac * duration_s
        pre = [a for a in arrivals if a.t < shift_t]
        post = [dataclasses.replace(a, t=a.t - shift_t)
                for a in arrivals if a.t >= shift_t]

        with _obs.ledger(ledger_path):
            with _obs.span("elastic_smoke/warm"):
                router.warm(spec)
            base_family = str(spec.family())

            with _obs.span("elastic_smoke/pre_shift",
                           arrivals=len(pre)):
                run1 = run_open_loop(router, pre,
                                     time_scale=time_scale,
                                     join_timeout_s=120.0)
            # mid-soak: the mix rotates to the unseen family while the
            # cache's bytes ceiling is squeezed (generous enough that
            # the shifted family still fits — the watermark is
            # pressure, not sabotage)
            ceiling = max(int(cache.bytes() * 3), 1)
            with _obs.span("elastic_smoke/shifted_open_loop",
                           arrivals=len(post)), \
                    memory_pressure_injector(cache, ceiling):
                run2 = run_open_loop(router, post,
                                     time_scale=time_scale,
                                     join_timeout_s=180.0)

            # settle: idle ticks decay the mix + drain the mode
            # ladder back to healthy and let the cold family shrink
            t_settle = time.monotonic()
            while time.monotonic() - t_settle < 20.0:
                manager.tick()
                shrunk = any(e["action"] == "shrink"
                             for e in manager.scale_events)
                if manager.mode == "healthy" and shrunk:
                    break
                time.sleep(0.25)
            manager.tick()

            # -- 5. the restart drill --------------------------------
            manager.save_manifest()
            if manager.drain(timeout_s=120.0):
                raise AssertionError("builds/watchers never finished "
                                     "before the restart drill")
            router2, manager2, restore_stats = \
                restore_serving_manifest(manifest_path)
            fam = dict((("n_lon", 12),))
            probe = router2.serve([ScenarioRequest(
                tenant="interactive-restart", n_cells=8, n_lat=6,
                n_lon=fam["n_lon"], steps=2,
                tenant_class="interactive")])[0]
            router2.drain_builds(timeout_s=60.0)
            _obs.chunk_boundary()

        # -- invariant 1: no lost request ----------------------------
        for run in (run1, run2):
            if run["hung_threads"]:
                raise AssertionError(
                    f"{run['hung_threads']} producer threads never "
                    f"finished — the elastic drill deadlocked")
            if run["errors"]:
                raise AssertionError(
                    f"serve() raised under the mix shift: "
                    f"{run['errors'][:3]}")
        records = list(_obs.read_ledger(ledger_path))
        admits = [r for r in records
                  if r.get("kind") == "request_admit"]
        terminals: dict = {}
        for r in records:
            if r.get("kind") in ("request", "request_shed"):
                tid = r.get("trace_id")
                terminals[tid] = terminals.get(tid, 0) + 1
        lost = [r["trace_id"] for r in admits
                if terminals.get(r["trace_id"], 0) == 0]
        doubled = [r["trace_id"] for r in admits
                   if terminals.get(r["trace_id"], 0) > 1]
        if lost or doubled:
            raise AssertionError(
                f"terminal-record invariant broken: {len(lost)} lost, "
                f"{len(doubled)} doubled (first: "
                f"{(lost + doubled)[:3]})")

        # -- invariant 2: scale-up before shed for the shifted mix ---
        grows = [r for r in records if r.get("kind") == "pool_scale"
                 and r.get("action") == "grow"
                 and r.get("family") == shifted_family]
        if not grows:
            raise AssertionError(
                f"the shifted family {shifted_family} never got a "
                f"grow decision — the mix estimator is blind")
        first_grow_seq = min(r["seq"] for r in grows)
        shifted_tids = {r["trace_id"] for r in admits
                        if r.get("family") == shifted_family}
        shifted_sheds = [r for r in records
                         if r.get("kind") == "request_shed"
                         and r.get("trace_id") in shifted_tids]
        early = [r for r in shifted_sheds
                 if r.get("seq", 0) < first_grow_seq]
        if early:
            raise AssertionError(
                f"{len(early)} shifted-family requests shed BEFORE "
                f"the grow decision (seq {first_grow_seq})")
        warmed = [r for r in records if r.get("kind") == "pool_scale"
                  and r.get("action") == "warmed"
                  and r.get("family") == shifted_family]
        shifted_warm = [r for r in records if r.get("kind") == "request"
                        and r.get("trace_id") in shifted_tids
                        and not r.get("cold")]
        if not warmed or not shifted_warm:
            raise AssertionError(
                f"shifted family never published warm "
                f"(warmed={len(warmed)}, warm_served="
                f"{len(shifted_warm)})")

        # -- invariant 3: brownout entry/exit without oscillation ----
        modes = [r for r in records if r.get("kind") == "serve_mode"]
        if not any(r["mode"] == "brownout" for r in modes):
            raise AssertionError(
                "the grow backlog never tripped brownout — the "
                "pressure signal is dead")
        if len(modes) > max_transitions:
            raise AssertionError(
                f"{len(modes)} mode transitions (> {max_transitions})"
                f" — the ladder is oscillating")
        if manager.mode != "healthy":
            raise AssertionError(
                f"mode never de-escalated (stuck {manager.mode})")

        # -- invariant 4: elastic shrink of the cold family ----------
        shrinks = [r for r in records if r.get("kind") == "pool_scale"
                   and r.get("action") == "shrink"]
        if not any(r.get("family") == base_family for r in shrinks):
            raise AssertionError(
                f"the pre-shift family {base_family} was never "
                f"shrunk after going cold")
        if shifted_family not in {str(f)
                                  for f in router.live_families()}:
            raise AssertionError(
                "the shifted (hot) family is not live after shrink")

        # -- invariant 5: restart reached warm with zero fresh builds
        if restore_stats["fresh_compiles"] != 0:
            raise AssertionError(
                f"restart drill paid {restore_stats['fresh_compiles']}"
                f" fresh compiles (cold_source attribution) — the "
                f"persistent layer did not survive the crash")
        if restore_stats["warmed"] == 0 or restore_stats["errors"]:
            raise AssertionError(
                f"restart re-warm failed: {restore_stats}")
        if probe.shed or probe.cold or not probe.ok:
            raise AssertionError(
                f"first post-restart request was not a warm serve: "
                f"cold={probe.cold} shed={probe.shed} ok={probe.ok}")

        results = run1["results"] + run2["results"]
        wall = run1["wall_s"] + run2["wall_s"]
        summary = traffic_summary(results, wall)
        cap = capacity_report(records, p99_ceiling_s=2.0)
        if cap["prediction"]["rps"] is None:
            raise AssertionError(
                "capacity model unevaluable — no warm samples in the "
                "elastic ledger")
        return {"elastic_smoke": "ok",
                "arrivals": len(arrivals),
                "admitted": len(admits),
                "lost": 0,
                "shed": summary["shed"],
                "mode_transitions": len(modes),
                "grows": len(grows),
                "shrinks": len(shrinks),
                "scale_up_s": max(r.get("warm_s", 0.0)
                                  for r in warmed),
                "restart_warm_s": restore_stats["warm_s"],
                "restart_fresh_compiles":
                    restore_stats["fresh_compiles"],
                "cache_bytes": cache.bytes(),
                "predicted_rps": cap["prediction"]["rps"],
                "measured_rps": summary["requests_per_s"],
                "wall_s": round(wall, 3),
                "ledger": (None if tmp is not None else ledger_path)}
    finally:
        if tmp is not None:
            tmp.cleanup()


def run_soak_smoke(directory: str | None = None,
                   duration_s: float = 5.0, rate_rps: float = 8.0,
                   time_scale: float = 0.5,
                   chaos_rate_rps: float = 3.0) -> dict:
    """Deterministic traffic-robustness drill (PR 17, dryrun path 21):
    the open-loop load generator drives a warm-pool router under ALL
    FOUR serving chaos injectors at once, and the liveness invariants
    are pinned from the merged ledger.

    1. **healthy traffic, chaos tenant burning** — seeded Poisson
       arrivals with a 4x burst window over the heavy-tailed
       interactive/batch mix share the router with a ``chaos``-class
       tenant whose requests land on NOVEL families (fresh bucket
       compiles) while a compile storm slows every build, the first
       build raises (retry fuel), one build thread is killed
       mid-flight, and the chaos families' lanes straggle;
    2. **no deadlock** — every producer thread joins inside the
       drill's bounded window (``hung_threads == 0``);
    3. **no lost request** — every ``request_admit`` trace_id in the
       ledger reaches EXACTLY one terminal record (``request`` or
       ``request_shed``), storm or no storm;
    4. **bounded shed** — healthy classes shed at most
       ``max_healthy_shed_rate``; the chaos class may shed freely
       (that is admission control doing its job, not a failure);
    5. **healthy p99 within band** — healthy tenants' warm first-step
       p99 stays inside the committed ``soak_warm_p99_s`` band while
       the chaos tenant burns.

    Raises on any failed expectation; returns a one-line JSON summary.
    """
    from ibamr_tpu import obs as _obs
    from ibamr_tpu.serve import aot_cache
    from ibamr_tpu.serve.loadgen import (SOAK_POLICIES, Scenario,
                                         poisson_burst_schedule,
                                         run_open_loop, traffic_summary)
    from ibamr_tpu.serve.router import BucketSpec, WarmPoolRouter

    max_healthy_shed_rate = 0.10
    healthy_warm_p99_band_s = 2.0

    tmp = None
    if directory is None:
        tmp = tempfile.TemporaryDirectory(prefix="ibamr_soak_smoke_")
        directory = tmp.name
    try:
        ledger_path = os.path.join(directory, "soak_ledger.jsonl")
        spec = BucketSpec(n_cells=8, n_lat=6, n_lon=8, lanes=2,
                          chunk_steps=2)
        router = WarmPoolRouter(
            [spec],
            cache=aot_cache.ExecutableCache(
                directory=os.path.join(directory, "cache")),
            allow_dynamic=True, policies=dict(SOAK_POLICIES))

        with _obs.ledger(ledger_path):
            with _obs.span("soak_smoke/warm"):
                router.warm(spec)

            # healthy mix on the pre-warmed family; chaos tenant on
            # two NOVEL families (distinct n_lon -> fresh builds)
            arrivals = poisson_burst_schedule(
                seed=0, duration_s=duration_s, rate_rps=rate_rps,
                burst_factor=4.0)
            chaos_mix = (Scenario("chaos/storm_probe", 1.0, "chaos",
                                  steps=1),)
            for j, n_lon in enumerate((10, 12)):
                arrivals += poisson_burst_schedule(
                    seed=100 + j, duration_s=duration_s,
                    rate_rps=chaos_rate_rps / 2.0, burst_factor=4.0,
                    mix=chaos_mix, n_lon=n_lon, tenants_per_class=1)
            arrivals.sort(key=lambda a: a.t)

            chaos_family = (lambda s: s.n_lon != 8)
            with _obs.span("soak_smoke/chaos_open_loop",
                           arrivals=len(arrivals)), \
                    compile_storm_injector(extra_s=0.2), \
                    failing_build_injector(n_failures=1) as build_faults, \
                    kill_router_thread_injector(n_kills=1) as kills, \
                    slow_lane_injector(extra_s=0.2, match=chaos_family):
                run = run_open_loop(router, arrivals,
                                    time_scale=time_scale,
                                    join_timeout_s=120.0)
            _obs.chunk_boundary()

        # -- 2. no deadlock ------------------------------------------
        # deadline-shed chaos requests leave their bucket builds
        # running; those threads must also terminate (and must do so
        # before interpreter exit, or teardown aborts the process)
        still = router.drain_builds(timeout_s=120.0)
        if still:
            raise AssertionError(
                f"{still} pool builds never finished — a build "
                f"thread is wedged")
        if run["hung_threads"]:
            raise AssertionError(
                f"{run['hung_threads']} producer threads never "
                f"finished — the router deadlocked under chaos")
        if run["errors"]:
            raise AssertionError(
                f"serve() raised under chaos (every fault must "
                f"terminate as a shed, not an exception): "
                f"{run['errors'][:3]}")
        if build_faults[0] != 0 or kills[0] != 0:
            raise AssertionError(
                f"injected faults not consumed: {build_faults[0]} "
                f"build failures, {kills[0]} kills left — the drill "
                f"did not exercise what it claims")

        # -- 3. no lost request, from the ledger alone ---------------
        records = list(_obs.read_ledger(ledger_path))
        admits = [r["trace_id"] for r in records
                  if r.get("kind") == "request_admit"]
        terminals: dict = {}
        for r in records:
            if r.get("kind") in ("request", "request_shed"):
                tid = r.get("trace_id")
                terminals[tid] = terminals.get(tid, 0) + 1
        lost = [t for t in admits if terminals.get(t, 0) == 0]
        doubled = [t for t in admits if terminals.get(t, 0) > 1]
        if lost:
            raise AssertionError(
                f"{len(lost)} admitted requests have NO terminal "
                f"record (first: {lost[:3]}) — requests were lost")
        if doubled:
            raise AssertionError(
                f"{len(doubled)} admitted requests have multiple "
                f"terminal records (first: {doubled[:3]})")

        # -- 4. bounded shed for healthy classes ---------------------
        summary = traffic_summary(run["results"], run["wall_s"])
        healthy_sub = healthy_shed = 0
        for cls, c in summary["classes"].items():
            if cls != "chaos":
                healthy_sub += c["submitted"]
                healthy_shed += c["shed"]
        healthy_rate = (healthy_shed / healthy_sub) if healthy_sub else 0.0
        if healthy_rate > max_healthy_shed_rate:
            raise AssertionError(
                f"healthy classes shed {healthy_rate:.2%} "
                f"(> {max_healthy_shed_rate:.0%}) — the chaos tenant "
                f"stole healthy capacity")

        # -- 5. healthy warm p99 within band -------------------------
        healthy_warm = sorted(
            r["first_step_s"] for r in records
            if r.get("kind") == "request"
            and r.get("tenant_class") in ("interactive", "batch")
            and not r.get("cold"))
        if not healthy_warm:
            raise AssertionError("no healthy warm completions — the "
                                 "soak never reached the warm path")
        import math
        p99 = healthy_warm[min(len(healthy_warm) - 1,
                               max(0, math.ceil(0.99 * len(healthy_warm))
                                   - 1))]
        if p99 > healthy_warm_p99_band_s:
            raise AssertionError(
                f"healthy warm p99 {p99:.3f}s blew the "
                f"{healthy_warm_p99_band_s}s band while the chaos "
                f"tenant burned")

        chaos = summary["classes"].get("chaos", {})
        return {"soak_smoke": "ok",
                "arrivals": len(arrivals),
                "admitted": len(admits),
                "lost": 0,
                "healthy_shed_rate": round(healthy_rate, 4),
                "chaos_submitted": chaos.get("submitted", 0),
                "chaos_shed": chaos.get("shed", 0),
                "chaos_completed": chaos.get("completed", 0),
                "retried": summary["retried"],
                "healthy_warm_p99_s": round(float(p99), 4),
                "hung_threads": 0,
                "wall_s": round(run["wall_s"], 3)}
    finally:
        if tmp is not None:
            tmp.cleanup()


def run_design_smoke(directory: str | None = None,
                     num_iters: int = 3, lr: float = 0.05) -> dict:
    """Deterministic inverse-design drill (PR 19, dryrun path 23): the
    eel2d gait objective (``design.eel_gait`` — swim displacement
    differentiated THROUGH the ConstraintIB rollout) on a tiny f64
    config, with the adjoint-at-primal-cost contract pinned end to end:

    1. **adjoint correctness** — the jitted ``value_and_grad`` of the
       rollout objective agrees with an f64 central difference on the
       gait amplitude to 1e-6 relative (the custom-VJP chain through
       spectral solve + packed transfers + scan is a DERIVATIVE, not
       an approximation);
    2. **strict descent** — ``num_iters`` Adam iterations through
       :class:`~ibamr_tpu.design.DesignLoop` produce strictly
       decreasing objectives (every update helped);
    3. **zero warm compiles** — iteration 1 pays exactly one
       executable-cache MISS (the single AOT compile of the fused
       value_and_grad + Adam iterate); every later iteration is one
       cache HIT and zero misses, so a warm design iteration
       structurally cannot retrace or recompile;
    4. **ledger coverage** — each iteration lands one ``design_iter``
       record in the attached run ledger (the same records
       ``tools/obs.py summary`` renders as the design-loop block).

    Raises on any failed expectation; returns a one-line JSON summary.
    """
    import jax
    import jax.numpy as jnp

    from ibamr_tpu import obs as _obs
    from ibamr_tpu.design import DesignLoop, build_eel_gait_problem
    from ibamr_tpu.serve.aot_cache import ExecutableCache

    if not jax.config.jax_enable_x64:
        jax.config.update("jax_enable_x64", True)

    tmp = None
    if directory is None:
        tmp = tempfile.TemporaryDirectory(prefix="ibamr_design_smoke_")
        directory = tmp.name
    try:
        t_all = time.perf_counter()
        objective, params0 = build_eel_gait_problem(
            n=24, ns=17, num_steps=10, dtype=jnp.float64)

        # 1. adjoint correctness: compiled grad vs central difference
        # on the gait amplitude (f64; FD step sized for ~1e-10 trunc)
        loop = DesignLoop(objective, params0, lr=lr,
                          cache=ExecutableCache(), label="eel_smoke")
        _, grads = jax.jit(loop.value_and_grad_fn())(params0)
        g_a0 = float(grads["A0"])
        obj = jax.jit(objective)
        a0 = float(params0["A0"])
        fd_eps = 1e-5

        def at(a):
            p = dict(params0)
            p["A0"] = jnp.asarray(a, jnp.float64)
            return float(obj(p))

        fd = (at(a0 + fd_eps) - at(a0 - fd_eps)) / (2.0 * fd_eps)
        fd_rel = abs(g_a0 - fd) / max(abs(fd), 1e-30)
        if fd_rel > 1e-6:
            raise AssertionError(
                f"adjoint disagrees with central difference: "
                f"grad {g_a0:.12e} vs FD {fd:.12e} "
                f"(rel {fd_rel:.3e} > 1e-6)")

        # 2-4. the loop itself, ledger attached
        ledger = _obs.RunLedger(
            os.path.join(directory, "design_ledger.jsonl"))
        prev = _obs.attach(ledger)
        try:
            res = loop.run(num_iters)
        finally:
            _obs.detach()
            if prev is not None:
                _obs.attach(prev)
            ledger.close()

        objs = [it.objective for it in res.history]
        for earlier, later in zip(objs, objs[1:]):
            if not later < earlier:
                raise AssertionError(
                    f"objective did not strictly decrease: {objs}")
        first = res.history[0]
        if first.cache_misses != 1:
            raise AssertionError(
                f"iteration 1 should pay exactly one compile, "
                f"paid {first.cache_misses}")
        for it in res.history[1:]:
            if it.cache_misses != 0 or it.cache_hits != 1:
                raise AssertionError(
                    f"warm iteration {it.iteration} not served from "
                    f"cache: hits={it.cache_hits} "
                    f"misses={it.cache_misses}")
        recs = [r for r in _obs.read_ledger(ledger.path)
                if r.get("kind") == "design_iter"]
        if len(recs) != num_iters:
            raise AssertionError(
                f"expected {num_iters} design_iter ledger records, "
                f"found {len(recs)}")

        return {"design_smoke": "ok",
                "iterations": num_iters,
                "objectives": [round(v, 10) for v in objs],
                "fd_rel_err": float(f"{fd_rel:.3e}"),
                "grad_A0": float(f"{g_a0:.6e}"),
                "cold_misses": first.cache_misses,
                "warm_misses": sum(
                    it.cache_misses for it in res.history[1:]),
                "warm_wall_s": round(sum(
                    it.wall_s for it in res.history[1:]), 3),
                "cold_wall_s": round(first.wall_s, 3),
                "ledger_records": len(recs),
                "wall_s": round(time.perf_counter() - t_all, 3)}
    finally:
        if tmp is not None:
            tmp.cleanup()


# ---------------------------------------------------------------------------
# assimilation faults (PR 20): bad sensors and bad members
# ---------------------------------------------------------------------------
#
# The first three injectors wrap the cycle's ``obs_source`` seam — a
# pure schedule transform over the sensor stream (which channels go
# bad, at which cycles), so an armed drill is bit-reproducible from
# its parameters alone. ``member_divergence_injector`` is a lane-
# confined STATE fault (the lane_nan shape) and is ``recorded()`` so
# capsules of an assimilating run carry it.

def obs_dropout_injector(source, channels, at_cycles):
    """Wrap an ``obs_source`` so the named channels read NaN (a dead
    sensor) at the named cycles — the QC gate must reject each with
    reason ``dropout`` and the analysis must proceed on the rest."""
    chans, cycs = list(channels), {int(c) for c in at_cycles}

    def wrapped(cycle, step):
        b = source(cycle, step)
        if b is None or cycle not in cycs:
            return b
        b = dataclasses.replace(b, values=b.values.copy())
        b.values[chans] = np.nan
        return b

    return wrapped


def obs_outlier_injector(source, channels, at_cycles,
                         magnitude: float = 50.0):
    """Wrap an ``obs_source`` so the named channels spike by
    ``magnitude`` observation-sigmas (an electrical transient) at the
    named cycles — far beyond any plausible innovation, so the QC
    gate's background check rejects each with reason ``outlier``."""
    chans, cycs = list(channels), {int(c) for c in at_cycles}

    def wrapped(cycle, step):
        b = source(cycle, step)
        if b is None or cycle not in cycs:
            return b
        b = dataclasses.replace(b, values=b.values.copy())
        b.values[chans] += magnitude * np.sqrt(b.r[chans])
        return b

    return wrapped


def stale_obs_injector(source, channels, at_cycles,
                       age_s: float = 1e6):
    """Wrap an ``obs_source`` so the named channels arrive ``age_s``
    seconds old (a feed replaying its last value) at the named cycles
    — the QC gate must reject each with reason ``stale``."""
    chans, cycs = list(channels), {int(c) for c in at_cycles}

    def wrapped(cycle, step):
        b = source(cycle, step)
        if b is None or cycle not in cycs:
            return b
        b = dataclasses.replace(b, age_s=b.age_s.copy())
        b.age_s[chans] = age_s
        return b

    return wrapped


def member_divergence_injector(stacked_step, at_step: int, lane: int,
                               fleet_size: int,
                               leaf_path: str = "u[0]",
                               dt_gate: float | None = None,
                               step_attr: str = "ins.k"):
    """One ensemble MEMBER diverges mid-run: lane ``lane``'s rows go
    NaN at its ``at_step`` (the :func:`lane_nan_injector` mechanics
    under the assimilation drill's name). The fleet triage must
    quarantine the member, and the masked analysis statistics must
    exclude it instead of averaging a diverged state into every other
    lane — the failure mode ensemble filters are famously soft on."""
    return lane_nan_injector(stacked_step, at_step=at_step, lane=lane,
                             fleet_size=fleet_size,
                             leaf_path=leaf_path, dt_gate=dt_gate,
                             step_attr=step_attr)


def run_assim_smoke(directory: str | None = None, fleet_size: int = 6,
                    cycles: int = 6, steps_per_cycle: int = 2,
                    bad_lane: int | None = None) -> dict:
    """Deterministic end-to-end ASSIMILATION drill (PR 20, dryrun path
    24): the B-lane shell fleet runs as a forecasting service while
    ALL FOUR assimilation injectors are armed at once —

    1. **bad sensors rejected, not assimilated** — a dropped channel
       (NaN), a 50-sigma outlier spike and a stale feed each hit a
       distinct channel at a distinct cycle; the QC gate must reject
       exactly those (channel, cycle, reason) triples as structured
       ``assim_qc_reject`` ledger records while the analysis proceeds
       on the surviving channels;
    2. **bad member quarantined, not averaged in** — one lane's state
       goes NaN mid-run; the lane-granular supervisor quarantines it
       and the masked ensemble statistics exclude it from every
       subsequent analysis (its rows ride through frozen);
    3. **zero lost cycles** — every cycle lands exactly one terminal
       ``assim_cycle`` ledger record (skipped or analyzed), through
       quarantine and QC rejections alike;
    4. **the filter earns its keep** — the final cycle's forecast
       error (rms innovation over accepted channels) beats the
       open-loop ensemble (same fleet, same injected member fault, no
       analysis) against the same sensors;
    5. **zero retraces** — the whole episode (quarantine, rejections,
       per-lane dt backoff) runs one trace signature per chunk length
       and exactly two analysis-executable compiles (observe +
       analyze), everything after a pure cache hit.

    Raises on any failed expectation; returns a one-line JSON summary
    (``tools/slo.py check --assim`` evaluates the same ledger against
    SLO.json's ``assim_slos``). Needs x64 — enabled here if not
    already."""
    import jax
    import jax.numpy as jnp

    from ibamr_tpu import obs as _obs
    from ibamr_tpu.assim import (AssimConfig, AssimilationCycle,
                                 ObservationOperator, masked_moments,
                                 stream_from_list, synthesize_batches)
    from ibamr_tpu.instruments import InstrumentPanel, make_meters
    from ibamr_tpu.models.shell3d import build_shell_example
    from ibamr_tpu.serve.aot_cache import ExecutableCache
    from ibamr_tpu.utils.flight_recorder import (FlightRecorder,
                                                 factory_spec)
    from ibamr_tpu.utils.health import HealthProbe
    from ibamr_tpu.utils.lanes import stack_lanes
    from ibamr_tpu.assim import qc as _aqc

    if not jax.config.jax_enable_x64:
        jax.config.update("jax_enable_x64", True)

    B = int(fleet_size)
    BAD = B - 1 if bad_lane is None else int(bad_lane)
    n_cyc, spc = int(cycles), int(steps_per_cycle)
    dt0 = 1e-3
    t_all = time.perf_counter()
    tmp = None
    if directory is None:
        tmp = tempfile.TemporaryDirectory(prefix="ibamr_assim_smoke_")
        directory = tmp.name
    try:
        kwargs = dict(n_cells=16, n_lat=8, n_lon=16, mu=0.05,
                      dtype="float64")
        integ, st0 = build_shell_example(**kwargs)
        n_lon = kwargs["n_lon"]
        # two flow meters: latitude rings of the shell (closed loops)
        loops = [[2 * n_lon + j for j in range(n_lon)],
                 [5 * n_lon + j for j in range(n_lon)]]
        panel = InstrumentPanel(integ.ins.grid,
                                make_meters(loops, closed=True,
                                            dtype=jnp.float64))
        op = ObservationOperator(panel)

        # truth trajectory -> noisy synthetic sensors (twin experiment)
        st, truth_states = st0, []
        for _ in range(n_cyc):
            for _ in range(spc):
                st = integ.step(st, dt0)
            truth_states.append(st)
        sigma = 1e-5
        batches = synthesize_batches(op, truth_states, sigma=sigma,
                                     seed=7)
        names = op.channel_names()

        # heterogeneous ensemble: additive per-lane velocity offsets
        # (the initial shell state is quiescent — multiplicative
        # perturbations would leave the ensemble degenerate)
        lane_states = [st0._replace(ins=st0.ins._replace(
            u=tuple(c + 2e-3 * (i + 1) for c in st0.ins.u)))
            for i in range(B)]
        fleet0 = stack_lanes(lane_states)

        # the four injectors, armed at once: three sensor faults on
        # distinct (channel, cycle) slots + one diverging member
        injected = {(1, names[0], "dropout"),
                    (2, names[1], "outlier"),
                    (3, names[2], "stale")}
        member_inj = dict(at_step=spc + 1, lane=BAD, fleet_size=B,
                          leaf_path="u[0]", step_attr="ins.k")
        source = stream_from_list(batches)
        source = obs_dropout_injector(source, [0], [1])
        # the spike must clear the background check however wide the
        # ensemble is: 2e4 obs-sigmas dwarfs any plausible HPH^T
        source = obs_outlier_injector(source, [1], [2],
                                      magnitude=2e4)
        source = stale_obs_injector(source, [2], [3])

        ledger_path = os.path.join(directory, "assim_ledger.jsonl")
        cfg = AssimConfig(steps_per_cycle=spc, dt=dt0,
                          qc=_aqc.QCConfig(k_sigma=6.0))
        cache = ExecutableCache()
        probe = HealthProbe.for_integrator(integ)
        with _obs.ledger(ledger_path):
            with recorded("member_divergence", **member_inj):
                cyc = AssimilationCycle(
                    integ, op, B, cfg, probe=probe, cache=cache,
                    fleet_step_wrap=lambda s:
                        member_divergence_injector(s, **member_inj),
                    recorder=FlightRecorder(capacity=4,
                                            spec=factory_spec(
                        "ibamr_tpu.models.shell3d",
                        "build_shell_example", **kwargs)))
                out = cyc.run(fleet0, batches, directory=directory,
                              obs_source=source, max_retries=1)

        # -- 2. the diverged member is quarantined, stats exclude it --
        if cyc.driver.lane_alive[BAD]:
            raise AssertionError("diverged member never quarantined")
        if not all(cyc.driver.lane_alive[i] for i in range(B)
                   if i != BAD):
            raise AssertionError("a healthy member was quarantined")

        records = list(_obs.read_ledger(ledger_path))

        # -- 1. exactly the injected bad observations were rejected ---
        rej = {(r["cycle"], r["instrument"], r["reason"])
               for r in records if r.get("kind") == "assim_qc_reject"}
        if not injected <= rej:
            raise AssertionError(
                f"injected bad observations not all rejected: "
                f"missing {injected - rej}")
        extra = rej - injected
        if extra:
            raise AssertionError(
                f"QC rejected healthy observations: {extra}")

        # -- 3. zero lost cycles --------------------------------------
        cyc_recs = [r for r in records
                    if r.get("kind") == "assim_cycle"]
        done = {r["cycle"] for r in cyc_recs}
        if done != set(range(n_cyc)):
            raise AssertionError(
                f"lost cycles: {sorted(set(range(n_cyc)) - done)}")
        analyzed = [r for r in cyc_recs if not r.get("skipped")]
        if not analyzed:
            raise AssertionError("no cycle ever analyzed")

        # -- 5. zero retraces / zero steady-state compiles ------------
        if any(c != 1 for c in cyc.driver.trace_counts.values()):
            raise AssertionError(
                f"fleet chunk retraced: {cyc.driver.trace_counts}")
        stats = cache.stats()
        if stats["misses"] != 2:
            raise AssertionError(
                f"expected exactly 2 analysis compiles (observe + "
                f"analyze), got {stats['misses']}")

        # -- 4. the filter beats the open-loop ensemble ---------------
        # open loop: same fleet, same member fault, no analysis
        ol_cfg = AssimConfig(steps_per_cycle=spc, dt=dt0)
        ol = AssimilationCycle(
            integ, op, B, ol_cfg, probe=HealthProbe.for_integrator(integ),
            cache=ExecutableCache(),
            fleet_step_wrap=lambda s:
                member_divergence_injector(s, **member_inj))
        ol_dir = os.path.join(directory, "open_loop")
        os.makedirs(ol_dir, exist_ok=True)
        ol_out = ol.run(fleet0, directory=ol_dir, n_cycles=n_cyc,
                        obs_source=lambda c, s: None, max_retries=1)

        def _forecast_err(fleet_state, alive, batch):
            pred = np.asarray(jax.vmap(op)(fleet_state))
            ybar, _, _ = masked_moments(jnp.asarray(pred),
                                        jnp.asarray(alive))
            d = np.asarray(batch.values) - np.asarray(ybar)
            d = d[np.isfinite(d)]
            return float(np.sqrt(np.mean(d * d)))

        clean_final = batches[-1]
        err_assim = _forecast_err(out, cyc.driver.lane_alive,
                                  clean_final)
        err_open = _forecast_err(ol_out, ol.driver.lane_alive,
                                 clean_final)
        if not err_assim < err_open:
            raise AssertionError(
                f"assimilation did not beat the open loop: "
                f"{err_assim:.3e} vs {err_open:.3e}")

        # land the drill verdict in the ledger itself (append-only:
        # reopening continues the seq) — tools/slo.py check --assim
        # computes its SLIs from the ledger ALONE, and the
        # open-loop baseline exists nowhere else
        with _obs.ledger(ledger_path):
            _obs.emit("assim_summary", cycles=n_cyc, fleet_size=B,
                      bad_lane=BAD, forecast_error=err_assim,
                      open_loop_error=err_open,
                      analysis_compiles=stats["misses"],
                      analysis_cache_hits=stats["hits"],
                      final_inflation=cyc.inflation,
                      inflation_escalations=len(cyc.escalations))

        return {"assim_smoke": "ok", "fleet_size": B,
                "bad_lane": BAD, "cycles": n_cyc,
                "qc_rejections": sorted(
                    [list(t) for t in rej]),
                "lost_cycles": 0,
                "analysis_compiles": stats["misses"],
                "analysis_cache_hits": stats["hits"],
                "forecast_error": float(f"{err_assim:.6e}"),
                "open_loop_error": float(f"{err_open:.6e}"),
                "final_inflation": cyc.inflation,
                "ledger": ledger_path,
                "wall_s": round(time.perf_counter() - t_all, 3)}
    finally:
        if tmp is not None:
            tmp.cleanup()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="deterministic fault-injection drills")
    ap.add_argument("--smoke", action="store_true",
                    help="run the end-to-end resilience drill")
    ap.add_argument("--silent-smoke", action="store_true",
                    help="run the silent-failure drill (health vitals "
                         "+ solver escalation + watchdog)")
    ap.add_argument("--replay-smoke", action="store_true",
                    help="run the record -> escalate -> replay drill")
    ap.add_argument("--crash-child", metavar="DIR",
                    help="run the checkpoint-writer victim loop in DIR")
    ap.add_argument("--sharded-crash-child", metavar="DIR",
                    help="run the SHARDED checkpoint-writer victim loop "
                         "in DIR (forces the CPU backend with "
                         "--n-devices virtual devices and x64)")
    ap.add_argument("--sharded-smoke", action="store_true",
                    help="run the sharded-checkpoint drill (no-gather "
                         "save, elastic restore, damage inventory, "
                         "collision, supervised rollback, fsck gate)")
    ap.add_argument("--soak-smoke", action="store_true",
                    help="run the traffic-robustness soak drill "
                         "(open-loop load + serving chaos injectors)")
    ap.add_argument("--elastic-smoke", action="store_true",
                    help="run the elastic warm-pool drill (mix shift "
                         "+ memory pressure -> grow/brownout/shrink + "
                         "crash-safe restart)")
    ap.add_argument("--assim-smoke", action="store_true",
                    help="run the fault-tolerant ensemble data "
                         "assimilation drill (QC-rejected bad "
                         "sensors, quarantined divergent member, "
                         "zero lost cycles, filter beats open loop)")
    ap.add_argument("--design-smoke", action="store_true",
                    help="run the inverse-design drill (eel2d gait "
                         "objective: FD-checked adjoint, strict Adam "
                         "descent, zero warm compiles)")
    ap.add_argument("--fleet-smoke", action="store_true",
                    help="run the lane-quarantine fleet drill (vmapped "
                         "ensemble, one poisoned lane, per-lane "
                         "rollback -> quarantine, sliced-capsule "
                         "replay)")
    ap.add_argument("--n-devices", type=int, default=8)
    ap.add_argument("--record-capsule", metavar="DIR",
                    help="record a divergence capsule in DIR, print "
                         "CAPSULE <dir> and linger for SIGKILL")
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--interval", type=int, default=5)
    ap.add_argument("--keep", type=int, default=3)
    ap.add_argument("--dir", default=None,
                    help="work directory for --smoke (default: temp)")
    args = ap.parse_args(argv)
    if args.crash_child:
        run_crash_child(args.crash_child, args.steps, args.interval,
                        keep=args.keep)
        return 0
    if args.sharded_crash_child:
        # the victim must never touch the TPU relay, and the parent
        # verifies its f64 closed-form trajectory bitwise — pin the
        # CPU backend and x64 BEFORE any jax compute
        from ibamr_tpu.utils.backend_guard import force_cpu
        jax = force_cpu(args.n_devices)
        jax.config.update("jax_enable_x64", True)
        run_sharded_crash_child(args.sharded_crash_child, args.steps,
                                args.interval, keep=args.keep,
                                n_devices=args.n_devices)
        return 0
    if args.sharded_smoke:
        # same backend pin as the crash child: the drill needs the
        # virtual CPU mesh, never the relay
        from ibamr_tpu.utils.backend_guard import force_cpu
        force_cpu(args.n_devices)
        print(json.dumps(run_sharded_smoke(args.dir)), flush=True)
        return 0
    if args.fleet_smoke:
        # the drill is vmap-parallel, not device-parallel — one CPU
        # device suffices; f64 bitwise pins need x64 before any compute
        from ibamr_tpu.utils.backend_guard import force_cpu
        jax = force_cpu(1)
        jax.config.update("jax_enable_x64", True)
        print(json.dumps(run_fleet_smoke(args.dir)), flush=True)
        return 0
    if args.soak_smoke:
        # bounded CPU soak — pin the backend before any jax compute
        from ibamr_tpu.utils.backend_guard import force_cpu
        force_cpu(1)
        print(json.dumps(run_soak_smoke(args.dir)), flush=True)
        return 0
    if args.elastic_smoke:
        # bounded CPU elasticity drill — same backend pin as the soak
        from ibamr_tpu.utils.backend_guard import force_cpu
        force_cpu(1)
        print(json.dumps(run_elastic_smoke(args.dir)), flush=True)
        return 0
    if args.design_smoke:
        # tiny f64 design loop — one CPU device; the drill enables
        # x64 itself (the FD check needs it before any jax compute)
        from ibamr_tpu.utils.backend_guard import force_cpu
        force_cpu(1)
        print(json.dumps(run_design_smoke(args.dir)), flush=True)
        return 0
    if args.assim_smoke:
        # tiny f64 twin experiment — one CPU device; the drill
        # enables x64 itself (deterministic filter pins need it)
        from ibamr_tpu.utils.backend_guard import force_cpu
        force_cpu(1)
        print(json.dumps(run_assim_smoke(args.dir)), flush=True)
        return 0
    if args.record_capsule:
        record_capsule_drill(args.record_capsule)
        return 0
    if args.smoke:
        print(json.dumps(run_smoke(args.dir)), flush=True)
        return 0
    if args.silent_smoke:
        print(json.dumps(run_silent_smoke(args.dir)), flush=True)
        return 0
    if args.replay_smoke:
        print(json.dumps(run_replay_smoke(args.dir)), flush=True)
        return 0
    ap.print_help()
    return 2


if __name__ == "__main__":
    # ``python -m tools.fault_injection`` executes this file as
    # ``__main__`` — a SECOND module object from the canonical
    # ``tools.fault_injection`` the flight recorder fingerprints
    # ``ACTIVE_INJECTORS`` from. Delegate to the canonical import so
    # ``recorded`` blocks land in the registry replays read.
    import tools.fault_injection as _canonical
    raise SystemExit(_canonical.main())
