"""Fault-injection harness for the resilience layer (PR 2 tentpole 4).

The recovery machinery (atomic verified checkpoints, the
ResilientDriver rollback loop, engine degradation) is only trustworthy
if the failure paths are EXERCISED — a recovery path that has never run
is a second bug waiting behind the first. This module supplies the
deterministic fault injectors the resilience tests and the multichip
dryrun drill are built from:

- :func:`nan_injector_step` / :func:`inject_nan` — poison a named state
  leaf with NaN at a chosen step, inside or outside jit. The jittable
  wrapper is dt-gated so a supervised retry at backed-off dt passes
  cleanly (the injected fault models a too-aggressive timestep, the
  exact failure dt-backoff exists to cure).
- :func:`truncate_checkpoint` / :func:`corrupt_checkpoint` /
  :func:`drop_sidecar` — the three on-disk damage modes a crash or a
  bad disk can leave: a short file, flipped bytes at unchanged size,
  and an array file whose commit marker never landed.
- :func:`failing_checkpoint_writes` — make the Nth checkpoint write(s)
  raise, underneath the async writer's retry.
- :func:`run_crash_child` — the deterministic checkpoint-writer loop
  the SIGKILL-mid-write subprocess drill runs as its victim: the whole
  trajectory is a closed-form function of the step count
  (:func:`crash_state`), so the parent can verify any restored
  checkpoint bitwise without trusting the child.
- :func:`run_smoke` — a self-contained end-to-end drill (supervised
  NaN recovery + corruption fallback + flaky-write retry) wired into
  ``__graft_entry__.dryrun_multichip`` as path 16 and exposed as
  ``python -m tools.fault_injection --smoke``.

Everything here is deliberately boring and deterministic: no random
fuzzing, every fault lands at a named step/byte so a failure
reproduces.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import tempfile

import numpy as np


# ---------------------------------------------------------------------------
# NaN injection
# ---------------------------------------------------------------------------

def _match_paths(state, leaf_path: str):
    """Pytree paths whose keystr contains ``leaf_path`` (e.g. ``"u[0]"``
    matches the first MAC velocity component of an INSState)."""
    import jax

    flat = jax.tree_util.tree_flatten_with_path(state)[0]
    return [jax.tree_util.keystr(p) for p, _ in flat
            if leaf_path in jax.tree_util.keystr(p)]


def inject_nan(state, leaf_path: str):
    """Host-side: return ``state`` with NaN written into every floating
    leaf whose path contains ``leaf_path``. Raises if nothing matches
    (a typo'd path must not silently inject nothing)."""
    import jax
    import jax.numpy as jnp

    hit = []

    def _poison(path, leaf):
        key = jax.tree_util.keystr(path)
        if leaf_path in key and hasattr(leaf, "dtype") \
                and jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
            hit.append(key)
            bad = jnp.asarray(leaf).at[...].set(jnp.nan)
            return bad
        return leaf

    out = jax.tree_util.tree_map_with_path(_poison, state)
    if not hit:
        raise KeyError(
            f"no floating leaf path contains {leaf_path!r}; "
            f"available: {_match_paths(state, '')}")
    return out


def nan_injector_step(step_fn, at_step: int, leaf_path: str = "u",
                      dt_gate: float | None = None,
                      step_attr: str = "k"):
    """Wrap ``step_fn(state, dt) -> state`` so the stepped state comes
    out poisoned (NaN in every floating leaf matching ``leaf_path``)
    exactly when its step counter ``state.<step_attr>`` equals
    ``at_step`` — jit/scan-safe (the fault is a ``jnp.where`` on traced
    values, not python control flow).

    ``dt_gate`` arms the fault only while ``dt >= dt_gate``: a
    supervised retry at backed-off dt then passes cleanly, modelling an
    instability that a smaller timestep cures. Without it the injector
    would re-fire on every retry and the supervisor could never win.
    """
    import jax
    import jax.numpy as jnp

    def wrapped(state, dt):
        out = step_fn(state, dt)
        k = getattr(out, step_attr)
        fire = jnp.asarray(k) == at_step
        if dt_gate is not None:
            fire = jnp.logical_and(fire, jnp.asarray(dt) >= dt_gate)
        hit = []

        def _poison(path, leaf):
            key = jax.tree_util.keystr(path)
            if leaf_path in key and hasattr(leaf, "dtype") \
                    and jnp.issubdtype(leaf.dtype, jnp.floating):
                hit.append(key)
                return jnp.where(fire, jnp.asarray(jnp.nan, leaf.dtype),
                                 leaf)
            return leaf

        out = jax.tree_util.tree_map_with_path(_poison, out)
        if not hit:
            raise KeyError(f"no floating leaf path contains {leaf_path!r}")
        return out

    return wrapped


# ---------------------------------------------------------------------------
# On-disk checkpoint damage
# ---------------------------------------------------------------------------

def _ckpt_path(directory: str, step: int, ext: str = "npz") -> str:
    return os.path.join(directory, f"restore.{step:08d}.{ext}")


def truncate_checkpoint(directory: str, step: int,
                        keep_bytes: int | None = None) -> str:
    """Chop the array file short (default: half) — what a torn write
    WOULD look like if the writer were not atomic. The sidecar's size
    record must now flunk verification."""
    path = _ckpt_path(directory, step)
    size = os.path.getsize(path)
    keep = size // 2 if keep_bytes is None else keep_bytes
    with open(path, "r+b") as f:
        f.truncate(keep)
    return path

def corrupt_checkpoint(directory: str, step: int,
                       offset: int | None = None) -> str:
    """Flip one byte WITHOUT changing the size — the bad-disk/bitrot
    mode that only the CRC32 can catch."""
    path = _ckpt_path(directory, step)
    size = os.path.getsize(path)
    pos = size // 2 if offset is None else offset
    with open(path, "r+b") as f:
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ 0xFF]))
    return path


def drop_sidecar(directory: str, step: int) -> str:
    """Remove the JSON commit marker: the array file may be perfect but
    without a sidecar the checkpoint never committed."""
    path = _ckpt_path(directory, step, "json")
    os.remove(path)
    return path


@contextlib.contextmanager
def failing_checkpoint_writes(fail_calls, exc_type=OSError):
    """Patch ``checkpoint._write_arrays`` so the 0-based call indices
    in ``fail_calls`` raise ``exc_type``. The async writer's retry
    looks the symbol up per attempt, so ``{0}`` fails only the first
    attempt and the retry lands. Yields the call counter dict."""
    from ibamr_tpu.utils import checkpoint as _ckpt

    fail = set(fail_calls)
    orig = _ckpt._write_arrays
    counter = {"calls": 0}

    def flaky(*args, **kwargs):
        i = counter["calls"]
        counter["calls"] += 1
        if i in fail:
            raise exc_type(f"injected checkpoint write failure (call {i})")
        return orig(*args, **kwargs)

    _ckpt._write_arrays = flaky
    try:
        yield counter
    finally:
        _ckpt._write_arrays = orig


# ---------------------------------------------------------------------------
# Crash-child loop (SIGKILL-mid-write victim)
# ---------------------------------------------------------------------------

def crash_state(step: int, n: int = 64) -> dict:
    """Closed-form deterministic trajectory: the state after ``step``
    iterations of a fixed contraction map. float64 numpy, so every
    process that evaluates it gets bitwise-identical leaves — the
    parent verifies a child's checkpoint by recomputing, not by
    trusting the (possibly killed) child."""
    u = np.linspace(0.0, 1.0, n)
    for k in range(1, step + 1):
        u = np.cos(u) * 0.9 + 0.01 * k
    return {"u": u, "k": np.int64(step)}


def run_crash_child(directory: str, num_steps: int, interval: int,
                    keep: int = 3) -> int:
    """The victim loop: resume from the newest VERIFIED checkpoint,
    iterate the contraction map, checkpoint every ``interval`` steps
    printing ``SAVED <k>`` markers (the parent kills on a marker).
    Returns the step reached."""
    from ibamr_tpu.utils.checkpoint import (latest_step,
                                            restore_checkpoint,
                                            save_checkpoint)

    start = latest_step(directory)
    if start is None:
        start, u = 0, crash_state(0)["u"]
    else:
        state, start, _ = restore_checkpoint(
            directory, template=crash_state(start), step=start)
        u = np.asarray(state["u"])
    print(f"START {start}", flush=True)
    for k in range(start + 1, num_steps + 1):
        u = np.cos(u) * 0.9 + 0.01 * k
        if k % interval == 0:
            save_checkpoint(directory, {"u": u, "k": np.int64(k)}, k,
                            keep=keep)
            print(f"SAVED {k}", flush=True)
    print("DONE", flush=True)
    return num_steps


# ---------------------------------------------------------------------------
# End-to-end smoke drill
# ---------------------------------------------------------------------------

def run_smoke(directory: str | None = None) -> dict:
    """Deterministic end-to-end resilience drill on a 16^2 INS run:

    1. supervised recovery — NaN injected at step 6 diverges the run;
       the ResilientDriver rolls back to the step-4 checkpoint, halves
       dt (which disarms the dt-gated injector) and completes;
    2. corruption fallback — flip a byte in the newest checkpoint and
       prove ``latest_step``/``restore_checkpoint`` fall back to the
       newest VERIFIED one;
    3. flaky-write retry — fail the next write's first attempt and
       prove the async writer's retry still lands a verified file.

    Returns (and the CLI prints) a one-line JSON summary. Raises on
    any failed expectation — wired into the multichip dryrun rotation,
    so a regression in the recovery path fails CI, not a real run.
    """
    import jax.numpy as jnp

    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
    from ibamr_tpu.utils.checkpoint import (AsyncCheckpointWriter,
                                            latest_step,
                                            restore_checkpoint,
                                            verify_checkpoint)
    from ibamr_tpu.utils.hierarchy_driver import HierarchyDriver, RunConfig
    from ibamr_tpu.utils.supervisor import ResilientDriver

    tmp = None
    if directory is None:
        tmp = tempfile.TemporaryDirectory(prefix="ibamr_fault_smoke_")
        directory = tmp.name
    try:
        g = StaggeredGrid(n=(16, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
        integ = INSStaggeredIntegrator(g, rho=1.0, mu=0.05)
        xf, yc = g.face_centers(0, jnp.float32)
        xc, yf = g.face_centers(1, jnp.float32)
        u = jnp.sin(2 * jnp.pi * xf) * jnp.cos(2 * jnp.pi * yc) + 0 * yc
        v = -jnp.cos(2 * jnp.pi * xc) * jnp.sin(2 * jnp.pi * yf) + 0 * xc
        st0 = integ.initialize(u0_arrays=(u, v))

        dt0 = 1e-3
        cfg = RunConfig(dt=dt0, num_steps=12, restart_interval=4,
                        health_interval=2)
        drv = HierarchyDriver(
            integ, cfg,
            step_fn=nan_injector_step(integ.step, at_step=6,
                                      leaf_path="u[0]",
                                      dt_gate=dt0 * 0.99))
        sup = ResilientDriver(drv, directory, max_retries=2,
                              dt_backoff=0.5, handle_signals=False)
        out = sup.run(st0)
        if int(out.k) != cfg.num_steps:
            raise AssertionError(f"supervised run stopped at {int(out.k)}")
        if not bool(jnp.all(jnp.isfinite(out.u[0]))):
            raise AssertionError("supervised run finished non-finite")
        div = [r for r in sup.incidents if r["event"] == "divergence"]
        if len(div) != 1 or div[0]["rollback_step"] != 4:
            raise AssertionError(f"unexpected incidents: {sup.incidents}")

        # 2. corruption fallback
        newest = latest_step(directory)
        corrupt_checkpoint(directory, newest)
        if verify_checkpoint(directory, newest):
            raise AssertionError("byte flip went undetected")
        fell_back = latest_step(directory)
        if fell_back is None or fell_back >= newest:
            raise AssertionError("latest_step did not fall back")
        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            _, got, _ = restore_checkpoint(directory, template=out)
        if got != fell_back:
            raise AssertionError("restore did not fall back")

        # 3. flaky-write retry under the async writer
        w = AsyncCheckpointWriter(directory, keep=3)
        try:
            with failing_checkpoint_writes({0}) as ctr:
                w.save(out, 99)
                w.wait()
            if ctr["calls"] != 2:
                raise AssertionError(f"expected a retry, saw {ctr}")
        finally:
            w.close()
        if not verify_checkpoint(directory, 99):
            raise AssertionError("retried write is not verified")

        return {"fault_smoke": "ok", "divergence_incidents": len(div),
                "rollback_step": div[0]["rollback_step"],
                "corrupt_step_skipped": newest,
                "fallback_step": fell_back,
                "flaky_write_calls": ctr["calls"]}
    finally:
        if tmp is not None:
            tmp.cleanup()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="deterministic fault-injection drills")
    ap.add_argument("--smoke", action="store_true",
                    help="run the end-to-end resilience drill")
    ap.add_argument("--crash-child", metavar="DIR",
                    help="run the checkpoint-writer victim loop in DIR")
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--interval", type=int, default=5)
    ap.add_argument("--keep", type=int, default=3)
    ap.add_argument("--dir", default=None,
                    help="work directory for --smoke (default: temp)")
    args = ap.parse_args(argv)
    if args.crash_child:
        run_crash_child(args.crash_child, args.steps, args.interval,
                        keep=args.keep)
        return 0
    if args.smoke:
        print(json.dumps(run_smoke(args.dir)), flush=True)
        return 0
    ap.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
