"""Serving-path SLO gate (docs/SERVING.md).

``slo.py check`` evaluates measured SLIs against the versioned
``SLO.json`` contract — the latency/health counterpart of the
``tools/serve.py check`` compile-count contract. SLIs come from a run
ledger: by default the command runs a fresh ``cold_warm_drill`` with a
ledger attached (forced host-CPU backend unless ``--backend device``),
flushes the metric registry into it, and reads the SLIs back from the
ledger alone — the same computation works on any production ledger via
``--ledger``, and on a saved drill/bench JSON via ``--drill-json``.

SLIs (:func:`slis_from_ledger`):

- ``warm_first_step_p99_s`` — p99 request-to-first-step latency on the
  warm path, estimated from the
  ``serve_first_step_seconds{path="warm"}`` histogram snapshot in the
  last ``counters`` record (empirical fallback from ``request``
  records when no snapshot landed);
- ``warm_path_compiles`` — ``aot_cache`` miss records at or after the
  first warm request's admission (the PR-11 "warm path is free" claim
  restated as an SLO);
- ``padding_fraction`` — mean of the ``serve_padding_fraction``
  histogram (dead lanes stepped per batch);
- ``quarantine_rate`` — quarantined / completed requests;
- ``cache_hit_ratio`` — executable-cache hits / (hits + misses).

Soak mode (PR 17): ``slo.py check --soak`` runs the bounded
deterministic CPU soak (``serve.loadgen.soak_drill`` — seeded Poisson
+ burst arrivals over the heavy-tailed mix, open loop, committed
tenant-class policies) instead of the cold/warm drill, and evaluates
the SOAK SLIs against the contract's separate ``soak_slos`` section
(:func:`soak_slis_from_ledger`):

- ``soak_warm_p99_s`` — warm first-step p99 UNDER SUSTAINED TRAFFIC
  (the single-request drill number, restated with queueing);
- ``soak_queue_wait_p99_s`` — admission queue-wait p99 from the
  ``serve_queue_wait_seconds`` histogram;
- ``soak_shed_rate`` — shed / admitted;
- ``soak_lost_requests`` — admitted trace_ids with no terminal
  ``request``/``request_shed`` record (the no-lost-request liveness
  invariant; budgeted at exactly 0).

``--soak --tighten`` merges a fresh ``soak_slos`` section into the
existing contract without touching the cold/warm ``slos``.

Elastic mode (PR 18): ``slo.py check --elastic`` runs the elastic
warm-pool drill (``tools.fault_injection.run_elastic_smoke`` — a
mid-soak mix shift onto an unseen family under memory pressure, then
a crash-safe restart) and evaluates the ELASTIC SLIs against the
contract's ``elastic_slos`` section
(:func:`elastic_slis_from_ledger`):

- ``elastic_scale_up_latency_s`` — worst grow-decision-to-warm
  latency (``pool_scale`` warmed confirmations);
- ``elastic_restart_to_warm_s`` — manifest-restore-to-all-warm wall
  time (the ``serving_restore`` record);
- ``elastic_restart_fresh_compiles`` — fresh XLA compiles paid by the
  restart re-warm (aot-cache ``cold_source`` attribution; budgeted at
  exactly 0 — the persistent layer IS the crash-safety claim);
- ``elastic_lost_requests`` — the no-lost-request join, through scale
  events, brownout, and shed (exactly 0);
- ``elastic_mode_transitions`` — serve-mode ladder transitions (an
  oscillating ladder fails the budget, not just the drill);
- ``elastic_interactive_p99_s`` — warm INTERACTIVE first-step p99
  while batch is capped/shed (brownout protects it, or this trips).

``--elastic --tighten`` merges a fresh ``elastic_slos`` section, same
discipline as soak.

Assimilation mode (PR 20): ``slo.py check --assim`` runs the chaos
assimilation drill (``tools.fault_injection.run_assim_smoke`` — all
four observation/member injectors armed at once against the
supervised ensemble filter) and evaluates the ASSIM SLIs against the
contract's ``assim_slos`` section
(:func:`assim_slis_from_ledger`):

- ``assim_lost_cycles`` — observation cycles with no ``assim_cycle``
  ledger record, derived by joining the ``assim_summary`` expected
  count against the cycle stream (budgeted at EXACTLY 0 — a rollback
  that silently drops an analysis is the failure mode this pins);
- ``assim_forecast_error_ratio`` — final forecast error over the
  open-loop (no-assimilation) baseline from the same drill; any
  ceiling below 1.0 IS the "assimilation helps" claim;
- ``assim_analysis_wall_p99_s`` — p99 analysis wall time per cycle
  (histogram snapshot when one landed, else empirical from the
  ``assim_cycle`` records).

``--assim --tighten`` merges a fresh ``assim_slos`` section, same
discipline as soak/elastic.

Exit convention (the ``graph_audit`` family, with one deliberate
difference): **headroom under a ceiling is attainment, not drift** —
a warm p99 far below budget is the system working, so it exits 0, not
1. Exit 1 means the check could not be evaluated (no contract, or a
budgeted SLI the measurement cannot produce); exit 2 means an SLO is
violated. ``--tighten`` rewrites the contract from the measurement
with slack on the latency/ratio budgets.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CONTRACT_PATH = os.path.join(REPO, "SLO.json")
SLO_SCHEMA = 1

# SLI names and their direction; a contract may budget any subset
CEILINGS = ("warm_first_step_p99_s", "warm_path_compiles",
            "padding_fraction", "quarantine_rate")
FLOORS = ("cache_hit_ratio",)
SLI_NAMES = CEILINGS + FLOORS

_WARM_FIRST_KEY = 'serve_first_step_seconds{path="warm"}'
_PADFRAC_KEY = "serve_padding_fraction"

# soak SLIs (PR 17): all ceilings, evaluated against the contract's
# separate "soak_slos" section so the cold/warm check stays untouched
SOAK_SLI_NAMES = ("soak_warm_p99_s", "soak_queue_wait_p99_s",
                  "soak_shed_rate", "soak_lost_requests")
_QWAIT_KEY = "serve_queue_wait_seconds"

# elastic SLIs (PR 18): the autoscaling/brownout/restart invariants of
# the elastic warm-pool drill, evaluated against the contract's
# separate "elastic_slos" section. All ceilings; the two count SLIs
# (lost requests, fresh restart compiles) are budgeted at EXACTLY 0.
ELASTIC_SLI_NAMES = ("elastic_scale_up_latency_s",
                     "elastic_restart_to_warm_s",
                     "elastic_restart_fresh_compiles",
                     "elastic_lost_requests",
                     "elastic_mode_transitions",
                     "elastic_interactive_p99_s")

# assimilation SLIs (PR 20): the forecasting-service invariants of
# the chaos assimilation drill, evaluated against the contract's
# separate "assim_slos" section. All ceilings; lost cycles pin at
# EXACTLY 0 and the error ratio's ceiling sits below 1.0 by
# construction (beating the open loop is the product claim).
ASSIM_SLI_NAMES = ("assim_lost_cycles",
                   "assim_forecast_error_ratio",
                   "assim_analysis_wall_p99_s")
_AWALL_KEY = "assim_analysis_wall_seconds"


def _last_histograms(records) -> dict:
    """The histogram snapshot of the LAST ``counters`` record carrying
    one (cumulative, so the last wins)."""
    out = {}
    for rec in records:
        if rec.get("kind") == "counters" and rec.get("histograms"):
            out = rec["histograms"]
    return out


def _empirical_quantile(values, q):
    if not values:
        return None
    vs = sorted(values)
    return vs[min(len(vs) - 1, max(0, math.ceil(q * len(vs)) - 1))]


def slis_from_ledger(records) -> dict:
    """Compute every SLI the ledger can support; absent ones are
    ``None`` (a budgeted-but-``None`` SLI makes the check exit 1)."""
    from ibamr_tpu.obs.bus import quantiles_from_counts

    requests = [r for r in records if r.get("kind") == "request"]
    admits = [r for r in records if r.get("kind") == "request_admit"]
    cache_ev = [r for r in records if r.get("kind") == "aot_cache"]
    warm = [r for r in requests if not r.get("cold")]

    slis: dict = {name: None for name in SLI_NAMES}
    hists = _last_histograms(records)

    # warm first-step p99: histogram estimate, else empirical
    snap = hists.get(_WARM_FIRST_KEY)
    if snap and snap.get("count"):
        slis["warm_first_step_p99_s"] = quantiles_from_counts(
            snap["counts"], [0.99])[0]
    elif warm:
        slis["warm_first_step_p99_s"] = _empirical_quantile(
            [r["first_step_s"] for r in warm
             if r.get("first_step_s") is not None], 0.99)

    # compiles on the warm path: aot_cache misses at/after the first
    # warm request's admission (trace ids join the two record kinds)
    if warm:
        warm_tids = {r["trace_id"] for r in warm if r.get("trace_id")}
        warm_admits = [a["seq"] for a in admits
                       if a.get("trace_id") in warm_tids]
        if warm_admits:
            first_warm_seq = min(warm_admits)
            slis["warm_path_compiles"] = sum(
                1 for e in cache_ev
                if e.get("event") == "miss"
                and e.get("seq", -1) >= first_warm_seq)

    snap = hists.get(_PADFRAC_KEY)
    if snap and snap.get("count"):
        slis["padding_fraction"] = (float(snap["sum"])
                                    / float(snap["count"]))

    if requests:
        slis["quarantine_rate"] = (
            sum(1 for r in requests if r.get("quarantined"))
            / len(requests))

    hits = sum(1 for e in cache_ev if e.get("event") == "hit")
    misses = sum(1 for e in cache_ev if e.get("event") == "miss")
    if hits + misses:
        slis["cache_hit_ratio"] = hits / (hits + misses)
    return slis


def slis_from_drill(drill: dict) -> dict:
    """SLIs from a saved ``cold_warm_drill`` / serve-bench JSON (the
    ``--drill-json`` path — no ledger needed)."""
    from ibamr_tpu.obs.bus import quantiles_from_counts

    slis: dict = {name: None for name in SLI_NAMES}
    hists = drill.get("histograms") or {}
    snap = hists.get(_WARM_FIRST_KEY)
    if snap and snap.get("count"):
        slis["warm_first_step_p99_s"] = quantiles_from_counts(
            snap["counts"], [0.99])[0]
    elif drill.get("warm_p99_s") is not None:
        slis["warm_first_step_p99_s"] = drill["warm_p99_s"]
    elif drill.get("warm_first_step_s") is not None:
        slis["warm_first_step_p99_s"] = drill["warm_first_step_s"]
    if drill.get("warm_compiles") is not None:
        slis["warm_path_compiles"] = drill["warm_compiles"]
    snap = hists.get(_PADFRAC_KEY)
    if snap and snap.get("count"):
        slis["padding_fraction"] = (float(snap["sum"])
                                    / float(snap["count"]))
    oks = [drill.get("cold_ok"), drill.get("warm_ok")]
    if all(o is not None for o in oks):
        slis["quarantine_rate"] = sum(0 if o else 1 for o in oks) / 2
    hits = drill.get("warm_hits")
    if hits is not None:
        misses = (drill.get("warm_compiles") or 0)
        if hits + misses:
            slis["cache_hit_ratio"] = hits / (hits + misses)
    return slis


def soak_slis_from_ledger(records) -> dict:
    """Soak SLIs from a traffic ledger (``soak_drill`` with a ledger
    attached, or any production ledger). Absent SLIs are ``None``."""
    from ibamr_tpu.obs.bus import quantiles_from_counts

    records = list(records)
    requests = [r for r in records if r.get("kind") == "request"]
    sheds = [r for r in records if r.get("kind") == "request_shed"]
    admits = [r for r in records if r.get("kind") == "request_admit"]
    warm = [r for r in requests if not r.get("cold")]
    hists = _last_histograms(records)

    slis: dict = {name: None for name in SOAK_SLI_NAMES}

    snap = hists.get(_WARM_FIRST_KEY)
    if snap and snap.get("count"):
        slis["soak_warm_p99_s"] = quantiles_from_counts(
            snap["counts"], [0.99])[0]
    elif warm:
        slis["soak_warm_p99_s"] = _empirical_quantile(
            [r["first_step_s"] for r in warm
             if r.get("first_step_s") is not None], 0.99)

    snap = hists.get(_QWAIT_KEY)
    if snap and snap.get("count"):
        slis["soak_queue_wait_p99_s"] = quantiles_from_counts(
            snap["counts"], [0.99])[0]
    else:
        qwaits = [r["queue_wait_s"] for r in requests + sheds
                  if r.get("queue_wait_s") is not None]
        if qwaits:
            slis["soak_queue_wait_p99_s"] = _empirical_quantile(
                qwaits, 0.99)

    terminal = len(requests) + len(sheds)
    if terminal:
        slis["soak_shed_rate"] = len(sheds) / terminal

    # the liveness invariant, from the ledger alone: every admitted
    # trace_id must reach a terminal record
    if admits:
        done = {r.get("trace_id") for r in requests + sheds
                if r.get("trace_id")}
        slis["soak_lost_requests"] = sum(
            1 for a in admits
            if a.get("trace_id") and a["trace_id"] not in done)
    return slis


def elastic_slis_from_ledger(records) -> dict:
    """Elastic SLIs from an elastic-drill (or production) ledger:
    scaling latency from ``pool_scale`` warm confirmations, restart
    health from the ``serving_restore`` record, mode-ladder stability
    from ``serve_mode`` transitions, and the interactive warm p99 +
    no-lost-request join from the request stream. Absent SLIs are
    ``None``."""
    records = list(records)
    requests = [r for r in records if r.get("kind") == "request"]
    sheds = [r for r in records if r.get("kind") == "request_shed"]
    admits = [r for r in records if r.get("kind") == "request_admit"]

    slis: dict = {name: None for name in ELASTIC_SLI_NAMES}

    warmed = [r.get("warm_s") for r in records
              if r.get("kind") == "pool_scale"
              and r.get("action") == "warmed"
              and r.get("warm_s") is not None]
    if warmed:
        slis["elastic_scale_up_latency_s"] = max(warmed)

    restores = [r for r in records
                if r.get("kind") == "serving_restore"]
    if restores:
        last = restores[-1]          # the drill's (only) restart
        slis["elastic_restart_to_warm_s"] = last.get("warm_s")
        slis["elastic_restart_fresh_compiles"] = last.get(
            "fresh_compiles")

    modes = [r for r in records if r.get("kind") == "serve_mode"]
    if modes or restores or warmed:
        # zero transitions is a measurement (a quiet drill), but only
        # when the ledger demonstrably came from an elastic run
        slis["elastic_mode_transitions"] = len(modes)

    interactive = [r["first_step_s"] for r in requests
                   if not r.get("cold")
                   and r.get("tenant_class") == "interactive"
                   and r.get("first_step_s") is not None]
    if interactive:
        slis["elastic_interactive_p99_s"] = _empirical_quantile(
            interactive, 0.99)

    if admits:
        done = {r.get("trace_id") for r in requests + sheds
                if r.get("trace_id")}
        slis["elastic_lost_requests"] = sum(
            1 for a in admits
            if a.get("trace_id") and a["trace_id"] not in done)
    return slis


def assim_slis_from_ledger(records) -> dict:
    """Assimilation SLIs from an assimilation-drill (or production)
    ledger. Lost cycles come from joining the ``assim_summary``
    record's expected-cycle count against the observed
    ``assim_cycle`` stream — self-reported verdicts are NOT trusted;
    the forecast-error ratio has to come from the summary because the
    open-loop baseline runs outside the ledger. Absent SLIs are
    ``None``."""
    from ibamr_tpu.obs.bus import quantiles_from_counts

    records = list(records)
    cycles = [r for r in records if r.get("kind") == "assim_cycle"]
    summaries = [r for r in records
                 if r.get("kind") == "assim_summary"]
    hists = _last_histograms(records)

    slis: dict = {name: None for name in ASSIM_SLI_NAMES}

    if summaries:
        last = summaries[-1]
        expected = last.get("cycles")
        if expected is not None:
            done = {r.get("cycle") for r in cycles}
            slis["assim_lost_cycles"] = sum(
                1 for c in range(int(expected)) if c not in done)
        fe, ol = last.get("forecast_error"), last.get("open_loop_error")
        if fe is not None and ol:
            slis["assim_forecast_error_ratio"] = float(fe) / float(ol)

    snap = hists.get(_AWALL_KEY)
    if snap and snap.get("count"):
        slis["assim_analysis_wall_p99_s"] = quantiles_from_counts(
            snap["counts"], [0.99])[0]
    else:
        walls = [r["analysis_wall_s"] for r in cycles
                 if not r.get("skipped")
                 and r.get("analysis_wall_s") is not None]
        if walls:
            slis["assim_analysis_wall_p99_s"] = _empirical_quantile(
                walls, 0.99)
    return slis


def load_contract(path: str = CONTRACT_PATH) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("slo_schema") != SLO_SCHEMA:
        raise ValueError(f"unsupported slo_schema "
                         f"{doc.get('slo_schema')!r} in {path}")
    return doc


def evaluate(slis: dict, contract: dict):
    """(violations, unmeasurable, met) — human-readable lines for each
    budgeted SLO. Attainment headroom is 'met', never drift."""
    violations, unmeasurable, met = [], [], []
    for name, budget in sorted((contract.get("slos") or {}).items()):
        got = slis.get(name)
        if "ceiling" in budget:
            want, floor = float(budget["ceiling"]), False
        elif "floor" in budget:
            want, floor = float(budget["floor"]), True
        else:
            unmeasurable.append(f"{name}: budget has neither ceiling "
                                f"nor floor")
            continue
        if got is None:
            unmeasurable.append(f"{name}: not measurable from this "
                                f"ledger")
            continue
        got = float(got)
        bad = got < want if floor else got > want
        word = "floor" if floor else "ceiling"
        if bad:
            violations.append(f"{name}: measured {got:.6g} vs {word} "
                              f"{want:.6g} (VIOLATED)")
        else:
            met.append(f"{name}: measured {got:.6g} within {word} "
                       f"{want:.6g}")
    return violations, unmeasurable, met


def tighten_contract(slis: dict, drill_cfg: dict) -> dict:
    """A fresh contract from measured SLIs, with slack where variance
    lives: latency ceilings at 2x measured (floored at 0.5 s), ratio
    ceilings +0.2, the hit-ratio floor −0.2; count SLOs pin exactly."""
    slos = {}
    if slis.get("warm_first_step_p99_s") is not None:
        slos["warm_first_step_p99_s"] = {"ceiling": round(
            max(2.0 * slis["warm_first_step_p99_s"], 0.5), 4)}
    if slis.get("warm_path_compiles") is not None:
        slos["warm_path_compiles"] = {
            "ceiling": int(slis["warm_path_compiles"])}
    if slis.get("padding_fraction") is not None:
        slos["padding_fraction"] = {"ceiling": round(
            min(slis["padding_fraction"] + 0.2, 1.0), 4)}
    if slis.get("quarantine_rate") is not None:
        slos["quarantine_rate"] = {
            "ceiling": round(slis["quarantine_rate"], 4)}
    if slis.get("cache_hit_ratio") is not None:
        slos["cache_hit_ratio"] = {"floor": round(
            max(slis["cache_hit_ratio"] - 0.2, 0.0), 4)}
    return {
        "_doc": ("Serving-path SLO contract (tools/slo.py check; see "
                 "docs/SERVING.md). Ceilings violate UP, floors "
                 "violate DOWN; headroom is attainment, not drift. "
                 "Written by --tighten."),
        "slo_schema": SLO_SCHEMA,
        "drill": drill_cfg,
        "slos": slos,
    }


def run_drill_ledger(args, ledger_path: str) -> dict:
    """Run ``cold_warm_drill`` with a fresh attached ledger and flush
    the metric registry into it; returns the drill output."""
    if args.backend == "device":
        from ibamr_tpu.utils.backend_guard import init_backend_with_retry
        _jax, _platform, err = init_backend_with_retry(retries=1,
                                                       delay=2.0)
        if err:
            print(f"[slo] backend init degraded: {err}",
                  file=sys.stderr)
    else:
        from ibamr_tpu.utils.backend_guard import force_cpu
        force_cpu()
    from ibamr_tpu import obs as _obs
    from ibamr_tpu.serve.router import cold_warm_drill

    with _obs.ledger(ledger_path):
        out = cold_warm_drill(
            n_cells=args.n, n_lat=args.n_lat, n_lon=args.n_lon,
            lanes=args.lanes, steps=args.steps, dt=args.dt,
            engine=args.engine or None,
            warm_requests=args.warm_requests)
        # land the histogram snapshots in the ledger: the SLI
        # computation must work from the ledger ALONE
        _obs.chunk_boundary()
    return out


def run_soak_ledger(args, ledger_path: str) -> dict:
    """Run the bounded open-loop soak with a fresh attached ledger
    and flush the metric registry into it; returns the traffic
    summary."""
    if args.backend == "device":
        from ibamr_tpu.utils.backend_guard import init_backend_with_retry
        _jax, _platform, err = init_backend_with_retry(retries=1,
                                                       delay=2.0)
        if err:
            print(f"[slo] backend init degraded: {err}",
                  file=sys.stderr)
    else:
        from ibamr_tpu.utils.backend_guard import force_cpu
        force_cpu()
    from ibamr_tpu import obs as _obs
    from ibamr_tpu.serve.loadgen import soak_drill

    with _obs.ledger(ledger_path):
        out = soak_drill(seed=args.soak_seed,
                         duration_s=args.soak_duration,
                         rate_rps=args.soak_rate,
                         burst_factor=args.soak_burst,
                         n_cells=args.n, n_lat=args.n_lat,
                         n_lon=args.n_lon, lanes=args.lanes,
                         time_scale=args.soak_time_scale)
        _obs.chunk_boundary()
    return out


def run_elastic_drill(args, directory: str) -> dict:
    """Run the bounded elastic warm-pool drill in ``directory``; the
    drill owns its own attached ledger
    (``<directory>/elastic_ledger.jsonl``) and raises on any broken
    invariant before the SLO layer even evaluates."""
    if args.backend == "device":
        from ibamr_tpu.utils.backend_guard import init_backend_with_retry
        _jax, _platform, err = init_backend_with_retry(retries=1,
                                                       delay=2.0)
        if err:
            print(f"[slo] backend init degraded: {err}",
                  file=sys.stderr)
    else:
        from ibamr_tpu.utils.backend_guard import force_cpu
        force_cpu()
    from tools.fault_injection import run_elastic_smoke

    return run_elastic_smoke(directory,
                             duration_s=args.elastic_duration,
                             rate_rps=args.elastic_rate,
                             time_scale=args.elastic_time_scale,
                             shift_frac=args.elastic_shift_frac)


def run_assim_drill(args, directory: str) -> dict:
    """Run the chaos assimilation drill in ``directory``; the drill
    owns its own attached ledger (``<directory>/assim_ledger.jsonl``)
    and raises on any broken invariant (unrejected bad obs,
    unquarantined member, lost cycle, retrace) before the SLO layer
    even evaluates."""
    if args.backend == "device":
        from ibamr_tpu.utils.backend_guard import init_backend_with_retry
        _jax, _platform, err = init_backend_with_retry(retries=1,
                                                       delay=2.0)
        if err:
            print(f"[slo] backend init degraded: {err}",
                  file=sys.stderr)
    else:
        from ibamr_tpu.utils.backend_guard import force_cpu
        force_cpu()
    from tools.fault_injection import run_assim_smoke

    return run_assim_smoke(directory,
                           fleet_size=args.assim_fleet,
                           cycles=args.assim_cycles)


def tighten_assim(slis: dict, assim_cfg: dict, contract_path: str):
    """Merge a fresh ``assim_slos`` section (plus the drill cfg) into
    the existing contract, leaving every other section untouched.
    Lost cycles pin EXACTLY (zero is the invariant); the error-ratio
    ceiling gets 4x slack but is clamped BELOW 1.0 — a contract that
    tolerated losing to the open loop would not be a forecasting SLO;
    the wall ceiling gets 3x slack floored at 0.5 s (the p99 of a
    short drill IS the first cycle, which pays the one-time AOT
    compile — noisier than a steady-state latency)."""
    assim_slos = {}
    if slis.get("assim_lost_cycles") is not None:
        assim_slos["assim_lost_cycles"] = {
            "ceiling": int(slis["assim_lost_cycles"])}
    if slis.get("assim_forecast_error_ratio") is not None:
        assim_slos["assim_forecast_error_ratio"] = {"ceiling": round(
            min(max(4.0 * slis["assim_forecast_error_ratio"], 0.25),
                0.9), 4)}
    if slis.get("assim_analysis_wall_p99_s") is not None:
        assim_slos["assim_analysis_wall_p99_s"] = {"ceiling": round(
            max(3.0 * slis["assim_analysis_wall_p99_s"], 0.5), 4)}
    try:
        doc = load_contract(contract_path)
    except FileNotFoundError:
        doc = {"slo_schema": SLO_SCHEMA, "slos": {}}
    doc["assim"] = assim_cfg
    doc["assim_slos"] = assim_slos
    return doc


def tighten_elastic(slis: dict, elastic_cfg: dict,
                    contract_path: str):
    """Merge a fresh ``elastic_slos`` section (plus the drill cfg)
    into the existing contract, leaving ``slos``/``soak_slos``
    untouched. Latency ceilings get 2x slack (floored at 1 s), the
    transition ceiling +2; lost requests and fresh restart compiles
    pin EXACTLY (zero is the invariant, not a budget)."""
    elastic_slos = {}
    if slis.get("elastic_scale_up_latency_s") is not None:
        elastic_slos["elastic_scale_up_latency_s"] = {"ceiling": round(
            max(2.0 * slis["elastic_scale_up_latency_s"], 1.0), 4)}
    if slis.get("elastic_restart_to_warm_s") is not None:
        elastic_slos["elastic_restart_to_warm_s"] = {"ceiling": round(
            max(2.0 * slis["elastic_restart_to_warm_s"], 1.0), 4)}
    if slis.get("elastic_restart_fresh_compiles") is not None:
        elastic_slos["elastic_restart_fresh_compiles"] = {
            "ceiling": int(slis["elastic_restart_fresh_compiles"])}
    if slis.get("elastic_lost_requests") is not None:
        elastic_slos["elastic_lost_requests"] = {
            "ceiling": int(slis["elastic_lost_requests"])}
    if slis.get("elastic_mode_transitions") is not None:
        elastic_slos["elastic_mode_transitions"] = {
            "ceiling": int(slis["elastic_mode_transitions"]) + 2}
    if slis.get("elastic_interactive_p99_s") is not None:
        elastic_slos["elastic_interactive_p99_s"] = {"ceiling": round(
            max(2.0 * slis["elastic_interactive_p99_s"], 1.0), 4)}
    try:
        doc = load_contract(contract_path)
    except FileNotFoundError:
        doc = {"slo_schema": SLO_SCHEMA, "slos": {}}
    doc["elastic"] = elastic_cfg
    doc["elastic_slos"] = elastic_slos
    return doc


def tighten_soak(slis: dict, soak_cfg: dict, contract_path: str):
    """Merge a fresh ``soak_slos`` section (plus the soak drill cfg)
    into the existing contract, leaving the cold/warm ``slos``
    untouched. Latency ceilings get 2x slack (floored at 0.5 s), the
    shed-rate ceiling +0.2; lost requests pin EXACTLY (zero is the
    invariant, not a budget)."""
    soak_slos = {}
    if slis.get("soak_warm_p99_s") is not None:
        soak_slos["soak_warm_p99_s"] = {"ceiling": round(
            max(2.0 * slis["soak_warm_p99_s"], 0.5), 4)}
    if slis.get("soak_queue_wait_p99_s") is not None:
        soak_slos["soak_queue_wait_p99_s"] = {"ceiling": round(
            max(2.0 * slis["soak_queue_wait_p99_s"], 0.5), 4)}
    if slis.get("soak_shed_rate") is not None:
        soak_slos["soak_shed_rate"] = {"ceiling": round(
            min(slis["soak_shed_rate"] + 0.2, 1.0), 4)}
    if slis.get("soak_lost_requests") is not None:
        soak_slos["soak_lost_requests"] = {
            "ceiling": int(slis["soak_lost_requests"])}
    try:
        doc = load_contract(contract_path)
    except FileNotFoundError:
        doc = {"slo_schema": SLO_SCHEMA, "slos": {}}
    doc["soak"] = soak_cfg
    doc["soak_slos"] = soak_slos
    return doc


def cmd_check(args) -> int:
    if getattr(args, "assim", False):
        return _check_assim(args)
    if getattr(args, "elastic", False):
        return _check_elastic(args)
    if getattr(args, "soak", False):
        return _check_soak(args)
    if args.ledger:
        from ibamr_tpu.obs.bus import read_ledger
        slis = slis_from_ledger(read_ledger(args.ledger))
        drill_cfg = {"source": args.ledger}
    elif args.drill_json:
        with open(args.drill_json) as f:
            doc = json.load(f)
        drill = doc.get("serve", doc)   # bench artifact or raw drill
        slis = slis_from_drill(drill)
        drill_cfg = {"source": args.drill_json}
    else:
        from ibamr_tpu.obs.bus import read_ledger
        with tempfile.TemporaryDirectory(prefix="slo-") as td:
            lp = os.path.join(td, "ledger.jsonl")
            run_drill_ledger(args, lp)
            records = read_ledger(lp)
        slis = slis_from_ledger(records)
        drill_cfg = {"n": args.n, "n_lat": args.n_lat,
                     "n_lon": args.n_lon, "lanes": args.lanes,
                     "steps": args.steps,
                     "warm_requests": args.warm_requests}

    if args.tighten:
        doc = tighten_contract(slis, drill_cfg)
        with open(args.contract, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"[slo] wrote {args.contract}")
        return 0

    try:
        contract = load_contract(args.contract)
    except FileNotFoundError:
        contract = None
    if contract is None:
        violations, unmeasurable, met = [], [], []
    else:
        violations, unmeasurable, met = evaluate(slis, contract)
    rc = (2 if violations
          else 1 if unmeasurable or contract is None
          else 0)
    if args.as_json:
        print(json.dumps({
            "exit": rc, "slis": slis,
            "violated": violations, "unmeasurable": unmeasurable,
            "met": met, "unbudgeted": contract is None},
            indent=1, sort_keys=True))
        return rc
    for line in violations:
        print(f"[slo] {line}")
    for line in unmeasurable:
        print(f"[slo] {line}")
    for line in met:
        print(f"[slo] {line}")
    if contract is None:
        print(f"[slo] no contract at {args.contract} — run --tighten "
              f"to pin")
    verdict = {0: "clean — every SLO attained",
               1: "unevaluable — missing contract or SLI "
                  "(run --tighten to pin)",
               2: "VIOLATED — the serving path is out of SLO"}[rc]
    print(f"[slo] {verdict}")
    return rc


def _check_assim(args) -> int:
    """The ``check --assim`` path: assimilation SLIs vs the
    contract's ``assim_slos`` section, same exit convention as the
    cold/warm check. Without ``--ledger`` the chaos assimilation
    drill runs first — its own pinned invariants (every injected bad
    obs rejected, the diverged member quarantined, zero lost cycles,
    zero retraces, filter beats open loop) raise before the budget is
    even consulted, so exit 2 here means a BUDGET regression on a
    drill that still satisfies the hard invariants."""
    from ibamr_tpu.obs.bus import read_ledger

    if args.ledger:
        records = read_ledger(args.ledger)
        assim_cfg = {"source": args.ledger}
    else:
        with tempfile.TemporaryDirectory(prefix="slo-assim-") as td:
            run_assim_drill(args, td)
            records = read_ledger(
                os.path.join(td, "assim_ledger.jsonl"))
        assim_cfg = {"fleet_size": args.assim_fleet,
                     "cycles": args.assim_cycles}
    slis = assim_slis_from_ledger(records)

    if args.tighten:
        doc = tighten_assim(slis, assim_cfg, args.contract)
        with open(args.contract, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"[slo] wrote {args.contract} (assim_slos)")
        return 0

    try:
        contract = load_contract(args.contract)
    except FileNotFoundError:
        contract = None
    budget = (contract or {}).get("assim_slos")
    if not budget:
        violations, unmeasurable, met = [], [], []
    else:
        violations, unmeasurable, met = evaluate(slis, {"slos": budget})
    unbudgeted = not budget
    rc = (2 if violations
          else 1 if unmeasurable or unbudgeted
          else 0)
    if args.as_json:
        print(json.dumps({
            "exit": rc, "slis": slis,
            "violated": violations, "unmeasurable": unmeasurable,
            "met": met, "unbudgeted": unbudgeted},
            indent=1, sort_keys=True))
        return rc
    for line in violations + unmeasurable + met:
        print(f"[slo] {line}")
    if unbudgeted:
        print(f"[slo] no assim_slos in {args.contract} — run "
              f"--assim --tighten to pin")
    verdict = {0: "clean — every assimilation SLO attained",
               1: "unevaluable — missing assim_slos or SLI (run "
                  "--assim --tighten to pin)",
               2: "VIOLATED — the forecasting service is out of "
                  "SLO"}[rc]
    print(f"[slo] {verdict}")
    return rc


def _check_elastic(args) -> int:
    """The ``check --elastic`` path: elastic SLIs vs the contract's
    ``elastic_slos`` section, same exit convention as the cold/warm
    check. Without ``--ledger`` the bounded elastic drill runs first
    — its own pinned invariants raise before the budget is even
    consulted, so exit 2 here means a BUDGET regression on a drill
    that still satisfies the hard invariants."""
    from ibamr_tpu.obs.bus import read_ledger

    if args.ledger:
        records = read_ledger(args.ledger)
        elastic_cfg = {"source": args.ledger}
    else:
        with tempfile.TemporaryDirectory(prefix="slo-elastic-") as td:
            run_elastic_drill(args, td)
            records = read_ledger(
                os.path.join(td, "elastic_ledger.jsonl"))
        elastic_cfg = {"duration_s": args.elastic_duration,
                       "rate_rps": args.elastic_rate,
                       "shift_frac": args.elastic_shift_frac,
                       "time_scale": args.elastic_time_scale}
    slis = elastic_slis_from_ledger(records)

    if args.tighten:
        doc = tighten_elastic(slis, elastic_cfg, args.contract)
        with open(args.contract, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"[slo] wrote {args.contract} (elastic_slos)")
        return 0

    try:
        contract = load_contract(args.contract)
    except FileNotFoundError:
        contract = None
    budget = (contract or {}).get("elastic_slos")
    if not budget:
        violations, unmeasurable, met = [], [], []
    else:
        violations, unmeasurable, met = evaluate(slis, {"slos": budget})
    unbudgeted = not budget
    rc = (2 if violations
          else 1 if unmeasurable or unbudgeted
          else 0)
    if args.as_json:
        print(json.dumps({
            "exit": rc, "slis": slis,
            "violated": violations, "unmeasurable": unmeasurable,
            "met": met, "unbudgeted": unbudgeted},
            indent=1, sort_keys=True))
        return rc
    for line in violations + unmeasurable + met:
        print(f"[slo] {line}")
    if unbudgeted:
        print(f"[slo] no elastic_slos in {args.contract} — run "
              f"--elastic --tighten to pin")
    verdict = {0: "clean — every elastic SLO attained",
               1: "unevaluable — missing elastic_slos or SLI (run "
                  "--elastic --tighten to pin)",
               2: "VIOLATED — the elastic serving path is out of "
                  "SLO"}[rc]
    print(f"[slo] {verdict}")
    return rc


def _check_soak(args) -> int:
    """The ``check --soak`` path: soak SLIs vs the contract's
    ``soak_slos`` section, same exit convention as the cold/warm
    check."""
    from ibamr_tpu.obs.bus import read_ledger

    if args.ledger:
        records = read_ledger(args.ledger)
        soak_cfg = {"source": args.ledger}
    else:
        with tempfile.TemporaryDirectory(prefix="slo-soak-") as td:
            lp = os.path.join(td, "ledger.jsonl")
            run_soak_ledger(args, lp)
            records = read_ledger(lp)
        soak_cfg = {"seed": args.soak_seed,
                    "duration_s": args.soak_duration,
                    "rate_rps": args.soak_rate,
                    "burst_factor": args.soak_burst,
                    "time_scale": args.soak_time_scale,
                    "n": args.n, "n_lat": args.n_lat,
                    "n_lon": args.n_lon, "lanes": args.lanes}
    slis = soak_slis_from_ledger(records)

    if args.tighten:
        doc = tighten_soak(slis, soak_cfg, args.contract)
        with open(args.contract, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"[slo] wrote {args.contract} (soak_slos)")
        return 0

    try:
        contract = load_contract(args.contract)
    except FileNotFoundError:
        contract = None
    budget = (contract or {}).get("soak_slos")
    if not budget:
        violations, unmeasurable, met = [], [], []
    else:
        violations, unmeasurable, met = evaluate(slis, {"slos": budget})
    unbudgeted = not budget
    rc = (2 if violations
          else 1 if unmeasurable or unbudgeted
          else 0)
    if args.as_json:
        print(json.dumps({
            "exit": rc, "slis": slis,
            "violated": violations, "unmeasurable": unmeasurable,
            "met": met, "unbudgeted": unbudgeted},
            indent=1, sort_keys=True))
        return rc
    for line in violations + unmeasurable + met:
        print(f"[slo] {line}")
    if unbudgeted:
        print(f"[slo] no soak_slos in {args.contract} — run "
              f"--soak --tighten to pin")
    verdict = {0: "clean — every soak SLO attained",
               1: "unevaluable — missing soak_slos or SLI (run "
                  "--soak --tighten to pin)",
               2: "VIOLATED — the serving path is out of SLO under "
                  "sustained traffic"}[rc]
    print(f"[slo] {verdict}")
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="serving-path SLO gate: evaluate a ledger (or a "
                    "fresh cold_warm_drill) against SLO.json")
    sub = ap.add_subparsers(dest="cmd", required=True)

    c = sub.add_parser("check", help="evaluate SLIs vs the contract "
                                     "(exit 0 clean / 1 unevaluable / "
                                     "2 violated)")
    c.add_argument("--contract", type=str, default=CONTRACT_PATH)
    c.add_argument("--ledger", type=str, default="",
                   help="evaluate an existing ledger.jsonl instead of "
                        "running a drill")
    c.add_argument("--drill-json", type=str, default="",
                   help="evaluate a saved drill/bench JSON instead of "
                        "running a drill")
    c.add_argument("--backend", choices=("cpu", "device"),
                   default="cpu",
                   help="drill backend: forced host CPU (hermetic CI "
                        "default) or the real device (relay captures)")
    c.add_argument("--n", type=int, default=8)
    c.add_argument("--n-lat", type=int, default=6)
    c.add_argument("--n-lon", type=int, default=8)
    c.add_argument("--lanes", type=int, default=2)
    c.add_argument("--steps", type=int, default=3)
    c.add_argument("--dt", type=float, default=5e-5)
    c.add_argument("--engine", type=str, default="",
                   help="engine name ('' = auto via the resolver)")
    c.add_argument("--warm-requests", type=int, default=8)
    c.add_argument("--soak", action="store_true",
                   help="run the bounded open-loop soak instead of "
                        "the cold/warm drill and evaluate the "
                        "soak_slos section")
    c.add_argument("--soak-duration", type=float, default=6.0,
                   help="virtual seconds of arrivals in the soak")
    c.add_argument("--soak-rate", type=float, default=6.0,
                   help="base arrival rate (requests per virtual s)")
    c.add_argument("--soak-seed", type=int, default=0)
    c.add_argument("--soak-burst", type=float, default=4.0,
                   help="rate multiplier inside the burst window")
    c.add_argument("--soak-time-scale", type=float, default=0.5,
                   help="wall seconds per virtual second (0.5 = "
                        "replay the schedule at 2x speed)")
    c.add_argument("--elastic", action="store_true",
                   help="run the elastic warm-pool drill (mix shift "
                        "+ memory pressure + restart) and evaluate "
                        "the elastic_slos section")
    c.add_argument("--elastic-duration", type=float, default=5.0,
                   help="virtual seconds of arrivals in the elastic "
                        "drill")
    c.add_argument("--elastic-rate", type=float, default=8.0,
                   help="base arrival rate (requests per virtual s)")
    c.add_argument("--elastic-shift-frac", type=float, default=0.4,
                   help="fraction of the run after which the mix "
                        "rotates to the unseen family")
    c.add_argument("--elastic-time-scale", type=float, default=0.5,
                   help="wall seconds per virtual second")
    c.add_argument("--assim", action="store_true",
                   help="run the chaos assimilation drill (all four "
                        "obs/member injectors armed) and evaluate "
                        "the assim_slos section")
    c.add_argument("--assim-fleet", type=int, default=6,
                   help="ensemble size B for the assimilation drill")
    c.add_argument("--assim-cycles", type=int, default=6,
                   help="observation cycles in the assimilation "
                        "drill")
    c.add_argument("--tighten", action="store_true",
                   help="rewrite the contract from the measured SLIs "
                        "(with slack on latency/ratio budgets)")
    c.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout")
    c.set_defaults(fn=cmd_check)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
