"""Micro-benchmark: decompose the spread/interp cost at the flagship size.

Times the SUB-phases of the bucketed MXU transfer engine (bucket build,
weight evaluation, einsum contraction, overlap-add) separately on the
real chip, so transfer-engine optimization is driven by measurement
instead of the aggregate `phases` table in bench.py.

Usage:  python tools/microbench_transfer.py [--n 256] [--cap 0] [--reps 10]
(--cap 0 = use suggest_cap like the flagship model does).
"""

from __future__ import annotations

import argparse
import math
import os
import sys
import time

import numpy as np

# importable regardless of caller cwd (the relay watcher invokes this
# as a script; python puts tools/ on sys.path, not the repo root)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def timeit(fn, reps):
    import jax

    jax.block_until_ready(fn())  # compile + drain the warm-up step
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--cap", type=int, default=0)
    ap.add_argument("--tile", type=int, default=8)
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--no-pallas", action="store_true",
                    help="skip the pallas-packed legs (remote-compile "
                         "stall risk)")
    args = ap.parse_args()

    import os

    import jax
    import jax.numpy as jnp

    d = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", d)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.models.shell3d import make_spherical_shell
    from ibamr_tpu.ops import interaction_fast as fast

    n = args.n
    grid = StaggeredGrid(n=(n, n, n), x_lo=(0.0, 0.0, 0.0),
                         x_up=(1.0, 1.0, 1.0))
    n_lat = n_lon = 316 if n >= 256 else 180
    s = make_spherical_shell(n_lat, n_lon, 0.25, (0.5, 0.5, 0.5), 1.0,
                             aspect=1.2)
    X = jnp.asarray(s.vertices, dtype=jnp.float32)
    N = X.shape[0]
    F = jnp.ones((N, 3), dtype=jnp.float32)

    cap = args.cap or min(fast.suggest_cap(grid, s.vertices, tile=args.tile),
                          1024)
    eng = fast.FastInteraction(grid, tile=args.tile, cap=cap,
                               overflow_cap=max(2048, N // 4))
    geom = eng.geom
    B = int(np.prod(geom.nblk))
    print(f"n={n} N={N} tile={args.tile} cap={cap} B={B} "
          f"slots={B * cap} util={N / (B * cap):.3f} "
          f"backend={jax.default_backend()}")

    b = jax.jit(eng.buckets)(X)
    occ = np.asarray(jnp.sum(b.wb > 0, axis=1))
    print(f"occupancy: mean={occ.mean():.1f} max={occ.max()} "
          f"active_tiles={np.sum(occ > 0)} "
          f"overflow={int(jnp.sum(b.w_overflow > 0))}")

    r = args.reps
    t_bucket = timeit(jax.jit(lambda: eng.buckets(X)), r)

    wfn = jax.jit(lambda: fast._tile_weights(geom, grid, b, 0, "IB_4"))
    t_weights = timeit(wfn, r)
    A, Wlast = wfn()

    ein = jax.jit(lambda: jnp.einsum(
        "bmp,bmz->bpz", A, Wlast, precision=jax.lax.Precision.HIGHEST))
    t_einsum = timeit(ein, r)
    T = ein()

    ov = jax.jit(lambda: fast._overlap_add(geom, grid, T.reshape(
        (T.shape[0],) + tuple(geom.width) + (n,))))
    t_overlap = timeit(ov, r)

    ex = jax.jit(lambda: fast._extract_tiles(geom, grid, ov()))
    t_extract = timeit(ex, r) - t_overlap

    t_spread3 = timeit(jax.jit(
        lambda: eng.spread_vel(F, X, b=b)), r)
    u = tuple(jnp.zeros(grid.n, dtype=jnp.float32) for _ in range(3))
    t_interp3 = timeit(jax.jit(
        lambda: eng.interpolate_vel(u, X, b=b)), r)

    # packed-chunk engine comparison
    from ibamr_tpu.ops import interaction_packed as packed

    Q = packed.suggest_chunks(grid, s.vertices, tile=args.tile, chunk=128)
    peng = packed.PackedInteraction(grid, tile=args.tile, chunk=128,
                                    nchunks=Q,
                                    overflow_cap=max(2048, N // 4))
    pb = jax.jit(peng.buckets)(X)
    print(f"packed: Q={Q} slots={Q * 128} util={N / (Q * 128):.3f} "
          f"overflow={int(jnp.sum(pb.w_overflow > 0))}")
    t_pbucket = timeit(jax.jit(lambda: peng.buckets(X)), r)

    # slot-preserving refresh vs full re-pack: a half-step-sized drift
    # (well under the footprint slack) re-gathers into the pack-time
    # layout — the integrator pays THIS instead of a second bucket_prep
    dxm = float(min(grid.dx))
    Xh = X + jnp.asarray([[0.3 * dxm, -0.2 * dxm, 0.15 * dxm]],
                         dtype=X.dtype)
    refresh_hit = bool(jax.jit(lambda: peng.refresh(pb, Xh)[1])())
    t_refresh = timeit(jax.jit(lambda: peng.refresh(pb, Xh)[0]), r)

    t_pspread3 = timeit(jax.jit(lambda: peng.spread_vel(F, X, b=pb)), r)
    t_pinterp3 = timeit(jax.jit(
        lambda: peng.interpolate_vel(u, X, b=pb)), r)

    # bf16-compressed twins (operand HBM traffic halved)
    engb = fast.FastInteraction(grid, tile=args.tile, cap=cap,
                                overflow_cap=max(2048, N // 4),
                                compute_dtype=jnp.bfloat16)
    t_bspread3 = timeit(jax.jit(lambda: engb.spread_vel(F, X, b=b)), r)
    t_binterp3 = timeit(jax.jit(
        lambda: engb.interpolate_vel(u, X, b=b)), r)
    pengb = packed.PackedInteraction(grid, tile=args.tile, chunk=128,
                                     nchunks=Q,
                                     overflow_cap=max(2048, N // 4),
                                     compute_dtype=jnp.bfloat16)
    t_pbspread3 = timeit(jax.jit(lambda: pengb.spread_vel(F, X, b=pb)),
                         r)
    t_pbinterp3 = timeit(jax.jit(
        lambda: pengb.interpolate_vel(u, X, b=pb)), r)

    # fully-blocked packed3: z-tiled chunks + spill-folding overlap-add
    from ibamr_tpu.ops import interaction_packed3 as packed3

    tz = 16 if grid.n[-1] % 16 == 0 else 8
    Q3 = packed3.suggest_chunks3(grid, s.vertices, tile=args.tile,
                                 tile_last=tz, chunk=64)
    p3eng = packed3.PackedInteraction3(grid, tile=args.tile,
                                       tile_last=tz, chunk=64,
                                       nchunks=Q3,
                                       overflow_cap=max(2048, N // 4))
    p3b = jax.jit(p3eng.buckets)(X)
    print(f"packed3: Q={Q3} slots={Q3 * 64} util={N / (Q3 * 64):.3f} "
          f"overflow={int(jnp.sum(p3b.w_overflow > 0))}")
    t_p3bucket = timeit(jax.jit(lambda: p3eng.buckets(X)), r)
    t_p3spread3 = timeit(jax.jit(lambda: p3eng.spread_vel(F, X, b=p3b)), r)
    t_p3interp3 = timeit(jax.jit(
        lambda: p3eng.interpolate_vel(u, X, b=p3b)), r)
    p3engb = packed3.PackedInteraction3(grid, tile=args.tile,
                                        tile_last=tz, chunk=64,
                                        nchunks=Q3,
                                        overflow_cap=max(2048, N // 4),
                                        compute_dtype=jnp.bfloat16)
    t_p3bspread3 = timeit(jax.jit(
        lambda: p3engb.spread_vel(F, X, b=p3b)), r)
    t_p3binterp3 = timeit(jax.jit(
        lambda: p3engb.interpolate_vel(u, X, b=p3b)), r)

    # pallas-packed: same chunk layout, Pallas tile programs
    t_ppspread3 = t_ppinterp3 = None
    t_hyspread3 = t_hyinterp3 = None
    if not args.no_pallas:
        from ibamr_tpu.ops.pallas_interaction import (
            HybridPackedInteraction, PallasPackedInteraction)

        ppeng = PallasPackedInteraction(grid, tile=args.tile, chunk=128,
                                        nchunks=Q,
                                        overflow_cap=max(2048, N // 4))
        ppb = jax.jit(ppeng.buckets)(X)
        t_ppspread3 = timeit(jax.jit(
            lambda: ppeng.spread_vel(F, X, b=ppb)), r)
        t_ppinterp3 = timeit(jax.jit(
            lambda: ppeng.interpolate_vel(u, X, b=ppb)), r)

        # hybrid: pallas spread + XLA bf16 interp on the SAME context
        hyeng = HybridPackedInteraction(grid, tile=args.tile, chunk=128,
                                        nchunks=Q,
                                        overflow_cap=max(2048, N // 4),
                                        compute_dtype=jnp.bfloat16)
        t_hyspread3 = timeit(jax.jit(
            lambda: hyeng.spread_vel(F, X, b=ppb)), r)
        t_hyinterp3 = timeit(jax.jit(
            lambda: hyeng.interpolate_vel(u, X, b=ppb)), r)

    gb = (A.nbytes + Wlast.nbytes + T.nbytes) / 1e9
    print(f"bucket_build      {t_bucket:8.2f} ms")
    print(f"weights (1 ch)    {t_weights:8.2f} ms   "
          f"A {A.nbytes / 1e6:.0f} MB + Wz {Wlast.nbytes / 1e6:.0f} MB")
    print(f"einsum  (1 ch)    {t_einsum:8.2f} ms   "
          f"{gb:.2f} GB operands -> "
          f"{gb / max(t_einsum, 1e-9) * 1e3:.0f} GB/s")
    print(f"overlap (1 ch)    {t_overlap:8.2f} ms")
    print(f"extract (1 ch)    {t_extract:8.2f} ms")
    print(f"spread_vel (3ch)  {t_spread3:8.2f} ms")
    print(f"interp_vel (3ch)  {t_interp3:8.2f} ms")
    est = 3 * (t_weights + t_einsum + t_overlap)
    print(f"sum est 3ch sprd  {est:8.2f} ms")
    print(f"packed bucket     {t_pbucket:8.2f} ms")
    print(f"packed refresh    {t_refresh:8.2f} ms   "
          f"(vs full re-pack {t_pbucket:.2f} ms, "
          f"hit={refresh_hit})")
    print(f"packed spread 3ch {t_pspread3:8.2f} ms")
    print(f"packed interp 3ch {t_pinterp3:8.2f} ms")
    print(f"mxu-bf16 sprd 3ch {t_bspread3:8.2f} ms")
    print(f"mxu-bf16 intp 3ch {t_binterp3:8.2f} ms")
    print(f"pk-bf16 sprd 3ch  {t_pbspread3:8.2f} ms")
    print(f"pk-bf16 intp 3ch  {t_pbinterp3:8.2f} ms")
    print(f"packed3 bucket    {t_p3bucket:8.2f} ms")
    print(f"packed3 sprd 3ch  {t_p3spread3:8.2f} ms")
    print(f"packed3 intp 3ch  {t_p3interp3:8.2f} ms")
    print(f"p3-bf16 sprd 3ch  {t_p3bspread3:8.2f} ms")
    print(f"p3-bf16 intp 3ch  {t_p3binterp3:8.2f} ms")
    if t_ppspread3 is not None:
        print(f"pallas-pk sprd 3c {t_ppspread3:8.2f} ms")
        print(f"pallas-pk intp 3c {t_ppinterp3:8.2f} ms")
    if t_hyspread3 is not None:
        # the hybrid_bf16 registry engine: pallas spread + bf16 interp
        print(f"hybrid_bf16 sprd  {t_hyspread3:8.2f} ms")
        print(f"hybrid_bf16 intp  {t_hyinterp3:8.2f} ms")


if __name__ == "__main__":
    main()
