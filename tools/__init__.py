# makes tools/ importable (tests import the HLO op census from
# tools.hlo_cost_audit); the scripts themselves stay runnable directly.
