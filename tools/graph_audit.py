"""The graph-contract drift gate (see docs/ANALYSIS.md).

Re-lowers every artifact in the contract registry
(:mod:`ibamr_tpu.analysis.contracts`) on the host-CPU backend, runs
the graph censuses, and diffs the budget-comparable metrics against
``GRAPH_BUDGETS.json``:

- exit 0 — every artifact matches its budget exactly (clean);
- exit 1 — at least one metric IMPROVED (e.g. a convert chain
  disappeared): re-run with ``--tighten`` to ratchet the budget and
  commit the diff, so the win is pinned;
- exit 2 — at least one metric regressed (a new scatter, an un-fused
  FFT, a host transfer inside the scan, a dropped donation, a dtype
  widening); the report names artifact, metric, measured and budget.

Each artifact lowers in its own child process (the
``tools/hlo_cost_audit.py`` pattern: the XLA CPU pipeline has a rare
native-crash flake, and a fresh process also guarantees the
production x64-off config regardless of the caller's environment —
the in-process path additionally wraps measurement in
``jax.experimental.disable_x64()``).

Flags: ``--artifacts a,b`` subset, ``--heavy`` includes the
flagship-scale artifacts (minutes of compile), ``--tighten`` ratchets
``GRAPH_BUDGETS.json`` toward the measured values (merge-don't-clobber
twice over: unmeasured artifacts keep their committed budgets, and per
metric the ratchet is DIRECTIONAL — ceilings only tighten down, floors
like ``hidden_fraction``/``donated_args`` only tighten up; loosening a
budget after an intentional structural change requires
``--tighten --clobber``), ``--json`` emits
the machine-readable report (consumed by ``tools/relay_watch.py``'s
on-healthy capture), ``--in-process`` skips the child processes (used
by the test suite, which already isolates per-module).
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def tighten_merge(old: dict, measured: dict) -> dict:
    """Directional ratchet of ONE artifact's budget (PR 16).

    ``--tighten`` may only make a budget TIGHTER: ceiling metrics
    (BUDGET_MAX_METRICS — counts that regress UP) take
    ``min(old, measured)``; floor metrics (BUDGET_MIN_METRICS —
    ``donated_args``, ``hidden_fraction``, which regress DOWN) take
    ``max(old, measured)``. A metric the census newly emits is adopted
    at its measured value; a metric only the committed file knows is
    KEPT (the census/budget disagreement then surfaces as MISSING in
    the audit instead of being silently erased). A genuine regression
    therefore never launders through --tighten — loosening a budget on
    purpose requires ``--clobber``. Pinned by
    tests/test_graph_census.py::test_tighten_merges_directionally."""
    from ibamr_tpu.analysis.contracts import BUDGET_MIN_METRICS

    out = dict(old)
    for k, v in measured.items():
        if k not in old:
            out[k] = v
        elif k in BUDGET_MIN_METRICS:
            out[k] = max(int(old[k]), int(v))
        else:
            out[k] = min(int(old[k]), int(v))
    return out


def _measure_child(q, name):
    try:
        from ibamr_tpu.utils.backend_guard import force_cpu

        # 8 virtual devices so the sharded artifacts (sharded_chunk,
        # fftpar_transpose, lagrangian_exchange) see a real (4,2) mesh;
        # the single-device artifacts are unaffected by the count.
        force_cpu(8)
        from ibamr_tpu.analysis.contracts import measure_artifact

        t0 = time.perf_counter()
        metrics = measure_artifact(name)
        q.put({"name": name, "metrics": metrics,
               "compile_s": round(time.perf_counter() - t0, 1)})
    except Exception as e:  # noqa: BLE001 - report to parent
        q.put({"name": name, "error": f"{type(e).__name__}: {e}"})


def measure(name, timeout_s, in_process=False):
    if in_process:
        from ibamr_tpu.analysis.contracts import measure_artifact

        try:
            t0 = time.perf_counter()
            metrics = measure_artifact(name)
            return {"name": name, "metrics": metrics,
                    "compile_s": round(time.perf_counter() - t0, 1)}
        except Exception as e:  # noqa: BLE001
            return {"name": name, "error": f"{type(e).__name__}: {e}"}
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_measure_child, args=(q, name))
    p.start()
    p.join(timeout_s)
    if p.is_alive():
        p.terminate()
        p.join(10)
        return {"name": name, "error": f"timeout > {timeout_s:.0f}s"}
    try:
        return q.get_nowait()
    except Exception:
        return {"name": name, "error": f"child died rc={p.exitcode}"}


def main(argv=None) -> int:
    from ibamr_tpu.analysis.contracts import (
        ARTIFACTS, BUDGET_PATH, diff_budget, load_budgets, report_drift)

    ap = argparse.ArgumentParser(
        description="audit compiled-graph contracts against "
                    "GRAPH_BUDGETS.json")
    ap.add_argument("--artifacts", type=str, default="",
                    help="comma-separated subset (default: all "
                         "non-heavy)")
    ap.add_argument("--heavy", action="store_true",
                    help="include flagship-scale artifacts")
    ap.add_argument("--tighten", action="store_true",
                    help="ratchet budgets toward the measured values "
                         "(directional: ceilings only move DOWN, "
                         "floors only move UP — a regression never "
                         "launders through)")
    ap.add_argument("--clobber", action="store_true",
                    help="with --tighten: overwrite measured "
                         "artifacts' budgets wholesale (required to "
                         "LOOSEN a budget after an intentional "
                         "structural change)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--in-process", action="store_true",
                    help="skip child processes (test harness use)")
    ap.add_argument("--timeout", type=float, default=900.0)
    ap.add_argument("--budgets", type=str, default=BUDGET_PATH)
    args = ap.parse_args(argv)

    if args.artifacts:
        names = [s.strip() for s in args.artifacts.split(",")]
        unknown = set(names) - set(ARTIFACTS)
        if unknown:
            raise SystemExit(f"unknown artifacts {sorted(unknown)}")
    else:
        names = [n for n, a in ARTIFACTS.items()
                 if args.heavy or not a.heavy]

    try:
        budgets = load_budgets(args.budgets)
    except FileNotFoundError:
        budgets = {}

    results, drifts, errors = {}, [], []
    for i, name in enumerate(names):
        if not args.as_json:
            print(f"[graph-audit] {i + 1}/{len(names)}: {name}",
                  flush=True)
        r = measure(name, args.timeout, in_process=args.in_process)
        if "error" in r:
            errors.append(r)
            if not args.as_json:
                print(f"[graph-audit]   ERROR {r['error']}",
                      flush=True)
            continue
        results[name] = r
        if name in budgets:
            drifts.append(diff_budget(name, r["metrics"],
                                      budgets[name]))
        elif not args.tighten and not args.as_json:
            print(f"[graph-audit]   (no budget yet — run --tighten "
                  f"to pin)", flush=True)

    if args.tighten:
        doc = {"_doc": (
            "Graph-contract budgets (tools/graph_audit.py; see "
            "docs/ANALYSIS.md). Measured on the host-CPU backend "
            "under the production x64-off config; 'donated_args' and "
            "'hidden_fraction' are floors (regress DOWN), every other "
            "metric a ceiling (regresses UP)."),
            "artifacts": dict(budgets)}
        for name, r in results.items():
            if args.clobber or name not in budgets:
                doc["artifacts"][name] = r["metrics"]
            else:
                doc["artifacts"][name] = tighten_merge(budgets[name],
                                                       r["metrics"])
        with open(args.budgets, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        if not args.as_json:
            print(f"[graph-audit] wrote {args.budgets} "
                  f"({len(results)} artifact(s) "
                  f"{'clobbered' if args.clobber else 'tightened'})")

    regressed = [d for d in drifts if d.regressions or d.missing]
    improved = [d for d in drifts if d.improvements
                and not (d.regressions or d.missing)]
    missing_budgets = [n for n in results if n not in budgets]
    rc = 0
    if errors or regressed:
        rc = 2
    elif improved or (missing_budgets and not args.tighten):
        rc = 1

    if args.as_json:
        print(json.dumps({
            "exit": rc,
            "artifacts": {n: r["metrics"] for n, r in results.items()},
            "compile_s": {n: r["compile_s"]
                          for n, r in results.items()},
            "regressed": [d.name for d in regressed],
            "improved": [d.name for d in improved],
            "unbudgeted": missing_budgets,
            "errors": errors,
        }, indent=1, sort_keys=True))
        return rc

    report = report_drift(drifts)
    if report:
        print(report)
    for e in errors:
        print(f"[graph-audit] {e['name']}: ERROR {e['error']}")
    if missing_budgets and not args.tighten:
        print(f"[graph-audit] unbudgeted artifact(s): "
              f"{missing_budgets} — run --tighten to pin")
    verdict = {0: "clean — every artifact matches its budget",
               1: "improved — run --tighten to ratchet the budgets",
               2: "REGRESSED — see the drift report above"}[rc]
    print(f"[graph-audit] {len(results)} measured, "
          f"{len(regressed)} regressed, {len(improved)} improved, "
          f"{len(errors)} error(s): {verdict}")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
