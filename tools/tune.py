"""Autotuner CLI: measured engine search + tuning-DB lifecycle
(docs/TUNING.md).

``tune.py search`` — walk the engine x spectral_dtype x chunk-length
grid for one or more grid sizes on the current backend (or ``--cpu``),
emitting ONE JSON line per size; ``--publish`` merges each winner into
the tuning DB (atomic write, re-publication replaces the matching
entry). ``tools/relay_watch.py`` runs this on every healthy TPU window
so the committed defaults stay device-measured.

``tune.py show`` — render the DB: entries, measured margins,
provenance, and the shadowed-entry lint.

``tune.py publish`` — merge a previously captured ``search --json``
result file into the DB (the offline half of search --publish).

``tune.py check`` — the revalidation gate (the ``graph_audit`` /
``serve.py check`` exit-code convention), run on the forced host-CPU
backend so CI verdicts are hermetic:

- exit 0 — schema + lint clean; every re-timed winner still wins;
- exit 1 — STALE: rankings hold but a winner's measured steps/s
  drifted beyond ``--band`` — re-run ``search --publish``;
- exit 2 — REGRESSED: schema/lint errors, or a re-timed runner-up
  now beats its winner by more than ``--band`` (a ranking flip) — the
  DB is steering the resolver wrong.

Only entries whose ``provenance.platform`` matches the current
backend are re-timed (re-timing a TPU number on the CPU host would
manufacture a fake flip); the committed TPU-measured seed therefore
costs CI schema + lint only, and the on-chip re-validation rides the
relay watcher's healthy windows.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DB_PATH = os.path.join(REPO, "TUNING_DB.json")


def _git_rev() -> str:
    try:
        import subprocess
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=REPO).stdout
        return out.strip() or "norev"
    except Exception:
        return "norev"


def _backend(force_cpu_backend: bool) -> str:
    if force_cpu_backend:
        from ibamr_tpu.utils.backend_guard import force_cpu
        force_cpu()
        return "cpu"
    from ibamr_tpu.utils.backend_guard import init_backend_with_retry
    _jax, platform, err = init_backend_with_retry(retries=1, delay=2.0)
    if err:
        print(f"[tune] backend init degraded: {err}", file=sys.stderr)
    return platform


def _device_kind() -> str:
    try:
        import jax
        return jax.devices()[0].device_kind
    except Exception:
        return ""


def _auto_markers(n: int, n_lat: int, n_lon: int):
    """Flagship-matched marker lattice per size (the microbench
    convention: 316^2 markers at >=256, 180^2 at >=128) unless the
    caller pinned --n-lat/--n-lon."""
    if n_lat and n_lon:
        return n_lat, n_lon
    side = 316 if n >= 256 else (180 if n >= 128 else 0)
    return (side or 8, side or 16)


def _csv(text, cast):
    return tuple(cast(v.strip()) for v in str(text).split(",")
                 if v.strip())


# ---------------------------------------------------------------------------
# search / publish
# ---------------------------------------------------------------------------

def entry_from_search_dict(d: dict, *, platform: str, timestamp: str,
                           device_kind=None, jax_version=None,
                           git_rev=None, source=None):
    """A schema-v1 entry from a ``search --json`` result dict (the
    offline twin of ``runner.db_entry_from_search``)."""
    from ibamr_tpu.tune import db as _db

    w, ru = d.get("winner"), d.get("runner_up")
    if not w:
        return None
    cfg = d.get("config") or {}
    markers = int(cfg.get("markers") or 0)
    measured = {"steps_per_s": w["steps_per_s"],
                "chunk_length": w["chunk_length"],
                "reps": cfg.get("reps"),
                "n_lat": cfg.get("n_lat"), "n_lon": cfg.get("n_lon")}
    if ru:
        measured.update(runner_up=ru["engine"],
                        runner_up_steps_per_s=ru["steps_per_s"],
                        runner_up_chunk_length=ru["chunk_length"],
                        margin=d.get("margin"))
    prov = _db.make_provenance(
        platform, timestamp, device_kind=device_kind,
        jax_version=jax_version, git_rev=git_rev, source=source)
    return _db.make_entry(
        w["engine"], n=cfg.get("n"),
        markers_min=max(1, markers // 2) if markers else None,
        markers_max=markers * 2 if markers else None,
        spectral_dtype=w["spectral_dtype"], platform=platform,
        measured=measured, provenance=prov)


def publish_entries(entries, db_path: str) -> list:
    """Merge entries into the DB at ``db_path`` (created if absent);
    validates BEFORE writing — a publication that would fail the gate
    never lands. Returns validation problems (empty = written)."""
    from ibamr_tpu.tune import db as _db

    doc = _db.load_db(db_path) if os.path.exists(db_path) \
        else _db.new_db()
    for e in entries:
        _db.merge_entry(doc, e)
    problems = _db.validate_db(doc)
    if not problems:
        _db.save_db(doc, db_path)
    return problems


def cmd_search(args) -> int:
    platform = _backend(args.cpu)
    from ibamr_tpu.serve import aot_cache
    aot_cache.enable_persistent_cache()
    from ibamr_tpu.tune import runner

    timestamp = args.timestamp or time.strftime("%Y-%m-%d")
    results, entries = [], []
    for n in _csv(args.n, int):
        n_lat, n_lon = _auto_markers(n, args.n_lat, args.n_lon)
        res = runner.search(
            n_cells=n, n_lat=n_lat, n_lon=n_lon,
            engines=_csv(args.engines, str),
            spectral_dtypes=_csv(args.dtypes, str),
            chunk_lengths=_csv(args.chunk_lengths, int),
            reps=args.reps, dt=args.dt, probe=not args.no_probe)
        d = res.to_dict()
        d["platform"] = platform
        results.append(d)
        print(json.dumps(d, sort_keys=True), flush=True)
        entry = runner.db_entry_from_search(
            res, platform=platform, timestamp=timestamp,
            device_kind=_device_kind(),
            jax_version=__import__("jax").__version__,
            git_rev=_git_rev(), source=f"tune.py search @{n}^3")
        if entry is not None:
            entries.append(entry)
    if args.publish:
        if not entries:
            print("[tune] nothing to publish (no trial succeeded)",
                  file=sys.stderr)
            return 1
        problems = publish_entries(entries, args.db)
        if problems:
            for p in problems:
                print(f"[tune] publish refused: {p}", file=sys.stderr)
            return 2
        print(f"[tune] published {len(entries)} entr"
              f"{'y' if len(entries) == 1 else 'ies'} -> {args.db}",
              file=sys.stderr)
    return 0


def cmd_publish(args) -> int:
    with open(args.from_file) as f:
        results = [json.loads(line) for line in f
                   if line.strip().startswith("{")]
    timestamp = args.timestamp or time.strftime("%Y-%m-%d")
    entries = []
    for d in results:
        entry = entry_from_search_dict(
            d, platform=d.get("platform") or "cpu",
            timestamp=timestamp, git_rev=_git_rev(),
            source=f"tune.py publish {os.path.basename(args.from_file)}")
        if entry is not None:
            entries.append(entry)
    if not entries:
        print("[tune] no winners in the search capture",
              file=sys.stderr)
        return 1
    problems = publish_entries(entries, args.db)
    if problems:
        for p in problems:
            print(f"[tune] publish refused: {p}", file=sys.stderr)
        return 2
    print(f"[tune] published {len(entries)} entr"
          f"{'y' if len(entries) == 1 else 'ies'} -> {args.db}")
    return 0


# ---------------------------------------------------------------------------
# show / check
# ---------------------------------------------------------------------------

def cmd_show(args) -> int:
    from ibamr_tpu.tune import db as _db

    doc = _db.load_db(args.db)
    entries = doc.get("entries") or []
    print(f"tuning DB {args.db}: schema {doc.get('schema')}, "
          f"{len(entries)} entr{'y' if len(entries) == 1 else 'ies'}")
    for i, e in enumerate(entries):
        match = ", ".join(
            f"{f}={e[f]}" for f in
            ("n", "n_cells", "markers_min", "markers_max",
             "spectral_dtype", "platform", "chunk_length")
            if e.get(f) is not None)
        m, prov = e.get("measured") or {}, e.get("provenance") or {}
        margin = (f", margin {m['margin']}x over {m.get('runner_up')}"
                  if m.get("margin") else "")
        print(f"  [{i}] {e.get('engine')}  ({match or 'matches all'})")
        if m:
            print(f"      measured {m.get('steps_per_s')} steps/s"
                  f"{margin}")
        if prov:
            print(f"      provenance: {prov.get('platform')}"
                  f" {prov.get('device_kind') or ''}"
                  f" rev={prov.get('git_rev')}"
                  f" @{prov.get('timestamp')}")
    problems = _db.validate_db(doc)
    for p in problems:
        print(f"  LINT: {p}")
    return 2 if problems else 0


def _retime_entry(entry: dict, band: float, reps: int,
                  retime_fn) -> tuple:
    """(verdict, lines) for one platform-matching entry:
    'ok' / 'stale' / 'flip'. Re-times winner and runner-up at the
    entry's recorded drill configuration."""
    from ibamr_tpu.tune.space import Candidate

    m = entry.get("measured") or {}
    cfg_n = entry.get("n") or [entry.get("n_cells") or 16] * 3
    n_cells = int(cfg_n[0])
    n_lat = int(m.get("n_lat") or 8)
    n_lon = int(m.get("n_lon") or 16)
    sd = entry.get("spectral_dtype") or "f32"
    win = Candidate(engine=entry["engine"], spectral_dtype=sd,
                    chunk_length=int(m.get("chunk_length") or 1))
    ru = Candidate(engine=m["runner_up"], spectral_dtype=sd,
                   chunk_length=int(m.get("runner_up_chunk_length")
                                    or m.get("chunk_length") or 1))
    tw = retime_fn(win, n_cells=n_cells, n_lat=n_lat, n_lon=n_lon,
                   reps=reps)
    tr = retime_fn(ru, n_cells=n_cells, n_lat=n_lat, n_lon=n_lon,
                   reps=reps)
    lines = []
    if tw.error or tr.error:
        lines.append(f"{win.label()} vs {ru.label()}: re-time failed "
                     f"({tw.error or tr.error})")
        return "flip", lines
    lines.append(f"{entry['engine']} {tw.steps_per_s:.3f} steps/s vs "
                 f"runner-up {m['runner_up']} {tr.steps_per_s:.3f} "
                 f"(recorded {m.get('steps_per_s')})")
    if tr.steps_per_s > tw.steps_per_s * (1.0 + band):
        lines.append(
            f"RANKING FLIP: {m['runner_up']} beats {entry['engine']} "
            f"by {tr.steps_per_s / max(tw.steps_per_s, 1e-12):.2f}x "
            f"(> 1 + band {band})")
        return "flip", lines
    rec = float(m.get("steps_per_s") or 0.0)
    if rec > 0 and abs(tw.steps_per_s - rec) > band * rec:
        lines.append(
            f"stale: winner drifted {tw.steps_per_s / rec:.2f}x vs "
            f"recorded (band {band}) — re-run search --publish")
        return "stale", lines
    return "ok", lines


def check_db(doc: dict, *, platform: str, band: float = 0.15,
             reps: int = 2, retime_fn=None) -> tuple:
    """(exit_code, report_lines) — the gate body, separated from the
    CLI so tests can drive it with a synthetic ``retime_fn``."""
    from ibamr_tpu.tune import db as _db

    problems = _db.validate_db(doc)
    lines = [f"schema/lint: {p}" for p in problems]
    if problems:
        return 2, lines
    if retime_fn is None:
        from ibamr_tpu.tune.runner import run_trial as retime_fn
    rc = 0
    retimed = 0
    for entry in doc.get("entries") or []:
        prov = entry.get("provenance") or {}
        if str(prov.get("platform", "")).lower() != platform:
            lines.append(
                f"{entry.get('engine')}: provenance platform "
                f"{prov.get('platform')!r} != {platform!r} — not "
                f"re-timed here (schema/lint only)")
            continue
        if not (entry.get("measured") or {}).get("runner_up"):
            lines.append(f"{entry.get('engine')}: no recorded "
                         f"runner-up — nothing to re-race")
            continue
        verdict, vlines = _retime_entry(entry, band, reps, retime_fn)
        retimed += 1
        lines.extend(vlines)
        rc = max(rc, {"ok": 0, "stale": 1, "flip": 2}[verdict])
    lines.append(f"re-timed {retimed} entr"
                 f"{'y' if retimed == 1 else 'ies'} on {platform}")
    return rc, lines


def cmd_check(args) -> int:
    from ibamr_tpu.tune import db as _db

    try:
        doc = _db.load_db(args.db)
    except FileNotFoundError:
        print(f"[tune] no DB at {args.db} — nothing to check")
        return 0
    except ValueError as e:
        print(f"[tune] {e}")
        return 2
    platform = _backend(force_cpu_backend=True)
    rc, lines = check_db(doc, platform=platform, band=args.band,
                         reps=args.reps)
    if args.as_json:
        print(json.dumps({"exit": rc, "db": args.db,
                          "platform": platform, "report": lines},
                         indent=1, sort_keys=True))
        return rc
    for ln in lines:
        print(f"[tune] {ln}")
    verdict = {0: "clean — the DB's winners hold",
               1: "STALE — re-run search --publish to refresh",
               2: "REGRESSED — a winner flipped (or the DB is "
                  "malformed); the resolver is being steered wrong"}[rc]
    print(f"[tune] {verdict}")
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="measured-search engine autotuner: search/show/"
                    "publish/check the tuning DB (docs/TUNING.md)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("search", help="measure the engine grid; one "
                                      "JSON line per size")
    s.add_argument("--n", type=str, default="16",
                   help="comma-separated grid sizes (cells/axis)")
    s.add_argument("--n-lat", type=int, default=0,
                   help="marker rings (0 = flagship-matched auto)")
    s.add_argument("--n-lon", type=int, default=0)
    s.add_argument("--engines", type=str,
                   default="scatter,packed,packed_bf16,pallas_packed")
    s.add_argument("--dtypes", type=str, default="f32,bf16",
                   help="spectral dtypes to search")
    s.add_argument("--chunk-lengths", type=str, default="1,4")
    s.add_argument("--reps", type=int, default=3)
    s.add_argument("--dt", type=float, default=5e-5)
    s.add_argument("--no-probe", action="store_true",
                   help="skip the Pallas compile probes")
    s.add_argument("--cpu", action="store_true",
                   help="force the host-CPU backend")
    s.add_argument("--publish", action="store_true",
                   help="merge each size's winner into --db")
    s.add_argument("--db", type=str, default=DB_PATH)
    s.add_argument("--timestamp", type=str, default="",
                   help="provenance timestamp (default: today)")
    s.set_defaults(fn=cmd_search)

    p = sub.add_parser("publish", help="merge a captured search JSON "
                                       "into the DB")
    p.add_argument("from_file", type=str)
    p.add_argument("--db", type=str, default=DB_PATH)
    p.add_argument("--timestamp", type=str, default="")
    p.set_defaults(fn=cmd_publish)

    w = sub.add_parser("show", help="render the DB + shadow lint")
    w.add_argument("--db", type=str, default=DB_PATH)
    w.set_defaults(fn=cmd_show)

    c = sub.add_parser("check", help="revalidation gate: schema + "
                                     "lint + winner-vs-runner-up "
                                     "re-race (exit 0/1/2)")
    c.add_argument("--db", type=str, default=DB_PATH)
    c.add_argument("--band", type=float, default=0.15,
                   help="tolerated ratio drift before a flip/staleness "
                        "verdict")
    c.add_argument("--reps", type=int, default=2)
    c.add_argument("--json", action="store_true", dest="as_json")
    c.set_defaults(fn=cmd_check)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
