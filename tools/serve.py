"""Warm-pool scenario server CLI (docs/SERVING.md).

``serve.py bench`` — time request-to-first-step latency cold vs warm
through the router (``cold_warm_drill``) on the current backend (or
``--cpu``), emitting ONE JSON line on stdout. ``tools/relay_watch.py``
runs this in its on-healthy capture sequence so every TPU window times
the serving path.

``serve.py check`` — the cold-vs-warm compile-count contract gate
(the ``graph_audit`` exit-code convention):

- exit 0 — the drill matches SERVE_CONTRACT.json exactly (clean);
- exit 1 — improved (fewer cold compiles) or unbudgeted: re-run with
  ``--tighten`` to pin;
- exit 2 — regressed: a compile on the warm path, a new trace
  signature, a lost cache hit, or a failed request. A cache
  regression fails CI structurally, not anecdotally.

Contract metric directions: ``cold_compiles``, ``warm_compiles`` and
``warm_new_trace_signatures`` are ceilings (regress UP);
``warm_hits`` is a floor (regresses DOWN). The check runs on the
forced host-CPU backend so the verdict is hermetic.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CONTRACT_PATH = os.path.join(REPO, "SERVE_CONTRACT.json")

CEILINGS = ("cold_compiles", "warm_compiles",
            "warm_new_trace_signatures")
FLOORS = ("warm_hits",)
CONTRACT_METRICS = CEILINGS + FLOORS


def run_drill(args, force_cpu_backend: bool) -> dict:
    if force_cpu_backend:
        from ibamr_tpu.utils.backend_guard import force_cpu
        force_cpu()
        platform = "cpu"
    else:
        from ibamr_tpu.utils.backend_guard import init_backend_with_retry
        _jax, platform, err = init_backend_with_retry(retries=1,
                                                      delay=2.0)
        if err:
            print(f"[serve] backend init degraded: {err}",
                  file=sys.stderr)
    from ibamr_tpu.serve import aot_cache
    aot_cache.enable_persistent_cache()
    from ibamr_tpu.serve.router import cold_warm_drill

    out = cold_warm_drill(
        n_cells=args.n, n_lat=args.n_lat, n_lon=args.n_lon,
        lanes=args.lanes, steps=args.steps, dt=args.dt,
        engine=args.engine or None)
    out["platform"] = platform
    return out


def load_contract(path: str = CONTRACT_PATH):
    with open(path) as f:
        return json.load(f)["contract"]


def diff_contract(measured: dict, contract: dict):
    """(regressions, improvements) — each a list of human-readable
    drift lines."""
    regressions, improvements = [], []
    for name in CONTRACT_METRICS:
        if name not in contract:
            continue
        got, want = measured.get(name), contract[name]
        if got is None:
            regressions.append(f"{name}: missing from measurement")
            continue
        if name in FLOORS:
            worse, better = got < want, got > want
        else:
            worse, better = got > want, got < want
        if worse:
            regressions.append(f"{name}: measured {got} vs budget "
                               f"{want} (REGRESSED)")
        elif better:
            improvements.append(f"{name}: measured {got} vs budget "
                                f"{want} (improved)")
    for flag in ("cold_ok", "warm_ok"):
        if not measured.get(flag, False):
            regressions.append(f"{flag}: request failed")
    return regressions, improvements


def cmd_bench(args) -> int:
    out = run_drill(args, force_cpu_backend=args.cpu)
    print(json.dumps(out, sort_keys=True))
    return 0


def cmd_check(args) -> int:
    measured = run_drill(args, force_cpu_backend=True)
    if args.tighten:
        doc = {"_doc": (
            "Cold-vs-warm serving compile-count contract "
            "(tools/serve.py check; see docs/SERVING.md). Measured on "
            "the forced host-CPU backend. 'warm_hits' is a floor "
            "(regresses DOWN), every other metric a ceiling (regresses "
            "UP); warm_compiles == 0 is the kill-the-cold-start "
            "guarantee."),
            "drill": {k: measured[k] for k in
                      ("n", "lanes", "steps", "engine")},
            "contract": {k: measured[k] for k in CONTRACT_METRICS}}
        with open(args.contract, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"[serve] wrote {args.contract}")
        return 0
    try:
        contract = load_contract(args.contract)
    except FileNotFoundError:
        contract = None
    regressions, improvements = ([], []) if contract is None \
        else diff_contract(measured, contract)
    if contract is None:
        # an unbudgeted drill still gates request health
        regressions = [f"{flag}: request failed"
                       for flag in ("cold_ok", "warm_ok")
                       if not measured.get(flag, False)]
    rc = 2 if regressions else (1 if improvements or contract is None
                                else 0)
    if args.as_json:
        print(json.dumps({
            "exit": rc, "measured": measured,
            "regressed": regressions, "improved": improvements,
            "unbudgeted": contract is None}, indent=1, sort_keys=True))
        return rc
    for line in regressions:
        print(f"[serve] {line}")
    for line in improvements:
        print(f"[serve] {line}")
    if contract is None:
        print(f"[serve] no contract at {args.contract} — run "
              f"--tighten to pin")
    verdict = {0: "clean — drill matches the serve contract",
               1: "improved/unbudgeted — run --tighten to pin",
               2: "REGRESSED — the warm path is no longer free"}[rc]
    print(f"[serve] cold {measured['cold_first_step_s']}s / warm "
          f"{measured['warm_first_step_s']}s "
          f"(ratio {measured['warm_over_cold']}), "
          f"{measured['cold_compiles']} cold / "
          f"{measured['warm_compiles']} warm compile(s): {verdict}")
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="warm-pool scenario server: cold/warm latency "
                    "bench + compile-count contract gate")
    sub = ap.add_subparsers(dest="cmd", required=True)

    def drill_args(p):
        p.add_argument("--n", type=int, default=16)
        p.add_argument("--n-lat", type=int, default=8)
        p.add_argument("--n-lon", type=int, default=16)
        p.add_argument("--lanes", type=int, default=2)
        p.add_argument("--steps", type=int, default=3)
        p.add_argument("--dt", type=float, default=5e-5)
        p.add_argument("--engine", type=str, default="",
                       help="engine name ('' = auto via the resolver)")

    b = sub.add_parser("bench", help="cold/warm request-to-first-step "
                                     "latency, one JSON line")
    drill_args(b)
    b.add_argument("--cpu", action="store_true",
                   help="force the host-CPU backend")
    b.set_defaults(fn=cmd_bench)

    c = sub.add_parser("check", help="gate the cold-vs-warm "
                                     "compile-count contract")
    drill_args(c)
    c.add_argument("--tighten", action="store_true",
                   help="rewrite the contract to the measured values")
    c.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout")
    c.add_argument("--contract", type=str, default=CONTRACT_PATH)
    c.set_defaults(fn=cmd_check)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
