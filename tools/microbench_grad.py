"""Micro-benchmark: the price of the adjoint, piece by piece (PR 19).

Measures primal-vs-VJP wall time AND the batched-FFT / byte / scatter
census for each differentiable piece — the fused spectral substep, the
packed spread/interp transfers, and the whole coupled IB step — so the
"adjoint at primal cost" claim is a measured ratio, not a budget
assertion alone. The graph numbers come from the same jaxpr-level
censuses the graph budgets pin (``fft_census``, ``convert_census``,
``scatter_gather_census``): the substep VJP must show exactly 2x the
primal's FFT calls, the spread VJP zero scatter primitives beyond the
primal forward it replays (the reverse sweep is pure gathers —
``grad_spread`` pins its isolated backward pass at zero), and every
piece zero f64 widenings.

Usage:  python tools/microbench_grad.py [--n 64] [--reps 5] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# importable regardless of caller cwd (the relay watcher invokes this
# as a script; python puts tools/ on sys.path, not the repo root)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def timeit(fn, reps):
    import jax

    jax.block_until_ready(fn())  # compile + drain the warm-up step
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps * 1e3


def census(fn, *args):
    """fft/convert/scatter slice of the jaxpr census for one callable."""
    import jax

    from ibamr_tpu.analysis.graph_census import (convert_census,
                                                 fft_census,
                                                 scatter_gather_census)

    jaxpr = jax.make_jaxpr(fn)(*args)
    out = {}
    f = fft_census(jaxpr)
    out["fft_ops"] = f["fft_ops"]
    out["fft_bytes"] = f["fft_bytes"]
    out["f64_widenings"] = convert_census(jaxpr)["f64_widenings"]
    out["scatter_prims"] = scatter_gather_census(jaxpr)["scatter_prims"]
    return out


def run(n=64, reps=5, dt=5e-5, quiet=False):
    """Measure every piece at one size; returns the flat metrics dict.

    Callable in-process (bench.py's --grad leg runs it in a guarded
    CPU child) as well as from the CLI below."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    d = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", d)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.models.shell3d import build_shell_example
    from ibamr_tpu.solvers import spectral_plan

    r = reps
    rho, mu = 1.0, 0.05
    alpha, beta = rho / dt, -0.5 * mu
    if not quiet:
        print(f"n={n} dt={dt} backend={jax.default_backend()}")
    out = {"n": n, "backend": jax.default_backend()}

    rng = np.random.default_rng(0)
    grid = StaggeredGrid(n=(n, n, n), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    rhs = tuple(jnp.asarray(rng.standard_normal(grid.n), jnp.float32)
                for _ in range(3))
    plan = spectral_plan.get_plan(grid.n, grid.dx, jnp.float32)

    # -- fused substep: primal vs full vjp round trip -------------------
    def substep(rr):
        return plan.substep(rr, alpha, beta, (alpha, beta))

    ct = jax.tree_util.tree_map(
        lambda s: jnp.ones(s.shape, s.dtype), jax.eval_shape(substep, rhs))

    def substep_vjp(rr, c):
        val, pull = jax.vjp(substep, rr)
        return val, pull(c)

    out["substep_primal_ms"] = timeit(jax.jit(lambda: substep(rhs)), r)
    out["substep_vjp_ms"] = timeit(
        jax.jit(lambda: substep_vjp(rhs, ct)), r)
    for k, v in census(substep, rhs).items():
        out[f"substep_primal_{k}"] = v
    for k, v in census(substep_vjp, rhs, ct).items():
        out[f"substep_vjp_{k}"] = v

    # -- packed transfers: primal vs vjp through the SAME buckets -------
    nl = max(8, (5 * n) // 4)
    integ, state = build_shell_example(
        n_cells=n, n_lat=nl, n_lon=nl, radius=0.25, aspect=1.2,
        stiffness=1.0, rest_length_factor=0.75, mu=mu,
        use_fast_interaction="packed")
    eng = integ.ib.fast
    X, mask = state.X, state.mask
    b = eng.buckets(X, mask)
    F = jnp.asarray(rng.standard_normal(X.shape), jnp.float32)
    u = state.ins.u

    def spread(Fa, Xa):
        return eng.spread_vel(Fa, Xa, b=b)

    gct = jax.tree_util.tree_map(jnp.ones_like, jax.eval_shape(
        spread, F, X))

    def spread_vjp(Fa, Xa):
        val, pull = jax.vjp(spread, Fa, Xa)
        return val, pull(gct)

    def interp(ua, Xa):
        return eng.interpolate_vel(ua, Xa, b=b)

    uct = jnp.ones_like(jax.eval_shape(interp, u, X))

    def interp_vjp(ua, Xa):
        val, pull = jax.vjp(interp, ua, Xa)
        return val, pull(uct)

    out["spread_primal_ms"] = timeit(jax.jit(lambda: spread(F, X)), r)
    out["spread_vjp_ms"] = timeit(jax.jit(lambda: spread_vjp(F, X)), r)
    out["interp_primal_ms"] = timeit(jax.jit(lambda: interp(u, X)), r)
    out["interp_vjp_ms"] = timeit(jax.jit(lambda: interp_vjp(u, X)), r)
    for k, v in census(spread, F, X).items():
        out[f"spread_primal_{k}"] = v
    for k, v in census(spread_vjp, F, X).items():
        out[f"spread_vjp_{k}"] = v
    for k, v in census(interp_vjp, u, X).items():
        out[f"interp_vjp_{k}"] = v

    # -- whole coupled IB step: primal vs reverse pass ------------------
    def step(st):
        return integ.step(st, dt)

    def step_loss(st):
        leaves = jax.tree_util.tree_leaves(step(st))
        return sum(jnp.sum(l) for l in leaves
                   if jnp.issubdtype(l.dtype, jnp.inexact))

    step_grad = jax.grad(step_loss, allow_int=True)
    out["step_primal_ms"] = timeit(jax.jit(lambda: step(state)), r)
    out["step_vjp_ms"] = timeit(jax.jit(lambda: step_grad(state)), r)
    for k, v in census(step, state).items():
        out[f"step_primal_{k}"] = v
    for k, v in census(step_grad, state).items():
        out[f"step_vjp_{k}"] = v

    for piece in ("substep", "spread", "interp", "step"):
        p, v = out.get(f"{piece}_primal_ms"), out.get(f"{piece}_vjp_ms")
        out[f"{piece}_grad_ratio"] = round(v / max(p, 1e-9), 3)

    if not quiet:
        print(f"{'piece':10s} {'primal ms':>10s} {'vjp ms':>10s} "
              f"{'ratio':>7s} {'ffts p/v':>9s} {'scat v':>7s}")
        for piece in ("substep", "spread", "interp", "step"):
            pf = out.get(f"{piece}_primal_fft_ops", 0)
            vf = out.get(f"{piece}_vjp_fft_ops", 0)
            print(f"{piece:10s} {out[f'{piece}_primal_ms']:10.2f} "
                  f"{out[f'{piece}_vjp_ms']:10.2f} "
                  f"{out[f'{piece}_grad_ratio']:7.2f} "
                  f"{pf:4d}/{vf:<4d} "
                  f"{out.get(f'{piece}_vjp_scatter_prims', 0):7d}")
        wid = sum(v for k, v in out.items()
                  if k.endswith("f64_widenings"))
        print(f"f64 widenings across all graphs: {wid}")
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=64,
                    help="fluid cells per side (3D substep; the coupled "
                         "step scales its shell with it)")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--dt", type=float, default=5e-5)
    ap.add_argument("--json", action="store_true",
                    help="emit a machine-readable JSON line after the "
                         "table (the relay watcher's capture format)")
    args = ap.parse_args()
    out = run(n=args.n, reps=args.reps, dt=args.dt)
    if args.json:
        print(json.dumps({k: (round(v, 3) if isinstance(v, float)
                              else v) for k, v in out.items()}),
              flush=True)


if __name__ == "__main__":
    main()
