"""Offline checkpoint auditor for BOTH on-disk formats (PR 6
satellite): the single-host ``restore.<step>.npz`` + sidecar layout
and the sharded ``sharded.<step>/shard-*.npz`` + manifest layout.

The run-time verifiers (``verify_checkpoint`` /
``verify_sharded_checkpoint``) answer "can I restore THIS step right
now"; this tool answers the operator's question — "what is the state
of this whole run directory" — without loading a model or touching a
device:

- walks a run directory (recursively: a supervised run nests
  ``incidents/`` and sub-run dirs), finds every checkpoint step of
  either format;
- RE-VERIFIES every digest from the bytes on disk: whole-file CRC32 +
  size per array/shard file, and — deeper than the run-time check —
  every per-leaf CRC32 against the sidecar/manifest, so in-file
  corruption that whole-file digests would catch anyway is attributed
  to the leaf;
- reports per step: ``verified``, ``torn`` (no/torn commit marker —
  what a killed writer leaves), ``corrupt`` (marker present, digest
  mismatch / missing shard), ``partial`` (a LANE-STACKED fleet
  checkpoint whose damage is confined to some lanes' slices — the
  per-lane CRCs in the PR-7 sidecar prove the other lanes' slices are
  intact, so ``restore_lane`` can still serve them), and whether the
  step is ``prunable`` (an older-than-newest-verified step the pruner
  may reclaim);
- ``--repair`` QUARANTINES corrupt/torn steps (renames into
  ``<dir>/quarantine/``, never deletes) so a resuming run stops
  re-walking them; the newest verified step is never touched, and a
  directory whose every step is damaged refuses to quarantine the
  last restorable candidate — fsck must never shorten a recovery
  chain the run-time fallback could still limp along;
- exits ``0`` on a clean tree, ``1`` on corruption (so CI and
  ``relay_watch`` can gate on it), ``2`` on usage errors.

Usage::

    python -m tools.ckpt_fsck RUN_DIR [--repair] [--json] [-q]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from ibamr_tpu.utils import checkpoint as ckpt               # noqa: E402
from ibamr_tpu.utils import checkpoint_sharded as cksh       # noqa: E402

QUARANTINE_DIR = "quarantine"


# ---------------------------------------------------------------------------
# per-step audits
# ---------------------------------------------------------------------------

def _leaf_crcs_of_npz(path: str) -> dict:
    with np.load(path) as z:
        return {k: ckpt._leaf_crc(z[k]) for k in z.files}


def _lane_audit(fname: str, integ: dict):
    """Per-lane re-verification of a DAMAGED lane-stacked step.

    The PR-7 fleet sidecar records one CRC32 per lane slice of every
    lane-stacked leaf (``integrity.lanes.leaves``). When the whole-file
    or whole-leaf digests fail, those per-lane digests tell the
    operator WHICH lanes' slices are still intact — the difference
    between a dead checkpoint and one ``restore_lane`` can still serve
    for B-1 lanes. Returns ``{count, lanes_ok, lanes_bad}``, or
    ``None`` when the damage is not lane-attributable (no lane record,
    unparseable file, missing/reshaped leaf)."""
    lanes = integ.get("lanes") or {}
    count = int(lanes.get("count", 0))
    lane_leaves = lanes.get("leaves") or {}
    if count < 1 or not lane_leaves:
        return None
    bad: set = set()
    try:
        with np.load(fname) as z:
            for key, crcs in lane_leaves.items():
                if key not in z.files:
                    return None          # structural, not lane-local
                arr = z[key]
                if (arr.ndim < 1 or arr.shape[0] != count
                        or len(crcs) != count):
                    return None
                for i in range(count):
                    if ckpt._leaf_crc(arr[i]) != int(crcs[i]):
                        bad.add(i)
    except Exception:
        return None
    return {"count": count,
            "lanes_ok": [i for i in range(count) if i not in bad],
            "lanes_bad": sorted(bad)}


def audit_single_step(directory: str, step: int) -> dict:
    """One ``restore.<step>`` checkpoint, re-verified from bytes."""
    rec = {"format": "single", "step": step, "status": "verified",
           "problems": []}
    fname = os.path.join(directory, f"restore.{step:08d}.npz")
    meta = ckpt._read_sidecar(directory, step)
    if meta is None:
        rec["status"] = "torn"
        rec["problems"].append("sidecar missing or torn (uncommitted)")
        return rec
    integ = meta.get("integrity")
    if integ is None:
        rec["status"] = "legacy"
        rec["problems"].append("pre-integrity sidecar (trusted as-is)")
        return rec
    try:
        if os.path.getsize(fname) != integ.get("npz_size"):
            rec["problems"].append("array file size mismatch")
        elif ckpt._file_crc(fname) != integ.get("npz_crc32"):
            rec["problems"].append("array file CRC32 mismatch")
    except OSError as e:
        rec["problems"].append(f"array file unreadable: {e}")
    if not rec["problems"]:
        # whole-file digest held: attribute any in-file damage per leaf
        try:
            found = _leaf_crcs_of_npz(fname)
        except Exception as e:
            rec["problems"].append(f"array file unparseable: {e}")
        else:
            recorded = {k: int(v)
                        for k, v in (integ.get("leaves") or {}).items()}
            for k, v in recorded.items():
                if k not in found:
                    rec["problems"].append(f"leaf {k!r} missing")
                elif found[k] != v:
                    rec["problems"].append(f"leaf {k!r} CRC32 mismatch")
    if rec["problems"]:
        rec["status"] = "corrupt"
        lanes = _lane_audit(fname, integ)
        if lanes is not None and lanes["lanes_bad"] \
                and lanes["lanes_ok"]:
            # damage confined to some lanes' slices of a fleet
            # checkpoint: the step is PARTIALLY restorable, and saying
            # only "corrupt" would hide the B-1 recoverable lanes
            rec["status"] = "partial"
            rec["lanes"] = lanes
            rec["problems"].append(
                f"lane slices {lanes['lanes_bad']} corrupt; lanes "
                f"{lanes['lanes_ok']} verify per-lane "
                f"(restore_lane-servable)")
    return rec


def audit_sharded_step(directory: str, step: int) -> dict:
    """One ``sharded.<step>`` checkpoint, re-verified from bytes down
    to every manifest-recorded chunk CRC."""
    rec = {"format": "sharded", "step": step, "status": "verified",
           "problems": []}
    sdir = cksh._step_dir(directory, step)
    manifest = cksh.read_manifest(directory, step)
    if manifest is None or manifest.get("step") != step:
        rec["status"] = "torn"
        rec["problems"].append("manifest missing or torn (uncommitted)")
        return rec
    shard_leaf_crcs: dict = {}
    for name, srec in (manifest.get("shards") or {}).items():
        path = os.path.join(sdir, name)
        try:
            if os.path.getsize(path) != srec.get("size"):
                rec["problems"].append(f"{name}: size mismatch "
                                       f"(stale or truncated shard)")
                continue
            if ckpt._file_crc(path) != srec.get("crc32"):
                rec["problems"].append(f"{name}: file CRC32 mismatch")
                continue
            shard_leaf_crcs[name] = _leaf_crcs_of_npz(path)
        except OSError:
            rec["problems"].append(f"{name}: missing or unreadable")
        except Exception as e:
            rec["problems"].append(f"{name}: unparseable: {e}")
    if not rec["problems"]:
        for key, meta in (manifest.get("leaves") or {}).items():
            for ch in meta.get("chunks", []):
                name = cksh._shard_name(int(ch["shard"]))
                crcs = shard_leaf_crcs.get(name, {})
                if key not in crcs:
                    rec["problems"].append(
                        f"{name}: leaf {key!r} missing")
                elif crcs[key] != int(ch["crc32"]):
                    rec["problems"].append(
                        f"{name}: leaf {key!r} chunk CRC32 mismatch")
    if rec["problems"]:
        rec["status"] = "corrupt"
    return rec


# ---------------------------------------------------------------------------
# directory walk
# ---------------------------------------------------------------------------

def _checkpoint_dirs(root: str):
    """Directories under ``root`` holding checkpoints of either format
    (including ``root`` itself); quarantine subtrees are skipped."""
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d != QUARANTINE_DIR]
        has_single = any(f.startswith("restore.") and f.endswith(".npz")
                         for f in filenames)
        has_sharded = bool(cksh._all_sharded_steps(dirpath))
        if has_single or has_sharded:
            yield dirpath


def audit_dir(directory: str) -> dict:
    """Audit one checkpoint directory: every step of both formats."""
    steps = []
    for s in ckpt._all_steps(directory):
        steps.append(audit_single_step(directory, s))
    for s in cksh._all_sharded_steps(directory):
        steps.append(audit_sharded_step(directory, s))
    steps.sort(key=lambda r: (r["step"], r["format"]))
    newest_verified = max(
        (r["step"] for r in steps if r["status"] in ("verified",
                                                     "legacy")),
        default=None)
    for r in steps:
        r["prunable"] = (newest_verified is not None
                         and r["step"] < newest_verified)
    return {"directory": directory, "steps": steps,
            "newest_verified": newest_verified,
            "counts": _counts(steps)}


def _counts(steps) -> dict:
    c = {"verified": 0, "legacy": 0, "torn": 0, "corrupt": 0,
         "partial": 0, "prunable": 0}
    for r in steps:
        c[r["status"]] += 1
        if r.get("prunable"):
            c["prunable"] += 1
    return c


def audit(root: str) -> dict:
    """Audit a whole run tree. ``clean`` is False iff any torn,
    corrupt, or partial step exists anywhere under ``root`` (a partial
    step is damage too — just lane-attributed damage)."""
    dirs = [audit_dir(d) for d in _checkpoint_dirs(root)]
    total = _counts([r for d in dirs for r in d["steps"]])
    return {"root": os.path.abspath(root), "dirs": dirs,
            "run_id": _ledger_run_id(root),
            "counts": total,
            "clean": (total["torn"] == 0 and total["corrupt"] == 0
                      and total["partial"] == 0)}


def _ledger_run_id(root: str):
    """The ``run_id`` of the run that wrote this tree, read from its
    ``ledger.jsonl`` (PR 9) — so an fsck report, the ledger, and the
    incident capsules of one run cross-reference by the same id.
    ``None`` when the run predates the ledger."""
    path = os.path.join(root, "ledger.jsonl")
    try:
        from ibamr_tpu.obs import read_ledger
        for rec in read_ledger(path):
            rid = rec.get("run_id")
            if rid:
                return rid
    except Exception:
        pass
    return None


# ---------------------------------------------------------------------------
# repair (quarantine, never delete)
# ---------------------------------------------------------------------------

def _step_paths(directory: str, rec: dict):
    if rec["format"] == "sharded":
        return [cksh._step_dir(directory, rec["step"])]
    base = os.path.join(directory, f"restore.{rec['step']:08d}")
    return [p for p in (base + ".npz", base + ".json")
            if os.path.exists(p)]


def repair_dir(dir_report: dict) -> list:
    """Quarantine every torn/corrupt step of one audited directory.
    Moves (never deletes) into ``<dir>/quarantine/``; refuses to touch
    the newest verified step, and — when NO step verified — leaves the
    newest damaged candidate in place (the run-time fallback may still
    salvage leaves from it; an empty directory salvages nothing).
    ``partial`` steps are NEVER quarantined: their intact lane slices
    are exactly what ``restore_lane`` needs after a lane fault.
    Returns the quarantined step records."""
    directory = dir_report["directory"]
    bad = [r for r in dir_report["steps"]
           if r["status"] in ("torn", "corrupt")]
    if dir_report["newest_verified"] is None and bad:
        spared = max(bad, key=lambda r: r["step"])
        bad = [r for r in bad if r is not spared]
    moved = []
    qdir = os.path.join(directory, QUARANTINE_DIR)
    for r in bad:
        os.makedirs(qdir, exist_ok=True)
        for p in _step_paths(directory, r):
            dst = os.path.join(qdir, os.path.basename(p))
            if os.path.exists(dst):      # re-run after a prior repair
                i = 1
                while os.path.exists(f"{dst}.{i}"):
                    i += 1
                dst = f"{dst}.{i}"
            os.replace(p, dst)
        r["quarantined"] = True
        moved.append(r)
    return moved


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="offline checkpoint auditor: re-verify every CRC "
                    "of both checkpoint formats under a run directory")
    ap.add_argument("root", help="run directory to audit")
    ap.add_argument("--repair", action="store_true",
                    help="quarantine torn/corrupt steps into "
                         "<dir>/quarantine/ (never deletes; never "
                         "touches the newest verified step)")
    ap.add_argument("--json", action="store_true",
                    help="print the full report as JSON")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="print nothing but the exit code")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.root):
        ap.error(f"{args.root!r} is not a directory")

    report = audit(args.root)
    if args.repair:
        report["repaired"] = [
            {"directory": d["directory"],
             "quarantined": [{"format": r["format"], "step": r["step"]}
                             for r in repair_dir(d)]}
            for d in report["dirs"]]

    if args.json:
        print(json.dumps(report, indent=1))
    elif not args.quiet:
        for d in report["dirs"]:
            c = d["counts"]
            print(f"{d['directory']}: {c['verified']} verified"
                  + (f", {c['legacy']} legacy" if c["legacy"] else "")
                  + (f", {c['torn']} torn" if c["torn"] else "")
                  + (f", {c['corrupt']} corrupt" if c["corrupt"] else "")
                  + (f", {c['partial']} partial" if c["partial"] else "")
                  + (f", {c['prunable']} prunable"
                     if c["prunable"] else "")
                  + (f" (newest verified: {d['newest_verified']})"
                     if d["newest_verified"] is not None else ""))
            for r in d["steps"]:
                if r["status"] in ("torn", "corrupt", "partial"):
                    tag = " [quarantined]" if r.get("quarantined") else ""
                    print(f"  {r['format']} step {r['step']}: "
                          f"{r['status']}{tag} — "
                          + "; ".join(r["problems"]))
        if not report["dirs"]:
            print(f"{args.root}: no checkpoints found")
    return 0 if report["clean"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
