"""Run-ledger reader: summarize, follow, and compare telemetry (PR 9).

The ledger (``ibamr_tpu.obs``) is an append-only ``ledger.jsonl`` —
spans, per-chunk counter snapshots, incidents — every record stamped
with the run fingerprint digest (``run_id``) and a monotonic ``seq``.
This tool is the operator's side of that contract:

- ``summary``: one screen per run — the span tree aggregated by path
  with percent-of-parent, the counter/gauge table from the LAST
  per-chunk snapshot (counters are cumulative, so the last snapshot IS
  the run total — no summing, which is what makes supervised retries
  double-count-proof), and the incident timeline cross-referenced by
  seq.
- ``tail``: live follow of a growing ledger alongside the watchdog
  heartbeat (staleness age), for watching a run without attaching to
  its process; ``--grep``/``--trace`` narrow the stream to one
  substring or one request's trace id.
- ``trace``: one served request's full admission→completion timeline
  (admission record, spans with parentage, cache events, quarantine,
  completion verdict) reconstructed from the ledger alone by its
  ``trace_id`` (PR 14 — unique prefixes accepted).
- ``compare``: two ledgers -> per-phase wall deltas; two bench JSONs
  (``BENCH_r*.json`` or raw ``bench.py`` output) -> per-stage,
  per-phase, and serve-leg latency-percentile deltas between
  revisions; two fleet directories (auto-detected by their
  ``ledger-<proc>.jsonl`` shards) -> per-proc deltas.
- ``summary --fleet``: one pod run's merged rollup (PR 15) — the
  directory's per-process ledger shards interleaved in ``(seq, proc)``
  order: per-proc span trees, each proc's comm fraction from its
  newest ``device_time`` attribution, per-host last-record staleness,
  and the proc-labeled counter registry (cumulative per process,
  never summed across procs).

Examples::

    python tools/obs.py summary /tmp/fleet/ledger.jsonl
    python tools/obs.py summary /tmp/pod --fleet
    python tools/obs.py tail /tmp/fleet --max-seconds 30 --trace 3fa2
    python tools/obs.py trace /tmp/serve/ledger.jsonl 3fa2
    python tools/obs.py compare /tmp/a/ledger.jsonl /tmp/b/ledger.jsonl
    python tools/obs.py compare BENCH_r04.json BENCH_r05.json
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from ibamr_tpu.obs import (  # noqa: E402
    quantiles_from_counts,
    read_ledger,
    record_trace_ids,
)

LEDGER_NAME = "ledger.jsonl"


def resolve_ledger(path: str) -> str:
    """A directory is accepted and means its ``ledger.jsonl``."""
    if os.path.isdir(path):
        return os.path.join(path, LEDGER_NAME)
    return path


def _fmt_s(v) -> str:
    if v is None:
        return "-"
    v = float(v)
    if v >= 100:
        return f"{v:.1f}s"
    if v >= 0.1:
        return f"{v:.3f}s"
    return f"{v * 1e3:.2f}ms"


def _fmt_num(v) -> str:
    if isinstance(v, float) and v == int(v):
        v = int(v)
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


# ---------------------------------------------------------------------------
# summary
# ---------------------------------------------------------------------------

def span_tree(records: list) -> dict:
    """Aggregate span records by slash ``path``:
    ``{path: {"count": n, "total_s": s, "errors": e, "depth": d}}``."""
    tree: dict = {}
    for rec in records:
        if rec.get("kind") != "span":
            continue
        path = rec.get("path") or rec.get("name", "?")
        node = tree.setdefault(path, {"count": 0, "total_s": 0.0,
                                      "errors": 0,
                                      "depth": path.count("/")})
        node["count"] += 1
        node["total_s"] += float(rec.get("dur_s") or 0.0)
        if rec.get("error"):
            node["errors"] += 1
    return tree


def percent_of_parent(tree: dict, path: str,
                      wall_s=None) -> float | None:
    """Share of the parent phase's wall time this phase accounts for.
    Roots are charged against ``wall_s`` (the run's first->last record
    span) when known, else against the sum of all root phases."""
    total = tree[path]["total_s"]
    denom = 0.0
    p = path
    while "/" in p:
        # nearest ancestor that actually has spans (a slash inside a
        # single span NAME does not invent a phantom parent)
        p = p.rsplit("/", 1)[0]
        denom = tree.get(p, {}).get("total_s") or 0.0
        if denom:
            break
    if not denom:
        roots = [q for q in tree
                 if not any(q != r and q.startswith(r + "/")
                            for r in tree)]
        denom = wall_s if wall_s else sum(
            tree[q]["total_s"] for q in roots)
        if path not in roots and not wall_s:
            return None
    if not denom:
        return None
    return 100.0 * total / denom


def render_span_tree(records: list, wall_s=None) -> list:
    tree = span_tree(records)
    lines = []
    if not tree:
        return ["  (no spans)"]

    def eff_depth(path):
        # indent by ancestors that actually exist as spans, so a slash
        # inside one span NAME does not indent under a phantom parent
        return sum(1 for r in tree
                   if r != path and path.startswith(r + "/"))

    width = max(len(p.split("/")[-1]) + 2 * eff_depth(p)
                for p in tree) + 2
    for path in sorted(tree):
        node = tree[path]
        pct = percent_of_parent(tree, path, wall_s)
        label = "  " * eff_depth(path) + path.split("/")[-1]
        err = f"  errors={node['errors']}" if node["errors"] else ""
        lines.append(
            f"  {label:<{width}} {_fmt_s(node['total_s']):>10}"
            f"  x{node['count']:<5}"
            f" {'' if pct is None else f'{pct:5.1f}%':>7}{err}")
    return lines


def last_counters(records: list):
    """The newest ``counters`` record (cumulative => run totals)."""
    snap = None
    for rec in records:
        if rec.get("kind") == "counters":
            snap = rec
    return snap


def render_counters(snap) -> list:
    if snap is None:
        return ["  (no counter snapshots)"]
    lines = []
    for kind in ("counters", "gauges"):
        table = snap.get(kind) or {}
        for key in sorted(table):
            lines.append(f"  {key:<58} {_fmt_num(table[key]):>14}")
    return lines or ["  (empty snapshot)"]


def render_latency(snap) -> list:
    """Latency-percentile table from the histogram snapshots of the
    last ``counters`` record (cumulative => run distribution). Empty
    when the run recorded no histograms."""
    hists = (snap or {}).get("histograms") or {}
    rows = []
    for key in sorted(hists):
        s = hists[key]
        n = s.get("count") or 0
        if not n:
            continue
        p50, p95, p99 = quantiles_from_counts(s["counts"],
                                              [0.5, 0.95, 0.99])
        rows.append((key, n, float(s.get("sum") or 0.0) / n,
                     p50, p95, p99))
    if not rows:
        return []
    width = max(len(k) for k, *_ in rows) + 2
    lines = [f"  {'histogram':<{width}} {'count':>7} {'mean':>10}"
             f" {'p50':>10} {'p95':>10} {'p99':>10}"]
    for key, n, mean, p50, p95, p99 in rows:
        # *_seconds families render as durations; dimensionless
        # histograms (padding fraction) as plain numbers
        fmt = (_fmt_s if key.split("{", 1)[0].endswith("_seconds")
               else lambda v: _fmt_num(round(float(v), 6)))
        lines.append(f"  {key:<{width}} {n:>7} {fmt(mean):>10}"
                     f" {fmt(p50):>10} {fmt(p95):>10}"
                     f" {fmt(p99):>10}")
    return lines


def render_serving(snap, records: list) -> list:
    """Warm-pool efficacy block (PR 12): the AOT executable cache's
    hit ratio plus the router's request/quarantine/padding totals,
    from the counter snapshot; per-request ``request`` records add the
    cold-vs-warm first-step latency split. Empty when the run never
    touched the serving layer."""
    table = (snap or {}).get("counters") or {}
    hits = table.get("aot_cache_hits_total", 0)
    misses = table.get("aot_cache_misses_total", 0)
    if not (hits or misses):
        # router-only runs never emit a counters snapshot (no driver
        # chunk accounting) — fall back to the per-event records
        events = [r.get("event") for r in records
                  if r.get("kind") == "aot_cache"]
        hits = events.count("hit")
        misses = events.count("miss")
    reqs = [r for r in records if r.get("kind") == "request"]
    if not (hits or misses or reqs):
        return []
    lines = []
    total = hits + misses
    ratio = f" ({100.0 * hits / total:.1f}% warm)" if total else ""
    lines.append(f"  executables: {hits} hit(s) / {misses} miss(es)"
                 f"{ratio}")
    for key, label in (("aot_cache_evictions_total", "evictions"),
                       ("aot_cache_corrupt_total",
                        "corrupt entries refused"),
                       ("aot_cache_inflight_waits_total",
                        "in-flight compile waits"),
                       ("serve_requests_total", "requests served"),
                       ("serve_cold_requests_total", "cold requests"),
                       ("serve_quarantined_total", "lanes quarantined"),
                       ("serve_padded_lanes_total", "padded lanes")):
        if table.get(key):
            lines.append(f"  {label}: {_fmt_num(table[key])}")
    if reqs:
        cold = [r["first_step_s"] for r in reqs
                if r.get("cold") and r.get("first_step_s") is not None]
        warm = [r["first_step_s"] for r in reqs
                if not r.get("cold")
                and r.get("first_step_s") is not None]
        if cold:
            lines.append(f"  cold first-step: "
                         f"{_fmt_s(max(cold))} worst of {len(cold)}")
        if warm:
            lines.append(f"  warm first-step: "
                         f"{_fmt_s(max(warm))} worst of {len(warm)}")
    return lines


def render_tuning(snap, records: list) -> list:
    """Autotuner block (PR 13): the resolver's DB hit/fallback/skip
    totals plus the measured winner per searched configuration key,
    from ``tune_trial`` ledger records. Empty when the run never
    touched the tuner or the tuning DB."""
    table = (snap or {}).get("counters") or {}
    trials = [r for r in records if r.get("kind") == "tune_trial"]
    counter_keys = ("tuning_db_hits_total", "tuning_db_fallbacks_total",
                    "tuning_db_provenance_skips_total",
                    "tune_trials_total", "tune_pruned_total",
                    "tune_errors_total")
    if not trials and not any(table.get(k) for k in counter_keys):
        return []
    lines = []
    for key, label in ((counter_keys[0], "DB hits"),
                       (counter_keys[1], "DB fallbacks (heuristic)"),
                       (counter_keys[2], "DB provenance skips"),
                       (counter_keys[3], "trials measured"),
                       (counter_keys[4], "candidates pruned"),
                       (counter_keys[5], "trial errors")):
        if table.get(key):
            lines.append(f"  {label}: {_fmt_num(table[key])}")
    # winner per configuration key (n, markers), with its margin over
    # the best OTHER engine — the same ranking tune.py publishes
    by_key = {}
    for r in trials:
        if r.get("error") or not r.get("steps_per_s"):
            continue
        by_key.setdefault((r.get("n"), r.get("markers")), []).append(r)
    for (n, markers), rows in sorted(by_key.items(),
                                     key=lambda kv: kv[0]):
        rows.sort(key=lambda r: r["steps_per_s"], reverse=True)
        w = rows[0]
        ru = next((r for r in rows[1:]
                   if r.get("engine") != w.get("engine")), None)
        margin = (f", {w['steps_per_s'] / ru['steps_per_s']:.2f}x over "
                  f"{ru['engine']}" if ru and ru.get("steps_per_s")
                  else "")
        lines.append(
            f"  n={n} markers={markers}: {w.get('engine')}"
            f"/{w.get('spectral_dtype')}/L{w.get('chunk_length')} "
            f"{w['steps_per_s']:.2f} steps/s ({len(rows)} trials"
            f"{margin})")
    return lines


_REASON_RE = re.compile(r'reason="([^"]*)"')


def render_traffic(snap, records: list) -> list:
    """Admission & overload block (PR 17): shed totals by reason,
    retry totals, reclaimed quarantined slots, queue-wait percentiles
    from the ``serve_queue_wait_seconds`` histogram, and a per-tenant-
    class request table joined from the
    ``request_admit``/``request``/``request_shed``/``request_retry``
    records. Empty when the run saw no admission-control activity
    (no sheds, retries, reclaims, or nonzero queue waits) — a plain
    serving run keeps its summary unchanged."""
    table = (snap or {}).get("counters") or {}
    hists = (snap or {}).get("histograms") or {}
    sheds = [r for r in records if r.get("kind") == "request_shed"]
    retries = [r for r in records if r.get("kind") == "request_retry"]
    shed_counters = {k: v for k, v in table.items()
                     if k.startswith("serve_shed_total")}
    retry_counters = {k: v for k, v in table.items()
                      if k.startswith("serve_retries_total")}
    reclaimed = table.get("serve_slots_reclaimed_total", 0)
    qsnap = hists.get("serve_queue_wait_seconds")
    waited = bool(qsnap and qsnap.get("count") and qsnap.get("sum"))
    if not (sheds or retries or shed_counters or retry_counters
            or reclaimed or waited):
        return []
    lines = []
    by_reason: dict = {}
    if shed_counters:
        for k, v in shed_counters.items():
            m = _REASON_RE.search(k)
            by_reason[m.group(1) if m else "?"] = int(v)
    else:
        for r in sheds:
            key = r.get("reason") or "?"
            by_reason[key] = by_reason.get(key, 0) + 1
    total_shed = sum(by_reason.values())
    if total_shed:
        detail = ", ".join(f"{k}={v}"
                           for k, v in sorted(by_reason.items()))
        lines.append(f"  shed: {total_shed} ({detail})")
    by_retry: dict = {}
    if retry_counters:
        for k, v in retry_counters.items():
            m = _REASON_RE.search(k)
            by_retry[m.group(1) if m else "?"] = int(v)
    else:
        for r in retries:
            key = r.get("reason") or "?"
            by_retry[key] = by_retry.get(key, 0) + 1
    if by_retry:
        detail = ", ".join(f"{k}={v}"
                           for k, v in sorted(by_retry.items()))
        lines.append(f"  retries: {sum(by_retry.values())} ({detail})")
    if reclaimed:
        lines.append(f"  quarantined slots reclaimed: "
                     f"{_fmt_num(reclaimed)}")
    if qsnap and qsnap.get("count"):
        p50, p99 = quantiles_from_counts(qsnap["counts"], [0.5, 0.99])
        lines.append(f"  queue wait: p50 {_fmt_s(p50)}  "
                     f"p99 {_fmt_s(p99)} "
                     f"({_fmt_num(qsnap['count'])} admissions)")
    else:
        qwaits = sorted(r["queue_wait_s"] for r in records
                        if r.get("kind") in ("request", "request_shed")
                        and r.get("queue_wait_s") is not None)
        if qwaits:
            import math
            idx = lambda q: qwaits[min(len(qwaits) - 1,  # noqa: E731
                                       max(0, math.ceil(q * len(qwaits))
                                           - 1))]
            lines.append(f"  queue wait: p50 {_fmt_s(idx(0.5))}  "
                         f"p99 {_fmt_s(idx(0.99))} "
                         f"({_fmt_num(len(qwaits))} requests)")
    classes: dict = {}

    def _cls(r):
        return classes.setdefault(
            r.get("tenant_class") or "?",
            {"admitted": 0, "completed": 0, "shed": 0, "retried": 0})

    for r in records:
        kind = r.get("kind")
        if kind == "request_admit":
            _cls(r)["admitted"] += 1
        elif kind == "request":
            _cls(r)["completed"] += 1
        elif kind == "request_shed":
            _cls(r)["shed"] += 1
        elif kind == "request_retry":
            _cls(r)["retried"] += 1
    for cls, c in sorted(classes.items()):
        lines.append(f"  class {cls:<12} admitted={c['admitted']:<5} "
                     f"completed={c['completed']:<5} "
                     f"shed={c['shed']:<5} retried={c['retried']}")
    return lines


def render_elastic(snap, records: list) -> list:
    """Elastic-pool block (PR 18): scale events by action+reason from
    the ``pool_scale`` records, the serve-mode ladder history from
    ``serve_mode`` transitions, and the restart drill's
    checkpoint/restore outcome from
    ``serving_manifest``/``serving_restore``. Empty when the run had
    no elastic manager — a static-router summary is unchanged."""
    scales = [r for r in records if r.get("kind") == "pool_scale"]
    modes = [r for r in records if r.get("kind") == "serve_mode"]
    manifests = [r for r in records
                 if r.get("kind") == "serving_manifest"]
    restores = [r for r in records
                if r.get("kind") == "serving_restore"]
    if not (scales or modes or manifests or restores):
        return []
    lines = []
    by_action: dict = {}
    for r in scales:
        key = (r.get("action") or "?", r.get("reason") or "?")
        by_action[key] = by_action.get(key, 0) + 1
    if by_action:
        detail = ", ".join(f"{a}/{re}={n}" for (a, re), n
                           in sorted(by_action.items()))
        lines.append(f"  scale events: {len(scales)} ({detail})")
    warmed = [r.get("warm_s") for r in scales
              if r.get("action") == "warmed"
              and r.get("warm_s") is not None]
    if warmed:
        lines.append(f"  scale-up latency: max {_fmt_s(max(warmed))} "
                     f"over {len(warmed)} grow(s)")
    fams = ((snap or {}).get("gauges")
            or {}).get("serve_families_live")
    if fams is not None:
        lines.append(f"  families live (last): {int(fams)}")
    if modes:
        hist = " -> ".join([modes[0].get("prev") or "?"]
                           + [m.get("mode") or "?" for m in modes])
        lines.append(f"  mode ladder: {hist} "
                     f"({len(modes)} transition(s))")
    for r in manifests:
        lines.append(f"  manifest saved: {r.get('path')} "
                     f"({r.get('families')} families, "
                     f"digest {str(r.get('scale_digest'))[:12]})")
    for r in restores:
        lines.append(f"  restart: {r.get('warmed')}/"
                     f"{r.get('families')} re-warmed in "
                     f"{_fmt_s(r.get('warm_s'))}, "
                     f"fresh_compiles={r.get('fresh_compiles')} "
                     f"persistent_loads={r.get('persistent_loads')}")
    return lines


def render_design(snap, records: list) -> list:
    """Design-loop block (PR 19): per-label iteration counts, the
    objective trajectory, compile accounting (cold misses vs warm
    hits — the adjoint-at-primal-cost contract says warm iterations
    pay ZERO compiles), and cold-vs-warm iteration wall from the
    ``design_iter`` records :class:`ibamr_tpu.design.DesignLoop`
    emits. Empty when the run had no design loop."""
    iters = [r for r in records if r.get("kind") == "design_iter"]
    if not iters:
        return []
    lines = []
    by_label: dict = {}
    for r in iters:
        by_label.setdefault(r.get("label") or "?", []).append(r)
    for label, rs in sorted(by_label.items()):
        rs = sorted(rs, key=lambda r: (r.get("iteration") or 0))
        objs = [r.get("objective") for r in rs]
        misses = sum(int(r.get("cache_misses") or 0) for r in rs)
        warm_miss = sum(int(r.get("cache_misses") or 0)
                        for r in rs[1:])
        warm_wall = [r.get("wall_s") for r in rs[1:]
                     if r.get("wall_s") is not None]
        lines.append(f"  {label}: {len(rs)} iteration(s), "
                     f"objective {objs[0]:.4e} -> {objs[-1]:.4e}"
                     + (" (decreasing)" if len(objs) > 1
                        and all(b < a for a, b in zip(objs, objs[1:]))
                        else ""))
        lines.append(f"    compiles: {misses} total, {warm_miss} warm"
                     + ("  [warm iterations recompiled!]"
                        if warm_miss else ""))
        if rs and rs[0].get("wall_s") is not None and warm_wall:
            lines.append(
                f"    wall: cold {_fmt_s(rs[0].get('wall_s'))}, "
                f"warm mean {_fmt_s(sum(warm_wall) / len(warm_wall))}")
        gn = [r.get("grad_norm") for r in rs
              if r.get("grad_norm") is not None]
        if gn:
            lines.append(f"    grad norm: {gn[0]:.3e} -> {gn[-1]:.3e}")
    return lines


def render_assim(snap, records: list) -> list:
    """Assimilation block (PR 20): forecast-error trajectory and
    spread trend from the ``assim_cycle`` records, QC rejections by
    reason from the labelled counter (record fallback), inflation
    escalations from the supervisor's incident stream, and the drill
    verdict (``assim_summary``) when one landed. Empty when the run
    never assimilated."""
    cycles = [r for r in records if r.get("kind") == "assim_cycle"]
    rejects = [r for r in records
               if r.get("kind") == "assim_qc_reject"]
    summaries = [r for r in records
                 if r.get("kind") == "assim_summary"]
    if not (cycles or rejects or summaries):
        return []
    lines = []
    analyzed = [r for r in cycles if not r.get("skipped")]
    if cycles:
        lines.append(f"  cycles: {len(cycles)} "
                     f"({len(analyzed)} analyzed, "
                     f"{len(cycles) - len(analyzed)} skipped)")
    errs = [r["forecast_error"] for r in analyzed
            if r.get("forecast_error") is not None]
    if errs:
        shown = (errs if len(errs) <= 6
                 else errs[:3] + [None] + errs[-2:])
        traj = " -> ".join("..." if e is None else f"{e:.3e}"
                           for e in shown)
        lines.append(f"  forecast error: {traj}")
    spreads = [(r.get("spread_f"), r.get("spread_a"))
               for r in analyzed if r.get("spread_f") is not None]
    if spreads:
        f0, a0 = spreads[0]
        fl, al = spreads[-1]
        lines.append(f"  spread (forecast/analysis): "
                     f"{f0:.3e}/{a0:.3e} -> {fl:.3e}/{al:.3e}")
    if analyzed and analyzed[-1].get("consistency") is not None:
        lines.append(f"  innovation consistency (last): "
                     f"{analyzed[-1]['consistency']:.3f} "
                     f"(1 = spread matches error)")

    # QC rejections by reason: the counter labels are authoritative;
    # the structured reject records are the fallback
    by_reason: dict = {}
    for k, v in ((snap or {}).get("counters") or {}).items():
        if k.startswith("assim_qc_rejections_total"):
            m = _REASON_RE.search(k)
            by_reason[m.group(1) if m else "?"] = int(v)
    if not by_reason:
        for r in rejects:
            key = r.get("reason") or "?"
            by_reason[key] = by_reason.get(key, 0) + 1
    if by_reason:
        detail = ", ".join(f"{k}={n}"
                           for k, n in sorted(by_reason.items()))
        lines.append(f"  qc rejections: {sum(by_reason.values())} "
                     f"({detail})")
    escal = [r for r in records
             if r.get("kind") == "incident"
             and r.get("event") == "inflation_escalation"]
    if escal:
        ladder = " -> ".join(
            [f"{escal[0].get('inflation_before')}"]
            + [f"{r.get('inflation_after')}" for r in escal])
        lines.append(f"  inflation escalations: {len(escal)} "
                     f"({ladder})")
    elif analyzed:
        lines.append(f"  inflation (last): "
                     f"{analyzed[-1].get('inflation')}")
    for r in summaries:
        fe, ol = r.get("forecast_error"), r.get("open_loop_error")
        if fe is not None and ol:
            lines.append(f"  drill verdict: forecast {fe:.3e} vs "
                         f"open-loop {ol:.3e} "
                         f"({ol / fe:.1f}x better)")
    return lines


def render_incidents(records: list, t0=None) -> list:
    lines = []
    for rec in records:
        if rec.get("kind") not in ("incident", "replay"):
            continue
        rel = ("     -" if t0 is None or rec.get("t") is None
               else f"{rec['t'] - t0:+9.2f}s")
        what = rec.get("event") or rec.get("incident_kind") \
            or rec.get("verdict") or rec["kind"]
        extra = " ".join(
            f"{k}={rec[k]}" for k in ("incident_kind", "step", "lane",
                                      "retry", "verdict")
            if rec.get(k) is not None and rec.get(k) != what)
        lines.append(f"  seq={rec['seq']:<6} {rel}  {what:<22} {extra}")
    return lines or ["  (no incidents)"]


# ---------------------------------------------------------------------------
# the device column (PR 10): host spans x attributed device time
# ---------------------------------------------------------------------------

def device_spans(records: list, summary_path: str = ""):
    """Per-span device seconds + total, from an explicit
    ``prof_summary.json`` or from the ledger's LAST ``device_time``
    record (``tools/prof.py attribute --ledger`` appends one).
    Returns ``(spans, total_device_s)`` or ``None``."""
    if summary_path:
        from ibamr_tpu.obs.deviceprof import read_summary

        s = read_summary(summary_path)
        spans = {k: (v.get("device_s") if isinstance(v, dict) else v)
                 for k, v in (s.get("spans") or {}).items()}
        return spans, s.get("total_device_s")
    recs = [r for r in records if r.get("kind") == "device_time"]
    if not recs:
        return None
    last = recs[-1]
    return (last.get("spans") or {}), last.get("total_device_s")


def render_device_table(records: list, dev) -> list:
    """host vs attributed device time per phase: host share of the
    run, device share of the capture, and the host/device gap — the
    dispatch/python overhead the device never saw (a host phase much
    wider than its device time is overhead; the reverse is a span that
    closed before its async work drained)."""
    spans, dev_total = dev
    tree = span_tree(records)
    host_total = sum(n["total_s"] for p, n in tree.items()
                     if not any(p != r and p.startswith(r + "/")
                                for r in tree)) or None
    paths = sorted(set(tree) | set(spans))
    if not paths:
        return ["  (no spans on either side)"]
    width = max(len(p) for p in paths) + 2
    lines = [f"  {'phase':<{width}} {'host':>10} {'host%':>7}"
             f" {'device':>10} {'dev%':>7} {'gap':>10}"]
    for p in paths:
        h = tree.get(p, {}).get("total_s")
        d = spans.get(p)
        hp = (f"{100.0 * h / host_total:6.1f}%"
              if h is not None and host_total else "      -")
        dp = (f"{100.0 * d / dev_total:6.1f}%"
              if d is not None and dev_total else "      -")
        gap = (_fmt_s(h - d) if h is not None and d is not None
               else "-")
        lines.append(f"  {p:<{width}} {_fmt_s(h):>10} {hp:>7}"
                     f" {_fmt_s(d):>10} {dp:>7} {gap:>10}")
    if dev_total is not None:
        lines.append(f"  {'(device total)':<{width}} {'':>10} {'':>7}"
                     f" {_fmt_s(dev_total):>10}")
    return lines


# ---------------------------------------------------------------------------
# fleet (PR 15): merged multi-process rollup
# ---------------------------------------------------------------------------

def _proc_records(merged: dict, proc: str) -> list:
    return [r for r in merged["records"]
            if str(r.get("proc", "")) == proc]


def _comm_line(records: list):
    """The comm rollup of one proc's NEWEST ``device_time`` record
    (``tools/prof.py attribute --ledger`` appends one per capture) —
    comm seconds, device total, and the comm fraction — or ``None``
    when no attribution with op classes has run on that shard."""
    for rec in reversed(records):
        if rec.get("kind") != "device_time":
            continue
        oc = rec.get("op_classes") or {}
        total = rec.get("total_device_s")
        if "comm_s" not in oc or not total:
            continue
        comm = float(oc["comm_s"] or 0.0)
        return (f"  comm: {_fmt_s(comm)} of {_fmt_s(total)} device "
                f"({100.0 * comm / float(total):.1f}% of capture)")
    return None


def _census_line(records: list):
    """The structural comm split of one proc's NEWEST ``graph_census``
    record (``tools/fleet.py`` emits one per supervised run, PR 16) —
    how many data-moving collectives the chunk issues and how many have
    an independent-compute window to hide behind. Backend-independent,
    so it complements the measured ``comm_s`` line even on captures
    where the CPU scheduler serialized everything."""
    for rec in reversed(records):
        if rec.get("kind") != "graph_census":
            continue
        total = rec.get("structural_collectives")
        if total is None:
            continue
        hid = int(rec.get("hidden_collectives") or 0)
        unhid = int(rec.get("unhidden_collectives") or 0)
        frac = rec.get("hidden_fraction")
        extra = ""
        if rec.get("mesh_devices"):
            extra = (f" [lanes={rec.get('lanes')} x "
                     f"D={rec['mesh_devices']}]")
        if int(total) == 0:
            return (f"  comm graph: 0 data-moving collectives in the "
                    f"chunk (fully lane-local){extra}")
        return (f"  comm graph: {total} data-moving collectives, "
                f"{hid} hidden / {unhid} unhidden "
                f"({frac}% structurally hidden){extra}")
    return None


def cmd_fleet_summary(args) -> int:
    from ibamr_tpu.obs.merge import fleet_counters, merge_ledgers

    try:
        merged = merge_ledgers(args.ledger)
    except ValueError as e:
        print(f"[obs] {e}", file=sys.stderr)
        return 1
    if not merged["records"]:
        print(f"[obs] no ledger shards under {args.ledger} "
              f"(expected ledger-<proc>.jsonl)", file=sys.stderr)
        return 1
    now = time.time()
    print(f"run_id: {merged['run_id']}   procs: "
          f"{len(merged['procs'])}   records: "
          f"{len(merged['records'])}")
    for proc in merged["procs"]:
        recs = _proc_records(merged, proc)
        info = merged["per_proc"][proc]
        times = [r["t"] for r in recs
                 if isinstance(r.get("t"), (int, float))]
        wall = (max(times) - min(times)) if len(times) > 1 else None
        stale = (f"{now - info['last_t']:.1f}s ago"
                 if info.get("last_t") else "-")
        ended = any(r.get("kind") == "run_end" for r in recs)
        print(f"\nproc {proc}: {info['records']} records   wall "
              f"{_fmt_s(wall)}   last record {stale}"
              + ("" if ended else "   (no run_end — alive or killed)"))
        for ln in render_span_tree(recs, wall):
            print(ln)
        comm = _comm_line(recs)
        if comm:
            print(comm)
        census = _census_line(recs)
        if census:
            print(census)
    snap = fleet_counters(merged)
    if snap["counters"] or snap["gauges"]:
        print("\nfleet counters (last snapshot per proc, "
              "proc-labeled — cumulative per process, never summed):")
        for kind in ("counters", "gauges"):
            for key in sorted(snap[kind]):
                print(f"  {key:<58} {_fmt_num(snap[kind][key]):>14}")
    print("\nincidents (all procs, merged order):")
    times = [r["t"] for r in merged["records"]
             if isinstance(r.get("t"), (int, float))]
    t0 = min(times) if times else None
    for ln in render_incidents(merged["records"], t0):
        print(ln)
    return 0


def cmd_summary(args) -> int:
    if getattr(args, "fleet", False):
        return cmd_fleet_summary(args)
    path = resolve_ledger(args.ledger)
    records = read_ledger(path)
    if not records:
        print(f"[obs] no readable records in {path}", file=sys.stderr)
        return 1
    start = next((r for r in records if r.get("kind") == "run_start"),
                 records[0])
    end = next((r for r in records if r.get("kind") == "run_end"), None)
    times = [r["t"] for r in records if isinstance(r.get("t"),
                                                   (int, float))]
    wall = (max(times) - min(times)) if len(times) > 1 else None
    print(f"run_id: {start.get('run_id')}   records: {len(records)}"
          f"   wall: {_fmt_s(wall)}"
          + ("" if end is None else
             f"   obs_overhead: {_fmt_s(end.get('overhead_s'))}"))
    fp = start.get("fingerprint") or {}
    if fp:
        print(f"fingerprint: platform={fp.get('platform')}"
              f" engine={fp.get('engine')}"
              f" spectral_dtype={fp.get('spectral_dtype')}"
              f" config_digest={str(fp.get('config_digest'))[:12]}")
    print("\nphases (total, calls, % of parent):")
    for ln in render_span_tree(records, wall):
        print(ln)
    if getattr(args, "device", None) is not None:
        dev = device_spans(records, "" if args.device is True
                           else args.device)
        print("\ndevice time (host vs attributed device, per phase):")
        if dev is None:
            print("  (no device_time record in the ledger — run "
                  "`tools/prof.py attribute <capture> --ledger ...`, "
                  "or pass --device <prof_summary.json>)")
        else:
            for ln in render_device_table(records, dev):
                print(ln)
    print("\ncounters (last snapshot = run totals):")
    for ln in render_counters(last_counters(records)):
        print(ln)
    latency = render_latency(last_counters(records))
    if latency:
        print("\nlatency (histogram percentiles, last snapshot):")
        for ln in latency:
            print(ln)
    serving = render_serving(last_counters(records), records)
    if serving:
        print("\nserving (warm-pool efficacy):")
        for ln in serving:
            print(ln)
    tuning = render_tuning(last_counters(records), records)
    if tuning:
        print("\ntuning (autotuner + resolver DB):")
        for ln in tuning:
            print(ln)
    traffic = render_traffic(last_counters(records), records)
    if traffic:
        print("\ntraffic (admission & overload):")
        for ln in traffic:
            print(ln)
    elastic = render_elastic(last_counters(records), records)
    if elastic:
        print("\nelastic pools (scaling, brownout, restart):")
        for ln in elastic:
            print(ln)
    design = render_design(last_counters(records), records)
    if design:
        print("\ndesign loop (adjoint iterations, compile "
              "accounting):")
        for ln in design:
            print(ln)
    assim = render_assim(last_counters(records), records)
    if assim:
        print("\nassimilation (filter health, QC, forecast skill):")
        for ln in assim:
            print(ln)
    print("\nincidents:")
    t0 = min(times) if times else None
    for ln in render_incidents(records, t0):
        print(ln)
    return 0


# ---------------------------------------------------------------------------
# tail
# ---------------------------------------------------------------------------

def _one_line(rec: dict) -> str:
    kind = rec.get("kind")
    if kind == "span":
        return (f"seq={rec['seq']:<6} span      "
                f"{rec.get('path')}  {_fmt_s(rec.get('dur_s'))}")
    if kind == "counters":
        n = len(rec.get("counters") or {}) + len(rec.get("gauges") or {})
        return (f"seq={rec['seq']:<6} counters  step={rec.get('step')} "
                f"chunk={_fmt_s(rec.get('chunk_wall_s'))} "
                f"({n} metrics)")
    if kind == "profile":
        return (f"seq={rec['seq']:<6} profile   "
                f"stage={rec.get('stage')} -> {rec.get('capture_dir')}")
    if kind == "request":
        return (f"seq={rec['seq']:<6} request   "
                f"tenant={rec.get('tenant')} "
                f"{'cold' if rec.get('cold') else 'warm'} "
                f"lane={rec.get('lane')} "
                f"first_step={_fmt_s(rec.get('first_step_s'))} "
                f"ok={rec.get('ok')}")
    if kind == "request_shed":
        return (f"seq={rec['seq']:<6} shed      "
                f"tenant={rec.get('tenant')} "
                f"reason={rec.get('reason')} "
                f"queue_wait={_fmt_s(rec.get('queue_wait_s'))} "
                f"retries={rec.get('retries')}")
    if kind == "request_retry":
        return (f"seq={rec['seq']:<6} retry     "
                f"tenant={rec.get('tenant')} "
                f"attempt={rec.get('attempt')} "
                f"reason={rec.get('reason')} "
                f"backoff={_fmt_s(rec.get('backoff_s'))}")
    if kind == "tune_trial":
        return (f"seq={rec['seq']:<6} tune      "
                f"{rec.get('engine')}/{rec.get('spectral_dtype')}"
                f"/L{rec.get('chunk_length')} n={rec.get('n')} "
                f"{rec.get('steps_per_s')} steps/s "
                f"{'HIT' if rec.get('cache_hit') else 'compile'}"
                + (f" ERROR={rec.get('error')}" if rec.get("error")
                   else ""))
    if kind == "aot_cache":
        return (f"seq={rec['seq']:<6} aot_cache "
                f"{rec.get('event')} key={rec.get('key')} "
                f"label={rec.get('label')}")
    if kind == "pool_scale":
        return (f"seq={rec['seq']:<6} scale     "
                f"{rec.get('action')} family={rec.get('family')} "
                f"reason={rec.get('reason')} "
                f"live={rec.get('families_live')}"
                + (f" warm={_fmt_s(rec.get('warm_s'))}"
                   if rec.get("warm_s") is not None else ""))
    if kind == "serve_mode":
        return (f"seq={rec['seq']:<6} mode      "
                f"{rec.get('prev')} -> {rec.get('mode')} "
                f"queue_p99={_fmt_s(rec.get('queue_p99_s'))} "
                f"backlog={rec.get('backlog')}")
    if kind == "serving_manifest":
        return (f"seq={rec['seq']:<6} manifest  "
                f"{rec.get('path')} families={rec.get('families')} "
                f"digest={str(rec.get('scale_digest'))[:12]}")
    if kind == "serving_restore":
        return (f"seq={rec['seq']:<6} restore   "
                f"warmed={rec.get('warmed')}/{rec.get('families')} "
                f"{_fmt_s(rec.get('warm_s'))} "
                f"fresh={rec.get('fresh_compiles')} "
                f"persistent={rec.get('persistent_loads')}")
    if kind == "assim_cycle":
        if rec.get("skipped"):
            return (f"seq={rec['seq']:<6} assim     "
                    f"cycle={rec.get('cycle')} step={rec.get('step')} "
                    f"SKIPPED accepted={rec.get('accepted')} "
                    f"rejected={rec.get('rejected')}")
        return (f"seq={rec['seq']:<6} assim     "
                f"cycle={rec.get('cycle')} step={rec.get('step')} "
                f"err={rec.get('forecast_error'):.3e} "
                f"spread={rec.get('spread_a'):.3e} "
                f"infl={rec.get('inflation')} "
                f"alive={rec.get('n_alive')} "
                f"wall={_fmt_s(rec.get('analysis_wall_s'))}")
    if kind == "assim_qc_reject":
        return (f"seq={rec['seq']:<6} qc_reject "
                f"cycle={rec.get('cycle')} "
                f"{rec.get('instrument')} reason={rec.get('reason')} "
                f"innovation={rec.get('innovation')}")
    if kind == "device_time":
        return (f"seq={rec['seq']:<6} device    "
                f"{_fmt_s(rec.get('total_device_s'))} device, "
                f"{100.0 * (rec.get('fraction_attributed') or 0):.1f}% "
                f"attributed ({rec.get('capture_dir')})")
    body = {k: v for k, v in rec.items()
            if k not in ("seq", "run_id", "t", "kind")}
    return f"seq={rec['seq']:<6} {kind:<9} {json.dumps(body)[:140]}"


def _tail_match(rec: dict, grep: str, trace: str) -> bool:
    """Both filters must pass: ``grep`` is a substring match against
    the raw record JSON, ``trace`` a (prefix-tolerant) trace-id match —
    together they let one request be followed live."""
    if trace and not any(t == trace or t.startswith(trace)
                         for t in record_trace_ids(rec)):
        return False
    if grep and grep not in json.dumps(rec):
        return False
    return True


def cmd_tail(args) -> int:
    path = resolve_ledger(args.ledger)
    hb_path = args.heartbeat or os.path.join(
        os.path.dirname(path) or ".", "heartbeat.json")
    from ibamr_tpu.utils.watchdog import heartbeat_age
    seen = -1
    deadline = (time.monotonic() + args.max_seconds
                if args.max_seconds else None)
    last_hb_print = 0.0
    while True:
        for rec in read_ledger(path):
            if rec["seq"] > seen:
                seen = rec["seq"]
                if _tail_match(rec, args.grep, args.trace):
                    print(_one_line(rec), flush=True)
        now = time.monotonic()
        if now - last_hb_print >= args.heartbeat_every:
            last_hb_print = now
            age = heartbeat_age(hb_path)
            if age is not None:
                print(f"[heartbeat] age={age:.1f}s ({hb_path})",
                      file=sys.stderr, flush=True)
        if deadline is not None and now >= deadline:
            return 0
        time.sleep(args.interval)


# ---------------------------------------------------------------------------
# trace: one request's timeline, from the ledger alone
# ---------------------------------------------------------------------------

def render_trace(records: list, tid: str) -> list:
    """One request's full admission→completion timeline: every record
    carrying ``tid``, chronological, spans indented by their recorded
    depth (parentage), times relative to the first record (admission).
    Empty when nothing carries the id."""
    matched = [r for r in records if tid in record_trace_ids(r)]
    if not matched:
        return []
    t0 = next((r["t"] for r in matched
               if isinstance(r.get("t"), (int, float))), None)
    run_id = matched[0].get("run_id")
    admit = next((r for r in matched
                  if r.get("kind") == "request_admit"), None)
    done = next((r for r in matched if r.get("kind") == "request"),
                None)
    tenant = admit.get("tenant") if admit else None
    lines = [f"trace {tid}  (run {run_id}"
             + (f", tenant {tenant}" if tenant else "")
             + f")  {len(matched)} record(s)"]
    for rec in matched:
        rel = ("        -" if t0 is None
               or not isinstance(rec.get("t"), (int, float))
               else f"{rec['t'] - t0:+9.3f}s")
        kind = rec.get("kind")
        if kind == "span":
            indent = "  " * int(rec.get("depth") or 0)
            desc = (f"{indent}span {rec.get('path')}  "
                    f"{_fmt_s(rec.get('dur_s'))}")
        elif kind == "request_admit":
            desc = (f"admitted         tenant={rec.get('tenant')} "
                    f"steps={rec.get('steps')}"
                    + (f" class={rec.get('tenant_class')}"
                       if rec.get("tenant_class") else ""))
        elif kind == "request":
            qw = rec.get("queue_wait_s")
            desc = (f"completed        "
                    f"{'cold' if rec.get('cold') else 'warm'} "
                    f"ok={rec.get('ok')} lane={rec.get('lane')} "
                    f"first_step={_fmt_s(rec.get('first_step_s'))} "
                    f"total={_fmt_s(rec.get('total_s'))}"
                    + (f" queue_wait={_fmt_s(qw)}" if qw else "")
                    + (f" retries={rec.get('retries')}"
                       if rec.get("retries") else "")
                    + (" QUARANTINED" if rec.get("quarantined")
                       else ""))
        elif kind == "request_shed":
            desc = (f"SHED             "
                    f"reason={rec.get('reason')} "
                    f"queue_wait={_fmt_s(rec.get('queue_wait_s'))} "
                    f"retries={rec.get('retries')}"
                    + (f" error={rec.get('error')}"
                       if rec.get("error") else ""))
        elif kind == "request_retry":
            desc = (f"retry #{rec.get('attempt')}         "
                    f"reason={rec.get('reason')} "
                    f"backoff={_fmt_s(rec.get('backoff_s'))}")
        elif kind == "aot_cache":
            desc = (f"aot_cache {rec.get('event'):<7}"
                    f"label={rec.get('label')}"
                    + (f" compile={_fmt_s(rec.get('compile_s'))}"
                       if rec.get("compile_s") is not None else ""))
        elif kind == "lane_quarantine":
            desc = (f"lane_quarantine  lane={rec.get('lane')} "
                    f"step={rec.get('step')}")
        elif kind == "pool_scale":
            desc = (f"SCALE {rec.get('action'):<10} "
                    f"family={rec.get('family')} "
                    f"reason={rec.get('reason')}"
                    + (f" warm={_fmt_s(rec.get('warm_s'))}"
                       if rec.get("warm_s") is not None else ""))
        elif kind == "serve_mode":
            desc = (f"MODE             {rec.get('prev')} -> "
                    f"{rec.get('mode')} "
                    f"queue_p99={_fmt_s(rec.get('queue_p99_s'))} "
                    f"backlog={rec.get('backlog')}")
        elif kind == "assim_cycle":
            if rec.get("skipped"):
                desc = (f"assim cycle #{rec.get('cycle')}  SKIPPED "
                        f"(accepted={rec.get('accepted')} of "
                        f"{(rec.get('accepted') or 0) + (rec.get('rejected') or 0)})")
            else:
                desc = (f"assim cycle #{rec.get('cycle')}  "
                        f"err={rec.get('forecast_error'):.3e} "
                        f"spread={rec.get('spread_a'):.3e} "
                        f"infl={rec.get('inflation')} "
                        f"alive={rec.get('n_alive')} "
                        f"wall={_fmt_s(rec.get('analysis_wall_s'))}")
        elif kind == "assim_qc_reject":
            desc = (f"QC REJECT        {rec.get('instrument')} "
                    f"reason={rec.get('reason')} "
                    f"innovation={rec.get('innovation')}")
        else:
            body = {k: v for k, v in rec.items()
                    if k not in ("seq", "run_id", "t", "kind",
                                 "trace_id", "trace_ids")}
            desc = f"{kind:<16} {json.dumps(body)[:120]}"
        lines.append(f"  seq={rec['seq']:<6} {rel}  {desc}")
    if done is not None:
        verdict = ("ok" if done.get("ok")
                   else "quarantined" if done.get("quarantined")
                   else "failed")
        lines.append(f"  verdict: {verdict}")
    else:
        shed = next((r for r in matched
                     if r.get("kind") == "request_shed"), None)
        if shed is not None:
            lines.append(f"  verdict: shed ({shed.get('reason')})")
    return lines


def cmd_trace(args) -> int:
    path = resolve_ledger(args.ledger)
    records = read_ledger(path)
    wanted = args.trace_id
    full = sorted({t for r in records for t in record_trace_ids(r)
                   if t == wanted or t.startswith(wanted)})
    if not full:
        print(f"[obs] no records carry trace id {wanted!r} in {path}",
              file=sys.stderr)
        return 1
    if len(full) > 1 and wanted not in full:
        print(f"[obs] ambiguous trace-id prefix {wanted!r}: "
              f"{', '.join(full)}", file=sys.stderr)
        return 1
    tid = wanted if wanted in full else full[0]
    for ln in render_trace(records, tid):
        print(ln)
    return 0


# ---------------------------------------------------------------------------
# compare
# ---------------------------------------------------------------------------

def _is_ledger(path: str) -> bool:
    return os.path.isdir(path) or path.endswith(".jsonl")


def _bench_payload(path: str) -> dict:
    """Accept a raw ``bench.py`` JSON or a ``BENCH_r*.json`` wrapper
    (the relay driver stores the parsed result under ``parsed``)."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict) and isinstance(data.get("parsed"), dict):
        return data["parsed"]
    return data


def _delta_line(name: str, a, b) -> str:
    if a in (None, 0) or b is None:
        return f"  {name:<34} {_fmt_num(a):>12} -> {_fmt_num(b):>12}"
    return (f"  {name:<34} {_fmt_num(a):>12} -> {_fmt_num(b):>12}"
            f"   {100.0 * (float(b) - float(a)) / float(a):+7.1f}%")


def compare_ledgers(path_a: str, path_b: str) -> list:
    lines = []
    ta = span_tree(read_ledger(resolve_ledger(path_a)))
    tb = span_tree(read_ledger(resolve_ledger(path_b)))
    lines.append("per-phase wall (A -> B):")
    for path in sorted(set(ta) | set(tb)):
        a = ta.get(path, {}).get("total_s")
        b = tb.get(path, {}).get("total_s")
        lines.append(_delta_line(path, a, b))
    ca = last_counters(read_ledger(resolve_ledger(path_a)))
    cb = last_counters(read_ledger(resolve_ledger(path_b)))
    if ca or cb:
        lines.append("counters (last snapshot, A -> B):")
        ka = (ca or {}).get("counters") or {}
        kb = (cb or {}).get("counters") or {}
        for key in sorted(set(ka) | set(kb)):
            lines.append(_delta_line(key, ka.get(key), kb.get(key)))
    return lines


def _profile_entries(payload: dict) -> dict:
    """{stage label: entry dict} from a bench JSON's ``profiles``
    manifest — dict entries (PR 10: ``{dir, stage, rev, bytes,
    attributed, summary?}``) or the bare path strings older bench
    JSONs recorded (``<label>_<rev>`` dirs -> label)."""
    out = {}
    for e in payload.get("profiles") or []:
        if isinstance(e, dict):
            out[e.get("stage") or e.get("dir", "?")] = e
        elif isinstance(e, str):
            label = os.path.basename(os.path.normpath(e))
            label = label.rsplit("_", 1)[0] if "_" in label else label
            out[label] = {"dir": e, "stage": label, "bytes": None,
                          "attributed": False}
    return out


def compare_bench(path_a: str, path_b: str) -> list:
    a, b = _bench_payload(path_a), _bench_payload(path_b)
    lines = []
    sa = {s.get("n"): s for s in (a.get("stages") or [])}
    sb = {s.get("n"): s for s in (b.get("stages") or [])}
    lines.append("stages steps/s (A -> B):")
    for n in sorted(set(sa) | set(sb), key=lambda x: (x is None, x)):
        lines.append(_delta_line(
            f"n={n}", sa.get(n, {}).get("steps_per_sec"),
            sb.get(n, {}).get("steps_per_sec")))
    pa, pb = a.get("phases") or {}, b.get("phases") or {}
    keys = [k for k in sorted(set(pa) | set(pb))
            if isinstance(pa.get(k), (int, float))
            or isinstance(pb.get(k), (int, float))]
    if keys:
        lines.append("phases (A -> B):")
        for k in keys:
            lines.append(_delta_line(k, pa.get(k), pb.get(k)))
    for key in ("value", "mxu_vs_scatter"):
        if a.get(key) is not None or b.get(key) is not None:
            lines.append(_delta_line(key, a.get(key), b.get(key)))
    va, vb = a.get("serve") or {}, b.get("serve") or {}
    serve_keys = [k for k in ("cold_first_step_s", "warm_first_step_s",
                              "warm_p50_s", "warm_p99_s",
                              "warm_over_cold")
                  if va.get(k) is not None or vb.get(k) is not None]
    if serve_keys:
        lines.append("serve (cold/warm drill, A -> B):")
        for k in serve_keys:
            lines.append(_delta_line(k, va.get(k), vb.get(k)))
        ha = (va.get("histograms") or {})
        hb = (vb.get("histograms") or {})
        for key in sorted(set(ha) | set(hb)):
            sa_, sb_ = ha.get(key), hb.get(key)
            pa_ = (quantiles_from_counts(sa_["counts"], [0.99])[0]
                   if sa_ and sa_.get("count") else None)
            pb_ = (quantiles_from_counts(sb_["counts"], [0.99])[0]
                   if sb_ and sb_.get("count") else None)
            if pa_ is not None or pb_ is not None:
                lines.append(_delta_line(
                    f"p99[{key}]",
                    None if pa_ is None else round(pa_, 6),
                    None if pb_ is None else round(pb_, 6)))
    fa, fb = _profile_entries(a), _profile_entries(b)
    if fa or fb:
        lines.append("profiles (attributed device s/capture, A -> B;"
                     " gate drift with tools/prof.py diff):")
        for label in sorted(set(fa) | set(fb)):
            lines.append(_delta_line(
                f"device[{label}]",
                ((fa.get(label) or {}).get("summary")
                 or {}).get("total_device_s"),
                ((fb.get(label) or {}).get("summary")
                 or {}).get("total_device_s")))
    return lines


def _is_fleet(path: str) -> bool:
    """A directory holding >= 2 ledger shards, or a shard file —
    compare then goes per-proc."""
    from ibamr_tpu.obs.merge import find_shards

    if os.path.isfile(path):
        return os.path.basename(path).startswith("ledger-")
    return os.path.isdir(path) and len(find_shards(path)) > 1


def compare_fleet(path_a: str, path_b: str) -> list:
    """Per-proc deltas between two merged fleet ledgers: each proc's
    span tree compared proc-to-proc (proc ids name the same rank of
    the pod on both sides), then the proc-labeled counter registry."""
    from ibamr_tpu.obs.merge import fleet_counters, merge_ledgers

    ma, mb = merge_ledgers(path_a), merge_ledgers(path_b)
    lines = [f"fleet: A procs={ma['procs']} run={ma['run_id']}   "
             f"B procs={mb['procs']} run={mb['run_id']}"]
    for proc in sorted(set(ma["procs"]) | set(mb["procs"])):
        ta = span_tree(_proc_records(ma, proc))
        tb = span_tree(_proc_records(mb, proc))
        if not (ta or tb):
            continue
        lines.append(f"proc {proc} per-phase wall (A -> B):")
        for path in sorted(set(ta) | set(tb)):
            lines.append(_delta_line(path,
                                     ta.get(path, {}).get("total_s"),
                                     tb.get(path, {}).get("total_s")))
    ka = fleet_counters(ma)["counters"]
    kb = fleet_counters(mb)["counters"]
    if ka or kb:
        lines.append("fleet counters (last snapshot per proc, A -> B):")
        for key in sorted(set(ka) | set(kb)):
            lines.append(_delta_line(key, ka.get(key), kb.get(key)))
    return lines


def cmd_compare(args) -> int:
    if _is_fleet(args.a) and _is_fleet(args.b):
        try:
            lines = compare_fleet(args.a, args.b)
        except ValueError as e:
            print(f"[obs] {e}", file=sys.stderr)
            return 1
    elif _is_ledger(args.a) and _is_ledger(args.b):
        lines = compare_ledgers(args.a, args.b)
    else:
        lines = compare_bench(args.a, args.b)
    print(f"A: {args.a}\nB: {args.b}")
    for ln in lines:
        print(ln)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="run-ledger summary / tail / compare")
    sub = ap.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("summary", help="phase tree + counters + "
                                       "incident timeline")
    s.add_argument("ledger", help="ledger.jsonl or its directory")
    s.add_argument("--fleet", action="store_true",
                   help="merge the directory's ledger-<proc>.jsonl "
                        "shards (one pod run) into per-proc span "
                        "trees, comm fractions, staleness, and a "
                        "proc-labeled counter rollup")
    s.add_argument("--device", nargs="?", const=True, default=None,
                   metavar="PROF_SUMMARY",
                   help="add the host-vs-device table per phase, from "
                        "the ledger's device_time record (bare flag) "
                        "or an explicit prof_summary.json / capture "
                        "dir")
    s.set_defaults(fn=cmd_summary)

    t = sub.add_parser("tail", help="follow a growing ledger (plus "
                                    "heartbeat staleness)")
    t.add_argument("ledger")
    t.add_argument("--interval", type=float, default=1.0)
    t.add_argument("--heartbeat", default="",
                   help="heartbeat.json (default: next to the ledger)")
    t.add_argument("--heartbeat-every", type=float, default=5.0)
    t.add_argument("--max-seconds", type=float, default=0.0,
                   help="exit after this long (0 = follow forever)")
    t.add_argument("--grep", default="",
                   help="only records whose JSON contains this "
                        "substring")
    t.add_argument("--trace", default="",
                   help="only records carrying this trace id (prefix "
                        "ok) — follow one request live")
    t.set_defaults(fn=cmd_tail)

    tr = sub.add_parser("trace", help="one request's full "
                                      "admission->completion timeline "
                                      "from the ledger")
    tr.add_argument("ledger", help="ledger.jsonl or its directory")
    tr.add_argument("trace_id", help="trace id (unique prefix ok)")
    tr.set_defaults(fn=cmd_trace)

    c = sub.add_parser("compare", help="two ledgers, or two bench "
                                       "JSONs (BENCH_r*.json)")
    c.add_argument("a")
    c.add_argument("b")
    c.set_defaults(fn=cmd_compare)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
