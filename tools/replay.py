"""Deterministic incident replay harness (PR 5 tentpole 2).

``incidents/<step>/replay.npz`` + ``manifest.json`` (dumped by the
:class:`~ibamr_tpu.utils.flight_recorder.FlightRecorder` through the
supervisor) is a self-contained capsule of the failing chunk: the
pre-chunk state, the run fingerprint (integrator spec, engine,
``spectral_dtype``, armed fault injectors, audit params) and the
post-chunk digest (per-leaf CRC32s + vitals). This tool re-executes
the capsule in a fresh process:

1. **baseline** — rebuild the integrator exactly per the fingerprint,
   re-arm the recorded injectors, run the chunk, and pin the produced
   state BITWISE against the recorded post-chunk CRCs;
2. **substitution** — ``--override engine=…``,
   ``--override spectral_dtype=…`` and ``--dt-scale`` re-run the same
   capsule under one substitution;
3. **verdict** — a structured classification of what the failure
   depends on::

       reproduced          baseline matched bitwise (and the override,
                           if any, still failed)
       engine_dependent    baseline reproduced; swapping the transfer
                           engine cured it
       precision_dependent baseline reproduced; escalating
                           spectral_dtype cured it
       not_reproduced      the baseline re-execution did not match the
                           recorded digest (environment drift — the
                           fingerprint says what to look at)

   A dt-scale cure is reported via ``dt_dependent: true`` on a
   ``reproduced`` verdict.

Usage::

    python -m tools.replay CKPT_DIR/incidents/00000004 \
        [--override spectral_dtype=f64] [--override engine=mxu] \
        [--dt-scale 0.5] [--json]

Cross-mesh: capsules record UNSHARDED host arrays, so a capsule
recorded on one device replays on any mesh size (pinned by
tests/test_replay.py on the CPU virtual 8-device mesh). Capsules from
SHARDED runs additionally carry the mesh spec in their fingerprint
(stamped by ``ResilientDriver(sharded=True, mesh=...)``): the default
replay still runs them on 1 device, while ``--sharded`` re-executes
the recorded sharded program — degrading to a failure-reproduction pin
(``mesh_degraded``) when fewer devices are available than the incident
ran on.

Lane capsules (fleet runs): a capsule whose manifest carries a
``lane`` record is a SINGLE lane sliced out of a lane-batched fleet
chunk. It replays as a B=1 fleet chunk (vmapped step + freeze mask —
the program shape whose lanes are batch-size invariant), with recorded
``lane_nan``/``lane_drift`` injectors transformed onto lane 0; the
bitwise pin is against the recorded lane-sliced digest, independent of
the original fleet size.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


class ReplayError(RuntimeError):
    pass


# ---------------------------------------------------------------------------
# incident log reading (schema v2/v3 tolerant)
# ---------------------------------------------------------------------------

def read_incidents(path: str) -> list:
    """Read ``incidents.jsonl`` tolerantly across schema versions:
    records written before v3 (no ``schema`` field) read as
    ``schema=2`` with ``replay=None``, so a log that spans an upgrade
    parses uniformly."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            rec.setdefault("schema", 2)
            rec.setdefault("replay", None)
            out.append(rec)
    return out


# ---------------------------------------------------------------------------
# capsule loading / integrator rebuild
# ---------------------------------------------------------------------------

def load_capsule(capsule_dir: str):
    """(manifest, {path: np.ndarray}) from a capsule directory."""
    mpath = os.path.join(capsule_dir, "manifest.json")
    if not os.path.exists(mpath):
        raise ReplayError(f"no manifest.json in {capsule_dir!r}")
    with open(mpath) as f:
        manifest = json.load(f)
    npz = os.path.join(capsule_dir,
                       manifest.get("state_file", "replay.npz"))
    with np.load(npz) as z:
        arrays = {k: z[k] for k in z.files}
    return manifest, arrays


_ENGINE_TO_KWARG = {"scatter": False, "mxu": True, "auto": None}


def rebuild(manifest: dict, overrides: dict | None = None):
    """(integ, template_state) per the manifest fingerprint, with
    ``overrides`` substituted (``spectral_dtype`` -> the spectral knob,
    ``engine`` -> the factory's ``use_fast_interaction``; any other key
    substitutes into factory kwargs verbatim)."""
    overrides = dict(overrides or {})
    spec = manifest["fingerprint"]["integrator"]
    kind = spec.get("kind")
    if kind == "ins":
        import jax.numpy as jnp

        from ibamr_tpu.grid import StaggeredGrid
        from ibamr_tpu.integrators.ins import INSStaggeredIntegrator

        if "engine" in overrides:
            raise ReplayError("--override engine applies to factory "
                              "capsules (the plain INS integrator has "
                              "no transfer engine)")
        gd = spec["grid"]
        grid = StaggeredGrid(n=tuple(gd["n"]), x_lo=tuple(gd["x_lo"]),
                             x_up=tuple(gd["x_up"]))
        wall = spec.get("wall_axes")
        integ = INSStaggeredIntegrator(
            grid, rho=spec["rho"], mu=spec["mu"],
            convective_op_type=spec["convective_op_type"],
            dtype=jnp.dtype(spec["dtype"]),
            wall_axes=None if wall is None else tuple(wall),
            spectral_dtype=overrides.get("spectral_dtype",
                                         spec.get("spectral_dtype")))
        return integ, integ.initialize()
    if kind == "factory":
        mod = importlib.import_module(spec["module"])
        fn = getattr(mod, spec["name"])
        kwargs = dict(spec.get("kwargs", {}))
        for key, val in overrides.items():
            if key == "engine":
                kwargs["use_fast_interaction"] = \
                    _ENGINE_TO_KWARG.get(val, val)
            else:
                kwargs[key] = val
        out = fn(**kwargs)
        if isinstance(out, tuple):
            integ, template = out[0], out[1]
        else:
            integ, template = out, out.initialize()
        return integ, template
    raise ReplayError(
        f"capsule integrator spec kind={kind!r} is not replayable "
        f"(record an explicit factory spec on the FlightRecorder)")


def effective_engine(manifest: dict, overrides: dict | None) -> str | None:
    """The engine label the (possibly overridden) rebuild runs with —
    what engine-gated recorded injectors arm against."""
    overrides = overrides or {}
    if "engine" in overrides:
        return str(overrides["engine"])
    return manifest["fingerprint"].get("engine")


def state_from_capsule(manifest: dict, arrays: dict, template):
    """Rebuild the device pytree: capsule arrays are keyed by the
    checkpoint path convention in recorded ``leaf_order``."""
    import jax
    import jax.numpy as jnp

    from ibamr_tpu.utils.checkpoint import _path_str

    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    order = manifest["leaf_order"]
    keys = [_path_str(p) for p, _ in flat]
    if set(keys) != set(order):
        raise ReplayError(
            f"capsule/template leaf mismatch: capsule has "
            f"{sorted(set(order) - set(keys))} extra, template has "
            f"{sorted(set(keys) - set(order))} extra")
    return jax.tree_util.tree_unflatten(
        treedef, [jnp.asarray(arrays[k]) for k in keys])


# ---------------------------------------------------------------------------
# chunk execution + failure classification
# ---------------------------------------------------------------------------

def rebuild_mesh(mesh_spec: dict):
    """The recorded device mesh, rebuilt on THIS process's devices —
    same axis shape and names, devices in id order (the shard-index
    convention of ``checkpoint_sharded``). Raises :class:`ReplayError`
    when fewer devices are available than the incident ran on."""
    import jax
    from jax.sharding import Mesh

    shape = tuple(int(s) for s in mesh_spec["shape"])
    need = int(np.prod(shape))
    devs = sorted(jax.devices(), key=lambda d: d.id)
    if len(devs) < need:
        raise ReplayError(
            f"capsule was recorded on a {shape} mesh ({need} devices); "
            f"only {len(devs)} available")
    names = mesh_spec.get("axis_names") or \
        [f"ax{i}" for i in range(len(shape))]
    return Mesh(np.array(devs[:need]).reshape(shape), tuple(names))


def execute_chunk(integ, state, dt: float, length: int, step_wrap=None,
                  step_fn=None):
    """Re-execute the failing chunk: the same jitted
    ``lax.scan(step, ...)`` the driver compiled, minus the cadence
    machinery. ``step_fn`` substitutes a prebuilt step (the sharded
    one) for ``integ.step``. Returns the post-chunk state."""
    import jax

    step = integ.step if step_fn is None else step_fn
    if step_wrap is not None:
        step = step_wrap(step)

    @jax.jit
    def chunk(s, dt_):
        def body(x, _):
            return step(x, dt_), None

        out, _ = jax.lax.scan(body, s, None, length=length)
        return out

    return chunk(state, dt)


def execute_lane_chunk(integ, state, dt: float, length: int,
                       step_wrap=None):
    """Re-execute a LANE capsule's chunk as a B=1 fleet chunk: vmapped
    step, per-lane dt vector, lane-alive freeze mask — the same program
    shape :meth:`HierarchyDriver._build_fleet_chunk` compiles, which is
    the bitwise solo reference for any lane of any fleet (the
    batch-size-invariance contract in ``ibamr_tpu.utils.lanes``). The
    classic unbatched scan is NOT used here: it fuses differently and
    drifts by ULPs from the fleet execution the digest was recorded
    from. ``step_wrap`` (re-armed lane injectors, already transformed
    to lane 0 of a size-1 fleet) wraps the STACKED step."""
    import jax
    import jax.numpy as jnp

    stacked = jax.tree_util.tree_map(lambda l: jnp.asarray(l)[None],
                                     state)
    vstep = jax.vmap(integ.step, in_axes=(0, 0))
    if step_wrap is not None:
        vstep = step_wrap(vstep)

    @jax.jit
    def chunk(s, d, alive):
        def body(x, _):
            new = vstep(x, d)
            frozen = jax.tree_util.tree_map(
                lambda nl, ol: jnp.where(
                    alive.reshape((1,) + (1,) * (nl.ndim - 1)), nl, ol),
                new, x)
            return frozen, None

        out, _ = jax.lax.scan(body, s, None, length=length)
        return out

    out = chunk(stacked, jnp.asarray([dt]), jnp.ones(1, dtype=bool))
    return jax.tree_util.tree_map(lambda l: l[0], out)


def digest_state(post_state) -> dict:
    from ibamr_tpu.utils.checkpoint import _gather_arrays, _leaf_crc

    arrays = _gather_arrays(post_state)
    return {k: _leaf_crc(v) for k, v in arrays.items()}


def _all_finite(state) -> bool:
    import jax
    import jax.numpy as jnp

    for leaf in jax.tree_util.tree_leaves(state):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                     jnp.floating):
            if not bool(jnp.all(jnp.isfinite(leaf))):
                return False
    return True


def chunk_failed(manifest: dict, integ, post_state, dt: float) -> bool:
    """Did THIS execution exhibit the recorded failure? Kind-specific:
    non-finite leaves for divergence-family incidents; a recomputed
    shadow audit breach for ``precision_drift`` (the state itself is
    finite in that family)."""
    kind = (manifest.get("incident") or {}).get("kind", "divergence")
    finite = _all_finite(post_state)
    if not finite:
        return True
    if kind == "precision_drift":
        from ibamr_tpu.solvers.escalation import (PrecisionDrift,
                                                  ShadowAuditor)

        audit = manifest["fingerprint"].get("audit") or {}
        aud = ShadowAuditor(every=1, bound=audit.get("bound", 0.02),
                            div_bound=audit.get("div_bound"))
        try:
            aud.audit(integ, post_state, dt,
                      step=manifest["chunk"]["start_step"]
                      + manifest["chunk"]["length"])
        except PrecisionDrift:
            return True
        return False
    return False


# ---------------------------------------------------------------------------
# the replay entry point
# ---------------------------------------------------------------------------

def _x64_scope(manifest):
    """Execute under the RECORDED x64 mode. A capsule recorded by a
    standalone run (x64 off) replayed inside the test harness (x64 on)
    would trace its np-derived constants at f64 instead of f32 — a
    different computation, so the bitwise pin fails for a reason that
    has nothing to do with the incident. Old capsules without the flag
    replay under the current mode."""
    import contextlib

    import jax

    rec = manifest["fingerprint"].get("x64")
    if rec is None or bool(rec) == bool(jax.config.jax_enable_x64):
        return contextlib.nullcontext()
    from jax.experimental import disable_x64, enable_x64
    return enable_x64() if rec else disable_x64()


def _run_once(manifest, arrays, overrides, dt_scale, sharded=False):
    import jax

    from tools.fault_injection import apply_recorded_injectors

    injectors = dict(manifest["fingerprint"].get("injectors") or {})
    engine = effective_engine(manifest, overrides)
    lane_rec = manifest.get("lane")
    if lane_rec is not None and sharded:
        raise ReplayError("lane capsules replay unbatched (B=1); "
                          "--sharded does not apply")
    # engine-gated faults arm only when the effective engine matches
    armed = {}
    for name, params in injectors.items():
        if name == "engine_nan":
            p = dict(params)
            gate = p.pop("engine", None)
            if gate is not None and engine is not None \
                    and _norm_engine(gate) != _norm_engine(engine):
                continue
            armed["nan"] = p
        elif name in ("lane_nan", "lane_drift") and lane_rec is not None:
            # lane capsule: a fault aimed at THIS lane re-arms onto
            # lane 0 of the B=1 replay fleet; a fault aimed at any
            # OTHER lane could never fire here and is dropped
            p = dict(params)
            if int(p.get("lane", -1)) != int(lane_rec["index"]):
                continue
            p["lane"] = 0
            p["fleet_size"] = 1
            armed[name] = p
        else:
            armed[name] = params
    with apply_recorded_injectors(armed) as wrap, _x64_scope(manifest):
        # patched module functions must reach the trace: executables
        # compiled before the patch would replay the CLEAN computation
        jax.clear_caches()
        integ, template = rebuild(manifest, overrides)
        state = state_from_capsule(manifest, arrays, template)
        dt = float(manifest["chunk"]["dt"]) * float(dt_scale)
        if lane_rec is not None:
            post = execute_lane_chunk(integ, state, dt,
                                      int(manifest["chunk"]["length"]),
                                      step_wrap=wrap)
        else:
            step_fn = None
            if sharded:
                # re-execute the SAME sharded program the incident ran:
                # rebuild the recorded mesh, re-place the capsule state
                # under the spatial sharding, and scan the sharded step
                from ibamr_tpu.parallel.mesh import (make_sharded_step,
                                                     place_state)
                mesh = rebuild_mesh(manifest["fingerprint"]["mesh"])
                state = place_state(state, integ.grid, mesh)
                step_fn = make_sharded_step(integ, mesh)
            post = execute_chunk(integ, state, dt,
                                 int(manifest["chunk"]["length"]),
                                 step_wrap=wrap, step_fn=step_fn)
        crcs = digest_state(post)
        failed = chunk_failed(manifest, integ, post, dt)
    return {"leaf_crcs": crcs, "failed": failed,
            "finite": _all_finite(post)}


def _norm_engine(label) -> str:
    try:
        from ibamr_tpu.ops.interaction_packed import normalize_engine_name
        return normalize_engine_name(label)
    except Exception:
        return str(label).lower()


def replay(capsule_dir: str, overrides: dict | None = None,
           dt_scale: float = 1.0, sharded: bool = False) -> dict:
    """Full replay: baseline bitwise pin, optional substitution run,
    structured verdict. See the module docstring for the verdict
    vocabulary.

    ``sharded=True`` re-executes on the RECORDED mesh (the fingerprint
    carries the mesh spec of a sharded run). When fewer devices are
    available than the incident ran on, the replay degrades to the
    single-device program with ``mesh_degraded: true`` and the bitwise
    pin relaxes to the failure-reproduction pin — a cross-mesh digest
    mismatch there says nothing about the incident. The DEFAULT
    (``sharded=False``) replays any capsule on one device: capsule
    arrays are unsharded host copies, the cross-mesh guarantee."""
    manifest, arrays = load_capsule(capsule_dir)
    recorded_post = manifest.get("post")
    mesh_spec = (manifest.get("fingerprint") or {}).get("mesh")
    mesh_degraded = False
    use_sharded = False
    if sharded:
        if not mesh_spec or int(mesh_spec.get("n_shards", 1)) <= 1:
            raise ReplayError(
                "sharded replay requested but the capsule records no "
                "multi-device mesh (was the run supervised with "
                "ResilientDriver(sharded=True, mesh=...)?)")
        import jax
        need = int(np.prod([int(s) for s in mesh_spec["shape"]]))
        if jax.device_count() >= need:
            use_sharded = True
        else:
            mesh_degraded = True

    base = _run_once(manifest, arrays, overrides=None, dt_scale=1.0,
                     sharded=use_sharded)
    if recorded_post and recorded_post.get("leaf_crcs"):
        bitwise = base["leaf_crcs"] == {
            k: int(v) for k, v in recorded_post["leaf_crcs"].items()}
        if not bitwise and mesh_degraded:
            # the recorded digest belongs to the sharded program we
            # could not rebuild — pin failure reproduction instead
            bitwise = base["failed"]
    else:
        # no recorded digest (e.g. a stall capsule): fall back to the
        # weaker failure-reproduction pin
        bitwise = base["failed"]

    result = {
        "capsule": os.path.abspath(capsule_dir),
        "kind": (manifest.get("incident") or {}).get("kind"),
        "bitwise": bool(bitwise),
        "baseline_failed": bool(base["failed"]),
        "override": dict(overrides) if overrides else None,
        "dt_scale": float(dt_scale),
        "override_failed": None,
        "dt_dependent": None,
        "recorded_mesh": mesh_spec,
        "sharded_replay": use_sharded,
        "mesh_degraded": mesh_degraded,
    }
    has_sub = bool(overrides) or dt_scale != 1.0
    if has_sub:
        sub = _run_once(manifest, arrays, overrides=overrides,
                        dt_scale=dt_scale, sharded=use_sharded)
        result["override_failed"] = bool(sub["failed"])

    if not bitwise:
        verdict = "not_reproduced"
    elif not has_sub:
        verdict = "reproduced" if base["failed"] else "not_reproduced"
    elif result["override_failed"]:
        verdict = "reproduced"
    elif overrides and "spectral_dtype" in overrides:
        verdict = "precision_dependent"
    elif overrides and "engine" in overrides:
        verdict = "engine_dependent"
    else:
        verdict = "reproduced"
        result["dt_dependent"] = True
    result["verdict"] = verdict
    from ibamr_tpu import obs as _obs
    _obs.counter("replay_verdicts_total", verdict=verdict).inc()
    _obs.emit("replay", verdict=verdict, step=result.get("step"),
              override_failed=result.get("override_failed"))
    return result


def newest_capsule(root: str) -> str | None:
    """Newest ``incidents/<step>`` capsule dir under a checkpoint root
    (or an incidents dir itself). Used by relay_watch to attach a replay
    pointer when it kills a stalled bench."""
    cand = root
    if os.path.isdir(os.path.join(root, "incidents")):
        cand = os.path.join(root, "incidents")
    if not os.path.isdir(cand):
        return None
    caps = [os.path.join(cand, d) for d in sorted(os.listdir(cand))
            if os.path.exists(os.path.join(cand, d, "manifest.json"))]
    return caps[-1] if caps else None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="re-execute an incident replay capsule, bitwise-"
                    "pinned against its recorded post-chunk digest")
    ap.add_argument("capsule", help="incidents/<step> capsule directory")
    ap.add_argument("--override", action="append", default=[],
                    metavar="KEY=VALUE",
                    help="substitute one knob (engine=…, "
                         "spectral_dtype=…, or a factory kwarg)")
    ap.add_argument("--dt-scale", type=float, default=1.0,
                    help="re-run the chunk at dt * SCALE")
    ap.add_argument("--sharded", action="store_true",
                    help="re-execute on the capsule's recorded device "
                         "mesh (degrades to 1 device with a "
                         "failure-reproduction pin when fewer devices "
                         "are available)")
    ap.add_argument("--json", action="store_true",
                    help="print the full result dict as JSON")
    args = ap.parse_args(argv)

    overrides = {}
    for item in args.override:
        if "=" not in item:
            ap.error(f"--override {item!r}: expected KEY=VALUE")
        key, val = item.split("=", 1)
        overrides[key.strip()] = val.strip()

    result = replay(args.capsule, overrides=overrides or None,
                    dt_scale=args.dt_scale, sharded=args.sharded)
    if args.json:
        print(json.dumps(result, indent=1))
    else:
        print(f"verdict: {result['verdict']} "
              f"(bitwise={result['bitwise']}, "
              f"baseline_failed={result['baseline_failed']}, "
              f"override_failed={result['override_failed']})")
    return 0 if result["verdict"] != "not_reproduced" else 3


if __name__ == "__main__":
    raise SystemExit(main())
