"""Device-profile attribution, roofline, and drift gate (PR 10).

The operator's side of ``ibamr_tpu/obs/deviceprof.py``:

- ``attribute``: parse the trace-viewer JSON inside one
  ``jax.profiler`` capture dir, attribute device-lane op time to span
  paths (joining a run ledger's recorded spans when given), and land
  ``prof_summary.json`` next to the capture.
- ``show``: render a summary (span table, residual, roofline) without
  re-parsing the multi-MB trace.
- ``check``: validate a ``prof_summary.json`` against the schema —
  exit 2 on malformation, so automation (``relay_watch``) archives
  garbage loudly instead of silently.
- ``diff``: compare two attributed summaries — capture dirs, summary
  files, or the summaries EMBEDDED in two bench JSONs — per span path
  with tolerance bands, exiting like ``tools/graph_audit.py``:
  0 within band, 1 improved beyond band, 2 regressed beyond band.
  ``--comm-tol-pct`` arms a dedicated, tighter gate on the ``comm_s``
  op-class alone (PR 16) — the fleet-mesh legs' health line — which
  is advisory (printed, never enforced) on CPU captures.
- ``archive``: the relay_watch step — attribute if needed, validate,
  and only then prune the raw multi-MB profiler outputs, keeping the
  compact summary; a malformed summary exits 2 and prunes nothing.

Examples::

    python tools/prof.py attribute /tmp/prof/n256_ab12cd3 \
        --ledger /tmp/fleet
    python tools/prof.py show /tmp/prof/n256_ab12cd3
    python tools/prof.py diff BENCH_r06.json BENCH_r07.json
    python tools/prof.py diff /tmp/prof/a /tmp/prof/b --tol-pct 30
    python tools/prof.py archive /tmp/prof/n256_ab12cd3

All offline and host-side: no jax import, no backend, usable on a
laptop against a capture scp'd off the pod.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from ibamr_tpu.obs import deviceprof  # noqa: E402
from ibamr_tpu.obs.roofline import render_roofline  # noqa: E402

# drift bands (mirroring graph_audit's clean/improved/regressed): a
# span drifts only when BOTH the relative band and the absolute floor
# are exceeded — CPU captures jitter by whole percents on sub-ms spans,
# and the floor keeps that noise from paging anyone
DEFAULT_TOL_PCT = 25.0
DEFAULT_ABS_FLOOR_S = 200e-6


def _fmt_s(v) -> str:
    if v is None:
        return "-"
    v = float(v)
    if v >= 1.0:
        return f"{v:.3f}s"
    if v >= 1e-3:
        return f"{v * 1e3:.3f}ms"
    return f"{v * 1e6:.1f}us"


# ---------------------------------------------------------------------------
# attribute / show / check
# ---------------------------------------------------------------------------

def _parse_module_map(spec: str) -> dict:
    out = {}
    for part in (spec or "").split(","):
        if "=" in part:
            k, v = part.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def render_summary(summary: dict) -> list:
    total = summary.get("total_device_s") or 0.0
    frac = summary.get("fraction_attributed")
    lines = [
        f"device time: {_fmt_s(total)} across "
        f"{summary.get('trace_files', '?')} trace file(s), "
        f"{len(summary.get('lanes') or [])} lane(s)",
        f"attributed:  {_fmt_s(summary.get('attributed_s'))} "
        f"({100.0 * frac:.1f}%)" if frac is not None else "attributed: -",
        "",
        "per-span device time:",
    ]
    spans = summary.get("spans") or {}
    width = max([len(p) for p in spans] + [20]) + 2
    for path in sorted(spans,
                       key=lambda p: -(spans[p].get("device_s") or 0)):
        node = spans[path]
        dv = node.get("device_s") or 0.0
        pct = 100.0 * dv / total if total else 0.0
        via = ",".join(sorted(node.get("via") or ()))
        lines.append(f"  {path:<{width}} {_fmt_s(dv):>10} {pct:6.1f}%"
                     f"   x{node.get('events', '?'):<6} {via}")
    unatt = summary.get("unattributed") or {}
    lines.append(f"residual (unattributed: "
                 f"{_fmt_s(summary.get('unattributed_s'))}):")
    for name in sorted(unatt, key=lambda k: -unatt[k]):
        lines.append(f"  {name:<{width}} {_fmt_s(unatt[name]):>10}")
    if not unatt:
        lines.append("  (none)")
    lines.append("roofline:")
    lines.extend(render_roofline(summary.get("roofline")))
    return lines


def cmd_attribute(args) -> int:
    summary = deviceprof.attribute_capture(
        args.capture_dir,
        span_paths=args.span or (),
        module_map=_parse_module_map(args.module_map),
        ledger=args.ledger or None)
    probs = deviceprof.validate_summary(summary)
    if probs:
        for p in probs:
            print(f"[prof] INVALID: {p}", file=sys.stderr)
        return 2
    path = deviceprof.write_summary(args.capture_dir, summary)
    if args.ledger:
        _ledger_device_record(args.ledger, summary)
    if args.json:
        print(json.dumps(deviceprof.compact_summary(summary), indent=1,
                         sort_keys=True))
    else:
        print(f"wrote {path}")
        for ln in render_summary(summary):
            print(ln)
    return 0


def _ledger_device_record(ledger: str, summary: dict) -> None:
    """Append the per-span device-time table to the run ledger as a
    ``device_time`` record — the ledger's device column. Appended
    directly (one ``os.write`` on an ``O_APPEND`` fd, continuing the
    run's ``seq`` and ``run_id``) rather than through ``RunLedger``,
    whose constructor stamps a fresh ``run_start`` — post-hoc
    attribution is part of the SAME run, not a new one."""
    import time

    from ibamr_tpu.obs.bus import read_ledger

    if os.path.isdir(ledger):
        ledger = os.path.join(ledger, "ledger.jsonl")
    records = read_ledger(ledger)
    seq = max((r["seq"] for r in records), default=-1) + 1
    run_id = next((r.get("run_id") for r in records
                   if r.get("run_id")), None)
    rec = {
        "seq": seq, "run_id": run_id, "t": round(time.time(), 6),
        "kind": "device_time",
        "capture_dir": summary.get("capture_dir"),
        "total_device_s": summary.get("total_device_s"),
        "attributed_s": summary.get("attributed_s"),
        "unattributed_s": summary.get("unattributed_s"),
        "fraction_attributed": summary.get("fraction_attributed"),
        "spans": {k: (v.get("device_s") if isinstance(v, dict) else v)
                  for k, v in (summary.get("spans") or {}).items()},
        "op_classes": summary.get("op_classes"),
    }
    fd = os.open(ledger, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
    try:
        os.write(fd, (json.dumps(rec) + "\n").encode())
    finally:
        os.close(fd)


def cmd_show(args) -> int:
    summary = deviceprof.read_summary(args.path)
    probs = deviceprof.validate_summary(summary)
    for p in probs:
        print(f"[prof] WARNING: {p}", file=sys.stderr)
    print(f"summary: {deviceprof.summary_path(args.path)}")
    for ln in render_summary(summary):
        print(ln)
    return 0


def cmd_check(args) -> int:
    try:
        summary = deviceprof.read_summary(args.path)
    except (OSError, ValueError) as e:
        print(f"[prof] unreadable: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    probs = deviceprof.validate_summary(summary)
    if probs:
        for p in probs:
            print(f"[prof] INVALID: {p}", file=sys.stderr)
        return 2
    print(f"ok: {deviceprof.summary_path(args.path)}")
    return 0


# ---------------------------------------------------------------------------
# diff
# ---------------------------------------------------------------------------

def _bench_payload(path: str) -> dict:
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict) and isinstance(data.get("parsed"), dict):
        return data["parsed"]
    return data if isinstance(data, dict) else {}


def load_summaries(path: str) -> dict:
    """{label: summary} from a capture dir, a ``prof_summary.json``, or
    a bench JSON with embedded ``profiles[*].summary`` entries."""
    if os.path.isdir(path) or path.endswith(deviceprof.SUMMARY_NAME):
        s = deviceprof.read_summary(path)
        label = ((s.get("census") or {}).get("label")
                 or os.path.basename(os.path.normpath(
                     s.get("capture_dir") or path)))
        return {label: s}
    data = _bench_payload(path)
    if data.get("schema") == deviceprof.PROF_SCHEMA \
            and "total_device_s" in data:
        return {(data.get("census") or {}).get("label") or path: data}
    out = {}
    for entry in data.get("profiles") or []:
        if isinstance(entry, dict) and isinstance(entry.get("summary"),
                                                  dict):
            out[entry.get("stage")
                or (entry.get("summary").get("census") or {}).get("label")
                or entry.get("dir", "?")] = entry["summary"]
    return out


def _per_exec(summary: dict, seconds: float) -> float:
    execs = ((summary.get("roofline") or {}).get("executions")
             or (summary.get("census") or {}).get("executions") or 0)
    return seconds / execs if execs and execs > 0 else seconds


def _cpu_capture(summary: dict) -> bool:
    """True when the capture has no ``/device:*`` timeline process —
    a CPU (TFRT) trace, where XLA lowers every collective synchronously
    and ``comm_s`` measures the serialized copy, not overlap headroom.
    Unknown (no lanes recorded) counts as CPU: advisory beats a false
    page."""
    lanes = summary.get("lanes") or []
    return not any("/device:" in str(ln.get("process") or "")
                   for ln in lanes)


def diff_summaries(sa: dict, sb: dict, tol_pct: float,
                   floor_s: float, comm_tol_pct=None) -> tuple:
    """(report lines, verdict) for one pair — verdict in
    {"clean", "improved", "regressed"}. Times are normalized
    per-execution when both sides recorded execution counts, so a diff
    between a 40-step and an 80-step capture compares steps, not
    captures.

    ``comm_tol_pct`` arms the dedicated comm gate (PR 16): a tighter
    band on ``op_class/comm_s`` alone, because on the pod fleet comm
    time is the one class the overlap work is supposed to keep flat —
    a comm_s growth that stays inside the general band is exactly how
    a halo that quietly stopped overlapping would slip through. On CPU
    captures (no device timeline) the gate is ADVISORY: it prints, but
    never flips the verdict."""
    lines = []
    verdict = "clean"

    def judge(name, a, b):
        nonlocal verdict
        a, b = float(a or 0.0), float(b or 0.0)
        delta = b - a
        pct = 100.0 * delta / a if a > 0 else (100.0 if b > 0 else 0.0)
        mark = ""
        if abs(delta) > floor_s and abs(pct) > tol_pct:
            if delta > 0:
                mark = "  REGRESSED"
                verdict = "regressed"
            else:
                mark = "  improved"
                if verdict != "regressed":
                    verdict = "improved"
        lines.append(f"  {name:<38} {_fmt_s(a):>10} -> {_fmt_s(b):>10}"
                     f" {pct:+7.1f}%{mark}")

    judge("total_device", _per_exec(sa, sa.get("total_device_s") or 0),
          _per_exec(sb, sb.get("total_device_s") or 0))
    spa = {k: (v.get("device_s") if isinstance(v, dict) else v)
           for k, v in (sa.get("spans") or {}).items()}
    spb = {k: (v.get("device_s") if isinstance(v, dict) else v)
           for k, v in (sb.get("spans") or {}).items()}
    for path in sorted(set(spa) | set(spb)):
        judge(path, _per_exec(sa, spa.get(path) or 0.0),
              _per_exec(sb, spb.get(path) or 0.0))
    judge("unattributed",
          _per_exec(sa, sa.get("unattributed_s") or 0),
          _per_exec(sb, sb.get("unattributed_s") or 0))
    # op-class drift (PR 15): comm_s is the pod health line — a halo
    # that stopped overlapping or a new resharding shows up here even
    # when the owning span's total stays inside the band. other_s is a
    # remainder (total minus the named classes) so judging it would
    # double-report every named-class move.
    oca = sa.get("op_classes") or {}
    ocb = sb.get("op_classes") or {}
    for cls in sorted((set(oca) | set(ocb)) - {"other_s"}):
        judge(f"op_class/{cls}", _per_exec(sa, oca.get(cls) or 0.0),
              _per_exec(sb, ocb.get(cls) or 0.0))
    if comm_tol_pct is not None:
        ca = _per_exec(sa, float(oca.get("comm_s") or 0.0))
        cb = _per_exec(sb, float(ocb.get("comm_s") or 0.0))
        delta = cb - ca
        pct = 100.0 * delta / ca if ca > 0 else (100.0 if cb > 0
                                                 else 0.0)
        if delta > floor_s and pct > comm_tol_pct:
            cpu = _cpu_capture(sa) or _cpu_capture(sb)
            if cpu:
                lines.append(
                    f"  comm gate (>{comm_tol_pct:.0f}%): comm_s "
                    f"{_fmt_s(ca)} -> {_fmt_s(cb)} {pct:+.1f}% — "
                    f"ADVISORY (cpu capture: collectives lower "
                    f"synchronously, comm_s is not overlap headroom)")
            else:
                lines.append(
                    f"  comm gate (>{comm_tol_pct:.0f}%): comm_s "
                    f"{_fmt_s(ca)} -> {_fmt_s(cb)} {pct:+.1f}%"
                    f"  REGRESSED")
                verdict = "regressed"
        else:
            lines.append(f"  comm gate (>{comm_tol_pct:.0f}%): comm_s "
                         f"{_fmt_s(ca)} -> {_fmt_s(cb)} within band")
    return lines, verdict


def cmd_diff(args) -> int:
    try:
        a_map, b_map = load_summaries(args.a), load_summaries(args.b)
    except (OSError, ValueError) as e:
        print(f"[prof] cannot load summaries: {e}", file=sys.stderr)
        return 2
    for label, path in (("A", args.a), ("B", args.b)):
        m = a_map if label == "A" else b_map
        if not m:
            print(f"[prof] no attributed summaries in {label}: {path}"
                  " (run `prof.py attribute` first?)", file=sys.stderr)
            return 2
    print(f"A: {args.a}\nB: {args.b}   "
          f"(band: >{args.tol_pct:.0f}% and >{_fmt_s(args.abs_floor)})")
    worst = "clean"
    shared = sorted(set(a_map) & set(b_map))
    if not shared:
        print(f"[prof] no common stage labels: A={sorted(a_map)} "
              f"B={sorted(b_map)}", file=sys.stderr)
        return 2
    for label in shared:
        print(f"\nstage {label} (per-execution device time, A -> B):")
        lines, verdict = diff_summaries(
            a_map[label], b_map[label], args.tol_pct, args.abs_floor,
            comm_tol_pct=args.comm_tol_pct)
        for ln in lines:
            print(ln)
        if verdict == "regressed" or (verdict == "improved"
                                      and worst == "clean"):
            worst = verdict
    only = sorted(set(a_map) ^ set(b_map))
    if only:
        print(f"\n(unpaired stages ignored: {only})")
    print(f"\nverdict: {worst}")
    return {"clean": 0, "improved": 1, "regressed": 2}[worst]


# ---------------------------------------------------------------------------
# archive (relay_watch's fifth capture step)
# ---------------------------------------------------------------------------

def cmd_archive(args) -> int:
    spath = os.path.join(args.capture_dir, deviceprof.SUMMARY_NAME)
    if not os.path.exists(spath):
        summary = deviceprof.attribute_capture(
            args.capture_dir, ledger=args.ledger or None)
        probs = deviceprof.validate_summary(summary)
        if probs:
            for p in probs:
                print(f"[prof] INVALID: {p}", file=sys.stderr)
            print(f"[prof] refusing to archive {args.capture_dir}",
                  file=sys.stderr)
            return 2
        deviceprof.write_summary(args.capture_dir, summary)
    else:
        try:
            summary = deviceprof.read_summary(spath)
        except (OSError, ValueError) as e:
            print(f"[prof] unreadable summary: {e}", file=sys.stderr)
            return 2
        probs = deviceprof.validate_summary(summary)
        if probs:
            for p in probs:
                print(f"[prof] INVALID: {p}", file=sys.stderr)
            print(f"[prof] refusing to prune {args.capture_dir}",
                  file=sys.stderr)
            return 2
    freed = 0
    if not args.keep_raw:
        freed = deviceprof.prune_raw_traces(args.capture_dir)
    print(f"archived {args.capture_dir}: "
          f"{_fmt_s(summary.get('total_device_s'))} device, "
          f"{100.0 * (summary.get('fraction_attributed') or 0):.1f}% "
          f"attributed, {freed / 1e6:.1f} MB raw pruned")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="device-profile attribution / roofline / drift gate")
    sub = ap.add_subparsers(dest="cmd", required=True)

    a = sub.add_parser("attribute", help="parse a capture dir into "
                                         "prof_summary.json")
    a.add_argument("capture_dir")
    a.add_argument("--ledger", default="",
                   help="run ledger (.jsonl or its dir): contributes "
                        "span paths and receives the device_time record")
    a.add_argument("--span", action="append",
                   help="extra span path to attribute against "
                        "(repeatable)")
    a.add_argument("--module-map", default="",
                   help="hlo_module=span/path overrides, comma-sep")
    a.add_argument("--json", action="store_true",
                   help="print the compact summary as JSON")
    a.set_defaults(fn=cmd_attribute)

    s = sub.add_parser("show", help="render an existing summary")
    s.add_argument("path", help="capture dir or prof_summary.json")
    s.set_defaults(fn=cmd_show)

    k = sub.add_parser("check", help="schema-validate a summary "
                                     "(exit 2 when malformed)")
    k.add_argument("path")
    k.set_defaults(fn=cmd_check)

    d = sub.add_parser("diff", help="drift gate: 0 clean / 1 improved "
                                    "/ 2 regressed")
    d.add_argument("a", help="capture dir, prof_summary.json, or "
                             "bench JSON with embedded summaries")
    d.add_argument("b")
    d.add_argument("--tol-pct", type=float, default=DEFAULT_TOL_PCT)
    d.add_argument("--abs-floor", type=float, default=DEFAULT_ABS_FLOOR_S,
                   help="seconds; drift needs BOTH bands exceeded")
    d.add_argument("--comm-tol-pct", type=float, default=None,
                   metavar="PCT",
                   help="arm the dedicated comm gate (PR 16): regress "
                        "when op_class/comm_s alone grows more than "
                        "PCT%% (and the abs floor) — tighter than the "
                        "general band, because overlapped pipelines "
                        "are supposed to keep comm flat; advisory "
                        "(printed, not enforced) on CPU captures")
    d.set_defaults(fn=cmd_diff)

    r = sub.add_parser("archive", help="attribute + validate, then "
                                       "prune raw traces (exit 2 and "
                                       "keep raw when malformed)")
    r.add_argument("capture_dir")
    r.add_argument("--ledger", default="")
    r.add_argument("--keep-raw", action="store_true")
    r.set_defaults(fn=cmd_archive)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
