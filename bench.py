"""Headline benchmark: IB/explicit/ex4-equivalent 3D elastic shell.

Measures coupled IB timesteps/sec (interp -> force -> spread -> INS
projection solve -> correct) on the BASELINE.json north-star config:
256^3 grid, ~1e5 markers, IB_4 delta. Prints ONE JSON line (last line of
stdout); all progress goes to stderr.

Hardened per VERDICT.md round 1 (items 1-2 of "Next round"):
- backend init retries transient TPU-relay failures and falls back to
  CPU with a labelled ``platform`` field instead of crashing;
- sizes are staged (64^3 -> 128^3 -> 256^3) so a late-stage OOM/timeout
  still leaves a real number from the largest completed stage;
- a JSON line is ALWAYS emitted — on total failure it carries an
  ``error`` field;
- the MXU-bucketed and scatter/gather spread-interp paths are compared
  at a mid stage (``mxu_vs_scatter``).

``vs_baseline``: BASELINE.json ``published`` is empty and the reference
mount was empty at survey time (SURVEY.md §6) — no measured reference
denominator exists, so vs_baseline stays null.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import traceback


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def enable_compile_cache(jax) -> None:
    """Persistent XLA compilation cache: reruns and the staged ramp skip
    the 40-100 s flagship compiles (VERDICT round 2, weak #7). The
    wiring itself lives in the serving layer (one policy for bench and
    the warm-pool router, see docs/SERVING.md)."""
    try:
        from ibamr_tpu.serve.aot_cache import enable_persistent_cache
        enable_persistent_cache(jax)
    except Exception as e:
        log(f"[bench] compile cache unavailable: {e}")


def try_upgrade_to_tpu(probe_timeout: float = 45.0):
    """Between stages, see if the relay came back; if so re-init the
    accelerator in-process (VERDICT round 2, weak #1: a transient outage
    at t=0 must not forfeit the whole round's perf artifact).
    Returns (jax, platform, error); jax/platform are None when the
    accelerator is still unavailable."""
    import os

    from ibamr_tpu.utils.backend_guard import (probe_accelerator,
                                               restore_accelerator)

    probe_timeout = float(os.environ.get("IBAMR_BENCH_REPROBE_TIMEOUT",
                                         probe_timeout))
    plat, err = probe_accelerator(probe_timeout)
    if plat is None or plat == "cpu":
        return None, None, err
    jax, plat2 = restore_accelerator()
    if plat2 is None:
        return None, None, f"probe saw {plat} but in-process re-init failed"
    return jax, plat2, None


def _pallas_stage_child(q, n, n_lat, n_lon, steps, warmup, dt,
                        engine="pallas"):
    """Child-process body for a pallas compare leg."""
    try:
        from ibamr_tpu.utils.backend_guard import init_backend_with_retry

        jax, platform, err = init_backend_with_retry(retries=1,
                                                     delay=2.0)
        enable_compile_cache(jax)
        st = run_stage(jax, n, n_lat, n_lon, steps, warmup, dt,
                       use_fast=engine)
        st["platform"] = platform
        q.put(st)
    except Exception as e:  # noqa: BLE001 - report, parent decides
        q.put({"error": f"{type(e).__name__}: {e}"})


def _run_guarded_child(target, child_args, timeout_s: float,
                       hang_msg: str, died_what: str):
    """Run ``target(q, *child_args)`` in a TERMINABLE spawn child and
    return its queued dict, {'error': hang_msg} on timeout, or
    {'error': ...} if the child died without reporting. Shared by every
    bench child (pallas legs, CPU sharded reference) so the guard
    policy cannot drift between them."""
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=target, args=(q, *child_args))
    p.start()
    p.join(timeout_s)
    if p.is_alive():
        p.terminate()
        p.join(10.0)
        return {"error": hang_msg}
    try:
        return q.get_nowait()
    except Exception:
        return {"error": f"{died_what} child died rc={p.exitcode}"}


def run_pallas_stage_guarded(n, n_lat, n_lon, steps, warmup, dt,
                             timeout_s: float, engine="pallas"):
    """Run a pallas stage in a TERMINABLE child: the relay's
    remote-compile service stalled on this kernel in round 2, and an
    in-process hang would forfeit the whole bench artifact. Returns the
    stage dict or {'error': ...}."""
    return _run_guarded_child(
        _pallas_stage_child, (n, n_lat, n_lon, steps, warmup, dt, engine),
        timeout_s,
        f"pallas stage hung > {timeout_s:.0f}s (remote-compile stall?)",
        "pallas")


def _cpu_sharded_child(q, n, n_lat, n_lon, steps, warmup, dt,
                       n_devices):
    """Child body: time the FLAGSHIP sharded step on an n_devices
    virtual host-CPU mesh (VERDICT round 3 item 8 — the
    relay-independent regression signal)."""
    try:
        from ibamr_tpu.utils.backend_guard import force_cpu

        jax = force_cpu(n_devices)
        enable_compile_cache(jax)
        import time as _t

        from ibamr_tpu.models.shell3d import build_shell_example
        from ibamr_tpu.parallel import make_mesh, make_sharded_ib_step
        from ibamr_tpu.parallel.mesh import place_state

        integ, state0 = build_shell_example(
            n_cells=n, n_lat=n_lat, n_lon=n_lon, radius=0.25,
            aspect=1.2, stiffness=1.0, rest_length_factor=0.75,
            mu=0.05)

        def timed(step_fn, state):
            t0 = _t.perf_counter()
            for _ in range(warmup):
                state = step_fn(state, dt)
            jax.block_until_ready(state)
            compile_s = _t.perf_counter() - t0
            t0 = _t.perf_counter()
            for _ in range(steps):
                state = step_fn(state, dt)
            jax.block_until_ready(state)
            return _t.perf_counter() - t0, compile_s

        mesh = make_mesh(n_devices)
        state = place_state(state0, integ.ins.grid, mesh)
        el_sh, compile_s = timed(make_sharded_ib_step(integ, mesh),
                                 state)
        # single-device leg of the same step: the only scaling signal
        # available without multi-chip hardware (VERDICT round 3 weak
        # #4 — "no scaling measurement exists anywhere"). Virtual CPU
        # devices share the host's cores, so the ratio reads as an
        # SPMD-overhead bound, not real chip scaling; it still catches
        # a sharded-path regression that the single-device number hides
        el_1, _ = timed(jax.jit(lambda s, d: integ.step(s, d)), state0)
        q.put({"n": n, "n_devices": n_devices,
               "markers": n_lat * n_lon,
               "steps_per_sec": round(steps / el_sh, 3),
               "ms_per_step": round(1e3 * el_sh / steps, 3),
               "single_device_steps_per_sec": round(steps / el_1, 3),
               # >1 means the sharded step is FASTER than single-device
               # (a speedup, renamed from 'sharded_over_single' whose
               # name read as the inverse ratio — ADVICE round 4)
               "sharded_speedup": round(el_1 / el_sh, 3),
               "compile_warmup_s": round(compile_s, 2)})
    except Exception as e:  # noqa: BLE001 - report, parent decides
        q.put({"error": f"{type(e).__name__}: {e}"})


def cpu_sharded_reference(timeout_s: float = 300.0, n: int = 32,
                          n_lat: int = 24, n_lon: int = 24,
                          steps: int = 10, warmup: int = 2,
                          dt: float = 5e-5, n_devices: int = 8):
    """Relay-INDEPENDENT perf signal (VERDICT round 3 item 8): the
    8-virtual-device sharded flagship step timed on the host CPU in a
    child process, emitted EVERY round regardless of the accelerator's
    health — so a stage regression stays visible across rounds whose
    TPU platform differs or whose relay is down. Small fixed shape
    (32^3, ~600 markers) keeps it a bounded smoke-timing, not a
    benchmark of the host."""
    return _run_guarded_child(
        _cpu_sharded_child,
        (n, n_lat, n_lon, steps, warmup, dt, n_devices), timeout_s,
        f"cpu sharded reference hung > {timeout_s:.0f}s", "cpu sharded")


def _fleet_child(q, B, n, n_lat, n_lon, steps, dt):
    """Child body: aggregate throughput of B ensemble lanes through ONE
    vmapped chunk vs the same lanes run one at a time (PR 7 fleet
    mode), on a single virtual CPU device so the signal is
    relay-independent like the sharded reference."""
    try:
        import sys as _sys
        _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from ibamr_tpu.utils.backend_guard import force_cpu

        jax = force_cpu(1)
        enable_compile_cache(jax)
        from ibamr_tpu.utils.hierarchy_driver import RunConfig
        from tools.fleet import build_fleet, run_fleet, run_sequential

        cfg = RunConfig(dt=dt, num_steps=steps, health_interval=4)
        integ, lane_states, stacked = build_fleet(
            n, n_lat, n_lon, 0.05, B, 0.01, None)
        summary, _ = run_fleet(integ, stacked, cfg, B)
        seq = run_sequential(integ, lane_states, cfg)
        out = {"lanes": B, "n": n, "markers": n_lat * n_lon,
               "steps": steps,
               "aggregate_steps_per_s":
                   summary["aggregate_steps_per_s"],
               "lanes_quarantined": summary["lanes_quarantined"],
               "sequential_steps_per_s":
                   seq["aggregate_steps_per_s"]}
        if seq["aggregate_steps_per_s"] > 0:
            out["fleet_speedup"] = round(
                summary["aggregate_steps_per_s"]
                / seq["aggregate_steps_per_s"], 3)
        q.put(out)
    except Exception as e:  # noqa: BLE001 - report, parent decides
        q.put({"error": f"{type(e).__name__}: {e}"})


def fleet_reference(B: int = 8, timeout_s: float = 600.0, n: int = 32,
                    n_lat: int = 16, n_lon: int = 16, steps: int = 8,
                    dt: float = 1e-3):
    """Vmapped-ensemble throughput signal (PR 7): B lanes of the small
    shell stepped as one lane-batched fleet vs sequentially, in a
    TERMINABLE child. Small fixed shape — a bounded smoke-timing whose
    quarantine count doubles as a fleet-health regression check (a
    healthy run must report 0)."""
    return _run_guarded_child(
        _fleet_child, (B, n, n_lat, n_lon, steps, dt), timeout_s,
        f"fleet leg hung > {timeout_s:.0f}s", "fleet")


def _fleet_mesh_child(q, Bs, n, n_lat, n_lon, steps, dt, n_devices):
    """Child body: the B×D pod-fleet leg (PR 16) — the lane axis of a
    B-lane fleet sharded over ``n_devices`` virtual CPU devices
    (``parallel.mesh.make_lane_mesh``), aggregate lane-steps/s per B.
    Relay-independent like the sharded reference; on a real pod the
    same call times ICI-resident lanes."""
    try:
        import sys as _sys
        _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from ibamr_tpu.utils.backend_guard import force_cpu

        jax = force_cpu(n_devices)
        enable_compile_cache(jax)
        from ibamr_tpu.parallel.mesh import make_lane_mesh
        from ibamr_tpu.utils.hierarchy_driver import RunConfig
        from tools.fleet import build_fleet, run_fleet

        mesh = make_lane_mesh(n_devices)
        cfg = RunConfig(dt=dt, num_steps=steps, health_interval=4)
        legs = []
        for B in Bs:
            integ, _, stacked = build_fleet(
                n, n_lat, n_lon, 0.05, B, 0.01, None)
            summary, _ = run_fleet(integ, stacked, cfg, B,
                                   lane_mesh=mesh)
            legs.append({
                "lanes": B,
                "lanes_per_device": B // n_devices,
                "aggregate_steps_per_s":
                    summary["aggregate_steps_per_s"],
                "lanes_quarantined": summary["lanes_quarantined"],
                "wall_s": summary["wall_s"]})
        q.put({"n": n, "markers": n_lat * n_lon, "steps": steps,
               "mesh_devices": n_devices, "legs": legs})
    except Exception as e:  # noqa: BLE001 - report, parent decides
        q.put({"error": f"{type(e).__name__}: {e}"})


def fleet_mesh_reference(Bs=(8, 64, 256), timeout_s: float = 900.0,
                         n: int = 16, n_lat: int = 8, n_lon: int = 16,
                         steps: int = 4, dt: float = 1e-3,
                         n_devices: int = 8):
    """Pod-fleet throughput signal (PR 16): aggregate lane-steps/s of
    B∈{8,64,256} lanes sharded over the 8-device lane mesh, in a
    TERMINABLE child. Small fixed shape — a bounded smoke-timing on
    CPU whose per-B trend (and 0-quarantine invariant) is what
    relay_watch trends across rounds; the next healthy TPU window
    times the same leg on real ICI."""
    return _run_guarded_child(
        _fleet_mesh_child,
        (tuple(Bs), n, n_lat, n_lon, steps, dt, n_devices), timeout_s,
        f"fleet-mesh leg hung > {timeout_s:.0f}s", "fleet-mesh")


def _serve_child(q, n, n_lat, n_lon, lanes, steps, dt, warm_requests):
    """Child body: the request-to-first-step latency drill — one
    scenario family served cold then warm through a fresh warm-pool
    router (ibamr_tpu/serve/router.py), on a single virtual CPU device
    so the signal is relay-independent like the sharded reference."""
    try:
        import sys as _sys
        _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from ibamr_tpu.utils.backend_guard import force_cpu

        jax = force_cpu(1)
        enable_compile_cache(jax)
        from ibamr_tpu.serve.router import cold_warm_drill

        q.put(cold_warm_drill(n_cells=n, n_lat=n_lat, n_lon=n_lon,
                              lanes=lanes, steps=steps, dt=dt,
                              warm_requests=warm_requests))
    except Exception as e:  # noqa: BLE001 - report, parent decides
        q.put({"error": f"{type(e).__name__}: {e}"})


def serve_reference(timeout_s: float = 300.0, n: int = 16,
                    n_lat: int = 8, n_lon: int = 16, lanes: int = 2,
                    steps: int = 3, dt: float = 5e-5,
                    warm_requests: int = 8):
    """Cold-vs-warm serving latency signal (PR 12): request-to-first-
    step latency of the warm-pool router, cold (bucket compiles on
    miss) vs warm (AOT cache hit), in a TERMINABLE child. The same
    drill that SERVE_CONTRACT.json pins structurally
    (``tools/serve.py check``); here it rides the bench artifact so the
    cold/warm ratio is trended across rounds. ``warm_requests`` extra
    warm serves (PR 14) give the drill's ``warm_p50_s``/``warm_p99_s``
    histogram percentiles a real sample, and the per-key histogram
    snapshot rides the artifact for ``tools/obs.py compare``."""
    return _run_guarded_child(
        _serve_child, (n, n_lat, n_lon, lanes, steps, dt,
                       warm_requests), timeout_s,
        f"serve leg hung > {timeout_s:.0f}s", "serve")


def _tune_child(q, n, n_lat, n_lon, reps):
    """Child body: a small measured autotuner grid (ibamr_tpu/tune/)
    on a single virtual CPU device — scatter vs packed across both
    spectral dtypes, trials compiled through the AOT cache."""
    try:
        import sys as _sys
        _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from ibamr_tpu.utils.backend_guard import force_cpu

        jax = force_cpu(1)
        enable_compile_cache(jax)
        from ibamr_tpu.tune.runner import search

        res = search(n_cells=n, n_lat=n_lat, n_lon=n_lon,
                     engines=("scatter", "packed"),
                     spectral_dtypes=("f32", "bf16"),
                     chunk_lengths=(1,), reps=reps, probe=False)
        q.put(res.to_dict())
    except Exception as e:  # noqa: BLE001 - report, parent decides
        q.put({"error": f"{type(e).__name__}: {e}"})


def tune_reference(timeout_s: float = 300.0, n: int = 16,
                   n_lat: int = 8, n_lon: int = 16, reps: int = 2):
    """Measured engine-search signal (PR 13): the autotuner's small
    CPU grid in a TERMINABLE child. Trends the measured ranking and
    margins across rounds next to the serve leg; the full on-chip
    search + DB publication rides tools/relay_watch.py instead."""
    return _run_guarded_child(
        _tune_child, (n, n_lat, n_lon, reps), timeout_s,
        f"tune leg hung > {timeout_s:.0f}s", "tune")


def _soak_child(q, rates, durations, seed, burst):
    """Child body: the open-loop soak grid — one pre-warmed router
    and executable cache SHARED across the rate x duration cells (the
    grid measures traffic handling, not recompilation), seeded
    Poisson + burst arrivals over the heavy-tailed mix on a single
    virtual CPU device."""
    try:
        import sys as _sys
        _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from ibamr_tpu.utils.backend_guard import force_cpu

        jax = force_cpu(1)
        enable_compile_cache(jax)
        from ibamr_tpu.serve import aot_cache
        from ibamr_tpu.serve.loadgen import SOAK_POLICIES, soak_drill
        from ibamr_tpu.serve.router import BucketSpec, WarmPoolRouter

        spec = BucketSpec(n_cells=8, n_lat=6, n_lon=8, lanes=2,
                          chunk_steps=2)
        router = WarmPoolRouter([spec],
                                cache=aot_cache.ExecutableCache(),
                                allow_dynamic=True,
                                policies=dict(SOAK_POLICIES))
        router.warm(spec)
        cells = []
        for rate in rates:
            for dur in durations:
                out = soak_drill(seed=seed, duration_s=dur,
                                 rate_rps=rate, burst_factor=burst,
                                 time_scale=0.5, router=router)
                cells.append({
                    "rate_rps": rate, "duration_s": dur,
                    "arrivals": out["arrivals"],
                    "requests_per_s": out["requests_per_s"],
                    "shed_rate": out["shed_rate"],
                    "warm_first_step_p99_s":
                        out["warm_first_step_p99_s"],
                    "queue_wait_p99_s": out["queue_wait_p99_s"],
                    "hung_threads": out["hung_threads"]})
        q.put({"seed": seed, "burst_factor": burst, "grid": cells})
    except Exception as e:  # noqa: BLE001 - report, parent decides
        q.put({"error": f"{type(e).__name__}: {e}"})


def soak_reference(timeout_s: float = 300.0,
                   rates=(4.0, 8.0), durations=(4.0,),
                   seed: int = 0, burst: float = 4.0):
    """Sustained-traffic signal (PR 17): the open-loop Poisson+burst
    soak over an arrival-rate x duration grid in a TERMINABLE child —
    requests/s, shed rate, and warm/queue-wait p99 per cell land in
    the round artifact so traffic capacity is trended across rounds
    next to the single-request serve leg. The chaos-injected variant
    lives in ``tools.fault_injection.run_soak_smoke`` (dryrun path
    21); this leg is the clean-path capacity number."""
    return _run_guarded_child(
        _soak_child, (tuple(rates), tuple(durations), seed, burst),
        timeout_s, f"soak leg hung > {timeout_s:.0f}s", "soak")


def _elastic_child(q, duration_s, rate_rps, shift_frac):
    """Child body: the elastic warm-pool drill (mix shift + memory
    pressure + crash-safe restart) on a single virtual CPU device;
    the drill's own pinned invariants raise inside the child and
    surface as the leg's error string."""
    try:
        import sys as _sys
        _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from ibamr_tpu.utils.backend_guard import force_cpu

        force_cpu(1)
        from tools.fault_injection import run_elastic_smoke

        out = run_elastic_smoke(duration_s=duration_s,
                                rate_rps=rate_rps,
                                shift_frac=shift_frac)
        q.put({"duration_s": duration_s, "rate_rps": rate_rps,
               "shift_frac": shift_frac,
               "scale_up_s": out["scale_up_s"],
               "restart_warm_s": out["restart_warm_s"],
               "restart_fresh_compiles":
                   out["restart_fresh_compiles"],
               "mode_transitions": out["mode_transitions"],
               "grows": out["grows"], "shrinks": out["shrinks"],
               "shed": out["shed"], "lost": out["lost"],
               "predicted_rps": out["predicted_rps"],
               "measured_rps": out["measured_rps"]})
    except Exception as e:  # noqa: BLE001 - report, parent decides
        q.put({"error": f"{type(e).__name__}: {e}"})


def elastic_reference(timeout_s: float = 300.0,
                      duration_s: float = 5.0, rate_rps: float = 8.0,
                      shift_frac: float = 0.4):
    """Elasticity signal (PR 18): scale-up latency, restart-to-warm
    time, fresh restart compiles (must stay 0), and the capacity
    model's predicted-vs-measured rps from the elastic warm-pool
    drill in a TERMINABLE child — trended across rounds next to the
    soak leg so a scaling or restart regression shows up as a number,
    not an incident."""
    return _run_guarded_child(
        _elastic_child, (duration_s, rate_rps, shift_frac),
        timeout_s, f"elastic leg hung > {timeout_s:.0f}s", "elastic")


def _assim_child(q, fleet_sizes, cycles):
    """Child body: the CLEAN assimilation cadence (no injectors) on a
    single virtual CPU device — one twin-experiment miniature, then
    for each ensemble size B a full supervised observe->analyze->
    advance run with an attached ledger, reporting the analysis wall
    (first cycle pays the AOT compile; steady state is the recurring
    bill) against the chunk cadence and cycles/s. The chaos-injected
    variant lives in ``tools.fault_injection.run_assim_smoke``; this
    leg is the clean-path cost number."""
    try:
        import sys as _sys
        _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from ibamr_tpu.utils.backend_guard import force_cpu

        force_cpu(1)
        import tempfile as _tempfile

        import jax
        if not jax.config.jax_enable_x64:
            jax.config.update("jax_enable_x64", True)
        import jax.numpy as jnp

        from ibamr_tpu import obs as _obs
        from ibamr_tpu.assim import (AssimConfig, AssimilationCycle,
                                     ObservationOperator,
                                     synthesize_batches)
        from ibamr_tpu.instruments import InstrumentPanel, make_meters
        from ibamr_tpu.models.shell3d import build_shell_example
        from ibamr_tpu.serve.aot_cache import ExecutableCache
        from ibamr_tpu.utils.health import HealthProbe
        from ibamr_tpu.utils.lanes import stack_lanes

        spc, dt0, n_lon = 2, 1e-3, 16
        integ, st0 = build_shell_example(n_cells=16, n_lat=8,
                                         n_lon=n_lon, mu=0.05,
                                         dtype="float64")
        loops = [[2 * n_lon + j for j in range(n_lon)],
                 [5 * n_lon + j for j in range(n_lon)]]
        panel = InstrumentPanel(integ.ins.grid,
                                make_meters(loops, closed=True,
                                            dtype=jnp.float64))
        op = ObservationOperator(panel)
        st, truth = st0, []
        for _ in range(cycles):
            for _ in range(spc):
                st = integ.step(st, dt0)
            truth.append(st)
        batches = synthesize_batches(op, truth, sigma=1e-5, seed=3)

        legs = []
        for B in fleet_sizes:
            fleet0 = stack_lanes([st0._replace(ins=st0.ins._replace(
                u=tuple(c + 2e-3 * (i + 1) for c in st0.ins.u)))
                for i in range(B)])
            cyc = AssimilationCycle(
                integ, op, B,
                AssimConfig(steps_per_cycle=spc, dt=dt0),
                probe=HealthProbe.for_integrator(integ),
                cache=ExecutableCache())
            with _tempfile.TemporaryDirectory(
                    prefix="bench-assim-") as td:
                lp = os.path.join(td, "ledger.jsonl")
                t0 = time.perf_counter()
                with _obs.ledger(lp):
                    cyc.run(fleet0, batches, directory=td,
                            max_retries=1)
                wall = time.perf_counter() - t0
                recs = list(_obs.read_ledger(lp))
            walls = [r["analysis_wall_s"] for r in recs
                     if r.get("kind") == "assim_cycle"
                     and not r.get("skipped")
                     and r.get("analysis_wall_s") is not None]
            steady = walls[1:] or walls
            legs.append({
                "lanes": B, "cycles": len(walls),
                "analysis_wall_first_s": round(walls[0], 4),
                "analysis_wall_steady_s": round(
                    sum(steady) / len(steady), 4),
                "analysis_fraction": round(sum(walls) / wall, 4),
                "cycles_per_s": round(len(walls) / wall, 4),
                "wall_s": round(wall, 3)})
        q.put({"steps_per_cycle": spc, "legs": legs})
    except Exception as e:  # noqa: BLE001 - report, parent decides
        q.put({"error": f"{type(e).__name__}: {e}"})


def assim_reference(timeout_s: float = 420.0,
                    fleet_sizes=(8, 64), cycles: int = 3):
    """Forecasting-cadence signal (PR 20): per-cycle analysis wall
    against the advance cadence and cycles/s for a small and a large
    ensemble from the clean assimilation run in a TERMINABLE child —
    trended across rounds next to the soak/elastic/grad legs so a
    regression in the between-chunk analysis cost (an accidental
    retrace, a host sync creeping into the gain computation) shows up
    as a number, not an incident."""
    return _run_guarded_child(
        _assim_child, (tuple(fleet_sizes), cycles), timeout_s,
        f"assim leg hung > {timeout_s:.0f}s", "assim")


def _grad_child(q, n, reps):
    """Child body: the gradient microbench (PR 19) on a single
    virtual CPU device — primal-vs-VJP wall time and the FFT /
    scatter / f64-widening census for the fused substep, the packed
    transfers, and the whole coupled step."""
    try:
        import sys as _sys
        _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from ibamr_tpu.utils.backend_guard import force_cpu

        force_cpu(1)
        from tools.microbench_grad import run as grad_run

        out = grad_run(n=n, reps=reps, quiet=True)
        keep = {"n", "backend"}
        for piece in ("substep", "spread", "interp", "step"):
            keep.update({f"{piece}_primal_ms", f"{piece}_vjp_ms",
                         f"{piece}_grad_ratio",
                         f"{piece}_primal_fft_ops",
                         f"{piece}_vjp_fft_ops",
                         f"{piece}_vjp_scatter_prims"})
        slim = {k: (round(v, 3) if isinstance(v, float) else v)
                for k, v in out.items() if k in keep}
        # the VJP graph replays the primal forward (overflow-fallback
        # scatters included); the pinned claim is that the REVERSE
        # sweep adds none on the spread path, so report the delta
        slim["spread_vjp_scatter_added"] = (
            out.get("spread_vjp_scatter_prims", 0)
            - out.get("spread_primal_scatter_prims", 0))
        slim["f64_widenings_total"] = sum(
            v for k, v in out.items() if k.endswith("f64_widenings"))
        q.put(slim)
    except Exception as e:  # noqa: BLE001 - report, parent decides
        q.put({"error": f"{type(e).__name__}: {e}"})


def grad_reference(timeout_s: float = 300.0, n: int = 24,
                   reps: int = 3):
    """Adjoint-cost signal (PR 19): VJP-vs-primal wall ratio plus the
    batched-FFT and scatter counts per differentiable piece from the
    gradient microbench in a TERMINABLE child — trended across rounds
    so a reverse-pass cost regression (an extra transpose FFT, a
    scatter sneaking into the spread adjoint, an f64 widening) shows
    up as a number next to the forward flagship legs. The full-size
    on-chip capture rides tools/relay_watch.py at 256^3."""
    return _run_guarded_child(
        _grad_child, (n, reps), timeout_s,
        f"grad leg hung > {timeout_s:.0f}s", "grad")


def cpu_sharded_reference_with_trend(n_devices: int = 8):
    """The n=32 smoke leg PLUS a larger n=48 leg, with the
    speedup-vs-size trend (round 5, VERDICT round 4 weak #3: the
    sub-1 ratio needed an explanation, not just a number). On ONE
    physical host core, 8 virtual devices add partitioner-inserted
    reshard/collective passes over field-scale data, so the sharded
    step can never beat single-device here; the RISING two-leg trend
    shows the overhead is a CONSTANT-FACTOR cost that amortizes as
    per-step compute grows — a fixed tax, not a scaling defect. (The
    offline three-point sweep in PERF.md measured 0.17 -> 0.33 ->
    0.38 at n = 32, 48, 64; the in-bench artifact carries the 32/48
    pair to stay inside the deadline.) On real multi-chip hardware
    the same pins become ICI collectives and the ratio crosses 1; the
    equality tests pin correctness either way."""
    leg32 = cpu_sharded_reference(timeout_s=420.0, n=32, n_lat=24,
                                  n_lon=24, steps=6,
                                  n_devices=n_devices)
    out = dict(leg32)
    leg48 = cpu_sharded_reference(timeout_s=900.0, n=48, n_lat=32,
                                  n_lon=32, steps=6,
                                  n_devices=n_devices)
    out["legs"] = [leg32, leg48]
    s32 = leg32.get("sharded_speedup")
    s48 = leg48.get("sharded_speedup")
    if s32 is not None and s48 is not None:
        out["speedup_trend_32_to_48"] = round(s48 - s32, 3)
        out["trend_note"] = (
            "virtual devices share one host core: <1 is expected; "
            "the RISING trend with n shows constant-factor SPMD "
            "overhead amortizing, not a scaling defect")
    return out


def git_short_rev() -> str:
    """The repo's short commit hash (``norev`` outside git): profile
    captures are named ``<stage>_<rev>`` so two revisions' traces of
    the same stage sit side by side in one TensorBoard logdir."""
    try:
        import subprocess
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__))).stdout
        return out.strip() or "norev"
    except Exception:
        return "norev"


def stage_profile_dir(args, label: str, rev: str,
                      used=None) -> str:
    """Capture dir for one stage under ``--profile-stages``, or ``""``
    (no capture). ``--profile-stages`` is a comma-separated list of
    fnmatch globs over stage labels — ramp stages are ``n<size>``
    (``n256``), flagship legs their engine label (``packed*``).

    ``used`` (a per-run dict the caller owns) de-collides repeated
    labels: two stages sharing a label under the same rev used to get
    the SAME dir, interleaving their traces into one unusable capture
    — now the repeat gets a ``_2``/``_3`` suffix and a warning."""
    import fnmatch
    if not args.profile or not args.profile_stages:
        return ""
    pats = [p.strip() for p in args.profile_stages.split(",")
            if p.strip()]
    if not any(fnmatch.fnmatch(label, p) for p in pats):
        return ""
    d = os.path.join(args.profile, f"{label}_{rev}")
    if used is not None:
        n = used.get(d, 0) + 1
        used[d] = n
        if n > 1:
            log(f"[bench] profile label {label!r} repeats under rev "
                f"{rev}; capturing into {label}_{rev}_{n} instead")
            d = f"{d}_{n}"
    return d


def run_engine_leg(jax, label, engine, n, n_lat, n_lon, args, t_start,
                   platform, profile_dir=None):
    """One transfer-engine leg at size ``n``: pallas engines run in a
    TERMINABLE child with a deadline-derived budget (remote-compile
    stall history) and must land on the parent's platform; the rest
    run in-process. Shared by the flagship shootout and the mid-size
    compare so the guard policy cannot drift between them.
    ``profile_dir`` arms the in-stage device capture (pallas/hybrid
    children excepted: the profiler is per-process and the child owns
    the step there)."""
    if label == "fluid_bf16":
        # mixed-precision FLUID leg: the best non-pallas transfer
        # engine (packed_bf16) plus bf16/split-real spectral
        # transforms — the round-6 lever aimed at the fluid_solve
        # floor itself
        return run_stage(jax, n, n_lat, n_lon, args.steps, args.warmup,
                         args.dt, use_fast="packed_bf16",
                         spectral_dtype="bf16", profile_dir=profile_dir,
                         profile_stage=label)
    if label.startswith(("pallas", "hybrid")):
        # guarded child: these engines contain Pallas programs (the
        # relay's remote-compile service stalled on one in round 2)
        budget = max(60.0, min(600.0, args.deadline
                               - (time.perf_counter() - t_start)))
        st = run_pallas_stage_guarded(n, n_lat, n_lon, args.steps,
                                      args.warmup, args.dt, budget,
                                      engine=engine)
        if "error" in st:
            raise RuntimeError(st["error"])
        if st.get("platform") != platform:
            # a relay drop mid-run must not record a CPU-interpreter
            # number beside compiled-TPU entries
            raise RuntimeError(f"{label} leg ran on "
                               f"{st.get('platform')!r}, parent on "
                               f"{platform!r}")
        return st
    return run_stage(jax, n, n_lat, n_lon, args.steps, args.warmup,
                     args.dt, use_fast=engine, profile_dir=profile_dir,
                     profile_stage=label)


def phase_breakdown(jax, integ, state, dt: float, iters: int = 10) -> dict:
    """Per-phase ms/step on the current device: bucket prep (+ the
    half-step slot-preserving refresh when the engine has one), interp,
    force, spread, fluid solve — the TimerManager-style table SURVEY §6
    asks for. ``bucket_prep_per_step`` records how many full preps the
    midpoint step actually pays (1 with refresh, 2 without). Each phase is jitted standalone; the sum differs from the
    fused step (XLA fuses across phases there), so the table names the
    dominant phase rather than reconstructing the exact step time."""
    import time as _t

    grid = integ.ins.grid
    ib = integ.ib
    mask = state.mask
    out = {}

    def timeit(name, fn, *args):
        res = fn(*args)
        jax.block_until_ready(res)  # compile + warm
        t0 = _t.perf_counter()
        for _ in range(iters):
            res = fn(*args)
        jax.block_until_ready(res)
        out[name] = round(1e3 * (_t.perf_counter() - t0) / iters, 3)
        return res

    ctx = None
    if getattr(ib, "fast", None) is not None:
        ctx = timeit("bucket_prep",
                     jax.jit(lambda X: ib.prepare(X, mask)), state.X)
        refresh = getattr(ib, "refresh", None)
        refreshes = (refresh is not None
                     and refresh(ctx, state.X, mask)[0] is not None)
        if refreshes:
            # slot-preserving half-step refresh: with it the midpoint
            # step pays bucket_prep ONCE per step (plus this cheaper
            # re-gather); without it, twice
            timeit("bucket_refresh",
                   jax.jit(lambda c, X: refresh(c, X, mask)[0]),
                   ctx, state.X)
        out["bucket_prep_per_step"] = 1 if refreshes else 2
    U = timeit("interp",
               jax.jit(lambda u, X, c: ib.interpolate_velocity(
                   u, grid, X, mask, ctx=c)),
               state.ins.u, state.X, ctx)
    F = timeit("force",
               jax.jit(lambda X, U: ib.compute_force(X, U, 0.0)),
               state.X, U)
    f = timeit("spread",
               jax.jit(lambda F, X, c: ib.spread_force(
                   F, grid, X, mask, ctx=c)),
               F, state.X, ctx)
    timeit("fluid_solve",
           jax.jit(lambda s, f: integ.ins.step(s, dt, f=f)),
           state.ins, f)
    if getattr(integ.ins, "fused_stokes", None) is not None:
        # spectral decomposition of the fluid substep: transform cost
        # (the batched rfftn/irfftn pair) vs the diagonal k-space
        # algebra between them — names WHICH half of the fluid floor
        # the next lever must attack (transform-bound means only
        # precision/sharding moves it; algebra-bound means fusion does)
        from ibamr_tpu.solvers import spectral_plan

        jnp_ = jax.numpy
        dim = len(grid.n)
        axes = tuple(range(1, dim + 1))
        plan = spectral_plan.get_plan(grid.n, grid.dx, integ.ins.dtype)
        alpha = integ.ins.rho / dt
        beta = -0.5 * integ.ins.mu
        spec = {}

        def timeit_s(name, fn, *a):
            res = fn(*a)
            jax.block_until_ready(res)
            t0 = _t.perf_counter()
            for _ in range(iters):
                res = fn(*a)
            jax.block_until_ready(res)
            spec[name] = round(1e3 * (_t.perf_counter() - t0) / iters, 3)
            return res

        x = jnp_.stack(state.ins.u)
        uh = timeit_s("fwd_transform",
                      jax.jit(lambda x: jnp_.fft.rfftn(x, axes=axes)), x)
        outh = timeit_s("kspace_algebra",
                        jax.jit(lambda uh: plan.kspace_algebra(
                            uh, alpha, beta, (alpha, beta))), uh)
        timeit_s("inv_transform",
                 jax.jit(lambda oh: jnp_.fft.irfftn(
                     oh, s=grid.n, axes=axes)), outh)
        spec["transform_ms"] = round(spec["fwd_transform"]
                                     + spec["inv_transform"], 3)
        out["spectral"] = spec
    out["dominant"] = max(
        (k for k in out
         if k not in ("dominant", "bucket_prep_per_step", "spectral")),
        key=lambda k: out[k])
    return out


def run_stage(jax, n: int, n_lat: int, n_lon: int, steps: int,
              warmup: int, dt: float, use_fast=None,
              fast_opts=None, spectral_dtype=None,
              record_dir=None, profile_dir=None,
              profile_stage=None) -> dict:
    """Build the shell config at one grid size and time the jitted step.
    ``fast_opts=(tile, cap)`` overrides the MXU engine geometry (the
    cap/tile sweep); ``spectral_dtype="bf16"`` opts the fluid substep
    into the mixed-precision transform path. ``record_dir`` arms a
    flight recorder on the stage: the pre-run state is snapshotted
    (host-side, before donation can invalidate it) and a non-finite
    finish dumps a ``record_dir/incidents`` replay capsule carrying the
    exact factory spec — ``tools/replay.py`` rebuilds the stage from it
    offline (docs/RESILIENCE.md).

    ``profile_dir`` captures a device profile of the MEASURED loop
    only — the capture starts after compile+warmup, because the
    trace-viewer JSON export caps at 1e6 events and a multi-second
    XLA compile floods it with python-tracer events, truncating the
    device-op events attribution needs (measured: an 8 s in-capture
    compile left 25 op events of a 4-step run). The capture also gets
    the ``census_counts.json`` roofline sidecar: the PR-8 byte/flop
    census of one step jaxpr plus the exact number of step launches
    captured, so ``tools/prof.py`` can turn attributed seconds into
    achieved GB/s — traced while the step function is still in hand
    (trace only, no extra compile)."""
    from ibamr_tpu.models.shell3d import build_shell_example

    integ, state = build_shell_example(
        n_cells=n, n_lat=n_lat, n_lon=n_lon,
        radius=0.25, aspect=1.2, stiffness=1.0, rest_length_factor=0.75,
        mu=0.05, use_fast_interaction=use_fast,
        spectral_dtype=spectral_dtype)
    recorder = None
    if record_dir:
        from ibamr_tpu.utils.flight_recorder import (FlightRecorder,
                                                     factory_spec)
        recorder = FlightRecorder(capacity=1, spec=factory_spec(
            "ibamr_tpu.models.shell3d", "build_shell_example",
            n_cells=n, n_lat=n_lat, n_lon=n_lon, radius=0.25,
            aspect=1.2, stiffness=1.0, rest_length_factor=0.75,
            mu=0.05, use_fast_interaction=use_fast,
            spectral_dtype=spectral_dtype))
        recorder.snapshot(state, step=0, dt=dt, length=warmup + steps,
                          integ=integ)
    if fast_opts is not None:
        from ibamr_tpu.ops.interaction_fast import FastInteraction
        tile, cap = fast_opts
        integ.ib.fast = FastInteraction(
            integ.ins.grid, kernel=integ.ib.kernel, tile=tile, cap=cap,
            overflow_cap=max(2048, state.X.shape[0] // 4))

    # donate the state: the step rewrites every field, so reusing the
    # input buffers saves one full state allocation per step (~0.5 GB
    # of HBM traffic at 256^3). step_with_stats rides the refresh_hit
    # flag out beside the state (None when the engine has no
    # slot-preserving half-step refresh). The executable comes through
    # the AOT cache (one compile per fingerprint+aval family, shared
    # with the warm-pool router); fast_opts changes constants baked
    # into the graph without changing input avals, so it must be in
    # the key. The raw python callable stays in hand for the census
    # (a Compiled executable cannot be re-traced).
    from ibamr_tpu.serve import aot_cache

    cache_before = aot_cache.executable_cache_stats()
    t_aot = time.perf_counter()
    step, _entry = aot_cache.cached_step(
        integ, state, dt, donate=True, with_stats=True,
        extra={"fast_opts": list(fast_opts) if fast_opts else None},
        label=f"bench:n{n}")
    aot_s = time.perf_counter() - t_aot
    cache_after = aot_cache.executable_cache_stats()
    step_raw, _dn = aot_cache.step_callable(integ, donate=True,
                                            with_stats=True)

    def hard_sync(s):
        # block_until_ready proved unreliable over the axon relay after
        # a compile-helper restart (round 3: a 256^3 stage "measured"
        # 12055 steps/s); a device_get round-trip of a state leaf is a
        # true barrier.
        jax.device_get(s.X[0])

    from ibamr_tpu.utils.timers import profile_trace

    def timed_run(capture_dir=""):
        nonlocal state
        t_c0 = time.perf_counter()
        for _ in range(max(warmup, 1)):
            state, _ = step(state, dt)
        hard_sync(state)
        compile_s = time.perf_counter() - t_c0

        # accumulate refresh hits as a device scalar (no per-step sync;
        # a host round-trip per step would poison the timing); the
        # profile capture brackets EXACTLY these `steps` launches (the
        # census sidecar's executions count) — trace start/stop sit
        # outside the timed window
        hit_acc = None
        elapsed = 0.0
        with profile_trace(capture_dir, stage=profile_stage):
            t0 = time.perf_counter()
            for _ in range(steps):
                state, st_stats = step(state, dt)
                rh = st_stats.get("refresh_hit")
                if rh is not None:
                    rh = rh.astype(jax.numpy.int32)
                    hit_acc = rh if hit_acc is None else hit_acc + rh
            hard_sync(state)
            elapsed = time.perf_counter() - t0
        if hit_acc is not None:
            hit_acc = int(jax.device_get(hit_acc))
        return compile_s, elapsed, hit_acc

    compile_s, elapsed, refresh_hits = timed_run(
        capture_dir=profile_dir or "")
    # plausibility floor: one 256^3 step streams >1 GB of HBM; anything
    # under 1 ms/step at n>=128 is a relay timing artifact -> remeasure
    # (without re-capturing: the profiler session already closed)
    if n >= 128 and (elapsed / steps) * 1e3 < 1.0:
        log(f"[bench] n={n}: implausible {elapsed / steps * 1e3:.3f} "
            "ms/step; remeasuring once")
        _, elapsed, refresh_hits = timed_run()

    import numpy as np
    if not bool(np.isfinite(np.asarray(jax.device_get(state.X))).all()):
        err = FloatingPointError(f"non-finite marker state at n={n}")
        if recorder is not None:
            cap = recorder.dump_incident(
                directory=os.path.join(record_dir, "incidents"),
                kind="divergence")
            err.capsule = cap
            log(f"[bench] n={n} diverged; replay capsule: {cap}")
        raise err

    n_markers = int(state.X.shape[0])
    out = {
        "n": n,
        "markers": n_markers,
        "steps_per_sec": round(steps / elapsed, 4),
        "ms_per_step": round(1e3 * elapsed / steps, 3),
        "compile_warmup_s": round(compile_s + aot_s, 2),
        "cache_hits": cache_after["hits"] - cache_before["hits"],
        "cache_misses": cache_after["misses"] - cache_before["misses"],
        "fast_path": {True: "mxu", False: "scatter",
                      None: "auto"}.get(use_fast, use_fast),
    }
    if spectral_dtype is not None:
        out["spectral_dtype"] = str(spectral_dtype)
    if refresh_hits is not None:
        # slot-preserving half-step refresh bookkeeping: hits took the
        # cheap re-gather, falls paid a full re-pack (drift bound blown)
        out["refresh_hits"] = refresh_hits
        out["repack_falls"] = steps - refresh_hits
    if profile_dir:
        # roofline sidecar beside the capture; never let a census
        # hiccup (an exotic engine's trace failing) cost the stage
        try:
            from ibamr_tpu.obs import deviceprof
            from ibamr_tpu.obs.roofline import census_sidecar

            census = census_sidecar(
                lambda s: step_raw(s, dt)[0], (state,),
                label=profile_stage or f"n{n}",
                executions=steps, n=n, markers=n_markers)
            os.makedirs(profile_dir, exist_ok=True)
            with open(os.path.join(profile_dir,
                                   deviceprof.CENSUS_NAME), "w") as f:
                json.dump(census, f, indent=1, sort_keys=True)
        except Exception as e:  # noqa: BLE001
            log(f"[bench] census sidecar failed for n={n}: "
                f"{type(e).__name__}: {e}")
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=256, help="target cells/axis")
    ap.add_argument("--n-lat", type=int, default=316)
    ap.add_argument("--n-lon", type=int, default=316)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--dt", type=float, default=5e-5)
    ap.add_argument("--stages", type=str, default="64,128",
                    help="comma-separated ramp sizes run before --n")
    ap.add_argument("--compare-at", type=int, default=128,
                    help="grid size for the MXU-vs-scatter comparison "
                         "(0 disables)")
    ap.add_argument("--deadline", type=float, default=1500.0,
                    help="soft wall-clock budget (s); later stages are "
                         "skipped once exceeded")
    ap.add_argument("--sweep", action="store_true",
                    help="MXU tile/cap sweep at the comparison size")
    ap.add_argument("--profile", type=str, default="",
                    help="capture a jax device profile of the final "
                         "stage into this directory (TensorBoard/"
                         "Perfetto viewable)")
    ap.add_argument("--profile-stages", type=str, default="",
                    help="comma-separated fnmatch globs over stage "
                         "labels ('n256,packed*'); each matching ramp "
                         "stage (n<size>) or flagship leg captures its "
                         "device profile into <--profile>/<label>_"
                         "<gitrev>/ instead of only the final stage")
    ap.add_argument("--heartbeat", type=str, default="",
                    help="write a liveness heartbeat.json to this path "
                         "(or directory) so an external watcher can "
                         "tell a hung relay from a slow stage")
    ap.add_argument("--fleet", type=int, default=0,
                    help="also time a B-lane vmapped ensemble of the "
                         "small shell vs the same lanes sequentially "
                         "(0 disables)")
    ap.add_argument("--fleet-mesh", action="store_true",
                    help="also time the B x D pod fleet (PR 16): "
                         "B in {8,64,256} lanes sharded over an "
                         "8-device lane mesh, aggregate lane-steps/s "
                         "per B")
    ap.add_argument("--tune-grid", action="store_true",
                    help="also run the autotuner's small measured "
                         "engine grid (scatter vs packed x f32/bf16) "
                         "in a CPU child and trend the ranking")
    ap.add_argument("--soak", action="store_true",
                    help="also run the open-loop Poisson+burst soak "
                         "grid (arrival rate x duration) in a CPU "
                         "child and trend requests/s + shed rate")
    ap.add_argument("--elastic", action="store_true",
                    help="also run the elastic warm-pool drill (mix "
                         "shift + memory pressure + restart) in a "
                         "CPU child and trend scale-up/restart "
                         "latency")
    ap.add_argument("--grad", action="store_true",
                    help="also run the gradient microbench (primal vs "
                         "VJP wall + FFT/scatter census per piece) in "
                         "a CPU child and trend the adjoint ratios")
    ap.add_argument("--assim", action="store_true",
                    help="also run the clean assimilation cadence "
                         "(analysis wall vs chunk cadence, cycles/s "
                         "for a small and a large ensemble) in a CPU "
                         "child and trend the per-cycle analysis "
                         "cost")
    ap.add_argument("--record", type=str, default="",
                    help="arm a flight recorder on every ramp stage; a "
                         "diverged stage dumps a replay capsule under "
                         "this directory (tools/replay.py re-executes "
                         "it offline)")
    args = ap.parse_args()

    t_start = time.perf_counter()
    wd = None
    if args.heartbeat:
        from ibamr_tpu.utils.watchdog import RunWatchdog

        # generous floor: a remote-compile stall is minutes, a 256^3
        # XLA compile can legitimately be too — the watcher's kill
        # policy lives outside, this only keeps the file honest
        wd = RunWatchdog(heartbeat_path=args.heartbeat, interval_s=5.0,
                         stall_factor=4.0, min_stall_s=300.0,
                         on_stall=lambda rec: log(
                             f"[bench] WATCHDOG STALL: {rec}"))
        wd.start()
        wd.beat(step=0)
    result = {
        "metric": f"IB/explicit/ex4 3D shell {args.n}^3: timesteps/sec",
        "value": 0.0,
        "unit": "steps/s",
        "vs_baseline": None,
        "platform": None,
        "stages": [],
        "mxu_vs_scatter": None,
        "phases": None,
        "cpu_sharded_ref": None,
        "fleet": None,
        "fleet_mesh": None,
        "serve": None,
        "tune": None,
        "profiles": [],
        "error": None,
    }
    orig_steps, orig_deadline = args.steps, args.deadline
    profile_rev = git_short_rev() if args.profile_stages else "norev"
    profile_dirs_used = {}

    def profile_dir_for(label: str) -> str:
        d = stage_profile_dir(args, label, profile_rev,
                              used=profile_dirs_used)
        if d:
            # manifest entries are dicts since PR 10 (was: bare path
            # strings — tools/obs.py compare still reads those from
            # old bench JSONs); attribute_profile fills bytes/summary
            # once the capture closes
            result["profiles"].append(
                {"dir": d, "stage": label, "rev": profile_rev,
                 "bytes": None, "attributed": False})
        return d

    def attribute_profile(d: str) -> None:
        """Post-capture: record the capture's on-disk weight and
        attribute it in-process (offline parsing — a failure costs the
        summary, never the bench)."""
        if not d:
            return
        entry = next((e for e in result["profiles"]
                      if isinstance(e, dict) and e.get("dir") == d),
                     None)
        if entry is None:
            return
        try:
            from ibamr_tpu.obs import deviceprof

            entry["bytes"] = deviceprof.capture_bytes(d)
            if not deviceprof.find_trace_files(d):
                # a guarded-child leg (pallas) or failed stage leaves
                # the dir empty: say so instead of writing a vacuous
                # all-zero summary
                raise FileNotFoundError("no trace files captured")
            summary = deviceprof.attribute_capture(d)
            probs = deviceprof.validate_summary(summary)
            if probs:
                raise ValueError("; ".join(probs))
            deviceprof.write_summary(d, summary)
            entry["summary"] = deviceprof.compact_summary(summary)
            entry["attributed"] = True
        except Exception as e:  # noqa: BLE001
            entry["error"] = f"{type(e).__name__}: {e}"
            log(f"[bench] profile attribution failed for {d}: "
                f"{entry['error']}")

    try:
        from ibamr_tpu.utils.backend_guard import init_backend_with_retry

        jax, platform, backend_err = init_backend_with_retry(
            retries=3, delay=10.0)
        result["platform"] = platform
        if backend_err is not None:
            result["error"] = f"accelerator init failed: {backend_err}"
        log(f"[bench] platform={platform}")
        enable_compile_cache(jax)
        if platform == "cpu":
            # fallback exists to EMIT A LABELLED LINE, not to benchmark
            # the host: bound the wall clock well inside any driver
            # timeout so the JSON always lands
            args.deadline = min(args.deadline, 420.0)
            args.steps = min(args.steps, 5)

        sizes = [int(s) for s in args.stages.split(",") if s.strip()]
        sizes = sorted({s for s in sizes if s < args.n}) + [args.n]
        errors = []
        # no upgrade attempts when the CONTAINER pinned cpu (the guard
        # records the pre-force_cpu value; post-fallback env always
        # says cpu)
        from ibamr_tpu.utils.backend_guard import _ORIG_JAX_PLATFORMS
        reprobes_left = 0 if (_ORIG_JAX_PLATFORMS or "").strip().lower() \
            == "cpu" else 2
        for n in sizes:
            if time.perf_counter() - t_start > args.deadline:
                log(f"[bench] deadline exceeded, skipping n={n}")
                errors.append(f"n={n}: skipped (deadline)")
                continue
            if platform == "cpu" and reprobes_left > 0:
                # a transient relay outage at t=0 must not forfeit the
                # round's perf artifact: re-probe between stages and
                # upgrade mid-run if the relay healed (VERDICT r2 weak
                # #1). Bounded: the hang-wait costs up to 45 s against
                # the clamped 420 s CPU budget, so at most 2 attempts,
                # and none when CPU was explicitly requested.
                reprobes_left -= 1
                log("[bench] on cpu fallback: re-probing accelerator ...")
                upj, uplat, uerr = try_upgrade_to_tpu()
                if upj is not None:
                    jax = upj
                    platform = uplat
                    result["platform"] = platform
                    result["error"] = None
                    args.steps, args.deadline = orig_steps, orig_deadline
                    enable_compile_cache(jax)
                    log(f"[bench] accelerator recovered: {platform}")
                else:
                    log(f"[bench] accelerator still down: {uerr}")
            if platform == "cpu" and n > 64:
                # the CPU FALLBACK exists so a downed TPU relay still
                # yields a labelled number — big CPU stages (128^3+)
                # can blow the driver timeout mid-stage (the deadline
                # is only checked between stages; XLA compile alone is
                # minutes) and lose the whole artifact
                log(f"[bench] cpu fallback: skipping n={n}")
                errors.append(f"n={n}: skipped (cpu fallback)")
                continue
            # marker count scales with grid size toward the north-star
            # 316x316 (~1e5) lattice at 256^3
            frac = n / args.n
            n_lat = max(16, int(round(args.n_lat * frac)))
            n_lon = max(16, int(round(args.n_lon * frac)))
            try:
                log(f"[bench] stage n={n} markers~{n_lat * n_lon} ...")
                t_stage = time.perf_counter()
                pd = (profile_dir_for(f"n{n}") if args.profile_stages
                      else (args.profile if n == args.n else ""))
                # the ramp pins the BUCKETED-MXU engine: it has been
                # the staged baseline since round 1, and keeping it
                # preserves the longitudinal r1/r3/r5 comparison now
                # that the model's auto default is the (faster)
                # packed engine; the shootout below times the fast
                # engines at the target size. run_stage owns the
                # profile capture (measured loop only — see its doc).
                stage = run_stage(jax, n, n_lat, n_lon, args.steps,
                                  args.warmup, args.dt,
                                  use_fast=True,
                                  record_dir=(os.path.join(
                                      args.record, f"n{n}")
                                      if args.record else None),
                                  profile_dir=(pd or None),
                                  profile_stage=f"n{n}")
                attribute_profile(pd)
                log(f"[bench] stage n={n}: {stage['steps_per_sec']} "
                    "steps/s")
                if wd is not None:
                    wd.beat(step=len(result["stages"]) + 1,
                            last_chunk_wall_s=(time.perf_counter()
                                               - t_stage))
                stage["platform"] = platform  # stages can straddle a
                # mid-run CPU->TPU upgrade; label each measurement
                result["stages"].append(stage)
                result["metric"] = (
                    f"IB/explicit/ex4 3D shell {n}^3, "
                    f"{stage['markers']} markers: timesteps/sec")
                result["value"] = stage["steps_per_sec"]
            except Exception as e:  # keep earlier stages on late failure
                log(f"[bench] stage n={n} FAILED: {e}")
                errors.append(f"n={n}: {type(e).__name__}: {e}")

        if (platform != "cpu"
                and any(s["n"] == args.n for s in result["stages"])
                and time.perf_counter() - t_start <= args.deadline):
            # flagship engine shootout: the main stage ran the default
            # (auto = bucketed MXU); the packed engines target exactly
            # its dominant cost (the low-utilization weight operands —
            # PERF.md round-3 breakdown), so time them at the SAME size
            # and report the best configuration as the headline value.
            # Each leg is deadline-guarded; the pallas leg runs in a
            # terminable child (remote-compile stall history).
            for label in ("packed", "packed_bf16", "packed3",
                          "packed3_bf16", "pallas_packed",
                          "hybrid_bf16", "fluid_bf16"):
                if time.perf_counter() - t_start > args.deadline:
                    errors.append(f"flagship[{label}]: skipped "
                                  "(deadline)")
                    continue
                try:
                    t_leg = time.perf_counter()
                    pd = profile_dir_for(label)
                    st = run_engine_leg(jax, label, label, args.n,
                                        args.n_lat, args.n_lon,
                                        args, t_start, platform,
                                        profile_dir=(pd or None))
                    attribute_profile(pd)
                    st["platform"] = platform
                    log(f"[bench] flagship {label}: "
                        f"{st['steps_per_sec']} steps/s")
                    if wd is not None:
                        wd.beat(step=len(result["stages"]) + 1,
                                last_chunk_wall_s=(time.perf_counter()
                                                   - t_leg))
                    result["stages"].append(st)
                    if st["steps_per_sec"] > result["value"]:
                        result["value"] = st["steps_per_sec"]
                        result["metric"] = (
                            f"IB/explicit/ex4 3D shell {args.n}^3, "
                            f"{st['markers']} markers ({label} "
                            "transfers): timesteps/sec")
                except Exception as e:
                    errors.append(f"flagship[{label}]: "
                                  f"{type(e).__name__}: {e}")

        if args.compare_at and platform != "cpu" and any(
                s["n"] >= args.compare_at for s in result["stages"]):
            # (skipped on the CPU fallback: two more full stages would
            # triple the runtime and the transfer-engine question is a
            # TPU question)
            if time.perf_counter() - t_start <= args.deadline:
                try:
                    cn = args.compare_at
                    frac = cn / args.n
                    n_lat = max(16, int(round(args.n_lat * frac)))
                    n_lon = max(16, int(round(args.n_lon * frac)))
                    cmp = {}
                    # transfer-engine compare: scatter / MXU-bucketed /
                    # occupancy-packed / Pallas tile kernel /
                    # Pallas-packed / hybrid pallas-spread + bf16-interp
                    # (VERDICT round 2 item 5 + round 3 packed engines).
                    # A Pallas compile stall (the relay's remote-compile
                    # service choked on it in round 2) only loses that
                    # engine's entry.
                    for label, fast in (("mxu", True),
                                        ("scatter", False),
                                        ("packed", "packed"),
                                        ("packed3", "packed3"),
                                        ("pallas", "pallas"),
                                        ("pallas_packed",
                                         "pallas_packed"),
                                        ("hybrid_bf16",
                                         "hybrid_bf16")):
                        if time.perf_counter() - t_start > args.deadline:
                            errors.append(f"compare[{label}]: skipped "
                                          "(deadline)")
                            continue
                        try:
                            st = run_engine_leg(jax, label, fast, cn,
                                                n_lat, n_lon, args,
                                                t_start, platform)
                            cmp[label] = st["steps_per_sec"]
                            log(f"[bench] {label}@{cn}^3: "
                                f"{st['steps_per_sec']} steps/s")
                        except Exception as e:
                            cmp[label] = None
                            errors.append(f"compare[{label}]: "
                                          f"{type(e).__name__}: {e}")
                    cmp["n"] = cn
                    if cmp.get("mxu") and cmp.get("scatter"):
                        cmp["speedup"] = round(cmp["mxu"]
                                               / cmp["scatter"], 3)
                    result["mxu_vs_scatter"] = cmp

                    if args.sweep:
                        # MXU geometry sweep at the same size
                        sweep = []
                        for tile in (8, 16):
                            for cap in (256, 512, 1024):
                                if (time.perf_counter() - t_start
                                        > args.deadline):
                                    break
                                try:
                                    st = run_stage(
                                        jax, cn, n_lat, n_lon,
                                        args.steps, args.warmup,
                                        args.dt, use_fast=True,
                                        fast_opts=(tile, cap))
                                    sweep.append(
                                        {"tile": tile, "cap": cap,
                                         "steps_per_sec":
                                             st["steps_per_sec"]})
                                    log(f"[bench] mxu tile={tile} "
                                        f"cap={cap}: "
                                        f"{st['steps_per_sec']}")
                                except Exception as e:
                                    sweep.append(
                                        {"tile": tile, "cap": cap,
                                         "error": str(e)[:120]})
                        result["mxu_sweep"] = sweep
                except Exception as e:
                    errors.append(f"compare: {type(e).__name__}: {e}")

        if (platform != "cpu" and result["stages"]
                and time.perf_counter() - t_start <= args.deadline):
            # per-phase TimerManager-style table at the largest completed
            # size (SURVEY §6: name the dominant phase)
            try:
                bn = result["stages"][-1]["n"]
                frac = bn / args.n
                from ibamr_tpu.models.shell3d import build_shell_example

                integ, st = build_shell_example(
                    n_cells=bn,
                    n_lat=max(16, int(round(args.n_lat * frac))),
                    n_lon=max(16, int(round(args.n_lon * frac))),
                    radius=0.25, aspect=1.2, stiffness=1.0,
                    rest_length_factor=0.75, mu=0.05)
                result["phases"] = {"n": bn,
                                    **phase_breakdown(jax, integ, st,
                                                      args.dt)}
                log(f"[bench] phases@{bn}^3: {result['phases']}")
            except Exception as e:
                errors.append(f"phases: {type(e).__name__}: {e}")

        # relay-independent regression signal: ALWAYS emitted (child
        # process on the virtual CPU mesh), even when every TPU stage
        # above failed or was skipped — it is the only cross-round
        # comparable number when the relay is down
        try:
            # charged against the remaining deadline budget: the CPU
            # fallback's bounded-wall-clock guarantee (JSON always
            # lands inside the driver timeout) must survive this child
            remaining = args.deadline - (time.perf_counter() - t_start)
            if remaining < 30.0:
                result["cpu_sharded_ref"] = {
                    "error": "skipped (deadline exhausted)"}
            elif remaining > 1500.0:
                # room for the two-leg trend (round 5: the speedup
                # ratio gets its size trend, not just one number)
                result["cpu_sharded_ref"] = \
                    cpu_sharded_reference_with_trend()
            else:
                result["cpu_sharded_ref"] = cpu_sharded_reference(
                    timeout_s=min(300.0, remaining))
            log(f"[bench] cpu_sharded_ref: {result['cpu_sharded_ref']}")
        except Exception as e:
            result["cpu_sharded_ref"] = {"error": f"{type(e).__name__}: "
                                                  f"{e}"}

        if args.fleet:
            # ensemble-throughput leg (PR 7): like the sharded ref this
            # runs on a virtual CPU device in a child, so it lands in
            # every round's artifact regardless of the relay's health
            try:
                remaining = args.deadline - (time.perf_counter()
                                             - t_start)
                if remaining < 30.0:
                    result["fleet"] = {
                        "error": "skipped (deadline exhausted)"}
                else:
                    result["fleet"] = fleet_reference(
                        B=args.fleet, timeout_s=min(600.0, remaining))
                log(f"[bench] fleet: {result['fleet']}")
            except Exception as e:
                result["fleet"] = {"error": f"{type(e).__name__}: {e}"}

        if args.fleet_mesh:
            # pod-fleet leg (PR 16): the lane axis sharded over the
            # 8-device virtual lane mesh — B in {8,64,256} so the
            # aggregate lane-steps/s scaling curve (and the
            # zero-quarantine invariant) trends across rounds even
            # with the relay down
            try:
                remaining = args.deadline - (time.perf_counter()
                                             - t_start)
                if remaining < 30.0:
                    result["fleet_mesh"] = {
                        "error": "skipped (deadline exhausted)"}
                else:
                    result["fleet_mesh"] = fleet_mesh_reference(
                        timeout_s=min(900.0, remaining))
                log(f"[bench] fleet_mesh: {result['fleet_mesh']}")
            except Exception as e:
                result["fleet_mesh"] = {
                    "error": f"{type(e).__name__}: {e}"}

        # serving-latency leg: cold vs warm request-to-first-step
        # through the warm-pool router (PR 12). Like the sharded ref
        # this is a relay-independent CPU-child signal, so the
        # cold/warm ratio lands in every round's artifact
        try:
            remaining = args.deadline - (time.perf_counter() - t_start)
            if remaining < 30.0:
                result["serve"] = {
                    "error": "skipped (deadline exhausted)"}
            else:
                result["serve"] = serve_reference(
                    timeout_s=min(300.0, remaining))
            log("[bench] serve: " + str({
                k: v for k, v in (result["serve"] or {}).items()
                if k != "histograms"}))
        except Exception as e:
            result["serve"] = {"error": f"{type(e).__name__}: {e}"}

        # autotuner leg (PR 13): the measured scatter-vs-packed grid
        # in a CPU child, trending ranking + margin per round
        if args.tune_grid:
            try:
                remaining = (args.deadline
                             - (time.perf_counter() - t_start))
                if remaining < 30.0:
                    result["tune"] = {
                        "error": "skipped (deadline exhausted)"}
                else:
                    result["tune"] = tune_reference(
                        timeout_s=min(300.0, remaining))
                log(f"[bench] tune: {result['tune']}")
            except Exception as e:
                result["tune"] = {"error": f"{type(e).__name__}: {e}"}

        # sustained-traffic leg (PR 17): the open-loop soak grid in a
        # CPU child, trending requests/s + shed rate per round
        if args.soak:
            try:
                remaining = (args.deadline
                             - (time.perf_counter() - t_start))
                if remaining < 30.0:
                    result["soak"] = {
                        "error": "skipped (deadline exhausted)"}
                else:
                    result["soak"] = soak_reference(
                        timeout_s=min(300.0, remaining))
                log(f"[bench] soak: {result['soak']}")
            except Exception as e:
                result["soak"] = {"error": f"{type(e).__name__}: {e}"}

        # elasticity leg (PR 18): the mix-shift + restart drill in a
        # CPU child, trending scale-up/restart latency per round
        if args.elastic:
            try:
                remaining = (args.deadline
                             - (time.perf_counter() - t_start))
                if remaining < 30.0:
                    result["elastic"] = {
                        "error": "skipped (deadline exhausted)"}
                else:
                    result["elastic"] = elastic_reference(
                        timeout_s=min(300.0, remaining))
                log(f"[bench] elastic: {result['elastic']}")
            except Exception as e:
                result["elastic"] = {
                    "error": f"{type(e).__name__}: {e}"}

        # adjoint-cost leg (PR 19): primal-vs-VJP ratios + FFT/scatter
        # census in a CPU child, trending the reverse-pass price per
        # round (the "adjoint at primal cost" pins, measured)
        if args.grad:
            try:
                remaining = (args.deadline
                             - (time.perf_counter() - t_start))
                if remaining < 30.0:
                    result["grad"] = {
                        "error": "skipped (deadline exhausted)"}
                else:
                    result["grad"] = grad_reference(
                        timeout_s=min(300.0, remaining))
                log(f"[bench] grad: {result['grad']}")
            except Exception as e:
                result["grad"] = {"error": f"{type(e).__name__}: {e}"}

        # forecasting-cadence leg (PR 20): the clean assimilation run
        # in a CPU child, trending analysis wall + cycles/s per round
        if args.assim:
            try:
                remaining = (args.deadline
                             - (time.perf_counter() - t_start))
                if remaining < 30.0:
                    result["assim"] = {
                        "error": "skipped (deadline exhausted)"}
                else:
                    result["assim"] = assim_reference(
                        timeout_s=min(420.0, remaining))
                log(f"[bench] assim: {result['assim']}")
            except Exception as e:
                result["assim"] = {
                    "error": f"{type(e).__name__}: {e}"}

        if errors:
            msg = "; ".join(errors)
            result["error"] = (result["error"] + "; " + msg
                               if result["error"] else msg)
    except BaseException as e:
        result["error"] = (f"{type(e).__name__}: {e}\n"
                           + traceback.format_exc()[-1500:])

    if wd is not None:
        wd.beat(step=len(result["stages"]) + 1)   # final liveness mark
        wd.stop()
    if args.record:
        # incidents = real stage failures; replays = capsules on disk a
        # relay_watch/operator can hand straight to tools/replay.py
        import glob
        caps = sorted(os.path.dirname(m) for m in glob.glob(
            os.path.join(args.record, "**", "manifest.json"),
            recursive=True))
        result["incidents"] = len(
            [e for e in (result.get("error") or "").split("; ")
             if e and "skipped" not in e])
        result["replays"] = len(caps)
        result["replay_capsules"] = caps
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
