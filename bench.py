"""Headline benchmark: IB/explicit/ex4-equivalent 3D elastic shell.

Measures coupled IB timesteps/sec (interp -> force -> spread -> INS
projection solve -> correct) on the BASELINE.json north-star config:
256^3 grid, ~1e5 markers, IB_4 delta. Prints ONE JSON line.

`vs_baseline`: BASELINE.json `published` is empty and the reference mount
was empty at survey time (SURVEY.md §6) — no measured reference
denominator exists yet, so vs_baseline is null until one is produced.
"""

from __future__ import annotations

import argparse
import json
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=256, help="grid cells/axis")
    ap.add_argument("--n-lat", type=int, default=316)
    ap.add_argument("--n-lon", type=int, default=316)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--dt", type=float, default=5e-5)
    args = ap.parse_args()

    import jax
    from ibamr_tpu.models.shell3d import build_shell_example

    integ, state = build_shell_example(
        n_cells=args.n, n_lat=args.n_lat, n_lon=args.n_lon,
        radius=0.25, aspect=1.2, stiffness=1.0, rest_length_factor=0.75,
        mu=0.05)

    step = jax.jit(lambda s, dt: integ.step(s, dt))

    # compile + warmup
    for _ in range(max(args.warmup, 1)):
        state = step(state, args.dt)
    jax.block_until_ready(state)

    t0 = time.perf_counter()
    for _ in range(args.steps):
        state = step(state, args.dt)
    jax.block_until_ready(state)
    elapsed = time.perf_counter() - t0

    n_markers = int(state.X.shape[0])
    steps_per_sec = args.steps / elapsed
    print(json.dumps({
        "metric": (f"IB/explicit/ex4 3D shell {args.n}^3, "
                   f"{n_markers} markers: timesteps/sec"),
        "value": round(steps_per_sec, 4),
        "unit": "steps/s",
        "vs_baseline": None,
    }))


if __name__ == "__main__":
    main()
