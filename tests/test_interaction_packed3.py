"""Fully-blocked (z-tiled) occupancy-packed transfer engine + spill-
folding overlap-add (round 5, VERDICT item 2 — the structural attack on
the transfer roofline gap; see PERF_HLO.md for the measured reduction).
Same T2 semantics as every engine (LEInteractor::spread/interpolate,
SURVEY.md T2): exactness vs the scatter oracle, adjointness, overflow
fallback, bf16 twin tolerance.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import interaction
from ibamr_tpu.ops.interaction_packed3 import (PackedInteraction3,
                                               suggest_chunks3)

F64 = jnp.float64


def _markers(n, dim, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.rand(n, dim), dtype=F64)


@pytest.mark.parametrize("dim,n,kernel", [
    (2, 32, "IB_4"), (2, 32, "IB_3"), (2, 32, "IB_6"),
    (3, 24, "IB_4"), (3, 32, "IB_6"),
])
def test_matches_scatter_path(dim, n, kernel):
    grid = StaggeredGrid(n=(n,) * dim, x_lo=(0,) * dim, x_up=(1,) * dim)
    X = _markers(300, dim)
    rng = np.random.RandomState(1)
    F = jnp.asarray(rng.randn(300, dim), dtype=F64)
    mask = jnp.asarray((rng.rand(300) > 0.1).astype(np.float64),
                       dtype=F64)
    Q = suggest_chunks3(grid, X, kernel=kernel, tile=8, tile_last=8,
                        chunk=16)
    eng = PackedInteraction3(grid, kernel=kernel, tile=8, tile_last=8,
                             chunk=16, nchunks=Q)

    f_ref = interaction.spread_vel(F, grid, X, kernel=kernel,
                                   weights=mask)
    f_new = eng.spread_vel(F, X, weights=mask)
    for a, b in zip(f_ref, f_new):
        scale = float(jnp.max(jnp.abs(a))) + 1e-12
        assert float(jnp.max(jnp.abs(a - b))) < 1e-5 * scale

    u = tuple(jnp.asarray(rng.randn(*grid.n), dtype=F64)
              for _ in range(dim))
    U_ref = interaction.interpolate_vel(u, grid, X, kernel=kernel,
                                        weights=mask)
    U_new = eng.interpolate_vel(u, X, weights=mask)
    scale = float(jnp.max(jnp.abs(U_ref))) + 1e-12
    assert float(jnp.max(jnp.abs(U_ref - U_new))) < 1e-5 * scale


def test_unequal_tiles_per_axis():
    """The z axis takes its own tile extent (16 vs 8): exactness must
    hold with mixed tile sizes — the flagship configuration."""
    grid = StaggeredGrid(n=(24, 24, 32), x_lo=(0,) * 3, x_up=(1,) * 3)
    X = _markers(400, 3, seed=5)
    rng = np.random.RandomState(6)
    F = jnp.asarray(rng.randn(400, 3), dtype=F64)
    Q = suggest_chunks3(grid, X, tile=8, tile_last=16, chunk=32)
    eng = PackedInteraction3(grid, tile=8, tile_last=16, chunk=32,
                             nchunks=Q)
    f_ref = interaction.spread_vel(F, grid, X)
    f_new = eng.spread_vel(F, X)
    for a, b in zip(f_ref, f_new):
        scale = float(jnp.max(jnp.abs(a))) + 1e-12
        assert float(jnp.max(jnp.abs(a - b))) < 1e-5 * scale
    u = tuple(jnp.asarray(rng.randn(*grid.n), dtype=F64)
              for _ in range(3))
    U_ref = interaction.interpolate_vel(u, grid, X)
    U_new = eng.interpolate_vel(u, X)
    assert float(jnp.max(jnp.abs(U_ref - U_new))) < 1e-5 * (
        float(jnp.max(jnp.abs(U_ref))) + 1e-12)


def test_hot_tile_takes_many_chunks_no_overflow():
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    rng = np.random.RandomState(2)
    X = jnp.asarray(0.1 + 0.05 * rng.rand(200, 2), dtype=F64)
    F = jnp.asarray(rng.randn(200, 2), dtype=F64)
    eng = PackedInteraction3(grid, tile=8, tile_last=8, chunk=16,
                             nchunks=32)
    b = eng.buckets(X)
    assert not bool(b.any_overflow)
    used = np.asarray(jnp.sum(b.wb > 0, axis=1))
    assert used.sum() == 200 and (used > 0).sum() == 13
    f_ref = interaction.spread_vel(F, grid, X)
    f_new = eng.spread_vel(F, X)
    for a, c in zip(f_ref, f_new):
        assert float(jnp.max(jnp.abs(a - c))) < 1e-5 * (
            float(jnp.max(jnp.abs(a))) + 1e-12)


def test_chunk_capacity_overflow_exact():
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    rng = np.random.RandomState(3)
    X = jnp.asarray(rng.rand(400, 2), dtype=F64)
    F = jnp.asarray(rng.randn(400, 2), dtype=F64)
    eng = PackedInteraction3(grid, tile=8, tile_last=8, chunk=8,
                             nchunks=6)
    b = eng.buckets(X)
    assert bool(b.any_overflow)
    f_ref = interaction.spread_vel(F, grid, X)
    f_new = eng.spread_vel(F, X)
    for a, c in zip(f_ref, f_new):
        assert float(jnp.max(jnp.abs(a - c))) < 1e-5 * (
            float(jnp.max(jnp.abs(a))) + 1e-12)
    u = tuple(jnp.asarray(rng.randn(32, 32), dtype=F64)
              for _ in range(2))
    U_ref = interaction.interpolate_vel(u, grid, X)
    U_new = eng.interpolate_vel(u, X)
    assert float(jnp.max(jnp.abs(U_ref - U_new))) < 1e-5


def test_adjointness():
    grid = StaggeredGrid(n=(16, 16, 16), x_lo=(0,) * 3, x_up=(1,) * 3)
    X = _markers(150, 3, seed=3)
    rng = np.random.RandomState(4)
    F = jnp.asarray(rng.randn(150, 3), dtype=F64)
    u = tuple(jnp.asarray(rng.randn(16, 16, 16), dtype=F64)
              for _ in range(3))
    eng = PackedInteraction3(grid, tile=8, tile_last=8, chunk=32,
                             nchunks=24)
    b = eng.buckets(X)
    f = eng.spread_vel(F, X, b=b)
    U = eng.interpolate_vel(u, X, b=b)
    h3 = float(np.prod(grid.dx))
    lhs = sum(float(jnp.sum(a * c)) for a, c in zip(f, u)) * h3
    rhs = float(jnp.sum(F * U))
    assert abs(lhs - rhs) < 1e-5 * (abs(lhs) + abs(rhs) + 1e-12)


def test_bf16_compute_matches_f32_within_tolerance():
    grid = StaggeredGrid(n=(24, 24, 32), x_lo=(0,) * 3, x_up=(1,) * 3)
    X = _markers(300, 3, seed=7)
    rng = np.random.RandomState(8)
    F = jnp.asarray(rng.randn(300, 3), dtype=jnp.float32)
    Q = suggest_chunks3(grid, X, tile=8, tile_last=16, chunk=32)
    exact = PackedInteraction3(grid, tile=8, tile_last=16, chunk=32,
                               nchunks=Q)
    comp = PackedInteraction3(grid, tile=8, tile_last=16, chunk=32,
                              nchunks=Q, compute_dtype=jnp.bfloat16)
    Xf = X.astype(jnp.float32)
    f_exact = exact.spread_vel(F, Xf)
    f_comp = comp.spread_vel(F, Xf)
    for a, b in zip(f_exact, f_comp):
        scale = float(jnp.max(jnp.abs(a))) + 1e-12
        # bf16 mantissa ~ 8 bits -> ~3 decimal digits on the weights
        assert float(jnp.max(jnp.abs(a - b))) < 2e-2 * scale
    u = tuple(jnp.asarray(rng.randn(24, 24, 32), dtype=jnp.float32)
              for _ in range(3))
    U_exact = exact.interpolate_vel(u, Xf)
    U_comp = comp.interpolate_vel(u, Xf)
    scale = float(jnp.max(jnp.abs(U_exact))) + 1e-12
    assert float(jnp.max(jnp.abs(U_exact - U_comp))) < 2e-2 * scale


def test_shell_engine_knob_and_step():
    """The flagship builder accepts the packed3 engines and the coupled
    step runs finite (the bench shootout's construction path)."""
    from ibamr_tpu.models.shell3d import build_shell_example

    for eng in ("packed3", "packed3_bf16"):
        integ, state = build_shell_example(
            n_cells=32, n_lat=24, n_lon=24, radius=0.25, aspect=1.2,
            stiffness=1.0, rest_length_factor=0.75, mu=0.05,
            use_fast_interaction=eng)
        for _ in range(3):
            state = integ.step(state, 5e-5)
        assert bool(jnp.all(jnp.isfinite(state.X)))
        assert bool(jnp.all(jnp.isfinite(state.ins.u[0])))
