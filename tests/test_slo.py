"""Serving-path SLO gate (tools/slo.py, SLO.json).

One module-scoped drill ledger feeds every CLI test — the acceptance
matrix (0 clean / 1 unevaluable / 2 violated) re-evaluates the same
measurement against different contracts instead of re-compiling a
bucket per case. Pure-function tests (evaluate, slis_from_*,
tighten_contract) run on synthetic inputs.
"""

import json
import os
import sys
import types

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ibamr_tpu import obs                              # noqa: E402
import tools.slo as slo                                # noqa: E402


# ---------------------------------------------------------------------------
# one drill, one ledger (module-scoped: a single bucket compile)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def drill_ledger(tmp_path_factory):
    td = tmp_path_factory.mktemp("slo")
    path = str(td / "ledger.jsonl")
    obs.reset_metrics()                 # hermetic SLIs for this ledger
    args = types.SimpleNamespace(
        backend="cpu", n=8, n_lat=6, n_lon=8, lanes=2, steps=3,
        dt=5e-5, engine="", warm_requests=8)
    out = slo.run_drill_ledger(args, path)
    return path, out


# ---------------------------------------------------------------------------
# acceptance: committed contract vs a fresh drill ledger
# ---------------------------------------------------------------------------

def test_committed_contract_attained(drill_ledger, capsys):
    """The repo's pinned SLO.json exits 0 against a fresh drill."""
    path, _ = drill_ledger
    rc = slo.main(["check", "--ledger", path])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "clean" in out
    assert "VIOLATED" not in out


def test_injected_violation_exits_2(drill_ledger, tmp_path, capsys):
    path, _ = drill_ledger
    bad = {"slo_schema": 1, "drill": {},
           "slos": {"warm_path_compiles": {"ceiling": -1},
                    "warm_first_step_p99_s": {"ceiling": 1e-9}}}
    cpath = str(tmp_path / "bad_slo.json")
    json.dump(bad, open(cpath, "w"))
    rc = slo.main(["check", "--ledger", path, "--contract", cpath,
                   "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 2
    assert doc["exit"] == 2
    assert len(doc["violated"]) == 2
    assert any("warm_path_compiles" in v for v in doc["violated"])


def test_missing_contract_and_unmeasurable_exit_1(drill_ledger,
                                                  tmp_path, capsys):
    path, _ = drill_ledger
    # no contract file at all -> unevaluable
    rc = slo.main(["check", "--ledger", path, "--contract",
                   str(tmp_path / "absent.json")])
    out = capsys.readouterr().out
    assert rc == 1 and "no contract" in out
    # a budgeted SLI the ledger cannot produce -> unevaluable
    weird = {"slo_schema": 1, "drill": {},
             "slos": {"p999_of_nothing_s": {"ceiling": 1.0}}}
    cpath = str(tmp_path / "weird.json")
    json.dump(weird, open(cpath, "w"))
    rc = slo.main(["check", "--ledger", path, "--contract", cpath])
    out = capsys.readouterr().out
    assert rc == 1 and "not measurable" in out


def test_tighten_then_check_round_trips(drill_ledger, tmp_path,
                                        capsys):
    path, _ = drill_ledger
    cpath = str(tmp_path / "tight.json")
    assert slo.main(["check", "--ledger", path, "--tighten",
                     "--contract", cpath]) == 0
    capsys.readouterr()
    doc = json.load(open(cpath))
    assert doc["slo_schema"] == slo.SLO_SCHEMA
    assert "warm_first_step_p99_s" in doc["slos"]
    assert doc["slos"]["warm_path_compiles"] == {"ceiling": 0}
    # the tightened contract is attained by the measurement it came from
    assert slo.main(["check", "--ledger", path,
                     "--contract", cpath]) == 0
    capsys.readouterr()


def test_drill_json_path_evaluates_saved_artifact(drill_ledger,
                                                  tmp_path, capsys):
    _, drill = drill_ledger
    # as a bench artifact ({"serve": {...}}) — the compare shape
    jpath = str(tmp_path / "bench.json")
    json.dump({"serve": drill}, open(jpath, "w"))
    rc = slo.main(["check", "--drill-json", jpath])
    out = capsys.readouterr().out
    assert rc == 0, out


# ---------------------------------------------------------------------------
# unit: SLI computation and the evaluate matrix
# ---------------------------------------------------------------------------

def test_slis_from_ledger_on_drill(drill_ledger):
    path, drill = drill_ledger
    slis = slo.slis_from_ledger(obs.read_ledger(path))
    assert slis["warm_path_compiles"] == 0          # PR-11 guarantee
    assert slis["quarantine_rate"] == 0.0
    assert 0.0 < slis["cache_hit_ratio"] < 1.0      # cold misses exist
    assert slis["warm_first_step_p99_s"] is not None
    assert slis["warm_first_step_p99_s"] < 2.0
    # the histogram estimate brackets the drill's own percentile
    assert slis["padding_fraction"] is not None
    assert 0.0 <= slis["padding_fraction"] <= 1.0


def test_slis_from_ledger_synthetic_fallback():
    """No histogram snapshot: warm p99 falls back to the empirical
    quantile of request records."""
    recs = [
        {"kind": "request_admit", "seq": 1, "trace_id": "a" * 16},
        {"kind": "aot_cache", "seq": 2, "event": "miss"},
        {"kind": "request", "seq": 3, "trace_id": "a" * 16,
         "cold": True, "quarantined": False, "first_step_s": 5.0},
        {"kind": "request_admit", "seq": 4, "trace_id": "b" * 16},
        {"kind": "aot_cache", "seq": 5, "event": "hit"},
        {"kind": "request", "seq": 6, "trace_id": "b" * 16,
         "cold": False, "quarantined": False, "first_step_s": 0.01},
    ]
    slis = slo.slis_from_ledger(recs)
    assert slis["warm_first_step_p99_s"] == 0.01
    assert slis["warm_path_compiles"] == 0    # the miss predates warm
    assert slis["quarantine_rate"] == 0.0
    assert slis["cache_hit_ratio"] == 0.5
    assert slis["padding_fraction"] is None   # no histogram anywhere
    # a miss AFTER the warm admission counts against the warm path
    recs.append({"kind": "aot_cache", "seq": 7, "event": "miss"})
    assert slo.slis_from_ledger(recs)["warm_path_compiles"] == 1


def test_evaluate_matrix():
    contract = {"slos": {
        "warm_first_step_p99_s": {"ceiling": 1.0},
        "cache_hit_ratio": {"floor": 0.5},
        "quarantine_rate": {"ceiling": 0.0},
    }}
    ok = {"warm_first_step_p99_s": 0.01, "cache_hit_ratio": 0.9,
          "quarantine_rate": 0.0}
    v, u, m = slo.evaluate(ok, contract)
    assert (v, u) == ([], []) and len(m) == 3
    # headroom is attainment, never drift
    assert any("within ceiling" in s for s in m)
    bad = dict(ok, cache_hit_ratio=0.1, quarantine_rate=0.5)
    v, u, m = slo.evaluate(bad, contract)
    assert len(v) == 2 and not u
    assert any("floor" in s for s in v)
    part = dict(ok, cache_hit_ratio=None)
    v, u, m = slo.evaluate(part, contract)
    assert not v and len(u) == 1 and len(m) == 2
    # a malformed budget (no ceiling/floor) is unmeasurable, not fatal
    v, u, m = slo.evaluate(ok, {"slos": {"x": {}}})
    assert not v and len(u) == 1


def test_load_contract_rejects_wrong_schema(tmp_path):
    p = str(tmp_path / "future.json")
    json.dump({"slo_schema": 99, "slos": {}}, open(p, "w"))
    with pytest.raises(ValueError, match="slo_schema"):
        slo.load_contract(p)


def test_tighten_contract_slack_rules():
    slis = {"warm_first_step_p99_s": 0.01, "warm_path_compiles": 0,
            "padding_fraction": 0.95, "quarantine_rate": 0.0,
            "cache_hit_ratio": 0.1}
    doc = slo.tighten_contract(slis, {"n": 8})
    s = doc["slos"]
    assert s["warm_first_step_p99_s"]["ceiling"] == 0.5   # floored
    assert s["warm_path_compiles"]["ceiling"] == 0        # exact pin
    assert s["padding_fraction"]["ceiling"] == 1.0        # clamped
    assert s["cache_hit_ratio"]["floor"] == 0.0           # clamped
    big = slo.tighten_contract(
        dict(slis, warm_first_step_p99_s=3.0), {})
    assert big["slos"]["warm_first_step_p99_s"]["ceiling"] == 6.0
    # absent SLIs produce no budget at all
    sparse = slo.tighten_contract({"quarantine_rate": 0.0}, {})
    assert set(sparse["slos"]) == {"quarantine_rate"}


def test_empirical_quantile_edges():
    assert slo._empirical_quantile([], 0.99) is None
    assert slo._empirical_quantile([7.0], 0.5) == 7.0
    vals = [float(i) for i in range(1, 101)]
    assert slo._empirical_quantile(vals, 0.99) == 99.0
    assert slo._empirical_quantile(vals, 0.5) == 50.0


def test_committed_contract_matches_schema():
    """The contract in the repo root is loadable and budgets only
    known SLIs in known directions."""
    doc = slo.load_contract()
    assert doc["slo_schema"] == slo.SLO_SCHEMA
    for name, budget in doc["slos"].items():
        assert name in slo.SLI_NAMES, name
        key = "floor" if name in slo.FLOORS else "ceiling"
        assert set(budget) == {key}, (name, budget)
    # PR 18: the committed elastic budget names only known elastic
    # SLIs, all ceilings, with both count invariants pinned at zero
    for name, budget in doc.get("elastic_slos", {}).items():
        assert name in slo.ELASTIC_SLI_NAMES, name
        assert set(budget) == {"ceiling"}, (name, budget)
    assert doc["elastic_slos"]["elastic_lost_requests"] == {"ceiling": 0}
    assert (doc["elastic_slos"]["elastic_restart_fresh_compiles"]
            == {"ceiling": 0})


# ---------------------------------------------------------------------------
# elastic mode (PR 18): SLIs from a synthetic drill ledger + the CLI
# exit matrix — no live router, no compiles
# ---------------------------------------------------------------------------

def _elastic_records():
    """A minimal but complete elastic-drill story: one grow that
    warms, two mode transitions, a restart that paid zero fresh
    compiles, and a fully-joined interactive request stream."""
    return [
        {"kind": "pool_scale", "seq": 1, "action": "grow",
         "family": "(8, 6, 12, None, None, 0.05)",
         "reason": "mix_shift", "t": 1.0},
        {"kind": "serve_mode", "seq": 2, "mode": "brownout",
         "prev": "healthy", "t": 1.1, "queue_p99_s": 2.0,
         "backlog": 1, "cache_frac": 0.0},
        {"kind": "pool_scale", "seq": 3, "action": "warmed",
         "family": "(8, 6, 12, None, None, 0.05)",
         "reason": "mix_shift", "t": 2.5, "warm_s": 1.5},
        {"kind": "serve_mode", "seq": 4, "mode": "healthy",
         "prev": "brownout", "t": 3.0, "queue_p99_s": 0.1,
         "backlog": 0, "cache_frac": 0.0},
        {"kind": "request_admit", "seq": 5, "trace_id": "a" * 16},
        {"kind": "request", "seq": 6, "trace_id": "a" * 16,
         "cold": False, "tenant_class": "interactive",
         "first_step_s": 0.05},
        {"kind": "request_admit", "seq": 7, "trace_id": "b" * 16},
        {"kind": "request_shed", "seq": 8, "trace_id": "b" * 16,
         "shed_reason": "brownout"},
        {"kind": "serving_restore", "seq": 9, "warm_s": 1.0,
         "fresh_compiles": 0, "persistent_loads": 2},
    ]


def test_elastic_slis_from_synthetic_ledger():
    slis = slo.elastic_slis_from_ledger(_elastic_records())
    assert slis["elastic_scale_up_latency_s"] == 1.5
    assert slis["elastic_restart_to_warm_s"] == 1.0
    assert slis["elastic_restart_fresh_compiles"] == 0
    assert slis["elastic_mode_transitions"] == 2
    assert slis["elastic_interactive_p99_s"] == 0.05
    assert slis["elastic_lost_requests"] == 0    # admit/terminal join
    # a dropped terminal record is a LOST request, never silence
    recs = [r for r in _elastic_records() if r["seq"] != 8]
    assert slo.elastic_slis_from_ledger(recs)[
        "elastic_lost_requests"] == 1
    # a non-elastic ledger measures nothing (every SLI absent)
    plain = [{"kind": "request_admit", "seq": 1, "trace_id": "c" * 16},
             {"kind": "request", "seq": 2, "trace_id": "c" * 16,
              "cold": True, "first_step_s": 1.0}]
    slis = slo.elastic_slis_from_ledger(plain)
    assert slis["elastic_mode_transitions"] is None
    assert slis["elastic_scale_up_latency_s"] is None


def test_check_elastic_ledger_exit_matrix(tmp_path, capsys):
    """``check --elastic --ledger`` against the committed contract is
    clean; a hostile budget exits 2; a contract with no elastic_slos
    exits 1 (unbudgeted, never silently green)."""
    lpath = str(tmp_path / "elastic_ledger.jsonl")
    with open(lpath, "w") as f:
        for rec in _elastic_records():
            f.write(json.dumps(rec) + "\n")
    rc = slo.main(["check", "--elastic", "--ledger", lpath, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0, doc
    assert doc["exit"] == 0 and not doc["violated"]
    assert len(doc["met"]) == len(slo.ELASTIC_SLI_NAMES)
    # violated budget -> 2
    bad = {"slo_schema": 1,
           "elastic_slos": {"elastic_lost_requests": {"ceiling": -1},
                            "elastic_scale_up_latency_s":
                                {"ceiling": 1e-9}},
           "slos": {}}
    cpath = str(tmp_path / "bad.json")
    json.dump(bad, open(cpath, "w"))
    rc = slo.main(["check", "--elastic", "--ledger", lpath,
                   "--contract", cpath])
    out = capsys.readouterr().out
    assert rc == 2 and "VIOLATED" in out
    # no elastic_slos section -> 1
    json.dump({"slo_schema": 1, "slos": {}}, open(cpath, "w"))
    rc = slo.main(["check", "--elastic", "--ledger", lpath,
                   "--contract", cpath])
    out = capsys.readouterr().out
    assert rc == 1 and "no elastic_slos" in out


def test_tighten_elastic_merges_without_clobbering(tmp_path):
    """--elastic --tighten rewrites only elastic/elastic_slos; the
    cold/warm and soak sections survive byte-identical."""
    base = slo.load_contract()
    cpath = str(tmp_path / "contract.json")
    json.dump(base, open(cpath, "w"))
    slis = slo.elastic_slis_from_ledger(_elastic_records())
    doc = slo.tighten_elastic(slis, {"source": "synthetic"}, cpath)
    assert doc["slos"] == base["slos"]
    assert doc.get("soak_slos") == base.get("soak_slos")
    s = doc["elastic_slos"]
    assert s["elastic_lost_requests"] == {"ceiling": 0}        # exact
    assert s["elastic_restart_fresh_compiles"] == {"ceiling": 0}
    assert s["elastic_mode_transitions"] == {"ceiling": 4}     # +2
    assert s["elastic_scale_up_latency_s"]["ceiling"] == 3.0   # 2x
    assert s["elastic_interactive_p99_s"]["ceiling"] == 1.0    # floored
