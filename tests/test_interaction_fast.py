"""Bucketed MXU spread/interp (hard-part #1): bitwise-level agreement
with the reference scatter formulation, adjointness, overflow fallback
exactness, and the 2D blocked variant."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import interaction
from ibamr_tpu.ops.interaction_fast import (FastInteraction, bucket_markers,
                                            make_geometry, suggest_cap)

F64 = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def _markers(n, dim, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.rand(n, dim), dtype=F64)


@pytest.mark.parametrize("dim,n", [(2, 32), (3, 16)])
@pytest.mark.parametrize("kernel", ["IB_4", "IB_3", "BSPLINE_4"])
def test_matches_scatter_path(dim, n, kernel):
    grid = StaggeredGrid(n=(n,) * dim, x_lo=(0,) * dim, x_up=(1,) * dim)
    X = _markers(300, dim)
    rng = np.random.RandomState(1)
    F = jnp.asarray(rng.randn(300, dim), dtype=F64)
    mask = jnp.asarray((rng.rand(300) > 0.1).astype(np.float64), dtype=F64)
    fast = FastInteraction(grid, kernel=kernel, tile=8, cap=128)

    f_ref = interaction.spread_vel(F, grid, X, kernel=kernel, weights=mask)
    f_new = fast.spread_vel(F, X, weights=mask)
    for a, b in zip(f_ref, f_new):
        scale = float(jnp.max(jnp.abs(a))) + 1e-12
        assert float(jnp.max(jnp.abs(a - b))) < 1e-5 * scale

    u = tuple(jnp.asarray(rng.randn(*grid.n), dtype=F64)
              for _ in range(dim))
    U_ref = interaction.interpolate_vel(u, grid, X, kernel=kernel,
                                        weights=mask)
    U_new = fast.interpolate_vel(u, X, weights=mask)
    scale = float(jnp.max(jnp.abs(U_ref))) + 1e-12
    assert float(jnp.max(jnp.abs(U_ref - U_new))) < 1e-5 * scale


def test_overflow_fallback_exact():
    # cap tiny -> most markers overflow; result must STILL match exactly
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    # clustered markers: all in one tile
    rng = np.random.RandomState(2)
    X = jnp.asarray(0.1 + 0.05 * rng.rand(200, 2), dtype=F64)
    F = jnp.asarray(rng.randn(200, 2), dtype=F64)
    fast = FastInteraction(grid, tile=8, cap=8)
    b = fast.buckets(X)
    assert bool(b.any_overflow)
    f_ref = interaction.spread_vel(F, grid, X)
    f_new = fast.spread_vel(F, X)
    for a, c in zip(f_ref, f_new):
        assert float(jnp.max(jnp.abs(a - c))) < 1e-5 * (
            float(jnp.max(jnp.abs(a))) + 1e-12)
    u = tuple(jnp.asarray(rng.randn(32, 32), dtype=F64) for _ in range(2))
    U_ref = interaction.interpolate_vel(u, grid, X)
    U_new = fast.interpolate_vel(u, X)
    assert float(jnp.max(jnp.abs(U_ref - U_new))) < 1e-5


def test_adjointness():
    grid = StaggeredGrid(n=(16, 16, 16), x_lo=(0,) * 3, x_up=(1,) * 3)
    X = _markers(150, 3, seed=3)
    rng = np.random.RandomState(4)
    F = jnp.asarray(rng.randn(150, 3), dtype=F64)
    u = tuple(jnp.asarray(rng.randn(16, 16, 16), dtype=F64)
              for _ in range(3))
    fast = FastInteraction(grid, tile=8, cap=64)
    b = fast.buckets(X)
    f = fast.spread_vel(F, X, b=b)
    U = fast.interpolate_vel(u, X, b=b)
    h3 = float(np.prod(grid.dx))
    lhs = sum(float(jnp.sum(a * c)) for a, c in zip(f, u)) * h3
    rhs = float(jnp.sum(F * U))
    assert abs(lhs - rhs) < 1e-5 * (abs(lhs) + abs(rhs) + 1e-12)


def test_constant_field_interp_and_moment():
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    X = _markers(100, 2, seed=5)
    fast = FastInteraction(grid, tile=8, cap=64)
    u = (jnp.full(grid.n, 1.3, dtype=F64), jnp.full(grid.n, -0.4, dtype=F64))
    U = fast.interpolate_vel(u, X)
    assert np.allclose(np.asarray(U[:, 0]), 1.3, atol=1e-5)
    assert np.allclose(np.asarray(U[:, 1]), -0.4, atol=1e-5)
    # spread of unit forces integrates back to the forces
    F = jnp.ones((100, 2), dtype=F64)
    f = fast.spread_vel(F, X)
    h2 = float(np.prod(grid.dx))
    for d in range(2):
        assert abs(float(jnp.sum(f[d])) * h2 - 100.0) < 1e-4


def test_suggest_cap_and_jit_stability():
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    X = _markers(500, 2, seed=6)
    cap = suggest_cap(grid, X, tile=8)
    assert cap % 8 == 0 and cap >= 8
    fast = FastInteraction(grid, tile=8, cap=cap)
    F = jnp.ones((500, 2), dtype=F64)

    @jax.jit
    def go(F, X):
        return fast.spread_vel(F, X)

    f1 = go(F, X)
    f2 = go(F, X + 0.01)   # same shapes -> cached compile
    assert np.isfinite(np.asarray(f1[0])).all()
    assert np.isfinite(np.asarray(f2[0])).all()


def test_shell_step_fast_matches_scatter():
    # full coupled IB step: fast engine vs scatter path, same trajectory
    from ibamr_tpu.models.shell3d import build_shell_example
    import jax

    kw = dict(n_cells=16, n_lat=12, n_lon=12, mu=0.05)
    integ_a, st_a = build_shell_example(use_fast_interaction=False, **kw)
    integ_b, st_b = build_shell_example(use_fast_interaction=True, **kw)
    assert integ_b.ib.fast is not None
    step_a = jax.jit(lambda s: integ_a.step(s, 1e-3))
    step_b = jax.jit(lambda s: integ_b.step(s, 1e-3))
    for _ in range(5):
        st_a = step_a(st_a)
        st_b = step_b(st_b)
    dX = float(jnp.max(jnp.abs(st_a.X - st_b.X)))
    du = float(jnp.max(jnp.abs(st_a.ins.u[0] - st_b.ins.u[0])))
    assert dX < 1e-5 and du < 1e-4
