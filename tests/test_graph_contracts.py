"""Tier-1 graph-contract gate (PR 8 tentpole satellite): every
artifact in the contract registry must census EXACTLY to its committed
budget in GRAPH_BUDGETS.json — a regression (new scatter, un-fused
FFT, host transfer in the scan body, dropped donation, f64 widening)
fails, and an IMPROVEMENT also fails with instructions to ratchet the
budget (``python tools/graph_audit.py --tighten``), so the committed
file never drifts from reality in either direction.

Measurement is in-process (the suite already isolates per module and
``measure_artifact`` wraps the build in ``disable_x64()``, so the
budgets match the production x64-off posture even though conftest
enables x64). The flagship-scale artifact rides the slow tier.

Also the two repo-wide static gates: the jit-safety linter must be
clean over ``ibamr_tpu/`` (waivers allowed, bare waivers are not),
and the first-wave f64-request fixes stay pinned by asserting the
fixed call sites trace warning-free under x64-off.
"""

import os
import warnings

import jax
import pytest

from ibamr_tpu.analysis.contracts import (
    ARTIFACTS, REPO_ROOT, diff_budget, load_budgets, measure_artifact)
from ibamr_tpu.analysis.jit_lint import lint_paths

BUDGETS = load_budgets()

# Whole-step / chunk lowerings each cost 4-10 s of XLA compile (by
# --durations on the tier-1 box); with the fast tier already within
# ~30 s of the 870 s gate they ride the slow tier per the conftest
# re-tier policy. The fast tier keeps the acceptance-critical
# contracts: the fused substep (zero-scatter / <=2-FFT), verified
# donation via donated_step (same step graph as solo_step), all four
# transfer engines, and the lane fetch path. The slow-tiered
# artifacts stay fully gated by `tools/graph_audit.py` (CI) and the
# full-suite run.
_SLOW_LIGHT = {"solo_step", "solo_step_bf16", "solo_chunk",
               "donated_chunk", "fleet_chunk", "open_channel_step",
               "sharded_chunk", "fleet_mesh_chunk"}

_PARAMS = [
    pytest.param(name, marks=pytest.mark.slow)
    if art.heavy or name in _SLOW_LIGHT else name
    for name, art in ARTIFACTS.items()
]


@pytest.mark.parametrize("name", _PARAMS)
def test_artifact_matches_committed_budget(name):
    assert name in BUDGETS, (
        f"artifact {name!r} has no committed budget — run "
        f"`python tools/graph_audit.py --tighten` and commit "
        f"GRAPH_BUDGETS.json")
    measured = measure_artifact(name)
    d = diff_budget(name, measured, BUDGETS[name])
    assert not d.regressions and not d.missing, (
        f"graph contract REGRESSED for {name!r}: "
        + ", ".join(f"{m}={got} (budget {bound})"
                    for m, (got, bound) in d.regressions.items())
        + (f"; unmeasurable budget metric(s) {d.missing}"
           if d.missing else ""))
    assert not d.improvements, (
        f"graph contract IMPROVED for {name!r}: "
        + ", ".join(f"{m}={got} (budget {bound})"
                    for m, (got, bound) in d.improvements.items())
        + " — ratchet it in with `python tools/graph_audit.py "
          "--tighten` and commit GRAPH_BUDGETS.json")


def test_headline_invariants_are_budgeted():
    """The acceptance-critical invariants must be present in the
    committed file itself, not just implied: the fused spectral substep
    is zero-scatter / <=2-FFT, the donated artifacts actually alias,
    and no artifact tolerates a host transfer inside a scan body."""
    fused = BUDGETS["fused_substep"]
    assert fused["scatter_ops"] == 0 and fused["scatter_prims"] == 0
    assert fused["fft_ops"] <= 2
    assert BUDGETS["donated_step"]["donated_args"] >= 1
    assert BUDGETS["donated_chunk"]["donated_args"] >= 1
    for name, b in BUDGETS.items():
        assert b["host_transfers_in_scan"] == 0, name
    # PR 15: the pod comm-layer pins are in the committed file — the
    # three sharded artifacts budget their collective census and the
    # S2 exchange's halo pushes are ppermutes
    for name in ("sharded_chunk", "fftpar_transpose",
                 "lagrangian_exchange"):
        assert BUDGETS[name]["collective_prims"] > 0, name
    assert BUDGETS["lagrangian_exchange"]["ppermute_prims"] > 0
    assert BUDGETS["sharded_chunk"]["ppermute_prims"] > 0
    assert BUDGETS["sharded_chunk"]["all_to_all_prims"] > 0
    # PR 16: the comm is HIDDEN, and the file pins it. The pipelined
    # pencil transpose splits each of the 4 all_to_alls in 2 tiles
    # (bytes unchanged); the unhidden counts are strictly below the
    # PR-15 baselines (fftpar 4 -> 1, lagrangian 6 -> 2) and the
    # hidden_fraction floors hold every comm-bearing artifact above
    # its measured overlap
    assert BUDGETS["fftpar_transpose"]["all_to_all_prims"] == 8
    assert BUDGETS["fftpar_transpose"]["unhidden_collectives"] <= 1
    assert BUDGETS["fftpar_transpose"]["hidden_fraction"] >= 80
    assert BUDGETS["lagrangian_exchange"]["unhidden_collectives"] <= 2
    assert BUDGETS["lagrangian_exchange"]["hidden_fraction"] >= 80
    for name in ("sharded_chunk", "fftpar_transpose",
                 "lagrangian_exchange", "fleet_mesh_chunk",
                 "krylov_reduce"):
        assert "hidden_fraction" in BUDGETS[name], name
    # the lane-mesh fleet chunk moves no data between lanes
    assert BUDGETS["fleet_mesh_chunk"]["collective_prims"] == 0
    assert BUDGETS["fleet_mesh_chunk"]["unhidden_collectives"] == 0


def test_jit_lint_clean_over_package():
    report = lint_paths([os.path.join(REPO_ROOT, "ibamr_tpu")])
    assert report["files_scanned"] > 20
    active = [f for f in report["findings"] if not f["waived"]]
    assert active == [], (
        "jit-lint findings in ibamr_tpu/ — fix them or add a "
        "justified `# jitlint: ok(<rule>): <reason>` waiver:\n"
        + "\n".join(f"  {f['path']}:{f['line']}: [{f['rule']}] "
                    f"{f['message']}" for f in active))
    # every waiver on the books must carry a reason and be in use
    for w in report["waivers"]:
        assert w["reason"], w
        assert w["used"], f"stale waiver: {w}"


def test_first_wave_f64_fixes_stay_warning_free():
    """Pin the first-wave findings: ins_open's stabilized-PPM boundary
    ramp and the spectral Gaussian filter symbol must trace without
    'Explicitly requested dtype float64' warnings under the production
    x64-off config (the warning means silent truncation)."""
    from ibamr_tpu.solvers.spectral_plan import gaussian_filter_symbol

    with jax.experimental.disable_x64():
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            gaussian_filter_symbol((16, 16), (1.0 / 16, 1.0 / 16),
                                   width=2.0)
            measure_artifact("open_channel_step")
        bad = [w for w in rec
               if "requested dtype" in str(w.message).lower()]
        assert bad == [], [str(w.message) for w in bad]
