"""Pod-scope observability tests (PR 15): the collective/overlap
censuses on tiny hand-built shard_map programs and synthetic HLO, the
``comm_s`` device-op class with its accounting invariants, the comm
roofline join, per-process ledger shards, and the merge machinery —
deterministic (seq, proc) interleave, torn-tail tolerance, same-run
checking, and the no-double-counted-counters fleet rollup.

Everything runs on the conftest's 8 virtual CPU devices; the async
start/done pairing is exercised on synthetic HLO text because the CPU
backend only ever emits synchronous collectives.
"""

import json
import os
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

import ibamr_tpu.obs as obs
from ibamr_tpu.analysis.graph_census import (collective_census,
                                             overlap_census)
from ibamr_tpu.obs import deviceprof
from ibamr_tpu.obs.merge import (find_shards, fleet_counters,
                                 fleet_prometheus_text, merge_ledgers)
from ibamr_tpu.obs.roofline import census_sidecar, roofline_join

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mesh1d():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the conftest's 8 virtual devices")
    return Mesh(np.array(devs[:8]), ("x",))


# ---------------------------------------------------------------------------
# collective census (jaxpr level)
# ---------------------------------------------------------------------------

def test_collective_census_psum():
    mesh = _mesh1d()
    f = shard_map(lambda x: jax.lax.psum(x, "x"), mesh,
                  in_specs=P("x"), out_specs=P(), check_rep=False)
    jaxpr = jax.make_jaxpr(f)(jnp.zeros((64, 4), jnp.float32)).jaxpr
    c = collective_census(jaxpr)
    assert c["psum_prims"] == 1
    # bytes are PER-SHARD avals: (8, 4) f32 = 128 B per device
    assert c["psum_bytes"] == 128
    assert c["collective_prims"] == 1
    assert c["collective_bytes"] == 128
    assert c["ppermute_prims"] == 0


def test_collective_census_ppermute():
    mesh = _mesh1d()
    perm = [(i, (i + 1) % 8) for i in range(8)]
    f = shard_map(lambda x: jax.lax.ppermute(x, "x", perm=perm), mesh,
                  in_specs=P("x"), out_specs=P("x"), check_rep=False)
    jaxpr = jax.make_jaxpr(f)(jnp.zeros((64, 4), jnp.float32)).jaxpr
    c = collective_census(jaxpr)
    assert c["ppermute_prims"] == 1
    assert c["ppermute_bytes"] == 128
    assert c["collective_prims"] == 1


def test_collective_census_all_to_all_and_clean_program():
    mesh = _mesh1d()
    f = shard_map(
        lambda x: jax.lax.all_to_all(x, "x", split_axis=1,
                                     concat_axis=0, tiled=True),
        mesh, in_specs=P("x", None), out_specs=P(None, "x"),
        check_rep=False)
    jaxpr = jax.make_jaxpr(f)(jnp.zeros((64, 8), jnp.float32)).jaxpr
    c = collective_census(jaxpr)
    assert c["all_to_all_prims"] == 1
    # per-shard output: (64, 1) f32 = 256 B per device
    assert c["all_to_all_bytes"] == 256
    # a collective-free program counts zero everywhere
    c2 = collective_census(
        jax.make_jaxpr(lambda a: a * 2.0)(jnp.ones(4)).jaxpr)
    assert c2["collective_prims"] == 0
    assert c2["collective_bytes"] == 0


def test_collective_census_sees_through_scan():
    # collectives inside control flow count (iter_eqns recursion) —
    # the sharded driver chunk is exactly a scan over ppermutes
    mesh = _mesh1d()
    perm = [(i, (i + 1) % 8) for i in range(8)]

    def body(x):
        def step(c, _):
            return jax.lax.ppermute(c, "x", perm=perm), ()
        out, _ = jax.lax.scan(step, x, None, length=3)
        return out

    f = shard_map(body, mesh, in_specs=P("x"), out_specs=P("x"),
                  check_rep=False)
    jaxpr = jax.make_jaxpr(f)(jnp.zeros((64, 4), jnp.float32)).jaxpr
    c = collective_census(jaxpr)
    assert c["ppermute_prims"] == 1          # one eqn inside the scan body


# ---------------------------------------------------------------------------
# overlap census (HLO text level)
# ---------------------------------------------------------------------------

_ASYNC_HLO = """\
HloModule overlap_test
ENTRY main {
  %p0 = f32[8]{0} parameter(0)
  %ag-start = (f32[8]{0}, f32[16]{0}) all-gather-start(f32[8]{0} %p0), dimensions={0}
  %mul = f32[8]{0} multiply(f32[8]{0} %p0, f32[8]{0} %p0)
  %ag-done = f32[16]{0} all-gather-done((f32[8]{0}, f32[16]{0}) %ag-start)
  %cp-start.1 = (f32[8]{0}, f32[8]{0}) collective-permute-start(f32[8]{0} %mul)
  %cp-done.1 = f32[8]{0} collective-permute-done((f32[8]{0}, f32[8]{0}) %cp-start.1)
  %ar = f32[8]{0} all-reduce(f32[8]{0} %mul), to_apply=%add
  ROOT %t = (f32[16]{0}, f32[8]{0}, f32[8]{0}) tuple(%ag-done, %cp-done.1, %ar)
}
"""


def test_overlap_census_pairs_hidden_and_unhidden():
    c = overlap_census(_ASYNC_HLO)
    # all-gather pair has the multiply scheduled inside its window
    # (hidden); the collective-permute pair has an empty window
    assert c["overlap_pairs"] == 2
    assert c["overlap_hidden"] == 1
    assert c["overlap_unhidden"] == 1
    # the synchronous all-reduce can never overlap
    assert c["collective_sync_ops"] == 1
    sites = {s["op"]: s["compute_between"] for s in c["overlap_sites"]}
    assert sites["all-gather-start"] == 1
    assert sites["collective-permute-start"] == 0


def test_overlap_census_structural_window_is_unhidden():
    # only bookkeeping ops between start and done hide nothing
    text = "\n".join([
        "  %s-start = (f32[8]{0}, f32[8]{0}) "
        "collective-permute-start(f32[8]{0} %p)",
        "  %gte = f32[8]{0} get-tuple-element((f32[8]{0}) %other), "
        "index=0",
        "  %tup = (f32[8]{0}) tuple(f32[8]{0} %gte)",
        "  %s-done = f32[8]{0} collective-permute-done("
        "(f32[8]{0}, f32[8]{0}) %s-start)",
    ])
    c = overlap_census(text)
    assert c["overlap_pairs"] == 1
    assert c["overlap_unhidden"] == 1
    assert c["overlap_hidden"] == 0


def test_overlap_census_ignores_quoted_metadata():
    # an opcode name inside quoted metadata must not fake a collective
    text = ('  %f = f32[8]{0} add(f32[8]{0} %a, f32[8]{0} %b), '
            'metadata={op_name="jit(all-reduce)(fake)"}')
    c = overlap_census(text)
    assert c["collective_sync_ops"] == 0
    assert c["overlap_pairs"] == 0


# ---------------------------------------------------------------------------
# deviceprof: the comm_s op class
# ---------------------------------------------------------------------------

def _x(name, dur_us, pid=7, tid=2, args=None):
    return {"ph": "X", "pid": pid, "tid": tid, "ts": 0,
            "dur": dur_us, "name": name, "args": args}


def _comm_trace():
    """TPU-shaped trace: an explicit collective opcode, a fused op
    inside the parallel layer's ``comm`` named scope, plus fft / dot /
    plain compute."""
    events = [
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/device:TPU:0 (chip 0)"}},
        {"ph": "M", "pid": 7, "tid": 2, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        _x("all-reduce.3", 300,
           args={"tf_op": "jit(step)/step/all-reduce.3"}),
        _x("fusion.9", 200,
           args={"tf_op": "jit(step)/step/comm/fusion.9"}),
        _x("fft.1", 100, args={"tf_op": "jit(step)/step/fft.1"}),
        _x("dot_general.2", 50,
           args={"tf_op": "jit(step)/step/dot_general.2"}),
        _x("fusion.4", 50, args={"tf_op": "jit(step)/step/fusion.4"}),
    ]
    return {"traceEvents": events}


def test_comm_op_class_by_opcode_and_scope():
    events, _ = deviceprof.device_op_events(_comm_trace())
    s = deviceprof.attribute_events(events, ["step"])
    oc = s["op_classes"]
    # collective opcode + comm-scoped fusion both land in comm_s
    assert oc["comm_s"] == pytest.approx(500e-6)
    assert oc["fft_s"] == pytest.approx(100e-6)
    assert oc["dot_s"] == pytest.approx(50e-6)
    assert oc["other_s"] == pytest.approx(50e-6)
    # the classes partition the total exactly
    assert (oc["fft_s"] + oc["dot_s"] + oc["comm_s"] + oc["other_s"]
            == pytest.approx(s["total_device_s"]))
    # and the span accounting identity is untouched
    assert s["attributed_s"] + s["unattributed_s"] == pytest.approx(
        s["total_device_s"])
    assert deviceprof.validate_summary(
        {**s, "schema": deviceprof.PROF_SCHEMA}) == []


def test_real_sharded_capture_reports_comm_class(tmp_path):
    """Acceptance: an 8-device virtual-mesh capture attributes with
    ``comm_s`` present and the accounting identity holding. The CPU
    backend emits synchronous collectives with their opcode names, so
    the class is populated whenever the trace tags collective ops; the
    invariant must hold either way."""
    mesh = _mesh1d()
    perm = [(i, (i + 1) % 8) for i in range(8)]
    f = jax.jit(shard_map(
        lambda x: jax.lax.ppermute(x, "x", perm=perm) * 2.0,
        mesh, in_specs=P("x"), out_specs=P("x"), check_rep=False))
    x = jnp.ones((64, 16), jnp.float32)
    f(x).block_until_ready()            # compile outside the capture
    cap = str(tmp_path / "cap")
    try:
        with jax.profiler.trace(cap):
            for _ in range(3):
                f(x).block_until_ready()
    except Exception as e:              # pragma: no cover
        pytest.skip(f"profiler unavailable: {e}")
    if not deviceprof.find_trace_files(cap):  # pragma: no cover
        pytest.skip("no trace files produced")
    s = deviceprof.attribute_capture(cap)
    assert "comm_s" in s["op_classes"]
    assert s["op_classes"]["comm_s"] >= 0.0
    assert deviceprof.validate_summary(s) == []


# ---------------------------------------------------------------------------
# roofline: the comm join
# ---------------------------------------------------------------------------

def test_roofline_comm_join_subtracts_pbroadcast():
    summary = {"total_device_s": 0.004,
               "op_classes": {"fft_s": 0.0, "dot_s": 0.0,
                              "comm_s": 0.001, "other_s": 0.003}}
    census = {"executions": 2, "collective_bytes": 2_000_000,
              "pbroadcast_bytes": 500_000, "collective_prims": 10}
    r = roofline_join(summary, census)
    assert r["comm"]["bytes_per_execution"] == 1_500_000
    assert r["comm"]["device_s_per_execution"] == pytest.approx(5e-4)
    assert r["comm"]["achieved_gb_per_s"] == pytest.approx(3.0)
    assert r["comm"]["collective_prims"] == 10
    assert r["fraction_of_step_accounted"] == pytest.approx(0.25)


def test_roofline_comm_absent_without_comm_time():
    r = roofline_join(
        {"total_device_s": 0.004,
         "op_classes": {"fft_s": 0.0, "dot_s": 0.0, "comm_s": 0.0}},
        {"executions": 2, "collective_bytes": 1000,
         "pbroadcast_bytes": 0})
    assert r["comm"] is None


def test_census_sidecar_includes_collective_counts():
    mesh = _mesh1d()
    perm = [(i, (i + 1) % 8) for i in range(8)]
    f = shard_map(lambda x: jax.lax.ppermute(x, "x", perm=perm),
                  mesh, in_specs=P("x"), out_specs=P("x"),
                  check_rep=False)
    side = census_sidecar(f, (jnp.zeros((64, 4), jnp.float32),),
                          label="halo", executions=4)
    assert side["ppermute_prims"] == 1
    assert side["collective_bytes"] == side["ppermute_bytes"] > 0


# ---------------------------------------------------------------------------
# per-process ledger shards
# ---------------------------------------------------------------------------

def test_ledger_proc_none_is_unchanged(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    with obs.ledger(path, fingerprint={"c": 1}):
        obs.emit("marker", x=1)
    recs = obs.read_ledger(path)
    assert os.path.exists(path)
    assert all("proc" not in r for r in recs)


def test_ledger_proc_reroutes_and_stamps(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    with obs.ledger(path, fingerprint={"c": 1}, proc=3) as led:
        obs.emit("marker", x=1)
    assert led.path == str(tmp_path / "ledger-3.jsonl")
    assert not os.path.exists(path)
    recs = obs.read_ledger(led.path)
    assert recs and all(r["proc"] == "3" for r in recs)
    # a directory path works too
    assert obs.shard_path(str(tmp_path), 7) == str(
        tmp_path / "ledger-7.jsonl")
    # hostile proc ids cannot escape the directory
    assert os.sep not in os.path.basename(
        obs.shard_path(str(tmp_path), "../evil"))


def _write_pod(tmp_path, n_procs=2):
    fp = {"cfg": "pod"}
    for proc in range(n_procs):
        obs.reset_metrics()
        with obs.ledger(str(tmp_path / "ledger.jsonl"),
                        fingerprint=fp, proc=proc):
            obs.counter("chunks_total").inc(4 + proc)
            with obs.span("driver"):
                with obs.span("chunk"):
                    pass
            obs.chunk_boundary(step=20)
    obs.reset_metrics()
    return str(tmp_path)


def test_merge_is_deterministic_and_stamped(tmp_path):
    d = _write_pod(tmp_path)
    assert sorted(find_shards(d)) == ["0", "1"]
    m = merge_ledgers(d)
    assert m["procs"] == ["0", "1"]
    # one shared run identity across shards
    assert all(v["run_id"] == m["run_id"]
               for v in m["per_proc"].values())
    # (seq, proc) order: non-decreasing seq, proc breaks ties
    keys = [(r["seq"], r["proc"]) for r in m["records"]]
    assert keys == sorted(keys)
    assert all(r.get("proc") in ("0", "1") for r in m["records"])


def test_merge_tolerates_sigkill_torn_tail(tmp_path):
    d = _write_pod(tmp_path)
    full = merge_ledgers(d)
    shard = os.path.join(d, "ledger-1.jsonl")
    # a SIGKILL mid-write tears at most the final line: truncate the
    # shard mid-record and the merge must lose exactly that record
    raw = open(shard, "rb").read()
    open(shard, "wb").write(raw[:-10])
    torn = merge_ledgers(d)
    assert len(torn["records"]) == len(full["records"]) - 1
    assert torn["run_id"] == full["run_id"]
    assert torn["per_proc"]["1"]["records"] == \
        full["per_proc"]["1"]["records"] - 1


def test_merge_refuses_mixed_runs(tmp_path):
    d = _write_pod(tmp_path)
    with obs.ledger(str(tmp_path / "ledger.jsonl"),
                    fingerprint={"cfg": "OTHER"}, proc=2):
        pass
    with pytest.raises(ValueError, match="run_id"):
        merge_ledgers(d)
    m = merge_ledgers(d, allow_mixed_run_ids=True)
    assert m["procs"] == ["0", "1", "2"]


def test_fleet_counters_namespaced_not_summed(tmp_path):
    d = _write_pod(tmp_path)
    snap = fleet_counters(merge_ledgers(d))
    assert snap["counters"]['chunks_total{proc="0"}'] == 4
    assert snap["counters"]['chunks_total{proc="1"}'] == 5
    # no un-namespaced key survives — a fleet sum must be explicit
    assert "chunks_total" not in snap["counters"]
    text = fleet_prometheus_text(merge_ledgers(d))
    assert 'chunks_total{proc="0"} 4' in text
    assert 'chunks_total{proc="1"} 5' in text


def test_fleet_summary_roundtrip_no_double_count(tmp_path, capsys):
    from tools.obs import main as obs_main

    d = _write_pod(tmp_path)
    # stamp a device_time record with op classes on proc 0's shard
    # (what `prof.py attribute --ledger` appends post-hoc)
    shard = os.path.join(d, "ledger-0.jsonl")
    recs = obs.read_ledger(shard)
    rec = {"seq": max(r["seq"] for r in recs) + 1,
           "run_id": recs[0]["run_id"], "t": recs[-1]["t"] + 1.0,
           "kind": "device_time", "proc": "0", "total_device_s": 0.5,
           "op_classes": {"fft_s": 0.2, "dot_s": 0.1, "comm_s": 0.15,
                          "other_s": 0.05}}
    with open(shard, "a") as f:
        f.write(json.dumps(rec) + "\n")
    assert obs_main(["summary", d, "--fleet"]) == 0
    out = capsys.readouterr().out
    assert "procs: 2" in out
    # each proc's counter renders exactly once — whole-name match, so
    # import-registered siblings like driver_chunks_total don't count
    for proc, val in (("0", 4), ("1", 5)):
        hits = re.findall(
            r'(?m)^\s*chunks_total\{proc="%s"\}\s+(\d+)\s*$' % proc,
            out)
        assert hits == [str(val)], (proc, hits)
    assert "30.0% of capture" in out          # 0.15 / 0.5 comm share
    # per-proc span trees render under per-proc headers
    assert "proc 0:" in out and "proc 1:" in out


def test_fleet_summary_renders_comm_graph_split(tmp_path, capsys):
    """The per-proc hidden/unhidden collective split (PR 16): a
    ``graph_census`` record on a shard (what ``tools/fleet.py`` emits
    per supervised run) renders as the proc's ``comm graph:`` line
    next to the measured comm share."""
    from tools.obs import main as obs_main

    d = _write_pod(tmp_path)
    shard = os.path.join(d, "ledger-1.jsonl")
    recs = obs.read_ledger(shard)
    rec = {"seq": max(r["seq"] for r in recs) + 1,
           "run_id": recs[0]["run_id"], "t": recs[-1]["t"] + 1.0,
           "kind": "graph_census", "proc": "1", "scope": "fleet_chunk",
           "chunk_length": 4, "lanes": 8, "mesh_devices": 8,
           "structural_collectives": 12, "hidden_collectives": 10,
           "unhidden_collectives": 2, "hidden_fraction": 83}
    with open(shard, "a") as f:
        f.write(json.dumps(rec) + "\n")
    assert obs_main(["summary", d, "--fleet"]) == 0
    out = capsys.readouterr().out
    assert ("comm graph: 12 data-moving collectives, 10 hidden / "
            "2 unhidden (83% structurally hidden) [lanes=8 x D=8]"
            in out)
    # proc 0 has no census record -> no comm-graph line in its block
    block0 = out.split("proc 0:")[1].split("proc 1:")[0]
    assert "comm graph" not in block0


def test_run_fleet_emits_chunk_census(tmp_path, capsys):
    """The producing side: a supervised lane-mesh fleet run lands one
    ``graph_census`` record in its ledger, and the lane-mesh chunk is
    fully lane-local (zero data-moving collectives)."""
    from tools.fleet import build_fleet, run_fleet
    from ibamr_tpu.parallel.mesh import make_lane_mesh
    from ibamr_tpu.utils.hierarchy_driver import RunConfig

    _mesh1d()  # skip unless 8 virtual devices
    # x64 session (conftest): the shell must be built in f64 too
    integ, _, stacked = build_fleet(16, 8, 16, 0.05, 8, 0.01,
                                    "float64")
    cfg = RunConfig(dt=1e-3, num_steps=4, health_interval=2)
    summary, _ = run_fleet(integ, stacked, cfg, 8,
                           directory=str(tmp_path),
                           lane_mesh=make_lane_mesh(8))
    recs = obs.read_ledger(os.path.join(str(tmp_path),
                                        "ledger.jsonl"))
    census = [r for r in recs if r.get("kind") == "graph_census"]
    assert len(census) == 1
    c = census[0]
    assert c["scope"] == "fleet_chunk"
    assert c["lanes"] == 8 and c["mesh_devices"] == 8
    assert c["structural_collectives"] == 0
    assert c["hidden_fraction"] == 100
    assert summary["lanes_quarantined"] == 0


# ---------------------------------------------------------------------------
# prof diff: the dedicated comm gate (PR 16)
# ---------------------------------------------------------------------------

def _gate_summaries(comm_a, comm_b, device):
    proc = "/device:TPU:0" if device else "python"
    mk = lambda comm: {  # noqa: E731 - table of two
        "total_device_s": 1.0,
        "spans": {}, "unattributed_s": 0.0,
        "op_classes": {"fft_s": 0.4, "dot_s": 0.3, "comm_s": comm,
                       "other_s": 0.3 - comm},
        "lanes": [{"process": proc, "thread": "XLA Ops",
                   "events": 1, "busy_s": 1.0}]}
    return mk(comm_a), mk(comm_b)


def test_comm_gate_regresses_on_device_capture():
    from tools.prof import diff_summaries

    sa, sb = _gate_summaries(0.010, 0.013, device=True)
    # +30% comm: inside the default 25%+floor general band would not
    # fire for a 3 ms move on a 1 s capture... the op_class judge does
    # fire at 25% — so use a general band ABOVE the move and show the
    # dedicated gate still catches it
    lines, verdict = diff_summaries(sa, sb, tol_pct=50.0,
                                    floor_s=200e-6, comm_tol_pct=10.0)
    assert verdict == "regressed"
    assert any("comm gate" in ln and "REGRESSED" in ln
               for ln in lines)


def test_comm_gate_advisory_on_cpu_capture():
    from tools.prof import diff_summaries

    sa, sb = _gate_summaries(0.010, 0.013, device=False)
    lines, verdict = diff_summaries(sa, sb, tol_pct=50.0,
                                    floor_s=200e-6, comm_tol_pct=10.0)
    assert verdict == "clean"
    assert any("comm gate" in ln and "ADVISORY" in ln
               for ln in lines)


def test_comm_gate_within_band_and_unarmed():
    from tools.prof import diff_summaries

    sa, sb = _gate_summaries(0.010, 0.0101, device=True)
    lines, verdict = diff_summaries(sa, sb, tol_pct=50.0,
                                    floor_s=200e-6, comm_tol_pct=10.0)
    assert verdict == "clean"
    assert any("comm gate" in ln and "within band" in ln
               for ln in lines)
    # unarmed (default): no gate line at all, behavior unchanged
    lines, _ = diff_summaries(sa, sb, tol_pct=50.0, floor_s=200e-6)
    assert not any("comm gate" in ln for ln in lines)


def test_fleet_compare_per_proc_deltas(tmp_path, capsys):
    from tools.obs import main as obs_main

    a = _write_pod(tmp_path / "a")
    b = _write_pod(tmp_path / "b")
    assert obs_main(["compare", a, b]) == 0
    out = capsys.readouterr().out
    assert "proc 0 per-phase wall" in out
    assert "proc 1 per-phase wall" in out
    assert 'chunks_total{proc="1"}' in out
