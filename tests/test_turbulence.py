"""P22 turbulence closures: Smagorinsky LES + Wilcox k-omega.

Oracles: rigid rotation has zero strain, hence zero eddy viscosity;
nu_t scales as Delta^2 under grid refinement for a fixed resolved
field; homogeneous (k, omega) decay matches the closed-form ODE
solution; an under-resolved high-Re Taylor-Green LES run stays bounded
and dissipates energy; shear production raises k where the shear is.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.physics import turbulence


def _grid(n, L=1.0):
    return StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(L, L))


def test_rigid_rotation_zero_eddy_viscosity():
    """Solid-body rotation: E = 0 exactly, so nu_t must vanish (up to
    the roll-stencil roundoff) while vorticity is O(1)."""
    g = _grid(32)
    xc = g.cell_centers(jnp.float64)
    # MAC faces of u: x-face coords; of v: y-face coords
    fx = g.face_centers(0, jnp.float64)
    fy = g.face_centers(1, jnp.float64)
    om = 2.0
    u = (jnp.broadcast_to(-om * (fx[1] - 0.5), g.n),
         jnp.broadcast_to(om * (fy[0] - 0.5), g.n))
    nu_t = turbulence.eddy_viscosity_smagorinsky(u, g.dx)
    # the linear field is NOT periodic: the wrap rows see the jump, so
    # only the interior is the rigid-rotation oracle
    assert float(jnp.max(nu_t[2:-2, 2:-2])) < 1e-12


def test_eddy_viscosity_delta_squared_scaling():
    """For the same analytic velocity field, nu_t at the same physical
    point scales as Delta^2 = (dx dy)^(1/2)^2 ~ 1/n^2."""
    vals = []
    for n in (32, 64):
        g = _grid(n)
        fx = g.face_centers(0, jnp.float64)
        fy = g.face_centers(1, jnp.float64)
        u = (jnp.broadcast_to(jnp.sin(2 * jnp.pi * fx[0])
                              * jnp.cos(2 * jnp.pi * fx[1]), g.n),
             jnp.broadcast_to(-jnp.cos(2 * jnp.pi * fy[0])
                              * jnp.sin(2 * jnp.pi * fy[1]), g.n))
        nu_t = turbulence.eddy_viscosity_smagorinsky(u, g.dx)
        vals.append(float(jnp.max(nu_t)))
    ratio = vals[0] / vals[1]
    assert 3.5 < ratio < 4.5, (vals, ratio)


def test_k_omega_homogeneous_decay_matches_ode():
    """No flow, uniform (k, omega): the transport system reduces to
      dw/dt = -beta w^2   ->  w(t) = w0 / (1 + beta w0 t)
      dk/dt = -beta* k w  ->  k(t) = k0 (1 + beta w0 t)^(-beta*/beta)
    The pointwise-implicit discrete sinks must track this to O(dt)."""
    g = _grid(16)
    model = turbulence.KOmegaModel(g, nu=0.0)
    k0, w0 = 1.0, 5.0
    st = turbulence.KOmegaState(
        k=jnp.full(g.n, k0, dtype=jnp.float64),
        omega=jnp.full(g.n, w0, dtype=jnp.float64))
    u = tuple(jnp.zeros(g.n, dtype=jnp.float64) for _ in range(2))
    dt, steps = 1e-3, 2000
    adv = jax.jit(lambda s: model.advance(s, u, dt))
    for _ in range(steps):
        st = adv(st)
    t = dt * steps
    beta, beta_star = model.beta, model.beta_star
    w_exact = w0 / (1.0 + beta * w0 * t)
    k_exact = k0 * (1.0 + beta * w0 * t) ** (-beta_star / beta)
    assert np.isclose(float(st.omega[0, 0]), w_exact, rtol=2e-3), \
        (float(st.omega[0, 0]), w_exact)
    assert np.isclose(float(st.k[0, 0]), k_exact, rtol=5e-3), \
        (float(st.k[0, 0]), k_exact)
    # still uniform (advection/diffusion of a uniform field is zero)
    assert float(jnp.std(st.k)) < 1e-12


def test_les_taylor_green_high_re_bounded():
    """64^2 Taylor-Green at Re ~ 4e4 (hopelessly under-resolved DNS):
    the LES step must stay finite with monotonically decaying energy
    (dt inside the EXPLICIT eddy-viscosity stability limit — the
    calibration found dt = 5e-3 blows while 2.5e-3 is stable), and the
    t=0 eddy viscosity matches the hand-computed (Cs Delta)^2 |S|."""
    n = 64
    g = _grid(n, L=2.0 * math.pi)
    les = turbulence.SmagorinskyINS(g, mu=1e-4, rho=1.0, cs=0.17)
    fx = g.face_centers(0, jnp.float32)
    fy = g.face_centers(1, jnp.float32)
    u0 = (jnp.broadcast_to(jnp.sin(fx[0]) * jnp.cos(fx[1]), g.n),
          jnp.broadcast_to(-jnp.cos(fy[0]) * jnp.sin(fy[1]), g.n))
    # analytic check: TG |S| = sqrt(2 E:E), max|E_xy| = ... = 2 at the
    # vortex corners (|du/dy + dv/dx|/2 = |sin x sin y| max 1... times
    # the two off-diagonals) -> max |S| = 2, nu_t_max = (Cs dx)^2 * 2
    nu_t0 = turbulence.eddy_viscosity_smagorinsky(u0, g.dx, cs=0.17)
    expect = (0.17 * float(g.dx[0])) ** 2 * 2.0
    assert abs(float(jnp.max(nu_t0)) - expect) < 0.2 * expect
    st = les.initialize(u0=u0)
    step = jax.jit(lambda s: les.step(s, 2.5e-3))
    e0 = float(sum(jnp.sum(c * c) for c in st.u))
    for k in range(300):
        st = step(st)
        if (k + 1) % 50 == 0:
            e = float(sum(jnp.sum(c * c) for c in st.u))
            assert np.isfinite(e)
            # bounded (small AB2/projection startup transients allowed;
            # the unstable dt blows through this within ~30 steps)
            assert e < 1.05 * e0, (k, e, e0)
    assert e < e0                      # net viscous dissipation


def test_k_omega_shear_production():
    """URANS shear layer: production pumps k exactly where the resolved
    shear is; k elsewhere only decays. nu_t stays positive/finite."""
    n = 64
    g = _grid(n)
    ko = turbulence.KOmegaINS(g, mu=1e-4, rho=1.0)
    fx = g.face_centers(0, jnp.float32)
    shear = jnp.tanh((fx[1] - 0.5) / 0.05)
    u0 = (jnp.broadcast_to(0.5 * shear, g.n),
          jnp.zeros(g.n, dtype=jnp.float32))
    ins, turb = ko.initialize(u0=u0, k0=1e-5, omega0=2.0)
    step = jax.jit(lambda a, b: ko.step(a, b, 2e-3))
    for _ in range(450):
        ins, turb = step(ins, turb)
    k_field = np.asarray(turb.k)
    mid = k_field[:, n // 2 - 2:n // 2 + 2].mean()   # in the layer
    # the quiet band is y ~ 0.25: the tanh profile ALSO jumps at the
    # periodic wrap (a second shear layer at j=0), so "far" must avoid
    # both layers
    far = k_field[:, 12:20].mean()
    assert np.isfinite(k_field).all()
    assert mid > 10.0 * far, (mid, far)
    assert mid > 5e-5                                 # produced, not decayed
    assert far < 1e-5                                 # far field only decays
    nu_t = np.asarray(ko.model.nu_t(turb))
    assert (nu_t >= 0).all() and np.isfinite(nu_t).all()


def test_komega_channel_law_of_the_wall():
    """Wall-RESOLVED k-omega channel at Re_tau = 395 (VERDICT round 3,
    weak #5): the steady profile must reproduce the viscous sublayer
    u+ = y+ and the log law u+ = ln(y+)/0.41 + 5.0, and satisfy the
    exact total-stress balance (1 + nu_t+) du+/dy+ = 1 - y+/Re_tau —
    the latter is the discrete steady-state certificate."""
    import numpy as np

    from ibamr_tpu.physics.turbulence import channel_komega

    p = channel_komega(re_tau=395.0, n=80, iters=30000)
    y = np.asarray(p.y_plus)
    u = np.asarray(p.u_plus)

    # viscous sublayer: u+ = y+ within 2% at y+ ~ 2
    assert abs(np.interp(2.0, y, u) - 2.0) < 0.04

    # log layer: within 0.7 plus-units of the Coles log law over
    # 30 <= y+ <= 100 (Wilcox-88's known accuracy at this Re_tau)
    for yp in (30.0, 50.0, 70.0, 100.0):
        loglaw = np.log(yp) / 0.41 + 5.0
        assert abs(np.interp(yp, y, u) - loglaw) < 0.7, (yp,)

    # steady total-stress balance (away from the end cells where the
    # np.gradient stencil is one-sided)
    g = np.gradient(u, y)
    tot = (1.0 + np.asarray(p.nu_t_plus)) * g
    expect = 1.0 - y / 395.0
    assert float(np.max(np.abs(tot - expect)[5:-5])) < 0.02

    # eddy viscosity grows away from the wall and k peaks near-wall
    nut = np.asarray(p.nu_t_plus)
    assert nut[0] < 0.1 and np.max(nut) > 20.0
    k = np.asarray(p.k_plus)
    assert 5.0 < y[np.argmax(k)] < 60.0     # near-wall k peak


def test_smagorinsky_walled_channel_decays_bounded():
    """Wall-bounded LES (Smagorinsky over the VC wall machinery): a
    sheared channel stream decays monotonically in energy and stays
    bounded — the LES term must only ever add dissipation in the
    no-slip channel."""
    import numpy as np

    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.physics.turbulence import SmagorinskyINS

    n = 32
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    les = SmagorinskyINS(g, mu=1e-3, rho=1.0, cs=0.17,
                         wall_axes=(False, True), dtype=jnp.float64)
    yc = (np.arange(n) + 0.5) / n
    u0x = jnp.asarray(np.broadcast_to(
        np.sin(np.pi * yc)[None, :] * (1.0 + 0.1 * np.sin(
            4 * np.pi * yc))[None, :], (n, n)))
    st = les.initialize(u0=(u0x, jnp.zeros((n, n), dtype=jnp.float64)))
    e = [float(sum(jnp.sum(c * c) for c in st.u))]
    step = jax.jit(lambda s: les.step(s, 1e-3))
    for _ in range(5):
        for _ in range(20):
            st = step(st)
        e.append(float(sum(jnp.sum(c * c) for c in st.u)))
    assert all(b < a for a, b in zip(e, e[1:])), e
    assert bool(jnp.all(jnp.isfinite(st.u[0])))
    # wall faces pinned
    assert float(jnp.max(jnp.abs(st.u[1][:, 0:1]))) == 0.0


def test_komega_walled_transport_sane():
    """Wall-bounded k-omega TRANSPORT (round 4): on a walled axis the
    model holds the omega smooth-wall asymptote rows, drains k at the
    k=0 walls (one-sided Dirichlet wall flux), keeps everything
    positive/finite, and the interior still follows the homogeneous
    decay it is pinned to in the periodic test."""
    import numpy as np

    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.physics.turbulence import KOmegaModel, KOmegaState

    n = 48
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    nu = 1e-3
    model = KOmegaModel(g, nu=nu, wall_axes=(False, True))
    k0, w0 = 1.0, 5.0
    st = KOmegaState(k=jnp.full((n, n), k0, dtype=jnp.float64),
                     omega=jnp.full((n, n), w0, dtype=jnp.float64))
    u = (jnp.zeros((n, n), dtype=jnp.float64),
         jnp.zeros((n, n), dtype=jnp.float64))
    dt = 2e-3
    T = 200
    for _ in range(T):
        st = model.advance(st, u, dt)
    k = np.asarray(st.k)
    w = np.asarray(st.omega)
    assert np.all(np.isfinite(k)) and np.all(np.isfinite(w))
    assert k.min() >= 0.0
    # omega wall rows hold the asymptote (both walls, two layers)
    h = 1.0 / n
    for layer in (0, 1):
        val = 6.0 * nu / (KOmegaModel.beta * ((layer + 0.5) * h) ** 2)
        np.testing.assert_allclose(w[:, layer], val, rtol=1e-12)
        np.testing.assert_allclose(w[:, n - 1 - layer], val, rtol=1e-12)
    # k drains fastest at the k=0 walls: wall-adjacent k well below
    # the mid-channel value
    assert k[:, 0].max() < 0.5 * k[:, n // 2].min()
    # interior (away from walls) still tracks the homogeneous decay
    # ODE pair within a few percent
    from scipy.integrate import solve_ivp

    def rhs(t, y):
        kk, ww = y
        return [-KOmegaModel.beta_star * kk * ww,
                -KOmegaModel.beta * ww * ww]

    sol = solve_ivp(rhs, [0.0, T * dt], [k0, w0], rtol=1e-10,
                    atol=1e-12)
    k_exact = sol.y[0, -1]
    mid = k[n // 4:3 * n // 4, n // 2]
    assert abs(float(mid.mean()) - k_exact) / k_exact < 0.05


def test_komega_ins_walled_channel_smoke():
    """Wall-bounded URANS driver: an UNDRIVEN plug flow eroding at
    the no-slip walls — the walls shear a symmetric near-wall deficit
    into the profile while k and omega stay positive and the
    wall-normal velocity faces stay pinned. (Sustained driven-channel
    equilibrium is validated by the 1D wall-resolved channel_komega
    law-of-the-wall test, not here.)"""
    import numpy as np

    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.physics.turbulence import KOmegaINS

    n = 32
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    ko = KOmegaINS(g, mu=2e-3, rho=1.0, wall_axes=(False, True),
                   dtype=jnp.float64)
    dt = 5e-4
    step = jax.jit(lambda i, t: ko.step(i, t, dt))
    # start from a plug flow and watch the walls erode it while the
    # turbulence fields stay sane
    u0x = jnp.ones((n, n), dtype=jnp.float64)
    ins, turb = ko.initialize(u0=(u0x, jnp.zeros((n, n),
                                                 dtype=jnp.float64)),
                              k0=1e-3, omega0=10.0)
    for _ in range(150):
        ins, turb = step(ins, turb)
    u = np.asarray(ins.u[0])
    assert np.all(np.isfinite(u))
    prof = u.mean(axis=0)
    assert prof[0] < prof[n // 2] and prof[-1] < prof[n // 2]
    assert float(jnp.min(turb.k)) >= 0.0
    assert float(jnp.max(jnp.abs(ins.u[1][:, 0:1]))) == 0.0


# ---------------------------------------------------------------------------
# LES in a refined window (round 5, VERDICT item 3b: AMR x P22)
# ---------------------------------------------------------------------------

def test_les_refined_window_matches_uniform_fine():
    """Smagorinsky LES composed with the two-level hierarchy: a
    composite run with the window over the energetic region must track
    the UNIFORM-FINE Smagorinsky oracle inside the window, and the
    eddy stress must be load-bearing (the no-LES composite drifts from
    the oracle by much more)."""
    F64 = jnp.float64
    import numpy as np

    from ibamr_tpu.amr import FineBox
    from ibamr_tpu.amr_ins import restrict_mac
    from ibamr_tpu.physics.turbulence import (SmagorinskyINS,
                                              TwoLevelSmagorinskyINS)

    n = 32
    r = 2
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    gf = StaggeredGrid(n=(n * r, n * r), x_lo=(0.0, 0.0),
                       x_up=(1.0, 1.0))
    box = FineBox(lo=(8, 8), shape=(16, 16))
    mu, rho, cs, amp = 1e-3, 1.0, 0.4, 2.0
    dt, steps = 1.5e-3, 12

    def tg(grid):
        # compact vortex centered in the window, discretely div-free:
        # psi at nodes, MAC faces by differencing (the quiet exterior
        # keeps the comparison from being CF-boundary-dominated)
        sig = 0.1
        xn = np.arange(grid.n[0] + 1) * grid.dx[0]
        yn = np.arange(grid.n[1] + 1) * grid.dx[1]
        XN, YN = np.meshgrid(xn, yn, indexing="ij")
        psi = amp * sig * np.exp(
            -((XN - 0.5) ** 2 + (YN - 0.5) ** 2) / (2 * sig ** 2))
        u = (psi[:-1, 1:] - psi[:-1, :-1]) / grid.dx[1]
        v = -(psi[1:, :-1] - psi[:-1, :-1]) / grid.dx[0]
        return (jnp.asarray(u, F64), jnp.asarray(v, F64))

    # uniform-fine oracle with the SAME discretization as the
    # composite core (explicit centered convection + explicit
    # diffusion + exact projection), so the comparison isolates the
    # hierarchy composition instead of time-scheme differences
    from ibamr_tpu.integrators.ins_vc import INSVCStaggeredIntegrator
    from ibamr_tpu.ops import stencils
    from ibamr_tpu.ops.convection import convective_rate
    from ibamr_tpu.physics.turbulence import eddy_viscosity_smagorinsky
    from ibamr_tpu.solvers import fft

    vc_f = INSVCStaggeredIntegrator(gf, rho0=rho, rho1=rho, mu0=mu,
                                    mu1=mu, reinit_interval=0,
                                    precond="fft")

    def fine_step(u, dt):
        lap = stencils.laplacian_vel(u, gf.dx)
        nc = convective_rate(u, gf.dx, "centered")
        mu_t = rho * eddy_viscosity_smagorinsky(u, gf.dx, cs)
        fe = vc_f._viscous_force(u, mu_t)
        ustar = tuple(u[d] + dt * (-nc[d] + (mu * lap[d] + fe[d]) / rho)
                      for d in range(2))
        u_new, _ = fft.project_divergence_free(ustar, gf.dx)
        return u_new

    uf_o = tg(gf)
    for _ in range(steps):
        uf_o = fine_step(uf_o, dt)

    class _O:  # oracle state shim
        u = uf_o
    st_f = _O()

    # composite-window LES + no-LES control. The window is seeded
    # with the FINE-sampled field (not the prolonged coarse one), so
    # both runs start from the oracle's exact initial data inside the
    # window and the comparison isolates the STEPPING composition
    from ibamr_tpu.amr_ins import (TwoLevelINSState,
                                   scatter_box_mac_to_coarse)

    les = TwoLevelSmagorinskyINS(g, box, mu=mu, rho=rho, cs=cs)
    uc0 = tg(g)
    uf_full = tg(gf)
    uf0 = []
    for d in range(2):
        sl = tuple(slice(box.lo[a] * r,
                         box.lo[a] * r + box.fine_n[a]
                         + (1 if a == d else 0)) for a in range(2))
        uf0.append(uf_full[d][sl])
    uf0 = tuple(uf0)
    uc_sync = scatter_box_mac_to_coarse(uc0, restrict_mac(uf0), box)
    st = TwoLevelINSState(uc=uc_sync, uf=uf0,
                          t=jnp.zeros((), F64),
                          k=jnp.zeros((), jnp.int32))
    st_n = st
    for _ in range(steps):
        st = les.step(st, dt)
        st_n = les.core.step(st_n, dt)

    # compare the window's fine field against the oracle's same cells
    sl = tuple(slice(box.lo[d] * r, box.lo[d] * r + box.fine_n[d])
               for d in range(2))
    gaps, gaps_ctrl = [], []
    for d in range(2):
        ref = np.asarray(st_f.u[d])[sl]
        win = np.asarray(st.uf[d])[tuple(slice(0, s.stop - s.start)
                                         for s in sl)]
        ctrl = np.asarray(st_n.uf[d])[tuple(slice(0, s.stop - s.start)
                                            for s in sl)]
        gaps.append(np.max(np.abs(win - ref)))
        gaps_ctrl.append(np.max(np.abs(ctrl - ref)))
    gap, gap_ctrl = max(gaps), max(gaps_ctrl)
    # tracks the oracle within scheme-difference tolerance...
    assert gap < 0.05 * amp, (gap, gap_ctrl)
    # ...and the eddy stress is load-bearing: without it the composite
    # drifts from the LES oracle several times farther
    assert gap_ctrl > 2.0 * gap, (gap, gap_ctrl)
    assert float(les.max_divergence(st)) < 1e-7
