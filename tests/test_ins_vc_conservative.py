"""Conservative-form VC INS (the INSVCStaggeredConservative half of
P22): consistent mass-momentum transport.

Oracles: EXACT global mass conservation (telescoping upwind fluxes),
EXACT global momentum conservation under net-force-free forcing (the
property the non-conservative velocity form cannot have — compared
head-to-head), uniform-flow preservation, hydrostatic quiescence, and
relative drop buoyancy."""

import jax.numpy as jnp
import numpy as np

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ins_vc import (INSVCConservativeIntegrator,
                                          INSVCStaggeredIntegrator,
                                          advance_vc,
                                          advance_vc_conservative)


def _drop_phi(n, center=(0.5, 0.6), r0=0.12):
    x = (np.arange(n) + 0.5) / n
    X, Y = np.meshgrid(x, x, indexing="ij")
    return jnp.asarray(
        r0 - np.sqrt((X - center[0]) ** 2 + (Y - center[1]) ** 2),
        dtype=jnp.float64)


def _grid(n=32):
    return StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))


def test_mass_and_momentum_conserved_exactly():
    g = _grid()
    integ = INSVCConservativeIntegrator(
        g, rho0=1.0, rho1=100.0, mu0=0.02, mu1=0.05,
        gravity=(0.0, -1.0), sigma=0.0, cg_tol=1e-11,
        dtype=jnp.float64)
    st = integ.initialize(_drop_phi(32))
    m0 = float(integ.total_mass(st))
    p0 = [float(c) for c in integ.total_momentum(st)]
    st = advance_vc_conservative(integ, st, 2e-4, 60)
    m1 = float(integ.total_mass(st))
    p1 = [float(c) for c in integ.total_momentum(st)]
    assert abs(m1 - m0) < 1e-12 * m0
    for a, b in zip(p0, p1):
        assert abs(b - a) < 1e-11          # roundoff-scale drift


def test_momentum_conservation_beats_nonconservative():
    """Head-to-head under identical physics: the conservative form's
    momentum drift is orders of magnitude below the velocity form's."""
    g = _grid()
    phi0 = _drop_phi(32)
    kw = dict(rho0=1.0, rho1=100.0, mu0=0.02, mu1=0.05,
              gravity=(0.0, -1.0), sigma=0.0, cg_tol=1e-10,
              dtype=jnp.float64)
    cons = INSVCConservativeIntegrator(g, **kw)
    nonc = INSVCStaggeredIntegrator(g, **kw)

    st_c = cons.initialize(phi0)
    st_c = advance_vc_conservative(cons, st_c, 2e-4, 60)
    drift_c = abs(float(cons.total_momentum(st_c)[1]))

    st_n = nonc.initialize(phi0)
    st_n = advance_vc(nonc, st_n, 2e-4, 60)
    rho_n = nonc.density(st_n.phi)
    mom_n = float(jnp.sum(st_n.u[1]
                          / (0.5 * (1.0 / rho_n
                                    + jnp.roll(1.0 / rho_n, 1, 1))))
                  * g.cell_volume)
    assert drift_c < 1e-9
    assert abs(mom_n) > 1e-4 * 1.0     # velocity form drifts visibly
    assert drift_c < 1e-3 * abs(mom_n)


def test_uniform_flow_preserved():
    """Uniform rho + uniform u is an exact discrete equilibrium."""
    g = _grid(16)
    integ = INSVCConservativeIntegrator(
        g, rho0=1.0, rho1=1.0, mu0=0.02, mu1=0.02, cg_tol=1e-12,
        dtype=jnp.float64)
    u0 = (jnp.full(g.n, 0.3), jnp.full(g.n, -0.2))
    st = integ.initialize(jnp.full(g.n, -1.0), u0_arrays=u0)
    st = advance_vc_conservative(integ, st, 1e-3, 10)
    assert np.max(np.abs(np.asarray(st.u[0]) - 0.3)) < 1e-12
    assert np.max(np.abs(np.asarray(st.u[1]) + 0.2)) < 1e-12


def test_uniform_translation_of_density_jump_is_equilibrium():
    """THE consistency property: a dense drop translating in uniform
    flow (mu=0, sigma=0, no gravity) must stay in uniform flow — the
    face momentum density is updated by the same interpolated mass
    fluxes as the momentum advection, so no spurious interface
    accelerations develop (regression: the harmonic face rule produced
    ~17% spurious velocity in 20 steps at ratio 100)."""
    g = _grid(32)
    integ = INSVCConservativeIntegrator(
        g, rho0=1.0, rho1=100.0, mu0=0.0, mu1=0.0, sigma=0.0,
        reinit_interval=10 ** 9, cg_tol=1e-12, dtype=jnp.float64)
    u0 = (jnp.full(g.n, 0.3), jnp.zeros(g.n))
    st = integ.initialize(_drop_phi(32), u0_arrays=u0)
    st = advance_vc_conservative(integ, st, 5e-4, 20)
    assert np.max(np.abs(np.asarray(st.u[0]) - 0.3)) < 1e-10
    assert np.max(np.abs(np.asarray(st.u[1]))) < 1e-10


def test_hydrostatic_pool_quiescent_conservative():
    g = _grid()
    y = (np.arange(32) + 0.5) / 32
    phi0 = jnp.asarray(np.broadcast_to((0.5 - y)[None, :], (32, 32)),
                       dtype=jnp.float64)
    integ = INSVCConservativeIntegrator(
        g, rho0=1.0, rho1=100.0, mu0=0.01, mu1=0.01,
        gravity=(0.0, -1.0), sigma=0.0, reinit_interval=1000,
        cg_tol=1e-11, dtype=jnp.float64)
    st = integ.initialize(phi0)
    st = advance_vc_conservative(integ, st, 1e-3, 20)
    umax = max(float(jnp.max(jnp.abs(c))) for c in st.u)
    assert umax < 1e-9, umax


def test_drop_buoyancy_conservative():
    g = _grid()
    integ = INSVCConservativeIntegrator(
        g, rho0=1.0, rho1=100.0, mu0=0.02, mu1=0.05,
        gravity=(0.0, -1.0), cg_tol=1e-9, dtype=jnp.float64)
    st = integ.initialize(_drop_phi(32))
    st = advance_vc_conservative(integ, st, 2e-4, 100)
    v = np.asarray(st.u[1])
    H = np.asarray(st.phi) > 0
    assert v[H].mean() < -1e-4
    assert v[~H].mean() > 1e-6
    # and, unlike the velocity form, with ~zero mean drift
    assert abs(float(integ.total_momentum(st)[1])) < 1e-8


def test_conservative_3d_smoke():
    """Dimension-generic: 3D conservative step conserves mass exactly
    and stays finite."""
    n = 16
    g3 = StaggeredGrid(n=(n,) * 3, x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    x = (np.arange(n) + 0.5) / n
    X, Y, Z = np.meshgrid(x, x, x, indexing="ij")
    phi0 = jnp.asarray(
        0.2 - np.sqrt((X - 0.5) ** 2 + (Y - 0.6) ** 2 + (Z - 0.5) ** 2),
        dtype=jnp.float64)
    integ = INSVCConservativeIntegrator(
        g3, rho0=1.0, rho1=50.0, mu0=0.02, mu1=0.05,
        gravity=(0.0, -1.0, 0.0), cg_tol=1e-9, dtype=jnp.float64)
    st = integ.initialize(phi0)
    m0 = float(integ.total_mass(st))
    st = advance_vc_conservative(integ, st, 2e-4, 20)
    assert abs(float(integ.total_mass(st)) - m0) < 1e-12 * m0
    assert all(np.all(np.isfinite(np.asarray(c))) for c in st.u)
    mom = [abs(float(c)) for c in integ.total_momentum(st)]
    assert max(mom) < 1e-10
