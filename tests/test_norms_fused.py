"""The fused stacked reduction (PR 16 satellite): ``tree_dots`` must
return EXACTLY what K scalar ``tree_dot`` calls return — each row
reduces the same elements in the same order — because the Krylov
solvers now route their per-iteration (r,z)/(r,r) and (t,t)/(t,s)
pairs through it to collapse two sync collectives into one. Any value
drift here would silently change every CG/BiCGStab trajectory."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.ops.norms import tree_dot, tree_dots


def _rand_tree(key, shapes):
    ks = jax.random.split(key, len(shapes))
    return tuple(jax.random.normal(k, s, dtype=jnp.float64)
                 for k, s in zip(ks, shapes))


@pytest.mark.parametrize("shapes", [
    [(17,)],
    [(8, 8), (8, 8), (64,)],            # velocity-tuple-like pytree
    [(4, 4, 4)],
])
def test_tree_dots_rows_equal_tree_dot_exactly(shapes):
    key = jax.random.PRNGKey(0)
    ka, kb, kc, kd = jax.random.split(key, 4)
    a, b = _rand_tree(ka, shapes), _rand_tree(kb, shapes)
    c, d = _rand_tree(kc, shapes), _rand_tree(kd, shapes)

    fused = tree_dots([(a, b), (a, a), (c, d), (d, d)])
    scalars = [tree_dot(a, b), tree_dot(a, a),
               tree_dot(c, d), tree_dot(d, d)]
    assert fused.shape == (4,)
    for row, s in zip(np.asarray(fused), scalars):
        # bitwise: identical reduction tree per row
        assert float(row) == float(np.asarray(s))


def test_tree_dots_matches_tree_dot_inside_one_compiled_program():
    # the contract the Krylov solvers actually rely on: INSIDE one
    # compiled solve, swapping K scalar dots for the fused vector is
    # value-neutral (jit-vs-eager bitwise is NOT promised — XLA may
    # reassociate a lone reduction differently from the eager path)
    key = jax.random.PRNGKey(3)
    a = jax.random.normal(key, (32,), dtype=jnp.float64)
    b = a * 0.5 - 1.0

    @jax.jit
    def both(x, y):
        fused = tree_dots([(x, y), (y, y)])
        return fused, jnp.stack([tree_dot(x, y), tree_dot(y, y)])

    fused, scalars = both(a, b)
    np.testing.assert_array_equal(np.asarray(fused), np.asarray(scalars))


def test_tree_dots_empty_and_singleton():
    assert tree_dots([]).shape == (0,)
    x = jnp.arange(5.0)
    one = tree_dots([(x, x)])
    assert one.shape == (1,)
    assert float(one[0]) == float(tree_dot(x, x))


def test_krylov_cg_trajectory_unchanged_by_fusion():
    # the consumer-side pin: CG on an SPD system converges to the same
    # answer through the fused reductions (values are bitwise per
    # iteration, so iterates and iteration count match the reference
    # semantics of the scalar-dot formulation)
    from ibamr_tpu.solvers.krylov import cg

    n = 24
    key = jax.random.PRNGKey(7)
    d = 1.0 + jax.random.uniform(key, (n,), dtype=jnp.float64)

    def A(x):
        return d * x + 0.25 * (jnp.roll(x, 1) + jnp.roll(x, -1))

    b = jnp.sin(jnp.arange(n, dtype=jnp.float64))
    res = cg(A, b, tol=1e-12, maxiter=200)
    assert bool(res.converged)
    r = b - A(res.x)
    assert float(jnp.linalg.norm(r)) <= 1e-10 * max(
        1.0, float(jnp.linalg.norm(b)))
