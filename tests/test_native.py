"""Native C++ host-runtime tests: the ctypes-bound parser/encoder must
agree exactly with the Python fallbacks, survive comments/short rows,
and beat the Python path on large files."""

import base64
import os
import time
import xml.etree.ElementTree as ET

import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.io import structures
from ibamr_tpu.io.native import base64_native, get_lib, parse_table_native
from ibamr_tpu.io.vtk import write_vti

HAVE_NATIVE = get_lib() is not None


@pytest.mark.skipif(not HAVE_NATIVE, reason="g++ toolchain unavailable")
def test_parse_table_matches_python():
    text = b"""3  # count line with comment
0.5 1.5 2.5
// full-line comment to skip
1.0 2.0
-3.5e-2 4e3 5 6
"""
    rows, ncols = parse_table_native(text, 4)
    assert rows.shape[0] == 4          # count line + 3 data rows
    assert ncols.tolist() == [1, 3, 2, 4]
    assert rows[0, 0] == 3.0
    assert np.allclose(rows[1, :3], [0.5, 1.5, 2.5])
    assert np.allclose(rows[2, :2], [1.0, 2.0])
    assert np.allclose(rows[3], [-3.5e-2, 4e3, 5.0, 6.0])


@pytest.mark.skipif(not HAVE_NATIVE, reason="g++ toolchain unavailable")
def test_structure_roundtrip_native_vs_python(tmp_path):
    rng = np.random.RandomState(0)
    n = 500
    verts = rng.rand(n, 2)
    springs = np.stack([np.arange(n), (np.arange(n) + 1) % n,
                        np.full(n, 2.0), np.full(n, 0.01)], axis=1)
    data = structures.StructureData(name="s", vertices=verts,
                                    springs=springs)
    base = str(tmp_path / "s")
    structures.write_structure(base, data)

    back_native = structures.read_structure(base)
    # force the Python path by monkeypatching the native probe
    orig = structures._read_table_native
    structures._read_table_native = lambda *a, **k: None
    try:
        back_python = structures.read_structure(base)
    finally:
        structures._read_table_native = orig
    assert np.allclose(back_native.vertices, back_python.vertices)
    assert np.allclose(back_native.springs, back_python.springs)
    assert np.allclose(back_native.vertices, verts, atol=1e-12)


@pytest.mark.skipif(not HAVE_NATIVE, reason="g++ toolchain unavailable")
def test_base64_matches_stdlib():
    rng = np.random.RandomState(1)
    for n in (0, 1, 2, 3, 100, 1001):
        data = rng.bytes(n)
        assert base64_native(data) == base64.b64encode(data)


@pytest.mark.skipif(not HAVE_NATIVE, reason="g++ toolchain unavailable")
def test_binary_vti_roundtrip(tmp_path):
    grid = StaggeredGrid(n=(6, 5), x_lo=(0, 0), x_up=(1, 1))
    rng = np.random.RandomState(2)
    p = rng.randn(6, 5).astype(np.float32)
    path = write_vti(str(tmp_path / "b.vti"), grid, {"p": p},
                     fmt="binary")
    root = ET.parse(path).getroot()
    da = next(d for d in root.iter("DataArray") if d.get("Name") == "p")
    assert da.get("format") == "binary"
    raw = base64.b64decode(da.text.strip())
    nbytes = np.frombuffer(raw[:4], dtype=np.uint32)[0]
    vals = np.frombuffer(raw[4:4 + nbytes], dtype=np.float32)
    assert np.allclose(vals, p.ravel(order="F"))


@pytest.mark.skipif(not HAVE_NATIVE, reason="g++ toolchain unavailable")
def test_native_parser_speedup(tmp_path):
    # a structure file large enough that tokenization dominates
    n = 200_000
    rng = np.random.RandomState(3)
    verts = rng.rand(n, 3)
    path = str(tmp_path / "big.vertex")
    with open(path, "w") as f:
        f.write(f"{n}\n")
        np.savetxt(f, verts, fmt="%.8f")

    t0 = time.perf_counter()
    fast = structures._read_table(path, 2, 3, "vertex")
    t_native = time.perf_counter() - t0

    orig = structures._read_table_native
    structures._read_table_native = lambda *a, **k: None
    try:
        t0 = time.perf_counter()
        slow = structures._read_table(path, 2, 3, "vertex")
        t_python = time.perf_counter() - t0
    finally:
        structures._read_table_native = orig
    assert np.allclose(fast, slow)
    assert t_native < t_python, (t_native, t_python)


@pytest.mark.skipif(not HAVE_NATIVE, reason="g++ toolchain unavailable")
def test_native_strict_rejects_bad_tokens(tmp_path):
    # corrupt token: both paths must raise, not shift columns
    p = tmp_path / "bad.spring"
    p.write_text("1\n0 1 oops 100.0\n")
    with pytest.raises(ValueError):
        structures._read_table(str(p), 4, 5, "spring")
    # hex and partial floats rejected too
    for tok in ("0x10", "1e"):
        p.write_text(f"1\n0 1 {tok} 0.5\n")
        with pytest.raises(ValueError):
            structures._read_table(str(p), 4, 5, "spring")


@pytest.mark.skipif(not HAVE_NATIVE, reason="g++ toolchain unavailable")
def test_native_rejects_extra_columns(tmp_path):
    p = tmp_path / "t.target"
    p.write_text("1\n1 2 3 4 5\n")
    with pytest.raises(ValueError, match="columns"):
        structures._read_table(str(p), 2, 3, "target")


@pytest.mark.skipif(not HAVE_NATIVE, reason="g++ toolchain unavailable")
def test_native_rejects_bad_count(tmp_path):
    p = tmp_path / "v.vertex"
    p.write_text("0.5 1.5\n0.25 0.75\n")   # missing count header
    with pytest.raises(ValueError, match="count"):
        structures._read_table(str(p), 2, 3, "vertex")
    p.write_text("-3\n1 2\n")
    with pytest.raises(ValueError, match="count"):
        structures._read_table(str(p), 2, 3, "vertex")


@pytest.mark.skipif(not HAVE_NATIVE, reason="g++ toolchain unavailable")
def test_native_preserves_data_nan(tmp_path):
    p = tmp_path / "t.target"
    p.write_text("1\n1.0 2.0 nan\n")
    out = structures._read_table(str(p), 2, 3, "target")
    assert np.isnan(out[0, 2])   # genuine nan survives, pads do not
    p.write_text("2\n1.0 2.0 nan\n3.0 4.0\n")
    out = structures._read_table(str(p), 2, 3, "target")
    assert np.isnan(out[0, 2]) and out[1, 2] == 0.0
