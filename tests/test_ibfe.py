"""IBFE finite-element structure tests (stage 10, P17/T16 parity).

Oracles: mesh/quadrature measure identities, zero residual force at the
reference configuration, autodiff assembly == explicit PK1 assembly,
exact force conservation under spreading, and the end-to-end stretched-
disc relaxation (the IBFE/explicit/ex0 acceptance behavior).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ibamr_tpu.fe import (block_mesh_tet, block_mesh_tri, build_assembly,
                          deformation_gradients, disc_mesh, elastic_energy,
                          l2_project_from_quads, neo_hookean, nodal_forces,
                          nodal_forces_pk1, project_to_quads, quad_positions,
                          read_triangle, stvk)
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.models.fe_disc2d import build_fe_disc_example
from ibamr_tpu.integrators.ibfe import IBFEMethod


F64 = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


# -- mesh + assembly ---------------------------------------------------------

def test_block_mesh_measure():
    m2 = block_mesh_tri(4, 3, (0.0, 0.0), (2.0, 1.5))
    assert np.isclose(m2.volume(), 3.0)
    m3 = block_mesh_tet(2, 2, 2, (0, 0, 0), (1, 2, 1))
    assert np.isclose(m3.volume(), 2.0)


def test_disc_mesh_area():
    m = disc_mesh(radius=0.3, center=(0.5, 0.5), n_rings=16)
    # polygonal approximation of the circle: area below pi r^2, O(1/n^2)
    assert abs(m.volume() - np.pi * 0.09) / (np.pi * 0.09) < 5e-3


def test_assembly_measures_match_mesh():
    for m in (block_mesh_tri(3, 3), disc_mesh(n_rings=4),
              block_mesh_tet(2, 2, 2)):
        asm = build_assembly(m, dtype=F64)
        assert np.isclose(float(jnp.sum(asm.wdV)), m.volume(), rtol=1e-5)
        assert np.isclose(float(jnp.sum(asm.lumped_mass)), m.volume(),
                          rtol=1e-5)


def test_identity_deformation():
    m = disc_mesh(n_rings=4)
    asm = build_assembly(m, dtype=F64)
    FF = deformation_gradients(asm, jnp.asarray(m.nodes, dtype=F64))
    assert np.allclose(np.asarray(FF),
                       np.broadcast_to(np.eye(2), FF.shape), atol=1e-5)


# -- forces ------------------------------------------------------------------

@pytest.mark.parametrize("W", [neo_hookean(1.0, 4.0), stvk(1.0, 4.0)])
def test_zero_force_at_reference(W):
    m = disc_mesh(n_rings=4)
    asm = build_assembly(m, dtype=F64)
    F = nodal_forces(asm, W, jnp.asarray(m.nodes, dtype=F64))
    assert float(jnp.max(jnp.abs(F))) < 1e-5


def test_translation_invariance_and_total_force():
    m = block_mesh_tri(3, 3)
    asm = build_assembly(m, dtype=F64)
    W = neo_hookean(1.0, 2.0)
    x = jnp.asarray(m.nodes, dtype=F64)
    x_def = x.at[:, 0].mul(1.3)  # uniaxial stretch
    F1 = nodal_forces(asm, W, x_def)
    F2 = nodal_forces(asm, W, x_def + jnp.array([0.7, -0.2], dtype=F64))
    assert np.allclose(np.asarray(F1), np.asarray(F2), atol=1e-5)
    # partition of unity => internal forces sum to zero
    assert np.allclose(np.asarray(jnp.sum(F1, axis=0)), 0.0, atol=1e-4)


@pytest.mark.parametrize("W", [neo_hookean(1.0, 4.0), stvk(0.5, 1.0)])
def test_autodiff_matches_pk1_assembly(W):
    m = disc_mesh(n_rings=3)
    asm = build_assembly(m, dtype=F64)
    rng = np.random.RandomState(0)
    x = jnp.asarray(m.nodes + 0.02 * rng.randn(*m.nodes.shape), dtype=F64)
    Fa = np.asarray(nodal_forces(asm, W, x))
    Fp = np.asarray(nodal_forces_pk1(asm, W, x))
    assert np.allclose(Fa, Fp, atol=1e-4 * max(1.0, np.abs(Fa).max()))


def test_energy_decreases_along_force():
    m = disc_mesh(n_rings=3)
    asm = build_assembly(m, dtype=F64)
    W = neo_hookean(1.0, 4.0)
    x = jnp.asarray(m.nodes, dtype=F64)
    x = x.at[:, 0].mul(1.2)
    E0 = float(elastic_energy(asm, W, x))
    F = nodal_forces(asm, W, x)
    E1 = float(elastic_energy(asm, W, x + 1e-3 * F))
    assert E1 < E0


# -- quadrature-point transfer (unified coupling) ----------------------------

def test_quad_projection_constant_roundtrip():
    m = disc_mesh(n_rings=4)
    asm = build_assembly(m, dtype=F64)
    c = jnp.full((asm.n_nodes, 2), 1.7, dtype=F64)
    cq = project_to_quads(asm, c)
    assert np.allclose(np.asarray(cq), 1.7, atol=1e-6)
    back = l2_project_from_quads(asm, cq)
    assert np.allclose(np.asarray(back), 1.7, atol=1e-5)


def test_quad_positions_inside_hull():
    m = disc_mesh(radius=0.2, center=(0.5, 0.5), n_rings=4)
    asm = build_assembly(m, dtype=F64)
    xq = np.asarray(quad_positions(asm, jnp.asarray(m.nodes, dtype=F64)))
    r = np.linalg.norm(xq - 0.5, axis=1)
    assert r.max() < 0.2


# -- coupling: spreading conservation + interp consistency -------------------

@pytest.mark.parametrize("coupling", ["nodal", "unified"])
def test_spread_conserves_total_force(coupling):
    grid = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    m = disc_mesh(radius=0.15, center=(0.5, 0.5), n_rings=4)
    fe = IBFEMethod(m, neo_hookean(1.0, 4.0), coupling=coupling, dtype=F64)
    rng = np.random.RandomState(1)
    X = jnp.asarray(m.nodes * 1.1 - 0.05, dtype=F64)
    F = jnp.asarray(rng.randn(m.n_nodes, 2), dtype=F64)
    mask = jnp.ones(m.n_nodes, dtype=F64)
    f = fe.spread_force(F, grid, X, mask)
    h2 = float(np.prod(grid.dx))
    total_grid = np.array([float(jnp.sum(comp)) * h2 for comp in f])
    total_F = np.asarray(jnp.sum(F, axis=0))
    assert np.allclose(total_grid, total_F, rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("coupling", ["nodal", "unified"])
def test_interp_constant_velocity(coupling):
    grid = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    m = disc_mesh(radius=0.15, center=(0.5, 0.5), n_rings=4)
    fe = IBFEMethod(m, neo_hookean(1.0, 4.0), coupling=coupling, dtype=F64)
    u = (jnp.full(grid.n, 0.8, dtype=F64),
         jnp.full(grid.n, -0.3, dtype=F64))
    mask = jnp.ones(m.n_nodes, dtype=F64)
    U = fe.interpolate_velocity(u, grid, jnp.asarray(m.nodes, dtype=F64),
                                mask)
    assert np.allclose(np.asarray(U[:, 0]), 0.8, atol=1e-5)
    assert np.allclose(np.asarray(U[:, 1]), -0.3, atol=1e-5)


# -- end-to-end: stretched disc relaxation (ex0 behavior) --------------------

@pytest.mark.parametrize("coupling", ["unified"])
def test_stretched_disc_relaxes(coupling):
    integ, state = build_fe_disc_example(
        n_cells=32, n_rings=4, radius=0.2, stretch=1.3,
        mu_s=1.0, lam_s=4.0, mu=0.1, coupling=coupling)
    fe = integ.ib
    E0 = float(fe.energy(state.X))
    A0 = float(fe.current_volume(state.X))
    dt = 2e-3
    from ibamr_tpu.integrators.ib import advance_ib
    state = jax.block_until_ready(advance_ib(integ, state, dt, 300))
    E1 = float(fe.energy(state.X))
    A1 = float(fe.current_volume(state.X))
    assert np.isfinite(E1) and E1 < 0.5 * E0      # elastic energy released
    assert abs(A1 - A0) / A0 < 0.02               # incompressibility
    # aspect ratio of the deformed disc has moved toward 1
    Xc = np.asarray(state.X) - np.asarray(state.X).mean(axis=0)
    sx, sy = Xc[:, 0].std(), Xc[:, 1].std()
    assert max(sx, sy) / min(sx, sy) < 1.25


# -- io ----------------------------------------------------------------------

def test_read_triangle_roundtrip(tmp_path):
    node = tmp_path / "m.node"
    ele = tmp_path / "m.ele"
    node.write_text(
        "4 2 0 0\n1 0.0 0.0\n2 1.0 0.0\n3 1.0 1.0\n4 0.0 1.0\n")
    ele.write_text("2 3 0\n1 1 2 3\n2 1 3 4\n")
    m = read_triangle(str(node), str(ele))
    assert m.n_nodes == 4 and m.n_elems == 2 and m.elem_type == "TRI3"
    assert np.isclose(m.volume(), 1.0)
    assert m.elems.min() == 0


@pytest.mark.parametrize("coupling", ["nodal", "unified"])
@pytest.mark.parametrize("family", ["volume", "surface"])
def test_fast_engine_matches_scatter(coupling, family):
    """IBFE transfers through the MXU bucketed engine equal the XLA
    scatter path to roundoff — the FE quadrature/node clouds are
    ordinary marker clouds to the engines (same contract the classic
    IB flagship pins). Covers the volumetric AND codim-1 surface
    strategies, with the prepare/ctx bucket-reuse protocol."""
    from ibamr_tpu.ops.interaction_fast import FastInteraction

    grid = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    eng = FastInteraction(grid, kernel="IB_4", tile=8, cap=64)
    if family == "volume":
        m = disc_mesh(radius=0.15, center=(0.5, 0.5), n_rings=4)
        fe0 = IBFEMethod(m, neo_hookean(1.0, 4.0), coupling=coupling,
                         dtype=F64)
        fe1 = IBFEMethod(m, neo_hookean(1.0, 4.0), coupling=coupling,
                         dtype=F64, fast=eng)
    else:
        from ibamr_tpu.fe import surface
        from ibamr_tpu.integrators.ibfe import IBFESurfaceMethod

        m = surface.ring_mesh(center=(0.5, 0.5), radius=0.15, n=48)
        W = surface.neo_hookean_membrane(1.0, 2.0)
        fe0 = IBFESurfaceMethod(m, W, coupling=coupling, dtype=F64)
        fe1 = IBFESurfaceMethod(m, W, coupling=coupling, dtype=F64,
                                fast=eng)
    rng = np.random.RandomState(3)
    X = jnp.asarray(m.nodes * 1.1 - 0.05, dtype=F64)
    F = jnp.asarray(rng.randn(m.n_nodes, 2), dtype=F64)
    mask = jnp.ones(m.n_nodes, dtype=F64)
    u = (jnp.asarray(rng.randn(*grid.n), dtype=F64),
         jnp.asarray(rng.randn(*grid.n), dtype=F64))

    ctx = fe1.prepare(X, mask)
    f0 = fe0.spread_force(F, grid, X, mask)
    f1 = fe1.spread_force(F, grid, X, mask, ctx=ctx)
    for a, b in zip(f0, f1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-10, atol=1e-10)
    U0 = fe0.interpolate_velocity(u, grid, X, mask)
    U1 = fe1.interpolate_velocity(u, grid, X, mask, ctx=ctx)
    np.testing.assert_allclose(np.asarray(U0), np.asarray(U1),
                               rtol=1e-10, atol=1e-10)
