"""Moving-window refined INS/IB (5.7 completion): the fine window
tracks the immersed structure through marker-tagged host-side regrids.

Oracles: a membrane advected by a background flow must STAY inside the
window (with delta-support clearance) across multiple window moves; the
fluid transfer must keep the composite state divergence-free after
every regrid; fine-resolution data must survive on the overlap (the
refine-schedule copy); and the structure's drift must track the
background advection speed."""

import jax.numpy as jnp
import numpy as np

from ibamr_tpu.amr import FineBox, _box_mac_divergence
from ibamr_tpu.amr_ins import (TwoLevelIBINS, TwoLevelIBState,
                               advance_two_level_ib_regridding,
                               regrid_two_level_ib)
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ib import IBMethod
from ibamr_tpu.models.membrane2d import make_circle_membrane
from ibamr_tpu.ops import stencils


def _setup(n=64, box_shape=(20, 20), center=(0.3, 0.5), U0=0.5):
    grid = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    struct = make_circle_membrane(64, 0.06, center, stiffness=0.5)
    X0 = struct.vertices
    ib = IBMethod(struct.force_specs(dtype=jnp.float64), kernel="IB_4")
    lo = tuple(int(round(c * n - s / 2))
               for c, s in zip(center, box_shape))
    box = FineBox(lo=lo, shape=box_shape)
    integ = TwoLevelIBINS(grid, box, ib, mu=0.02, proj_tol=1e-10)
    uc = (jnp.full(grid.n, U0, dtype=jnp.float64),
          jnp.zeros(grid.n, dtype=jnp.float64))
    st = integ.initialize(jnp.asarray(X0, dtype=jnp.float64), uc=uc)
    return grid, integ, st


def _markers_inside(grid, box, X, margin_cells=2):
    Xn = np.asarray(X)
    for d in range(2):
        c = (Xn[:, d] - grid.x_lo[d]) / grid.dx[d]
        if c.min() < box.lo[d] + margin_cells or \
                c.max() > box.hi[d] - margin_cells:
            return False
    return True


def test_regrid_transfers_keep_div_free():
    grid, integ, st = _setup()
    # force a window move by displacing markers
    st2 = TwoLevelIBState(fluid=st.fluid, X=st.X + jnp.asarray([0.1, 0.0]),
                          U=st.U, mask=st.mask)
    integ2, st3 = regrid_two_level_ib(integ, st2)
    assert integ2.box.lo != integ.box.lo          # window moved
    div_f = np.asarray(_box_mac_divergence(
        st3.fluid.uf, integ2.core.dx_f))
    assert np.max(np.abs(div_f)) < 1e-8
    div_c = np.asarray(stencils.divergence(st3.fluid.uc, grid.dx))
    covered = np.zeros(grid.n, dtype=bool)
    covered[integ2.box.lo[0]:integ2.box.hi[0],
            integ2.box.lo[1]:integ2.box.hi[1]] = True
    assert np.max(np.abs(div_c[~covered])) < 1e-8


def test_regrid_noop_when_window_fits():
    grid, integ, st = _setup()
    integ2, st2 = regrid_two_level_ib(integ, st)
    assert integ2 is integ and st2 is st


def test_regrid_carries_projection_config():
    """A moved window must keep the full projection configuration —
    custom m/restarts AND the external preconditioner, rebuilt at the
    NEW box by its factory (ADVICE round 2: a FAC-preconditioned run
    must not silently revert to the default preconditioner mid-run)."""
    built = []

    def factory(grid, box):
        def precond(r):
            return r
        built.append(box.lo)
        return precond

    grid = StaggeredGrid(n=(64, 64), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    struct = make_circle_membrane(64, 0.06, (0.3, 0.5), stiffness=0.5)
    ib = IBMethod(struct.force_specs(dtype=jnp.float64), kernel="IB_4")
    box = FineBox(lo=(9, 22), shape=(20, 20))
    integ = TwoLevelIBINS(grid, box, ib, mu=0.02, proj_tol=1e-10,
                          proj_m=17, proj_restarts=3,
                          precond_factory=factory)
    st = integ.initialize(jnp.asarray(struct.vertices, jnp.float64))
    assert built == [(9, 22)]
    st2 = TwoLevelIBState(fluid=st.fluid,
                          X=st.X + jnp.asarray([0.1, 0.0]),
                          U=st.U, mask=st.mask)
    integ2, _ = regrid_two_level_ib(integ, st2)
    assert integ2.box.lo != integ.box.lo
    assert integ2.core.proj.m == 17
    assert integ2.core.proj.restarts == 3
    assert integ2.core.proj._external_precond is not None
    assert built[-1] == integ2.box.lo     # rebuilt at the NEW box


def test_window_tracks_advected_membrane():
    U0 = 0.5
    grid, integ, st = _setup(U0=U0)
    x_start = float(jnp.mean(st.X[:, 0]))
    # fine-level explicit-diffusion limit: mu dt/dx_f^2 = 0.16 < 0.25
    dt = 5e-4
    steps = 400
    integ, st = advance_two_level_ib_regridding(
        integ, st, dt, steps, regrid_interval=20)
    # the window MOVED downstream with the structure (initial lo[0]=9)
    assert integ.box.lo[0] >= 12
    assert _markers_inside(grid, integ.box, st.X)
    # structure advected with the background flow (~U0 * t)
    drift = float(jnp.mean(st.X[:, 0])) - x_start
    assert abs(drift - U0 * dt * steps) < 0.15 * (U0 * dt * steps)
    # composite state stayed healthy
    assert float(integ.core.max_divergence(st.fluid)) < 1e-8
    assert np.all(np.isfinite(np.asarray(st.X)))


def test_window_regrid_3d_smoke():
    """3D: the regrid transfer machinery is dimension-generic — a
    displaced shell forces a window move; the transferred composite
    state stays div-free."""
    from ibamr_tpu.models.shell3d import make_spherical_shell

    n = 32
    grid = StaggeredGrid(n=(n,) * 3, x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    struct = make_spherical_shell(12, 12, 0.08, (0.4, 0.5, 0.5),
                                  stiffness=0.5)
    ib = IBMethod(struct.force_specs(dtype=jnp.float64), kernel="IB_4")
    box = FineBox(lo=(7, 10, 10), shape=(12, 12, 12))
    integ = TwoLevelIBINS(grid, box, ib, mu=0.02, proj_tol=1e-8)
    st = integ.initialize(jnp.asarray(struct.vertices, jnp.float64))
    st2 = TwoLevelIBState(fluid=st.fluid,
                          X=st.X + jnp.asarray([0.12, 0.0, 0.0]),
                          U=st.U, mask=st.mask)
    integ2, st3 = regrid_two_level_ib(integ, st2)
    assert integ2.box.lo[0] > integ.box.lo[0]
    div_f = np.asarray(_box_mac_divergence(st3.fluid.uf,
                                           integ2.core.dx_f))
    assert np.max(np.abs(div_f)) < 1e-6
    # one coupled step at the new window stays finite
    st4 = integ2.step(st3, 2e-4)
    assert np.all(np.isfinite(np.asarray(st4.X)))
