"""Stage-0 acceptance: grid functions, timers, metrics, checkpoint round-trip."""

import json
import math
import os
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.utils.checkpoint import (
    latest_step, restore_checkpoint, save_checkpoint)
from ibamr_tpu.utils.gridfunctions import CartGridFunction
from ibamr_tpu.utils.input_db import parse_input_string
from ibamr_tpu.utils.gridfunctions import function_from_db
from ibamr_tpu.utils.metrics import MetricsLogger
from ibamr_tpu.utils.timers import TimerManager


def test_gridfunction_scalar():
    f = CartGridFunction("sin(2*PI*X_0)*cos(2*PI*X_1)", dim=2)
    x = jnp.array([0.25])
    y = jnp.array([0.0])
    v = f((x, y), t=0.0)
    assert float(v[0]) == pytest.approx(math.sin(math.pi / 2), abs=1e-6)


def test_gridfunction_time_and_power():
    f = CartGridFunction("t + X_0^2", dim=1)
    v = f((jnp.array([3.0]),), t=1.5)
    assert float(v[0]) == pytest.approx(10.5)


def test_gridfunction_rejects_evil():
    with pytest.raises(Exception):
        CartGridFunction("__import__('os')", dim=1)
    with pytest.raises(Exception):
        CartGridFunction("X_0.__class__", dim=1)


def test_function_from_db_vector():
    db = parse_input_string("""
    V {
       function_0 = "X_1"
       function_1 = "-X_0"
    }
    """)
    f = function_from_db(db.get_database("V"), dim=2)
    out = f((jnp.array([1.0]), jnp.array([2.0])))
    assert float(out[0][0]) == 2.0
    assert float(out[1][0]) == -1.0


def test_timer_report():
    tm = TimerManager()
    with tm.scope("IB::spreadForce"):
        pass
    with tm.scope("IB::spreadForce"):
        pass
    rep = tm.report()
    assert "IB::spreadForce" in rep
    assert tm.timers["IB::spreadForce"].count == 2


def test_metrics_jsonl(tmp_path):
    path = os.path.join(tmp_path, "m.jsonl")
    with MetricsLogger(path) as m:
        m.log({"step": 1, "dt": np.float64(0.5), "cfl": jnp.array(0.9)})
    rec = json.loads(open(path).read().strip())
    assert rec == {"step": 1, "dt": 0.5, "cfl": pytest.approx(0.9)}


class FakeState(NamedTuple):
    u: jnp.ndarray
    markers: jnp.ndarray
    t: jnp.ndarray


def _mkstate(seed):
    rng = np.random.default_rng(seed)
    return FakeState(
        u=jnp.asarray(rng.standard_normal((4, 4)), dtype=jnp.float32),
        markers=jnp.asarray(rng.standard_normal((7, 2)), dtype=jnp.float32),
        t=jnp.asarray(1.25, dtype=jnp.float32),
    )


def test_checkpoint_roundtrip(tmp_path):
    state = _mkstate(0)
    d = str(tmp_path / "ckpt")
    save_checkpoint(d, state, step=42, metadata={"note": "hi"})
    assert latest_step(d) == 42
    template = _mkstate(99)  # different values, same structure
    restored, step, meta = restore_checkpoint(d, template)
    assert step == 42
    assert meta["note"] == "hi"
    np.testing.assert_array_equal(np.asarray(restored.u), np.asarray(state.u))
    np.testing.assert_array_equal(
        np.asarray(restored.markers), np.asarray(state.markers))
    assert float(restored.t) == pytest.approx(1.25)


def test_checkpoint_prune(tmp_path):
    d = str(tmp_path / "ckpt")
    s = _mkstate(1)
    for i in range(5):
        save_checkpoint(d, s, step=i, keep=2)
    steps = sorted(int(f.split(".")[1]) for f in os.listdir(d)
                   if f.endswith(".npz"))
    assert steps == [3, 4]


def test_gridfunction_piecewise_conditionals():
    f = CartGridFunction("X_0 if X_0 > 0.5 else 0.0", dim=1)
    x = jnp.array([0.25, 0.75])
    out = np.asarray(f((x,)))
    np.testing.assert_allclose(out, [0.0, 0.75])
    g = CartGridFunction("(X_0 > 0.2 and X_0 < 0.8) * 2.0", dim=1)
    out = np.asarray(g((x,)))
    np.testing.assert_allclose(out, [2.0, 2.0])


def test_checkpoint_schema_mismatch_diagnosed(tmp_path):
    """A refactored state layout produces a named schema diff, not a
    silent orphan or a bare KeyError (VERDICT round 1, weak #9)."""
    import jax.numpy as jnp
    import pytest
    from ibamr_tpu.utils.checkpoint import (restore_checkpoint,
                                            save_checkpoint)

    state = {"u": jnp.zeros((4, 4)), "t": jnp.zeros(())}
    save_checkpoint(str(tmp_path), state, 1)
    # same structure restores fine
    out, step, meta = restore_checkpoint(str(tmp_path), state)
    assert step == 1 and "schema" in meta
    # renamed leaf -> clear diagnostic naming both sides
    bad = {"u_new": jnp.zeros((4, 4)), "t": jnp.zeros(())}
    with pytest.raises(ValueError, match="u_new"):
        restore_checkpoint(str(tmp_path), bad)
    # reshaped leaf -> shape mismatch named
    bad2 = {"u": jnp.zeros((8, 8)), "t": jnp.zeros(())}
    with pytest.raises(ValueError, match="shape mismatch"):
        restore_checkpoint(str(tmp_path), bad2)


def test_async_checkpoint_writer(tmp_path):
    """Async writes must produce checkpoints identical to sync ones,
    keep ordering under multiple enqueues, and surface worker errors on
    wait (S6)."""
    import jax.numpy as jnp

    from ibamr_tpu.utils.checkpoint import (AsyncCheckpointWriter,
                                            latest_step,
                                            restore_checkpoint,
                                            save_checkpoint)

    state = {"u": jnp.arange(12.0).reshape(3, 4), "t": jnp.asarray(1.5)}
    sync_dir = str(tmp_path / "sync")
    async_dir = str(tmp_path / "async")
    save_checkpoint(sync_dir, state, 7)

    w = AsyncCheckpointWriter(async_dir, keep=2)
    for k in (5, 6, 7):
        st_k = {"u": state["u"] + k, "t": state["t"]}
        w.save(st_k, k)
    w.wait()
    assert latest_step(async_dir) == 7
    template = {"u": jnp.zeros((3, 4)), "t": jnp.asarray(0.0)}
    got, step, _ = restore_checkpoint(async_dir, template)
    assert step == 7
    import numpy as np
    assert np.allclose(np.asarray(got["u"]),
                       np.asarray(state["u"]) + 7)
    # keep=2 pruned the oldest
    assert latest_step(async_dir) == 7
    import os
    files = [f for f in os.listdir(async_dir) if f.endswith(".npz")]
    assert len(files) == 2
    w.close()

    # error propagation: unwritable directory surfaces on wait
    bad = AsyncCheckpointWriter("/proc/definitely/not/writable")
    bad.save(state, 1)
    import pytest
    with pytest.raises(Exception):
        bad.wait()
