"""S3 workload-balanced partitioning + S4 sharded multilevel AMR.

Oracles: the cost model must steer the mesh factorization AWAY from
splitting through a marker cluster (picking the axis that balances it),
capacity sizing must cover the measured peak, the rebalance trigger
must fire exactly when pools would overflow or a much better partition
exists, and the sharded 3-level composite step must equal the
single-device result to roundoff on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.amr import FineBox
from ibamr_tpu.amr_multilevel import MultiLevelAdvDiff
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.parallel.mesh import make_sharded_multilevel_step
from ibamr_tpu.parallel.workload import (choose_mesh, needs_rebalance,
                                         recommended_capacity,
                                         shard_marker_counts,
                                         workload_estimate)


def _grid(n=64):
    return StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))


def test_counts_match_owner_math():
    g = _grid(16)
    X = np.array([[0.1, 0.1], [0.9, 0.1], [0.9, 0.9], [0.6, 0.2]])
    counts = shard_marker_counts(X, g, (2, 2))
    assert counts.tolist() == [[1, 0], [2, 1]]


def test_choose_mesh_avoids_splitting_cluster():
    """Markers concentrated in a thin x-slab: sharding along x puts
    nearly all markers on one device; the cost model must prefer the
    y-split (or a mixed split with lower max cost)."""
    g = _grid(64)
    rng = np.random.default_rng(0)
    X = np.stack([0.5 + 0.01 * rng.standard_normal(4000),
                  rng.random(4000)], axis=-1)
    rep = choose_mesh(X, g, 8, max_axes=2, min_block=4)
    # max cost under the chosen split beats the pure-x split clearly
    counts_x = shard_marker_counts(X, g, (8, 1))
    cost_x = workload_estimate(counts_x, g).max()
    assert rep.cost_per_shard.max() < 0.5 * cost_x
    # and the chosen split balances markers well
    assert rep.max_markers < 4000 // 2


def test_capacity_covers_peak():
    g = _grid(32)
    rng = np.random.default_rng(1)
    X = rng.random((1000, 2))
    counts = shard_marker_counts(X, g, (4, 2))
    cap = recommended_capacity(counts, slack=1.5)
    assert cap >= counts.max()
    assert cap % 8 == 0


def test_needs_rebalance_triggers_on_drift():
    g = _grid(64)
    rng = np.random.default_rng(2)
    # balanced start
    X0 = rng.random((2000, 2))
    rep = choose_mesh(X0, g, 8, min_block=4)
    assert not needs_rebalance(X0, g, rep.sizes, rep.capacity,
                               min_block=4)
    # everything drifts into one corner: pools overflow -> rebalance
    X1 = 0.1 * X0
    assert needs_rebalance(X1, g, rep.sizes, rep.capacity, min_block=4)


def test_sharded_multilevel_matches_single_device(mesh8):
    """S4: the 3-level composite advance under an 8-device mesh equals
    the unsharded result to roundoff (CF transfers ride collectives)."""
    n = 32
    g = _grid(n)
    ml = MultiLevelAdvDiff(
        g, [FineBox(lo=(8, 8), shape=(16, 16)),
            FineBox(lo=(8, 8), shape=(16, 16))],
        kappa=0.002,
        vel_fn=lambda m: (0.7 + 0 * m[0], 0.3 + 0 * m[1]))
    Qs0 = ml.initialize(lambda c: jnp.exp(
        -((c[0] - 0.45) ** 2 + (c[1] - 0.5) ** 2) / 0.02))
    dt = 0.2 / n

    Qs_ref = Qs0
    for _ in range(5):
        Qs_ref = ml.step(Qs_ref, dt)

    step = make_sharded_multilevel_step(ml, mesh8)
    Qs_sh = Qs0
    for _ in range(5):
        Qs_sh = step(Qs_sh, dt)

    for a, b in zip(Qs_ref, Qs_sh):
        assert np.max(np.abs(np.asarray(a) - np.asarray(b))) < 1e-12
