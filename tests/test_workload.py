"""S3 workload-balanced partitioning + S4 sharded multilevel AMR.

Oracles: the cost model must steer the mesh factorization AWAY from
splitting through a marker cluster (picking the axis that balances it),
capacity sizing must cover the measured peak, the rebalance trigger
must fire exactly when pools would overflow or a much better partition
exists, and the sharded 3-level composite step must equal the
single-device result to roundoff on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.amr import FineBox
from ibamr_tpu.amr_multilevel import MultiLevelAdvDiff
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.parallel.mesh import make_sharded_multilevel_step
from ibamr_tpu.parallel.workload import (choose_mesh, needs_rebalance,
                                         recommended_capacity,
                                         shard_marker_counts,
                                         workload_estimate)


def _grid(n=64):
    return StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))


def test_counts_match_owner_math():
    g = _grid(16)
    X = np.array([[0.1, 0.1], [0.9, 0.1], [0.9, 0.9], [0.6, 0.2]])
    counts = shard_marker_counts(X, g, (2, 2))
    assert counts.tolist() == [[1, 0], [2, 1]]


def test_choose_mesh_avoids_splitting_cluster():
    """Markers concentrated in a thin x-slab: sharding along x puts
    nearly all markers on one device; the cost model must prefer the
    y-split (or a mixed split with lower max cost)."""
    g = _grid(64)
    rng = np.random.default_rng(0)
    X = np.stack([0.5 + 0.01 * rng.standard_normal(4000),
                  rng.random(4000)], axis=-1)
    rep = choose_mesh(X, g, 8, max_axes=2, min_block=4)
    # max cost under the chosen split beats the pure-x split clearly
    counts_x = shard_marker_counts(X, g, (8, 1))
    cost_x = workload_estimate(counts_x, g).max()
    assert rep.cost_per_shard.max() < 0.5 * cost_x
    # and the chosen split balances markers well
    assert rep.max_markers < 4000 // 2


def test_capacity_covers_peak():
    g = _grid(32)
    rng = np.random.default_rng(1)
    X = rng.random((1000, 2))
    counts = shard_marker_counts(X, g, (4, 2))
    cap = recommended_capacity(counts, slack=1.5)
    assert cap >= counts.max()
    assert cap % 8 == 0


def test_needs_rebalance_triggers_on_drift():
    g = _grid(64)
    rng = np.random.default_rng(2)
    # balanced start
    X0 = rng.random((2000, 2))
    rep = choose_mesh(X0, g, 8, min_block=4)
    assert not needs_rebalance(X0, g, rep.sizes, rep.capacity,
                               min_block=4)
    # everything drifts into one corner: pools overflow -> rebalance
    X1 = 0.1 * X0
    assert needs_rebalance(X1, g, rep.sizes, rep.capacity, min_block=4)


def test_sharded_multilevel_matches_single_device(mesh8):
    """S4: the 3-level composite advance under an 8-device mesh equals
    the unsharded result to roundoff (CF transfers ride collectives)."""
    n = 32
    g = _grid(n)
    ml = MultiLevelAdvDiff(
        g, [FineBox(lo=(8, 8), shape=(16, 16)),
            FineBox(lo=(8, 8), shape=(16, 16))],
        kappa=0.002,
        vel_fn=lambda m: (0.7 + 0 * m[0], 0.3 + 0 * m[1]))
    Qs0 = ml.initialize(lambda c: jnp.exp(
        -((c[0] - 0.45) ** 2 + (c[1] - 0.5) ** 2) / 0.02))
    dt = 0.2 / n

    Qs_ref = Qs0
    for _ in range(5):
        Qs_ref = ml.step(Qs_ref, dt)

    step = make_sharded_multilevel_step(ml, mesh8)
    Qs_sh = Qs0
    for _ in range(5):
        Qs_sh = step(Qs_sh, dt)

    for a, b in zip(Qs_ref, Qs_sh):
        assert np.max(np.abs(np.asarray(a) - np.asarray(b))) < 1e-12


# ---------------------------------------------------------------------------
# Workload-balanced box->device placement (round 5, VERDICT item 4:
# the real LoadBalancer — greedy bin-packing of window costs, S3)
# ---------------------------------------------------------------------------

def test_lpt_assign_beats_contiguous_split():
    """Greedy LPT packing of uneven box costs onto devices: the max
    device load must beat the naive contiguous split and stay within
    the LPT 4/3 bound of the ideal."""
    from ibamr_tpu.parallel.workload import lpt_assign

    rng = np.random.default_rng(0)
    costs = np.concatenate([rng.uniform(10, 12, 3),
                            rng.uniform(1, 2, 9)])
    D = 4
    device, load = lpt_assign(costs, D)
    assert device.shape == (12,)
    assert np.allclose(np.bincount(device, weights=costs,
                                   minlength=D), load)
    # naive contiguous: 3 items per device -> the 3 hot boxes land
    # together on device 0
    naive = np.array([costs[3 * d:3 * d + 3].sum() for d in range(D)])
    assert load.max() < 0.8 * naive.max(), (load, naive)
    ideal = costs.sum() / D
    assert load.max() <= (4.0 / 3.0) * ideal + costs.max() * 1e-9


def test_box_costs_weights_markers():
    from ibamr_tpu.parallel.workload import box_costs

    g = _grid(32)
    lo = np.array([[4, 4], [20, 20]])
    X = np.array([[0.2, 0.2]] * 50)     # cluster inside box 0
    c = box_costs(lo, (8, 8), g, ratio=2, X=X, w_marker=4.0)
    assert c[0] == c[1] + 4.0 * 50


def test_multibox_balanced_placement_matches_single():
    """The LPT-placed, device-sharded multi-box step equals the plain
    step (1-vs-8 equality), and the placement spreads the work: with
    K=3 equal windows on 8 devices, max one window per device."""
    from ibamr_tpu.amr_multibox import MultiBoxDynamicAdvDiff
    from ibamr_tpu.parallel.mesh import (make_mesh,
                                         make_sharded_multibox_step)

    grid = StaggeredGrid(n=(48, 48), x_lo=(0, 0), x_up=(1, 1))

    def u_fn(coords, d):
        x = coords[0]
        if d == 0:
            return -0.3 * jnp.sin(2.0 * np.pi * x)
        return jnp.zeros_like(x)

    sim = MultiBoxDynamicAdvDiff(grid, (10, 10), K=3, kappa=1e-3,
                                 u_fn=u_fn, tag_threshold=0.03,
                                 dtype=jnp.float64)

    def three_gauss(coords):
        x, y = coords
        out = 0.0
        for cx, cy in ((0.25, 0.3), (0.55, 0.6), (0.8, 0.35)):
            out = out + jnp.exp(-(((x - cx) ** 2 + (y - cy) ** 2)
                                  / (2 * 0.05 ** 2)))
        return out

    st0 = sim.initialize(three_gauss)
    dt = 2.5e-4
    ref = st0
    for _ in range(5):
        ref = sim.step(ref, dt)

    mesh = make_mesh(8)
    step = make_sharded_multibox_step(sim, mesh)
    sh = st0
    for _ in range(5):
        sh = step(sh, dt)

    pl = step.placement()
    assert pl is not None
    # equal-cost windows: LPT spreads them one-per-device
    occupancy = np.bincount(pl["device_of_box"], minlength=8)
    assert occupancy.max() == 1
    # work-spread: max device load within 5% of the mean over LOADED
    # devices (equal costs -> exactly equal)
    loaded = pl["load"][pl["load"] > 0]
    assert loaded.max() <= 1.05 * loaded.mean()

    np.testing.assert_allclose(np.asarray(sh.Qc), np.asarray(ref.Qc),
                               rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(np.asarray(sh.Qf), np.asarray(ref.Qf),
                               rtol=1e-12, atol=1e-12)


def test_multibox_uneven_costs_sharded_equality():
    """Marker-weighted costs force an UNEVEN assignment (hot window
    alone, cold windows sharing); the sharded step still equals the
    plain one — placement is a performance decision, never a numerics
    one."""
    from ibamr_tpu.amr_multibox import MultiBoxDynamicAdvDiff
    from ibamr_tpu.parallel.mesh import (make_mesh,
                                         make_sharded_multibox_step)
    from ibamr_tpu.parallel.workload import box_costs, lpt_assign

    grid = StaggeredGrid(n=(48, 48), x_lo=(0, 0), x_up=(1, 1))
    sim = MultiBoxDynamicAdvDiff(grid, (10, 10), K=3, kappa=1e-3,
                                 tag_threshold=0.03,
                                 dtype=jnp.float64)

    def three_gauss(coords):
        x, y = coords
        out = 0.0
        for cx, cy in ((0.25, 0.3), (0.55, 0.6), (0.8, 0.35)):
            out = out + jnp.exp(-(((x - cx) ** 2 + (y - cy) ** 2)
                                  / (2 * 0.05 ** 2)))
        return out

    st0 = sim.initialize(three_gauss)
    # a marker cluster in window 0 makes it the hot box on 2 devices
    lo_np = np.asarray(st0.lo)
    X = np.repeat(((lo_np[0] + 5.0) / 48.0)[None, :], 200, axis=0)
    costs = box_costs(lo_np, (10, 10), grid, ratio=2, X=X,
                      w_marker=4.0)
    device, load = lpt_assign(costs, 2)
    # hot box isolated on its own device
    hot = int(np.argmax(costs))
    assert (device == device[hot]).sum() == 1

    dt = 2.5e-4
    ref = st0
    for _ in range(4):
        ref = sim.step(ref, dt)

    mesh = make_mesh(8)
    step = make_sharded_multibox_step(sim, mesh, X=X)
    sh = st0
    for _ in range(4):
        sh = step(sh, dt)

    np.testing.assert_allclose(np.asarray(sh.Qc), np.asarray(ref.Qc),
                               rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(np.asarray(sh.Qf), np.asarray(ref.Qf),
                               rtol=1e-12, atol=1e-12)
