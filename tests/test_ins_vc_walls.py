"""Wall-bounded variable-coefficient (multiphase) INS — the physical
no-slip walls of P22 (VERDICT round 3, missing #3 / next-round item 4).

Reference parity: ``INSVCStaggeredHierarchyIntegrator`` with physical
wall BCs (SURVEY.md §2.2 P22 [U]) — tanks and channels with real
floors/walls rather than Brinkman-penalized slabs inside a periodic
box. The wall machinery rides the pinned-face storage convention of
``integrators.ins_walls``: the wall-normal component's slot 0 is the lo
wall face (pinned 0) and the hi wall face is its periodic-wrap image,
so divergence/flux rolls stay exact and the projection's masked-face
coefficient reproduces the homogeneous-Neumann pressure rows.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ins_vc import (INSVCConservativeIntegrator,
                                          INSVCStaggeredIntegrator,
                                          advance_vc)
from ibamr_tpu.ops import stencils


def _wall_normal_faces_zero(st, wall_axes):
    for d, w in enumerate(wall_axes):
        if not w:
            continue
        idx = [slice(None)] * st.u[d].ndim
        idx[d] = slice(0, 1)
        assert float(jnp.max(jnp.abs(st.u[d][tuple(idx)]))) == 0.0


def test_hydrostatic_quiescence_closed_tank():
    """A flat heavy pool under gravity in a CLOSED tank (walls on both
    axes) stays exactly quiescent: the density-anomaly gravity force on
    a flat pool is a discrete wall-masked y-gradient, so the Neumann
    projection absorbs it to solver tolerance."""
    n = 32
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    y = (np.arange(n) + 0.5) / n
    phi0 = jnp.asarray(np.broadcast_to((0.5 - y)[None, :], (n, n)),
                       dtype=jnp.float64)
    integ = INSVCStaggeredIntegrator(
        g, rho0=1.0, rho1=100.0, mu0=0.01, mu1=0.01,
        gravity=(0.0, -1.0), sigma=0.0, convective_op_type="none",
        reinit_interval=1000, cg_tol=1e-11,
        wall_axes=(True, True), dtype=jnp.float64)
    st = integ.initialize(phi0)
    st = advance_vc(integ, st, 1e-3, 20)
    umax = max(float(jnp.max(jnp.abs(c))) for c in st.u)
    assert umax < 1e-9, umax
    _wall_normal_faces_zero(st, (True, True))


def test_hydrostatic_quiescence_conservative_walled():
    """Conservative form, same closed-tank quiescence (arithmetic face
    rule + conserved density)."""
    n = 32
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    y = (np.arange(n) + 0.5) / n
    phi0 = jnp.asarray(np.broadcast_to((0.5 - y)[None, :], (n, n)),
                       dtype=jnp.float64)
    integ = INSVCConservativeIntegrator(
        g, rho0=1.0, rho1=100.0, mu0=0.01, mu1=0.01,
        gravity=(0.0, -1.0), sigma=0.0, convective_op_type="none",
        reinit_interval=1000, cg_tol=1e-11,
        wall_axes=(True, True), dtype=jnp.float64)
    st = integ.initialize(phi0)
    st = advance_vc(integ, st, 1e-3, 20)
    umax = max(float(jnp.max(jnp.abs(c))) for c in st.u)
    assert umax < 1e-9, umax


def test_channel_viscous_mode_decay_rate():
    """Single-phase limit, walls on y only: the lowest no-slip channel
    mode u_x = sin(pi y/H) decays at the analytic rate
    (mu/rho)(pi/H)^2 — pins the wall-aware viscous stress (one-sided
    wall shear with the odd-reflection ghost) quantitatively."""
    n = 48
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    mu = 0.05
    yc = (jnp.arange(n, dtype=jnp.float64) + 0.5) / n
    u0x = jnp.broadcast_to(jnp.sin(jnp.pi * yc)[None, :], (n, n))
    integ = INSVCStaggeredIntegrator(
        g, rho0=1.0, rho1=1.0, mu0=mu, mu1=mu,
        convective_op_type="none", reinit_interval=10 ** 9,
        cg_tol=1e-11, wall_axes=(False, True), dtype=jnp.float64)
    st = integ.initialize(jnp.ones((n, n), dtype=jnp.float64),
                          u0_arrays=(u0x, jnp.zeros((n, n),
                                                    dtype=jnp.float64)))
    dt = 2e-4
    steps = 400
    st = advance_vc(integ, st, dt, steps)
    t = dt * steps
    rate = mu * jnp.pi ** 2              # H = 1, rho = 1
    expected = float(jnp.exp(-rate * t))
    measured = float(jnp.max(st.u[0]) / jnp.max(u0x))
    # 2nd-order wall discretization at n=48: a couple of percent
    assert abs(measured - expected) / expected < 0.03, \
        (measured, expected)


def test_falling_drop_walled_tank_stable_and_conserves():
    """A heavy drop falling inside a CLOSED tank: stable, discretely
    divergence-free, wall-normal faces exactly zero, heavy-phase
    volume drift bounded, and the drop's centroid actually falls."""
    n = 48
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    xx = (np.arange(n) + 0.5) / n
    X, Y = np.meshgrid(xx, xx, indexing="ij")
    r = np.sqrt((X - 0.5) ** 2 + (Y - 0.65) ** 2)
    phi0 = jnp.asarray(0.15 - r, dtype=jnp.float64)  # drop = heavy
    integ = INSVCStaggeredIntegrator(
        g, rho0=1.0, rho1=10.0, mu0=0.01, mu1=0.02,
        gravity=(0.0, -5.0), sigma=0.0, convective_op_type="upwind",
        reinit_interval=10, cg_tol=1e-10,
        wall_axes=(True, True), dtype=jnp.float64)
    st = integ.initialize(phi0)
    vol0 = float(integ.heavy_phase_volume(st))

    def centroid_y(phi):
        from ibamr_tpu.physics.level_set import heaviside
        H = heaviside(phi, integ.eps)
        yb = jnp.asarray(Y)
        return float(jnp.sum(H * yb) / jnp.sum(H))

    y0 = centroid_y(st.phi)
    st = advance_vc(integ, st, 5e-4, 200)
    assert all(bool(jnp.all(jnp.isfinite(c))) for c in st.u)
    div = float(jnp.max(jnp.abs(stencils.divergence(st.u, g.dx))))
    assert div < 1e-7, div
    _wall_normal_faces_zero(st, (True, True))
    vol1 = float(integ.heavy_phase_volume(st))
    assert abs(vol1 - vol0) / vol0 < 0.05, (vol0, vol1)
    y1 = centroid_y(st.phi)
    assert y1 < y0 - 0.015, (y0, y1)


def test_conservative_walled_mass_exact():
    """Conservative form in a closed tank: total mass is conserved to
    roundoff — every wall-face mass flux vanishes identically under
    the pinned-face convention, so the flux-form update telescopes."""
    n = 32
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    xx = (np.arange(n) + 0.5) / n
    X, Y = np.meshgrid(xx, xx, indexing="ij")
    r = np.sqrt((X - 0.5) ** 2 + (Y - 0.6) ** 2)
    phi0 = jnp.asarray(0.2 - r, dtype=jnp.float64)
    integ = INSVCConservativeIntegrator(
        g, rho0=1.0, rho1=50.0, mu0=0.01, mu1=0.05,
        gravity=(0.0, -2.0), sigma=0.0, convective_op_type="upwind",
        reinit_interval=10, cg_tol=1e-10,
        wall_axes=(True, True), dtype=jnp.float64)
    st = integ.initialize(phi0)
    m0 = float(integ.total_mass(st))
    st = advance_vc(integ, st, 5e-4, 100)
    m1 = float(integ.total_mass(st))
    assert abs(m1 - m0) / m0 < 1e-12, (m0, m1)


def test_reinitialize_walled_keeps_floor_clean():
    """Reinitializing a pool's signed-distance field with wall_axes
    must NOT corrupt the floor rows: the periodic wrap sees air above
    the domain top against water at the bottom (a spurious 'interface'
    at the floor), the walled version must not."""
    from ibamr_tpu.physics.level_set import reinitialize

    n = 48
    dx = (1.0 / n, 1.0 / n)
    y = (np.arange(n) + 0.5) / n
    phi = jnp.asarray(np.broadcast_to((y - 0.5)[None, :], (n, n)),
                      dtype=jnp.float64)   # pool below y=0.5
    out_w = reinitialize(phi, dx, iters=40, wall_axes=(False, True))
    # the field is already a signed distance: the walled reinit must be
    # a near-no-op INCLUDING the floor/top rows
    err_w = float(jnp.max(jnp.abs(out_w - phi)))
    assert err_w < 1e-6, err_w
    # the periodic version corrupts the wrap rows (documents why the
    # walled variant exists)
    out_p = reinitialize(phi, dx, iters=40)
    err_p = float(jnp.max(jnp.abs(out_p - phi)))
    assert err_p > 100.0 * max(err_w, 1e-12), (err_p, err_w)


def test_advect_walled_conserves_and_confines():
    """Godunov advection with wall_axes: exact conservation (wall-face
    fluxes vanish) and no leakage of a blob pushed against the wall."""
    from ibamr_tpu.ops.godunov import advect

    n = 48
    dx = (1.0 / n, 1.0 / n)
    xx = (np.arange(n) + 0.5) / n
    X, Y = np.meshgrid(xx, xx, indexing="ij")
    Q = jnp.asarray(np.exp(-((X - 0.5) ** 2 + (Y - 0.3) ** 2) / 0.01),
                    dtype=jnp.float64)
    # uniform downward velocity, pinned at the walls (storage
    # convention: slot 0 of the normal component is the wall face)
    uy = jnp.full((n, n), -0.5, dtype=jnp.float64)
    uy = uy.at[:, 0].set(0.0)
    u = (jnp.zeros((n, n), dtype=jnp.float64), uy)
    s0 = float(jnp.sum(Q))
    for _ in range(60):
        Q = advect(Q, u, dx, 5e-3, wall_axes=(False, True))
    assert abs(float(jnp.sum(Q)) - s0) / s0 < 1e-12
    assert bool(jnp.all(jnp.isfinite(Q)))
    assert float(jnp.min(Q)) > -1e-8          # TVD near the wall


def test_walled_momentum_wall_shear_sign():
    """A uniform rightward stream between two no-slip walls must
    decelerate monotonically (wall shear is the only force) — pins the
    sign/placement of the one-sided wall-shear assembly."""
    n = 32
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    u0x = jnp.ones((n, n), dtype=jnp.float64)
    integ = INSVCStaggeredIntegrator(
        g, rho0=1.0, rho1=1.0, mu0=0.05, mu1=0.05,
        convective_op_type="none", reinit_interval=10 ** 9,
        cg_tol=1e-11, wall_axes=(False, True), dtype=jnp.float64)
    st = integ.initialize(jnp.ones((n, n), dtype=jnp.float64),
                          u0_arrays=(u0x, jnp.zeros((n, n),
                                                    dtype=jnp.float64)))
    means = [1.0]
    for _ in range(5):
        st = advance_vc(integ, st, 2e-4, 20)
        means.append(float(jnp.mean(st.u[0])))
    assert all(b < a for a, b in zip(means, means[1:])), means
    # boundary cells decelerate fastest (the shear enters at the wall)
    prof = np.asarray(jnp.mean(st.u[0], axis=0))
    assert prof[0] < prof[n // 2]
    assert prof[-1] < prof[n // 2]


def test_hydrostatic_quiescence_3d_walled_tank():
    """3D closed tank (walls on all three axes): the flat heavy pool
    under gravity stays quiescent — pins the wall machinery's
    dimension-generic paths (viscous edge assembly per axis pair,
    Neumann projection, pinned faces) in the production shape."""
    n = 16
    g = StaggeredGrid(n=(n, n, n), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    z = (np.arange(n) + 0.5) / n
    phi0 = jnp.asarray(
        np.broadcast_to((0.5 - z)[None, None, :], (n, n, n)),
        dtype=jnp.float64)
    integ = INSVCStaggeredIntegrator(
        g, rho0=1.0, rho1=50.0, mu0=0.01, mu1=0.01,
        gravity=(0.0, 0.0, -1.0), sigma=0.0, convective_op_type="none",
        reinit_interval=1000, cg_tol=1e-11,
        wall_axes=(True, True, True), dtype=jnp.float64)
    st = integ.initialize(phi0)
    st = advance_vc(integ, st, 1e-3, 10)
    umax = max(float(jnp.max(jnp.abs(c))) for c in st.u)
    assert umax < 1e-9, umax
    _wall_normal_faces_zero(st, (True, True, True))


def test_falling_drop_3d_walled_smoke():
    """3D heavy drop in a closed tank: stable, div-free, walls pinned
    (the dimension-generic falling-drop path)."""
    n = 16
    g = StaggeredGrid(n=(n, n, n), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    xx = (np.arange(n) + 0.5) / n
    X, Y, Z = np.meshgrid(xx, xx, xx, indexing="ij")
    r = np.sqrt((X - 0.5) ** 2 + (Y - 0.5) ** 2 + (Z - 0.65) ** 2)
    phi0 = jnp.asarray(0.18 - r, dtype=jnp.float64)
    integ = INSVCStaggeredIntegrator(
        g, rho0=1.0, rho1=10.0, mu0=0.01, mu1=0.02,
        gravity=(0.0, 0.0, -5.0), sigma=0.0,
        convective_op_type="upwind", reinit_interval=10,
        cg_tol=1e-9, wall_axes=(True, True, True), dtype=jnp.float64)
    st = integ.initialize(phi0)
    st = advance_vc(integ, st, 1e-3, 20)
    assert all(bool(jnp.all(jnp.isfinite(c))) for c in st.u)
    div = float(jnp.max(jnp.abs(stencils.divergence(st.u, g.dx))))
    assert div < 1e-7, div
    _wall_normal_faces_zero(st, (True, True, True))
