"""Sharded checkpointing (PR 6): per-shard verified writes, elastic
N->M restore, bounded async writers, and the distributed-failure
injector inventory.

Oracles: the save path never materializes the global state on the
host (pinned by counting every ``_fetch_shard`` block); a checkpoint
written on N devices restores BITWISE on any M in {1, 2, 8} against
the gather-restore oracle; every injected damage mode (corrupt shard,
dropped shard, torn manifest, stale-manifest-newer-shards) flunks
verification and falls back to the previous verified step; a SIGKILL
mid-commit loses at most one checkpoint interval (subprocess drill,
slow tier); fsck re-verifies both formats offline and exits nonzero
on corruption.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ibamr_tpu.utils import checkpoint_sharded as cs
from ibamr_tpu.utils.checkpoint import (AsyncCheckpointWriter,
                                        CheckpointCorruptError,
                                        save_checkpoint)
from ibamr_tpu.utils import checkpoint as ckpt
from ibamr_tpu.utils.checkpoint_sharded import (AsyncShardedWriter,
                                                latest_sharded_step,
                                                read_manifest,
                                                restore_sharded,
                                                save_sharded_checkpoint,
                                                verify_sharded_checkpoint)
from ibamr_tpu.utils.watchdog import RunWatchdog, read_heartbeat
from tools.ckpt_fsck import audit, main as fsck_main
from tools.fault_injection import (corrupt_checkpoint, corrupt_shard,
                                   crash_state, drop_shard,
                                   stale_manifest_shard, tear_manifest)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mesh(n):
    devs = sorted(jax.devices(), key=lambda d: d.id)[:n]
    return Mesh(np.array(devs), ("x",))


def _host_state(seed=0, n=64):
    rng = np.random.default_rng(seed)
    return {"u": rng.standard_normal((n, n)),
            "v": rng.standard_normal(n),
            "k": np.int64(seed)}


def _place(state, mesh):
    # arrays shard over the mesh axis; scalars replicate
    sh = NamedSharding(mesh, P("x"))
    rep = NamedSharding(mesh, P())
    return {k: jax.device_put(jnp.asarray(v),
                              sh if np.ndim(v) >= 1 else rep)
            for k, v in state.items()}


def _assert_states_equal(got, want):
    for key in want:
        assert np.array_equal(np.asarray(got[key]),
                              np.asarray(want[key])), key


# ---------------------------------------------------------------------------
# save / verify / restore on one mesh
# ---------------------------------------------------------------------------

def test_sharded_save_restore_bitwise_same_mesh(tmp_path, mesh8):
    host = _host_state(1)
    st = _place(host, mesh8)
    save_sharded_checkpoint(str(tmp_path), st, 7, mesh=mesh8,
                            metadata={"tag": "x"})
    assert verify_sharded_checkpoint(str(tmp_path), 7)
    assert latest_sharded_step(str(tmp_path)) == 7
    man = read_manifest(str(tmp_path), 7)
    assert man["mesh"]["n_shards"] == 8
    assert tuple(man["mesh"]["shape"]) == (8,)
    assert list(man["mesh"]["axis_names"]) == ["x"]
    assert man["metadata"] == {"tag": "x"}
    # one shard file per device, plus the manifest commit marker
    sdir = cs._step_dir(str(tmp_path), 7)
    shards = [f for f in os.listdir(sdir) if f.startswith("shard-")]
    assert len(shards) == 8

    got, k, _ = restore_sharded(str(tmp_path), _place(_host_state(2),
                                                     mesh8))
    assert k == 7
    _assert_states_equal(got, host)
    # same-mesh restore is a memcpy: placement matches the template
    assert got["u"].sharding.device_set == st["u"].sharding.device_set


def test_sharded_save_never_gathers_global_state(tmp_path, mesh8,
                                                monkeypatch):
    """The save path moves only per-device blocks to the host — never
    a leaf's global array (the whole point of the sharded format)."""
    host = _host_state(3)
    st = _place(host, mesh8)
    u_bytes = np.asarray(host["u"]).nbytes
    fetched = []
    orig = cs._fetch_shard

    def counting(data):
        arr = orig(data)
        fetched.append(arr.nbytes)
        return arr

    monkeypatch.setattr(cs, "_fetch_shard", counting)
    save_sharded_checkpoint(str(tmp_path), st, 5, mesh=mesh8)
    assert fetched, "no shard fetches recorded"
    assert max(fetched) <= u_bytes // 8, \
        f"a fetch moved {max(fetched)} bytes (global u = {u_bytes})"
    got, _, _ = restore_sharded(str(tmp_path),
                                {k: np.asarray(v)
                                 for k, v in host.items()})
    _assert_states_equal(got, host)


# ---------------------------------------------------------------------------
# elastic N -> M restore matrix
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n_src", [1, 2, 8])
@pytest.mark.parametrize("n_dst", [1, 2, 8])
def test_elastic_restore_matrix(tmp_path, n_src, n_dst):
    """A checkpoint written on n_src devices restores on n_dst devices
    bitwise against the gather-restore oracle — the host arrays the
    source state held. All 9 {1,2,8}x{1,2,8} pairs."""
    host = _host_state(n_src * 10 + n_dst)
    d = str(tmp_path)
    save_sharded_checkpoint(d, _place(host, _mesh(n_src)), 3,
                            mesh=_mesh(n_src))
    man = read_manifest(d, 3)
    assert man["mesh"]["n_shards"] == n_src

    template = _place(_host_state(0), _mesh(n_dst))
    got, k, _ = restore_sharded(d, template)
    assert k == 3
    _assert_states_equal(got, host)                 # bitwise oracle
    for key in ("u", "v", "k"):
        assert got[key].sharding.device_set == \
            template[key].sharding.device_set, key
    # host-template restore (no .sharding) lands plain numpy
    got_np, _, _ = restore_sharded(
        d, {k: np.asarray(v) for k, v in host.items()})
    _assert_states_equal(got_np, host)
    assert isinstance(got_np["u"], np.ndarray)


# ---------------------------------------------------------------------------
# damage inventory: every injector flunks verification, restore falls
# back to the previous verified step
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("damage", [corrupt_shard, drop_shard,
                                    stale_manifest_shard])
def test_shard_damage_falls_back_to_previous_verified(tmp_path, mesh8,
                                                      damage):
    d = str(tmp_path)
    old = _host_state(10)
    new = _host_state(11)
    save_sharded_checkpoint(d, _place(old, mesh8), 5, mesh=mesh8)
    save_sharded_checkpoint(d, _place(new, mesh8), 10, mesh=mesh8)
    damage(d, 10)
    assert not verify_sharded_checkpoint(d, 10)
    assert verify_sharded_checkpoint(d, 5)
    assert latest_sharded_step(d) == 5              # never the damaged one
    with pytest.warns(UserWarning):
        got, k, _ = restore_sharded(
            d, {k2: np.asarray(v) for k2, v in old.items()})
    assert k == 5
    _assert_states_equal(got, old)
    with pytest.raises(CheckpointCorruptError):
        restore_sharded(d, {k2: np.asarray(v) for k2, v in new.items()},
                        step=10)


def test_torn_manifest_never_selected(tmp_path, mesh8):
    """A kill between the shard writes and the manifest commit leaves
    a torn manifest: the step must be invisible to every verified-only
    selector and an explicit restore of it must raise."""
    d = str(tmp_path)
    old = _host_state(20)
    save_sharded_checkpoint(d, _place(old, mesh8), 5, mesh=mesh8)
    save_sharded_checkpoint(d, _place(_host_state(21), mesh8), 10,
                            mesh=mesh8)
    tear_manifest(d, 10)
    assert read_manifest(d, 10) is None
    assert not verify_sharded_checkpoint(d, 10)
    assert latest_sharded_step(d) == 5
    assert latest_sharded_step(d, verified_only=False) == 10
    with pytest.warns(UserWarning):
        got, k, _ = restore_sharded(
            d, {k2: np.asarray(v) for k2, v in old.items()})
    assert k == 5
    _assert_states_equal(got, old)
    with pytest.raises(CheckpointCorruptError):
        restore_sharded(d, {k2: np.asarray(v) for k2, v in old.items()},
                        step=10)


# ---------------------------------------------------------------------------
# bounded async writers
# ---------------------------------------------------------------------------

def test_async_sharded_writer_commits_in_order(tmp_path, mesh8):
    d = str(tmp_path)
    states = {s: _host_state(s) for s in (5, 10, 15)}
    w = AsyncShardedWriter(d, keep=3, max_pending=1, mesh=mesh8)
    try:
        for s in (5, 10, 15):
            w.save(_place(states[s], mesh8), s)
        w.wait()
    finally:
        w.close()
    assert w.dropped_saves == 0
    for s in (5, 10, 15):
        assert verify_sharded_checkpoint(d, s), s
    got, k, _ = restore_sharded(
        d, {k2: np.asarray(v) for k2, v in states[15].items()})
    assert k == 15
    _assert_states_equal(got, states[15])


def test_async_sharded_writer_drop_overflow(tmp_path, mesh8,
                                            monkeypatch):
    monkeypatch.setenv(cs._COMMIT_DELAY_ENV, "0.2")
    d = str(tmp_path)
    w = AsyncShardedWriter(d, keep=0, max_pending=1, overflow="drop",
                           mesh=mesh8)
    try:
        for s in range(1, 6):
            w.save(_place(_host_state(s), mesh8), s)
        depth = w.queue_depth()
        assert depth <= 1
        w.wait()
    finally:
        w.close()
    assert w.dropped_saves >= 1
    assert latest_sharded_step(d) is not None


def test_async_single_host_writer_bounded_queue(tmp_path, monkeypatch):
    """The single-host writer sheds (or blocks) instead of queueing
    unbounded host copies, and surfaces the backlog via
    ``queue_depth``."""
    import time

    d = str(tmp_path)
    orig = ckpt._write_arrays

    def slow_write(*a, **kw):
        time.sleep(0.2)
        return orig(*a, **kw)

    monkeypatch.setattr(ckpt, "_write_arrays", slow_write)
    w = AsyncCheckpointWriter(d, keep=0, max_pending=1, overflow="drop")
    try:
        for s in range(1, 6):
            w.save({"u": np.full((8,), float(s))}, s)
        assert w.queue_depth() <= 1
        w.wait()
    finally:
        w.close()
    assert w.dropped_saves >= 1
    assert ckpt.latest_step(d) is not None

    # block mode: nothing dropped, every save lands
    w2 = AsyncCheckpointWriter(d, keep=0, max_pending=1,
                               overflow="block")
    try:
        for s in range(10, 13):
            w2.save({"u": np.full((8,), float(s))}, s)
        w2.wait()
    finally:
        w2.close()
    assert w2.dropped_saves == 0
    assert ckpt.latest_step(d) == 12
    with pytest.raises(ValueError):
        AsyncCheckpointWriter(d, max_pending=0)
    with pytest.raises(ValueError):
        AsyncCheckpointWriter(d, overflow="panic")


def test_watchdog_heartbeat_reports_queue_depth(tmp_path):
    wd = RunWatchdog(heartbeat_path=str(tmp_path), interval_s=60.0)
    wd.beat(step=3, last_chunk_wall_s=0.5, ckpt_queue_depth=2)
    hb = read_heartbeat(wd.heartbeat_path)
    assert hb is not None
    assert hb["ckpt_queue_depth"] == 2
    assert hb["step"] == 3


# ---------------------------------------------------------------------------
# offline fsck
# ---------------------------------------------------------------------------

def test_fsck_audits_both_formats_and_repairs(tmp_path, mesh8):
    """fsck re-verifies every digest of both formats, exits nonzero on
    corruption, and --repair quarantines (never deletes) the damaged
    steps while leaving the newest verified one untouched."""
    d = str(tmp_path)
    # sharded steps 5 (good) and 10 (corrupted)
    save_sharded_checkpoint(d, _place(_host_state(1), mesh8), 5,
                            mesh=mesh8)
    save_sharded_checkpoint(d, _place(_host_state(2), mesh8), 10,
                            mesh=mesh8)
    corrupt_shard(d, 10)
    # nested single-host dir: step 3 good, step 6 corrupted
    sub = os.path.join(d, "nested")
    os.makedirs(sub)
    save_checkpoint(sub, {"u": np.arange(8.0)}, 3)
    save_checkpoint(sub, {"u": np.arange(8.0) + 1}, 6)
    corrupt_checkpoint(sub, 6)

    rep = audit(d)
    assert not rep["clean"]
    assert rep["counts"]["corrupt"] == 2
    assert rep["counts"]["verified"] >= 2
    assert fsck_main([d, "-q"]) == 1

    assert fsck_main([d, "--repair", "-q"]) == 1
    rep2 = audit(d)
    assert rep2["clean"]
    assert fsck_main([d, "-q"]) == 0
    # the newest verified steps survived repair, bitwise
    assert verify_sharded_checkpoint(d, 5)
    assert ckpt.verify_checkpoint(sub, 3)
    # the damaged steps were MOVED, not deleted
    assert os.path.isdir(os.path.join(d, "quarantine", "sharded.00000010"))
    assert os.path.exists(os.path.join(sub, "quarantine",
                                       "restore.00000006.npz"))


def test_fsck_repair_spares_last_candidate(tmp_path, mesh8):
    """A directory where EVERY step is damaged keeps its newest
    candidate: fsck must never shorten the recovery chain to zero."""
    d = str(tmp_path)
    save_sharded_checkpoint(d, _place(_host_state(1), mesh8), 5,
                            mesh=mesh8)
    tear_manifest(d, 5)
    assert fsck_main([d, "--repair", "-q"]) == 1
    assert os.path.isdir(cs._step_dir(d, 5))        # spared in place


# ---------------------------------------------------------------------------
# SIGKILL-one-writer subprocess drill (slow tier)
# ---------------------------------------------------------------------------

def _spawn_sharded_crash_child(d, steps=40, interval=5, n_devices=8):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    # widen the shard-writes -> manifest-commit window so the kill
    # reliably lands mid-commit in at least one cycle
    env["IBAMR_SHARDED_COMMIT_DELAY_S"] = "0.05"
    return subprocess.Popen(
        [sys.executable, "-m", "tools.fault_injection",
         "--sharded-crash-child", str(d), "--steps", str(steps),
         "--interval", str(interval), "--n-devices", str(n_devices)],
        cwd=REPO_ROOT, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, bufsize=1)


def test_sharded_kill_one_writer_loses_at_most_one_interval(tmp_path):
    """SIGKILL the sharded checkpoint writer the instant a save lands,
    two crash cycles: after every kill the newest VERIFIED sharded
    step is no older than the last acknowledged save, restores bitwise
    against the closed-form trajectory on the full 8-device run
    directory AND on a 1-device mesh (the elastic acceptance pin).
    Then the child runs to completion from the wreckage."""
    d = str(tmp_path)
    last_acked = 0
    for cycle in range(2):
        p = _spawn_sharded_crash_child(d)
        acked = None
        try:
            for line in p.stdout:
                if line.startswith("SAVED"):
                    acked = int(line.split()[1])
                    if acked > last_acked:
                        break
                elif line.startswith("DONE"):
                    break
        finally:
            p.kill()
            p.wait()
        assert acked is not None and acked > last_acked, \
            f"cycle {cycle}: child made no progress"
        last_acked = acked
        ls = latest_sharded_step(d)
        assert ls is not None and ls >= acked       # <= 1 interval lost
        want = crash_state(ls)
        got, k, man = restore_sharded(
            d, {k2: np.asarray(v) for k2, v in want.items()}, step=ls)
        assert k == ls
        assert np.array_equal(np.asarray(got["u"]), want["u"])
        assert man["mesh"]["n_shards"] == 8
        # elastic: the same run directory restores bitwise on 1 device
        got1, k1, _ = restore_sharded(d, _place(want, _mesh(1)),
                                      step=ls)
        assert k1 == ls
        assert np.array_equal(np.asarray(got1["u"]), want["u"])

    p = _spawn_sharded_crash_child(d)
    out, _ = p.communicate(timeout=300)
    assert p.returncode == 0, out
    assert "DONE" in out
    assert latest_sharded_step(d) == 40
    want = crash_state(40)
    got, k, _ = restore_sharded(
        d, {k2: np.asarray(v) for k2, v in want.items()})
    assert k == 40
    assert np.array_equal(np.asarray(got["u"]), want["u"])


def test_sharded_smoke_drill_end_to_end(tmp_path):
    """The full dryrun path-19 drill in a subprocess: no-gather audit,
    elastic N->1, the four damage injectors, the concurrent-writer
    collision, supervised sharded rollback, and the fsck gate."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "tools.fault_injection",
         "--sharded-smoke", "--dir", str(tmp_path)],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True,
        timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    rep = json.loads(out.stdout.strip().splitlines()[-1])
    assert rep["sharded_smoke"] == "ok"
    assert rep["rollback_step"] == 4
    # the collision race has two acceptable endings, both asserted
    # inside the drill: verified-and-bitwise-one-writer, or
    # detected-corrupt (never a verified mix of the two writers)
    assert rep["collision_verified"] in (True, False)
    assert rep["fsck_quarantined"] >= 4
