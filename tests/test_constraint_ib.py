"""ConstraintIB (P16) tests: rigid-mode projection exactness, prescribed
kinematics imposing the body velocity on the fluid, free-body momentum
consistency, and deformational kinematics carrying no net momentum."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.cib import RigidBodies, rigid_velocity
from ibamr_tpu.integrators.constraint_ib import (
    ConstraintIBMethod, advance_constraint_ib, fill_disc, project_rigid)
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator

F64 = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def _bodies(n):
    return RigidBodies(body_id=jnp.zeros(n, dtype=jnp.int32), n_bodies=1)


# -- projection --------------------------------------------------------------

def test_project_rigid_recovers_rigid_motion_2d():
    X = fill_disc((0.5, 0.5), 0.2, 0.03, dtype=F64)
    bodies = _bodies(X.shape[0])
    U_true = jnp.array([[0.3, -0.7, 1.9]], dtype=F64)
    U = rigid_velocity(X, bodies, U_true)
    U_proj = project_rigid(X, bodies, U)
    assert np.allclose(np.asarray(U_proj), np.asarray(U_true), atol=1e-5)


def test_project_rigid_recovers_rigid_motion_3d():
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.rand(200, 3), dtype=F64)
    bodies = _bodies(200)
    U_true = jnp.array([[0.1, 0.2, -0.3, 0.5, -1.0, 0.7]], dtype=F64)
    U = rigid_velocity(X, bodies, U_true)
    U_proj = project_rigid(X, bodies, U)
    assert np.allclose(np.asarray(U_proj), np.asarray(U_true), atol=1e-4)


def test_project_rigid_kills_deformation():
    # a pure radial (breathing) field has zero rigid component
    X = fill_disc((0.5, 0.5), 0.2, 0.03, dtype=F64)
    bodies = _bodies(X.shape[0])
    r = X - jnp.array([0.5, 0.5], dtype=F64)
    U = 0.8 * r
    U_proj = np.asarray(project_rigid(X, bodies, U))
    assert np.allclose(U_proj, 0.0, atol=1e-6)


# -- prescribed kinematics ---------------------------------------------------

def test_prescribed_translation_imposes_fluid_velocity():
    grid = StaggeredGrid(n=(64, 64), x_lo=(0, 0), x_up=(1, 1))
    ins = INSStaggeredIntegrator(grid, rho=1.0, mu=0.02, dtype=F64)
    X0 = fill_disc((0.35, 0.5), 0.12, grid.dx[0], dtype=F64)
    bodies = _bodies(X0.shape[0])
    V = (0.5, 0.0)
    method = ConstraintIBMethod(
        ins, bodies,
        free=jnp.zeros((1, 3), dtype=F64),
        prescribed_fn=lambda t: jnp.array([[V[0], V[1], 0.0]], dtype=F64))
    state = method.initialize(X0)
    dt = 2e-3
    state = jax.block_until_ready(
        advance_constraint_ib(method, state, dt, 20))
    # markers moved with the prescribed velocity
    drift = np.asarray(state.X - X0).mean(axis=0)
    assert np.allclose(drift, [V[0] * 20 * dt, 0.0], atol=1e-6)
    # fluid inside the body moves (nearly) with the body
    from ibamr_tpu.ops import interaction
    U_i = interaction.interpolate_vel(state.ins.u, grid, state.X,
                                      kernel="IB_4")
    inner = np.linalg.norm(
        np.asarray(state.X) - np.asarray(state.X).mean(axis=0),
        axis=1) < 0.08
    assert abs(np.asarray(U_i)[inner, 0].mean() - V[0]) < 0.08
    # and momentum was actually transferred to the fluid
    ke = float(ins.kinetic_energy(state.ins))
    assert ke > 1e-5
    # incompressibility held
    assert float(ins.max_divergence(state.ins)) < 1e-6


def test_free_body_follows_uniform_flow():
    grid = StaggeredGrid(n=(48, 48), x_lo=(0, 0), x_up=(1, 1))
    ins = INSStaggeredIntegrator(grid, rho=1.0, mu=0.05,
                                 convective_op_type="none", dtype=F64)
    X0 = fill_disc((0.5, 0.5), 0.1, grid.dx[0], dtype=F64)
    bodies = _bodies(X0.shape[0])
    method = ConstraintIBMethod(ins, bodies)
    u0 = (jnp.full(grid.n, 0.4, dtype=F64),
          jnp.zeros(grid.n, dtype=F64))
    state = method.initialize(X0, ins_state=ins.initialize(u0_arrays=u0))
    dt = 2e-3
    state = jax.block_until_ready(
        advance_constraint_ib(method, state, dt, 10))
    # the free body rides the uniform flow; correction leaves it intact
    U = np.asarray(state.U_body[0])
    assert abs(U[0] - 0.4) < 1e-3 and abs(U[1]) < 1e-4 and abs(U[2]) < 1e-3
    drift = np.asarray(state.X - X0).mean(axis=0)
    assert abs(drift[0] - 0.4 * 10 * dt) < 1e-3


def test_deformation_velocity_carries_no_momentum():
    grid = StaggeredGrid(n=(48, 48), x_lo=(0, 0), x_up=(1, 1))
    ins = INSStaggeredIntegrator(grid, rho=1.0, mu=0.05, dtype=F64)
    X0 = fill_disc((0.5, 0.5), 0.1, grid.dx[0], dtype=F64)
    bodies = _bodies(X0.shape[0])

    def gait(t, X):
        # deliberately momentum-polluted deformation: uniform + radial
        r = X - jnp.array([0.5, 0.5], dtype=X.dtype)
        return 0.3 * jnp.ones_like(X) + 0.5 * r

    method = ConstraintIBMethod(ins, bodies, deformation_fn=gait)
    state = method.initialize(X0)
    dt = 2e-3
    state = jax.block_until_ready(
        advance_constraint_ib(method, state, dt, 10))
    # rigid projection strips the uniform part, so the body centroid
    # must not self-propel from the polluted gait
    drift = np.asarray(state.X - X0).mean(axis=0)
    assert np.all(np.abs(drift) < 2e-3)
