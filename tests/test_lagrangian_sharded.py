"""Lagrangian co-partitioning (S2): shard-owned markers + ppermute halos.

Reference parity: LDataManager marker-rank co-partitioning + VecScatter
ghost accumulation (T1/S2, SURVEY.md §2.3) — VERDICT round 1 item 2.

Oracles: the replicated scatter/gather path (ops.interaction) is exact;
the sharded engine must reproduce it to roundoff for every mesh shape,
including markers whose stencils straddle shard boundaries and the
periodic wrap, and under capacity overflow (compact fallback).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import interaction
from ibamr_tpu.parallel import ShardedInteraction, make_mesh
from ibamr_tpu.parallel.mesh import place_state


def _rand(n, rng):
    return jnp.asarray(rng.uniform(0.0, 1.0, n))


@pytest.mark.parametrize("gshape,max_axes", [
    ((32, 24), 1), ((32, 24), 2), ((16, 24, 12), 2), ((24, 16, 12), 1)])
def test_sharded_matches_replicated(gshape, max_axes):
    rng = np.random.default_rng(0)
    dim = len(gshape)
    g = StaggeredGrid(n=gshape, x_lo=(0.0,) * dim, x_up=(1.0,) * dim)
    mesh = make_mesh(8, max_axes=max_axes)
    N = 400
    X = _rand((N, dim), rng)
    F = jnp.asarray(rng.standard_normal((N, dim)))
    u = tuple(jnp.asarray(rng.standard_normal(g.n)) for _ in range(dim))
    si = ShardedInteraction(g, mesh, n_markers=N)

    f_ref = interaction.spread_vel(F, g, X)
    f_sh = si.spread_vel(F, X)
    for a, b in zip(f_ref, f_sh):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=1e-11)
    U_ref = interaction.interpolate_vel(u, g, X)
    U_sh = si.interpolate_vel(u, X)
    np.testing.assert_allclose(np.asarray(U_sh), np.asarray(U_ref),
                               atol=1e-12)


@pytest.mark.parametrize("gshape,max_axes", [
    ((16, 24, 12), 2), ((32, 16, 8), 1)])
def test_fused_vel_paths_bitwise_equal_per_component(gshape, max_axes):
    """The PR-16 fused kernels pipeline the halo exchange ACROSS
    components (component c+1's local scatter/stencil runs while
    component c's ghost slabs ride the ring) but never touch any
    component's own expression tree — so ``spread_vel`` /
    ``interpolate_vel`` must match the per-component ``spread`` /
    ``interpolate`` loop BITWISE in f64, masked markers included."""
    rng = np.random.default_rng(11)
    dim = len(gshape)
    g = StaggeredGrid(n=gshape, x_lo=(0.0,) * dim, x_up=(1.0,) * dim)
    mesh = make_mesh(8, max_axes=max_axes)
    N = 300
    X = _rand((N, dim), rng)
    F = jnp.asarray(rng.standard_normal((N, dim)))
    u = tuple(jnp.asarray(rng.standard_normal(g.n)) for _ in range(dim))
    w = jnp.asarray((rng.uniform(size=N) > 0.2).astype(float))
    si = ShardedInteraction(g, mesh, n_markers=N)
    b = si.buckets(X, w)

    f_fused = si.spread_vel(F, X, weights=w, b=b)
    for c in range(dim):
        f_ref = si.spread(F[:, c], X, c, b)
        np.testing.assert_array_equal(np.asarray(f_fused[c]),
                                      np.asarray(f_ref),
                                      err_msg=f"spread component {c}")

    U_fused = si.interpolate_vel(u, X, weights=w, b=b)
    for c in range(dim):
        U_ref = si.interpolate(u[c], X, c, b)
        np.testing.assert_array_equal(np.asarray(U_fused[:, c]),
                                      np.asarray(U_ref),
                                      err_msg=f"interp component {c}")


def test_fused_spread_hides_the_halo_exchange():
    """Structural pin at the unit level: the fused 3-component spread
    on the 2-D mesh leaves at most 2 unhidden ppermutes (the tail
    pair of the LAST component — no further local work exists), where
    a per-component chain leaves one unhidden pair per component."""
    from ibamr_tpu.analysis.graph_census import structural_overlap_census

    rng = np.random.default_rng(12)
    g = StaggeredGrid(n=(16, 24, 12), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    mesh = make_mesh(8, max_axes=2)
    N = 300
    X = _rand((N, 3), rng)
    F = jnp.asarray(rng.standard_normal((N, 3)))
    si = ShardedInteraction(g, mesh, n_markers=N)

    def fused(Fa, Xa):
        b = si.buckets(Xa, None)
        return si.spread_vel(Fa, Xa, b=b)

    c = structural_overlap_census(
        jax.make_jaxpr(fused)(F, X).jaxpr)
    assert c["unhidden_collectives"] <= 2
    assert c["hidden_fraction"] >= 80


def test_boundary_straddling_markers():
    """Markers seeded ON shard boundaries and the periodic seam exercise
    the halo-add and ghost-fill paths specifically."""
    rng = np.random.default_rng(1)
    g = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    mesh = make_mesh(8, max_axes=2)          # (4, 2): blocks of 8 x 16
    edges = np.array([0.0, 0.25, 0.5, 0.75])  # x shard boundaries
    xs = np.concatenate([edges + o for o in (-1e-9, 0.0, 1e-3, -1e-3)])
    xs = np.mod(xs, 1.0)
    X = jnp.asarray(np.stack([
        np.repeat(xs, 4),
        np.tile(rng.uniform(0, 1, 4), len(xs))], axis=1))
    N = X.shape[0]
    F = jnp.asarray(rng.standard_normal((N, 2)))
    si = ShardedInteraction(g, mesh, n_markers=N)
    f_ref = interaction.spread_vel(F, g, X)
    f_sh = si.spread_vel(F, X)
    for a, b in zip(f_ref, f_sh):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=1e-12)


def test_adjointness_sharded():
    """<spread(F), u> h^dim == sum_m F . interp(u) through the SHARDED
    paths (the free correctness oracle of SURVEY.md stage 4)."""
    rng = np.random.default_rng(2)
    g = StaggeredGrid(n=(16, 24, 16), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    mesh = make_mesh(8, max_axes=2)
    N = 300
    X = _rand((N, 3), rng)
    F = jnp.asarray(rng.standard_normal((N, 3)))
    u = tuple(jnp.asarray(rng.standard_normal(g.n)) for _ in range(3))
    si = ShardedInteraction(g, mesh, n_markers=N)
    b = si.buckets(X)
    f = si.spread_vel(F, X, b=b)
    U = si.interpolate_vel(u, X, b=b)
    lhs = sum(float(jnp.sum(a * c)) for a, c in zip(f, u)) * g.cell_volume
    rhs = float(jnp.sum(F * U))
    np.testing.assert_allclose(lhs, rhs, rtol=1e-12)


def test_overflow_compact_fallback_exact():
    """Cluster all markers into one shard with a tiny capacity: the
    overflow markers must flow through the compact replicated path and
    the result stays exact."""
    rng = np.random.default_rng(3)
    g = StaggeredGrid(n=(32, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    mesh = make_mesh(8, max_axes=1)
    N = 200
    # all markers inside shard 0's block [0, 1/8)
    X = jnp.asarray(np.stack([rng.uniform(0.0, 0.12, N),
                              rng.uniform(0.0, 1.0, N)], axis=1))
    F = jnp.asarray(rng.standard_normal((N, 2)))
    si = ShardedInteraction(g, mesh, n_markers=N, cap=16)
    b = si.buckets(X)
    assert bool(b.any_overflow)
    assert not bool(b.exceeded)
    f_ref = interaction.spread_vel(F, g, X)
    f_sh = si.spread_vel(F, X, b=b)
    for a, c in zip(f_ref, f_sh):
        np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                   atol=1e-12)
    u = tuple(jnp.asarray(rng.standard_normal(g.n)) for _ in range(2))
    np.testing.assert_allclose(np.asarray(si.interpolate_vel(u, X, b=b)),
                               np.asarray(interaction.interpolate_vel(
                                   u, g, X)), atol=1e-12)


def test_exceeded_full_fallback_exact():
    """Overflow buffer smaller than the overflow count: the full-scatter
    fallback must still be exact."""
    rng = np.random.default_rng(4)
    g = StaggeredGrid(n=(32, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    mesh = make_mesh(8, max_axes=1)
    N = 300
    X = jnp.asarray(np.stack([rng.uniform(0.0, 0.1, N),
                              rng.uniform(0.0, 1.0, N)], axis=1))
    F = jnp.asarray(rng.standard_normal((N, 2)))
    si = ShardedInteraction(g, mesh, n_markers=N, cap=8, overflow_cap=32)
    b = si.buckets(X)
    assert bool(b.exceeded)
    f_ref = interaction.spread_vel(F, g, X)
    f_sh = si.spread_vel(F, X, b=b)
    for a, c in zip(f_ref, f_sh):
        np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                   atol=1e-12)
    # with a 0/1 mask: masked markers must stay masked in the full
    # fallback (round-2 review regression: the fallback used weight 1.0
    # for every overflowed marker)
    mask = jnp.asarray((rng.uniform(size=N) > 0.5).astype(np.float64))
    bm = si.buckets(X, mask)
    assert bool(bm.exceeded)
    f_ref_m = interaction.spread_vel(F, g, X, weights=mask)
    f_sh_m = si.spread_vel(F, X, weights=mask, b=bm)
    for a, c in zip(f_ref_m, f_sh_m):
        np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                   atol=1e-12)
    u = tuple(jnp.asarray(rng.standard_normal(g.n)) for _ in range(2))
    np.testing.assert_allclose(
        np.asarray(si.interpolate_vel(u, X, weights=mask, b=bm)),
        np.asarray(interaction.interpolate_vel(u, g, X, weights=mask)),
        atol=1e-12)


def test_masked_markers_sharded():
    rng = np.random.default_rng(5)
    g = StaggeredGrid(n=(24, 24), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    mesh = make_mesh(8, max_axes=1)
    N = 100
    X = _rand((N, 2), rng)
    F = jnp.asarray(rng.standard_normal((N, 2)))
    mask = jnp.asarray((rng.uniform(size=N) > 0.4).astype(np.float64))
    si = ShardedInteraction(g, mesh, n_markers=N)
    f_ref = interaction.spread_vel(F, g, X, weights=mask)
    f_sh = si.spread_vel(F, X, weights=mask)
    for a, c in zip(f_ref, f_sh):
        np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                   atol=1e-12)


def test_coupled_ib_step_sharded_markers_equality():
    """Full coupled IB step, 1 device vs 8 devices with S2 sharded
    markers: marker trajectories must agree to roundoff (the mpirun=1
    vs mpirun=8 analog, SURVEY.md §4 implication 3)."""
    from ibamr_tpu.models.shell3d import build_shell_example
    from ibamr_tpu.parallel import make_sharded_ib_step

    integ, st = build_shell_example(
        n_cells=32, n_lat=20, n_lon=20, mu=0.05, dtype=jnp.float64,
        use_fast_interaction=False)
    step1 = jax.jit(lambda s, d: integ.step(s, d))
    ref = st
    for _ in range(5):
        ref = step1(ref, 1e-3)

    mesh = make_mesh(8)
    integ2, st2 = build_shell_example(
        n_cells=32, n_lat=20, n_lon=20, mu=0.05, dtype=jnp.float64,
        use_fast_interaction=False)
    st2 = place_state(st2, integ2.ins.grid, mesh)
    stepN = make_sharded_ib_step(integ2, mesh, sharded_markers=True)
    out = st2
    for _ in range(5):
        out = stepN(out, 1e-3)
    np.testing.assert_allclose(np.asarray(out.X), np.asarray(ref.X),
                               atol=1e-12)
    for a, b in zip(ref.ins.u, out.ins.u):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=1e-12)


def test_shell_128_10k_markers_sharded():
    """The VERDICT acceptance shape: >=128^3 grid, >=1e4 markers, 1-dev
    vs 8-dev equality of the sharded spread/interp transfers (f32 to
    keep the suite's memory/runtime sane; tolerance scaled to f32)."""
    from ibamr_tpu.models.shell3d import make_spherical_shell

    g = StaggeredGrid(n=(128, 128, 128), x_lo=(0.0,) * 3,
                      x_up=(1.0,) * 3)
    mesh = make_mesh(8, max_axes=2)
    s = make_spherical_shell(100, 100, 0.25, center=(0.5, 0.5, 0.5),
                             stiffness=1.0)
    X = jnp.asarray(s.vertices, dtype=jnp.float32)
    N = X.shape[0]
    assert N >= 10000
    rng = np.random.default_rng(6)
    F = jnp.asarray(rng.standard_normal((N, 3)), dtype=jnp.float32)
    # a spherical shell concentrates markers in the central mesh blocks
    # (no markers in the outer x-blocks), so capacity needs headroom
    # beyond the balanced share — slack 4 covers the ~35% max-block load
    si = ShardedInteraction(g, mesh, n_markers=N, slack=4.0)
    b = si.buckets(X)
    assert not bool(b.any_overflow)

    t0 = time.time()
    f_sh = si.spread_vel(F, X, b=b)
    jax.block_until_ready(f_sh)
    t_sh = time.time() - t0
    f_ref = interaction.spread_vel(F, g, X)
    scale = float(max(jnp.max(jnp.abs(c)) for c in f_ref))
    for a, c in zip(f_ref, f_sh):
        np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                   atol=3e-5 * scale)
    U_sh = si.interpolate_vel(
        tuple(jnp.asarray(rng.standard_normal(g.n), dtype=jnp.float32)
              for _ in range(3)), X, b=b)
    assert bool(jnp.all(jnp.isfinite(U_sh)))
    print(f"\n[sharded 128^3/{N} markers] spread wall {t_sh:.2f}s "
          f"(incl. compile)")


def test_parked_pool_markers_do_not_consume_capacity():
    """Inactive (weight-0) slots of a fixed-capacity pool parked at a
    common position must neither occupy shard capacity nor crowd the
    overflow buffer (round-2 review regression)."""
    rng = np.random.default_rng(7)
    g = StaggeredGrid(n=(32, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    mesh = make_mesh(8, max_axes=1)
    N_active, N_parked = 60, 400
    Xa = np.stack([rng.uniform(0, 1, N_active),
                   rng.uniform(0, 1, N_active)], axis=1)
    Xp = np.zeros((N_parked, 2))            # all parked at the origin
    X = jnp.asarray(np.concatenate([Xa, Xp]))
    mask = jnp.asarray(np.concatenate([np.ones(N_active),
                                       np.zeros(N_parked)]))
    F = jnp.asarray(rng.standard_normal((N_active + N_parked, 2)))
    # cap 16 >> active-per-shard but << parked count at shard 0
    si = ShardedInteraction(g, mesh, n_markers=N_active + N_parked,
                            cap=16, overflow_cap=16)
    b = si.buckets(X, mask)
    assert not bool(b.any_overflow)
    assert not bool(b.exceeded)
    f_ref = interaction.spread_vel(F, g, X, weights=mask)
    f_sh = si.spread_vel(F, X, weights=mask, b=b)
    for a, c in zip(f_ref, f_sh):
        np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                   atol=1e-12)
