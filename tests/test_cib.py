"""Stage-9 tests: CIB rigid-body mobility (SURVEY.md §7.2, examples/CIB/ex0
equivalent): steady Stokes solver exactness, mobility operator SPD,
resistance-matrix symmetry/isotropy, prescribed-motion constraint
residual, and quasi-static free-body motion.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators import cib
from ibamr_tpu.ops import stencils
from ibamr_tpu.solvers import fft


def _grid2d(n=64):
    return StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))


# -- steady Stokes solver ---------------------------------------------------

def test_stokes_periodic_discrete_exactness():
    """-mu lap(u) + grad(p) = f is satisfied to machine precision and
    div(u) == 0 (the discrete-symbol FFT contract)."""
    rng = np.random.default_rng(0)
    g = _grid2d(32)
    mu = 0.7
    f = tuple(jnp.asarray(rng.standard_normal(g.n)) for _ in range(2))
    u, p = fft.solve_stokes_periodic(f, g.dx, mu)
    assert float(jnp.max(jnp.abs(stencils.divergence(u, g.dx)))) < 1e-11
    lap_u = stencils.laplacian_vel(u, g.dx)
    gp = stencils.gradient(p, g.dx)
    for d in range(2):
        resid = -mu * lap_u[d] + gp[d] - f[d]
        # the solver works in the zero-mean frame: residual = -mean force
        resid = resid + jnp.mean(f[d])
        assert float(jnp.max(jnp.abs(resid))) < 1e-10


# -- mobility operator ------------------------------------------------------

def _disc_setup(n=64, n_markers=40, radius=0.12):
    g = _grid2d(n)
    X = cib.make_disc((0.5, 0.5), radius, n_markers)
    bodies = cib.RigidBodies(
        body_id=jnp.zeros(n_markers, dtype=jnp.int32), n_bodies=1)
    return g, X, bodies


def test_mobility_operator_spd():
    g, X, bodies = _disc_setup()
    m = cib.CIBMethod(g, bodies, mu=1.0)
    rng = np.random.default_rng(1)
    l1 = jnp.asarray(rng.standard_normal(X.shape))
    l2 = jnp.asarray(rng.standard_normal(X.shape))
    a = float(jnp.sum(l1 * m.mobility_apply(X, l2)))
    b = float(jnp.sum(l2 * m.mobility_apply(X, l1)))
    assert np.isclose(a, b, rtol=1e-10), "mobility not symmetric"
    q = float(jnp.sum(l1 * m.mobility_apply(X, l1)))
    assert q > 0, "mobility not positive"


def test_resistance_matrix_spd_isotropy():
    g, X, bodies = _disc_setup()
    m = cib.CIBMethod(g, bodies, mu=1.0)
    R, _, info = m.resistance_matrix(X)
    assert bool(info.converged)
    R = np.asarray(R)
    assert R.shape == (3, 3)          # 2 translations + 1 rotation
    np.testing.assert_allclose(R, R.T, rtol=1e-8)
    ev = np.linalg.eigvalsh(R)
    assert ev.min() > 0, f"resistance not SPD: {ev}"
    # disc isotropy: x and y drag equal; translation-rotation decoupled
    assert np.isclose(R[0, 0], R[1, 1], rtol=1e-6)
    assert abs(R[0, 2]) < 1e-6 * R[0, 0]
    assert abs(R[0, 1]) < 1e-6 * R[0, 0]


def test_constraint_rigid_motion_residual():
    """Prescribed translation: the solved flow moves every marker with
    the prescribed velocity (the CIB constraint, to CG tolerance)."""
    g, X, bodies = _disc_setup()
    m = cib.CIBMethod(g, bodies, mu=1.0, cg_tol=1e-11)
    U = jnp.asarray([[0.3, -0.1, 0.0]])
    lam, FT, info = m.solve_constraint(X, U)
    assert bool(info.converged)
    # replay: spread lambda, solve Stokes, interp
    got = m.mobility_apply(X, lam)
    want = cib.rigid_velocity(X, bodies, U)
    err = float(jnp.max(jnp.abs(got - want)))
    assert err < 1e-7, f"constraint residual {err}"
    # drag opposes motion: net force along +x must be positive (the
    # constraint force DRIVES the body against fluid drag)
    assert float(FT[0, 0]) * 0.3 > 0
    # torque-free for pure translation of a disc
    assert abs(float(FT[0, 2])) < 1e-6 * abs(float(FT[0, 0]))


def test_mobility_solve_roundtrip():
    """solve_mobility inverts solve_constraint: U -> (lam, FT) -> U."""
    g, X, bodies = _disc_setup()
    m = cib.CIBMethod(g, bodies, mu=1.0, cg_tol=1e-11)
    U = jnp.asarray([[0.2, 0.05, 0.4]])
    _, FT, _ = m.solve_constraint(X, U)
    U2, _, _ = m.solve_mobility(X, FT)
    np.testing.assert_allclose(np.asarray(U2), np.asarray(U),
                               rtol=1e-5, atol=1e-8)


def test_two_body_mobility_symmetry():
    """Hydrodynamic interactions: the cross-body resistance blocks are
    transposes (Lorentz reciprocity)."""
    g = _grid2d(64)
    X1 = cib.make_disc((0.35, 0.5), 0.08, 24)
    X2 = cib.make_disc((0.65, 0.5), 0.08, 24)
    X = jnp.concatenate([X1, X2])
    bid = jnp.concatenate([jnp.zeros(24, jnp.int32),
                           jnp.ones(24, jnp.int32)])
    m = cib.CIBMethod(g, cib.RigidBodies(body_id=bid, n_bodies=2), mu=1.0)
    R, _, info = m.resistance_matrix(X)
    assert bool(info.converged)
    R = np.asarray(R)
    assert R.shape == (6, 6)
    np.testing.assert_allclose(R[:3, 3:], R[3:, :3].T, rtol=1e-6,
                               atol=1e-8 * abs(R).max())
    # coupling is weaker than self-resistance
    assert abs(R[0, 3]) < abs(R[0, 0])


def test_free_body_sedimentation_step():
    """A forced body translates along the force; an unforced one stays."""
    g, X, bodies = _disc_setup()
    m = cib.CIBMethod(g, bodies, mu=1.0)
    FT = jnp.asarray([[0.0, -1.0, 0.0]])       # gravity-like
    X1, U, _ = m.step(X, FT, dt=1e-2)
    assert float(U[0, 1]) < 0, "body must sediment along the force"
    assert abs(float(U[0, 0])) < 1e-6 * abs(float(U[0, 1]))
    drop = np.asarray(X1 - X)
    np.testing.assert_allclose(drop[:, 1], drop[0, 1], rtol=1e-5)

    FT0 = jnp.zeros((1, 3))
    X2, U0, _ = m.step(X, FT0, dt=1e-2)
    assert float(jnp.max(jnp.abs(U0))) < 1e-10
    np.testing.assert_allclose(np.asarray(X2), np.asarray(X))


@pytest.mark.parametrize("dim", [3])
def test_sphere_mobility_3d(dim):
    """3D: sphere resistance is isotropic and SPD (6x6)."""
    n = 32
    g = StaggeredGrid(n=(n,) * 3, x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    X = cib.make_sphere((0.5, 0.5, 0.5), 0.12, 6, 8)
    bodies = cib.RigidBodies(
        body_id=jnp.zeros(X.shape[0], dtype=jnp.int32), n_bodies=1)
    m = cib.CIBMethod(g, bodies, mu=1.0, cg_tol=1e-8, cg_maxiter=300)
    R, _, info = m.resistance_matrix(X)
    assert bool(info.converged)
    R = np.asarray(R)
    assert R.shape == (6, 6)
    np.testing.assert_allclose(R, R.T, rtol=1e-6, atol=1e-8 * abs(R).max())
    ev = np.linalg.eigvalsh(R)
    assert ev.min() > 0
    # isotropy of translational drag
    d = np.diag(R)[:3]
    assert np.allclose(d, d[0], rtol=2e-2), d
