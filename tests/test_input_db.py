"""Stage-0 acceptance: config round-trip (SURVEY.md §7.2 stage 0)."""

import math

import pytest

from ibamr_tpu.utils.input_db import (
    InputDatabase, eval_arith, parse_input_string)

SAMPLE = """
// An input file in the reference's vocabulary (SURVEY.md §5.6)
L = 1.0
MAX_LEVELS = 1

Main {
   solver_type = "STAGGERED"          // trailing comment
   dt_max = 1.0e-2
   num_steps = 5
   enable_logging = TRUE
   viz_writers = "VisIt", "Silo"
   lower = 0.0, 0.0
   upper = 2*PI, 2*PI                 /* arithmetic values */

   VelocityInitialConditions {
      function_0 = "sin(2*PI*X_0)*cos(2*PI*X_1)"
      function_1 = "-cos(2*PI*X_0)*sin(2*PI*X_1)"
   }
}

CartesianGeometry {
   domain_boxes = 0, 0, 63, 63
   periodic_dimension = 1, 1
}
"""


def test_parse_scalars():
    db = parse_input_string(SAMPLE)
    main = db.get_database("Main")
    assert main.get_string("solver_type") == "STAGGERED"
    assert main.get_float("dt_max") == pytest.approx(1.0e-2)
    assert main.get_int("num_steps") == 5
    assert main.get_bool("enable_logging") is True
    assert db.get_float("L") == 1.0
    assert db.get_int("MAX_LEVELS") == 1


def test_parse_arrays_and_arith():
    db = parse_input_string(SAMPLE)
    main = db.get_database("Main")
    assert main.get_float_array("lower") == [0.0, 0.0]
    up = main.get_float_array("upper")
    assert up == pytest.approx([2 * math.pi, 2 * math.pi])
    assert main.get_array("viz_writers") == ["VisIt", "Silo"]
    geom = db.get_database("CartesianGeometry")
    assert geom.get_int_array("domain_boxes") == [0, 0, 63, 63]


def test_nested_and_defaults():
    db = parse_input_string(SAMPLE)
    vic = db.get_database("Main").get_database("VelocityInitialConditions")
    assert "sin" in vic.get_string("function_0")
    assert db.get_database("Main").get_float("missing", 3.5) == 3.5
    assert db.get_database("Main").get_bool("missing", False) is False
    with pytest.raises(KeyError):
        db.get_database("Main").get_float("missing")


def test_round_trip_dict():
    db = parse_input_string(SAMPLE)
    d = db.to_dict()
    db2 = InputDatabase.from_dict(d)
    assert db2.to_dict() == d


def test_eval_arith_safety():
    assert eval_arith("2*PI") == pytest.approx(2 * math.pi)
    assert eval_arith("2**3 + 1") == 9
    with pytest.raises(Exception):
        eval_arith("__import__('os').system('true')")
    with pytest.raises(Exception):
        eval_arith("().__class__")


def test_multiline_array():
    text = """
    arr = 1.0,
          2.0,
          3.0
    """
    db = parse_input_string(text)
    assert db.get_float_array("arr") == [1.0, 2.0, 3.0]


def test_unquoted_paths_and_atoms():
    db = parse_input_string("""
    dirname = viz2d/data
    file = data.txt
    precond = FAC-precond
    """)
    assert db.get_string("dirname") == "viz2d/data"
    assert db.get_string("file") == "data.txt"
    assert db.get_string("precond") == "FAC-precond"


def test_caret_power_and_inline_multi_assign():
    db = parse_input_string("Main { L = 2^6  N = 4*4  x = 1.5, 2.5 }")
    m = db.get_database("Main")
    assert m.get_int("L") == 64
    assert m.get_int("N") == 16
    assert m.get_float_array("x") == [1.5, 2.5]


def test_escaped_quotes_in_strings():
    db = parse_input_string(r'''s = "say \"hi\" // not a comment"''')
    assert db.get_string("s") == 'say "hi" // not a comment'


def test_hyphenated_keys_and_block_comment_in_string():
    db = parse_input_string("""
    max-levels = 3
    pattern = "viz/*"   /* a real comment */
    """)
    assert db.get_int("max-levels") == 3
    assert db.get_string("pattern") == "viz/*"
