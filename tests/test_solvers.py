"""Stage-2 acceptance (SURVEY.md §7.2 stage 2): FFT solves invert the
discrete operators to machine precision; CG/BiCGStab converge and agree
with the spectral solves; the Leray projection is exactly divergence-free.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import stencils
from ibamr_tpu.ops.norms import max_norm
from ibamr_tpu.solvers import fft
from ibamr_tpu.solvers.krylov import bicgstab, cg

TWO_PI = 2.0 * math.pi


def _rand_cc(g, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(g.n), dtype=dtype)


@pytest.mark.parametrize("shape", [(32, 32), (16, 24), (8, 12, 16)])
def test_fft_poisson_inverts_discrete_laplacian(shape):
    g = StaggeredGrid(n=shape, x_lo=(0.0,) * len(shape),
                      x_up=tuple(float(s) / shape[0] for s in shape))
    rhs = _rand_cc(g, dtype=jnp.float64)
    rhs = rhs - jnp.mean(rhs)  # compatibility
    p = fft.solve_poisson_periodic(rhs, g.dx)
    res = stencils.laplacian(p, g.dx) - rhs
    assert float(max_norm(res)) < 1e-9 * float(max_norm(rhs)) + 1e-9
    assert abs(float(jnp.mean(p))) < 1e-12


def test_fft_helmholtz_inverts_operator():
    g = StaggeredGrid(n=(24, 24), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    rhs = _rand_cc(g, dtype=jnp.float64)
    alpha, beta = 100.0, -0.05
    u = fft.solve_helmholtz_periodic(rhs, g.dx, alpha, beta)
    res = alpha * u + beta * stencils.laplacian(u, g.dx) - rhs
    assert float(max_norm(res)) < 1e-9 * float(max_norm(rhs))


def test_projection_exactly_divergence_free():
    g = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    rng = np.random.default_rng(3)
    u = tuple(jnp.asarray(rng.standard_normal(g.n), dtype=jnp.float64)
              for _ in range(2))
    u_proj, phi = fft.project_divergence_free(u, g.dx)
    div = stencils.divergence(u_proj, g.dx)
    assert float(max_norm(div)) < 1e-10 * float(max_norm(stencils.divergence(u, g.dx)) + 1)
    # projection is idempotent
    u2, _ = fft.project_divergence_free(u_proj, g.dx)
    for a, b in zip(u2, u_proj):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-10)


def test_cg_matches_fft_on_helmholtz():
    g = StaggeredGrid(n=(24, 24), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    rhs = _rand_cc(g, dtype=jnp.float64)
    alpha, beta = 50.0, -0.1

    def A(x):
        return alpha * x + beta * stencils.laplacian(x, g.dx)

    res = cg(A, rhs, tol=1e-12, maxiter=500)
    assert bool(res.converged)
    exact = fft.solve_helmholtz_periodic(rhs, g.dx, alpha, beta)
    np.testing.assert_allclose(np.asarray(res.x), np.asarray(exact),
                               rtol=1e-7, atol=1e-9)


def test_cg_with_preconditioner_converges_faster():
    g = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    rhs = _rand_cc(g, dtype=jnp.float64)
    alpha, beta = 1.0, -1.0

    def A(x):
        return alpha * x + beta * stencils.laplacian(x, g.dx)

    def M(r):  # exact spectral preconditioner
        return fft.solve_helmholtz_periodic(r, g.dx, alpha, beta)

    plain = cg(A, rhs, tol=1e-10, maxiter=2000)
    precond = cg(A, rhs, M=M, tol=1e-10, maxiter=2000)
    assert bool(precond.converged)
    assert int(precond.iters) <= 2
    assert int(precond.iters) < int(plain.iters)


def test_cg_on_velocity_pytree():
    """CG over a MAC velocity tuple (pytree operand)."""
    g = StaggeredGrid(n=(16, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    rng = np.random.default_rng(5)
    b = tuple(jnp.asarray(rng.standard_normal(g.n), dtype=jnp.float64)
              for _ in range(2))
    alpha, beta = 10.0, -0.01

    def A(u):
        return tuple(alpha * c + beta * stencils.laplacian(c, g.dx) for c in u)

    res = cg(A, b, tol=1e-11, maxiter=300)
    assert bool(res.converged)
    exact = fft.solve_helmholtz_periodic_vel(b, g.dx, alpha, beta)
    for a, e in zip(res.x, exact):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-6, atol=1e-8)


def test_cg_inside_jit():
    g = StaggeredGrid(n=(16, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    rhs = _rand_cc(g, dtype=jnp.float32)

    @jax.jit
    def solve(b):
        def A(x):
            return 10.0 * x - stencils.laplacian(x, g.dx)
        return cg(A, b, tol=1e-5, maxiter=200).x

    x = solve(rhs)
    res = 10.0 * x - stencils.laplacian(x, g.dx) - rhs
    assert float(max_norm(res)) < 1e-3


def test_bicgstab_nonsymmetric():
    """Advection-diffusion-like operator (upwind shift makes it
    nonsymmetric)."""
    g = StaggeredGrid(n=(24, 24), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    rhs = _rand_cc(g, dtype=jnp.float64)

    def A(x):
        adv = (x - jnp.roll(x, 1, 0)) / g.dx[0]
        return 20.0 * x - stencils.laplacian(x, g.dx) + 2.0 * adv

    res = bicgstab(A, rhs, tol=1e-10, maxiter=500)
    assert bool(res.converged)
    check = A(res.x) - rhs
    assert float(max_norm(check)) < 1e-8
