"""IBFE on the composite two-level hierarchy (round 4): the reference
runs its finite-element structures on locally-refined hierarchies
(``IBFEMethod`` + AMR, SURVEY.md P17/§0); TwoLevelIBINS now routes its
transfers through the IBStrategy seam, so the FE coupling (quadrature
clouds, unified projection) rides the fine window unchanged.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ibamr_tpu.amr import FineBox
from ibamr_tpu.amr_ins import TwoLevelIBINS, advance_two_level_ib
from ibamr_tpu.fe.fem import neo_hookean
from ibamr_tpu.fe.mesh import disc_mesh
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ibfe import IBFEMethod

F64 = jnp.float64


def _stretched_disc(stretch=1.08):
    m = disc_mesh(radius=0.08, center=(0.5, 0.5), n_rings=3)
    S = np.diag([stretch, 1.0 / stretch])
    X0 = (m.nodes - 0.5) @ S.T + 0.5
    return m, jnp.asarray(X0, F64)


def test_ibfe_on_two_level_hierarchy_relaxes():
    """A pre-stretched hyperelastic disc INSIDE the fine window of a
    composite two-level hierarchy: runs finite, the elastic energy
    decays (the disc relaxes toward the reference shape), and the
    fluid picks up the released energy — the IBFE-on-AMR
    configuration."""
    from ibamr_tpu.fe import build_assembly
    from ibamr_tpu.fe.fem import elastic_energy

    g = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    box = FineBox(lo=(8, 8), shape=(16, 16))
    m, X0 = _stretched_disc()
    fe = IBFEMethod(m, neo_hookean(1.0, 4.0), kernel="IB_4", dtype=F64)
    integ = TwoLevelIBINS(g, box, fe, mu=0.05, proj_tol=1e-9)
    st = integ.initialize(X0)

    asm = build_assembly(m, dtype=F64)
    W = neo_hookean(1.0, 4.0)

    def energy(X):
        return float(elastic_energy(asm, W, X))

    e0 = energy(st.X)
    st = advance_two_level_ib(integ, st, 5e-4, 160)
    assert bool(jnp.all(jnp.isfinite(st.X)))
    e1 = energy(st.X)
    assert e1 < 0.6 * e0, (e0, e1)
    # the released elastic energy moved the fluid on BOTH levels
    assert float(jnp.max(jnp.abs(st.fluid.uf[0]))) > 1e-4
    assert float(jnp.max(jnp.abs(st.fluid.uc[0]))) > 1e-6
    # composite divergence stays at solver tolerance
    assert float(integ.core.max_divergence(st.fluid)) < 1e-6


def test_ibfe_two_level_matches_uniform_fine():
    """The composite IBFE run tracks a UNIFORM fine-resolution IBFE
    run of the same disc (window covers the structure; both see the
    same fine spacing): node positions agree to a few 1e-3 after the
    early relaxation — the hierarchy does not distort the FE
    coupling."""
    from ibamr_tpu.integrators.ib import IBExplicitIntegrator
    from ibamr_tpu.integrators.ins import INSStaggeredIntegrator

    m, X0 = _stretched_disc()
    steps, dt = 80, 5e-4

    # composite: 32^2 coarse + 2x window -> fine spacing 1/64
    g = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    box = FineBox(lo=(8, 8), shape=(16, 16))
    fe = IBFEMethod(m, neo_hookean(1.0, 4.0), kernel="IB_4", dtype=F64)
    tl = TwoLevelIBINS(g, box, fe, mu=0.05, proj_tol=1e-9)
    st_tl = advance_two_level_ib(tl, tl.initialize(X0), dt, steps)

    # uniform 64^2 (same fine spacing everywhere)
    gu = StaggeredGrid(n=(64, 64), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    ins = INSStaggeredIntegrator(gu, mu=0.05, rho=1.0, dtype=F64)
    fe_u = IBFEMethod(m, neo_hookean(1.0, 4.0), kernel="IB_4",
                      dtype=F64)
    un = IBExplicitIntegrator(ins, fe_u)
    st_u = un.initialize(X0)
    step_u = jax.jit(lambda s, d: un.step(s, d))
    for _ in range(steps):
        st_u = step_u(st_u, dt)

    err = float(jnp.max(jnp.abs(st_tl.X - st_u.X)))
    assert err < 5e-3, err
