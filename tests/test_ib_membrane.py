"""Stage-5 acceptance, part 2 — MINIMUM SLICE (SURVEY.md §7.2 stage 5):
the ex0-equivalent 2D periodic membrane end-to-end. Volume (area)
conservation, membrane relaxation toward a circle, force balance,
jit/scan execution.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.integrators.ib import advance_ib, polygon_area
from ibamr_tpu.models.membrane2d import build_membrane_example
from ibamr_tpu.ops.forces import spring_energy


def _radii(state):
    c = np.mean(np.asarray(state.X), axis=0)
    return np.linalg.norm(np.asarray(state.X) - c, axis=1)


def test_membrane_end_to_end_area_conservation():
    integ, st = build_membrane_example(
        n_cells=32, num_markers=64, radius=0.25, aspect=1.0,
        stiffness=5.0, rest_length_factor=0.5, mu=0.1,
        dtype=jnp.float64)
    a0 = float(polygon_area(st.X))
    dt = 2e-4
    st = advance_ib(integ, st, dt, 200)
    a1 = float(polygon_area(st.X))
    # incompressible fluid + no-slip membrane advection => enclosed area
    # conserved (reference's volume-conservation acceptance check)
    assert abs(a1 - a0) / a0 < 0.01, (a0, a1)
    # taut springs (rest < natural) shrink the loop slightly; it must stay
    # a sane closed curve
    r = _radii(st)
    assert 0.15 < r.min() <= r.max() < 0.35
    assert float(integ.ins.max_divergence(st.ins)) < 1e-10


def test_ellipse_relaxes_toward_circle():
    """Classic ex0 behavior: an elliptical membrane under tension
    oscillates and relaxes toward a circle (area-conserving)."""
    integ, st = build_membrane_example(
        n_cells=32, num_markers=64, radius=0.2, aspect=1.4,
        stiffness=10.0, rest_length_factor=0.0,  # pure tension
        mu=0.2, dtype=jnp.float64)
    r0 = _radii(st)
    ecc0 = r0.max() / r0.min()
    a0 = float(polygon_area(st.X))
    st = advance_ib(integ, st, 2e-4, 400)
    r1 = _radii(st)
    ecc1 = r1.max() / r1.min()
    a1 = float(polygon_area(st.X))
    # relaxation toward circular is slow on the viscous timescale; require
    # clear monotone progress plus area conservation within the window
    assert ecc1 < ecc0 - 0.05, (ecc0, ecc1)
    assert abs(a1 - a0) / a0 < 0.02


def test_spring_energy_decays():
    integ, st = build_membrane_example(
        n_cells=32, num_markers=64, radius=0.2, aspect=1.3,
        stiffness=10.0, rest_length_factor=0.0, mu=0.2, dtype=jnp.float64)
    e0 = float(spring_energy(st.X, integ.ib.specs.springs))
    st = advance_ib(integ, st, 2e-4, 300)
    e1 = float(spring_energy(st.X, integ.ib.specs.springs))
    assert e1 < e0  # viscous dissipation drains elastic energy


def test_internal_forces_sum_to_zero():
    integ, st = build_membrane_example(
        n_cells=32, num_markers=48, stiffness=3.0, dtype=jnp.float64)
    Ftot = integ.total_marker_force(st)
    np.testing.assert_allclose(np.asarray(Ftot), [0.0, 0.0], atol=1e-12)


def test_whole_run_inside_single_jit():
    integ, st = build_membrane_example(
        n_cells=16, num_markers=32, dtype=jnp.float32)

    @jax.jit
    def run(s):
        return advance_ib(integ, s, 1e-3, 10)

    out = run(st)
    assert np.isfinite(np.asarray(out.X)).all()
    assert float(out.ins.t) == pytest.approx(0.01, rel=1e-5)


def test_forward_euler_scheme_runs():
    from ibamr_tpu.integrators.ib import IBExplicitIntegrator
    integ, st = build_membrane_example(n_cells=16, num_markers=32,
                                       dtype=jnp.float64)
    fe = IBExplicitIntegrator(integ.ins, integ.ib, scheme="forward_euler")
    out = advance_ib(fe, st, 1e-4, 20)
    assert np.isfinite(np.asarray(out.X)).all()


def test_masked_markers_stay_put():
    integ, st = build_membrane_example(n_cells=16, num_markers=32,
                                       stiffness=5.0, dtype=jnp.float64)
    mask = st.mask.at[0].set(0.0)
    st = st._replace(mask=mask)
    X0 = np.asarray(st.X[0])
    out = advance_ib(integ, st, 1e-4, 20)
    np.testing.assert_allclose(np.asarray(out.X[0]), X0, atol=1e-12)
