"""Adjoint-at-primal-cost gate (PR 19): the custom-VJP chain is a
DERIVATIVE, and it is a cheap one.

Correctness: central-difference checks at f64 (rel <= 1e-6) against the
compiled gradients of the fused spectral substep, the packed
spread/interp transfers (through the SAME buckets, overflow fallback
engaged), and the end-to-end eel2d rollout objective.

Cost: jaxpr-census pins that the substep VJP spends exactly 2x the
primal's batched FFT calls and the spread VJP adds ZERO scatter
primitives beyond the primal forward it replays (the reverse sweep is
pure gathers) — the same invariants GRAPH_BUDGETS.json ratchets via the
``grad_*`` artifacts, asserted here relationally so the claim is
self-contained.

Plumbing: ``jitted_step(donate=True)`` must REFUSE under a cotangent
trace (donation would free the primals the reverse pass replays from),
and a warm :class:`~ibamr_tpu.design.DesignLoop` iteration must be one
executable-cache HIT — zero retraces, zero recompiles.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops.interaction_packed import PackedInteraction

F64 = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
FD_EPS = 1e-6
FD_RTOL = 1e-6


def _fd_directional(f, x, v, eps=FD_EPS):
    """Central difference of scalar ``f`` at pytree ``x`` along ``v``."""
    add = lambda s: jax.tree_util.tree_map(
        lambda a, d: a + s * d, x, v)
    return (float(f(add(eps))) - float(f(add(-eps)))) / (2.0 * eps)


def _dot(g, v):
    return float(sum(jnp.vdot(a, b) for a, b in zip(
        jax.tree_util.tree_leaves(g), jax.tree_util.tree_leaves(v))))


def _rel(a, b):
    return abs(a - b) / max(abs(a), abs(b), 1e-30)


def _unit_like(x, seed):
    rng = np.random.RandomState(seed)
    leaves, treedef = jax.tree_util.tree_flatten(x)
    vs = [jnp.asarray(rng.randn(*l.shape), l.dtype) for l in leaves]
    norm = float(jnp.sqrt(sum(jnp.sum(v * v) for v in vs)))
    return jax.tree_util.tree_unflatten(
        treedef, [v / norm for v in vs])


# -- spectral substep ---------------------------------------------------------

def test_spectral_substep_vjp_matches_fd():
    from ibamr_tpu.solvers import spectral_plan

    n = 16
    grid = StaggeredGrid(n=(n, n, n), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    plan = spectral_plan.get_plan(grid.n, grid.dx, F64)
    rng = np.random.RandomState(0)
    rhs = tuple(jnp.asarray(rng.randn(*grid.n), F64) for _ in range(3))
    w_u = tuple(jnp.asarray(rng.randn(*grid.n), F64) for _ in range(3))
    w_p = jnp.asarray(rng.randn(*grid.n), F64)
    dt, rho, mu = 5e-4, 1.0, 0.05
    alpha, beta = rho / dt, -0.5 * mu

    def loss(rr):
        u, p = plan.substep(rr, alpha, beta, (alpha, beta))
        return (sum(jnp.sum(wi * ui) for wi, ui in zip(w_u, u))
                + jnp.sum(w_p * p))

    g = jax.jit(jax.grad(loss))(rhs)
    v = _unit_like(rhs, 1)
    fd = _fd_directional(jax.jit(loss), rhs, v)
    assert _rel(_dot(g, v), fd) < FD_RTOL


def test_substep_vjp_costs_exactly_two_x_primal_ffts():
    # the tentpole's cost half, relationally: the k-space solve is
    # self-adjoint, so the cotangent pass is the SAME plan — one more
    # batched forward + one more batched inverse, nothing else
    from ibamr_tpu.analysis.graph_census import fft_census
    from ibamr_tpu.solvers import spectral_plan

    n = 8
    grid = StaggeredGrid(n=(n, n, n), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    plan = spectral_plan.get_plan(grid.n, grid.dx, jnp.float32)
    rhs = tuple(jnp.zeros(grid.n, jnp.float32) for _ in range(3))
    alpha, beta = 2.0e3, -0.025

    def substep(rr):
        return plan.substep(rr, alpha, beta, (alpha, beta))

    ct = jax.tree_util.tree_map(
        lambda s: jnp.ones(s.shape, s.dtype),
        jax.eval_shape(substep, rhs))

    def substep_vjp(rr):
        val, pull = jax.vjp(substep, rr)
        return val, pull(ct)

    primal = fft_census(jax.make_jaxpr(substep)(rhs))["fft_ops"]
    vjp = fft_census(jax.make_jaxpr(substep_vjp)(rhs))["fft_ops"]
    assert primal == 2         # one batched rfftn + one batched irfftn
    assert vjp == 2 * primal


# -- packed transfers ---------------------------------------------------------

def _overflow_engine(seed=0):
    """2D engine sized so the chunk pool overflows: the VJP must be
    exact THROUGH the scatter fallback path too."""
    grid = StaggeredGrid(n=(16, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    rng = np.random.RandomState(seed)
    X = jnp.asarray(0.2 + 0.6 * rng.rand(60, 2), F64)
    eng = PackedInteraction(grid, kernel="IB_4", tile=8, chunk=8,
                            nchunks=3)
    b = eng.buckets(X)
    assert bool(b.any_overflow)   # the config exists to exercise this
    return grid, eng, b, X, rng


def test_packed_spread_vjp_matches_fd():
    # buckets recomputed INSIDE the loss: the custom VJP defines d/dX
    # as the oracle derivative of the true interaction operator (the
    # bucket pytree gets symbolic-zero cotangents), so the finite
    # difference must re-pack too — holding a stale b fixed would
    # difference a different function than the one differentiated
    grid, eng, b, X, rng = _overflow_engine()
    F = jnp.asarray(rng.randn(*X.shape), F64)
    w = tuple(jnp.asarray(rng.randn(*grid.n), F64) for _ in range(2))

    def loss(Fa, Xa):
        out = eng.spread_vel(Fa, Xa)
        return sum(jnp.sum(wi * oi) for wi, oi in zip(w, out))

    gF, gX = jax.jit(jax.grad(loss, argnums=(0, 1)))(F, X)
    vF = _unit_like(F, 1)
    fdF = _fd_directional(jax.jit(lambda Fa: loss(Fa, X)), F, vF)
    assert _rel(_dot((gF,), (vF,)), fdF) < FD_RTOL
    vX = _unit_like(X, 2)
    fdX = _fd_directional(jax.jit(lambda Xa: loss(F, Xa)), X, vX)
    assert _rel(_dot((gX,), (vX,)), fdX) < FD_RTOL


def test_packed_interp_vjp_matches_fd():
    grid, eng, b, X, rng = _overflow_engine(seed=3)
    u = tuple(jnp.asarray(rng.randn(*grid.n), F64) for _ in range(2))
    w = jnp.asarray(rng.randn(X.shape[0], 2), F64)

    def loss(ua, Xa):
        return jnp.sum(w * eng.interpolate_vel(ua, Xa))

    gu, gX = jax.jit(jax.grad(loss, argnums=(0, 1)))(u, X)
    vu = _unit_like(u, 4)
    fdu = _fd_directional(jax.jit(lambda ua: loss(ua, X)), u, vu)
    assert _rel(_dot(gu, vu), fdu) < FD_RTOL
    vX = _unit_like(X, 5)
    fdX = _fd_directional(jax.jit(lambda Xa: loss(u, Xa)), X, vX)
    assert _rel(_dot((gX,), (vX,)), fdX) < FD_RTOL


def test_spread_vjp_adds_zero_scatters_beyond_primal():
    from ibamr_tpu.analysis.graph_census import scatter_gather_census

    grid, eng, b, X, rng = _overflow_engine()
    F = jnp.asarray(rng.randn(*X.shape), F64)

    def spread(Fa, Xa):
        return eng.spread_vel(Fa, Xa, b=b)

    ct = jax.tree_util.tree_map(
        jnp.ones_like, jax.eval_shape(spread, F, X))

    def spread_vjp(Fa, Xa):
        val, pull = jax.vjp(spread, Fa, Xa)
        return val, pull(ct)

    primal = scatter_gather_census(
        jax.make_jaxpr(spread)(F, X))["scatter_prims"]
    vjp = scatter_gather_census(
        jax.make_jaxpr(spread_vjp)(F, X))["scatter_prims"]
    # the VJP graph replays the primal forward (its overflow-fallback
    # scatters included); the reverse sweep itself is pure gathers
    assert vjp == primal


# -- end-to-end rollout -------------------------------------------------------

def test_eel_objective_grad_matches_fd():
    from ibamr_tpu.design import build_eel_gait_problem

    if F64 != jnp.float64:
        pytest.skip("central-difference check needs x64")
    objective, params0 = build_eel_gait_problem(
        n=16, ns=9, num_steps=5, dtype=jnp.float64)
    obj = jax.jit(objective)
    g = jax.jit(jax.grad(objective))(params0)
    a0 = float(params0["A0"])
    eps = 1e-5

    def at(a):
        p = dict(params0)
        p["A0"] = jnp.asarray(a, jnp.float64)
        return float(obj(p))

    fd = (at(a0 + eps) - at(a0 - eps)) / (2.0 * eps)
    assert _rel(float(g["A0"]), fd) < FD_RTOL


# -- donation guard -----------------------------------------------------------

def test_donated_step_refuses_under_grad_trace():
    from ibamr_tpu.models.shell3d import build_shell_example

    integ, state = build_shell_example(n_cells=8, n_lat=6, n_lon=8,
                                       mu=0.05)
    donated = integ.jitted_step(donate=True)
    assert donated.__wrapped__ == integ.step   # contracts harness seam

    def loss(dt):
        out = donated(state, dt)
        return jnp.sum(out.ins.u[0])

    with pytest.raises(ValueError, match="donate"):
        jax.grad(loss)(jnp.asarray(0.001, state.X.dtype))

    # same request WITHOUT donation differentiates fine
    plain = integ.jitted_step(donate=False)
    g = jax.grad(lambda dt: jnp.sum(plain(state, dt).ins.u[0]))(
        jnp.asarray(0.001, state.X.dtype))
    assert np.isfinite(float(g))


# -- remat-policied driver chunks --------------------------------------------

def test_remat_driver_chunk_one_signature_and_differentiable():
    import math

    from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
    from ibamr_tpu.utils.hierarchy_driver import (HierarchyDriver,
                                                  RunConfig)

    g = StaggeredGrid(n=(16, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    integ = INSStaggeredIntegrator(g, rho=1.0, mu=0.01, dtype=F64)
    xf, yc = g.face_centers(0, F64)
    xc, yf = g.face_centers(1, F64)
    u = jnp.sin(2 * math.pi * xf) * jnp.cos(2 * math.pi * yc) + 0 * yc
    v = -jnp.cos(2 * math.pi * xc) * jnp.sin(2 * math.pi * yf) + 0 * xc
    st = integ.initialize(u0_arrays=(u, v))

    cfg = RunConfig(dt=1e-3, num_steps=30, health_interval=10,
                    remat="dots", donate=True)
    drv = HierarchyDriver(integ, cfg)
    out = drv.run(st)
    assert bool(jnp.all(jnp.isfinite(out.u[0])))
    # one trace signature per chunk length — remat must not retrace
    assert set(drv.trace_counts.values()) == {1}
    # donation FORCED OFF under remat: the pre-run state's buffers
    # survive (a donated chunk would have deleted them)
    assert bool(jnp.all(jnp.isfinite(st.u[0])))

    # the same chunk is reverse-mode differentiable (the design loop's
    # grad_chunk family); integer step counters ride as symbolic zeros
    chunk = drv._chunk(10)

    def loss(s):
        o, _ = chunk(s, 1e-3)
        return sum(jnp.sum(l) for l in jax.tree_util.tree_leaves(o)
                   if jnp.issubdtype(l.dtype, jnp.inexact))

    grads = jax.grad(loss, allow_int=True)(st)
    assert bool(jnp.all(jnp.isfinite(grads.u[0])))


# -- design loop caching ------------------------------------------------------

def _quadratic_loop(cache, label="quad"):
    from ibamr_tpu.design import DesignLoop

    target = jnp.asarray([0.3, -0.2, 0.7], F64)
    traces = []

    def objective(params):
        traces.append(1)   # python side effect: counts (re)traces
        x, _ = jax.lax.scan(lambda c, _: (0.5 * c + params["x"], None),
                            jnp.zeros_like(target), None, length=4)
        return jnp.sum((x - target) ** 2)

    loop = DesignLoop(objective, {"x": jnp.zeros(3, F64)}, lr=0.05,
                      cache=cache, label=label)
    return loop, traces


def test_design_loop_warm_iterations_hit_cache():
    from ibamr_tpu.serve.aot_cache import ExecutableCache

    cache = ExecutableCache()
    loop, traces = _quadratic_loop(cache)
    res = loop.run(4)
    objs = [it.objective for it in res.history]
    assert all(b < a for a, b in zip(objs, objs[1:]))
    assert res.history[0].cache_misses == 1
    for it in res.history[1:]:
        assert it.cache_misses == 0 and it.cache_hits == 1, (
            f"warm iteration {it.iteration} recompiled: {it}")
    # the objective traced exactly once (the single AOT lowering);
    # warm iterations call a jax.stages.Compiled — no retrace possible
    assert len(traces) == 1


def test_design_loop_second_run_is_fully_warm():
    from ibamr_tpu.serve.aot_cache import ExecutableCache

    cache = ExecutableCache()
    loop, _ = _quadratic_loop(cache)
    loop.run(2)
    # a FRESH loop over the same scenario family (same label, same
    # aval signature, same cache) never compiles — iteration 0 is warm
    loop2, traces2 = _quadratic_loop(cache)
    res2 = loop2.run(2)
    assert res2.history[0].cache_misses == 0
    assert res2.history[0].cache_hits == 1
    assert len(traces2) == 0
