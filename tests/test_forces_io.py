"""Stage-5 acceptance, part 1: force oracle vs NumPy; structure file IO
round-trips (SURVEY.md §7.2 stage 5)."""

import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.io.structures import (
    StructureData, read_structure, write_structure)
from ibamr_tpu.ops import forces


def test_spring_force_oracle():
    X = jnp.asarray([[0.0, 0.0], [2.0, 0.0], [0.0, 1.0]], dtype=jnp.float64)
    U = jnp.zeros_like(X)
    # one spring 0-1: k=3, L0=1 -> stretched by 1, force on 0 = +3 x-hat
    specs = forces.ForceSpecs(springs=forces.make_springs(
        [0], [1], [3.0], [1.0]))
    F = forces.compute_lagrangian_force(X, U, specs)
    np.testing.assert_allclose(np.asarray(F),
                               [[3.0, 0.0], [-3.0, 0.0], [0.0, 0.0]],
                               atol=1e-12)


def test_spring_newton_third_law_random():
    rng = np.random.default_rng(0)
    N, M = 20, 40
    X = jnp.asarray(rng.standard_normal((N, 3)), dtype=jnp.float64)
    specs = forces.ForceSpecs(springs=forces.make_springs(
        rng.integers(0, N, M), rng.integers(0, N, M),
        rng.uniform(0.5, 2.0, M), rng.uniform(0.1, 1.0, M)))
    F = forces.compute_lagrangian_force(X, jnp.zeros_like(X), specs)
    np.testing.assert_allclose(np.asarray(jnp.sum(F, axis=0)),
                               np.zeros(3), atol=1e-12)


def test_spring_force_is_negative_energy_gradient():
    rng = np.random.default_rng(1)
    N, M = 12, 25
    X = jnp.asarray(rng.standard_normal((N, 2)) * 2, dtype=jnp.float64)
    i0 = rng.integers(0, N, M)
    i1 = (i0 + rng.integers(1, N, M)) % N  # no self-loops (energy not
    # differentiable at zero length)
    specs = forces.ForceSpecs(springs=forces.make_springs(
        i0, i1, rng.uniform(0.5, 2.0, M), rng.uniform(0.5, 1.5, M)))
    import jax
    gradE = jax.grad(lambda x: forces.spring_energy(x, specs.springs))(X)
    F = forces.compute_lagrangian_force(X, jnp.zeros_like(X), specs)
    np.testing.assert_allclose(np.asarray(F), -np.asarray(gradE), atol=1e-10)


def test_beam_force_oracle():
    # three collinear points: no curvature -> no force; bent -> restoring
    X = jnp.asarray([[0.0, 0.0], [1.0, 0.0], [2.0, 0.0]], dtype=jnp.float64)
    specs = forces.ForceSpecs(beams=forces.make_beams([0], [1], [2], [2.0]))
    F = forces.compute_lagrangian_force(X, jnp.zeros_like(X), specs)
    np.testing.assert_allclose(np.asarray(F), np.zeros((3, 2)), atol=1e-12)

    Xb = jnp.asarray([[0.0, 0.0], [1.0, 0.5], [2.0, 0.0]], dtype=jnp.float64)
    F = forces.compute_lagrangian_force(Xb, jnp.zeros_like(Xb), specs)
    # D = X0 - 2X1 + X2 = (0, -1); c=2 -> F1 = 2cD = (0,-4); F0=F2=-cD=(0,2)
    np.testing.assert_allclose(np.asarray(F),
                               [[0.0, 2.0], [0.0, -4.0], [0.0, 2.0]],
                               atol=1e-12)
    # bending force field sums to zero (internal force)
    np.testing.assert_allclose(np.asarray(jnp.sum(F, axis=0)), [0.0, 0.0],
                               atol=1e-12)


def test_target_force_oracle():
    X = jnp.asarray([[1.0, 1.0]], dtype=jnp.float64)
    U = jnp.asarray([[0.5, 0.0]], dtype=jnp.float64)
    specs = forces.ForceSpecs(targets=forces.make_targets(
        [0], [10.0], jnp.asarray([[0.0, 1.0]]), damping=[2.0]))
    F = forces.compute_lagrangian_force(X, U, specs)
    # kappa (X0 - X) - eta U = 10*(-1,0) - 2*(0.5,0) = (-11, 0)
    np.testing.assert_allclose(np.asarray(F), [[-11.0, 0.0]], atol=1e-12)


def test_disabled_specs_masked_out():
    X = jnp.asarray([[0.0, 0.0], [2.0, 0.0]], dtype=jnp.float64)
    s = forces.make_springs([0], [1], [3.0], [1.0])
    s = s._replace(enabled=jnp.zeros_like(s.enabled))
    F = forces.compute_lagrangian_force(
        X, jnp.zeros_like(X), forces.ForceSpecs(springs=s))
    np.testing.assert_allclose(np.asarray(F), np.zeros((2, 2)), atol=1e-12)


def test_structure_file_round_trip(tmp_path):
    rng = np.random.default_rng(2)
    N = 16
    verts = rng.standard_normal((N, 2))
    springs = np.stack([np.arange(N), (np.arange(N) + 1) % N,
                        rng.uniform(1, 2, N), rng.uniform(0.1, 0.2, N)],
                       axis=1)
    beams = np.stack([(np.arange(N) - 1) % N, np.arange(N),
                      (np.arange(N) + 1) % N, rng.uniform(0.1, 1, N)], axis=1)
    targets = np.stack([np.arange(0, N, 4),
                        rng.uniform(5, 10, len(range(0, N, 4))),
                        rng.uniform(0, 1, len(range(0, N, 4)))], axis=1)
    data = StructureData(name="loop", vertices=verts, springs=springs,
                         beams=beams, targets=targets)
    base = str(tmp_path / "loop")
    write_structure(base, data)
    back = read_structure(base)
    np.testing.assert_allclose(back.vertices, verts, rtol=1e-15)
    np.testing.assert_allclose(back.springs, springs, rtol=1e-15)
    np.testing.assert_allclose(back.beams, beams, rtol=1e-15)
    np.testing.assert_allclose(back.targets, targets, rtol=1e-15)
    specs = back.force_specs()
    assert specs.springs is not None
    assert specs.beams is not None
    assert specs.targets is not None


def test_reader_validates(tmp_path):
    p = tmp_path / "bad.vertex"
    p.write_text("3\n0 0\n1 1\n")  # declares 3, provides 2
    with pytest.raises(ValueError):
        read_structure(str(tmp_path / "bad"))
    with pytest.raises(FileNotFoundError):
        read_structure(str(tmp_path / "missing"))


def test_index_offset_for_concatenated_structures():
    verts = np.zeros((4, 2))
    springs = np.array([[0, 1, 1.0, 0.1]])
    data = StructureData(name="s", vertices=verts, springs=springs,
                         index_offset=100)
    specs = data.force_specs()
    assert int(specs.springs.idx0[0]) == 100
    assert int(specs.springs.idx1[0]) == 101
