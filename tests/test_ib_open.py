"""IB coupling on open-boundary domains (round 4): flow past an
immersed cylinder in an inflow/outflow channel — the reference's
canonical external-flow IB configuration (SURVEY.md P2/P8).
"""

import jax
import jax.numpy as jnp
import numpy as np

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ib import IBMethod
from ibamr_tpu.integrators.ib_open import (IBOpenIntegrator,
                                           advance_ib_open)
from ibamr_tpu.integrators.ins_open import INSOpenIntegrator
from ibamr_tpu.solvers.stokes import channel_bc

F64 = jnp.float64


def _cylinder_markers(center, radius, n_markers):
    th = 2.0 * np.pi * np.arange(n_markers) / n_markers
    return np.stack([center[0] + radius * np.cos(th),
                     center[1] + radius * np.sin(th)], axis=1)


def _target_ib(X0, kappa, eta):
    X0j = jnp.asarray(X0, F64)

    def force(X, U, t):
        return -kappa * (X - X0j) - eta * U

    # no springs — pure target points; specs kept empty via force_fn
    from ibamr_tpu.ops.forces import ForceSpecs

    return IBMethod(ForceSpecs(), kernel="IB_4", force_fn=force)


def test_cylinder_wake_drag_re20():
    """Target-point cylinder (D = 8 dx) in a channel at Re_D = 20:
    the flow develops a wake deficit behind the body, the measured
    drag coefficient lands in the physical band for a confined
    cylinder at this Reynolds number (unbounded C_D ~ 2.0; blockage
    D/H = 0.25 raises it), the drag is statistically steady by the end
    of the run, and the markers are held near their anchors."""
    nx, ny = 64, 32
    dx = (2.0 / nx, 1.0 / ny)
    U0, D = 1.0, 0.25
    mu = U0 * D / 20.0                     # Re_D = 20
    dt = 3e-3
    ins = INSOpenIntegrator((nx, ny), dx, channel_bc(2), mu=mu, dt=dt,
                            bdry={(0, 0, 0): U0}, tol=1e-8,
                            convective_op_type="stabilized_ppm")
    X0 = _cylinder_markers((0.6, 0.5), D / 2.0, 40)
    # spring scale: spreading F multiplies by ~1/dx^2, so the coupled
    # oscillator frequency is omega^2 ~ kappa/(rho dx^2); kappa = 50
    # keeps omega*dt ~ 0.7 (stable) while holding markers to ~1e-2 D
    kappa, eta = 50.0, 1.0
    integ = IBOpenIntegrator(ins, _target_ib(X0, kappa, eta))
    st = integ.initialize(X0)

    st = advance_ib_open(integ, st, 900)
    drag_a = -float(integ.body_force_on_fluid(st)[0])
    st = advance_ib_open(integ, st, 300)
    drag_b = -float(integ.body_force_on_fluid(st)[0])

    assert bool(jnp.all(jnp.isfinite(st.fluid.u[0])))
    assert bool(jnp.all(jnp.isfinite(st.X)))

    # statistically steady drag (Re 20 is steady flow; the slow
    # marker-drift relaxation leaves a few-percent window drift)
    assert abs(drag_b - drag_a) < 0.15 * abs(drag_b), (drag_a, drag_b)
    # calibrated C_D band: unbounded cylinder at Re 20 is ~2.0; the
    # 25% blockage between NO-SLIP channel walls plus the IB_4
    # effective diameter (D + ~2dx, i.e. +25% at 8 cells/D) raise the
    # nominal-D coefficient several-fold (measured ~6.7 at this
    # config; grows toward the confined-cylinder values of the
    # blockage literature as resolution refines)
    cd = drag_b / (0.5 * 1.0 * U0 ** 2 * D)
    assert 3.0 < cd < 9.0, cd

    # wake: strong centerline deficit ~1 D behind the body (the
    # measured wake RECIRCULATES, u < 0); recovery downstream
    u = np.asarray(st.fluid.u[0])
    j = ny // 2
    i_wake = int(0.85 / dx[0])             # ~1 diameter behind
    i_far = int(1.7 / dx[0])
    assert u[i_wake, j] < 0.3 * U0, u[i_wake, j]
    assert u[i_far, j] > u[i_wake, j]
    # blockage accelerates the gap flow past the free stream
    assert u.max() > 1.3 * U0

    # the target springs hold the body (markers near anchors)
    disp = float(np.max(np.linalg.norm(np.asarray(st.X) - X0, axis=1)))
    assert disp < 0.2 * D, disp


def test_ib_open_free_structure_advects():
    """A force-free marker blob released in the channel advects
    downstream with the flow (the coupling's interp path against the
    face-complete layout is exact: uniform flow moves markers at
    exactly U0 before the blob nears the outflow)."""
    nx, ny = 32, 16
    dx = (2.0 / nx, 1.0 / ny)
    U0 = 0.5
    ins = INSOpenIntegrator((nx, ny), dx, channel_bc(2), mu=1e-12,
                            dt=0.01, bdry={(0, 0, 0): U0}, tol=1e-11,
                            convective_op_type="stabilized_ppm")
    from ibamr_tpu.ops.forces import ForceSpecs

    ib = IBMethod(ForceSpecs(), kernel="IB_4",
                  force_fn=lambda X, U, t: jnp.zeros_like(X))
    integ = IBOpenIntegrator(ins, ib)
    th = 2.0 * np.pi * np.arange(8) / 8
    X0 = np.stack([0.5 + 0.05 * np.cos(th),
                   0.5 + 0.05 * np.sin(th)], axis=1)
    # start from the developed uniform stream (plug inflow, frictionless
    # center: see test_ins_open free-stream preservation)
    st = integ.initialize(jnp.asarray(X0, F64))
    for _ in range(20):                    # develop the stream first
        st = st._replace(fluid=ins.step(st.fluid))
    T = 40
    x_start = float(jnp.mean(st.X[:, 0]))
    st = advance_ib_open(integ, st, T)
    adv = float(jnp.mean(st.X[:, 0])) - x_start
    # the CENTER of the channel carries ~U0 (free stream); the blob
    # spans a few cells so allow a finite band
    assert 0.6 * U0 * T * 0.01 < adv < 1.4 * U0 * T * 0.01, adv


def test_ib_open_3d_sphere_smoke():
    """3D external flow: a target-point SPHERE in an inflow/outflow
    duct — the coupling's layout bridge and drag sign in 3D."""
    n = (24, 12, 12)
    dx = (2.0 / 24, 1.0 / 12, 1.0 / 12)
    U0 = 1.0
    # dt note: the 3D spread/interp overlap factor (IB_4 delta^2 sums
    # over ~4 markers per stencil at this surface density) makes the
    # explicit coupling's effective damping rate ~200/s; dt = 1e-3
    # keeps dt*rate ~ 0.2 (4e-3 was observed marginally unstable)
    ins = INSOpenIntegrator(n, dx, channel_bc(3), mu=0.02, dt=1e-3,
                            bdry={(0, 0, 0): U0}, tol=1e-6,
                            convective_op_type="stabilized_ppm")
    from ibamr_tpu.integrators.cib import make_sphere
    from ibamr_tpu.ops.forces import ForceSpecs

    X0 = jnp.asarray(np.asarray(
        make_sphere((0.7, 0.5, 0.5), 0.15, 8, 12)), F64)
    # 3D spread scales ~1/dx^3, so the coupled spring frequency at
    # kappa=40 already grazes the explicit limit; kappa=10 is stable
    # and still holds the sphere to ~1e-2
    ib = IBMethod(ForceSpecs(), kernel="IB_4",
                  force_fn=lambda X, U, t: -10.0 * (X - X0) - 0.5 * U)
    integ = IBOpenIntegrator(ins, ib)
    st = integ.initialize(X0)
    st = advance_ib_open(integ, st, 150)
    assert bool(jnp.all(jnp.isfinite(st.fluid.u[0])))
    assert bool(jnp.all(jnp.isfinite(st.X)))
    drag = -float(integ.body_force_on_fluid(st)[0])
    assert drag > 0.0, drag
    # markers held near anchors
    disp = float(jnp.max(jnp.linalg.norm(st.X - X0, axis=1)))
    assert disp < 0.1, disp


def test_shedding_cylinder_adaptive_dt():
    """Vortex-shedding cylinder under CFL-ADAPTIVE dt (VERDICT round 4
    item 6): alpha = rho/dt no longer baked into the saddle solve, so
    the hierarchy_driver CFL loop drives the ib_open family. Pins:

    - the CFL bound actually bites (observed dt < cfg.dt cap, and more
      than one distinct dt over the run — adaptivity, not a constant);
    - at Re_D = 100 the near-wake transverse flow is active (lift
      fluctuates: the F_net[1] history changes sign after transients —
      shedding onset, impossible in the steady Re=20 configuration);
    - the flow stays finite and divergence stays at solver tolerance
      through every dt change.
    """
    from ibamr_tpu.utils.hierarchy_driver import HierarchyDriver, RunConfig

    nx, ny = 64, 32
    dx = (2.0 / nx, 1.0 / ny)
    U0, D = 1.0, 0.25
    mu = U0 * D / 100.0                    # Re_D = 100: unsteady wake
    dt_cap = 6e-3
    ins = INSOpenIntegrator((nx, ny), dx, channel_bc(2), mu=mu,
                            dt=dt_cap,
                            bdry={(0, 0, 0): U0}, tol=1e-8,
                            convective_op_type="stabilized_ppm")
    # off-center body seeds the asymmetric mode early
    X0 = _cylinder_markers((0.6, 0.47), D / 2.0, 40)
    integ = IBOpenIntegrator(ins, _target_ib(X0, 50.0, 1.0))
    st = integ.initialize(X0)

    lifts, dts = [], []

    def metrics(s, k):
        lifts.append(float(s.F_net[1]))
        dts.append(float(s.fluid.t))
        return {}

    drv = HierarchyDriver(
        integ, RunConfig(dt=dt_cap, num_steps=1500, health_interval=5,
                         cfl=0.3),
        metrics_fn=metrics)
    out = drv.run(st)

    assert bool(jnp.all(jnp.isfinite(out.fluid.u[0])))
    assert bool(jnp.all(jnp.isfinite(out.X)))
    assert float(ins.max_divergence(out.fluid)) < 1e-6

    chunk_dt = np.diff([0.0] + dts) / 5.0      # per-step dt per chunk
    # the developed flow (blockage accelerates past U0) pulls the CFL
    # bound below the cap, and the bound moves as the wake evolves
    assert chunk_dt.min() < dt_cap - 1e-9
    assert len({round(v, 12) for v in chunk_dt}) > 3   # dt adapted
    # shedding onset: the second-half lift history crosses zero
    late = np.asarray(lifts[len(lifts) // 2:])
    late = late - late.mean()
    crossings = int(np.sum(np.abs(np.diff(np.sign(late))) > 0))
    assert crossings >= 2, f"no lift oscillation: {late[:8]}..."
