"""Oracle tests for the P22 physics modules (VERDICT round 1 item 5).

- physics.level_set: reinitialization drives |grad phi| -> 1 without
  moving the zero level; fast-sweeping distances match the analytic
  circle distance; Zalesak's slotted disk survives a full rotation.
- integrators.ins_vc: the variable-density projection produces a
  discretely divergence-free field; a heavy drop falls under gravity
  while conserving phase volume and mirror symmetry.
- physics.complex_fluids: Oldroyd-B equilibrium is a fixed point; the
  steady simple-shear conformation matches the analytic solution;
  the polymer-stress divergence converges to the analytic divergence.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops.godunov import advect
from ibamr_tpu.physics import level_set as ls
from ibamr_tpu.physics.complex_fluids import (
    OldroydB, identity_conformation, oldroyd_b_source, pack,
    polymer_stress, stress_divergence_mac, unpack)


def _circle_phi(n, R=0.3, cx=0.5, cy=0.5, dtype=jnp.float64):
    c = (jnp.arange(n) + 0.5) / n
    X, Y = jnp.meshgrid(c, c, indexing="ij")
    return (jnp.sqrt((X - cx) ** 2 + (Y - cy) ** 2) - R).astype(dtype)


# --------------------------------------------------------------------------
# level set
# --------------------------------------------------------------------------

def test_reinitialize_gradient_norm_and_zero_level():
    """A distorted (non-distance) level set with the right zero level is
    relaxed to |grad phi| ~ 1 near the interface, and the interface
    (measured by the smoothed phase volume) does not drift."""
    n = 64
    dx = (1.0 / n, 1.0 / n)
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    phi_d = _circle_phi(n)
    # distortion: same zero level, |grad| between ~0.6 and ~3
    phi = phi_d * (1.0 + 2.0 * phi_d ** 2) * jnp.exp(0.5 * phi_d)
    eps = 1.5 / n
    vol0 = float(ls.phase_volume(phi_d, g, eps))

    out = ls.reinitialize(phi, dx, iters=80)
    band = jnp.abs(phi_d) < 0.12
    gn = ls.gradient_norm(out, dx)
    err = float(jnp.max(jnp.abs(jnp.where(band, gn, 1.0) - 1.0)))
    assert err < 0.12, err
    vol1 = float(ls.phase_volume(out, g, eps))
    assert abs(vol1 - vol0) / vol0 < 0.01, (vol0, vol1)


def test_fast_sweeping_matches_circle_distance():
    n = 64
    dx = (1.0 / n, 1.0 / n)
    phi0 = _circle_phi(n)
    # destroy far-field magnitudes, keep the zero level
    phi = jnp.tanh(8.0 * phi0) * 0.05
    d = ls.fast_sweeping_distance(phi, dx)
    # compare where the exact distance is the circle distance (inside
    # the periodic box, away from the wrap seam)
    c = (np.arange(n) + 0.5) / n
    X, Y = np.meshgrid(c, c, indexing="ij")
    exact = np.sqrt((X - 0.5) ** 2 + (Y - 0.5) ** 2) - 0.3
    mask = np.abs(exact) < 0.15
    err = np.max(np.abs(np.asarray(d) - exact)[mask])
    assert err < 2.5 / n, err


def test_zalesak_disk_full_rotation():
    """Rigid-rotate the slotted disk once around the domain center with
    the CTU Godunov advector: area conserved to roundoff (flux form)
    and shape error (misclassified area fraction) bounded."""
    n = 100
    dx = (1.0 / n, 1.0 / n)
    c = (jnp.arange(n) + 0.5) / n
    X, Y = jnp.meshgrid(c, c, indexing="ij")
    R, cx, cy, w, htop = 0.15, 0.5, 0.75, 0.05, 0.85
    disk = (jnp.sqrt((X - cx) ** 2 + (Y - cy) ** 2) < R)
    slot = (jnp.abs(X - cx) < w / 2) & (Y < htop)
    ind0 = jnp.where(disk & ~slot, 1.0, 0.0).astype(jnp.float64)

    # MAC rotation field about (0.5, 0.5), one revolution in T = 2 pi
    xf = jnp.arange(n) / n
    Xu, Yu = jnp.meshgrid(xf, c, indexing="ij")
    Xv, Yv = jnp.meshgrid(c, xf, indexing="ij")
    u = (-(Yu - 0.5), (Xv - 0.5))

    T = 2.0 * math.pi
    steps = 1600
    dt = T / steps

    def body(q, _):
        return advect(q, u, dx, dt), None

    out, _ = jax.lax.scan(body, ind0, None, length=steps)
    # conservative flux form: total "mass" exact to roundoff
    np.testing.assert_allclose(float(jnp.sum(out)), float(jnp.sum(ind0)),
                               rtol=1e-12)
    # shape: misclassified fraction (vs initial) after one revolution
    mis = float(jnp.sum(jnp.abs((out > 0.5).astype(jnp.float64)
                                - (ind0 > 0.5).astype(jnp.float64))))
    area = float(jnp.sum(ind0 > 0.5))
    # PLM/CTU at 100^2 keeps the slot; ~19% boundary-cell churn is the
    # measured scheme behavior (1st-order upwind would exceed 50%)
    assert mis / area < 0.25, mis / area


# --------------------------------------------------------------------------
# variable-coefficient (multiphase) INS
# --------------------------------------------------------------------------

def _vc_integ(n, **kw):
    from ibamr_tpu.integrators.ins_vc import INSVCStaggeredIntegrator

    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    kw.setdefault("dtype", jnp.float64)
    return g, INSVCStaggeredIntegrator(g, **kw)


def test_project_vc_divergence_free():
    n = 32
    g, integ = _vc_integ(n, rho0=1.0, rho1=10.0)
    rng = np.random.default_rng(5)
    u = tuple(jnp.asarray(rng.standard_normal(g.n)) for _ in range(2))
    phi = _circle_phi(n)
    rho = integ.density(phi)
    u_new, _ = integ.project_vc(u, rho, dt=1e-2)
    from ibamr_tpu.ops import stencils
    div0 = float(jnp.max(jnp.abs(stencils.divergence(u, g.dx))))
    div = float(jnp.max(jnp.abs(stencils.divergence(u_new, g.dx))))
    # reduced by the CG relative tolerance (1e-8) modulo norm slack
    assert div < 1e-6 * div0, (div, div0)


def test_falling_drop_volume_and_symmetry():
    """Heavy drop (phi<0 inside, rho0 heavy) in a light ambient under
    downward gravity: the drop's center of mass must fall, its smoothed
    volume must be conserved to ~1%, and x-mirror symmetry preserved."""
    from ibamr_tpu.integrators.ins_vc import advance_vc

    n = 48
    g, integ = _vc_integ(n, rho0=5.0, rho1=1.0, mu0=0.05, mu1=0.02,
                         gravity=(0.0, -5.0), reinit_interval=10)
    phi = _circle_phi(n, R=0.2, cx=0.5, cy=0.65)
    st = integ.initialize(phi)
    vol0 = float(integ.heavy_phase_volume(st))

    def com_y(phi):
        w = 1.0 - ls.heaviside(phi, integ.eps)
        c = (jnp.arange(n) + 0.5) / n
        _, Y = jnp.meshgrid(c, c, indexing="ij")
        return float(jnp.sum(w * Y) / jnp.sum(w))

    y0 = com_y(st.phi)
    st = advance_vc(integ, st, 2e-3, 150)
    assert bool(jnp.all(jnp.isfinite(st.u[0])))
    y1 = com_y(st.phi)
    assert y1 < y0 - 0.01, (y0, y1)          # it fell
    vol1 = float(integ.heavy_phase_volume(st))
    assert abs(vol1 - vol0) / vol0 < 0.015, (vol0, vol1)
    # mirror symmetry about x = 0.5: phi field symmetric under x-flip
    phi_np = np.asarray(st.phi)
    np.testing.assert_allclose(phi_np, phi_np[::-1, :], atol=1e-8)
    assert float(integ.max_divergence(st)) < 1e-6


# --------------------------------------------------------------------------
# complex fluids (Oldroyd-B)
# --------------------------------------------------------------------------

def test_oldroyd_b_equilibrium_fixed_point():
    g = StaggeredGrid(n=(16, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    ob = OldroydB(g, mu_p=0.5, lam=1.0, dtype=jnp.float64)
    C = ob.initialize()
    u = tuple(jnp.zeros(g.n, dtype=jnp.float64) for _ in range(2))
    C1 = ob.step(C, u, 0.05)
    np.testing.assert_allclose(np.asarray(C1), np.asarray(C), atol=1e-14)
    f = ob.body_force(C1)
    for c in f:
        np.testing.assert_allclose(np.asarray(c), 0.0, atol=1e-14)


def test_oldroyd_b_steady_shear_analytic():
    """ODE limit (homogeneous C, prescribed grad u): steady simple shear
    u = (gd*y, 0) has C_xx = 1 + 2 (lam gd)^2, C_xy = lam gd, C_yy = 1."""
    lam, gd = 0.8, 1.3
    gu = jnp.zeros((1, 1, 2, 2), dtype=jnp.float64)
    gu = gu.at[..., 0, 1].set(gd)           # du_x/dy
    C = pack(jnp.broadcast_to(jnp.eye(2), (1, 1, 2, 2))).astype(jnp.float64)
    dt = 0.01
    for _ in range(4000):                   # t = 40 = 50 lambda
        C = C + dt * oldroyd_b_source(C, gu, lam)
    Cf = unpack(C, 2)[0, 0]
    np.testing.assert_allclose(float(Cf[0, 0]), 1.0 + 2.0 * (lam * gd) ** 2,
                               rtol=1e-6)
    np.testing.assert_allclose(float(Cf[0, 1]), lam * gd, rtol=1e-6)
    np.testing.assert_allclose(float(Cf[1, 1]), 1.0, rtol=1e-6)


@pytest.mark.parametrize("n", [32, 64])
def test_polymer_stress_divergence_accuracy(n):
    """tau_xx = sin(2 pi x) (others 0): f_x = 2 pi cos(2 pi x) at
    x-faces; the discrete divergence must converge at 2nd order."""
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    c = (jnp.arange(n, dtype=jnp.float64) + 0.5) / n
    X, _ = jnp.meshgrid(c, c, indexing="ij")
    tau = jnp.zeros(g.n + (3,), dtype=jnp.float64)
    tau = tau.at[..., 0].set(jnp.sin(2.0 * math.pi * X))
    f = stress_divergence_mac(tau, g)
    xf = jnp.arange(n, dtype=jnp.float64) / n
    Xf, _ = jnp.meshgrid(xf, c, indexing="ij")
    exact = 2.0 * math.pi * jnp.cos(2.0 * math.pi * Xf)
    # backward difference of cell sin to faces is 2nd order (centered
    # about the face)
    err = float(jnp.max(jnp.abs(f[0] - exact)))
    assert err < 30.0 / n ** 2, err


def test_polymer_stress_identity():
    C = identity_conformation(
        StaggeredGrid(n=(8, 8), x_lo=(0.0, 0.0), x_up=(1.0, 1.0)),
        dtype=jnp.float64)
    tau = polymer_stress(C, mu_p=1.0, lam=2.0, dim=2)
    np.testing.assert_allclose(np.asarray(tau), 0.0, atol=1e-15)


def test_vc_projection_mg_preconditioner_ratio_robust():
    """The VC-multigrid preconditioner keeps CG iteration counts
    ratio-robust (the FAC promise): at density ratio 1000 the FFT
    preconditioner needs O(ratio) iterations while one VC V-cycle
    holds them near-constant. Both must produce the same projection."""
    import numpy as np

    from ibamr_tpu.integrators.ins_vc import INSVCStaggeredIntegrator
    from ibamr_tpu.ops import stencils
    from ibamr_tpu.solvers import krylov

    n = 32
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    x = (np.arange(n) + 0.5) / n
    X, Y = np.meshgrid(x, x, indexing="ij")
    phi = jnp.asarray(0.15 - np.sqrt((X - 0.5) ** 2 + (Y - 0.6) ** 2))
    rng = np.random.default_rng(0)
    u = tuple(jnp.asarray(rng.standard_normal(g.n)) * 0.1
              for _ in range(2))

    orig = krylov.cg
    iters = {}
    sols = {}
    for pc in ("fft", "mg"):
        integ = INSVCStaggeredIntegrator(
            g, rho0=1.0, rho1=1000.0, mu0=0.01, mu1=0.01,
            cg_tol=1e-9, cg_maxiter=400, precond=pc,
            dtype=jnp.float64)
        rho_cc = integ.density(phi)
        cap = {}

        def spy(A, b, **kw):
            r = orig(A, b, **kw)
            cap["it"] = int(r.iters)
            return r

        krylov.cg = spy
        try:
            u2, p = integ.project_vc(u, rho_cc, 1e-3)
        finally:
            krylov.cg = orig
        iters[pc] = cap["it"]
        sols[pc] = u2
        assert float(jnp.max(jnp.abs(
            stencils.divergence(u2, g.dx)))) < 1e-7

    assert iters["mg"] <= 20
    assert iters["mg"] * 4 < iters["fft"]
    for a, b in zip(sols["fft"], sols["mg"]):
        assert np.max(np.abs(np.asarray(a - b))) < 1e-7


def test_hydrostatic_balance_no_spurious_currents():
    """A flat heavy-over-nothing pool under gravity must stay
    quiescent: gravity enters as the uniform acceleration g and the
    harmonic-coefficient projection absorbs it into a discrete
    hydrostatic pressure exactly (regression: building rho*g with
    arithmetic faces and dividing by harmonic faces scaled gravity
    O(ratio) wrong at interface faces, driving spurious currents)."""
    import numpy as np

    from ibamr_tpu.integrators.ins_vc import (INSVCStaggeredIntegrator,
                                              advance_vc)

    n = 32
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    y = (np.arange(n) + 0.5) / n
    # heavy phase (phi > 0) fills the bottom half
    phi0 = jnp.asarray(np.broadcast_to((0.5 - y)[None, :], (n, n)),
                       dtype=jnp.float64)
    integ = INSVCStaggeredIntegrator(
        g, rho0=1.0, rho1=100.0, mu0=0.01, mu1=0.01,
        gravity=(0.0, -1.0), sigma=0.0, convective_op_type="none",
        reinit_interval=1000, cg_tol=1e-11, dtype=jnp.float64)
    st = integ.initialize(phi0)
    st = advance_vc(integ, st, 1e-3, 20)
    # the density-anomaly gravity force injects zero net momentum and
    # is a discrete y-gradient for a flat pool, so the projection
    # absorbs it EXACTLY: full quiescence, no free-fall drift
    umax = max(float(jnp.max(jnp.abs(c))) for c in st.u)
    assert umax < 1e-10, umax


def test_drop_buoyancy_relative_motion():
    """A heavy drop under the anomaly-form gravity sinks RELATIVE to
    the ambient while total momentum stays zero (periodic buoyancy)."""
    import numpy as np

    from ibamr_tpu.integrators.ins_vc import (INSVCStaggeredIntegrator,
                                              advance_vc)

    n = 32
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    x = (np.arange(n) + 0.5) / n
    X, Y = np.meshgrid(x, x, indexing="ij")
    phi0 = jnp.asarray(0.12 - np.sqrt((X - 0.5) ** 2 + (Y - 0.6) ** 2),
                       dtype=jnp.float64)
    integ = INSVCStaggeredIntegrator(
        g, rho0=1.0, rho1=100.0, mu0=0.02, mu1=0.05,
        gravity=(0.0, -1.0), cg_tol=1e-9, dtype=jnp.float64)
    st = integ.initialize(phi0)
    st = advance_vc(integ, st, 2e-4, 100)
    v = np.asarray(st.u[1])
    H = np.asarray(st.phi) > 0
    vmean = v.mean()
    # relative buoyancy: drop sinks, ambient recirculates up (the
    # VELOCITY mean is not conserved by the non-conservative VC form —
    # acceleration = force * 1/rho correlates sign with 1/rho — so the
    # oracle is motion RELATIVE to the mean; the conservative-form
    # variant is the documented trade, module docstring)
    assert v[H].mean() - vmean < -1e-4      # drop sinks
    assert v[~H].mean() - vmean > 1e-6      # ambient rises


def test_oldroyd_b_walled_channel_normal_stress():
    """Wall-bounded VISCOELASTIC channel (round 4): Oldroyd-B coupled
    to the walled VC momentum step in a body-force-driven channel.
    The steady viscometric signatures must appear with the right
    signs and symmetry: C_xy follows the shear (positive near the
    lower wall, negative near the upper), the first normal-stress
    difference N1 = C_xx - C_yy is positive in the sheared wall
    layers and ~0 at the centerline, conformation stays positive
    (trace >= dim at equilibrium scale), and the wall-normal faces
    stay pinned."""
    from ibamr_tpu.integrators.ins_vc import INSVCStaggeredIntegrator
    from ibamr_tpu.physics.complex_fluids import OldroydB, unpack

    n = 32
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    vc = INSVCStaggeredIntegrator(
        g, rho0=1.0, rho1=1.0, mu0=0.05, mu1=0.05,
        convective_op_type="none", reinit_interval=10 ** 9,
        cg_tol=1e-10, wall_axes=(False, True), dtype=jnp.float64)
    ob = OldroydB(g, mu_p=0.02, lam=0.2, wall_axes=(False, True),
                  dtype=jnp.float64)
    st = vc.initialize(jnp.ones((n, n), dtype=jnp.float64))
    C = ob.initialize()
    fx = 0.5
    drive = (jnp.full((n, n), fx, dtype=jnp.float64),
             jnp.zeros((n, n), dtype=jnp.float64))
    dt = 1e-3

    @jax.jit
    def one(st, C):
        f = ob.body_force(C)
        f = (f[0] + drive[0], f[1] + drive[1])
        st2 = vc.step(st, dt, f=f)
        return st2, ob.step(C, st2.u, dt)

    for _ in range(400):
        st, C = one(st, C)

    assert bool(jnp.all(jnp.isfinite(st.u[0])))
    assert bool(jnp.all(jnp.isfinite(C)))
    assert float(jnp.max(jnp.abs(st.u[1][:, 0:1]))) == 0.0

    Cf = np.asarray(unpack(C, 2))
    prof_xy = Cf[..., 0, 1].mean(axis=0)     # C_xy(y)
    N1 = (Cf[..., 0, 0] - Cf[..., 1, 1]).mean(axis=0)
    # shear sign: du_x/dy > 0 in the lower half -> C_xy = lam*gd > 0
    assert prof_xy[1] > 1e-4, prof_xy[1]
    assert prof_xy[-2] < -1e-4, prof_xy[-2]
    # antisymmetric about the centerline (channel symmetry)
    np.testing.assert_allclose(prof_xy[1], -prof_xy[-2], rtol=0.05)
    # N1 positive in the wall layers, ~0 at the centerline
    assert N1[1] > 5.0 * abs(N1[n // 2]), (N1[1], N1[n // 2])
    assert N1[-2] > 5.0 * abs(N1[n // 2])
    # conformation positivity proxy
    tr = Cf[..., 0, 0] + Cf[..., 1, 1]
    assert float(tr.min()) > 1.5, float(tr.min())


def test_fast_sweeping_grid_independent_and_beats_pde_iterations():
    """VERDICT round 4 item 9 pins: (a) the directional-sweep solver
    reaches O(h) accuracy with the DEFAULT sweep count at every grid
    size (4 rounds of 2*dim passes = 16 scans, an order of magnitude
    below the O(n) pseudo-time iterations the relaxation PDE needs to
    carry distance information n cells); (b) the two agree in the
    interface neighborhood."""
    for n in (32, 64, 128):
        dx = (1.0 / n, 1.0 / n)
        c = (np.arange(n) + 0.5) / n
        X, Y = np.meshgrid(c, c, indexing="ij")
        exact = np.sqrt((X - 0.5) ** 2 + (Y - 0.5) ** 2) - 0.3
        phi = jnp.tanh(8.0 * jnp.asarray(exact)) * 0.05

        d_fs = ls.fast_sweeping_distance(phi, dx)
        mask = np.abs(exact) < 0.15
        err_fs = np.max(np.abs(np.asarray(d_fs) - exact)[mask])
        # same sweeps at every n: accuracy must not degrade with n
        assert err_fs < 2.5 / n, (n, err_fs)

        # the relaxation PDE with the same total number of whole-grid
        # passes (16) has NOT converged away from the band (information
        # moves one cell per pseudo-step); at n cells it needs O(n)
        it_pde = 16
        d_pde = ls.reinitialize(phi, dx, iters=it_pde)
        far = np.abs(exact) > 0.25 * 1.0
        err_pde = np.max(np.abs(np.asarray(d_pde) - exact)[far])
        assert err_pde > 5.0 * err_fs, (err_pde, err_fs)

    # (b) steady-state agreement: a converged PDE reinit and the
    # sweeping solver agree where both are valid (near band, away
    # from the periodic wrap seam)
    n = 64
    dx = (1.0 / n, 1.0 / n)
    c = (np.arange(n) + 0.5) / n
    X, Y = np.meshgrid(c, c, indexing="ij")
    exact = np.sqrt((X - 0.5) ** 2 + (Y - 0.5) ** 2) - 0.3
    phi = jnp.tanh(8.0 * jnp.asarray(exact)) * 0.05
    d_fs = ls.fast_sweeping_distance(phi, dx)
    d_pde = ls.reinitialize(phi, dx, iters=400)
    mask = np.abs(exact) < 0.12
    gap = np.max(np.abs(np.asarray(d_fs) - np.asarray(d_pde))[mask])
    assert gap < 3.0 / n, gap


def test_fast_sweeping_3d_sphere():
    """3D branch of the Eikonal solve: sphere distance recovered from a
    magnitude-destroyed level set."""
    n = 32
    dx = (1.0 / n,) * 3
    c = (np.arange(n) + 0.5) / n
    X, Y, Z = np.meshgrid(c, c, c, indexing="ij")
    exact = np.sqrt((X - 0.5) ** 2 + (Y - 0.5) ** 2
                    + (Z - 0.5) ** 2) - 0.3
    phi = jnp.tanh(8.0 * jnp.asarray(exact)) * 0.05
    d = ls.fast_sweeping_distance(phi, dx)
    mask = np.abs(exact) < 0.12
    err = np.max(np.abs(np.asarray(d) - exact)[mask])
    assert err < 3.0 / n, err


def test_fast_sweeping_wall_axes_no_tunnel():
    """Wall-bounded sweeping (parity with reinitialize's wall_axes):
    a flat pool surface near the domain bottom, walls on the y axis.
    Without the wall flag the periodic wrap would see the phase jump
    across the top/bottom boundary and tunnel small distances through;
    with it, the distance grows monotonically to the top and matches
    the exact |y - y0| distance."""
    n = 64
    dx = (1.0 / n, 1.0 / n)
    c = (np.arange(n) + 0.5) / n
    _, Y = np.meshgrid(c, c, indexing="ij")
    y0 = 0.25
    exact = Y - y0                       # flat interface at y = 0.25
    phi = jnp.tanh(10.0 * jnp.asarray(exact)) * 0.03
    d_wall = ls.fast_sweeping_distance(phi, dx,
                                       wall_axes=(False, True))
    err = np.max(np.abs(np.asarray(d_wall) - exact))
    assert err < 2.5 / n, err
    # the periodic solver on the same data DOES wrap (control: the
    # wall flag is load-bearing) — near the top boundary the wrapped
    # distance is ~the distance through the floor, much smaller
    d_per = ls.fast_sweeping_distance(phi, dx)
    top_err = np.max(np.abs(np.asarray(d_per) - exact)[:, -4:])
    assert top_err > 10.0 / n, top_err
