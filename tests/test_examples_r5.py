"""Round-5 example drivers (VERDICT item 10: broaden the acceptance
surface the way the reference's examples do): flapping filament and
oscillating-cylinder CIB, run short via their own main() with reduced
input files, metrics pinned."""

import importlib.util
import json
import os
import sys

import numpy as np


def _load_main(path):
    spec = importlib.util.spec_from_file_location(
        "example_" + os.path.basename(os.path.dirname(path)), path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_filament_example_short(tmp_path):
    """Short filament run: stays finite, the near-inextensible fiber
    conserves its length to ~1%, and drag sweeps the tail downstream
    of the anchor (the pre-flapping transient every parameter set
    shares)."""
    inp = tmp_path / "input2d"
    inp.write_text("""
Main {
   viz_dump_interval = 0
   log_jsonl = "%s"
}
CartesianGeometry {
   n = 64, 32
   x_lo = 0.0, 0.0
   x_up = 4.0, 2.0
}
INSOpenIntegrator {
   mu = 0.01
   rho = 1.0
   dt = 4.0e-3
   U0 = 1.0
   num_steps = 150
   convective_op_type = "stabilized_ppm"
   tol = 1.0e-6
}
Filament {
   anchor = 0.8, 1.0
   length = 0.8
   n_markers = 24
   k_stretch = 200.0
   k_bend = 1.0e-4
   k_anchor = 200.0
   incline = 0.05
}
""" % (tmp_path / "m.jsonl"))
    mod = _load_main(os.path.join(
        REPO, "examples", "IB", "explicit", "filament2d", "main.py"))
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        mod.main(["main.py", str(inp)])
    finally:
        os.chdir(cwd)
    recs = [json.loads(ln) for ln in
            open(tmp_path / "m.jsonl").read().splitlines()]
    assert recs, "no metrics written"
    last = recs[-1]
    assert np.isfinite(last["tail_x"]) and np.isfinite(last["tail_y"])
    # drag sweeps the tail downstream of the anchor
    assert last["tail_x"] > 0.8 + 0.5 * 0.8, last
    assert last["drag"] > 0.0


def test_filament_length_conservation(tmp_path):
    """The spring backbone holds the fiber near-inextensible through
    the transient (length drift ~ U^2/k scale, pinned < 2%)."""
    sys.path.insert(0, REPO)
    import jax.numpy as jnp

    from examples.IB.explicit.filament2d.main import build_filament
    from ibamr_tpu.integrators.ib import IBMethod
    from ibamr_tpu.integrators.ib_open import (IBOpenIntegrator,
                                               advance_ib_open)
    from ibamr_tpu.integrators.ins_open import INSOpenIntegrator
    from ibamr_tpu.solvers.stokes import channel_bc
    from ibamr_tpu.utils.input_db import parse_input_string

    fil = parse_input_string("""
Filament {
   anchor = 0.8, 1.0
   length = 0.8
   n_markers = 24
   k_stretch = 400.0
   k_bend = 1.0e-4
   k_anchor = 400.0
   incline = 0.05
}
""").get_database("Filament")
    X0, specs = build_filament(fil, dtype=jnp.float64)
    ins = INSOpenIntegrator((64, 32), (4.0 / 64, 2.0 / 32),
                            channel_bc(2), mu=0.01, dt=2e-3,
                            bdry={(0, 0, 0): 1.0}, tol=1e-8)
    integ = IBOpenIntegrator(ins, IBMethod(specs, kernel="IB_4"))
    st = integ.initialize(X0)
    st = advance_ib_open(integ, st, 300)

    def length(X):
        X = np.asarray(X)
        return float(np.sum(np.linalg.norm(np.diff(X, axis=0),
                                           axis=1)))

    L0, L1 = length(X0), length(st.X)
    # steady elastic elongation under the drag tension scales
    # ~ rho U^2 / k; measured 4.0% at k_stretch = 400 (and the sign
    # is physical: TENSION, the fiber trails downstream)
    assert 0.0 < (L1 - L0) / L0 < 0.05, (L0, L1)


def test_oscillating_cylinder_example(tmp_path):
    """Quasi-static Stokes linearity: the prescribed-motion force
    tracks the velocity with a CONSTANT effective resistance across
    the cycle (R_eff spread < 2%), zero transverse force by symmetry,
    and every constraint solve converges."""
    inp = tmp_path / "input2d"
    inp.write_text("""
Main {
   log_jsonl = "%s"
}
CartesianGeometry {
   n_cells = 48, 48
   x_lo = 0.0, 0.0
   x_up = 1.0, 1.0
}
CIBMethod {
   mu = 1.0
   cg_tol = 1.0e-8
   cg_maxiter = 300
   domain = "walled"
}
Body {
   center = 0.5, 0.5
   radius = 0.12
   n_markers = 24
}
Oscillation {
   V0 = 1.0
   period = 1.0
   num_periods = 1
   steps_per_period = 8
}
""" % (tmp_path / "m.jsonl"))
    mod = _load_main(os.path.join(
        REPO, "examples", "CIB", "oscillating_cylinder", "main.py"))
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        mod.main(["main.py", str(inp)])
    finally:
        os.chdir(cwd)
    recs = [json.loads(ln) for ln in
            open(tmp_path / "m.jsonl").read().splitlines()]
    assert len(recs) == 8
    assert all(r["converged"] for r in recs)
    # quasi-static Stokes linearity is POSITION-wise: records at the
    # same |offset| share one R_eff to roundoff, and R_eff GROWS with
    # wall proximity (the disc sweeps toward the walls at the phase
    # extremes — the lubrication trend the walled domain adds)
    import collections
    groups = collections.defaultdict(list)
    for r in recs:
        if np.isfinite(r["R_eff"]) and abs(r["u"]) > 0.3:
            amp = 1.0 / (2.0 * np.pi)
            off = abs(amp * np.sin(2.0 * np.pi * r["t"]))
            groups[round(off, 6)].append(r["R_eff"])
    assert len(groups) >= 2
    for off, vals in groups.items():
        assert np.std(vals) / np.mean(vals) < 1e-6, (off, vals)
    offs = sorted(groups)
    assert np.mean(groups[offs[-1]]) > np.mean(groups[offs[0]]), groups
    assert max(abs(r["fy"]) for r in recs) < 0.05 * max(
        abs(r["fx"]) for r in recs)


def test_dam_break_example_short(tmp_path):
    """Short dam-break run: the surge front advances monotonically
    along the floor past the initial column width, the heavy phase
    conserves volume to <1%, and the projection keeps divergence at
    solver tolerance."""
    inp = tmp_path / "input2d"
    inp.write_text("""
Main {
   viz_dump_interval = 0
   log_interval = 20
   log_jsonl = "%s"
}
CartesianGeometry {
   n = 64, 48
   x_lo = 0.0, 0.0
   x_up = 1.0, 0.75
}
INSVCStaggeredHierarchyIntegrator {
   rho0 = 1.0
   rho1 = 1000.0
   mu0 = 1.8e-4
   mu1 = 1.0e-2
   sigma = 0.0
   gravity_y = -9.81
   column_width = 0.25
   column_height = 0.5
   dt = 1.0e-3
   num_steps = 120
   cg_tol = 1.0e-5
}
""" % (tmp_path / "m.jsonl"))
    mod = _load_main(os.path.join(
        REPO, "examples", "multiphase", "dam_break", "main.py"))
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        mod.main(["main.py", str(inp)])
    finally:
        os.chdir(cwd)
    recs = [json.loads(ln) for ln in
            open(tmp_path / "m.jsonl").read().splitlines()]
    assert recs, "no metrics written"
    fronts = [r["front"] for r in recs]
    # monotone surge (sampled every 20 steps; tolerate one-cell jitter)
    dx = 1.0 / 64
    assert all(b >= a - dx for a, b in zip(fronts, fronts[1:])), fronts
    assert fronts[-1] > 0.25 + 2 * dx, fronts     # front left the dam
    assert recs[-1]["volume_drift"] < 1e-2, recs[-1]
    assert recs[-1]["max_div"] < 1e-2, recs[-1]


def test_cavity_example_short(tmp_path):
    """Short Re=100 cavity run: the primary vortex spins up (negative
    return-flow u on the centerline), the field stays finite and
    divergence-free at solver tolerance. The full Ghia-profile pin
    lives in test_ins_ppm_walls.py; this drives the EXAMPLE surface."""
    inp = tmp_path / "input2d"
    inp.write_text("""
Main {
   viz_dump_interval = 0
   log_interval = 100
   log_jsonl = "%s"
}
CartesianGeometry {
   n = 32, 32
   x_lo = 0.0, 0.0
   x_up = 1.0, 1.0
}
INSStaggeredHierarchyIntegrator {
   rho = 1.0
   mu = 0.01
   U_lid = 1.0
   convective_op_type = "ppm"
   dt = 0.01
   num_steps = 300
}
""" % (tmp_path / "m.jsonl"))
    mod = _load_main(os.path.join(
        REPO, "examples", "navier_stokes", "cavity2d", "main.py"))
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        mod.main(["main.py", str(inp)])
    finally:
        os.chdir(cwd)
    recs = [json.loads(ln) for ln in
            open(tmp_path / "m.jsonl").read().splitlines()]
    assert recs, "no metrics written"
    spin = [r for r in recs if "u_center_min" in r]
    assert spin and spin[-1]["u_center_min"] < -0.05, spin[-1:]
    assert spin[-1]["max_div"] < 1e-5, spin[-1]
    prof = recs[-1].get("centerline_u")
    assert prof is not None and np.isfinite(prof).all()


def test_eel_example_swims_against_wave(tmp_path):
    """Self-propulsion oracle: the backward-traveling gait (wave
    toward +x/tail) must drive the swimmer in -x, with thrust emerging
    from the momentum projection alone — no prescribed translation.
    Pinned: monotone-ish COM retreat totaling > 0.1 body lengths over
    the run, finite rigid-motion diagnostics."""
    inp = tmp_path / "input2d"
    inp.write_text("""
Main {
   log_interval = 100
   log_jsonl = "%s"
}
CartesianGeometry {
   n = 64, 32
   x_lo = 0.0, 0.0
   x_up = 2.0, 1.0
}
INSStaggeredHierarchyIntegrator {
   rho = 1.0
   mu = 2.0e-3
   dt = 2.0e-3
   num_steps = 600
}
Eel {
   length = 0.4
   thickness = 0.04
   center = 1.4, 0.5
   amplitude = 0.06
   wavelength = 0.4
   frequency = 2.0
}
""" % (tmp_path / "m.jsonl"))
    mod = _load_main(os.path.join(
        REPO, "examples", "ConstraintIB", "eel2d", "main.py"))
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        mod.main(["main.py", str(inp)])
    finally:
        os.chdir(cwd)
    recs = [json.loads(ln) for ln in
            open(tmp_path / "m.jsonl").read().splitlines()]
    assert recs, "no metrics written"
    dxs = [r["swim_dx"] for r in recs]
    # swims AGAINST the wave: net displacement -x, > 0.25 body length
    # (0.4 * 0.25 = 0.1) by the end of the run, and retreating at
    # every logged sample after spin-up (samples straddle gait phases,
    # so allow intra-cycle COM oscillation up to a tenth of the
    # per-sample net advance)
    assert dxs[-1] < -0.1, dxs
    eps = 0.1 * abs(dxs[-1] - dxs[1]) / max(len(dxs) - 2, 1)
    assert all(b < a + eps for a, b in zip(dxs[1:], dxs[2:])), dxs
    assert np.isfinite(recs[-1]["U_body"]).all()


def test_ibfe_beam_example_bends_downstream(tmp_path):
    """Cantilever oracle: the clamped FE beam bends DOWNSTREAM (+x),
    settles to a steady deflection (fluid-elastic balance), stores
    positive elastic energy, and the tip drops below its upright
    height (finite rotation, not shear-off)."""
    inp = tmp_path / "input2d"
    inp.write_text("""
Main {
   log_interval = 100
   log_jsonl = "%s"
}
CartesianGeometry {
   n = 64, 32
   x_lo = 0.0, 0.0
   x_up = 2.0, 1.0
}
INSOpenIntegrator {
   rho = 1.0
   mu = 0.01
   U0 = 1.0
   dt = 2.0e-3
   num_steps = 500
   tol = 1.0e-6
}
Beam {
   width = 0.08
   height = 0.4
   base_x = 0.6
   base_y = 0.12
   nx_elems = 2
   ny_elems = 8
   shear_modulus = 40.0
   bulk_modulus = 400.0
   k_anchor = 2000.0
}
""" % (tmp_path / "m.jsonl"))
    mod = _load_main(os.path.join(
        REPO, "examples", "IBFE", "explicit", "beam2d", "main.py"))
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        mod.main(["main.py", str(inp)])
    finally:
        os.chdir(cwd)
    recs = [json.loads(ln) for ln in
            open(tmp_path / "m.jsonl").read().splitlines()]
    assert recs, "no metrics written"
    defl = [r["tip_deflection"] for r in recs]
    assert defl[-1] > 0.05, defl                  # bends downstream
    assert abs(defl[-1] - defl[-2]) < 0.02, defl  # settled
    assert recs[-1]["elastic_energy"] > 0.0
    # tip rotated over: below its upright height base_y + H = 0.52
    assert recs[-1]["tip_y"] < 0.52


def test_dam_break_restart_continuation(tmp_path):
    """RestartManager-style workflow: 20 steps + checkpoint, then
    --restart for 20 more must land bitwise on the straight-through
    40-step trajectory (same platform, same chunked advance)."""
    cfg = """
Main {
   viz_dump_interval = 0
   log_interval = 20
   log_jsonl = "%s"
   restart_dirname = "%s"
   restart_interval = %d
}
CartesianGeometry {
   n = 48, 32
   x_lo = 0.0, 0.0
   x_up = 1.0, 0.75
}
INSVCStaggeredHierarchyIntegrator {
   rho0 = 1.0
   rho1 = 1000.0
   mu0 = 1.8e-4
   mu1 = 1.0e-2
   sigma = 0.0
   gravity_y = -9.81
   column_width = 0.25
   column_height = 0.5
   dt = 1.5e-3
   num_steps = %d
   cg_tol = 1.0e-5
}
"""
    mod = _load_main(os.path.join(
        REPO, "examples", "multiphase", "dam_break", "main.py"))
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        # straight run: 40 steps, no restart dumps
        (tmp_path / "in_a").write_text(
            cfg % (tmp_path / "a.jsonl", tmp_path / "ra", 0, 40))
        mod.main(["main.py", str(tmp_path / "in_a")])
        # split run: 20 steps with a dump, then resume to 40
        (tmp_path / "in_b").write_text(
            cfg % (tmp_path / "b.jsonl", tmp_path / "rb", 20, 20))
        mod.main(["main.py", str(tmp_path / "in_b")])
        (tmp_path / "in_c").write_text(
            cfg % (tmp_path / "c.jsonl", tmp_path / "rb", 20, 40))
        mod.main(["main.py", str(tmp_path / "in_c"), "--restart"])
    finally:
        os.chdir(cwd)
    a = [json.loads(ln) for ln in
         open(tmp_path / "a.jsonl").read().splitlines()][-1]
    c = [json.loads(ln) for ln in
         open(tmp_path / "c.jsonl").read().splitlines()][-1]
    assert a["step"] == c["step"] == 40
    # bitwise continuation: identical front and identical drift metric
    assert a["front"] == c["front"], (a, c)
    assert a["volume_drift"] == c["volume_drift"], (a, c)
