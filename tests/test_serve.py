"""Serving layer: AOT executable cache + warm-pool router (PR 12).

Cache-mechanics tests use fake build functions (no compiles, pure
hash-cons semantics). The heavier router tests share one module-scoped
warm pool at the tiny shell shape so the fast tier pays the bucket
compile once; the cold-vs-warm smoke is the SAME drill
``tools/serve.py check`` pins against SERVE_CONTRACT.json, so the
zero-recompile warm-path guarantee gates tier-1 directly.
"""

import json
import os
import threading
import time

import pytest

from ibamr_tpu import obs
from ibamr_tpu.serve import aot_cache
from ibamr_tpu.serve.aot_cache import ExecutableCache
from ibamr_tpu.serve.router import (BucketSpec, ScenarioRequest,
                                    WarmPoolRouter, cold_warm_drill)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# tiny shell family shared by every heavy test in this module
_N, _N_LAT, _N_LON = 8, 6, 8


# ---------------------------------------------------------------------------
# cache mechanics (fake builds — no jax compiles)
# ---------------------------------------------------------------------------

def _fp(tag):
    """A minimal fingerprint-shaped dict distinct per tag."""
    return {"config_digest": f"cfg-{tag}", "engine": "scatter",
            "spectral_dtype": None, "x64": True, "platform": "cpu"}


def test_cache_hit_miss_and_lru_eviction():
    cache = ExecutableCache(capacity=2)
    builds = []

    def build(tag):
        def _b():
            builds.append(tag)
            return ("exe", tag)
        return _b

    e1 = cache.get_or_compile(_fp("a"), build("a"))
    assert cache.get_or_compile(_fp("a"), build("a")).executable \
        == e1.executable
    assert builds == ["a"]                       # second call was a hit
    cache.get_or_compile(_fp("b"), build("b"))
    # touch "a" so "b" is the LRU victim when "c" lands
    cache.get_or_compile(_fp("a"), build("a"))
    cache.get_or_compile(_fp("c"), build("c"))
    assert len(cache) == 2
    assert builds == ["a", "b", "c"]
    st = cache.stats()
    assert (st["hits"], st["misses"], st["evictions"]) == (2, 3, 1)
    # the evicted family recompiles (displacing LRU "a"); the freshly
    # retained one still hits
    cache.get_or_compile(_fp("b"), build("b"))
    assert builds == ["a", "b", "c", "b"]
    cache.get_or_compile(_fp("c"), build("c"))
    assert builds == ["a", "b", "c", "b"]


def test_cache_key_separates_extra_material():
    fp = _fp("x")
    k1 = aot_cache.cache_key(fp, extra={"lanes": 2, "length": 1})
    k2 = aot_cache.cache_key(fp, extra={"lanes": 2, "length": 2})
    k3 = aot_cache.cache_key(fp, extra={"length": 1, "lanes": 2})
    assert k1 != k2                  # chunk length is compile identity
    assert k1 == k3                  # dict order is not


def test_concurrent_get_or_compile_builds_once():
    cache = ExecutableCache(capacity=4)
    n_builds = [0]
    release = threading.Event()

    def slow_build():
        n_builds[0] += 1
        release.wait(5.0)
        return object()

    got, errs = [], []

    def worker():
        try:
            got.append(cache.get_or_compile(_fp("k"), slow_build))
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.2)                  # let every waiter reach the latch
    release.set()
    for t in threads:
        t.join(10.0)
    assert not errs
    assert n_builds[0] == 1          # exactly one build for the key
    assert len({id(e.executable) for e in got}) == 1
    st = cache.stats()
    assert st["misses"] == 1
    # each waiter re-enters after the latch and reads the published
    # entry as a hit (so it also counts one inflight wait)
    assert st["hits"] == 3
    assert 0 <= st["inflight_waits"] <= 3


def test_failed_build_propagates_and_does_not_poison():
    cache = ExecutableCache(capacity=4)
    with pytest.raises(RuntimeError, match="boom"):
        cache.get_or_compile(_fp("bad"), lambda: (_ for _ in ()).throw(
            RuntimeError("boom")))
    # the key is not latched dead: a later build succeeds
    ent = cache.get_or_compile(_fp("bad"), lambda: "ok")
    assert ent.executable == "ok"


def test_corrupt_manifest_refused_and_reaped(tmp_path):
    d = str(tmp_path / "aot")
    cache = ExecutableCache(capacity=4, directory=d)
    ent = cache.get_or_compile(_fp("m"), lambda: "exe")
    path = cache.manifest_path(ent.key)
    assert cache.published_keys() == [ent.key]

    # flip a byte inside the signed body -> digest mismatch
    doc = json.load(open(path))
    doc["body"]["label"] = "tampered"
    json.dump(doc, open(path, "w"))
    fresh = ExecutableCache(capacity=4, directory=d)
    assert fresh._read_manifest(ent.key) is None   # corruption never loads
    assert not os.path.exists(path)                # reaped
    assert fresh.stats()["corrupt"] == 1
    # the recompile is accounted a true cold build, not a cached load
    rebuilt = fresh.get_or_compile(_fp("m"), lambda: "exe2")
    assert rebuilt.cold_source == "compile"

    # unreadable JSON is refused the same way
    ent2 = cache.get_or_compile(_fp("m2"), lambda: "exe")
    with open(cache.manifest_path(ent2.key), "w") as f:
        f.write("{not json")
    assert fresh._read_manifest(ent2.key) is None
    # a valid manifest marks the rebuild as persistent-cache-served
    ent3 = cache.get_or_compile(_fp("m3"), lambda: "exe")
    fresh2 = ExecutableCache(capacity=4, directory=d)
    warm = fresh2.get_or_compile(_fp("m3"), lambda: "exe")
    assert warm.key == ent3.key
    assert warm.cold_source == "persistent"


# ---------------------------------------------------------------------------
# the cold-vs-warm drill (module-scoped: ONE bucket compile for the file)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def drill():
    return cold_warm_drill(n_cells=_N, n_lat=_N_LAT, n_lon=_N_LON,
                           lanes=2, steps=3)


def test_cold_warm_smoke_zero_recompiles(drill):
    assert drill["cold_ok"] and drill["warm_ok"]
    assert drill["cold_compiles"] >= 1      # ack + cruise chunks
    assert drill["warm_compiles"] == 0      # the tentpole guarantee
    assert drill["warm_hits"] >= 1
    assert drill["warm_new_trace_signatures"] == 0
    # acceptance: warm request-to-first-step <= 5% of cold
    assert drill["warm_over_cold"] <= 0.05
    assert drill["engine"] != "auto"        # resolver output, resolved


def test_drill_meets_serve_contract(drill):
    """The repo's pinned SERVE_CONTRACT.json gates tier-1 through the
    same diff the ``tools/serve.py check`` CLI applies."""
    from tools.serve import diff_contract, load_contract

    regressions, _ = diff_contract(drill, load_contract())
    assert regressions == []


def test_serve_check_exit_codes(tmp_path, monkeypatch):
    """check exits 0/1/2 exactly like graph_audit (clean / improved-or-
    unbudgeted / regressed), without re-running the drill."""
    import tools.serve as serve_cli

    measured = {"n": _N, "lanes": 2, "steps": 3, "engine": "scatter",
                "cold_first_step_s": 5.0, "warm_first_step_s": 0.01,
                "warm_over_cold": 0.002, "cold_compiles": 2,
                "warm_compiles": 0, "warm_hits": 2,
                "warm_new_trace_signatures": 0,
                "cold_ok": True, "warm_ok": True}
    monkeypatch.setattr(serve_cli, "run_drill",
                        lambda args, force_cpu_backend: dict(measured))
    contract = str(tmp_path / "contract.json")

    assert serve_cli.main(["check", "--tighten",
                           "--contract", contract]) == 0
    assert serve_cli.main(["check", "--contract", contract]) == 0

    improved = dict(measured, cold_compiles=1)
    monkeypatch.setattr(serve_cli, "run_drill",
                        lambda args, force_cpu_backend: improved)
    assert serve_cli.main(["check", "--contract", contract]) == 1

    regressed = dict(measured, warm_compiles=1)
    monkeypatch.setattr(serve_cli, "run_drill",
                        lambda args, force_cpu_backend: regressed)
    assert serve_cli.main(["check", "--json",
                           "--contract", contract]) == 2

    broken = dict(measured, warm_ok=False)
    monkeypatch.setattr(serve_cli, "run_drill",
                        lambda args, force_cpu_backend: broken)
    assert serve_cli.main(["check", "--contract", contract]) == 2


# ---------------------------------------------------------------------------
# router: bucketing, padding, quarantine, per-request accounting
# (one module-scoped 4-lane warm pool shared by every test below)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def warm_router():
    spec = BucketSpec(n_cells=_N, n_lat=_N_LAT, n_lon=_N_LON, lanes=4)
    router = WarmPoolRouter([spec], cache=ExecutableCache(),
                            allow_dynamic=False)
    router.warm(spec)
    return router, spec


def _req(tag, **kw):
    kw.setdefault("steps", 2)
    return ScenarioRequest(tenant=tag, n_cells=_N, n_lat=_N_LAT,
                           n_lon=_N_LON, **kw)


def test_short_group_padded_into_bucket(warm_router):
    router, spec = warm_router
    before = router.cache.stats()
    results = router.serve([_req("t0"), _req("t1"), _req("t2")])
    after = router.cache.stats()
    assert [r.tenant for r in results] == ["t0", "t1", "t2"]
    assert all(r.ok and not r.quarantined for r in results)
    assert all(r.bucket_lanes == 4 for r in results)   # padded to B=4
    assert [r.lane for r in results] == [0, 1, 2]
    assert all(not r.cold for r in results)            # pool was warm
    assert after["misses"] == before["misses"]         # zero compiles
    assert after["hits"] > before["hits"]


def test_oversize_group_splits_across_batches(warm_router):
    router, _ = warm_router
    results = router.serve([_req(f"t{i}", steps=1) for i in range(6)])
    assert all(r.ok for r in results)
    # 6 requests through a 4-lane bucket: lanes wrap across 2 batches
    assert [r.lane for r in results] == [0, 1, 2, 3, 0, 1]


def test_unknown_family_without_dynamic_raises(warm_router):
    router, _ = warm_router
    with pytest.raises(KeyError, match="no declared bucket"):
        router.serve([ScenarioRequest(tenant="alien", n_cells=_N,
                                      n_lat=4, n_lon=4)])


def test_quarantine_isolates_poisoned_lane(warm_router):
    router, _ = warm_router
    results = router.serve([_req("good"),
                            _req("bad", perturb=float("nan")),
                            _req("also-good")])
    by = {r.tenant: r for r in results}
    assert by["bad"].quarantined and not by["bad"].ok
    assert "quarantined" in by["bad"].error
    assert by["good"].ok and not by["good"].quarantined
    assert by["also-good"].ok


def test_request_ledger_accounting(warm_router, tmp_path):
    router, _ = warm_router
    path = str(tmp_path / "ledger.jsonl")
    with obs.ledger(path):
        results = router.serve([_req("tenant-a"), _req("tenant-b")])
    all_recs = obs.read_ledger(path)
    recs = [r for r in all_recs if r.get("kind") == "request"]
    assert [r["tenant"] for r in recs] == ["tenant-a", "tenant-b"]
    for r in recs:
        assert r["ok"] and not r["quarantined"] and not r["cold"]
        assert r["bucket_lanes"] == 4
        assert r["steps"] == 2
        assert r["first_step_s"] <= r["total_s"]
        assert r["engine"] and r["engine"] != "auto"
    # trace identity: every request minted a distinct id at admission,
    # and the completion record carries the same id as the result
    admits = [r for r in all_recs if r.get("kind") == "request_admit"]
    assert [a["tenant"] for a in admits] == ["tenant-a", "tenant-b"]
    tids = [a["trace_id"] for a in admits]
    assert len(set(tids)) == 2
    assert [r.trace_id for r in results] == tids
    assert [r["trace_id"] for r in recs] == tids
    # batch spans are stamped with BOTH ids (one batch, two requests)
    spans = [r for r in all_recs if r.get("kind") == "span"
             and r.get("path", "").startswith("serve/request")]
    assert spans and sorted(obs.record_trace_ids(spans[0])) \
        == sorted(tids)


def test_trace_timeline_reconstructs_request(warm_router, tmp_path,
                                             capsys):
    """Acceptance: ``tools/obs.py trace <id>`` rebuilds one request's
    admission -> execution -> completion timeline from the ledger
    alone, resolving unique id prefixes."""
    from tools.obs import main as obs_main

    router, _ = warm_router
    path = str(tmp_path / "ledger.jsonl")
    with obs.ledger(path):
        res = router.serve([_req("traced", steps=2)])[0]
    assert res.ok and res.trace_id

    rc = obs_main(["trace", path, res.trace_id[:6]])
    out = capsys.readouterr().out
    assert rc == 0
    assert f"trace {res.trace_id}" in out
    assert "admitted" in out and "tenant=traced" in out
    assert "completed" in out and "warm" in out
    assert "verdict: ok" in out
    lines = out.strip().splitlines()
    assert "admitted" in lines[1]        # admission leads the timeline
    assert any("serve/request" in ln for ln in lines)

    # unknown prefix: no timeline, rc 1
    assert obs_main(["trace", path, "ffffffff"]) == 1
    capsys.readouterr()


def test_tail_filters_by_trace_and_grep(warm_router, tmp_path,
                                        capsys):
    from tools.obs import main as obs_main

    router, _ = warm_router
    path = str(tmp_path / "ledger.jsonl")
    with obs.ledger(path):
        r0, r1 = router.serve([_req("tail-a"), _req("tail-b")])

    fast = ["--max-seconds", "0.01", "--interval", "0.01"]
    assert obs_main(["tail", path, "--trace", r0.trace_id[:8]]
                    + fast) == 0
    out = capsys.readouterr().out
    assert "tenant=tail-a" in out
    # per-request records of the OTHER request are filtered out
    assert "tenant=tail-b" not in out

    assert obs_main(["tail", path, "--grep", "tail-b"] + fast) == 0
    out = capsys.readouterr().out
    assert "tenant=tail-b" in out and "tenant=tail-a" not in out


def test_obs_summary_renders_serving_block(warm_router, tmp_path,
                                           capsys):
    from tools.obs import cmd_summary

    router, _ = warm_router
    path = str(tmp_path / "ledger.jsonl")
    with obs.ledger(path):
        router.serve([_req("render-me")])

    class _Args:
        ledger = path
        device = None

    assert cmd_summary(_Args()) == 0
    out = capsys.readouterr().out
    assert "serving (warm-pool efficacy)" in out
    assert "warm first-step" in out


def test_served_chunk_contract_artifact_registered():
    from ibamr_tpu.analysis.contracts import ARTIFACTS

    assert "served_chunk" in ARTIFACTS
    budgets = json.load(open(os.path.join(REPO, "GRAPH_BUDGETS.json")))
    pinned = budgets["artifacts"]["served_chunk"]
    assert pinned["host_transfers_in_scan"] == 0
