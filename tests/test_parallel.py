"""Multi-device sharding tests: 1-device vs 8-device agreement.

The analog of the reference's ``mpirun=1`` vs ``mpirun=4`` baseline
comparisons (SURVEY.md §4): the same config run replicated and sharded
must agree to roundoff tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.models.membrane2d import build_membrane_example
from ibamr_tpu.models.shell3d import build_shell_example, make_spherical_shell
from ibamr_tpu.parallel import (factor_devices, make_mesh,
                                make_sharded_ib_step, make_sharded_ins_step)
from ibamr_tpu.parallel.mesh import place_state


def _tree_allclose(a, b, rtol, atol):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=rtol, atol=atol)


def test_factor_devices():
    assert factor_devices(8) == (4, 2)
    assert factor_devices(4) == (2, 2)
    assert factor_devices(7) == (7,)
    assert factor_devices(1) == (1,)
    assert factor_devices(8, max_axes=1) == (8,)


def test_make_mesh_shapes():
    mesh = make_mesh(8)
    assert mesh.devices.shape == (4, 2)
    assert mesh.axis_names == ("x", "y")
    mesh1 = make_mesh(8, max_axes=1)
    assert mesh1.devices.shape == (8,)


@pytest.mark.parametrize("mesh_axes", [1, 2])
def test_ins_sharded_matches_single(mesh_axes):
    """Pure fluid step (Taylor-Green start) sharded vs replicated."""
    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.ins import INSStaggeredIntegrator

    grid = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    integ = INSStaggeredIntegrator(grid, rho=1.0, mu=0.01,
                                   dtype=jnp.float64)
    two_pi = 2.0 * np.pi

    def u0(coords, t):
        x, y = coords
        return [jnp.sin(two_pi * x) * jnp.cos(two_pi * y) + 0 * y,
                -jnp.cos(two_pi * x) * jnp.sin(two_pi * y) + 0 * x]

    state0 = integ.initialize(u0=u0)
    dt = 1e-3

    ref = state0
    step1 = jax.jit(lambda s, d: integ.step(s, d))
    for _ in range(5):
        ref = step1(ref, dt)

    mesh = make_mesh(8, max_axes=mesh_axes)
    stepN = make_sharded_ins_step(integ, mesh)
    out = place_state(state0, grid, mesh)
    for _ in range(5):
        out = stepN(out, dt)

    _tree_allclose(ref, out, rtol=1e-12, atol=1e-12)


def test_ib_membrane_sharded_matches_single():
    """Full coupled IB step (2D membrane) sharded vs replicated."""
    integ, state0 = build_membrane_example(
        n_cells=32, num_markers=64, aspect=1.3, dtype=jnp.float64)
    dt = 1e-3

    ref = state0
    step1 = jax.jit(lambda s, d: integ.step(s, d))
    for _ in range(5):
        ref = step1(ref, dt)

    mesh = make_mesh(8, max_axes=2)
    stepN = make_sharded_ib_step(integ, mesh)
    out = place_state(state0, integ.ins.grid, mesh)
    for _ in range(5):
        out = stepN(out, dt)

    _tree_allclose(ref, out, rtol=1e-11, atol=1e-12)


def test_ib_shell3d_sharded_matches_single():
    """Full coupled IB step (3D shell) on a 2D-sharded 3D grid."""
    integ, state0 = build_shell_example(
        n_cells=16, n_lat=8, n_lon=8, dtype=jnp.float64)
    dt = 1e-3

    ref = jax.jit(lambda s, d: integ.step(s, d))(state0, dt)

    mesh = make_mesh(8, max_axes=2)
    stepN = make_sharded_ib_step(integ, mesh)
    out = stepN(place_state(state0, integ.ins.grid, mesh), dt)

    _tree_allclose(ref, out, rtol=1e-11, atol=1e-12)


# ---------------------------------------------------------------------------
# 3D shell model structure checks
# ---------------------------------------------------------------------------

def test_shell_geometry():
    data = make_spherical_shell(8, 16, radius=0.25, center=(0.5, 0.5, 0.5),
                                stiffness=1.0)
    assert data.vertices.shape == (128, 3)
    r = np.linalg.norm(data.vertices - 0.5, axis=1)
    np.testing.assert_allclose(r, 0.25, rtol=1e-12)
    # ring springs (8*16) + meridian springs (7*16)
    assert data.springs.shape[0] == 8 * 16 + 7 * 16
    # spring indices valid
    assert data.springs[:, :2].max() < 128
    assert data.springs[:, :2].min() >= 0


def test_shell_beams_present():
    data = make_spherical_shell(8, 16, radius=0.25, center=(0.5, 0.5, 0.5),
                                stiffness=1.0, bend_rigidity=0.01)
    assert data.beams is not None
    assert data.beams.shape[0] == 6 * 16  # interior rings only


def test_shell_spring_rest_state_is_equilibrium_free():
    """With rest_length_factor=1 on a perfect sphere, ring springs are at
    their rest length -> near-zero net ring tension (chord vs arc gives a
    small systematic; verify it vanishes with resolution)."""
    from ibamr_tpu.ops import forces as fmod

    coarse = make_spherical_shell(16, 16, 0.25, (0.5, 0.5, 0.5), 1.0)
    fine = make_spherical_shell(64, 64, 0.25, (0.5, 0.5, 0.5), 1.0)

    def max_force(data):
        X = jnp.asarray(data.vertices)
        F = fmod.compute_lagrangian_force(X, jnp.zeros_like(X),
                                          data.force_specs())
        return float(jnp.max(jnp.abs(F)))

    # forces scale down as the lattice refines toward the smooth sphere
    assert max_force(fine) < max_force(coarse)


def test_wall_bounded_ins_sharded_matches_single(mesh8):
    """Sharded wall-bounded (cavity) Navier-Stokes: the fast-
    diagonalization solves are per-axis dense matmuls the SPMD
    partitioner distributes directly; 8-device must equal 1-device to
    roundoff (lifts the round-1 'periodic-only sharding' restriction)."""
    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
    from ibamr_tpu.parallel.mesh import make_sharded_ins_step

    g = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    integ = INSStaggeredIntegrator(
        g, mu=0.01, rho=1.0, dtype=jnp.float64,
        wall_axes=(True, True), wall_tangential={(0, 1, 1): 1.0},
        convective_op_type="ppm")
    st0 = integ.initialize()
    ref = st0
    for _ in range(5):
        ref = integ.step(ref, 1e-3)

    step = make_sharded_ins_step(integ, mesh8)
    sh = place_state(st0, g, mesh8)
    for _ in range(5):
        sh = step(sh, 1e-3)
    for a, b in zip(ref.u + (ref.p,), sh.u + (sh.p,)):
        assert np.max(np.abs(np.asarray(a) - np.asarray(b))) < 1e-13


def test_wall_bounded_adv_diff_sharded_matches_single(mesh8):
    from ibamr_tpu.bc import DomainBC, dirichlet_axis, periodic_axis
    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.adv_diff import (
        AdvDiffSemiImplicitIntegrator, TransportedQuantity)
    from ibamr_tpu.parallel.mesh import make_sharded_adv_diff_step

    g = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    bc = DomainBC((dirichlet_axis(0.0, 1.0), periodic_axis()))
    integ = AdvDiffSemiImplicitIntegrator(
        g, [TransportedQuantity(name="Q", kappa=0.05, bc=bc)],
        dtype=jnp.float64)
    x = (np.arange(32) + 0.5) / 32
    Q0 = jnp.asarray(np.broadcast_to(np.sin(np.pi * x)[:, None],
                                     (32, 32)))
    st_ref = integ.initialize([Q0])
    st_sh = integ.initialize([Q0])
    step = make_sharded_adv_diff_step(integ, mesh8)
    for _ in range(5):
        st_ref = integ.step(st_ref, 1e-3)
        st_sh = step(st_sh, 1e-3)
    assert np.max(np.abs(np.asarray(st_ref.Q[0])
                         - np.asarray(st_sh.Q[0]))) < 1e-13


@pytest.mark.parametrize("mesh_axes", [1, 2])
def test_two_level_ib_sharded_matches_single(mesh_axes):
    """The composite two-level INS/IB step — coarse level sharded over
    the mesh, fine window replicated, explicit pins at every level
    crossing — must match the unsharded step (VERDICT round 2 item 2:
    this replaces the fully-replicated workaround for the SPMD
    mixed scatter/gather miscompile)."""
    from ibamr_tpu.amr import FineBox
    from ibamr_tpu.amr_ins import TwoLevelIBINS
    from ibamr_tpu.integrators.ib import IBMethod
    from ibamr_tpu.models.membrane2d import make_circle_membrane
    from ibamr_tpu.parallel.mesh import make_sharded_two_level_ib_step

    n = 32
    from ibamr_tpu.grid import StaggeredGrid
    grid = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    struct = make_circle_membrane(48, 0.08, (0.5, 0.5), stiffness=0.5)
    ib = IBMethod(struct.force_specs(dtype=jnp.float64), kernel="IB_4")
    box = FineBox(lo=(8, 8), shape=(16, 16))
    integ = TwoLevelIBINS(grid, box, ib, mu=0.02, proj_tol=1e-10)
    st0 = integ.initialize(jnp.asarray(struct.vertices, jnp.float64))

    dt = 2e-4
    ref = st0
    for _ in range(3):
        ref = integ.step(ref, dt)

    mesh = make_mesh(8, max_axes=mesh_axes)
    step = make_sharded_two_level_ib_step(integ, mesh)
    sh = st0
    for _ in range(3):
        sh = step(sh, dt)

    _tree_allclose(ref, sh, rtol=1e-12, atol=1e-12)
    # the coarse level really is distributed
    assert len(sh.fluid.uc[0].sharding.device_set) == 8


def test_multilevel_ins_sharded_matches_single():
    """The L-level composite INS step — root level sharded, box levels
    replicated, pins at every level crossing — must match the
    unsharded step (the arbitrary-depth extension of the two-level
    equality above; removes the round-3 "L-level runs replicated"
    scope line)."""
    from ibamr_tpu.amr import FineBox
    from ibamr_tpu.amr_ins_multilevel import MultiLevelINS
    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.parallel.mesh import make_sharded_multilevel_ins_step

    grid = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    boxes = [FineBox(lo=(8, 8), shape=(16, 16)),
             FineBox(lo=(8, 8), shape=(16, 16))]
    integ = MultiLevelINS(grid, boxes, mu=0.02, proj_tol=1e-10)

    def vel(d, mesh):
        x, y = mesh
        if d == 0:
            return np.sin(2 * np.pi * x) * np.cos(2 * np.pi * y)
        return -np.cos(2 * np.pi * x) * np.sin(2 * np.pi * y)

    st0 = integ.initialize(vel_fn=vel)

    dt = 2e-4
    ref = st0
    for _ in range(3):
        ref = integ.step(ref, dt)

    mesh = make_mesh(8)
    step = make_sharded_multilevel_ins_step(integ, mesh)
    sh = st0
    for _ in range(3):
        sh = step(sh, dt)

    _tree_allclose(ref, sh, rtol=1e-12, atol=1e-12)
    assert len(sh.us[0][0].sharding.device_set) == 8


@pytest.mark.parametrize("mesh_axes", [1, 2])
def test_multilevel_ib_sharded_matches_single(mesh_axes):
    """3-level composite INS/IB: root sharded, boxes + markers
    replicated — bitwise-tolerance equal to the single-device step
    (S4 for the L-level FLAGSHIP path)."""
    from ibamr_tpu.amr import FineBox
    from ibamr_tpu.amr_ins_multilevel import MultiLevelIBINS
    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.ib import IBMethod
    from ibamr_tpu.models.membrane2d import make_circle_membrane
    from ibamr_tpu.parallel.mesh import make_sharded_multilevel_ib_step

    grid = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    boxes = [FineBox(lo=(8, 8), shape=(16, 16)),
             FineBox(lo=(8, 8), shape=(16, 16))]
    struct = make_circle_membrane(48, 0.08, (0.5, 0.5), stiffness=0.5)
    ib = IBMethod(struct.force_specs(dtype=jnp.float64), kernel="IB_4")
    integ = MultiLevelIBINS(grid, boxes, ib, mu=0.02, proj_tol=1e-10)
    st0 = integ.initialize(jnp.asarray(struct.vertices, jnp.float64))

    dt = 2e-4
    ref = st0
    for _ in range(3):
        ref = integ.step(ref, dt)

    mesh = make_mesh(8, max_axes=mesh_axes)
    step = make_sharded_multilevel_ib_step(integ, mesh)
    sh = st0
    for _ in range(3):
        sh = step(sh, dt)

    _tree_allclose(ref, sh, rtol=1e-12, atol=1e-12)
    assert len(sh.fluid.us[0][0].sharding.device_set) == 8


def test_two_level_ib_3d_sharded_matches_single():
    """The composite two-level INS/IB in 3D (the reference's production
    shape: adaptive 3D shell) under sharding — coarse level distributed,
    window replicated — equals the single-device step."""
    from ibamr_tpu.amr import FineBox
    from ibamr_tpu.amr_ins import TwoLevelIBINS
    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.ib import IBMethod
    from ibamr_tpu.parallel.mesh import make_sharded_two_level_ib_step

    g = StaggeredGrid(n=(16, 16, 16), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    s = make_spherical_shell(8, 8, 0.1, (0.5, 0.5, 0.5), 1.0)
    ib = IBMethod(s.force_specs(dtype=jnp.float64), kernel="IB_4")
    box = FineBox(lo=(4, 4, 4), shape=(8, 8, 8))
    integ = TwoLevelIBINS(g, box, ib, mu=0.05, proj_tol=1e-10)
    st0 = integ.initialize(jnp.asarray(s.vertices, jnp.float64))

    dt = 5e-4
    ref = st0
    for _ in range(2):
        ref = integ.step(ref, dt)

    mesh = make_mesh(8)
    step = make_sharded_two_level_ib_step(integ, mesh)
    sh = st0
    for _ in range(2):
        sh = step(sh, dt)

    _tree_allclose(ref, sh, rtol=1e-12, atol=1e-12)
    assert len(sh.fluid.uc[0].sharding.device_set) == 8


@pytest.mark.parametrize("mesh_axes", [1, 2])
def test_two_level_ib_sharded_window_matches_single(mesh_axes):
    """S4 DEPTH (VERDICT round 3 missing #2): with
    ``shard_window=True`` the fine window is DISTRIBUTED over the mesh
    instead of replicated — and still matches the single-device step at
    rtol 1e-12. The sharding assertion checks the window arrays really
    are split (not replicated onto all devices)."""
    from ibamr_tpu.amr import FineBox
    from ibamr_tpu.amr_ins import TwoLevelIBINS
    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.ib import IBMethod
    from ibamr_tpu.models.membrane2d import make_circle_membrane
    from ibamr_tpu.parallel.mesh import make_sharded_two_level_ib_step

    n = 32
    grid = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    struct = make_circle_membrane(48, 0.08, (0.5, 0.5), stiffness=0.5)
    ib = IBMethod(struct.force_specs(dtype=jnp.float64), kernel="IB_4")
    box = FineBox(lo=(8, 8), shape=(16, 16))
    integ = TwoLevelIBINS(grid, box, ib, mu=0.02, proj_tol=1e-10)
    st0 = integ.initialize(jnp.asarray(struct.vertices, jnp.float64))

    dt = 2e-4
    ref = st0
    for _ in range(3):
        ref = integ.step(ref, dt)

    mesh = make_mesh(8, max_axes=mesh_axes)
    step = make_sharded_two_level_ib_step(integ, mesh, shard_window=True)
    sh = st0
    for _ in range(3):
        sh = step(sh, dt)

    _tree_allclose(ref, sh, rtol=1e-12, atol=1e-12)
    # both levels really are distributed: at least one window MAC
    # component's OUTPUT sharding is split (XLA falls back to a
    # replicated jit-output layout for the component whose +1 MAC axis
    # doesn't divide the mesh axis — e.g. 17 over 8 — so assert on the
    # components collectively, not on uf[0] alone)
    assert any(not c.sharding.is_fully_replicated for c in sh.fluid.uf)
    assert len(sh.fluid.uf[0].sharding.device_set) == 8
    assert len(sh.fluid.uc[0].sharding.device_set) == 8


def test_two_level_ib_3d_sharded_window_matches_single():
    """3D twin of the sharded-window equality (the production shape)."""
    from ibamr_tpu.amr import FineBox
    from ibamr_tpu.amr_ins import TwoLevelIBINS
    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.ib import IBMethod
    from ibamr_tpu.parallel.mesh import make_sharded_two_level_ib_step

    g = StaggeredGrid(n=(16, 16, 16), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    s = make_spherical_shell(8, 8, 0.1, (0.5, 0.5, 0.5), 1.0)
    ib = IBMethod(s.force_specs(dtype=jnp.float64), kernel="IB_4")
    box = FineBox(lo=(4, 4, 4), shape=(8, 8, 8))
    integ = TwoLevelIBINS(g, box, ib, mu=0.05, proj_tol=1e-10)
    st0 = integ.initialize(jnp.asarray(s.vertices, jnp.float64))

    dt = 5e-4
    ref = st0
    for _ in range(2):
        ref = integ.step(ref, dt)

    mesh = make_mesh(8)
    step = make_sharded_two_level_ib_step(integ, mesh, shard_window=True)
    sh = st0
    for _ in range(2):
        sh = step(sh, dt)

    _tree_allclose(ref, sh, rtol=1e-12, atol=1e-12)
    assert not sh.fluid.uf[0].sharding.is_fully_replicated


def test_multilevel_ib_sharded_boxes_matches_single():
    """L-level S4 depth: every box level of the 3-level composite
    INS/IB distributed over the mesh (``shard_boxes=True``) — equal to
    the single-device step."""
    from ibamr_tpu.amr import FineBox
    from ibamr_tpu.amr_ins_multilevel import MultiLevelIBINS
    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.ib import IBMethod
    from ibamr_tpu.models.membrane2d import make_circle_membrane
    from ibamr_tpu.parallel.mesh import make_sharded_multilevel_ib_step

    grid = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    boxes = [FineBox(lo=(8, 8), shape=(16, 16)),
             FineBox(lo=(8, 8), shape=(16, 16))]
    struct = make_circle_membrane(48, 0.08, (0.5, 0.5), stiffness=0.5)
    ib = IBMethod(struct.force_specs(dtype=jnp.float64), kernel="IB_4")
    integ = MultiLevelIBINS(grid, boxes, ib, mu=0.02, proj_tol=1e-10)
    st0 = integ.initialize(jnp.asarray(struct.vertices, jnp.float64))

    dt = 2e-4
    ref = st0
    for _ in range(3):
        ref = integ.step(ref, dt)

    mesh = make_mesh(8)
    step = make_sharded_multilevel_ib_step(integ, mesh, shard_boxes=True)
    sh = st0
    for _ in range(3):
        sh = step(sh, dt)

    _tree_allclose(ref, sh, rtol=1e-12, atol=1e-12)
    for lev in sh.fluid.us:
        for c in lev:
            assert len(c.sharding.device_set) == 8
            assert not c.sharding.is_fully_replicated


@pytest.mark.parametrize("walls", [False, True])
def test_vc_ins_sharded_matches_single(walls):
    """The multiphase VC-INS step (S1 for P22) sharded over the mesh —
    periodic AND wall-bounded — equals the single-device step: the MG
    V-cycle's strided coarsening, the CG psum reductions, the Godunov
    advection, and the reinitialization all partition correctly."""
    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.ins_vc import INSVCStaggeredIntegrator
    from ibamr_tpu.parallel.mesh import make_sharded_vc_step

    n = 32
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    xx = (np.arange(n) + 0.5) / n
    X, Y = np.meshgrid(xx, xx, indexing="ij")
    phi0 = jnp.asarray(
        0.15 - np.sqrt((X - 0.5) ** 2 + (Y - 0.6) ** 2),
        dtype=jnp.float64)
    integ = INSVCStaggeredIntegrator(
        g, rho0=1.0, rho1=10.0, mu0=0.01, mu1=0.02,
        gravity=(0.0, -2.0), sigma=0.1, convective_op_type="upwind",
        reinit_interval=2, cg_tol=1e-10,
        wall_axes=(True, True) if walls else None,
        dtype=jnp.float64)
    st0 = integ.initialize(phi0)

    dt = 5e-4
    ref = st0
    for _ in range(4):                      # crosses a reinit cadence
        ref = integ.step(ref, dt)

    mesh = make_mesh(8)
    step = make_sharded_vc_step(integ, mesh)
    sh = st0
    for _ in range(4):
        sh = step(sh, dt)

    _tree_allclose(ref, sh, rtol=1e-12, atol=1e-12)
    assert len(sh.u[0].sharding.device_set) == 8


def test_two_level_ib_sharded_window_s2_markers_matches_single():
    """S4 depth + S2 at the FINE level: the sharded-window composite
    step with the fine-grid marker transfers routed through the
    owner-bucketed ShardedInteraction engine (ppermute halos) — still
    equal to the single-device step. This is the full 'distribute the
    fine-window arrays AND the fine-level marker transfers' composition
    (VERDICT round 3 missing #2)."""
    from ibamr_tpu.amr import FineBox
    from ibamr_tpu.amr_ins import TwoLevelIBINS
    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.ib import IBMethod
    from ibamr_tpu.models.membrane2d import make_circle_membrane
    from ibamr_tpu.parallel.mesh import make_sharded_two_level_ib_step

    n = 32
    grid = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    struct = make_circle_membrane(48, 0.08, (0.5, 0.5), stiffness=0.5)
    ib = IBMethod(struct.force_specs(dtype=jnp.float64), kernel="IB_4")
    box = FineBox(lo=(8, 8), shape=(16, 16))
    integ = TwoLevelIBINS(grid, box, ib, mu=0.02, proj_tol=1e-10)
    st0 = integ.initialize(jnp.asarray(struct.vertices, jnp.float64))

    dt = 2e-4
    ref = st0
    for _ in range(3):
        ref = integ.step(ref, dt)

    mesh = make_mesh(8)
    import warnings

    with warnings.catch_warnings():
        # the S2 engine must actually ENGAGE: a geometry/strategy
        # fallback (UserWarning) would make this test pass vacuously
        # on the GSPMD path
        warnings.simplefilter("error", UserWarning)
        step = make_sharded_two_level_ib_step(integ, mesh,
                                              shard_window=True,
                                              sharded_markers=True)
    sh = st0
    for _ in range(3):
        sh = step(sh, dt)

    _tree_allclose(ref, sh, rtol=1e-11, atol=1e-12)
    assert any(not c.sharding.is_fully_replicated for c in sh.fluid.uf)


def test_open_ins_sharded_matches_single(mesh8):
    """The inflow/outflow coupled saddle step (S1 for external flows)
    sharded over the mesh equals the single-device step."""
    from ibamr_tpu.integrators.ins_open import INSOpenIntegrator
    from ibamr_tpu.parallel.mesh import make_sharded_open_ins_step
    from ibamr_tpu.solvers.stokes import channel_bc

    nx, ny = 32, 16
    ins = INSOpenIntegrator((nx, ny), (2.0 / nx, 1.0 / ny),
                            channel_bc(2), mu=0.05, dt=5e-3,
                            bdry={(0, 0, 0): 1.0}, tol=1e-10)
    st0 = ins.initialize()
    ref = st0
    for _ in range(5):
        ref = ins.step(ref)

    step = make_sharded_open_ins_step(ins, mesh8)
    sh = st0
    for _ in range(5):
        sh = step(sh)

    _tree_allclose(ref, sh, rtol=1e-12, atol=1e-13)
    assert len(sh.u[0].sharding.device_set) == 8


def test_ib_open_sharded_matches_single(mesh8):
    """Flow past a target-point body with the open-boundary fluid
    sharded: the coupled IB step equals the single-device step."""
    from ibamr_tpu.integrators.ib import IBMethod
    from ibamr_tpu.integrators.ib_open import IBOpenIntegrator
    from ibamr_tpu.integrators.ins_open import INSOpenIntegrator
    from ibamr_tpu.ops.forces import ForceSpecs
    from ibamr_tpu.parallel.mesh import make_sharded_ib_open_step
    from ibamr_tpu.solvers.stokes import channel_bc

    nx, ny = 32, 16
    ins = INSOpenIntegrator((nx, ny), (2.0 / nx, 1.0 / ny),
                            channel_bc(2), mu=0.02, dt=5e-3,
                            bdry={(0, 0, 0): 0.8}, tol=1e-10,
                            convective_op_type="stabilized_ppm")
    th = 2.0 * np.pi * np.arange(24) / 24
    X0 = jnp.asarray(np.stack([0.7 + 0.12 * np.cos(th),
                               0.5 + 0.12 * np.sin(th)], axis=1))
    ib = IBMethod(ForceSpecs(), kernel="IB_4",
                  force_fn=lambda X, U, t: -40.0 * (X - X0) - U)
    integ = IBOpenIntegrator(ins, ib)
    st0 = integ.initialize(X0)

    ref = st0
    for _ in range(4):
        ref = integ.step(ref)

    step = make_sharded_ib_open_step(integ, mesh8)
    sh = st0
    for _ in range(4):
        sh = step(sh)

    _tree_allclose(ref, sh, rtol=1e-11, atol=1e-12)
    assert len(sh.fluid.u[0].sharding.device_set) == 8


def test_vc_open_outlet_sharded_matches_single():
    """Round-5 composition 3a sharded: the open-outlet VC tank (axis-0
    wall -> outlet assemblies are concatenations, which the SPMD
    partitioner must resolve against the spatially sharded axis)
    equals the single-device step."""
    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.ins_vc import INSVCStaggeredIntegrator
    from ibamr_tpu.parallel.mesh import make_sharded_vc_step

    n = (32, 16)
    g = StaggeredGrid(n=n, x_lo=(0.0, 0.0), x_up=(2.0, 1.0))
    still = 0.5
    z = (np.arange(n[1]) + 0.5) / n[1]
    phi0 = jnp.asarray(np.broadcast_to(z[None, :] - still, n),
                       dtype=jnp.float64)
    integ = INSVCStaggeredIntegrator(
        g, rho0=10.0, rho1=1.0, mu0=1e-3, mu1=1e-4,
        gravity=(0.0, -2.0), wall_axes=(False, True),
        open_outlet=True, still_level=still, cg_tol=1e-11,
        dtype=jnp.float64)
    st0 = integ.initialize(phi0)
    # a blob of momentum headed for the outlet
    u0 = np.zeros(n)
    u0[18:26, 4:12] = 0.2
    st0 = st0._replace(u=(jnp.asarray(u0), st0.u[1]))

    dt = 2e-3
    ref = st0
    for _ in range(4):
        ref = integ.step(ref, dt)

    mesh = make_mesh(8)
    step = make_sharded_vc_step(integ, mesh)
    sh = st0
    for _ in range(4):
        sh = step(sh, dt)

    _tree_allclose(ref, sh, rtol=1e-12, atol=1e-12)
    assert len(sh.u[0].sharding.device_set) == 8


def test_les_two_level_sharded_matches_single():
    """Round-5 composition 3b sharded: LES in a refined window with the
    coarse level distributed (eddy forces follow their level's
    sharding; the window stays replicated)."""
    from ibamr_tpu.amr import FineBox
    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.parallel.mesh import make_sharded_les_two_level_step
    from ibamr_tpu.physics.turbulence import TwoLevelSmagorinskyINS

    n = 32
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    box = FineBox(lo=(8, 8), shape=(16, 16))
    les = TwoLevelSmagorinskyINS(g, box, mu=1e-3, rho=1.0, cs=0.3)
    xn = np.arange(n + 1) / n
    XN, YN = np.meshgrid(xn, xn, indexing="ij")
    psi = 0.2 * np.exp(-((XN - 0.5) ** 2 + (YN - 0.5) ** 2)
                       / (2 * 0.1 ** 2))
    u = jnp.asarray((psi[:-1, 1:] - psi[:-1, :-1]) * n,
                    dtype=jnp.float64)
    v = jnp.asarray(-(psi[1:, :-1] - psi[:-1, :-1]) * n,
                    dtype=jnp.float64)
    st0 = les.initialize((u, v))

    dt = 2e-3
    ref = st0
    for _ in range(3):
        ref = les.step(ref, dt)

    mesh = make_mesh(8)
    step = make_sharded_les_two_level_step(les, mesh)
    sh = st0
    for _ in range(3):
        sh = step(sh, dt)

    _tree_allclose(ref, sh, rtol=1e-11, atol=1e-11)
    assert len(sh.uc[0].sharding.device_set) == 8


def test_cib_walled_sharded_matches_single():
    """Round-5 composition 3c sharded: the walled-domain CIB
    constraint solve with the nested saddle solves' grid fields
    distributed equals the single-device result."""
    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators import cib
    from ibamr_tpu.parallel.mesh import make_sharded_cib_constraint

    n = 32
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    X = cib.make_disc((0.5, 0.45), 0.12, 16, dtype=jnp.float64)
    bodies = cib.RigidBodies(body_id=jnp.zeros(16, dtype=jnp.int32),
                             n_bodies=1)
    cm = cib.CIBMethod(g, bodies, mu=1.0, cg_tol=1e-9,
                       cg_maxiter=200, domain="walled")
    U = jnp.asarray([[1.0, 0.0, 0.0]], dtype=jnp.float64)
    lam_ref, FT_ref, info_ref = cm.solve_constraint(X, U)
    assert bool(info_ref.converged)

    mesh = make_mesh(8)
    solve = make_sharded_cib_constraint(cm, mesh)
    lam_sh, FT_sh, info_sh = solve(X, U)
    assert bool(info_sh.converged)
    # lambda has near-null mobility components (delta-regularized M),
    # so compare the WELL-CONDITIONED observables: the net force/
    # torque and the constraint residual M lam - K U, not raw lambda
    np.testing.assert_allclose(np.asarray(FT_sh), np.asarray(FT_ref),
                               rtol=1e-6, atol=1e-8)
    rhs = cib.rigid_velocity(X, bodies, U)
    res_sh = float(jnp.max(jnp.abs(cm.mobility_apply(X, lam_sh)
                                   - rhs)))
    res_ref = float(jnp.max(jnp.abs(cm.mobility_apply(X, lam_ref)
                                    - rhs)))
    assert res_sh < 10.0 * max(res_ref, 1e-9), (res_sh, res_ref)


# ---------------------------------------------------------------------------
# Cross-mesh checkpoint/restore (round 5, VERDICT item 5: the
# RestartManager's rank-count-independent restart, SURVEY.md §5.4)
# ---------------------------------------------------------------------------

def test_cross_mesh_restart_flagship_1_to_8_and_back(tmp_path):
    """Save the flagship coupled-IB state from a SINGLE-device run,
    restore onto the 8-device mesh (with S2 sharded-marker transfers
    active) and continue; then save from the sharded run and restore
    back onto one device. Both continuations must match the unbroken
    single-device trajectory — the reference restarts on a different
    rank count with re-decomposed data, this is the mesh analog."""
    from ibamr_tpu.utils.checkpoint import (restore_checkpoint,
                                            save_checkpoint)

    integ, state0 = build_shell_example(
        n_cells=16, n_lat=8, n_lon=8, dtype=jnp.float64)
    dt = 1e-3
    step1 = jax.jit(lambda s, d: integ.step(s, d))

    # unbroken single-device reference: 6 steps
    ref = state0
    for _ in range(6):
        ref = step1(ref, dt)

    # leg 1: 3 single-device steps -> checkpoint
    mid = state0
    for _ in range(3):
        mid = step1(mid, dt)
    d1 = str(tmp_path / "ck1")
    save_checkpoint(d1, mid, step=3)

    # leg 2: restore ONTO THE 8-DEVICE MESH (template placed there),
    # continue 3 sharded steps with S2 marker transfers
    mesh = make_mesh(8, max_axes=2)
    template = place_state(state0, integ.ins.grid, mesh)
    restored, k, _ = restore_checkpoint(d1, template)
    assert k == 3
    assert len(restored.ins.u[0].sharding.device_set) == 8
    stepN = make_sharded_ib_step(integ, mesh, sharded_markers=True)
    sh = restored
    for _ in range(3):
        sh = stepN(sh, dt)
    _tree_allclose(ref, sh, rtol=1e-10, atol=1e-11)

    # leg 3: save the state the SHARDED computation produced (its
    # leaves carry the step's with_sharding_constraint layouts, not a
    # fresh device_put), restore back onto ONE device — 8 -> 1; it
    # must equal the unbroken single-device endpoint directly
    d2 = str(tmp_path / "ck2")
    save_checkpoint(d2, sh, step=6)
    back, k2, _ = restore_checkpoint(d2, state0)
    assert k2 == 6
    assert len(back.ins.u[0].sharding.device_set) == 1
    _tree_allclose(ref, back, rtol=1e-10, atol=1e-11)
    # and it keeps stepping on one device
    one = step1(back, dt)
    assert bool(jnp.all(jnp.isfinite(one.X)))


def test_cross_mesh_restart_composite_two_level(tmp_path):
    """Composite two-level IB state across mesh sizes: save from a
    single-device composite run, restore onto the mesh (coarse level
    sharded, window replicated) and continue; the continuation matches
    the unbroken single-device run."""
    from ibamr_tpu.amr import FineBox
    from ibamr_tpu.amr_ins import TwoLevelIBINS
    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.ib import IBMethod
    from ibamr_tpu.ops.forces import ForceSpecs
    from ibamr_tpu.parallel.mesh import make_sharded_two_level_ib_step
    from ibamr_tpu.utils.checkpoint import (restore_checkpoint,
                                            save_checkpoint)

    g = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    box = FineBox(lo=(8, 8), shape=(16, 16))
    th = np.linspace(0, 2 * np.pi, 17)[:-1]
    X0 = np.stack([0.5 + 0.08 * np.cos(th),
                   0.5 + 0.08 * np.sin(th)], -1)
    X0j = jnp.asarray(X0, dtype=jnp.float64)
    ib = IBMethod(ForceSpecs(), kernel="IB_4",
                  force_fn=lambda X, U, t: -40.0 * (X - X0j) - U)
    integ = TwoLevelIBINS(g, box, ib, mu=0.02)
    st0 = integ.initialize(X0j)
    dt = 1e-3
    step1 = jax.jit(lambda s, d: integ.step(s, d))

    ref = st0
    for _ in range(6):
        ref = step1(ref, dt)

    mid = st0
    for _ in range(3):
        mid = step1(mid, dt)
    d1 = str(tmp_path / "ck")
    save_checkpoint(d1, mid, step=3)

    # restore with RE-SHARDING onto the mesh (the sharding_fn hook is
    # the rank-count-independent re-decomposition): coarse level
    # spatially sharded, window/markers replicated
    from ibamr_tpu.parallel.mesh import grid_pspec
    from jax.sharding import NamedSharding, PartitionSpec as PSpec

    mesh = make_mesh(8, max_axes=2)
    spatial = NamedSharding(mesh, grid_pspec(mesh, 2))
    repl = NamedSharding(mesh, PSpec())

    def resharder(key, arr):
        sh = spatial if "fluid/uc" in key else repl
        return jax.device_put(jnp.asarray(arr), sh)

    restored, k, _ = restore_checkpoint(d1, st0,
                                        sharding_fn=resharder)
    assert k == 3
    assert len(restored.fluid.uc[0].sharding.device_set) == 8
    stepN = make_sharded_two_level_ib_step(integ, mesh)
    sh = restored
    for _ in range(3):
        sh = stepN(sh, dt)
    _tree_allclose(ref, sh, rtol=1e-10, atol=1e-11)


def test_make_sharded_step_dispatch():
    """The ONE sharding entry point (round 5, VERDICT item 7):
    make_sharded_step dispatches every registered family and its
    result equals the family factory's."""
    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
    from ibamr_tpu.integrators.ins_vc import INSVCStaggeredIntegrator
    from ibamr_tpu.parallel.mesh import make_sharded_step

    mesh = make_mesh(8)
    g = StaggeredGrid(n=(16, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))

    ins = INSStaggeredIntegrator(g, mu=0.02, dtype=jnp.float64)
    st = ins.initialize()
    out = make_sharded_step(ins, mesh)(st, 1e-3)
    ref = make_sharded_ins_step(ins, mesh)(st, 1e-3)
    _tree_allclose(ref, out, rtol=1e-14, atol=1e-14)

    vc = INSVCStaggeredIntegrator(g, rho0=1.0, rho1=2.0, mu0=0.01,
                                  mu1=0.01, dtype=jnp.float64,
                                  precond="fft")
    xx = (np.arange(16) + 0.5) / 16
    X, Y = np.meshgrid(xx, xx, indexing="ij")
    phi = jnp.asarray(0.1 - np.sqrt((X - 0.5) ** 2 + (Y - 0.5) ** 2))
    stv = vc.initialize(phi)
    outv = make_sharded_step(vc, mesh)(stv, 1e-3)
    assert bool(jnp.all(jnp.isfinite(outv.u[0])))
    assert len(outv.u[0].sharding.device_set) == 8

    # unknown single-level integrators ride the generic wrapper
    class Minimal:
        grid = g

        def step(self, s, dt):
            return tuple(c + dt for c in s)

    m_out = make_sharded_step(Minimal(), mesh)(
        tuple(jnp.zeros((16, 16)) for _ in range(2)), 1e-3)
    assert float(m_out[0][0, 0]) == 1e-3

    with np.testing.assert_raises(TypeError):
        make_sharded_step(object(), mesh)


def test_wall_bounded_ib_sharded_matches_single():
    """IB over a WALL-BOUNDED fluid sharded over the mesh: the seam
    consolidation routes walled INS through _prepare_fluid (fastdiag
    matmuls distributed by the partitioner) instead of raising — this
    pins that the enabled path is exact (round-5 code review)."""
    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.ib import IBExplicitIntegrator, IBMethod
    from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
    from ibamr_tpu.ops.forces import ForceSpecs

    g = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    ins = INSStaggeredIntegrator(g, mu=0.02, wall_axes=(True, True),
                                 dtype=jnp.float64)
    th = np.linspace(0, 2 * np.pi, 17)[:-1]
    X0 = jnp.asarray(np.stack([0.5 + 0.1 * np.cos(th),
                               0.5 + 0.1 * np.sin(th)], -1))
    ib = IBMethod(ForceSpecs(), kernel="IB_4",
                  force_fn=lambda X, U, t: -30.0 * (X - X0))
    integ = IBExplicitIntegrator(ins, ib)
    st0 = integ.initialize(X0)

    step1 = jax.jit(lambda s, d: integ.step(s, d))
    ref = st0
    for _ in range(3):
        ref = step1(ref, 1e-3)

    mesh = make_mesh(8)
    stepN = make_sharded_ib_step(integ, mesh)
    sh = st0
    for _ in range(3):
        sh = stepN(sh, 1e-3)
    _tree_allclose(ref, sh, rtol=1e-12, atol=1e-12)


def test_make_sharded_step_subclass_inherits_family():
    """MRO dispatch: a SUBCLASS of a registered family gets the
    family's prepare seam (the pencil-solver swap), not the bare
    generic wrapper (round-5 code review)."""
    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
    from ibamr_tpu.parallel.mesh import make_sharded_step

    class MyINS(INSStaggeredIntegrator):
        pass

    g = StaggeredGrid(n=(16, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    ins = MyINS(g, mu=0.02, dtype=jnp.float64)
    mesh = make_mesh(8)
    st = ins.initialize()
    ref = make_sharded_ins_step(ins, mesh)(st, 1e-3)
    out = make_sharded_step(ins, mesh)(st, 1e-3)
    _tree_allclose(ref, out, rtol=1e-14, atol=1e-14)
