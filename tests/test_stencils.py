"""Stage-1 acceptance (SURVEY.md §7.2 stage 1): MMS convergence of the MAC
vector calculus vs analytic fields, exact discrete identities, adjointness.

The manufactured fields are periodic trigonometric polynomials; the NumPy
oracle is the analytic derivative evaluated at the correct staggering.
"""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import stencils
from ibamr_tpu.ops.norms import dot, max_norm

TWO_PI = 2.0 * math.pi


F64 = jnp.float64


def _grid2(n):
    return StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))


def _grid3(n):
    return StaggeredGrid(n=(n, n, n), x_lo=(0.0, 0.0, 0.0), x_up=(1.0, 1.0, 1.0))


def _err_ratio(errs):
    """Average observed convergence order from successive halvings."""
    orders = [math.log2(errs[i] / errs[i + 1]) for i in range(len(errs) - 1)]
    return sum(orders) / len(orders)


def test_divergence_convergence_2d():
    errs = []
    for n in (16, 32, 64):
        g = _grid2(n)
        xf, yc = g.face_centers(0, F64)
        xc, yf = g.face_centers(1, F64)
        u = jnp.sin(TWO_PI * xf) * jnp.cos(TWO_PI * yc) + 0 * yc
        v = jnp.cos(TWO_PI * xc) * jnp.sin(TWO_PI * yf) + 0 * xc
        div = stencils.divergence((u, v), g.dx)
        cx, cy = g.cell_centers(F64)
        exact = 2 * TWO_PI * jnp.cos(TWO_PI * cx) * jnp.cos(TWO_PI * cy)
        errs.append(float(max_norm(div - exact)))
    assert _err_ratio(errs) > 1.9


def test_gradient_convergence_2d():
    errs = []
    for n in (16, 32, 64):
        g = _grid2(n)
        cx, cy = g.cell_centers(F64)
        p = jnp.sin(TWO_PI * cx) * jnp.sin(TWO_PI * cy)
        gx, gy = stencils.gradient(p, g.dx)
        xf, yc = g.face_centers(0, F64)
        exact_gx = TWO_PI * jnp.cos(TWO_PI * xf) * jnp.sin(TWO_PI * yc)
        errs.append(float(max_norm(gx - exact_gx)))
    assert _err_ratio(errs) > 1.9


def test_laplacian_convergence_3d():
    errs = []
    for n in (16, 32, 64):
        g = _grid3(n)
        cx, cy, cz = g.cell_centers(F64)
        p = jnp.sin(TWO_PI * cx) * jnp.sin(TWO_PI * cy) * jnp.sin(TWO_PI * cz)
        lap = stencils.laplacian(p, g.dx)
        exact = -3 * TWO_PI ** 2 * p
        errs.append(float(max_norm(lap - exact)))
    assert _err_ratio(errs) > 1.9


def test_div_grad_equals_laplacian_exactly():
    g = _grid2(32)
    rng = np.random.default_rng(0)
    p = jnp.asarray(rng.standard_normal(g.n), dtype=jnp.float64)
    lhs = stencils.divergence(stencils.gradient(p, g.dx), g.dx)
    rhs = stencils.laplacian(p, g.dx)
    np.testing.assert_allclose(np.asarray(lhs), np.asarray(rhs),
                               rtol=0, atol=1e-10)


def test_gradient_is_negative_adjoint_of_divergence():
    """<grad p, u> = -<p, div u> on the periodic MAC grid (exact identity)."""
    for gridmk in (_grid2, _grid3):
        g = gridmk(16)
        rng = np.random.default_rng(1)
        p = jnp.asarray(rng.standard_normal(g.n), dtype=jnp.float32)
        u = tuple(jnp.asarray(rng.standard_normal(g.n), dtype=jnp.float32)
                  for _ in range(g.dim))
        lhs = float(dot(stencils.gradient(p, g.dx), u, g.cell_volume))
        rhs = -float(dot(p, stencils.divergence(u, g.dx), g.cell_volume))
        assert lhs == pytest.approx(rhs, rel=1e-4, abs=1e-5)


def test_cc_fc_interp_preserves_constants_and_converges():
    g = _grid2(32)
    c = jnp.full(g.n, 3.25, dtype=jnp.float32)
    for comp in stencils.cc_to_fc(c):
        np.testing.assert_allclose(np.asarray(comp), 3.25, rtol=1e-6)
    for comp in stencils.fc_to_cc((c, c)):
        np.testing.assert_allclose(np.asarray(comp), 3.25, rtol=1e-6)

    errs = []
    for n in (16, 32, 64):
        g = _grid2(n)
        cx, cy = g.cell_centers(F64)
        p = jnp.sin(TWO_PI * cx) * jnp.cos(TWO_PI * cy)
        px = stencils.cc_to_fc(p)[0]
        xf, yc = g.face_centers(0, F64)
        exact = jnp.sin(TWO_PI * xf) * jnp.cos(TWO_PI * yc)
        errs.append(float(max_norm(px - exact)))
    assert _err_ratio(errs) > 1.9


def test_curl_2d_convergence():
    errs = []
    for n in (16, 32, 64):
        g = _grid2(n)
        xf, yc = g.face_centers(0, F64)
        xc, yf = g.face_centers(1, F64)
        # streamfunction psi = sin(2pi x) sin(2pi y): u = dpsi/dy, v = -dpsi/dx
        u = TWO_PI * jnp.sin(TWO_PI * xf) * jnp.cos(TWO_PI * yc)
        v = -TWO_PI * jnp.cos(TWO_PI * xc) * jnp.sin(TWO_PI * yf)
        w = stencils.curl_2d_node((u, v), g.dx)
        xn = g.face_coords_1d(0, F64)[:, None]
        yn = g.face_coords_1d(1, F64)[None, :]
        exact = 2 * TWO_PI ** 2 * jnp.sin(TWO_PI * xn) * jnp.sin(TWO_PI * yn)
        errs.append(float(max_norm(w - exact)))
    assert _err_ratio(errs) > 1.9


def test_fc_component_to_fc_linear_exact():
    """The 4-point cross average reproduces linear fields exactly up to
    periodic wrap; test on interior away from the wrap."""
    g = _grid2(16)
    xf, yc = g.face_centers(0, F64)
    u = (2.0 * xf + 3.0 * yc) + 0.0 * yc
    u_at_v = stencils.fc_component_to_fc((u, u), src=0, dst=1)
    xc, yf = g.face_centers(1, F64)
    exact = 2.0 * xc + 3.0 * yf
    err = np.abs(np.asarray(u_at_v - exact))[2:-2, 2:-2]
    assert err.max() < 1e-5


def test_position_to_index():
    g = StaggeredGrid(n=(8, 8), x_lo=(0.0, -1.0), x_up=(2.0, 1.0))
    idx = g.position_to_index(jnp.array([[0.125, -0.875]]))
    np.testing.assert_allclose(np.asarray(idx), [[0.5, 0.5]], atol=1e-6)
