"""Flight recorder + deterministic replay + precision escalation +
invariant sentinels (PR 5).

The contract under test: EVERY incident the supervisor records is
replayable — a bounded pre-chunk ring (host copies, donation-safe)
plus a run fingerprint is enough for ``tools/replay.py`` to re-execute
the failing chunk BITWISE in a fresh context and classify what the
failure depends on (engine, spectral precision, dt). On top of it:
the strided f64 shadow audit that turns silent bf16 drift into a
``PrecisionDrift`` incident the supervisor cures by walking
``PRECISION_FALLBACKS`` (dt untouched), and the two new fused vitals
slots (enclosed volume, momentum budget) that catch secular invariant
leaks while every state leaf is still finite.
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
from ibamr_tpu.solvers.escalation import (PRECISION_FALLBACKS,
                                          PRECISION_LEVELS,
                                          PrecisionDrift, ShadowAuditor,
                                          precision_chain,
                                          precision_level_name)
from ibamr_tpu.utils.flight_recorder import (FlightRecorder,
                                             describe_integrator,
                                             factory_spec)
from ibamr_tpu.utils.health import HealthDegraded, HealthProbe
from ibamr_tpu.utils.hierarchy_driver import (HierarchyDriver, RunConfig,
                                              SimulationDiverged)
from ibamr_tpu.utils.supervisor import ResilientDriver
from tools.fault_injection import (ACTIVE_INJECTORS, _bare_bf16_drift,
                                   apply_recorded_injectors,
                                   nan_injector_step, recorded,
                                   volume_leak_injector)
from tools.replay import (newest_capsule, read_incidents, replay)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ins(n=16, mu=0.05, **kw):
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    return INSStaggeredIntegrator(g, rho=1.0, mu=mu, **kw)


def _tg_state(integ, mean=0.0):
    import math
    g = integ.grid
    dtype = integ.dtype
    xf, yc = g.face_centers(0, dtype)
    xc, yf = g.face_centers(1, dtype)
    u = jnp.sin(2 * math.pi * xf) * jnp.cos(2 * math.pi * yc) \
        + mean + 0 * yc
    v = -jnp.cos(2 * math.pi * xc) * jnp.sin(2 * math.pi * yf) + 0 * xc
    return integ.initialize(u0_arrays=(u, v))


# ---------------------------------------------------------------------------
# precision chain + shadow audit
# ---------------------------------------------------------------------------

def test_precision_chain_shape():
    """PRECISION_FALLBACKS is the ESCALATION_FALLBACKS shape applied to
    the spectral_dtype knob: linear bf16 -> f32 -> f64, names assignable
    straight onto ``integ.spectral_dtype``."""
    assert precision_chain("bf16") == list(PRECISION_LEVELS)
    assert precision_chain("f32") == ["f32", "f64"]
    assert PRECISION_FALLBACKS["f64"] is None
    with pytest.raises(KeyError):
        precision_chain("f16")
    assert precision_level_name(None) == "f32"
    assert precision_level_name("bf16") == "bf16"
    assert precision_level_name(jnp.float64) == "f64"
    # "f32" canonicalizes to None (native precision) and round-trips
    assert precision_level_name("f32") == "f32"


def test_shadow_audit_clean_vs_biased():
    """The f64 shadow audit passes the NATURAL bf16 drift (~3e-3,
    pinned an order of magnitude under the default bound) and trips
    with a structured payload once the spectral rounding is biased."""
    integ = _ins(spectral_dtype="bf16")
    st = _tg_state(integ)
    aud = ShadowAuditor(every=1, bound=0.02)
    rec = aud.maybe_audit(integ, st, 1e-3, step=1)
    assert rec is not None and rec["drift"] < 0.02
    assert aud.audits == 1 and aud.last is rec

    with _bare_bf16_drift(scale=0.35):
        with pytest.raises(PrecisionDrift) as ei:
            aud.audit(integ, st, 1e-3, step=7)
    e = ei.value
    assert e.kind == "precision_drift" and e.step == 7
    payload = e.incident_payload()
    assert payload["drift"] > payload["bound"]
    assert payload["spectral_dtype"] == "bf16"
    assert e.bad_leaves == []            # nothing is non-finite

    # strided cadence: every=4 audits only every 4th chunk
    aud4 = ShadowAuditor(every=4, bound=0.02)
    hits = [aud4.maybe_audit(integ, st, 1e-3, step=i) is not None
            for i in range(1, 9)]
    assert hits == [False, False, False, True,
                    False, False, False, True]


def test_audit_rides_driver_without_retrace():
    """Wired into the driver the audit runs OUTSIDE the jitted chunk:
    one compiled trace per chunk shape, unchanged by auditing."""
    integ = _ins(spectral_dtype="bf16")
    st = _tg_state(integ)
    cfg = RunConfig(dt=1e-3, num_steps=8, health_interval=2)
    aud = ShadowAuditor(every=2, bound=0.5)   # loose: never trips
    drv = HierarchyDriver(integ, cfg, shadow_audit=aud)
    drv.run(st)
    assert aud.audits == 2                    # 4 chunks, every=2
    assert set(drv.trace_counts.values()) == {1}


# ---------------------------------------------------------------------------
# invariant sentinels (vitals slots 5-6)
# ---------------------------------------------------------------------------

def test_vitals_seven_slots_and_backward_unpack():
    integ = _ins()
    st = _tg_state(integ)
    probe = HealthProbe.for_integrator(integ)
    v = np.asarray(jax.jit(probe.measure)(st, 1e-3))
    assert v.shape == (len(HealthProbe.VITALS_FIELDS),)
    d = HealthProbe.unpack(v)
    assert np.isnan(d["vol"])            # no volume sentinel on plain INS
    assert np.isfinite(d["budget"])      # momentum budget is derived
    # a v2 5-float vitals vector still unpacks: trailing slots read NaN
    old = HealthProbe.unpack(np.ones(5, np.float32))
    assert old["func"] == 1.0
    assert np.isnan(old["vol"]) and np.isnan(old["budget"])


def test_volume_sentinel_trips_on_membrane_leak():
    """An injected secular membrane contraction (every leaf finite,
    velocity/divergence unremarkable) is caught by the enclosed-volume
    vitals slot, and the measured drift rides the HealthDegraded
    incident payload."""
    from ibamr_tpu.models.membrane2d import build_membrane_example

    integ, st0 = build_membrane_example(n_cells=16, num_markers=32)
    probe = HealthProbe.for_integrator(integ, vol_drift_fatal=0.05)
    assert probe.volume_fn is not None   # auto-derived for 2D IB
    cfg = RunConfig(dt=1e-4, num_steps=8, health_interval=2)
    drv = HierarchyDriver(
        integ, cfg,
        step_fn=volume_leak_injector(integ.step, rate=0.05,
                                     leaf_path="X"),
        health_probe=probe)
    with pytest.raises(HealthDegraded) as ei:
        drv.run(st0)
    e = ei.value
    assert any("vol drifted" in r for r in e.reasons)
    assert e.vitals["vol_drift"] > 0.05  # measured drift in the payload
    assert e.incident_payload()["vitals"]["vol_drift"] > 0.05
    assert set(drv.trace_counts.values()) == {1}   # sentinel is fused


def test_budget_sentinel_trips_on_momentum_injection():
    """The momentum-budget slot catches a finite amplification that
    conserves nothing: with a mean flow, multiplying u inflates the
    conserved net momentum and the relative-drift triage fires while
    the state is still finite everywhere."""
    from tools.fault_injection import growth_injector_step

    integ = _ins()
    st0 = _tg_state(integ, mean=0.5)
    probe = HealthProbe.for_integrator(integ, budget_drift_fatal=0.1)
    cfg = RunConfig(dt=1e-3, num_steps=8, health_interval=2)
    drv = HierarchyDriver(
        integ, cfg,
        step_fn=growth_injector_step(integ.step, rate=1.2,
                                     leaf_path="u"),
        health_probe=probe)
    with pytest.raises(HealthDegraded) as ei:
        drv.run(st0)
    assert any("budget drifted" in r for r in ei.value.reasons)
    assert ei.value.vitals["finite"] == 1.0   # caught while finite


# ---------------------------------------------------------------------------
# flight recorder: ring, fingerprint, donation safety, overhead
# ---------------------------------------------------------------------------

def test_recorder_ring_and_fingerprint():
    integ = _ins(spectral_dtype="bf16")
    st = _tg_state(integ)
    cfg = RunConfig(dt=1e-3, num_steps=12, health_interval=2)
    rec = FlightRecorder(capacity=3)
    drv = HierarchyDriver(integ, cfg, recorder=rec)
    drv.run(st)
    assert len(rec.ring) == 3              # bounded ring: 6 chunks
    assert [e.step for e in rec.ring] == [6, 8, 10]
    entry = rec.entry_for_step(9)             # newest entry covering 9
    assert entry.step == 8 and entry.covers(9)
    assert isinstance(next(iter(entry.arrays.values())), np.ndarray)

    with recorded("bf16_drift", scale=0.25):
        fp = rec.fingerprint(driver=drv)
    assert fp["spectral_dtype"] == "bf16"
    assert fp["integrator"]["kind"] == "ins"
    assert fp["injectors"] == {"bf16_drift": {"scale": 0.25}}
    assert fp["jax_version"] == jax.__version__
    assert fp["config_digest"] and fp["x64"] == bool(
        jax.config.jax_enable_x64)
    json.dumps(fp)                            # must be JSON-safe
    assert ACTIVE_INJECTORS == {}             # context popped


def test_recorder_survives_donated_chunks():
    """Regression (satellite b): with whole-chunk donation the chunk
    consumes the input buffers — the recorder must hold HOST copies
    taken pre-chunk, the run must complete without touching deleted
    buffers, and recording must not add a retrace."""
    integ = _ins(spectral_dtype=None)
    st = _tg_state(integ)
    cfg = RunConfig(dt=1e-3, num_steps=8, health_interval=2, donate=True)
    rec = FlightRecorder(capacity=4)
    drv = HierarchyDriver(integ, cfg, recorder=rec)
    out = drv.run(st)
    assert int(out.k) == 8
    assert set(drv.trace_counts.values()) == {1}
    for entry in rec.ring:
        for arr in entry.arrays.values():     # host copies, all live
            assert isinstance(arr, np.ndarray)
            assert np.isfinite(arr).all()
    # the ring state is restorable even though the device buffers the
    # snapshots were taken from are long donated away
    restored = rec.restore(rec.ring[0])
    assert int(restored.k) == rec.ring[0].step


def test_recorder_overhead_under_two_percent():
    """Snapshotting the pre-chunk state must stay amortized noise: the
    recorder's own accounting vs the measured run wall, warm. The chunk
    length matters — a snapshot is one host copy per chunk, so the test
    uses production-shaped chunks (tens of steps), not the short chunks
    other tests favor for speed."""
    integ = _ins(n=128)
    st = _tg_state(integ)
    cfg = RunConfig(dt=1e-4, num_steps=192, health_interval=96)
    rec = FlightRecorder(capacity=2)
    drv = HierarchyDriver(integ, cfg, recorder=rec)
    drv.run(st)                               # compile + first pass
    o0 = rec.overhead_s
    t0 = time.perf_counter()
    drv.run(st)                               # warm measured pass
    wall = time.perf_counter() - t0
    overhead = rec.overhead_s - o0
    assert overhead < 0.02 * wall, \
        f"recorder overhead {overhead:.4f}s on {wall:.4f}s wall"


# ---------------------------------------------------------------------------
# capsule round-trip + verdicts
# ---------------------------------------------------------------------------

def _record_nan_capsule(directory):
    integ = _ins()
    st0 = _tg_state(integ)
    cfg = RunConfig(dt=1e-3, num_steps=12, restart_interval=4,
                    health_interval=2)
    params = {"at_step": 6, "leaf_path": "u[0]"}
    with recorded("nan", **params):
        drv = HierarchyDriver(
            integ, cfg,
            step_fn=nan_injector_step(integ.step, **params),
            recorder=FlightRecorder(capacity=4))
        sup = ResilientDriver(drv, directory, max_retries=0,
                              handle_signals=False)
        with pytest.raises(SimulationDiverged):
            sup.run(st0)
    return sup


def test_capsule_roundtrip_bitwise(tmp_path):
    """The tentpole pin: a dumped capsule re-executes to the EXACT
    recorded post-chunk digest (per-leaf CRC32s) in fresh traces, and
    the incidents log is schema v3 with the replay pointer."""
    sup = _record_nan_capsule(str(tmp_path))
    rec = sup.incidents[-1]
    assert rec["schema"] == 3 and rec["event"] == "give_up"
    cap = rec["replay"]
    assert cap and os.path.exists(os.path.join(cap, "replay.npz"))
    manifest = json.load(open(os.path.join(cap, "manifest.json")))
    assert manifest["incident"]["kind"] == "divergence"
    assert manifest["chunk"] == {"start_step": 4, "length": 2,
                                 "dt": 1e-3}
    assert manifest["fingerprint"]["injectors"]["nan"]["at_step"] == 6
    assert manifest["post"]["finite"] is False

    res = replay(cap)
    assert res["verdict"] == "reproduced"
    assert res["bitwise"] and res["baseline_failed"]
    # second incident on the same chunk reuses the capsule dir
    assert newest_capsule(str(tmp_path)) == cap


def test_replay_dt_scale_cures_but_stays_reproduced(tmp_path):
    """A dt-scaled re-run that no longer fails is flagged
    ``dt_dependent`` on a ``reproduced`` verdict — dt is a stability
    knob, not a root-cause classification."""
    sup = _record_nan_capsule(str(tmp_path))
    cap = sup.incidents[-1]["replay"]
    # the recorded injector is NOT dt-gated, so a dt-scaled run still
    # hits it: override_failed stays true -> plain reproduced
    res = replay(cap, dt_scale=0.5)
    assert res["verdict"] == "reproduced"
    assert res["override_failed"] is True


@pytest.mark.slow
def test_precision_escalation_end_to_end_drill():
    """ISSUE acceptance drill (dryrun path 18): injected bf16 drift ->
    shadow audit -> capsule -> bf16->f32 escalation with dt unchanged
    -> completion; replay reproduces bitwise and classifies
    ``precision_dependent`` under --override spectral_dtype=f64."""
    from tools.fault_injection import run_replay_smoke

    out = run_replay_smoke()
    assert out["replay_smoke"] == "ok"
    assert out["baseline_verdict"] == "reproduced"
    assert out["override_verdict"] == "precision_dependent"
    assert out["spectral_dtype_after"] == "f32"


@pytest.mark.slow
def test_engine_override_verdict(tmp_path):
    """An engine-gated fault capsule: the baseline (scatter) replay
    reproduces bitwise; swapping the transfer engine via --override
    disarms it -> ``engine_dependent``."""
    from ibamr_tpu.models.shell3d import build_shell_example

    # 16^3: the smallest shell grid where the mxu engine actually
    # builds (8^3 silently degrades to scatter, which would disarm
    # nothing and make the override verdict vacuous)
    kwargs = dict(n_cells=16, n_lat=6, n_lon=8,
                  use_fast_interaction=False)
    integ, st0 = build_shell_example(**kwargs)
    cfg = RunConfig(dt=1e-4, num_steps=4, restart_interval=4,
                    health_interval=2)
    params = {"at_step": 2, "leaf_path": "X", "step_attr": "ins.k"}
    with recorded("engine_nan", engine="scatter", **params):
        drv = HierarchyDriver(
            integ, cfg,
            step_fn=nan_injector_step(integ.step, **params),
            recorder=FlightRecorder(capacity=4, spec=factory_spec(
                "ibamr_tpu.models.shell3d", "build_shell_example",
                **kwargs)))
        sup = ResilientDriver(drv, str(tmp_path), max_retries=0,
                              handle_signals=False)
        with pytest.raises(SimulationDiverged):
            sup.run(st0)
    cap = sup.incidents[-1]["replay"]
    manifest = json.load(open(os.path.join(cap, "manifest.json")))
    assert manifest["fingerprint"]["engine"] == "scatter"
    assert manifest["fingerprint"]["engine_chain"] == ["scatter"]

    base = replay(cap)
    assert base["verdict"] == "reproduced" and base["bitwise"]
    cured = replay(cap, overrides={"engine": "mxu"})
    assert cured["verdict"] == "engine_dependent"
    assert cured["override_failed"] is False


@pytest.mark.slow
def test_cross_mesh_kill_and_replay(tmp_path):
    """Kill-and-replay drill: a 1-device victim records a capsule and
    is SIGKILLed mid-linger; the orphaned capsule replays BITWISE on
    this suite's 8-device mesh — capsules record unsharded host
    arrays, so mesh shape is outside the reproduction contract."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + \
        env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tools.fault_injection",
         "--record-capsule", str(tmp_path)],
        cwd=REPO_ROOT, env=env, stdout=subprocess.PIPE, text=True)
    cap = None
    try:
        for line in proc.stdout:
            if line.startswith("CAPSULE "):
                cap = line.split(None, 1)[1].strip()
                break
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait()
    assert cap and os.path.exists(os.path.join(cap, "manifest.json"))
    manifest = json.load(open(os.path.join(cap, "manifest.json")))
    assert manifest["fingerprint"]["device_count"] == 1
    assert jax.device_count() == 8            # the replay-side mesh
    res = replay(cap)
    assert res["verdict"] == "reproduced" and res["bitwise"]


# ---------------------------------------------------------------------------
# incident log schema v3 / v2 compatibility
# ---------------------------------------------------------------------------

def test_incidents_v3_backward_reads_v2_lines(tmp_path):
    """A log that spans the schema upgrade parses uniformly: v2 lines
    (no ``schema``/``replay``) read as schema=2 with replay=None."""
    path = os.path.join(str(tmp_path), "incidents.jsonl")
    v2 = {"event": "divergence", "step": 6, "retry": 1,
          "rollback_step": 4, "dt": 1e-3, "time": 0.0}
    v3 = {"event": "precision_escalation", "kind": "precision_drift",
          "step": 2, "schema": 3, "replay": "/x/incidents/00000000",
          "time": 1.0}
    with open(path, "w") as f:
        f.write(json.dumps(v2) + "\n\n")      # blank line tolerated
        f.write(json.dumps(v3) + "\n")
    recs = read_incidents(path)
    assert [r["schema"] for r in recs] == [2, 3]
    assert recs[0]["replay"] is None
    assert recs[1]["replay"] == "/x/incidents/00000000"


def test_recorded_injector_registry_and_replay_arming():
    """The registry round-trip tools/replay.py depends on: ``recorded``
    arms/pops, double-arm raises, unknown manifest names raise instead
    of silently replaying clean."""
    with recorded("nan", at_step=3, leaf_path="u"):
        assert ACTIVE_INJECTORS["nan"]["at_step"] == 3
        with pytest.raises(ValueError):
            with recorded("nan", at_step=9):
                pass
    assert "nan" not in ACTIVE_INJECTORS
    with pytest.raises(KeyError):
        with apply_recorded_injectors({"warp_drive": {}}):
            pass
    # a recorded step fault re-arms through the returned wrapper
    integ = _ins()
    st = _tg_state(integ)
    with apply_recorded_injectors(
            {"nan": {"at_step": 1, "leaf_path": "u[0]"}}) as wrap:
        stepped = wrap(integ.step)(st, 1e-3)
    assert not bool(jnp.isfinite(stepped.u[0]).all())


def test_describe_integrator_rebuild_roundtrip():
    """The introspected ins spec is sufficient to rebuild an equivalent
    integrator (the replay 'ins' path)."""
    from tools.replay import rebuild

    integ = _ins(spectral_dtype="bf16")
    spec = describe_integrator(integ)
    assert spec["kind"] == "ins" and spec["spectral_dtype"] == "bf16"
    re_integ, template = rebuild(
        {"fingerprint": {"integrator": spec}})
    assert re_integ.grid.n == integ.grid.n
    assert re_integ.spectral_dtype is integ.spectral_dtype
    assert jax.tree_util.tree_structure(template) \
        == jax.tree_util.tree_structure(integ.initialize())
    # overriding precision at rebuild time walks the spectral knob
    esc, _ = rebuild({"fingerprint": {"integrator": spec}},
                     overrides={"spectral_dtype": "f32"})
    assert esc.spectral_dtype is None         # f32 == native precision
