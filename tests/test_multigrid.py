"""Geometric multigrid (T8): convergence, BC menu, variable coefficient.

Oracle strategy: manufacture the right-hand side by applying the SAME
discrete operator to a known field, so the solver must reproduce that
field to solver tolerance (exact-inverse testing, no truncation error in
the loop) — then separately check textbook grid-independent V-cycle
convergence, the property that distinguishes multigrid from plain
relaxation (reference: FAC is O(N), SURVEY.md §6)."""

import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu import bc as bcmod
from ibamr_tpu.bc import DomainBC, AxisBC, SideBC, dirichlet_axis, \
    neumann_axis, periodic_axis, robin_axis
from ibamr_tpu.solvers import fft
from ibamr_tpu.solvers.multigrid import (PoissonMultigrid, _apply_op,
                                         homogeneous_bc,
                                         prolong_linear,
                                         restrict_full_weighting)


def _cell_coords(n, lo=0.0, hi=1.0):
    h = (hi - lo) / n
    return lo + (np.arange(n) + 0.5) * h, h


def test_periodic_matches_fft():
    n = 32
    x, h = _cell_coords(n)
    X, Y = np.meshgrid(x, x, indexing="ij")
    f = np.sin(2 * np.pi * X) * np.cos(4 * np.pi * Y)
    f = jnp.asarray(f)
    bc = DomainBC.periodic(2)
    mg = PoissonMultigrid((n, n), bc, (h, h))
    sol = mg.solve(f, tol=1e-11)
    p_fft = fft.solve_poisson_periodic(f, (h, h))
    assert sol.converged
    assert np.max(np.abs(np.asarray(sol.x - p_fft))) < 1e-8


@pytest.mark.parametrize("n", [16, 32, 64])
def test_grid_independent_convergence(n):
    """V-cycle count to 1e-10 must NOT grow with n (the multigrid
    property). Plain relaxation would need O(n^2) iterations."""
    x, h = _cell_coords(n)
    rng = np.random.default_rng(7)
    f = jnp.asarray(rng.standard_normal((n, n)))
    bc = DomainBC((dirichlet_axis(), dirichlet_axis()))
    mg = PoissonMultigrid((n, n), bc, (h, h))
    sol = mg.solve(f, tol=1e-10)
    assert sol.converged
    assert int(sol.iters) <= 12


def test_dirichlet_exact_inverse():
    n = 48
    x, h = _cell_coords(n)
    X, Y = np.meshgrid(x, x, indexing="ij")
    u = jnp.asarray(np.sin(np.pi * X) * np.sin(2 * np.pi * Y))
    bc = DomainBC((dirichlet_axis(), dirichlet_axis()))
    mg = PoissonMultigrid((n, n), bc, (h, h))
    f = _apply_op(u, mg.levels[0], bc, 0.0, 1.0)
    sol = mg.solve(f, tol=1e-12, maxiter=60)
    assert np.max(np.abs(np.asarray(sol.x - u))) < 1e-9


def test_inhomogeneous_dirichlet_linear():
    """u = x is in the kernel of the discrete Laplacian with exact
    linear ghost extrapolation; inhomogeneous Dirichlet data must
    reproduce it from f=0."""
    n = 32
    x, h = _cell_coords(n)
    bc = DomainBC((dirichlet_axis(0.0, 1.0), neumann_axis()))
    mg = PoissonMultigrid((n, n), bc, (h, h))
    f = jnp.zeros((n, n))
    sol = mg.solve(f, tol=1e-12)
    u_exact = np.broadcast_to(x[:, None], (n, n))
    assert np.max(np.abs(np.asarray(sol.x) - u_exact)) < 1e-9


def test_robin_exact_inverse():
    n = 32
    x, h = _cell_coords(n)
    X, Y = np.meshgrid(x, x, indexing="ij")
    u = jnp.asarray(np.cos(np.pi * X) * (Y ** 2 + 1.0))
    bc = DomainBC((robin_axis(1.0, 2.0, lo=0.3, hi=-0.1),
                   robin_axis(2.0, 1.0, lo=0.0, hi=1.0)))
    mg = PoissonMultigrid((n, n), bc, (h, h))
    f = _apply_op(u, mg.levels[0], bc, 0.0, 1.0)
    sol = mg.solve(f, tol=1e-12, maxiter=60)
    assert sol.converged
    assert np.max(np.abs(np.asarray(sol.x - u))) < 1e-8


def test_helmholtz_implicit_diffusion_form():
    """(I - k lap) u = f — the CN diffusion sub-solve shape."""
    n = 32
    x, h = _cell_coords(n)
    rng = np.random.default_rng(3)
    u = jnp.asarray(rng.standard_normal((n, n)))
    bc = DomainBC((neumann_axis(), dirichlet_axis()))
    k = 0.37
    mg = PoissonMultigrid((n, n), bc, (h, h), alpha=1.0, beta=-k)
    f = _apply_op(u, mg.levels[0], bc, 1.0, -k)
    sol = mg.solve(f, tol=1e-12)
    assert sol.converged
    assert np.max(np.abs(np.asarray(sol.x - u))) < 1e-9


def test_variable_coefficient_poisson():
    """div(D grad u) = f with smoothly varying D, walls: exact-inverse
    check + V-cycle convergence stays multigrid-fast."""
    n = 32
    x, h = _cell_coords(n)
    X, Y = np.meshgrid(x, x, indexing="ij")
    D = jnp.asarray(1.0 + 0.8 * np.sin(2 * np.pi * X) * np.cos(np.pi * Y))
    u = jnp.asarray(np.sin(np.pi * X) * np.sin(np.pi * Y))
    bc = DomainBC((dirichlet_axis(), dirichlet_axis()))
    mg = PoissonMultigrid((n, n), bc, (h, h), D=D)
    f = _apply_op(u, mg.levels[0], bc, 0.0, 1.0)
    sol = mg.solve(f, tol=1e-11, maxiter=60)
    assert sol.converged
    assert int(sol.iters) <= 25
    assert np.max(np.abs(np.asarray(sol.x - u))) < 1e-8


def test_vc_beta_folds_into_coefficient():
    """alpha + beta*div(D grad) must honor beta (folded into D):
    regression for beta being silently dropped on the VC path."""
    n = 32
    x, h = _cell_coords(n)
    X, Y = np.meshgrid(x, x, indexing="ij")
    D = jnp.asarray(1.0 + 0.5 * np.cos(np.pi * X) * Y)
    u = jnp.asarray(np.sin(np.pi * X) * np.sin(np.pi * Y))
    bc = DomainBC((dirichlet_axis(), dirichlet_axis()))
    k = 0.25
    mg = PoissonMultigrid((n, n), bc, (h, h), alpha=1.0, beta=-k, D=D)
    # oracle: the SAME operator with beta pre-folded manually
    mg_ref = PoissonMultigrid((n, n), bc, (h, h), alpha=1.0, D=-k * D)
    f = _apply_op(u, mg_ref.levels[0], bc, 1.0, 1.0)
    sol = mg.solve(f, tol=1e-12)
    assert sol.converged
    assert np.max(np.abs(np.asarray(sol.x - u))) < 1e-9


def test_vc_poisson_3d():
    n = 16
    x, h = _cell_coords(n)
    X, Y, Z = np.meshgrid(x, x, x, indexing="ij")
    D = jnp.asarray(1.0 + 0.5 * np.cos(np.pi * X) * np.sin(np.pi * Z))
    u = jnp.asarray(np.sin(np.pi * X) * Y * np.cos(np.pi * Z / 2))
    bc = DomainBC((dirichlet_axis(), neumann_axis(), dirichlet_axis()))
    mg = PoissonMultigrid((n, n, n), bc, (h, h, h), D=D)
    f = _apply_op(u, mg.levels[0], bc, 0.0, 1.0)
    sol = mg.solve(f, tol=1e-11, maxiter=60)
    assert sol.converged
    assert np.max(np.abs(np.asarray(sol.x - u))) < 1e-8


def test_transfer_operators_partition_of_unity():
    """Restriction preserves constants; prolongation preserves
    constants away from Dirichlet walls (where corrections reflect)."""
    c = jnp.ones((8, 8))
    assert np.allclose(np.asarray(restrict_full_weighting(c)), 1.0)
    bc = DomainBC.periodic(2)
    p = prolong_linear(c, bc, (0.25, 0.25))
    assert p.shape == (16, 16)
    assert np.allclose(np.asarray(p), 1.0)


def test_nullspace_neumann_poisson():
    """All-Neumann Poisson: solvable for mean-zero rhs, returns the
    mean-zero solution."""
    n = 32
    x, h = _cell_coords(n)
    X, Y = np.meshgrid(x, x, indexing="ij")
    u = jnp.asarray(np.cos(np.pi * X) * np.cos(2 * np.pi * Y))
    bc = DomainBC((neumann_axis(), neumann_axis()))
    mg = PoissonMultigrid((n, n), bc, (h, h))
    f = _apply_op(u, mg.levels[0], bc, 0.0, 1.0)
    sol = mg.solve(f, tol=1e-11, maxiter=60)
    assert sol.converged
    err = np.asarray(sol.x - (u - jnp.mean(u)))
    assert np.max(np.abs(err)) < 1e-8
