"""Measured-search autotuner (PR 13 tentpole): ibamr_tpu/tune/.

Space enumeration prunes statically (tile/extent/z-tile geometry, the
wall-BC bf16 refusal, Pallas compile-probe gating) so the runner never
times a candidate that can't ship; trials compile through the AOT
executable cache (the second trial of a family is a HIT — zero
recompiles); winners persist in a schema-v1, provenance-stamped
TUNING_DB.json that models/engine_resolver.py consults with
most-specific-match semantics — and because the resolved name is
fingerprint material, a DB change PRODUCES A NEW SERVE CACHE KEY.
``tools/tune.py check`` is the revalidation gate (exit 0/1/2), and the
committed seed DB itself is tier-1-validated here.
"""

import json
import os
import subprocess
import sys

import pytest

from ibamr_tpu.models.engine_resolver import (DEFAULT_DB_PATH,
                                              RESOLVED_ENGINES)
from ibamr_tpu.tune import db as tdb
from ibamr_tpu.tune.runner import TrialResult, run_trial
from ibamr_tpu.tune.space import Candidate, enumerate_space

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SUPPORT = 4                          # the real IB_4 half-width


# ---------------------------------------------------------------------------
# space: enumeration + static pruning
# ---------------------------------------------------------------------------

def test_space_static_geometry_pruning():
    engines = ("scatter", "packed", "packed3")
    # non-8-divisible xy: every non-scatter candidate pruned
    cands, pruned = enumerate_space((12, 12, 12), 4096, _SUPPORT,
                                    engines=engines,
                                    spectral_dtypes=("f32",),
                                    chunk_lengths=(1,))
    assert {c.engine for c in cands} == {"scatter"}
    assert all("8-tile" in r for c, r in pruned)
    # eligible xy but no valid packed3 z tile (12 % 8 == 4)
    cands, pruned = enumerate_space((16, 16, 12), 4096, _SUPPORT,
                                    engines=engines,
                                    spectral_dtypes=("f32",),
                                    chunk_lengths=(1,))
    assert {c.engine for c in cands} == {"scatter", "packed"}
    assert any("z tile" in r for c, r in pruned
               if c.engine == "packed3")
    # every grid point is accounted for, nothing silently dropped
    total = len(engines) * 1 * 1
    assert len(cands) + len(pruned) == total


def test_space_small_marker_configs_keep_packed():
    # the n_markers >= 4096 promotion heuristic is exactly what the
    # tuner replaces with measurement — it must NOT prune
    cands, _ = enumerate_space((16, 16, 16), 128, _SUPPORT,
                               engines=("scatter", "packed"),
                               spectral_dtypes=("f32",),
                               chunk_lengths=(1,))
    assert {c.engine for c in cands} == {"scatter", "packed"}


def test_space_bf16_wall_bc_refusal():
    cands, pruned = enumerate_space((16, 16, 16), 128, _SUPPORT,
                                    engines=("scatter", "packed"),
                                    spectral_dtypes=("f32", "bf16"),
                                    chunk_lengths=(1,),
                                    bc="dirichlet")
    assert all(c.spectral_dtype == "f32" for c in cands)
    bf16_pruned = [(c, r) for c, r in pruned
                   if c.spectral_dtype == "bf16"]
    assert len(bf16_pruned) == 2
    assert all("periodic-only" in r for _, r in bf16_pruned)


def test_space_probe_gating_memoized():
    calls = []

    def probe(engine):
        calls.append(engine)
        raise RuntimeError("pallas lowering died")

    cands, pruned = enumerate_space(
        (16, 16, 16), 128, _SUPPORT,
        engines=("scatter", "pallas_packed"),
        spectral_dtypes=("f32", "bf16"), chunk_lengths=(1, 4),
        probe_fn=probe)
    # probe called ONCE per probed engine, never for scatter
    assert calls == ["pallas_packed"]
    assert {c.engine for c in cands} == {"scatter"}
    pp = [(c, r) for c, r in pruned if c.engine == "pallas_packed"]
    assert len(pp) == 4                     # 2 dtypes x 2 lengths
    assert all("compile probe failed" in r for _, r in pp)


def test_space_unknown_engine_raises():
    with pytest.raises(ValueError, match="unknown engine"):
        enumerate_space((16, 16, 16), 128, _SUPPORT,
                        engines=("scatterr",))


# ---------------------------------------------------------------------------
# runner: trials through the AOT cache
# ---------------------------------------------------------------------------

def test_trial_through_cache_second_is_hit():
    from ibamr_tpu.serve.aot_cache import ExecutableCache

    cache = ExecutableCache()
    cand = Candidate(engine="scatter", spectral_dtype="f32",
                     chunk_length=2)
    t1 = run_trial(cand, n_cells=8, n_lat=6, n_lon=8, reps=1,
                   cache=cache)
    assert t1.error is None
    assert t1.steps_per_s > 0
    assert not t1.cache_hit and t1.recompiles == 1
    # the second trial of the same candidate family is a cache HIT:
    # zero recompiles — a search re-run (or check's re-race) costs
    # only warm execution
    t2 = run_trial(cand, n_cells=8, n_lat=6, n_lon=8, reps=1,
                   cache=cache)
    assert t2.error is None
    assert t2.cache_hit and t2.recompiles == 0


def test_trial_build_failure_reported_not_raised():
    # packed3 has no valid z tile at n_z=12 and the trial builds with
    # engine_fallback=False — the error must land in the result, the
    # grid must survive
    res = run_trial(Candidate(engine="packed3"), n_cells=12, n_lat=6,
                    n_lon=8, reps=1)
    assert res.error is not None
    assert res.steps_per_s == 0.0


# ---------------------------------------------------------------------------
# db: round-trip, schema, merge, shadow lint
# ---------------------------------------------------------------------------

def test_db_roundtrip_and_validation(tmp_path):
    doc = tdb.new_db()
    prov = tdb.make_provenance("cpu", "2026-08-06",
                               device_kind="host", git_rev="abc1234")
    tdb.merge_entry(doc, tdb.make_entry(
        "packed", n=[128, 128, 128], markers_min=100,
        markers_max=1000, spectral_dtype="f32", platform="cpu",
        measured={"steps_per_s": 74.4}, provenance=prov))
    assert tdb.validate_db(doc) == []
    p = tmp_path / "db.json"
    tdb.save_db(doc, str(p))
    back = tdb.load_db(str(p))
    assert back == doc


def test_db_validation_rejects_bad_shapes():
    doc = {"schema": 99, "entries": [
        {"engine": "warp9"},
        {"engine": "packed", "markers_min": 500, "markers_max": 100},
        {"engine": "mxu", "n_cells": "big"},
        {"engine": "scatter", "measured": {"steps_per_s": "fast"}},
        {"engine": "packed3", "provenance": {"timestamp": "x"}},
    ]}
    problems = tdb.validate_db(doc)
    assert any("schema" in p for p in problems)
    assert any("RESOLVED_ENGINES" in p for p in problems)
    assert any("empty marker band" in p for p in problems)
    assert any("n_cells" in p for p in problems)
    assert any("steps_per_s" in p for p in problems)
    assert any("platform" in p for p in problems)


def test_db_provenance_requires_platform():
    with pytest.raises(ValueError, match="platform"):
        tdb.make_provenance("", "2026-08-06")


def test_db_merge_replaces_same_identity():
    doc = tdb.new_db()
    prov = tdb.make_provenance("cpu", "2026-08-06")
    e = dict(n=[16, 16, 16], markers_min=64, markers_max=256,
             spectral_dtype="f32", platform="cpu", provenance=prov)
    tdb.merge_entry(doc, tdb.make_entry(
        "scatter", measured={"steps_per_s": 10.0}, **e))
    tdb.merge_entry(doc, tdb.make_entry(
        "packed", measured={"steps_per_s": 20.0}, **e))
    # re-publication replaced in place, no shadowed duplicate accreted
    assert len(doc["entries"]) == 1
    assert doc["entries"][0]["engine"] == "packed"
    # a different platform's winner for the same key COEXISTS
    prov_tpu = tdb.make_provenance("tpu", "2026-08-06")
    e2 = {**e, "platform": "tpu", "provenance": prov_tpu}
    tdb.merge_entry(doc, tdb.make_entry(
        "packed_bf16", measured={"steps_per_s": 30.0}, **e2))
    assert len(doc["entries"]) == 2
    assert tdb.validate_db(doc) == []


def test_db_shadow_lint_flags_dead_entries():
    entries = [
        # generic band entry, first in file...
        {"engine": "mxu", "markers_min": 50, "markers_max": 500},
        # ...fully covers this equal-specificity narrower band: every
        # query entry[1] matches, entry[0] wins the file-order tie
        {"engine": "packed", "markers_min": 100, "markers_max": 400},
        # NOT shadowed: matches queries outside the band too
        {"engine": "packed3", "n_cells": 64},
    ]
    shadows = tdb.shadowed_entries(entries)
    assert [(j, i) for j, i, _ in shadows] == [(1, 0)]
    problems = tdb.validate_db({"schema": 1, "entries": entries})
    assert any("shadow lint" in p and "entry[1]" in p
               for p in problems)
    # a MORE specific later entry is not shadowed (it wins its overlap)
    entries2 = [
        {"engine": "mxu", "markers_min": 50, "markers_max": 500},
        {"engine": "packed", "n_cells": 64,
         "markers_min": 100, "markers_max": 400},
    ]
    assert tdb.shadowed_entries(entries2) == []


# ---------------------------------------------------------------------------
# resolver -> serve cache key propagation (the ISSUE-pinned contract)
# ---------------------------------------------------------------------------

def test_db_change_produces_new_serve_cache_key(tmp_path,
                                                monkeypatch):
    from ibamr_tpu.models.shell3d import build_shell_example
    from ibamr_tpu.serve.aot_cache import cache_key, step_fingerprint

    def build():
        integ, _ = build_shell_example(
            n_cells=16, n_lat=8, n_lon=16, radius=0.25, aspect=1.2,
            stiffness=1.0, rest_length_factor=0.75, mu=0.05,
            use_fast_interaction=None)
        return integ

    monkeypatch.setenv("IBAMR_TUNING_DB", "none")
    base = build()
    assert base.ib.engine_name == "scatter"     # heuristic at 16^3/128

    db_path = tmp_path / "tuning.json"
    doc = tdb.new_db()
    tdb.merge_entry(doc, tdb.make_entry(
        "packed", n=[16, 16, 16], markers_min=64, markers_max=256,
        spectral_dtype="f32", platform="cpu",
        measured={"steps_per_s": 99.0},
        provenance=tdb.make_provenance("cpu", "2026-08-06")))
    tdb.save_db(doc, str(db_path))
    monkeypatch.setenv("IBAMR_TUNING_DB", str(db_path))
    tuned = build()
    # the DB steered resolution, and the RESOLVED name is fingerprint
    # material: publishing a DB change produces a NEW serve cache key
    # (stale executables can never serve a re-tuned config)
    assert tuned.ib.engine_name == "packed"
    fp_base, fp_tuned = step_fingerprint(base), step_fingerprint(tuned)
    assert fp_base["engine"] == "scatter"
    assert fp_tuned["engine"] == "packed"
    assert cache_key(fp_base) != cache_key(fp_tuned)


def test_committed_seed_db_skipped_on_cpu(monkeypatch):
    # acceptance: the committed tpu-measured seed must never steer a
    # CPU run — resolution falls through to the heuristic
    from ibamr_tpu.models.engine_resolver import resolve_engine

    monkeypatch.delenv("IBAMR_TUNING_DB", raising=False)
    assert os.path.exists(DEFAULT_DB_PATH)
    assert resolve_engine((256, 256, 256), 99856, _SUPPORT,
                          env={}) == "packed"
    assert resolve_engine((16, 16, 16), 128, _SUPPORT,
                          env={}) == "scatter"


# ---------------------------------------------------------------------------
# the committed seed DB is itself tier-1-validated
# ---------------------------------------------------------------------------

def test_committed_tuning_db_valid():
    doc = tdb.load_db(DEFAULT_DB_PATH)
    assert doc.get("schema") == 1
    assert tdb.validate_db(doc) == []
    for e in doc["entries"]:
        assert e["engine"] in RESOLVED_ENGINES
        # every committed number must say where it came from
        prov = e.get("provenance") or {}
        assert prov.get("platform")
        assert prov.get("timestamp")


# ---------------------------------------------------------------------------
# tools/tune.py check: the revalidation gate
# ---------------------------------------------------------------------------

def _cpu_doc(winner="packed", winner_sps=90.0, runner="scatter",
             runner_sps=30.0):
    doc = tdb.new_db()
    tdb.merge_entry(doc, tdb.make_entry(
        winner, n=[16, 16, 16], markers_min=64, markers_max=256,
        spectral_dtype="f32", platform="cpu",
        measured={"steps_per_s": winner_sps, "chunk_length": 1,
                  "reps": 2, "n_lat": 8, "n_lon": 16,
                  "runner_up": runner,
                  "runner_up_steps_per_s": runner_sps,
                  "runner_up_chunk_length": 1,
                  "margin": round(winner_sps / runner_sps, 4)},
        provenance=tdb.make_provenance("cpu", "2026-08-06")))
    return doc


def _fake_retime(rates):
    def retime(cand, **kw):
        return TrialResult(candidate=cand,
                           steps_per_s=rates[cand.engine])
    return retime


def test_check_exit_codes():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import tune as tune_cli

    # winner holds at its recorded rate -> 0
    rc, _ = tune_cli.check_db(
        _cpu_doc(), platform="cpu",
        retime_fn=_fake_retime({"packed": 91.0, "scatter": 31.0}))
    assert rc == 0
    # ranking holds but the winner drifted beyond the band -> STALE 1
    rc, report = tune_cli.check_db(
        _cpu_doc(), platform="cpu",
        retime_fn=_fake_retime({"packed": 50.0, "scatter": 31.0}))
    assert rc == 1
    assert any("stale" in ln for ln in report)
    # the runner-up now WINS beyond the band -> REGRESSED 2
    rc, report = tune_cli.check_db(
        _cpu_doc(), platform="cpu",
        retime_fn=_fake_retime({"packed": 30.0, "scatter": 90.0}))
    assert rc == 2
    assert any("RANKING FLIP" in ln for ln in report)
    # schema/lint problems -> 2 without any re-timing
    rc, report = tune_cli.check_db(
        {"schema": 99, "entries": []}, platform="cpu",
        retime_fn=_fake_retime({}))
    assert rc == 2
    # provenance-mismatched entries are NOT re-timed (schema/lint
    # only) -> the committed tpu seed costs CI nothing
    rc, report = tune_cli.check_db(
        _cpu_doc(), platform="tpu", retime_fn=_fake_retime({}))
    assert rc == 0
    assert any("not re-timed" in ln for ln in report)


def test_check_cli_seed_db_exits_0():
    # acceptance: `tools/tune.py check` exits 0 against the committed
    # seed on the CPU drill (tpu provenance -> schema + lint only)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tune.py"),
         "check"],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr


@pytest.mark.slow
def test_check_cli_flipped_winner_exits_2(tmp_path):
    # acceptance: artificially flip the measured winner (the DB now
    # claims packed beats scatter at 16^3/128 markers on CPU — false)
    # and the gate's real re-race must exit 2
    doc = _cpu_doc(winner="packed", winner_sps=900.0,
                   runner="scatter", runner_sps=30.0)
    p = tmp_path / "flipped.json"
    tdb.save_db(doc, str(p))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tune.py"),
         "check", "--db", str(p), "--reps", "1"],
        capture_output=True, text=True, cwd=REPO, timeout=600)
    assert r.returncode == 2, r.stdout + r.stderr
    assert "RANKING FLIP" in r.stdout


# ---------------------------------------------------------------------------
# end-to-end: search -> publish -> resolve -> serve drill
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_search_publish_resolve_serve_roundtrip(tmp_path,
                                                monkeypatch):
    from ibamr_tpu.models.engine_resolver import resolve_engine
    from ibamr_tpu.serve.aot_cache import ExecutableCache
    from ibamr_tpu.tune.runner import db_entry_from_search, search

    cache = ExecutableCache()
    res = search(n_cells=16, n_lat=8, n_lon=16,
                 engines=("scatter", "packed"),
                 spectral_dtypes=("f32", "bf16"), chunk_lengths=(1,),
                 reps=2, probe=False, cache=cache)
    assert len(res.trials) == 4 and not res.pruned
    w = res.winner()
    assert w is not None and w.error is None
    entry = db_entry_from_search(res, platform="cpu",
                                 timestamp="2026-08-06")
    doc = tdb.new_db()
    tdb.merge_entry(doc, entry)
    assert tdb.validate_db(doc) == []
    p = tmp_path / "db.json"
    tdb.save_db(doc, str(p))
    # the resolver returns the MEASURED winner for the matching key
    resolved = resolve_engine(
        (16, 16, 16), 128, _SUPPORT,
        env={"IBAMR_TUNING_DB": str(p)},
        spectral_dtype=w.candidate.spectral_dtype, platform="cpu")
    assert resolved == w.candidate.engine
    # ...and the warm-pool serve drill stays green under the new DB:
    # zero warm compiles, the contract's whole point
    monkeypatch.setenv("IBAMR_TUNING_DB", str(p))
    from ibamr_tpu.serve.router import cold_warm_drill

    drill = cold_warm_drill(n_cells=16, n_lat=8, n_lon=16, lanes=2,
                            steps=2, dt=5e-5,
                            spectral_dtype=w.candidate.spectral_dtype)
    assert drill["warm_compiles"] == 0
    assert drill["cold_ok"] and drill["warm_ok"]
