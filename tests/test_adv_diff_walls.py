"""Adv-diff with physical (wall) BCs: decay modes, hot-wall steady state."""

import math

import pytest
import jax.numpy as jnp
import numpy as np

from ibamr_tpu.bc import (AxisBC, DomainBC, SideBC, dirichlet_axis,
                          neumann_axis, periodic_axis)
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.adv_diff import (AdvDiffSemiImplicitIntegrator,
                                            TransportedQuantity,
                                            advance_adv_diff)


def test_dirichlet_box_mode_decay():
    """sin(pi x) sin(pi y) on a homogeneous-Dirichlet box decays at the
    discrete CN rate (eigenvalue of the BC-modified operator)."""
    n, kappa, dt = 32, 0.02, 2e-3
    grid = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    bc = DomainBC(axes=(dirichlet_axis(), dirichlet_axis()))
    integ = AdvDiffSemiImplicitIntegrator(
        grid, [TransportedQuantity("Q", kappa=kappa,
                                   convective_op_type="none", bc=bc)],
        dtype=jnp.float64)
    x, y = grid.cell_centers(jnp.float64)
    Q0 = jnp.sin(math.pi * x) * jnp.sin(math.pi * y)
    state = integ.initialize([Q0])

    steps = 40
    state = advance_adv_diff(integ, state, dt, steps)

    h = grid.dx[0]
    # sin(pi (i+1/2) h) is NOT an exact eigenvector of the (-3,1)
    # Dirichlet end rows, but is within O(h^2); check decay against the
    # continuous rate with a modest tolerance instead.
    rate = math.exp(-2.0 * kappa * math.pi ** 2 * dt * steps)
    got = float(jnp.max(jnp.abs(state.Q[0])))
    assert abs(got - rate) / rate < 2e-2, (got, rate)


def test_hot_wall_steady_linear_profile():
    """Dirichlet Q=1 at lo-y wall, Q=0 at hi-y wall, periodic x: steady
    state is the linear conduction profile through cell centers."""
    nx, ny = 4, 24
    grid = StaggeredGrid(n=(nx, ny), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    bc = DomainBC(axes=(periodic_axis(),
                        AxisBC(SideBC("dirichlet", 1.0),
                               SideBC("dirichlet", 0.0))))
    integ = AdvDiffSemiImplicitIntegrator(
        grid, [TransportedQuantity("Q", kappa=0.1,
                                   convective_op_type="none", bc=bc)],
        dtype=jnp.float64)
    state = integ.initialize()
    # diffusive time 1/(kappa pi^2) ~ 1; run well past
    state = advance_adv_diff(integ, state, dt=0.02, num_steps=600)

    y = np.asarray(grid.cell_coords_1d(1, jnp.float64))
    exact = 1.0 - y
    got = np.asarray(state.Q[0][0, :])
    # residual transient ~ exp(-kappa pi^2 T) = 7e-6 at T = 12
    np.testing.assert_allclose(got, exact, rtol=0, atol=2e-5)


def test_neumann_walls_conserve_total():
    """Insulated (homogeneous Neumann) walls conserve the integral of Q
    under pure diffusion."""
    n = 16
    grid = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    bc = DomainBC(axes=(neumann_axis(), neumann_axis()))
    integ = AdvDiffSemiImplicitIntegrator(
        grid, [TransportedQuantity("Q", kappa=0.05,
                                   convective_op_type="none", bc=bc)],
        dtype=jnp.float64)
    x, y = grid.cell_centers(jnp.float64)
    Q0 = jnp.exp(-((x - 0.3) ** 2 + (y - 0.7) ** 2) / 0.02)
    state = integ.initialize([Q0])
    total0 = float(integ.total(state))
    # equilibration: slowest mode decays as exp(-kappa pi^2 T); T = 10
    state = advance_adv_diff(integ, state, dt=1e-2, num_steps=1000)
    total1 = float(integ.total(state))
    np.testing.assert_allclose(total1, total0, rtol=1e-12)
    # long-time limit: uniform at the mean
    spread = float(jnp.max(state.Q[0]) - jnp.min(state.Q[0]))
    assert spread < 0.05


def test_inhomogeneous_neumann_flux_injection():
    """dQ/dn = g at the lo wall injects flux kappa*g per unit area:
    d(total)/dt = -kappa * g * L (outward-normal convention: positive g
    is outward flux... sign checked both ways)."""
    nx, ny = 4, 16
    grid = StaggeredGrid(n=(nx, ny), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    kappa, g = 0.1, 2.0
    bc = DomainBC(axes=(periodic_axis(),
                        AxisBC(SideBC("neumann", g), SideBC("neumann", 0.0))))
    integ = AdvDiffSemiImplicitIntegrator(
        grid, [TransportedQuantity("Q", kappa=kappa,
                                   convective_op_type="none", bc=bc)],
        dtype=jnp.float64)
    state = integ.initialize()
    dt, steps = 1e-3, 200
    state = advance_adv_diff(integ, state, dt, steps)
    total = float(integ.total(state))
    # outward-normal gradient g at the wall -> diffusive INFLUX kappa*g
    # per unit wall length (area Lx = 1), over time T
    expected = kappa * g * 1.0 * dt * steps
    np.testing.assert_allclose(total, expected, rtol=1e-10)


@pytest.mark.parametrize("scheme", ["upwind", "cui"])
def test_wall_convection_matches_mirror_image(scheme):
    """BC-aware convective face states: a Neumann-walled channel with
    v = sin(pi y) advection is, by the method of images, the lower
    half of a periodic [0,2] domain with the same (odd-mirrored) field.
    CUI's two-cell reach near the wall must read the reflected ghosts,
    not the periodic wrap — the two runs agree to roundoff/truncation."""
    n, dt, steps = 32, 1e-3, 60
    kap = 0.0
    # wall run on [0,1]^2, walls in y
    gw = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    bcw = DomainBC(axes=(periodic_axis(), neumann_axis()))
    iw = AdvDiffSemiImplicitIntegrator(
        gw, [TransportedQuantity("Q", kappa=kap,
                                 convective_op_type=scheme, bc=bcw)],
        dtype=jnp.float64)
    xw, yw = gw.cell_centers(jnp.float64)
    Q0w = jnp.cos(math.pi * yw) + 0.0 * xw
    # v on y-faces (pinned layout: v[:, 0] = wall = 0)
    yfw = (jnp.arange(n, dtype=jnp.float64)) / n
    vw = jnp.tile(jnp.sin(math.pi * yfw)[None, :], (n, 1))
    uw = (jnp.zeros(gw.n, dtype=jnp.float64), vw)
    sw = iw.initialize([Q0w])
    sw = advance_adv_diff(iw, sw, dt, steps, u=uw)

    # mirror run on [0,1] x [0,2], fully periodic
    gm = StaggeredGrid(n=(n, 2 * n), x_lo=(0.0, 0.0), x_up=(1.0, 2.0))
    im = AdvDiffSemiImplicitIntegrator(
        gm, [TransportedQuantity("Q", kappa=kap,
                                 convective_op_type=scheme)],
        dtype=jnp.float64)
    xm, ym = gm.cell_centers(jnp.float64)
    Q0m = jnp.cos(math.pi * ym) + 0.0 * xm
    yfm = (jnp.arange(2 * n, dtype=jnp.float64)) / n
    vm = jnp.tile(jnp.sin(math.pi * yfm)[None, :], (n, 1))
    um = (jnp.zeros(gm.n, dtype=jnp.float64), vm)
    sm = im.initialize([Q0m])
    sm = advance_adv_diff(im, sm, dt, steps, u=um)

    np.testing.assert_allclose(np.asarray(sw.Q[0]),
                               np.asarray(sm.Q[0][:, :n]),
                               rtol=1e-10, atol=1e-10)
