"""Wall-bounded Navier-Stokes + PPM convective operator.

Reference parity: ``INSStaggeredPPMConvectiveOperator`` (P4, the
reference's default operator) and convecting wall-bounded flow
(P2/P3/T9) — the round-1 gap items (VERDICT round 1, "Next round" #4).

Oracles:
- Taylor-Green vortex (periodic): PPM converges at >= 2nd order.
- Poiseuille channel (periodic x, walls y, body force): exact discrete
  steady state with convection enabled.
- Lid-driven cavity at Re=100: centerline velocity profile vs the Ghia,
  Ghia & Shin (1982) tabulated values.
"""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator, advance
from ibamr_tpu.ops.convection import convective_rate, convective_rate_bc

TWO_PI = 2.0 * math.pi


# --------------------------------------------------------------------------
# operator-level checks
# --------------------------------------------------------------------------

def test_bc_path_matches_roll_path_periodic():
    """The ghost-padded formulation reproduces the roll formulation
    bitwise for periodic centered/upwind (same arithmetic)."""
    rng = np.random.default_rng(3)
    u = tuple(jnp.asarray(rng.standard_normal((16, 12))) for _ in range(2))
    dx = (1.0 / 16, 1.0 / 12)
    for scheme in ("centered", "upwind"):
        a = convective_rate(u, dx, scheme)
        b = convective_rate_bc(u, dx, scheme)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_ppm_reduces_to_centered_on_linear_field():
    """PPM's limited parabola is exact for linear data, so N(u) matches
    the centered operator away from periodic wrap seams."""
    n = 32
    xf = jnp.arange(n) / n
    yc = (jnp.arange(n) + 0.5) / n
    X, Y = jnp.meshgrid(xf, yc, indexing="ij")
    # gentle linear-in-y shear advected by constant u; v = 0
    u = (0.2 + 0.1 * Y, jnp.zeros((n, n)))
    dx = (1.0 / n, 1.0 / n)
    a = convective_rate(u, dx, "centered")
    b = convective_rate_bc(u, dx, "ppm")
    # exclude the wrap seam rows where the linear profile jumps
    interior = (slice(None), slice(4, n - 4))
    np.testing.assert_allclose(np.asarray(b[0][interior]),
                               np.asarray(a[0][interior]), atol=1e-12)


# --------------------------------------------------------------------------
# periodic PPM: Taylor-Green convergence
# --------------------------------------------------------------------------

def _tg_exact(g, t, nu, dtype=jnp.float64):
    decay = math.exp(-2.0 * TWO_PI ** 2 * nu * t)
    xf, yc = g.face_centers(0, dtype)
    xc, yf = g.face_centers(1, dtype)
    u = jnp.sin(TWO_PI * xf) * jnp.cos(TWO_PI * yc) * decay + 0 * yc
    v = -jnp.cos(TWO_PI * xc) * jnp.sin(TWO_PI * yf) * decay + 0 * xc
    return u, v


def _run_tg_ppm(n, steps, T, nu, scheme="ppm"):
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    integ = INSStaggeredIntegrator(g, rho=1.0, mu=nu,
                                   convective_op_type=scheme,
                                   dtype=jnp.float64)
    u0, v0 = _tg_exact(g, 0.0, nu)
    st = integ.initialize(u0_arrays=(u0, v0))
    st = advance(integ, st, T / steps, steps)
    ue, ve = _tg_exact(g, T, nu)
    return max(float(jnp.max(jnp.abs(st.u[0] - ue))),
               float(jnp.max(jnp.abs(st.u[1] - ve))))


def test_taylor_green_ppm_convergence():
    nu, T = 0.01, 0.25
    e16 = _run_tg_ppm(16, 32, T, nu)
    e32 = _run_tg_ppm(32, 64, T, nu)
    order = math.log2(e16 / e32)
    assert e32 < 3e-3, (e16, e32)
    assert order > 1.6, (e16, e32, order)


def test_taylor_green_cui_convergence():
    """CUI on the staggered momentum fluxes (SURVEY.md P4 newer menu):
    2nd-order on the smooth Taylor-Green field, like PPM."""
    nu, T = 0.01, 0.25
    e16 = _run_tg_ppm(16, 32, T, nu, scheme="cui")
    e32 = _run_tg_ppm(32, 64, T, nu, scheme="cui")
    order = math.log2(e16 / e32)
    assert e32 < 3e-3, (e16, e32)
    assert order > 1.6, (e16, e32, order)


def test_uppercase_scheme_names_accepted():
    g = StaggeredGrid(n=(8, 8), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    integ = INSStaggeredIntegrator(g, convective_op_type="PPM")
    assert integ.convective_op_type == "ppm"


# --------------------------------------------------------------------------
# wall-bounded Navier-Stokes
# --------------------------------------------------------------------------

@pytest.mark.parametrize("scheme", ["ppm", "centered", "upwind", "cui"])
def test_poiseuille_with_convection(scheme):
    """Channel flow driven by a body force: convection is analytically
    zero for the unidirectional profile, so the convecting integrator
    must reproduce the exact parabola to discretization error."""
    n = 32
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    mu, G = 0.1, 1.0
    integ = INSStaggeredIntegrator(g, rho=1.0, mu=mu,
                                   convective_op_type=scheme,
                                   dtype=jnp.float64,
                                   wall_axes=(False, True))
    st = integ.initialize()
    f = (jnp.full(g.n, G), jnp.zeros(g.n))
    st = advance(integ, st, 2e-3, 4500, f=f)   # t=9: transient ~ e^-t
    yc = (np.arange(n) + 0.5) / n
    exact = G / (2.0 * mu) * yc * (1.0 - yc)
    prof = np.asarray(st.u[0][0, :])
    rel = np.max(np.abs(prof - exact)) / exact.max()
    assert rel < 5e-3, rel
    assert float(integ.max_divergence(st)) < 1e-12


# Ghia, Ghia & Shin (1982), Re=100: u through the vertical centerline
_GHIA_Y = np.array([0.0547, 0.0625, 0.0703, 0.1016, 0.1719, 0.2813,
                    0.4531, 0.5000, 0.6172, 0.7344, 0.8516, 0.9531,
                    0.9609, 0.9688, 0.9766])
_GHIA_U = np.array([-0.03717, -0.04192, -0.04775, -0.06434, -0.10150,
                    -0.15662, -0.21090, -0.20581, -0.13641, 0.00332,
                    0.23151, 0.68717, 0.73722, 0.78871, 0.84123])


def test_lid_driven_cavity_re100_ghia():
    """Re=100 driven cavity at 64^2 to t=30; the u(x=0.5, y) centerline
    profile must match Ghia et al. to ~1% of the lid speed."""
    n = 64
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    integ = INSStaggeredIntegrator(
        g, rho=1.0, mu=0.01, convective_op_type="ppm", dtype=jnp.float64,
        wall_axes=(True, True), wall_tangential={(0, 1, 1): 1.0})
    st = integ.initialize()
    st = advance(integ, st, 0.005, 6000)     # t = 30 (steady for Re=100)
    uc = np.asarray(st.u[0][n // 2, :])
    yc = (np.arange(n) + 0.5) / n
    ui = np.interp(_GHIA_Y, yc, uc)
    assert np.max(np.abs(ui - _GHIA_U)) < 1.2e-2, ui - _GHIA_U
    # primary-vortex strength: u_min within ~2% of Ghia's -0.21090
    assert abs(uc.min() - (-0.21090)) < 4e-3, uc.min()
    assert float(integ.max_divergence(st)) < 1e-12


def test_cavity_velocity_bounded_and_stable():
    """Long cavity run stays bounded (no limiter-induced blowup) at
    modest resolution with the upwind fallback too."""
    n = 32
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    integ = INSStaggeredIntegrator(
        g, rho=1.0, mu=0.01, convective_op_type="upwind",
        dtype=jnp.float64,
        wall_axes=(True, True), wall_tangential={(0, 1, 1): 1.0})
    st = integ.initialize()
    st = advance(integ, st, 0.01, 2000)
    assert bool(jnp.all(jnp.isfinite(st.u[0])))
    assert float(jnp.max(jnp.abs(st.u[0]))) <= 1.0 + 1e-6
