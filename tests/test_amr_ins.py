"""Composite two-level INS + IB (VERDICT round 1 item 3).

Reference parity: INS on a locally-refined hierarchy with the structure
inside the refined region — the core IBAMR usage (SURVEY.md §0, §5.7,
P2/P8/T10).

Oracles:
- the composite projection drives the composite divergence (fine
  interior + uncovered coarse incl. the interface ring) to solver
  tolerance on random data;
- a compact vortex refined by the box: the two-level solution in the
  refined region is several times closer to the uniform-fine solution
  than the uniform-coarse solution is;
- a membrane inside the box: marker trajectories track the
  uniform-fine IB run far better than the uniform-coarse one.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ibamr_tpu.amr import FineBox, _box_mac_divergence, restrict_mac
from ibamr_tpu.amr_ins import (CompositeProjection, TwoLevelIBINS,
                               TwoLevelINS, advance_two_level,
                               advance_two_level_ib, box_from_markers,
                               scatter_box_mac_to_coarse)
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ib import (IBExplicitIntegrator, IBMethod,
                                      advance_ib, polygon_area)
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
from ibamr_tpu.models.membrane2d import make_circle_membrane
from ibamr_tpu.ops import stencils
from ibamr_tpu.ops.convection import convective_rate
from ibamr_tpu.solvers import fft


def _grid(n):
    return StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))


def _vortex_u(g, A=0.05, s=0.08):
    nx, ny = g.n
    X, Y = np.meshgrid(np.arange(nx) * g.dx[0],
                       np.arange(ny) * g.dx[1], indexing="ij")
    psi = A * np.exp(-((X - 0.5) ** 2 + (Y - 0.5) ** 2) / s ** 2)
    u = (np.roll(psi, -1, 1) - psi) / g.dx[1]
    v = -(np.roll(psi, -1, 0) - psi) / g.dx[0]
    return jnp.asarray(u), jnp.asarray(v)


def test_composite_projection_exact():
    g = _grid(32)
    box = FineBox(lo=(8, 8), shape=(16, 16))
    proj = CompositeProjection(g, box, tol=1e-12, m=30, restarts=20)
    rng = np.random.default_rng(0)
    uc = tuple(jnp.asarray(rng.standard_normal(g.n)) * 0.1
               for _ in range(2))
    uf = tuple(jnp.asarray(rng.standard_normal(
        (box.fine_n[0] + (1 if d == 0 else 0),
         box.fine_n[1] + (1 if d == 1 else 0)))) * 0.1 for d in range(2))
    uc = scatter_box_mac_to_coarse(uc, restrict_mac(uf), box)
    uc2, uf2, _, _ = proj.project(uc, uf)
    dc = jnp.where(proj._covered, 0.0, stencils.divergence(uc2, g.dx))
    df = _box_mac_divergence(uf2, proj.dx_f)
    assert float(jnp.max(jnp.abs(dc))) < 1e-10
    assert float(jnp.max(jnp.abs(df))) < 1e-10


def test_initialize_div_free_composite():
    g = _grid(32)
    box = FineBox(lo=(8, 8), shape=(16, 16))
    integ = TwoLevelINS(g, box, mu=0.005)
    st = integ.initialize(_vortex_u(g))
    assert float(integ.max_divergence(st)) < 1e-12


def _uniform_explicit_run(n, T, steps, mu):
    """Uniform-grid run with the SAME explicit time discretization as
    TwoLevelINS, so the comparison isolates the spatial composite."""
    g = _grid(n)
    u = _vortex_u(g)
    dt = T / steps

    def step(u, _):
        lap = stencils.laplacian_vel(u, g.dx)
        nc = convective_rate(u, g.dx, "centered")
        us = tuple(c + dt * (-a + mu * l)
                   for c, a, l in zip(u, nc, lap))
        un, _ = fft.project_divergence_free(us, g.dx)
        return un, None

    u, _ = jax.lax.scan(step, u, None, length=steps)
    return u


def test_vortex_matches_uniform_fine():
    """Compact vortex inside the box: the refined region must be much
    closer to uniform-fine than uniform-coarse is (measured: 7x)."""
    T, steps, mu = 0.25, 400, 0.002
    u64 = _uniform_explicit_run(64, T, steps, mu)
    u32 = _uniform_explicit_run(32, T, steps, mu)

    g = _grid(32)
    box = FineBox(lo=(8, 8), shape=(16, 16))
    integ = TwoLevelINS(g, box, rho=1.0, mu=mu, proj_tol=1e-11)
    st = integ.initialize(_vortex_u(g))
    st = advance_two_level(integ, st, T / steps, steps)
    assert float(integ.max_divergence(st)) < 1e-9

    # u-faces of the box region on the uniform-64 grid
    err_2lev = float(jnp.max(jnp.abs(
        st.uf[0] - u64[0][16:49, 16:48])))
    # coarse u-face value ~ mean of the two coincident fine faces
    u_ref_avg = 0.5 * (u64[0][16:50:2, 16:48:2]
                       + u64[0][16:50:2, 17:48:2])
    err_c32 = float(jnp.max(jnp.abs(u32[0][8:25, 8:24] - u_ref_avg)))
    assert err_2lev < 0.35 * err_c32, (err_2lev, err_c32)
    umax = float(jnp.max(jnp.abs(u64[0])))
    assert err_2lev < 0.02 * umax, (err_2lev, umax)


def test_membrane_in_refined_box_tracks_uniform_fine():
    """Membrane inside the fine box: two-level IB marker trajectories
    match the uniform-fine IB run ~200x closer than uniform-coarse
    (measured 6.5e-6 vs 1.5e-3 at these parameters)."""
    struct = make_circle_membrane(64, 0.15, (0.5, 0.5), stiffness=2.0,
                                  aspect=1.2, rest_length_factor=0.7)
    X0 = jnp.asarray(struct.vertices)
    dt, steps = 5e-4, 300

    g = _grid(32)
    box = FineBox(lo=(8, 8), shape=(16, 16))
    ib = IBMethod(struct.force_specs(dtype=jnp.float64), kernel="IB_4")
    integ = TwoLevelIBINS(g, box, ib, rho=1.0, mu=0.02, proj_tol=1e-10)
    st = integ.initialize(X0)
    a0 = float(polygon_area(st.X))
    st = advance_two_level_ib(integ, st, dt, steps)
    assert float(integ.core.max_divergence(st.fluid)) < 1e-9
    assert abs(float(polygon_area(st.X)) - a0) / a0 < 5e-4

    def uniform_run(n):
        gu = _grid(n)
        ins = INSStaggeredIntegrator(gu, rho=1.0, mu=0.02,
                                     convective_op_type="centered",
                                     dtype=jnp.float64)
        iu = IBExplicitIntegrator(
            ins, IBMethod(struct.force_specs(dtype=jnp.float64)),
            scheme="midpoint")
        su = iu.initialize(X0)
        return advance_ib(iu, su, dt, steps)

    fine = uniform_run(64)
    coarse = uniform_run(32)
    err_2lev = float(jnp.max(jnp.abs(st.X - fine.X)))
    err_c = float(jnp.max(jnp.abs(coarse.X - fine.X)))
    assert err_2lev < 0.05 * err_c, (err_2lev, err_c)


def test_box_from_markers_tags_structure():
    g = _grid(64)
    struct = make_circle_membrane(32, 0.1, (0.4, 0.6), stiffness=1.0)
    box = box_from_markers(g, struct.vertices, pad=4)
    box.validate(g)
    # structure strictly inside with >= pad-1 coarse cells of margin
    Xn = struct.vertices
    for d in range(2):
        c = Xn[:, d] / g.dx[d]
        assert box.lo[d] <= c.min() - 3
        assert box.hi[d] >= c.max() + 3
    assert all(s % 2 == 0 for s in box.shape)


def test_two_level_ib_3d_shell():
    """3D composite two-level INS/IB (the production adaptive-shell
    shape): divergence at solver tolerance, shell volume conserved,
    markers finite."""
    from ibamr_tpu.models.shell3d import make_spherical_shell, shell_volume

    g = StaggeredGrid(n=(32, 32, 32), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    # a SPHERE under taut springs: it stays spherical (radial
    # symmetry), so the radial-sample volume proxy is shape-exact and
    # incompressibility pins it; an aspect!=1 shell changes mean(r^3)
    # at fixed true volume while relaxing
    s = make_spherical_shell(16, 16, 0.12, (0.5, 0.5, 0.5), 1.0,
                             rest_length_factor=0.75)
    ib = IBMethod(s.force_specs(dtype=jnp.float64), kernel="IB_4")
    box = FineBox(lo=(8, 8, 8), shape=(16, 16, 16))
    integ = TwoLevelIBINS(g, box, ib, mu=0.05, proj_tol=1e-10)
    st = integ.initialize(jnp.asarray(s.vertices, jnp.float64))
    v0 = float(shell_volume(st.X, (0.5, 0.5, 0.5)))
    st = advance_two_level_ib(integ, st, 5e-4, 60)
    assert float(integ.core.max_divergence(st.fluid)) < 1e-8
    assert np.all(np.isfinite(np.asarray(st.X)))
    # shell_volume is a radial-sample PROXY (diagnostic only — see its
    # docstring; exact conservation is pinned in 2D): pole-weighted
    # sampling drifts ~2% as the taut shell settles
    assert abs(float(shell_volume(st.X, (0.5, 0.5, 0.5))) - v0) / abs(v0) < 3e-2


def test_stable_dt_advisory():
    """stable_dt flags the fine-level explicit viscous limit (the
    silent-NaN failure mode the 3D adaptive example hit at mu=0.05,
    dt=5e-4) and scales with the finest spacing."""
    g = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    box = FineBox(lo=(8, 8), shape=(16, 16))
    tl = TwoLevelINS(g, box, mu=0.05, proj_tol=1e-8)
    st = tl.initialize(tuple(jnp.zeros(g.n) for _ in range(2)))
    lim = float(tl.stable_dt(st))
    # viscous bound at dx_f = 1/64: rho dx^2/(2*2*mu) = (1/4096)/0.2
    expect = (1.0 / 64.0) ** 2 / (4.0 * 0.05)
    assert abs(lim - expect) / expect < 1e-6, (lim, expect)

    from ibamr_tpu.amr_ins_multilevel import MultiLevelINS
    ml = MultiLevelINS(g, [box, FineBox(lo=(8, 8), shape=(16, 16))],
                       mu=0.05, proj_tol=1e-8)
    sml = ml.initialize()
    lim3 = float(ml.stable_dt(sml))
    # finest level dx = 1/128: 4x tighter than the 2-level bound
    assert abs(lim3 - expect / 4.0) / (expect / 4.0) < 1e-6
