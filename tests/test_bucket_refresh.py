"""Slot-preserving half-step bucket refresh (ops.interaction_packed).

The midpoint IB step needs transfer contexts at X^n AND X^{n+1/2};
``refresh_packed`` re-gathers the drifted positions into the pack-time
chunk layout instead of paying a second full sort/bucket/pack. The
load-bearing claims pinned here:

- same-position refresh is a BITWISE identity;
- under drift within the footprint slack the refreshed context is
  exact against the scatter oracle (and bitwise-equal to a full
  re-pack when no bucket ids change — argsort is stable);
- the jittable drift bound checks BOTH staggered stencil origins per
  blocked axis (cell- and face-centered); the face-centered origin
  sits up to one cell above the cell-centered one used at pack time,
  so a bound on the cell origin alone silently corrupts component d
  along axis d (the regression test below);
- when the bound trips, the fallback is a full re-pack — bitwise
  identical to ``pack_markers`` at the new positions;
- the integrator pays ONE ``buckets`` build per step and reports the
  refresh outcome through ``step_with_stats``.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import interaction
from ibamr_tpu.ops.interaction_packed import PackedInteraction, pack_markers

F64 = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def _grid(n=32):
    return StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))


def _markers(n=32, N=200, seed=0):
    """Positions whose stencil origins sit away from floor boundaries,
    so sub-cell drift does not flip bucket ids (the bitwise tier needs
    a layout-stable placement; the drift tiers use it too and then
    drift far enough to flip origins on purpose)."""
    rng = np.random.default_rng(seed)
    i = rng.integers(0, n, size=(N, 2))
    u = rng.random((N, 2))
    return (i + 0.75 + 0.05 * u) / n, rng


def _bitwise_equal(a, b):
    return all(bool(jnp.array_equal(x, y)) for x, y in
               zip(jax.tree_util.tree_leaves(a),
                   jax.tree_util.tree_leaves(b)))


def _check_exact(eng, g, b, X, rng, tol=1e-10):
    N = X.shape[0]
    F = jnp.asarray(rng.standard_normal((N, 2)), dtype=F64)
    got = eng.spread_vel(F, X, b=b)
    ref = interaction.spread_vel(F, g, X, kernel="IB_4")
    for a, c in zip(ref, got):
        scale = max(float(jnp.max(jnp.abs(a))), 1.0)
        np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                   rtol=0, atol=tol * scale)
    U = eng.interpolate_vel(ref, X, b=b)
    Uref = interaction.interpolate_vel(ref, g, X, kernel="IB_4")
    scale = max(float(jnp.max(jnp.abs(Uref))), 1.0)
    np.testing.assert_allclose(np.asarray(U), np.asarray(Uref),
                               rtol=0, atol=tol * scale)


def test_refresh_same_position_is_bitwise_identity():
    g = _grid()
    base, _ = _markers()
    X = jnp.asarray(base, dtype=F64)
    eng = PackedInteraction(g, kernel="IB_4")
    b = eng.buckets(X)
    b2, hit = eng.refresh(b, X)
    assert bool(hit)
    assert _bitwise_equal(b, b2)


def test_refresh_small_drift_bitwise_equals_repack():
    # +0.2 dx keeps every bucket id: the stable argsort then produces
    # the SAME layout from a full re-pack, so refresh must match it
    # bit for bit
    g = _grid()
    base, rng = _markers()
    dx = 1.0 / 32
    X = jnp.asarray(base, dtype=F64)
    eng = PackedInteraction(g, kernel="IB_4")
    b = eng.buckets(X)
    Xd = X + 0.2 * dx
    b2, hit = eng.refresh(b, Xd)
    assert bool(hit)
    assert _bitwise_equal(b2, eng.buckets(Xd))
    _check_exact(eng, g, b2, Xd, rng)


def test_refresh_backward_drift_within_slack_exact():
    # -0.9 dx flips stencil origins downward for most markers; the
    # footprint's lower slack cell absorbs it, so the refresh must
    # HIT and stay exact against the scatter oracle
    g = _grid()
    base, rng = _markers(seed=1)
    dx = 1.0 / 32
    X = jnp.asarray(base, dtype=F64)
    eng = PackedInteraction(g, kernel="IB_4")
    b = eng.buckets(X)
    Xd = X - 0.9 * dx
    b2, hit = eng.refresh(b, Xd)
    assert bool(hit)
    _check_exact(eng, g, b2, Xd, rng)


def test_refresh_guards_face_centered_origin():
    # REGRESSION: markers placed just below a floor boundary, drifted
    # forward 0.9 dx. The cell-centered origin stays inside the
    # footprint but the FACE-centered origin (component d along blocked
    # axis d — one cell higher) escapes; a drift bound that only checks
    # the cell origin declares a hit and silently corrupts component 0
    # by O(1). The dual-origin bound must fall back — and the fallback
    # re-pack keeps the transfers exact.
    n = 32
    g = _grid(n)
    rng = np.random.default_rng(0)
    i = rng.integers(0, n, size=(200, 2))
    u = rng.random((200, 2))
    X = jnp.asarray((i + 0.45 + 0.1 * u) / n, dtype=F64)
    eng = PackedInteraction(g, kernel="IB_4")
    b = eng.buckets(X)
    Xd = X + 0.9 / n
    b2, hit = eng.refresh(b, Xd)
    assert not bool(hit)
    _check_exact(eng, g, b2, Xd, rng)


def test_refresh_far_drift_falls_back_to_full_repack():
    g = _grid()
    base, rng = _markers(seed=2)
    X = jnp.asarray(base, dtype=F64)
    eng = PackedInteraction(g, kernel="IB_4")
    b = eng.buckets(X)
    Xd = X + 3.2 / 32
    b2, hit = eng.refresh(b, Xd)
    assert not bool(hit)
    assert _bitwise_equal(b2, eng.buckets(Xd))
    _check_exact(eng, g, b2, Xd, rng)


def test_refresh_respects_marker_mask():
    g = _grid()
    base, rng = _markers(seed=3)
    dx = 1.0 / 32
    X = jnp.asarray(base, dtype=F64)
    mask = jnp.asarray(rng.random(200) > 0.3, dtype=F64)
    eng = PackedInteraction(g, kernel="IB_4")
    b = eng.buckets(X, mask)
    Xd = X + 0.2 * dx
    b2, hit = eng.refresh(b, Xd, weights=mask)
    assert bool(hit)
    F = jnp.asarray(rng.standard_normal((200, 2)), dtype=F64)
    got = eng.spread_vel(F, Xd, b=b2)
    ref = interaction.spread_vel(F, g, Xd, kernel="IB_4", weights=mask)
    for a, c in zip(ref, got):
        scale = max(float(jnp.max(jnp.abs(a))), 1.0)
        np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                   rtol=0, atol=1e-10 * scale)


def test_refresh_jits_and_matches_eager():
    g = _grid()
    base, _ = _markers(seed=4)
    X = jnp.asarray(base, dtype=F64)
    eng = PackedInteraction(g, kernel="IB_4")
    b = eng.buckets(X)
    Xd = X - 0.4 / 32
    b_e, hit_e = eng.refresh(b, Xd)
    b_j, hit_j = jax.jit(lambda bb, xx: eng.refresh(bb, xx))(b, Xd)
    assert bool(hit_e) == bool(hit_j) is True
    assert _bitwise_equal(b_e, b_j)


def test_integrator_pays_one_bucket_prep_per_step():
    from ibamr_tpu.models.shell3d import build_shell_example

    integ, state = build_shell_example(
        n_cells=16, n_lat=24, n_lon=24, radius=0.25,
        use_fast_interaction="packed")
    calls = {"n": 0}
    orig = integ.ib.fast.buckets

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    integ.ib.fast.buckets = counting
    lowered = jax.jit(integ.step_with_stats).lower(state, 1e-4)
    # the midpoint step needs contexts at X^n and X^{n+1/2}; with the
    # refresh path only ONE full pack is traced (the half-step context
    # is the re-gather + its cond fallback, which calls pack_markers
    # directly, not the engine's buckets entry point)
    assert calls["n"] == 1

    new_state, stats = lowered.compile()(state, 1e-4)
    assert stats["refresh_hit"] is not None
    assert bool(stats["refresh_hit"])
    assert bool(jnp.isfinite(new_state.X).all())

    # oracle: the scatter-path model advanced one step
    integ0, state0 = build_shell_example(
        n_cells=16, n_lat=24, n_lon=24, radius=0.25,
        use_fast_interaction=False)
    s0 = jax.jit(integ0.step)(state0, 1e-4)
    np.testing.assert_allclose(np.asarray(new_state.X),
                               np.asarray(s0.X), rtol=0, atol=5e-5)


def test_refresh_fallback_matches_pack_under_jit():
    # the lax.cond branches must agree in pytree structure AND the
    # taken fallback must equal an out-of-band pack bit for bit
    g = _grid()
    base, _ = _markers(seed=5)
    X = jnp.asarray(base, dtype=F64)
    eng = PackedInteraction(g, kernel="IB_4")
    b = eng.buckets(X)
    Xd = X + 2.5 / 32
    b_j, hit_j = jax.jit(lambda bb, xx: eng.refresh(bb, xx))(b, Xd)
    assert not bool(hit_j)
    assert _bitwise_equal(b_j, pack_markers(eng.geom, g, Xd, None,
                                            nchunks=eng.nchunks,
                                            overflow_cap=eng.overflow_cap))
