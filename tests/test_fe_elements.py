"""General FE element families (T16/P17 round 3): TRI6/TET10 quadratic
simplices, QUAD4/HEX8 tensor elements, per-quadrature-point assembly.

Oracles: partition of unity and gradient-consistency of every shape
table; exact affine patch test (FF == A at every quad point, energies
match the analytic volume integral); rigid rotation produces zero force
for an objective material; autodiff force == explicit PK1 assembly for
every family; HRZ lumped mass is positive and sums to the mesh volume;
quadratic conversion preserves volume and node sharing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.fe import fem
from ibamr_tpu.fe.mesh import (FEMesh, box_hex_mesh, disc_mesh,
                               rect_quad_mesh, to_quadratic)


def _meshes():
    tri = disc_mesh(n_rings=3)
    quad = rect_quad_mesh(3, 2)
    hexm = box_hex_mesh(2, 2, 2)
    from ibamr_tpu.fe.mesh import ball_mesh
    tet = ball_mesh(n_shells=2) if "ball_mesh" in dir() else None
    out = {"TRI3": tri, "TRI6": to_quadratic(tri), "QUAD4": quad,
           "HEX8": hexm}
    return out


ALL_TYPES = ["TRI3", "TRI6", "QUAD4", "HEX8", "TET10",
             "QUAD8", "QUAD9", "HEX20", "HEX27"]


def _mesh_of(etype):
    if etype in ("TRI3", "TRI6"):
        m = disc_mesh(n_rings=3)
        return m if etype == "TRI3" else to_quadratic(m)
    if etype == "QUAD4":
        return rect_quad_mesh(3, 2)
    if etype == "HEX8":
        return box_hex_mesh(2, 2, 2)
    if etype in ("QUAD8", "QUAD9"):
        from ibamr_tpu.fe.mesh import to_quadratic_tensor
        return to_quadratic_tensor(rect_quad_mesh(3, 2),
                                   serendipity=etype == "QUAD8")
    if etype in ("HEX20", "HEX27"):
        from ibamr_tpu.fe.mesh import to_quadratic_tensor
        return to_quadratic_tensor(box_hex_mesh(2, 2, 2),
                                   serendipity=etype == "HEX20")
    if etype == "TET10":
        # one reference tet is enough for the shape/patch oracles
        nodes = np.array([[0.0, 0.0, 0.0], [1.0, 0.0, 0.0],
                          [0.0, 1.0, 0.0], [0.0, 0.0, 1.0]])
        elems = np.array([[0, 1, 2, 3]])
        return to_quadratic(FEMesh(nodes=nodes, elems=elems,
                                   elem_type="TET4"))
    raise ValueError(etype)


@pytest.mark.parametrize("etype", ALL_TYPES)
def test_shape_partition_of_unity_and_gradient(etype):
    N, dN, qw = fem._shape_table(etype)
    assert np.allclose(N.sum(axis=1), 1.0, atol=1e-12)
    assert np.allclose(dN.sum(axis=1), 0.0, atol=1e-12)
    # shapes interpolate coordinates: sum_a N_a xi_a == qp (isoparam.)
    assert qw.sum() > 0


@pytest.mark.parametrize("etype", ALL_TYPES)
def test_affine_patch_exact(etype):
    """x = A X + b: FF must equal A at EVERY quadrature point and the
    energy must be vol * W(A) exactly, for every element family."""
    mesh = _mesh_of(etype)
    asm = fem.build_assembly(mesh, dtype=jnp.float64)
    d = mesh.dim
    rng = np.random.default_rng(0)
    A = np.eye(d) + 0.1 * rng.standard_normal((d, d))
    b = rng.standard_normal(d)
    x = jnp.asarray(mesh.nodes @ A.T + b)
    FF = fem.deformation_gradients(asm, x)
    assert np.allclose(np.asarray(FF),
                       np.broadcast_to(A, FF.shape), atol=1e-10)
    W = fem.neo_hookean(1.3, 0.7)
    E = float(fem.elastic_energy(asm, W, x))
    W_A = float(W(jnp.asarray(A)))
    assert np.isclose(E, mesh.volume() * W_A, rtol=1e-10), \
        (E, mesh.volume() * W_A)


@pytest.mark.parametrize("etype", ALL_TYPES)
def test_rigid_rotation_zero_force(etype):
    mesh = _mesh_of(etype)
    asm = fem.build_assembly(mesh, dtype=jnp.float64)
    d = mesh.dim
    th = 0.4
    if d == 2:
        R = np.array([[np.cos(th), -np.sin(th)],
                      [np.sin(th), np.cos(th)]])
    else:
        R = np.array([[np.cos(th), -np.sin(th), 0.0],
                      [np.sin(th), np.cos(th), 0.0],
                      [0.0, 0.0, 1.0]])
    x = jnp.asarray(mesh.nodes @ R.T)
    F = fem.nodal_forces(asm, fem.neo_hookean(1.0, 1.0), x)
    assert float(jnp.max(jnp.abs(F))) < 1e-10


@pytest.mark.parametrize("etype", ALL_TYPES)
def test_autodiff_matches_pk1_assembly(etype):
    mesh = _mesh_of(etype)
    asm = fem.build_assembly(mesh, dtype=jnp.float64)
    rng = np.random.default_rng(1)
    x = jnp.asarray(mesh.nodes
                    + 0.05 * rng.standard_normal(mesh.nodes.shape))
    W = fem.stvk(1.0, 0.5)
    Fa = fem.nodal_forces(asm, W, x)
    Fp = fem.nodal_forces_pk1(asm, W, x)
    assert np.allclose(np.asarray(Fa), np.asarray(Fp), atol=1e-11)
    # total internal force is zero (momentum conservation)
    assert np.allclose(np.asarray(jnp.sum(Fa, axis=0)), 0.0, atol=1e-10)


@pytest.mark.parametrize("etype", ALL_TYPES)
def test_hrz_lumped_mass_positive_sums_to_volume(etype):
    mesh = _mesh_of(etype)
    asm = fem.build_assembly(mesh, dtype=jnp.float64)
    m = np.asarray(asm.lumped_mass)
    assert (m > 0).all(), f"negative/zero lumped mass for {etype}"
    assert np.isclose(m.sum(), mesh.volume(), rtol=1e-10)


def test_quadratic_conversion_shares_midside_nodes():
    tri = disc_mesh(n_rings=3)
    tri6 = to_quadratic(tri)
    n_edges_upper = 3 * tri.n_elems          # with sharing it's fewer
    assert tri6.n_nodes < tri.n_nodes + n_edges_upper
    assert np.isclose(tri6.volume(), tri.volume(), rtol=1e-12)
    # interior midside nodes are shared by exactly two triangles
    counts = np.zeros(tri6.n_nodes, dtype=int)
    for conn in tri6.elems[:, 3:]:
        counts[conn] += 1
    assert counts[tri.n_nodes:].max() == 2


def _square_tri_mesh(n):
    """Structured TRI3 triangulation of the unit square (geometry is
    EXACT, so energy differences are pure interpolation/quadrature)."""
    xs = np.linspace(0.0, 1.0, n + 1)
    X, Y = np.meshgrid(xs, xs, indexing="ij")
    nodes = np.stack([X.reshape(-1), Y.reshape(-1)], axis=1)
    nid = np.arange((n + 1) ** 2).reshape(n + 1, n + 1)
    a, b = nid[:-1, :-1].reshape(-1), nid[1:, :-1].reshape(-1)
    c, d = nid[1:, 1:].reshape(-1), nid[:-1, 1:].reshape(-1)
    elems = np.concatenate([np.stack([a, b, c], axis=1),
                            np.stack([a, c, d], axis=1)])
    return FEMesh(nodes=nodes, elems=elems, elem_type="TRI3")


def test_tri6_beats_tri3_on_quadratic_displacement():
    """On an exact-geometry square, a quadratic displacement field is
    interpolated EXACTLY by TRI6 (FF error zero; only smooth quadrature
    error remains) while TRI3's piecewise-constant FF carries the
    leading discretization error."""
    tri = _square_tri_mesh(4)
    tri6 = to_quadratic(tri)

    def disp(X):
        return np.stack([X[:, 0] ** 2, X[:, 0] * X[:, 1]],
                        axis=1) / 10.0

    W = fem.stvk(1.0, 0.5)
    errs = {}
    for m in (tri, tri6):
        asm = fem.build_assembly(m, dtype=jnp.float64)
        x = jnp.asarray(m.nodes + disp(m.nodes))
        errs[m.elem_type] = float(fem.elastic_energy(asm, W, x))
    fine = to_quadratic(_square_tri_mesh(48))
    asm_f = fem.build_assembly(fine, dtype=jnp.float64)
    xf = jnp.asarray(fine.nodes + disp(fine.nodes))
    E_ref = float(fem.elastic_energy(asm_f, W, xf))
    err3 = abs(errs["TRI3"] - E_ref)
    err6 = abs(errs["TRI6"] - E_ref)
    assert err6 < 0.1 * err3, (errs, E_ref, err3, err6)


@pytest.mark.parametrize("etype", ["TRI3", "TRI6", "QUAD4"])
def test_quad_transfer_constant_and_conservation(etype):
    """The node<->quad transfers are exact for constants (interp) and
    conserve totals exactly (spread) on EVERY family — including the
    quadratic simplices whose N-weighted row sums vanish at vertices
    (round-3 review finding)."""
    mesh = _mesh_of(etype)
    asm = fem.build_assembly(mesh, dtype=jnp.float64)
    ones = jnp.ones((asm.wdV.size, 2), dtype=jnp.float64)
    nodal = fem.nodal_average_from_quads(asm.elems, asm.shape, asm.wdV,
                                         asm.n_nodes, ones)
    assert np.allclose(np.asarray(nodal), 1.0, atol=1e-12), etype
    rng = np.random.default_rng(3)
    F = jnp.asarray(rng.standard_normal((asm.n_nodes, 2)))
    Fq = fem.distribute_to_quads(asm.elems, asm.shape, asm.wdV,
                                 asm.n_nodes, F)
    assert np.allclose(np.asarray(jnp.sum(Fq, axis=0)),
                       np.asarray(jnp.sum(F, axis=0)), atol=1e-11)


# ---------------------------------------------------------------------------
# Adaptive transfer quadrature (round 5, VERDICT item 8: the
# FEDataManager::updateQuadratureRule analog)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("etype", ["TRI3", "TRI6", "QUAD4", "QUAD9",
                                   "HEX8", "HEX27"])
def test_transfer_quadrature_measures_and_density(etype):
    """Every transfer level integrates the reference measure exactly
    and strictly increases the point count."""
    from ibamr_tpu.fe.fem import transfer_quadrature

    ref_measure = {"TRI3": 0.5, "TRI6": 0.5, "QUAD4": 4.0,
                   "QUAD9": 4.0, "HEX8": 8.0, "HEX27": 8.0}[etype]
    last = 0
    for level in range(3):
        qp, qw = transfer_quadrature(etype, level)
        assert abs(qw.sum() - ref_measure) < 1e-12
        assert len(qw) > last
        last = len(qw)


def test_suggest_transfer_level_tracks_deformation():
    """A stretched configuration demands a higher transfer level —
    the deformation-adaptive density decision."""
    from ibamr_tpu.fe.fem import suggest_transfer_level

    m = disc_mesh(radius=0.2, center=(0.5, 0.5), n_rings=3)
    h = 1.0 / 32.0
    l0 = suggest_transfer_level(m, m.nodes, h)
    # stretch 4x: spacing quadruples -> the level must rise
    x_stretch = np.asarray(m.nodes) * np.array([4.0, 1.0])
    l1 = suggest_transfer_level(m, x_stretch, h)
    assert l1 > l0, (l0, l1)


def test_transfer_assembly_conserves_and_refines():
    """The denser transfer assembly conserves total spread force
    EXACTLY (distribute_to_quads' per-node normalization) and places
    more transfer points than the stiffness rule."""
    from ibamr_tpu.fe.fem import (build_transfer_assembly,
                                  distribute_to_quads,
                                  _node_qp_weights)

    m = disc_mesh(radius=0.25, center=(0.5, 0.5), n_rings=3)
    asm0 = fem.build_assembly(m, dtype=jnp.float64)
    asm2 = build_transfer_assembly(m, level=2, dtype=jnp.float64)
    assert asm2.shape.shape[0] > asm0.shape.shape[0]
    # same total measure
    np.testing.assert_allclose(float(asm2.wdV.sum()),
                               float(asm0.wdV.sum()), rtol=1e-12)
    rng = np.random.default_rng(0)
    F = jnp.asarray(rng.standard_normal((m.n_nodes, 2)))
    ww = _node_qp_weights(asm2.elems, asm2.shape, asm2.wdV,
                          asm2.n_nodes)
    Fq = distribute_to_quads(asm2.elems, asm2.shape, asm2.wdV,
                             asm2.n_nodes, F, ww_den=ww)
    np.testing.assert_allclose(np.asarray(Fq).sum(axis=0),
                               np.asarray(F).sum(axis=0), atol=1e-10)


def test_ibfe_with_adaptive_transfer_runs_and_conserves():
    """IBFEMethod(transfer_level=2): the coupled step runs with the
    denser transfer cloud; at rest the disc stays put (forces are
    zero regardless of the transfer rule)."""
    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.ib import IBExplicitIntegrator
    from ibamr_tpu.integrators.ibfe import IBFEMethod
    from ibamr_tpu.integrators.ins import INSStaggeredIntegrator

    m = disc_mesh(radius=0.2, center=(0.5, 0.5), n_rings=3)
    grid = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    ins = INSStaggeredIntegrator(grid, mu=0.05,
                                 convective_op_type="centered",
                                 dtype=jnp.float64)
    fe = IBFEMethod(m, fem.neo_hookean(1.0, 4.0), kernel="IB_4",
                    dtype=jnp.float64, transfer_level=2)
    assert fe.tasm.shape.shape[0] > fe.asm.shape.shape[0]
    integ = IBExplicitIntegrator(ins, fe)
    st = integ.initialize(jnp.asarray(m.nodes, jnp.float64))
    for _ in range(3):
        st = integ.step(st, 1e-3)
    assert bool(jnp.all(jnp.isfinite(st.X)))
    assert float(jnp.max(jnp.abs(st.X - jnp.asarray(m.nodes)))) < 1e-3
