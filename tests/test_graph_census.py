"""Unit tests for the census primitives themselves (PR 8 satellite):
each counter exercised against tiny hand-built programs — a known
scatter, a known convert chain, a donated vs non-donated jit, a
debug callback inside a scan body — with NO child processes. The
counters must be trustworthy in isolation before the contract gate
(tests/test_graph_contracts.py) leans on them.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.analysis import graph_census as gc
from ibamr_tpu.analysis.contracts import Drift, diff_budget
from ibamr_tpu.analysis.jit_lint import lint_file


# ---------------------------------------------------------------------------
# HLO-text censuses
# ---------------------------------------------------------------------------

def test_hlo_op_counts_strips_quoted_metadata():
    text = '\n'.join([
        '  %x = f32[8]{0} add(f32[8]{0} %a, f32[8]{0} %b), '
        'metadata={op_name="jit(scatter)(fake)"}',
        '  %y = f32[8]{0} scatter(f32[8]{0} %x, s32[1]{0} %i, '
        'f32[1]{0} %v)',
        '  no assignment on this line',
    ])
    counts = gc.hlo_op_counts(text)
    assert counts == {"add": 1, "scatter": 1}


def test_known_scatter_is_counted():
    # primitive-level census: the XLA CPU scatter expander rewrites
    # small scatters into while-loops before the optimized HLO, so the
    # jaxpr primitive count is the non-vacuous zero-scatter observable
    # on this backend (see scatter_gather_census docstring)
    def f(x, idx, v):
        return x.at[idx].add(v)

    x = jnp.zeros(16, jnp.float32)
    idx = jnp.array([3, 7], jnp.int32)
    v = jnp.ones(2, jnp.float32)
    cj = jax.make_jaxpr(f)(x, idx, v)
    cen = gc.scatter_gather_census(cj.jaxpr)
    assert cen["scatter_prims"] == 1
    # gather counted too, and a scatter-free program counts zero
    cj2 = jax.make_jaxpr(lambda a, i: a[i] * 2.0)(x, idx)
    cen2 = gc.scatter_gather_census(cj2.jaxpr)
    assert cen2["scatter_prims"] == 0
    assert cen2["gather_prims"] == 1


# ---------------------------------------------------------------------------
# jaxpr censuses
# ---------------------------------------------------------------------------

def test_fft_census_counts_batched_transforms():
    def f(x):
        h = jnp.fft.rfftn(x)
        return jnp.fft.irfftn(h, s=x.shape)

    cj = jax.make_jaxpr(f)(jnp.ones((8, 8), jnp.float32))
    cen = gc.fft_census(cj.jaxpr)
    assert cen["fft_ops"] == 2
    kinds = {t["kind"] for t in cen["fft_transforms"]}
    assert len(kinds) == 2              # one forward, one inverse


def test_convert_census_flags_widening_not_bf16_rounding():
    def f(x):
        good = x.astype(jnp.bfloat16).astype(jnp.float32)   # rounding
        bad = x.astype(jnp.float64).astype(jnp.float32)     # roundtrip
        return good + bad.astype(jnp.float32)

    cj = jax.make_jaxpr(f)(jnp.ones(4, jnp.float32))
    cen = gc.convert_census(cj.jaxpr)
    # exactly one f32->f64 widening, exactly one f32->f64->f32
    # roundtrip; the deliberate f32->bf16->f32 rounding is NOT flagged
    assert cen["f64_widenings"] == 1
    assert cen["roundtrip_chains"] == 1
    sites = cen["widening_sites"]
    assert sites and sites[0]["dst"] == "float64"


def test_convert_census_clean_program():
    cj = jax.make_jaxpr(lambda x: x * 2.0 + 1.0)(
        jnp.ones(4, jnp.float32))
    cen = gc.convert_census(cj.jaxpr)
    assert cen["f64_widenings"] == 0
    assert cen["roundtrip_chains"] == 0


def test_host_transfer_census_sees_callback_inside_scan():
    def noisy(x):
        def body(c, _):
            jax.debug.callback(lambda v: None, c)
            return c + 1.0, None
        out, _ = jax.lax.scan(body, x, None, length=3)
        return out

    cj = jax.make_jaxpr(noisy)(jnp.float32(0.0))
    cen = gc.host_transfer_census(cj.jaxpr)
    assert cen["host_transfers"] == 1
    assert cen["host_transfers_in_scan"] == 1

    def gated(x):
        def body(c, _):
            return c + 1.0, None
        out, _ = jax.lax.scan(body, x, None, length=3)
        jax.debug.callback(lambda v: None, out)    # OUTSIDE the scan
        return out

    cen2 = gc.host_transfer_census(jax.make_jaxpr(gated)(
        jnp.float32(0.0)).jaxpr)
    assert cen2["host_transfers"] == 1
    assert cen2["host_transfers_in_scan"] == 0


def test_dot_census_counts_contraction():
    a = jnp.ones((4, 8), jnp.float32)
    b = jnp.ones((8, 2), jnp.float32)
    cen = gc.dot_census(jax.make_jaxpr(jnp.matmul)(a, b).jaxpr)
    assert cen["dot_count"] == 1
    assert cen["dot_flops"] == 2 * 4 * 2 * 8


# ---------------------------------------------------------------------------
# structural overlap census (PR 16)
# ---------------------------------------------------------------------------

_AXIS = [("i", 2)]


def test_structural_census_hidden_vs_unhidden():
    # independent compute between the psum's issue and its first
    # consumer -> hidden; immediate consumption -> unhidden
    def hidden(x, y):
        s = jax.lax.psum(x, "i")
        w = y * 2.0 + 1.0          # schedulable work in the window
        return s + w

    def unhidden(x, y):
        s = jax.lax.psum(x, "i")
        return s + y

    x = jnp.ones(4, jnp.float32)
    ch = gc.structural_overlap_census(
        jax.make_jaxpr(hidden, axis_env=_AXIS)(x, x).jaxpr)
    cu = gc.structural_overlap_census(
        jax.make_jaxpr(unhidden, axis_env=_AXIS)(x, x).jaxpr)
    assert ch["structural_collectives"] == 1
    assert ch["hidden_collectives"] == 1
    assert ch["hidden_fraction"] == 100
    assert cu["unhidden_collectives"] == 1
    assert cu["hidden_fraction"] == 0
    assert cu["unhidden_sites"][0]["prim"] == "psum"


def test_structural_census_layout_window_hides_nothing():
    # a window containing only layout/bookkeeping primitives (reshape,
    # convert) cannot hide link latency — still unhidden
    def f(x, y):
        s = jax.lax.psum(x, "i")
        w = jnp.reshape(y, (2, 2)).astype(jnp.float32)
        return s + w.reshape(4)

    x = jnp.ones(4, jnp.float32)
    c = gc.structural_overlap_census(
        jax.make_jaxpr(f, axis_env=_AXIS)(x, x).jaxpr)
    assert c["unhidden_collectives"] == 1
    assert c["hidden_collectives"] == 0


def test_structural_census_output_collective_and_fraction():
    # a collective whose result is a body OUTPUT gets the remainder of
    # the body as its window: trailing independent work hides it
    def f(x, y):
        s = jax.lax.psum(x, "i")   # consumed only by the output
        w = y * 3.0
        return s, w

    x = jnp.ones(4, jnp.float32)
    c = gc.structural_overlap_census(
        jax.make_jaxpr(f, axis_env=_AXIS)(x, x).jaxpr)
    assert c["hidden_collectives"] == 1
    # and a collective-free program reads 100 (nothing to hide)
    c0 = gc.structural_overlap_census(
        jax.make_jaxpr(lambda a: a * 2.0)(x).jaxpr)
    assert c0["structural_collectives"] == 0
    assert c0["hidden_fraction"] == 100


def test_structural_census_walks_scan_bodies():
    # collectives inside a scan body are censused in the body's own
    # trace order, not against the outer body
    def f(x):
        def body(c, _):
            s = jax.lax.psum(c, "i")
            w = c * 2.0
            return s + w, None
        out, _ = jax.lax.scan(body, x, None, length=3)
        return out

    x = jnp.ones(4, jnp.float32)
    c = gc.structural_overlap_census(
        jax.make_jaxpr(f, axis_env=_AXIS)(x).jaxpr)
    assert c["structural_collectives"] == 1
    assert c["hidden_collectives"] == 1


# ---------------------------------------------------------------------------
# --tighten directional merge (pure python — no jax)
# ---------------------------------------------------------------------------

def test_tighten_merges_directionally():
    from tools.graph_audit import tighten_merge

    old = {"scatter_ops": 3, "fft_ops": 2,
           "donated_args": 2, "hidden_fraction": 50,
           "legacy_only": 7}
    measured = {"scatter_ops": 1,       # ceiling improved -> adopt
                "fft_ops": 5,           # ceiling regressed -> KEEP old
                "donated_args": 1,      # floor regressed -> KEEP old
                "hidden_fraction": 80,  # floor improved -> adopt
                "brand_new": 4}         # new metric -> adopt
    out = tighten_merge(old, measured)
    assert out == {"scatter_ops": 1, "fft_ops": 2,
                   "donated_args": 2, "hidden_fraction": 80,
                   "legacy_only": 7, "brand_new": 4}
    # inputs are not mutated (the audit reuses the loaded budgets)
    assert old["scatter_ops"] == 3 and "brand_new" not in old


# ---------------------------------------------------------------------------
# donation audit
# ---------------------------------------------------------------------------

def test_donation_census_donated_vs_not():
    x = jnp.ones((8, 8), jnp.float32)
    y = jnp.ones((8, 8), jnp.float32)

    def f(a, b):
        return a * 2.0 + b

    donated = jax.jit(f, donate_argnums=(0,)).lower(x, y).compile()
    plain = jax.jit(f).lower(x, y).compile()
    assert gc.donation_census(donated.as_text())["donated_args"] >= 1
    assert gc.donation_census(plain.as_text())["donated_args"] == 0


def test_graph_census_composite_and_budget_metrics():
    cen = gc.graph_census(
        lambda a, b: a * 2.0 + b,
        (jnp.ones((8, 8), jnp.float32), jnp.ones((8, 8), jnp.float32)),
        donate_argnums=(0,))
    m = gc.budget_metrics(cen)
    assert m["donated_args"] >= 1
    assert m["scatter_ops"] == 0 and m["fft_ops"] == 0
    assert set(m) == set(gc.BUDGET_MAX_METRICS
                         + gc.BUDGET_MIN_METRICS)


# ---------------------------------------------------------------------------
# budget diff semantics (pure python — no jax)
# ---------------------------------------------------------------------------

def test_diff_budget_directions():
    budget = {"scatter_ops": 0, "fft_ops": 2, "donated_args": 11}
    # clean
    d = diff_budget("a", {"scatter_ops": 0, "fft_ops": 2,
                          "donated_args": 11}, budget)
    assert d.clean
    # max metric regresses UP, min metric regresses DOWN
    d = diff_budget("a", {"scatter_ops": 1, "fft_ops": 2,
                          "donated_args": 3}, budget)
    assert set(d.regressions) == {"scatter_ops", "donated_args"}
    # improvements: fewer ffts, more donated args
    d = diff_budget("a", {"scatter_ops": 0, "fft_ops": 1,
                          "donated_args": 12}, budget)
    assert not d.regressions
    assert set(d.improvements) == {"fft_ops", "donated_args"}
    # a budgeted metric the census cannot measure is NOT a silent pass
    d = diff_budget("a", {"fft_ops": 2}, {"fft_ops": 2, "bogus": 0})
    assert d.missing == ("bogus",)
    assert not d.clean


# ---------------------------------------------------------------------------
# jit-lint rules on synthetic sources (no jax tracing involved)
# ---------------------------------------------------------------------------

_BAD_SRC = '''
import time, random
import numpy as np
import jax
import jax.numpy as jnp
from functools import partial

@jax.jit
def f(x, y):
    if x > 0:
        y = y + 1
    v = float(x)
    t = time.perf_counter()
    return y + v + t

@partial(jax.jit, static_argnums=(1,))
def g(x, n, acc=[]):
    z = x * 2
    return np.asarray(z)

def outer(xs):
    def body(c, x):
        while c.sum() > 0:
            c = c - 1
        return c, x.item()
    return jax.lax.scan(body, xs[0], xs)

def host_side(x):
    # NOT a traced scope: none of these may be flagged
    if x > 0:
        return float(x)
    return np.asarray(x)
'''

_OK_SRC = '''
import jax
import jax.numpy as jnp

@jax.jit
def f(x, mask=None):
    if mask is None:
        mask = jnp.ones_like(x)
    if x.ndim == 3:
        x = x.sum(axis=0)
    return x * mask

@jax.jit
def waived(x):
    v = float(x)  # jitlint: ok(tracer-cast): x is a concrete python scalar by contract
    return v
'''


def _lint_src(tmp_path, src, name="mod.py"):
    p = tmp_path / name
    p.write_text(src)
    return lint_file(str(p), name)


def test_jit_lint_catches_each_rule(tmp_path):
    findings, _ = _lint_src(tmp_path, _BAD_SRC)
    rules = sorted(f.rule for f in findings if not f.waived)
    assert rules.count("traced-branch") == 2      # if in f, while in body
    assert rules.count("tracer-cast") == 3        # float, asarray, .item
    assert rules.count("time-capture") == 1
    assert rules.count("mutable-default") == 1
    # the host-side function contributes nothing
    lines = {f.line for f in findings}
    assert all(l < _BAD_SRC.count("\n") - 4 or True for l in lines)
    host_findings = [f for f in findings
                     if "host_side" in _BAD_SRC.splitlines()[
                         f.line - 1]]
    assert not host_findings


def test_jit_lint_exemptions_and_waivers(tmp_path):
    findings, waivers = _lint_src(tmp_path, _OK_SRC)
    active = [f for f in findings if not f.waived]
    assert active == []                 # is-None + .ndim tests exempt
    used = [w for w in waivers if w.used]
    assert len(used) == 1 and used[0].rule == "tracer-cast"


def test_jit_lint_rejects_bare_waiver(tmp_path):
    src = ('import jax\n\n@jax.jit\ndef f(x):\n'
           '    return float(x)  # jitlint: ok(tracer-cast)\n')
    findings, _ = _lint_src(tmp_path, src)
    rules = sorted(f.rule for f in findings if not f.waived)
    # the waiver is malformed: the finding stays AND the bare waiver
    # is itself reported
    assert "tracer-cast" in rules
    assert "bad-waiver" in rules
