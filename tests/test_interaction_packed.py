"""Occupancy-packed chunk spread/interp: agreement with the scatter
oracle, adjointness, chunk-capacity overflow exactness, and clustered
(silhouette-like) distributions where packing beats the fixed-cap pool."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import interaction
from ibamr_tpu.ops.interaction_packed import (PackedInteraction,
                                              pack_markers, suggest_chunks)

F64 = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def _markers(n, dim, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.rand(n, dim), dtype=F64)


@pytest.mark.parametrize("dim,n", [(2, 32), (3, 16)])
@pytest.mark.parametrize("kernel", ["IB_4", "IB_3", "BSPLINE_4"])
def test_matches_scatter_path(dim, n, kernel):
    grid = StaggeredGrid(n=(n,) * dim, x_lo=(0,) * dim, x_up=(1,) * dim)
    X = _markers(300, dim)
    rng = np.random.RandomState(1)
    F = jnp.asarray(rng.randn(300, dim), dtype=F64)
    mask = jnp.asarray((rng.rand(300) > 0.1).astype(np.float64), dtype=F64)
    Q = suggest_chunks(grid, X, kernel=kernel, tile=8, chunk=16)
    eng = PackedInteraction(grid, kernel=kernel, tile=8, chunk=16,
                            nchunks=Q)

    f_ref = interaction.spread_vel(F, grid, X, kernel=kernel, weights=mask)
    f_new = eng.spread_vel(F, X, weights=mask)
    for a, b in zip(f_ref, f_new):
        scale = float(jnp.max(jnp.abs(a))) + 1e-12
        assert float(jnp.max(jnp.abs(a - b))) < 1e-5 * scale

    u = tuple(jnp.asarray(rng.randn(*grid.n), dtype=F64)
              for _ in range(dim))
    U_ref = interaction.interpolate_vel(u, grid, X, kernel=kernel,
                                        weights=mask)
    U_new = eng.interpolate_vel(u, X, weights=mask)
    scale = float(jnp.max(jnp.abs(U_ref))) + 1e-12
    assert float(jnp.max(jnp.abs(U_ref - U_new))) < 1e-5 * scale


def test_hot_tile_takes_many_chunks_no_overflow():
    # all markers clustered in ONE tile: the fixed-cap engine would
    # overflow at cap=16; the packed engine allocates ceil(200/16)
    # chunks to that tile and stays on the dense path
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    rng = np.random.RandomState(2)
    X = jnp.asarray(0.1 + 0.05 * rng.rand(200, 2), dtype=F64)
    F = jnp.asarray(rng.randn(200, 2), dtype=F64)
    eng = PackedInteraction(grid, tile=8, chunk=16, nchunks=32)
    b = eng.buckets(X)
    assert not bool(b.any_overflow)
    # chunks of the hot tile are contiguous and share a tile id
    used = np.asarray(jnp.sum(b.wb > 0, axis=1))
    assert used.sum() == 200 and (used > 0).sum() == 13  # ceil(200/16)
    f_ref = interaction.spread_vel(F, grid, X)
    f_new = eng.spread_vel(F, X)
    for a, c in zip(f_ref, f_new):
        assert float(jnp.max(jnp.abs(a - c))) < 1e-5 * (
            float(jnp.max(jnp.abs(a))) + 1e-12)


def test_chunk_capacity_overflow_exact():
    # nchunks too small -> excess markers flow through the compact
    # scatter fallback; result must STILL match the oracle exactly
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    rng = np.random.RandomState(3)
    X = jnp.asarray(rng.rand(400, 2), dtype=F64)
    F = jnp.asarray(rng.randn(400, 2), dtype=F64)
    eng = PackedInteraction(grid, tile=8, chunk=8, nchunks=6)
    b = eng.buckets(X)
    assert bool(b.any_overflow)
    f_ref = interaction.spread_vel(F, grid, X)
    f_new = eng.spread_vel(F, X)
    for a, c in zip(f_ref, f_new):
        assert float(jnp.max(jnp.abs(a - c))) < 1e-5 * (
            float(jnp.max(jnp.abs(a))) + 1e-12)
    u = tuple(jnp.asarray(rng.randn(32, 32), dtype=F64) for _ in range(2))
    U_ref = interaction.interpolate_vel(u, grid, X)
    U_new = eng.interpolate_vel(u, X)
    assert float(jnp.max(jnp.abs(U_ref - U_new))) < 1e-5


def test_adjointness():
    grid = StaggeredGrid(n=(16, 16, 16), x_lo=(0,) * 3, x_up=(1,) * 3)
    X = _markers(150, 3, seed=3)
    rng = np.random.RandomState(4)
    F = jnp.asarray(rng.randn(150, 3), dtype=F64)
    u = tuple(jnp.asarray(rng.randn(16, 16, 16), dtype=F64)
              for _ in range(3))
    eng = PackedInteraction(grid, tile=8, chunk=32, nchunks=16)
    b = eng.buckets(X)
    f = eng.spread_vel(F, X, b=b)
    U = eng.interpolate_vel(u, X, b=b)
    h3 = float(np.prod(grid.dx))
    lhs = sum(float(jnp.sum(a * c)) for a, c in zip(f, u)) * h3
    rhs = float(jnp.sum(F * U))
    assert abs(lhs - rhs) < 1e-5 * (abs(lhs) + abs(rhs) + 1e-12)


def test_shell_silhouette_packing_efficiency():
    # flagship-shaped distribution (spherical shell): packed slots must
    # be a small multiple of N where the fixed-cap pool pads by ~10x
    from ibamr_tpu.models.shell3d import make_spherical_shell
    from ibamr_tpu.ops.interaction_fast import suggest_cap

    grid = StaggeredGrid(n=(64, 64, 64), x_lo=(0,) * 3, x_up=(1,) * 3)
    s = make_spherical_shell(80, 80, 0.25, (0.5, 0.5, 0.5), 1.0)
    N = s.vertices.shape[0]
    Q = suggest_chunks(grid, s.vertices, tile=8, chunk=64)
    packed_slots = Q * 64
    cap = suggest_cap(grid, s.vertices, tile=8)
    pool_slots = 8 * 8 * cap
    assert packed_slots < 4 * N
    assert packed_slots < pool_slots / 2

    eng = PackedInteraction(grid, tile=8, chunk=64, nchunks=Q)
    X = jnp.asarray(s.vertices, dtype=F64)
    b = eng.buckets(X)
    assert not bool(b.any_overflow)
    F = jnp.ones((N, 3), dtype=F64)
    f_ref = interaction.spread_vel(F, grid, X)
    f_new = eng.spread_vel(F, X)
    for a, c in zip(f_ref, f_new):
        assert float(jnp.max(jnp.abs(a - c))) < 1e-5 * (
            float(jnp.max(jnp.abs(a))) + 1e-12)


def test_jit_stability_and_position_reuse():
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    X = _markers(500, 2, seed=6)
    Q = suggest_chunks(grid, X, tile=8, chunk=32)
    eng = PackedInteraction(grid, tile=8, chunk=32, nchunks=Q)
    F = jnp.ones((500, 2), dtype=F64)

    @jax.jit
    def go(F, X):
        b = eng.buckets(X)
        f = eng.spread_vel(F, X, b=b)
        U = eng.interpolate_vel(f, X, b=b)
        return f, U

    f1, U1 = go(F, X)
    f2, U2 = go(F, X + 0.002)   # same shapes -> cached compile
    assert np.isfinite(np.asarray(f1[0])).all()
    assert np.isfinite(np.asarray(U2)).all()


def test_bf16_compute_matches_f32_within_tolerance():
    """bf16-compressed contraction operands (the HBM-halving opt-in):
    spread and interp agree with the exact-f32 engines to bf16 weight
    precision (~4e-3 relative), and adjointness survives at that
    tolerance."""
    g = StaggeredGrid(n=(32, 32, 32), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    rng = np.random.default_rng(5)
    N = 3000
    X = jnp.asarray(0.15 + 0.7 * rng.random((N, 3)), jnp.float32)
    F = jnp.asarray(rng.standard_normal((N, 3)), jnp.float32)
    u = tuple(jnp.asarray(rng.standard_normal(g.n), jnp.float32)
              for _ in range(3))

    from ibamr_tpu.ops.interaction_fast import FastInteraction
    for mk in (lambda **kw: FastInteraction(g, tile=8, cap=256, **kw),
               lambda **kw: PackedInteraction(g, tile=8, chunk=128,
                                              nchunks=64, **kw)):
        exact = mk()
        comp = mk(compute_dtype=jnp.bfloat16)
        f0 = exact.spread_vel(F, X)
        f1 = comp.spread_vel(F, X)
        scale = max(float(jnp.max(jnp.abs(c))) for c in f0)
        err = max(float(jnp.max(jnp.abs(a - b)))
                  for a, b in zip(f0, f1))
        assert err < 8e-3 * scale, (type(exact).__name__, err, scale)

        U0 = exact.interpolate_vel(u, X)
        U1 = comp.interpolate_vel(u, X)
        uscale = float(jnp.max(jnp.abs(U0)))
        uerr = float(jnp.max(jnp.abs(U0 - U1)))
        assert uerr < 8e-3 * uscale, (type(exact).__name__, uerr)

        # adjointness at bf16 tolerance: <spread(F), u> == <F, interp(u)>
        lhs = sum(float(jnp.sum(a * b)) for a, b in
                  zip(comp.spread_vel(F, X), u))
        rhs = float(jnp.sum(F * comp.interpolate_vel(u, X))) \
            / float(np.prod(g.dx))
        assert abs(lhs - rhs) < 2e-2 * max(abs(lhs), abs(rhs), 1e-6), \
            (lhs, rhs)


def test_transfer_engine_input_key():
    """The reference-style input knob IBMethod{transfer_engine=...}
    selects the engine in build_shell_example; unknown names raise."""
    import pytest

    from ibamr_tpu.models.shell3d import build_shell_example
    from ibamr_tpu.utils.input_db import parse_input_string

    def db_for(eng):
        return parse_input_string(f'''
CartesianGeometry {{ n_cells = 16, 16, 16 }}
Shell {{ n_lat = 24 n_lon = 24 }}
IBMethod {{ transfer_engine = "{eng}" }}
''')

    for eng, cls in (("packed", "PackedInteraction"),
                     ("scatter", "NoneType"),
                     ("mxu", "FastInteraction"),
                     ("mxu_bf16", "FastInteraction")):
        integ, _ = build_shell_example(input_db=db_for(eng))
        assert type(integ.ib.fast).__name__ == cls, eng
    with pytest.raises(ValueError, match="transfer_engine"):
        build_shell_example(input_db=db_for("bf16"))
