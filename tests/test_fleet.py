"""Fleet mode (PR 7): vmapped ensemble lanes with per-lane triage,
quarantine, rollback, and lane-sliced incident capsules.

The contract under test is LANE ISOLATION (docs/RESILIENCE.md):

- lane k of a B-lane fleet is bitwise the state it would hold run
  alone (a B=1 fleet is THE solo reference — the masked vmapped chunk
  is batch-size invariant);
- a poisoned lane's fault never perturbs the other lanes' bits, and
  recovery (rollback, dt backoff, quarantine) costs the bad lane at
  most one checkpoint interval while the healthy lanes never stop;
- the whole episode — backoff'd dt vectors, flipped alive masks — runs
  through ONE compiled trace per (B, chunk length);
- the per-lane checkpoint sidecar CRCs make a lane-corrupt step
  PARTIALLY restorable (``restore_lane``, ``ckpt_fsck`` "partial"),
  and a lane-sliced capsule replays bitwise unbatched.
"""

import json
import math
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
from ibamr_tpu.utils.checkpoint import restore_lane, save_checkpoint
from ibamr_tpu.utils.health import HealthDegraded, HealthProbe
from ibamr_tpu.utils.hierarchy_driver import HierarchyDriver, RunConfig
from ibamr_tpu.utils.lanes import lane_slice, stack_lanes
from ibamr_tpu.utils.supervisor import ResilientDriver
from ibamr_tpu.utils.watchdog import RunWatchdog
from tools.fault_injection import lane_nan_injector

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ins(n=16, mu=0.01):
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    return INSStaggeredIntegrator(g, rho=1.0, mu=mu, dtype=jnp.float64)


def _tg_state(integ, amp=1.0):
    g = integ.grid
    xf, yc = g.face_centers(0, jnp.float64)
    xc, yf = g.face_centers(1, jnp.float64)
    u = amp * jnp.sin(2 * math.pi * xf) * jnp.cos(2 * math.pi * yc) \
        + 0 * yc
    v = -amp * jnp.cos(2 * math.pi * xc) * jnp.sin(2 * math.pi * yf) \
        + 0 * xc
    return integ.initialize(u0_arrays=(u, v))


def _lane_states(integ, B):
    """B distinct Taylor-Green lanes (per-lane amplitude)."""
    return [_tg_state(integ, amp=1.0 + 0.05 * i) for i in range(B)]


def _bitwise_equal(a, b) -> bool:
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return all(np.asarray(x).tobytes() == np.asarray(y).tobytes()
               for x, y in zip(la, lb))


def _solo_run(integ, st, num_steps, dt, health_interval=2):
    """THE solo reference: the same lane as a B=1 masked fleet."""
    drv = HierarchyDriver(
        integ, RunConfig(dt=dt, num_steps=num_steps,
                         health_interval=health_interval), lanes=1)
    return lane_slice(drv.run(stack_lanes([st])), 0)


# ---------------------------------------------------------------------------
# batch-size invariance: lane k of B == the same lane alone
# ---------------------------------------------------------------------------

def test_lane_of_fleet_matches_solo_bitwise():
    integ = _ins()
    B, steps, dt = 3, 4, 1e-3
    states = _lane_states(integ, B)
    drv = HierarchyDriver(
        integ, RunConfig(dt=dt, num_steps=steps, health_interval=2),
        lanes=B)
    fleet_final = drv.run(stack_lanes(states))
    for i in range(B):
        solo = _solo_run(integ, states[i], steps, dt)
        assert _bitwise_equal(lane_slice(fleet_final, i), solo), \
            f"lane {i} of B={B} differs from its solo run"


def test_fleet_rejects_bad_lane_configs():
    integ = _ins()
    with pytest.raises(ValueError, match="lanes"):
        HierarchyDriver(integ, RunConfig(dt=1e-3, num_steps=2), lanes=0)
    with pytest.raises(ValueError, match="cfl"):
        HierarchyDriver(integ, RunConfig(dt=1e-3, num_steps=2, cfl=0.5),
                        lanes=2)


# ---------------------------------------------------------------------------
# quarantine: one bad lane must not sink (or even touch) the fleet
# ---------------------------------------------------------------------------

def test_quarantine_leaves_healthy_lanes_bitwise_untouched(tmp_path):
    integ = _ins()
    B, BAD, steps, dt = 4, 1, 8, 1e-3
    states = _lane_states(integ, B)
    inj = dict(at_step=4, lane=BAD, fleet_size=B, leaf_path="u[0]",
               step_attr="k")
    drv = HierarchyDriver(
        integ, RunConfig(dt=dt, num_steps=steps, health_interval=2,
                         restart_interval=2),
        lanes=B, fleet_step_wrap=lambda s: lane_nan_injector(s, **inj))
    sup = ResilientDriver(drv, str(tmp_path), max_retries=0,
                          handle_signals=False)
    final = sup.run(stack_lanes(states))

    assert not drv.lane_alive[BAD]
    assert all(drv.lane_alive[i] for i in range(B) if i != BAD)
    quar = [r for r in sup.incidents if r.get("event")
            == "lane_quarantine"]
    assert len(quar) == 1 and quar[0]["lane"] == BAD
    # the quarantined lane was restored (finite) and frozen at the
    # rollback step's state
    bad = lane_slice(final, BAD)
    assert all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree_util.tree_leaves(bad))
    assert int(np.asarray(bad.k)) == quar[0]["rollback_step"]
    # healthy lanes: full progress, bitwise equal to their CLEAN solo
    # runs — the poisoned lane's NaNs and the flipped alive mask never
    # touched their bits
    for i in range(B):
        if i == BAD:
            continue
        got = lane_slice(final, i)
        assert int(np.asarray(got.k)) == steps
        assert _bitwise_equal(got, _solo_run(integ, states[i], steps,
                                             dt)), \
            f"healthy lane {i} perturbed by lane {BAD}'s fault"
    # the whole episode (fault, quarantine restore, resumed chunks)
    # reused one trace per chunk length
    assert all(v == 1 for v in drv.trace_counts.values()), \
        drv.trace_counts


def test_fleet_gives_up_past_quarantine_threshold(tmp_path):
    integ = _ins()
    B, steps, dt = 2, 8, 1e-3
    states = _lane_states(integ, B)

    def poison_all(s):
        for lane in range(B):
            s = lane_nan_injector(s, at_step=2, lane=lane, fleet_size=B,
                                  leaf_path="u[0]", step_attr="k")
        return s

    drv = HierarchyDriver(
        integ, RunConfig(dt=dt, num_steps=steps, health_interval=2,
                         restart_interval=2),
        lanes=B, fleet_step_wrap=poison_all)
    sup = ResilientDriver(drv, str(tmp_path), max_retries=0,
                          handle_signals=False)
    with pytest.raises(HealthDegraded, match="lanes quarantined"):
        sup.run(stack_lanes(states))
    assert any(r.get("event") == "fleet_give_up"
               for r in sup.incidents)


# ---------------------------------------------------------------------------
# per-lane rollback: dt backoff cures a marginal lane in place
# ---------------------------------------------------------------------------

def test_per_lane_rollback_loses_at_most_one_interval(tmp_path):
    integ = _ins()
    B, BAD, steps, dt = 3, 1, 8, 1e-3
    states = _lane_states(integ, B)
    # dt-gated poison: fires at k==4 only at full dt, so ONE rollback
    # with dt backoff cures the lane in place (no quarantine)
    inj = dict(at_step=4, lane=BAD, fleet_size=B, leaf_path="u[0]",
               step_attr="k", dt_gate=dt)
    drv = HierarchyDriver(
        integ, RunConfig(dt=dt, num_steps=steps, health_interval=2,
                         restart_interval=2),
        lanes=B, fleet_step_wrap=lambda s: lane_nan_injector(s, **inj))
    sup = ResilientDriver(drv, str(tmp_path), max_retries=1,
                          dt_backoff=0.5, handle_signals=False)
    final = sup.run(stack_lanes(states))

    rolls = [r for r in sup.incidents if r.get("event")
             == "lane_rollback"]
    assert len(rolls) == 1 and rolls[0]["lane"] == BAD
    assert rolls[0]["from_checkpoint"] and rolls[0]["rollback_step"] == 2
    assert not any(r.get("event") == "lane_quarantine"
                   for r in sup.incidents)
    assert all(drv.lane_alive)
    # only the bad lane's dt backed off; only it lost the rollback gap
    assert drv.lane_dt[BAD] == pytest.approx(0.5 * dt)
    for i in range(B):
        k = int(np.asarray(lane_slice(final, i).k))
        if i == BAD:
            # fault at step 4, newest checkpoint at step 2: the lane
            # re-stepped from 2 — exactly one interval behind at the end
            assert k == steps - 2
        else:
            assert k == steps
            assert drv.lane_dt[i] == pytest.approx(dt)
            assert _bitwise_equal(lane_slice(final, i),
                                  _solo_run(integ, states[i], steps, dt))
    assert all(v == 1 for v in drv.trace_counts.values()), \
        drv.trace_counts


# ---------------------------------------------------------------------------
# trace economy: dt backoff and mask flips are traced arguments
# ---------------------------------------------------------------------------

def test_one_trace_signature_per_chunk_length():
    integ = _ins()
    B, dt = 4, 1e-3
    states = _lane_states(integ, B)
    drv = HierarchyDriver(
        integ, RunConfig(dt=dt, num_steps=4, health_interval=2),
        lanes=B)
    drv.run(stack_lanes(states))
    assert drv.trace_counts == {2: 1}
    # new per-lane dt values and a dead lane are VALUE changes of
    # traced arguments, not new signatures
    drv.lane_dt[0] = 0.25 * dt
    drv.lane_alive[2] = False
    drv.run(stack_lanes(states))
    assert drv.trace_counts == {2: 1}


# ---------------------------------------------------------------------------
# lane-aware health plumbing
# ---------------------------------------------------------------------------

def test_unpack_accepts_lane_matrix_and_stays_compatible():
    B = 5
    mat = np.arange(7 * B, dtype=np.float64).reshape(7, B)
    d = HealthProbe.unpack(mat)
    for name in HealthProbe.VITALS_FIELDS:
        assert np.asarray(d[name]).shape == (B,)
    assert np.array_equal(d[HealthProbe.VITALS_FIELDS[0]], mat[0])
    # rank-1 (solo) and short older-schema vectors still unpack
    solo = HealthProbe.unpack(np.arange(7.0))
    assert solo[HealthProbe.VITALS_FIELDS[3]] == 3.0
    old = HealthProbe.unpack(np.arange(5.0))
    assert np.isnan(old[HealthProbe.VITALS_FIELDS[6]])


def test_watchdog_heartbeat_carries_lane_triage(tmp_path):
    hb = str(tmp_path / "hb.json")
    wd = RunWatchdog(heartbeat_path=hb)
    wd.beat(step=3)
    payload = json.load(open(hb))
    assert "lanes_ok" not in payload          # solo schema unchanged
    wd.beat(step=4, lanes_ok=6, lanes_quarantined=1, lanes_retrying=1)
    payload = json.load(open(hb))
    assert payload["lanes_ok"] == 6
    assert payload["lanes_quarantined"] == 1
    assert payload["lanes_retrying"] == 1


# ---------------------------------------------------------------------------
# per-lane checkpoint slices: restore_lane + fsck "partial"
# ---------------------------------------------------------------------------

def _lane_stacked_state(B, seed):
    rng = np.random.default_rng(seed)
    return {"u": rng.standard_normal((B, 6, 6)),
            "p": rng.standard_normal((B, 4))}


def _corrupt_lane_slice(directory, step, lane, key="u"):
    fname = os.path.join(directory, f"restore.{step:08d}.npz")
    z = dict(np.load(fname))
    z[key][lane] = z[key][lane] + 1.0
    np.savez(fname, **z)


def test_restore_lane_verifies_slice_and_falls_back(tmp_path):
    d = str(tmp_path)
    B, BAD = 4, 2
    for step in (2, 4):
        save_checkpoint(d, _lane_stacked_state(B, seed=step), step,
                        lanes=B)
    _corrupt_lane_slice(d, 4, BAD)
    template = _lane_stacked_state(B, seed=0)

    # healthy lane: newest step serves it (per-lane CRC verifies even
    # though the FILE digest no longer does)
    got = restore_lane(d, template, 0)
    assert got is not None
    state, step = got
    assert step == 4
    assert np.array_equal(np.asarray(state["u"])[0],
                          _lane_stacked_state(B, seed=4)["u"][0])
    # only the requested lane's slice was patched into the template
    assert np.array_equal(np.asarray(state["u"])[1], template["u"][1])

    # corrupt lane: newest step's slice fails its CRC -> falls back to
    # the older verified step
    with pytest.warns(UserWarning):
        got = restore_lane(d, template, BAD)
    assert got is not None
    state, step = got
    assert step == 2
    assert np.array_equal(np.asarray(state["u"])[BAD],
                          _lane_stacked_state(B, seed=2)["u"][BAD])


def test_ckpt_fsck_flags_lane_corrupt_step_partial(tmp_path):
    from tools.ckpt_fsck import audit, repair_dir

    d = str(tmp_path)
    B, BAD = 4, 1
    for step in (2, 4):
        save_checkpoint(d, _lane_stacked_state(B, seed=step), step,
                        lanes=B)
    _corrupt_lane_slice(d, 4, BAD)

    report = audit(d)
    assert not report["clean"]
    assert report["counts"]["partial"] == 1
    assert report["counts"]["corrupt"] == 0
    (dir_rep,) = report["dirs"]
    rec = next(r for r in dir_rep["steps"] if r["step"] == 4)
    assert rec["status"] == "partial"
    assert rec["lanes"]["lanes_bad"] == [BAD]
    assert BAD not in rec["lanes"]["lanes_ok"]
    # partial is not fully verified: the older intact step stays newest
    assert dir_rep["newest_verified"] == 2
    # repair never quarantines a partial step — its intact lanes are
    # restore_lane's source after a lane fault
    assert repair_dir(dir_rep) == []
    assert os.path.exists(os.path.join(d, "restore.00000004.npz"))


# ---------------------------------------------------------------------------
# lane-sliced capsule replay + the end-to-end drill (slow tier)
# ---------------------------------------------------------------------------

def test_sliced_capsule_replays_bitwise(tmp_path):
    """A fleet incident's capsule is ONE lane, replayable unbatched."""
    from ibamr_tpu.models.shell3d import build_shell_example
    from ibamr_tpu.utils.flight_recorder import (FlightRecorder,
                                                 factory_spec)
    from tools.fault_injection import recorded
    from tools.replay import replay

    kwargs = dict(n_cells=16, n_lat=8, n_lon=8, mu=0.05,
                  dtype="float64")
    integ, st0 = build_shell_example(**kwargs)
    B, BAD, dt = 2, 1, 1e-3
    states = [st0, st0._replace(ins=st0.ins._replace(
        u=tuple(c * 1.01 + 1e-4 for c in st0.ins.u)))]
    inj = dict(at_step=2, lane=BAD, fleet_size=B, leaf_path="u[0]",
               step_attr="ins.k")
    with recorded("lane_nan", **inj):
        drv = HierarchyDriver(
            integ, RunConfig(dt=dt, num_steps=4, health_interval=2,
                             restart_interval=2),
            lanes=B,
            fleet_step_wrap=lambda s: lane_nan_injector(s, **inj),
            recorder=FlightRecorder(capacity=4, spec=factory_spec(
                "ibamr_tpu.models.shell3d", "build_shell_example",
                **kwargs)))
        sup = ResilientDriver(drv, str(tmp_path / "ck"),
                              max_retries=0, handle_signals=False)
        sup.run(stack_lanes(states))

    quar = [r for r in sup.incidents
            if r.get("event") == "lane_quarantine"]
    assert len(quar) == 1 and quar[0]["replay"]
    cap = quar[0]["replay"]
    manifest = json.load(open(os.path.join(cap, "manifest.json")))
    assert manifest["lane"] == {"index": BAD, "fleet_size": B}
    res = replay(cap)
    assert res["verdict"] == "reproduced", res


def test_fleet_smoke_drill_end_to_end(tmp_path):
    """The CI drill (dryrun path 20) in a subprocess: B=8 shell fleet,
    NaN in one lane, rollback + backoff + quarantine, healthy lanes
    bitwise vs solo, sliced capsule replayed ``reproduced``."""
    r = subprocess.run(
        [sys.executable, "-m", "tools.fault_injection", "--fleet-smoke",
         "--dir", str(tmp_path / "drill")],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=900)
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["fleet_smoke"] == "ok"
    assert out["replay_verdict"] == "reproduced"
    assert out["lane_quarantines"] == 1
