"""IBFE surface method + direct-forcing kinematics (P17 round 3).

Oracles: rigid motion gives identity surface strain and zero membrane
force (EDGE2 and TRI3S); uniform stretch of a ring matches the analytic
membrane energy; an inflated sphere's membrane force points inward;
spread conserves total force; a stretched elliptic ring immersed in
fluid relaxes toward the circle releasing membrane energy with the
enclosed area conserved; a direct-forced disc tracks its prescribed
oscillation.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.fe import surface
from ibamr_tpu.grid import StaggeredGrid

F64 = jnp.float64


@pytest.mark.parametrize("mesh", [
    surface.ring_mesh(n=48),
    surface.sphere_surface_mesh(n_subdiv=1),
])
def test_rigid_motion_identity_strain_zero_force(mesh):
    asm = surface.build_surface_assembly(mesh, dtype=F64)
    d = mesh.dim
    th = 0.3
    if d == 2:
        R = np.array([[np.cos(th), -np.sin(th)],
                      [np.sin(th), np.cos(th)]])
    else:
        R = np.array([[np.cos(th), -np.sin(th), 0],
                      [np.sin(th), np.cos(th), 0], [0, 0, 1.0]])
    x = jnp.asarray(mesh.nodes @ R.T + 0.1)
    M = surface.surface_strain(asm, x)
    eye = np.broadcast_to(np.eye(asm.rdim), np.asarray(M).shape)
    assert np.allclose(np.asarray(M), eye, atol=1e-10)
    W = surface.neo_hookean_membrane(1.0, 2.0)
    F = surface.membrane_forces(asm, W, x)
    assert float(jnp.max(jnp.abs(F))) < 1e-9


def test_ring_uniform_stretch_analytic_energy():
    """Scaling a circle by lam stretches every element by lam: M =
    lam^2, E = perimeter_ref * W(lam^2)."""
    r, n = 0.25, 96
    mesh = surface.ring_mesh(radius=r, n=n)
    asm = surface.build_surface_assembly(mesh, dtype=F64)
    lam = 1.2
    c = np.array([0.5, 0.5])
    x = jnp.asarray(c + lam * (mesh.nodes - c))
    W = surface.neo_hookean_membrane(1.3, 0.7)
    E = float(surface.membrane_energy(asm, W, x))
    M_an = jnp.asarray([[lam ** 2]])
    # reference perimeter of the POLYGON (that's what the mesh measures)
    per = n * 2.0 * r * math.sin(math.pi / n)
    assert np.isclose(E, per * float(W(M_an)), rtol=1e-10)
    # current measure scales by lam
    assert np.isclose(float(surface.current_area(asm, x)),
                      lam * per, rtol=1e-10)


def test_inflated_sphere_force_points_inward():
    mesh = surface.sphere_surface_mesh(n_subdiv=2)
    asm = surface.build_surface_assembly(mesh, dtype=F64)
    c = np.array([0.5, 0.5, 0.5])
    x = jnp.asarray(c + 1.3 * (mesh.nodes - c))
    W = surface.neo_hookean_membrane(1.0, 2.0)
    F = surface.membrane_forces(asm, W, x)
    radial = np.einsum("ni,ni->n", np.asarray(F),
                       np.asarray(x) - c)
    assert (radial < 0).mean() > 0.99      # restoring toward the center
    assert np.allclose(np.asarray(jnp.sum(F, axis=0)), 0.0, atol=1e-9)


@pytest.mark.parametrize("coupling", ["nodal", "unified"])
def test_spread_conserves_total_force(coupling):
    from ibamr_tpu.integrators.ibfe import IBFESurfaceMethod

    g = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    mesh = surface.ring_mesh(n=40)
    m = IBFESurfaceMethod(mesh, surface.neo_hookean_membrane(1.0, 2.0),
                          coupling=coupling, dtype=F64)
    rng = np.random.default_rng(0)
    F = jnp.asarray(rng.standard_normal((mesh.n_nodes, 2)))
    mask = jnp.ones(mesh.n_nodes, dtype=F64)
    fgrid = m.spread_force(F, g, jnp.asarray(mesh.nodes), mask)
    vol = g.dx[0] * g.dx[1]
    for d in range(2):
        assert np.isclose(float(jnp.sum(fgrid[d])) * vol,
                          float(jnp.sum(F[:, d])), rtol=1e-8)


def test_elliptic_ring_relaxes_in_fluid():
    """The membrane IB classic, on the surface-FE path: a stretched
    elliptic ring releases membrane energy while the fluid keeps the
    enclosed area nearly conserved."""
    from ibamr_tpu.integrators.ib import IBExplicitIntegrator, advance_ib
    from ibamr_tpu.integrators.ibfe import IBFESurfaceMethod
    from ibamr_tpu.integrators.ins import INSStaggeredIntegrator

    g = StaggeredGrid(n=(64, 64), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    ins = INSStaggeredIntegrator(g, mu=0.1, rho=1.0)
    # REFERENCE is the circle; the initial POSITIONS are an ellipse
    # (area-preserving anisotropic stretch), so membrane energy is
    # stored at t=0 and released as the ring rounds up
    mesh = surface.ring_mesh(radius=0.18, n=96)
    fe = IBFESurfaceMethod(mesh,
                           surface.neo_hookean_membrane(0.0, 5.0),
                           coupling="unified", dtype=ins.dtype)
    integ = IBExplicitIntegrator(ins, fe)
    c = np.array([0.5, 0.5])
    X0 = c + (mesh.nodes - c) * np.array([1.3, 1.0 / 1.3])
    st = integ.initialize(jnp.asarray(X0, dtype=ins.dtype))
    E0 = float(fe.energy(st.X))

    def enclosed_area(X):
        x, y = np.asarray(X[:, 0]), np.asarray(X[:, 1])
        return 0.5 * abs(np.sum(x * np.roll(y, -1) - np.roll(x, -1) * y))

    A0 = enclosed_area(st.X)
    st = jax.block_until_ready(advance_ib(integ, st, 1e-3, 400))
    E1 = float(fe.energy(st.X))
    A1 = enclosed_area(st.X)
    assert np.isfinite(E1) and E1 < 0.6 * E0, (E0, E1)
    assert abs(A1 - A0) < 0.02 * A0, (A0, A1)


def test_direct_forcing_tracks_prescribed_motion():
    from ibamr_tpu.fe.mesh import disc_mesh
    from ibamr_tpu.fe.fem import neo_hookean
    from ibamr_tpu.integrators.ib import IBExplicitIntegrator, advance_ib
    from ibamr_tpu.integrators.ibfe import (DirectForcingKinematics,
                                            IBFEMethod)
    from ibamr_tpu.integrators.ins import INSStaggeredIntegrator

    g = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    ins = INSStaggeredIntegrator(g, mu=0.05, rho=1.0)
    mesh = disc_mesh(radius=0.12, n_rings=3)
    X_ref = jnp.asarray(mesh.nodes, dtype=ins.dtype)
    amp, om = 0.08, 2.0 * math.pi

    def target(t):
        return X_ref + amp * jnp.sin(om * t) * jnp.asarray([1.0, 0.0])

    base = IBFEMethod(mesh, neo_hookean(1.0, 4.0), dtype=ins.dtype)
    df = DirectForcingKinematics(base, target, kappa=2e3, eta=2.0)
    integ = IBExplicitIntegrator(ins, df)
    st = integ.initialize(X_ref)
    dt = 1e-3
    st = jax.block_until_ready(advance_ib(integ, st, dt, 500))
    t_end = 500 * dt
    Xt = np.asarray(target(t_end))
    err = np.abs(np.asarray(st.X) - Xt).max()
    assert err < 0.25 * amp, (err, amp)
    # the dragged fluid actually moves
    assert float(jnp.max(jnp.abs(st.ins.u[0]))) > 0.05 * amp * om
