"""Open-boundary x VC two-phase composition (round 5, VERDICT item 3a):
the numerical wave tank with a REAL outflow boundary — axis 0 runs
wall(lo) -> generation zone -> working region -> beach -> OUTLET(hi),
with the still-referenced hydrostatic pressure making the outlet's
homogeneous Dirichlet exact. Reference: the open-BC'd
``INSVCStaggeredHierarchyIntegrator`` + wave generation/damping zones
(SURVEY.md P22 [U])."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ins_vc import INSVCStaggeredIntegrator

F64 = jnp.float64


def _still_phi(grid, still):
    zax = grid.dim - 1
    z = (np.arange(grid.n[zax]) + 0.5) * grid.dx[zax] + grid.x_lo[zax]
    shape = [1] * grid.dim
    shape[zax] = grid.n[zax]
    return jnp.asarray(np.broadcast_to(
        z.reshape(shape) - still, grid.n), dtype=F64)


def _tank(n=(48, 32), L=1.5, H=1.0, still=0.5, rho_ratio=100.0,
          **kw):
    g = StaggeredGrid(n=n, x_lo=(0.0, 0.0), x_up=(L, H))
    vc = INSVCStaggeredIntegrator(
        g, rho0=100.0, rho1=100.0 / rho_ratio, mu0=1e-3, mu1=1e-5,
        gravity=(0.0, -9.81), wall_axes=(False, True),
        open_outlet=True, still_level=still, dtype=F64,
        cg_tol=1e-10, **kw)
    return g, vc


def test_open_outlet_hydrostatic_quiescence():
    """Still water + gravity + open outlet: the still-referenced
    anomaly gravity makes p = 0 the exact solution, so the state stays
    EXACTLY quiescent — the sharp pin that the outlet Dirichlet, the
    projection assembly, and the gravity reference are consistent."""
    g, vc = _tank()
    st = vc.initialize(_still_phi(g, 0.5))
    for _ in range(20):
        st = vc.step(st, 1e-3)
    umax = max(float(jnp.max(jnp.abs(c))) for c in st.u)
    assert umax < 1e-10, umax
    assert float(jnp.max(jnp.abs(st.p))) < 1e-8


def test_open_outlet_passes_throughflow():
    """A relaxation zone drives a uniform current in the water phase;
    the CLOSED walled tank has no exit (the zone fights the back
    pressure and the surface tilts); the OPEN tank passes the flux:
    outlet volumetric flux approaches the driven flux and the free
    surface stays flat. The control run pins that the outlet is
    load-bearing, not decorative."""
    n = (48, 32)
    L, H, still, U0 = 1.5, 1.0, 0.5, 0.05
    g, vc = _tank(n=n, L=L, H=H, still=still)
    vc_closed = INSVCStaggeredIntegrator(
        g, rho0=100.0, rho1=1.0, mu0=1e-3, mu1=1e-5,
        gravity=(0.0, -9.81), wall_axes=(True, True), dtype=F64,
        cg_tol=1e-10)

    x_f = np.arange(n[0]) * g.dx[0]          # u-face x positions
    zone = jnp.asarray((x_f < 0.3 * L).astype(np.float64))[:, None]
    phi0 = _still_phi(g, still)
    water_u = jnp.asarray(
        (np.asarray(phi0) < 0).astype(np.float64))

    def drive(vci, st, steps, dt=2e-3):
        def body(s, _):
            s = vci.step(s, dt)
            u0 = s.u[0] + zone * 0.5 * (U0 * water_u - s.u[0])
            s = s._replace(u=(u0,) + s.u[1:],
                           phi=s.phi + zone * 0.2 * (phi0 - s.phi))
            return s, None

        out, _ = jax.jit(lambda s: jax.lax.scan(body, s, None,
                                                length=steps))(st)
        return out

    st_o = drive(vc, vc.initialize(phi0), 700)
    st_c = drive(vc_closed, vc_closed.initialize(phi0), 700)

    # outlet flux (water column at the outlet face, slot 0 of u_x):
    # a genuine fraction of the driven flux leaves through the outlet
    # (the rest recirculates through the air phase above the surface)
    from ibamr_tpu.physics import level_set as ls

    dz = g.dx[1]
    out_face = np.asarray(st_o.u[0])[0, :]
    wmask = np.asarray(phi0)[-1, :] < 0
    q_out = float(np.sum(out_face[wmask]) * dz)
    q_drive = U0 * still
    assert q_out > 0.4 * q_drive, (q_out, q_drive)

    # volume balance: the zone pumps water in both runs; only the
    # open tank lets it OUT again. Measured (deterministic, f64):
    # open +1.03%, closed +2.03% over the run — the closed control
    # pins that the outlet is load-bearing, not decorative.
    eps = 1.5 * max(g.dx)
    v0 = float(ls.phase_volume(phi0, g, eps))
    grow_o = (float(ls.phase_volume(st_o.phi, g, eps)) - v0) / v0
    grow_c = (float(ls.phase_volume(st_c.phi, g, eps)) - v0) / v0
    assert grow_o < 0.015, (grow_o, grow_c)
    assert grow_c > 1.7 * max(grow_o, 1e-9), (grow_o, grow_c)
    assert bool(jnp.all(jnp.isfinite(st_o.u[0])))


def test_open_outlet_wave_train_finite_and_bounded():
    """A generation zone radiates a wave train toward the outlet
    (short beach in between): the run stays finite, the gauge
    amplitude lands in a physical band of the target, and the water
    volume drifts by < 2% (the outlet does not drain the tank)."""
    from ibamr_tpu.physics import level_set as ls
    from ibamr_tpu.physics.waves import (StokesWave, apply_zone,
                                         make_zone, still_targets,
                                         wave_targets)

    n = (96, 32)
    L, H, still = 3.0, 1.0, 0.5
    g = StaggeredGrid(n=n, x_lo=(0.0, 0.0), x_up=(L, H))
    amp, wl = 0.02, 1.0
    wave = StokesWave(amplitude=amp, wavelength=wl,
                      still_level=still, depth=still)
    vc = INSVCStaggeredIntegrator(
        g, rho0=100.0, rho1=1.0, mu0=1e-3, mu1=1e-5,
        gravity=(0.0, -9.81), wall_axes=(False, True),
        open_outlet=True, still_level=still, dtype=F64, cg_tol=1e-9)
    gen = make_zone(g, 0.0, 0.8, "generation", "lo", dtype=F64)
    damp = make_zone(g, 2.2, 3.0, "damping", "hi", dtype=F64)
    phi0 = _still_phi(g, still)
    st = vc.initialize(phi0)

    T = 2.0 * np.pi / wave.omega
    dt = 2.5e-3
    steps = int(3.0 * T / dt)
    gauge_i = n[0] // 2
    dz = g.dx[1]
    phi_s, u_s = still_targets(g, still, dtype=F64)

    def body(s, _):
        s = vc.step(s, dt)
        r = jnp.clip(s.t / (1.5 * T), 0.0, 1.0)
        soft = 0.5 * (1.0 - jnp.cos(jnp.pi * r))
        phi_t, u_t = wave_targets(g, wave.scaled(soft), s.t,
                                  dtype=F64)
        phi, u = apply_zone(s.phi, s.u, gen, phi_t, u_t)
        phi, u = apply_zone(phi, u, damp, phi_s, u_s)
        s = s._replace(phi=phi, u=u)
        return s, s.phi[gauge_i, :]

    st, phi_gauge = jax.jit(lambda s: jax.lax.scan(
        body, s, None, length=steps))(st)
    zc = (np.arange(n[1]) + 0.5) * dz
    eta_hist = [float(np.interp(0.0, np.asarray(ph), zc)) - still
                for ph in np.asarray(phi_gauge)]

    assert bool(jnp.all(jnp.isfinite(st.u[0])))
    assert bool(jnp.all(jnp.isfinite(st.phi)))
    late = np.asarray(eta_hist[len(eta_hist) // 2:])
    peak = float(np.max(np.abs(late)))
    # gauge sees a genuine wave of the right scale (not still, not
    # breaking garbage)
    assert 0.3 * amp < peak < 3.0 * amp, peak
    # volume drift bounded: the outlet passes waves, not the tank
    eps = 1.5 * max(g.dx)
    v0 = float(ls.phase_volume(phi0, g, eps))
    v1 = float(ls.phase_volume(st.phi, g, eps))
    assert abs(v1 - v0) / v0 < 0.02, (v0, v1)
