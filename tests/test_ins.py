"""Stage-3 acceptance (SURVEY.md §7.2 stage 3): Taylor-Green convergence,
exact discrete incompressibility, conservation properties.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator, advance
from ibamr_tpu.ops import stencils

TWO_PI = 2.0 * math.pi


def _tg_exact(g, t, nu, dtype=jnp.float64):
    decay = math.exp(-2.0 * TWO_PI ** 2 * nu * t)
    xf, yc = g.face_centers(0, dtype)
    xc, yf = g.face_centers(1, dtype)
    u = jnp.sin(TWO_PI * xf) * jnp.cos(TWO_PI * yc) * decay + 0 * yc
    v = -jnp.cos(TWO_PI * xc) * jnp.sin(TWO_PI * yf) * decay + 0 * xc
    return u, v


def _tg_state(integ, g, nu):
    u0, v0 = _tg_exact(g, 0.0, nu, integ.dtype)
    st = integ.initialize(u0_arrays=(u0, v0))
    return st


def _run_tg(n, steps, T, nu, dtype=jnp.float64, scheme="centered"):
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    integ = INSStaggeredIntegrator(g, rho=1.0, mu=nu,
                                   convective_op_type=scheme, dtype=dtype)
    st = _tg_state(integ, g, nu)
    dt = T / steps
    st = advance(integ, st, dt, steps)
    ue, ve = _tg_exact(g, T, nu, dtype)
    err = max(float(jnp.max(jnp.abs(st.u[0] - ue))),
              float(jnp.max(jnp.abs(st.u[1] - ve))))
    return st, err, integ, g


def test_taylor_green_accuracy_and_convergence():
    nu, T = 0.01, 0.25
    _, e16, _, _ = _run_tg(16, 32, T, nu)
    _, e32, _, _ = _run_tg(32, 64, T, nu)
    order = math.log2(e16 / e32)
    assert e32 < 2.5e-3
    assert order > 1.7, (e16, e32, order)


def test_divergence_free_to_machine_precision():
    st, _, integ, g = _run_tg(32, 20, 0.1, 0.02)
    assert float(integ.max_divergence(st)) < 1e-11


def test_momentum_conserved_periodic():
    g = StaggeredGrid(n=(24, 24), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    integ = INSStaggeredIntegrator(g, rho=1.0, mu=0.005, dtype=jnp.float64)
    rng = np.random.default_rng(7)
    u0 = tuple(jnp.asarray(rng.standard_normal(g.n), dtype=jnp.float64) * 0.1
               for _ in range(2))
    st = integ.initialize(u0_arrays=u0)
    mom0 = [float(jnp.mean(c)) for c in st.u]
    st = advance(integ, st, 1e-3, 50)
    mom1 = [float(jnp.mean(c)) for c in st.u]
    np.testing.assert_allclose(mom1, mom0, atol=1e-13)


def test_kinetic_energy_decays_unforced():
    st, _, integ, _ = _run_tg(32, 40, 0.2, 0.02)
    ke_T = float(integ.kinetic_energy(st))
    nu = 0.02
    ke_exact = 0.25 * math.exp(-4.0 * TWO_PI ** 2 * nu * 0.2)
    assert ke_T < 0.25  # decayed from initial
    assert ke_T == pytest.approx(ke_exact, rel=2e-2)


def test_upwind_scheme_stable():
    st, err, integ, _ = _run_tg(32, 40, 0.2, 0.02, scheme="upwind")
    assert np.isfinite(err)
    # 1st-order upwind is diffusive but must stay bounded and div-free
    assert err < 0.2
    assert float(integ.max_divergence(st)) < 1e-11


def test_body_force_accelerates_fluid():
    g = StaggeredGrid(n=(16, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    integ = INSStaggeredIntegrator(g, rho=2.0, mu=0.01, dtype=jnp.float64)
    st = integ.initialize()
    f = (jnp.ones(g.n, dtype=jnp.float64),
         jnp.zeros(g.n, dtype=jnp.float64))
    st = advance(integ, st, 1e-2, 10, f=f)
    # du/dt = f/rho (uniform force on rest fluid; convection/viscosity nil)
    np.testing.assert_allclose(np.asarray(st.u[0]),
                               0.1 * 1.0 / 2.0, rtol=1e-10)


def test_step_inside_jit_and_3d():
    g = StaggeredGrid(n=(8, 8, 8), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    integ = INSStaggeredIntegrator(g, rho=1.0, mu=0.01, dtype=jnp.float32)
    rng = np.random.default_rng(1)
    u0 = tuple(jnp.asarray(rng.standard_normal(g.n), dtype=jnp.float32) * 0.1
               for _ in range(3))
    st = integ.initialize(u0_arrays=u0)
    stepped = jax.jit(lambda s: integ.step(s, 1e-3))(st)
    assert float(integ.max_divergence(stepped)) < 1e-5
    assert float(stepped.t) == pytest.approx(1e-3)


def test_initialize_with_vector_callable():
    from ibamr_tpu.utils.input_db import parse_input_string
    from ibamr_tpu.utils.gridfunctions import function_from_db
    g = StaggeredGrid(n=(8, 8), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    integ = INSStaggeredIntegrator(g, dtype=jnp.float64)
    db = parse_input_string("""
    V { function_0 = "sin(2*PI*X_0)"  function_1 = "0.0" }
    """)
    f = function_from_db(db.get_database("V"), dim=2)
    st = integ.initialize(u0=f)
    xf, _ = g.face_centers(0, jnp.float64)
    np.testing.assert_allclose(np.asarray(st.u[0]),
                               np.broadcast_to(np.sin(TWO_PI * np.asarray(xf)), g.n),
                               atol=1e-12)
