"""Pallas packed-chunk spread/interp (the round-3 engine composition:
occupancy-packed chunks + in-VMEM weights + revisit accumulation).

Runs in Pallas interpret mode on the CPU suite; the compiled-TPU path
is exercised by ``bench.py``. Oracle: the XLA scatter path at f32
tolerances. The revisit-accumulation correctness (multiple chunks of
ONE tile summing into the same output block) is pinned by the
clustered-markers case."""

import jax.numpy as jnp
import numpy as np

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import interaction
from ibamr_tpu.ops.interaction_packed import suggest_chunks
from ibamr_tpu.ops.pallas_interaction import PallasPackedInteraction


def _engine(g, X, chunk=64, slack=1.3, **kw):
    Q = suggest_chunks(g, X, tile=8, chunk=chunk, slack=slack)
    return PallasPackedInteraction(g, kernel="IB_4", tile=8, chunk=chunk,
                                   nchunks=Q, interpret=True, **kw)


def test_packed_pallas_matches_scatter():
    rng = np.random.default_rng(0)
    g = StaggeredGrid(n=(16, 16, 32), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    X = jnp.asarray(rng.uniform(0, 1, (300, 3)), dtype=jnp.float32)
    F = jnp.asarray(rng.standard_normal((300, 3)), dtype=jnp.float32)
    eng = _engine(g, X)
    b = eng.buckets(X)
    f_pl = eng.spread_vel(F, X, b=b)
    f_ref = interaction.spread_vel(F, g, X, kernel="IB_4")
    for a, c in zip(f_ref, f_pl):
        scale = float(jnp.max(jnp.abs(a)))
        np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                   atol=2e-6 * scale)

    u = tuple(jnp.asarray(rng.standard_normal(g.n), dtype=jnp.float32)
              for _ in range(3))
    U_pl = eng.interpolate_vel(u, X, b=b)
    U_ref = interaction.interpolate_vel(u, g, X, kernel="IB_4")
    scale = float(jnp.max(jnp.abs(U_ref)))
    np.testing.assert_allclose(np.asarray(U_pl), np.asarray(U_ref),
                               atol=2e-6 * scale)


def test_packed_pallas_hot_tile_accumulation():
    # all markers in ONE tile across many chunks: the revisit pattern
    # must ACCUMULATE (not overwrite) the shared output block, and
    # untouched tiles must come out exactly zero
    rng = np.random.default_rng(1)
    g = StaggeredGrid(n=(16, 16, 16), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    N = 150
    X = jnp.asarray(np.stack([rng.uniform(0.30, 0.34, N),
                              rng.uniform(0.30, 0.34, N),
                              rng.uniform(0, 1, N)], axis=1),
                    dtype=jnp.float32)
    F = jnp.asarray(rng.standard_normal((N, 3)), dtype=jnp.float32)
    eng = PallasPackedInteraction(g, kernel="IB_4", tile=8, chunk=16,
                                  nchunks=16, interpret=True)
    b = eng.buckets(X)
    assert not bool(b.any_overflow)
    assert int(jnp.sum(jnp.sum(b.wb > 0, axis=1) > 0)) >= 9
    f_pl = eng.spread_vel(F, X, b=b)
    f_ref = interaction.spread_vel(F, g, X, kernel="IB_4")
    for a, c in zip(f_ref, f_pl):
        scale = float(jnp.max(jnp.abs(a)))
        np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                   atol=2e-6 * scale)


def test_packed_pallas_adjointness():
    rng = np.random.default_rng(2)
    g = StaggeredGrid(n=(16, 16, 16), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    X = jnp.asarray(rng.uniform(0, 1, (120, 3)), dtype=jnp.float32)
    F = jnp.asarray(rng.standard_normal((120, 3)), dtype=jnp.float32)
    u = tuple(jnp.asarray(rng.standard_normal(g.n), dtype=jnp.float32)
              for _ in range(3))
    eng = _engine(g, X, chunk=32)
    b = eng.buckets(X)
    f = eng.spread_vel(F, X, b=b)
    U = eng.interpolate_vel(u, X, b=b)
    h3 = float(np.prod(g.dx))
    lhs = sum(float(jnp.sum(a * c)) for a, c in zip(f, u)) * h3
    rhs = float(jnp.sum(F * U))
    assert abs(lhs - rhs) < 2e-4 * (abs(lhs) + abs(rhs) + 1e-12)


def test_packed_pallas_overflow_fallback():
    # chunk capacity exhausted -> compact scatter fallback keeps it exact
    rng = np.random.default_rng(3)
    g = StaggeredGrid(n=(16, 16, 16), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    X = jnp.asarray(rng.uniform(0, 1, (250, 3)), dtype=jnp.float32)
    F = jnp.asarray(rng.standard_normal((250, 3)), dtype=jnp.float32)
    eng = PallasPackedInteraction(g, kernel="IB_4", tile=8, chunk=16,
                                  nchunks=4, interpret=True)
    b = eng.buckets(X)
    assert bool(b.any_overflow)
    f_pl = eng.spread_vel(F, X, b=b)
    f_ref = interaction.spread_vel(F, g, X, kernel="IB_4")
    for a, c in zip(f_ref, f_pl):
        scale = float(jnp.max(jnp.abs(a)))
        np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                   atol=2e-6 * scale)
    u = tuple(jnp.asarray(rng.standard_normal(g.n), dtype=jnp.float32)
              for _ in range(3))
    U_pl = eng.interpolate_vel(u, X, b=b)
    U_ref = interaction.interpolate_vel(u, g, X, kernel="IB_4")
    np.testing.assert_allclose(np.asarray(U_pl), np.asarray(U_ref),
                               atol=2e-6 * float(jnp.max(jnp.abs(U_ref))))


def test_packed_pallas_refresh_drifted_context():
    # slot-preserving half-step refresh: the Pallas programs only ever
    # see the resulting PackedBuckets, so a refreshed context must be
    # as exact through the kernel as a freshly packed one — both under
    # drift (re-gather) and past the bound (full re-pack fallback)
    rng = np.random.default_rng(4)
    g = StaggeredGrid(n=(16, 16, 16), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    X = jnp.asarray(rng.uniform(0, 1, (150, 3)), dtype=jnp.float32)
    F = jnp.asarray(rng.standard_normal((150, 3)), dtype=jnp.float32)
    eng = _engine(g, X, chunk=32)
    b = eng.buckets(X)
    dx = float(g.dx[0])
    for drift, want_hit in ((-0.4 * dx, True), (2.5 * dx, False)):
        Xd = X + jnp.float32(drift)
        b2, hit = eng.refresh(b, Xd)
        assert bool(hit) == want_hit, drift
        f_pl = eng.spread_vel(F, Xd, b=b2)
        f_ref = interaction.spread_vel(F, g, Xd, kernel="IB_4")
        for a, c in zip(f_ref, f_pl):
            scale = float(jnp.max(jnp.abs(a)))
            np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                       atol=2e-6 * scale)
