"""Spectral-plan layer (round 6): the hash-cons plan cache, the
k-space-resident fused substep (bitwise vs the pre-plan fused
reference in f64), the bf16/split-real mixed-precision transform path
(tolerance-pinned vs the f64 oracle, exactly like packed_bf16), the
all-periodic exact Stokes saddle solve, and the whole-step buffer
donation contracts (no-new-retrace via the driver's trace_counts
observable; ResilientDriver forces donation off)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
from ibamr_tpu.solvers import fft, spectral_plan


def _reference_fused(rhs, dx, alpha, beta, pinc_coeffs):
    """The pre-plan fused substep (fft.helmholtz_project_periodic as
    it was before delegation), inlined verbatim: the plan path must be
    BITWISE identical to this in full precision — the refactor moved
    where the symbol tables live, not what the substep computes."""
    shape = rhs[0].shape
    dim = len(shape)
    rdtype = rhs[0].dtype
    axes = tuple(range(1, dim + 1))
    sym = fft.laplacian_symbol(shape, dx, rdtype)
    uh = jnp.fft.rfftn(jnp.stack(rhs), axes=axes)
    cdtype = uh.dtype
    denom = (alpha + beta * sym).astype(rdtype)
    uh = uh / denom[None]
    D = fft._staggered_div_symbols(shape, dx, cdtype)
    divh = None
    for d in range(dim):
        t = D[d] * uh[d]
        divh = t if divh is None else divh + t
    sym_safe = jnp.where(sym == 0, 1.0, sym)
    phih = jnp.where(sym == 0, 0.0, divh / sym_safe)
    a, b = pinc_coeffs
    outh = jnp.stack(
        [uh[d] + jnp.conj(D[d]) * phih for d in range(dim)]
        + [((a + b * sym) * phih).astype(cdtype)])
    out = jnp.fft.irfftn(outh, s=shape, axes=axes).astype(rdtype)
    return tuple(out[d] for d in range(dim)), out[dim]


def _rand_rhs(shape, dtype, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.standard_normal(shape), dtype)
                 for _ in range(len(shape)))


def test_plan_substep_bitwise_vs_reference_f64():
    spectral_plan.clear_plan_cache()
    for shape in ((32, 32), (16, 16, 16)):
        g_dx = tuple(1.0 / s for s in shape)
        rhs = _rand_rhs(shape, jnp.float64)
        alpha, beta = 50.0, -0.05
        u_ref, p_ref = _reference_fused(rhs, g_dx, alpha, beta,
                                        (alpha, beta))
        u_pl, p_pl = fft.helmholtz_project_periodic(
            rhs, g_dx, alpha=alpha, beta=beta, pinc_coeffs=(alpha, beta))
        for a, b in zip(u_pl, u_ref):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        assert np.array_equal(np.asarray(p_pl), np.asarray(p_ref))


def test_plan_substep_under_jit_matches_eager():
    # the plan's cached tables are concrete device constants; captured
    # in a jit trace they must NOT leak as tracers (the
    # ensure_compile_time_eval contract) and must reproduce the eager
    # result to f64 roundoff (XLA fusion may reassociate, so this is a
    # tight-tolerance pin, not bitwise)
    spectral_plan.clear_plan_cache()
    shape = (24, 24)
    dx = (1.0 / 24,) * 2
    rhs = _rand_rhs(shape, jnp.float64, seed=3)
    eager = fft.helmholtz_project_periodic(rhs, dx, alpha=10.0,
                                           beta=-0.01,
                                           pinc_coeffs=(10.0, -0.01))
    jitted = jax.jit(lambda r: fft.helmholtz_project_periodic(
        r, dx, alpha=10.0, beta=-0.01, pinc_coeffs=(10.0, -0.01)))(rhs)
    for a, b in zip(jitted[0], eager[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=1e-13)
    np.testing.assert_allclose(np.asarray(jitted[1]),
                               np.asarray(eager[1]), rtol=0, atol=1e-11)


def test_bf16_substep_tolerance_pinned_vs_f64_oracle():
    """The mixed-precision contract: bf16/split-real transform
    operands keep ~3 decimal digits (the packed_bf16 precision class);
    the f32 path stays at f32 roundoff. Pins both so a silent dtype
    regression in either direction fails loudly."""
    shape = (32, 32, 32)
    dx = tuple(1.0 / s for s in shape)
    alpha, beta = 2.0e4, -0.025   # rho/dt, -mu/2 at flagship-ish dt
    rhs64 = _rand_rhs(shape, jnp.float64, seed=1)
    rhs32 = tuple(c.astype(jnp.float32) for c in rhs64)
    u64, p64 = fft.helmholtz_project_periodic(
        rhs64, dx, alpha=alpha, beta=beta, pinc_coeffs=(alpha, beta))
    u32, p32 = fft.helmholtz_project_periodic(
        rhs32, dx, alpha=alpha, beta=beta, pinc_coeffs=(alpha, beta))
    ubf, pbf = fft.helmholtz_project_periodic(
        rhs32, dx, alpha=alpha, beta=beta, pinc_coeffs=(alpha, beta),
        spectral_dtype="bf16")

    def rel(a, ref):
        a, ref = np.asarray(a, np.float64), np.asarray(ref)
        return np.max(np.abs(a - ref)) / np.max(np.abs(ref))

    for d in range(3):
        assert rel(u32[d], u64[d]) < 1e-5          # f32 roundoff class
        e = rel(ubf[d], u64[d])
        assert e < 2e-2                            # bf16 operand class
        assert e > 1e-6   # and it really IS the compressed path
    assert rel(pbf, p64) < 2e-2


def test_bf16_divergence_stays_bounded():
    # bf16 transforms trade exact discrete div-freedom for operand
    # compression; the residual divergence must stay at the bf16
    # rounding class relative to the velocity scale, not blow up
    from ibamr_tpu.ops import stencils

    shape = (32, 32, 32)
    dx = tuple(1.0 / s for s in shape)
    rhs = _rand_rhs(shape, jnp.float32, seed=2)
    alpha, beta = 2.0e4, -0.025
    u, _ = fft.helmholtz_project_periodic(
        rhs, dx, alpha=alpha, beta=beta, pinc_coeffs=(alpha, beta),
        spectral_dtype="bf16")
    umax = max(float(jnp.max(jnp.abs(c))) for c in u)
    div = stencils.divergence(u, dx)
    # grid-scale divergence: |div| ~ eps_bf16 * |u| / h
    assert float(jnp.max(jnp.abs(div))) < 0.1 * umax / min(dx)


def test_spectral_dtype_knob_validation():
    with pytest.raises(ValueError, match="spectral_dtype"):
        spectral_plan.canonical_spectral_dtype("fp8")
    assert spectral_plan.canonical_spectral_dtype("f32") is None
    assert spectral_plan.canonical_spectral_dtype(None) is None
    assert spectral_plan.canonical_spectral_dtype("bf16") is jnp.bfloat16
    with pytest.raises(ValueError, match="wall_axes"):
        INSStaggeredIntegrator(
            StaggeredGrid(n=(16, 16), x_lo=(0.0,) * 2, x_up=(1.0,) * 2),
            wall_axes=(True, False), spectral_dtype="bf16")


def test_plan_cache_hit_miss_and_bounded_growth():
    """Regrid loops construct solvers over and over; the hash-cons
    cache must serve repeats from memory (hits) and stay LRU-bounded
    when a moving-window regrid walks through many shapes."""
    spectral_plan.clear_plan_cache()
    p1 = spectral_plan.get_plan((16, 16), (0.1, 0.1), jnp.float32)
    p2 = spectral_plan.get_plan((16, 16), (0.1, 0.1), jnp.float32)
    assert p1 is p2                       # hash-cons: the SAME object
    st = spectral_plan.plan_cache_stats()
    assert st["misses"] == 1 and st["hits"] == 1
    # distinct key components are distinct plans
    assert spectral_plan.get_plan((16, 16), (0.1, 0.1),
                                  jnp.float64) is not p1
    assert spectral_plan.get_plan((16, 16), (0.2, 0.1),
                                  jnp.float32) is not p1
    # a regrid-like walk over many shapes cannot grow the cache
    # unboundedly (tiny shapes: this tests the LRU, not the tables)
    for k in range(spectral_plan._CACHE_MAXSIZE + 2):
        spectral_plan.get_plan((4 + 2 * k, 4), (0.1, 0.1), jnp.float32)
    st = spectral_plan.plan_cache_stats()
    assert st["size"] <= st["maxsize"]
    assert st["evictions"] > 0
    spectral_plan.clear_plan_cache()


def test_periodic_saddle_solve_exact_and_matches_fgmres():
    from ibamr_tpu.solvers.stokes import StaggeredStokesSolver, StokesBC

    bc = StokesBC(axes=(None, None))
    n, dx = (24, 24), (1.0 / 24,) * 2
    s = StaggeredStokesSolver(n, dx, bc, alpha=100.0, mu=0.02)
    assert s.spectral is not None       # all-periodic -> spectral path
    rng = np.random.default_rng(5)
    f_u = tuple(jnp.asarray(rng.standard_normal(n)) for _ in range(2))
    f_p = jnp.asarray(rng.standard_normal(n))
    rhs = s.make_rhs(f_u=f_u, f_p=f_p - f_p.mean())
    sol = s.solve(rhs)
    assert bool(sol.converged)
    assert int(sol.iters) == 0          # direct solve, no Krylov sweeps
    assert float(sol.resnorm) < 1e-10
    assert s.last_solve_stats["solver"] == "spectral"
    # cross-validate against the Krylov path on the same rhs
    s.spectral = None
    ref = s.solve(rhs)
    for a, b in zip(sol.u, ref.u):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-9
    assert float(jnp.max(jnp.abs(sol.p - ref.p))) < 1e-9


def test_periodic_saddle_solve_traced_alpha_no_retrace():
    from ibamr_tpu.solvers.stokes import StaggeredStokesSolver, StokesBC

    bc = StokesBC(axes=(None, None, None))
    n, dx = (8, 8, 8), (0.125,) * 3
    s = StaggeredStokesSolver(n, dx, bc, alpha=50.0, mu=0.01)
    rng = np.random.default_rng(6)
    f_u = tuple(jnp.asarray(rng.standard_normal(n)) for _ in range(3))
    rhs = s.make_rhs(f_u=f_u)
    traces = []

    @jax.jit
    def solve_at(a):
        traces.append(1)
        return s.solve(rhs, alpha=a).u[0]

    # velocity (not pressure): with f_p = 0 the pressure is
    # alpha-independent, but u divides by A = alpha - mu*lam
    u1 = solve_at(40.0)
    u2 = solve_at(90.0)     # adaptive-dt contract: one trace, any dt
    assert len(traces) == 1
    assert not np.allclose(np.asarray(u1), np.asarray(u2))


def test_driver_donation_no_retrace_and_buffer_reuse():
    """cfg.donate=True: the chunked driver run keeps ONE trace per
    chunk length (trace_counts observable) and actually donates —
    the pre-chunk state buffers are deleted after the chunk."""
    from ibamr_tpu.utils.hierarchy_driver import HierarchyDriver, RunConfig

    g = StaggeredGrid(n=(16, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    integ = INSStaggeredIntegrator(g, mu=0.02, dtype=jnp.float32)
    rng = np.random.default_rng(7)
    u0 = tuple(jnp.asarray(rng.standard_normal(g.n) * 0.1, jnp.float32)
               for _ in range(2))
    state = integ.initialize(u0_arrays=u0)
    first_u = state.u[0]
    cfg = RunConfig(dt=1e-3, num_steps=12, health_interval=4,
                    donate=True)
    drv = HierarchyDriver(integ, cfg)
    out = drv.run(state)
    # one distinct input signature per chunk length — donation must
    # not introduce a retrace
    assert all(v == 1 for v in drv.trace_counts.values())
    assert drv.trace_counts                    # ... and chunks did run
    # the donated input buffer is gone (soft: is_deleted is a jax.Array
    # API detail, but on the CPU backend it is authoritative)
    if hasattr(first_u, "is_deleted"):
        assert first_u.is_deleted()
    assert bool(jnp.all(jnp.isfinite(out.u[0])))


def test_resilient_driver_forces_donation_off(tmp_path):
    from ibamr_tpu.utils.hierarchy_driver import HierarchyDriver, RunConfig
    from ibamr_tpu.utils.supervisor import ResilientDriver

    g = StaggeredGrid(n=(8, 8), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    integ = INSStaggeredIntegrator(g, mu=0.02, dtype=jnp.float32)
    cfg = RunConfig(dt=1e-3, num_steps=4, health_interval=2,
                    restart_interval=2, donate=True)
    drv = HierarchyDriver(integ, cfg)
    res = ResilientDriver(drv, str(tmp_path), handle_signals=False)
    # rollback retains pre-chunk state references; donation would
    # invalidate them, so the supervisor must have switched it off
    assert drv.cfg.donate is False
    state = integ.initialize()
    out = res.run(state)                     # and the run still works
    assert bool(jnp.all(jnp.isfinite(out.u[0])))


def test_jitted_step_donation_ib():
    from ibamr_tpu.models.shell3d import build_shell_example

    integ, st = build_shell_example(n_cells=16, n_lat=8, n_lon=8,
                                    mu=0.05)
    step = integ.jitted_step(donate=True, with_stats=False)
    assert step is integ.jitted_step(donate=True, with_stats=False)
    u_before = st.ins.u[0]
    s2 = step(st, 1e-3)
    s3 = step(s2, 1e-3)
    assert bool(jnp.all(jnp.isfinite(s3.X)))
    if hasattr(u_before, "is_deleted"):
        assert u_before.is_deleted()
