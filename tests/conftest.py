"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's ``foo.mpirun=4.input`` trick (SURVEY.md §4): the
reference exercises its MPI paths with oversubscribed local ranks; we
exercise our sharding paths with ``xla_force_host_platform_device_count``
virtual CPU devices. Real-TPU execution is covered by bench.py and the
driver's compile checks, not by this suite.

Must set env vars BEFORE jax is imported anywhere.
"""

import os

# The container pins JAX_PLATFORMS=axon (single real TPU chip behind a
# loopback relay) and a sitecustomize hook that registers that backend in
# every interpreter and would force-initialize it on first jax compute —
# even under JAX_PLATFORMS=cpu. Tests must run on the virtual CPU mesh
# (eager ops over the tunnel are ~1000x slower and hang forever if the
# relay is down), so below we drop the axon backend factory before any
# compute happens.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402

try:  # private jax API; harmless to skip if it moves between releases
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")

# Allow float64 in tests: production state is f32 (TPU), but convergence
# tests validate the SAME operators at f64 on CPU so truncation error is
# measured above the roundoff floor (SURVEY.md §7.3 hard-part #2).
jax.config.update("jax_enable_x64", True)


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(scope="session")
def mesh8():
    """A 1-D 8-device mesh for sharding tests."""
    import numpy as np
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8])
    return Mesh(devs, axis_names=("x",))


@pytest.fixture(scope="session")
def mesh2x4():
    import numpy as np
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    return Mesh(devs, axis_names=("x", "y"))
